"""Guard configuration: breach policy, probe cadence, watchdog knobs.

Armed by env (so drills can guard any process without code changes) or
explicitly via `Worker.query(guard=GuardConfig(...))` / `guard="halt"`:

    GRAPE_GUARD=off|warn|halt|rollback   breach policy (default off)
    GRAPE_GUARD_EVERY=K                  probe cadence in supersteps
                                         (stepwise: probe every Kth
                                         round; fused: chunk length —
                                         default 1)
    GRAPE_GUARD_STAGNATION=K             residual-stagnation window
                                         (default 256; 0 disables the
                                         heuristic, cycle detection
                                         stays on)
"""

from __future__ import annotations

import os
from dataclasses import dataclass

GUARD_ENV = "GRAPE_GUARD"
GUARD_EVERY_ENV = "GRAPE_GUARD_EVERY"
GUARD_STAGNATION_ENV = "GRAPE_GUARD_STAGNATION"

POLICIES = ("off", "warn", "halt", "rollback")


@dataclass(frozen=True)
class GuardConfig:
    """Resolved guard settings for one query."""

    policy: str = "off"
    # probe cadence in supersteps; stepwise probes every `every` rounds,
    # the guarded-fused path runs fused chunks of `every` supersteps
    every: int = 1
    # halt when the best residual has not improved for this many probes
    # (heuristic — a long-diameter BFS/SSSP legitimately plateaus, so
    # the default window is generous; 0 disables)
    stagnation_window: int = 256
    # rollback budget: a breach that keeps recurring past this many
    # restores is deterministic and halts with the diagnostic bundle
    max_rollbacks: int = 2

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(
                f"unknown guard policy {self.policy!r} "
                f"(expected one of {POLICIES})"
            )
        if self.every < 1:
            raise ValueError(f"guard cadence must be >= 1, got {self.every}")
        if self.stagnation_window < 0:
            raise ValueError(
                f"stagnation window must be >= 0, got {self.stagnation_window}"
            )
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {self.max_rollbacks}"
            )

    @property
    def enabled(self) -> bool:
        return self.policy != "off"

    @classmethod
    def resolve(cls, guard=None) -> "GuardConfig":
        """`GuardConfig` | policy string | None (env) -> GuardConfig.
        The env knobs fill whatever a bare policy string leaves open."""
        if isinstance(guard, GuardConfig):
            return guard
        if guard is None:
            policy = os.environ.get(GUARD_ENV, "") or "off"
        else:
            policy = str(guard) or "off"
        return cls(
            policy=policy,
            every=int(os.environ.get(GUARD_EVERY_ENV, "") or 1),
            stagnation_window=int(
                os.environ.get(GUARD_STAGNATION_ENV, "") or 256
            ),
        )
