"""Cross-rank breach votes: one rank's halt is every rank's halt.

The guard probes are jitted GLOBAL computations, so an invariant
breach or watchdog divergence produces the same verdict on every rank
— but the *raise* is host-side, and under `jax.distributed` a raise
on one rank strands its siblings blocked in the next superstep's
collective (they never learn; the gang hangs until an external
timeout).  Host-side failures are worse: an `InjectedFault`, an IO
error in a checkpoint hook, anything rank-local, halts exactly one
process.

`BreachVote.round_vote` closes that gap with a tiny host-side
allgather (`parallel.comm_spec.host_allgather`) at each superstep
boundary where hazard hooks run: every rank votes (verdict code,
superstep).  A healthy gang pays one 2-int32 exchange; any nonzero
vote makes EVERY rank raise at the same consistent cut — the
breaching rank re-raises its own error, the healthy ranks raise
`RemoteBreachError` naming who halted and why, and nobody is left in
a collective.  The vote also cross-checks the superstep number
itself: ranks voting at different cuts is a lockstep violation worth
halting over, not papering over.

The worker arms the vote only when a hazard hook exists (guard,
checkpointing, or an injected fault plan — all env/flag-symmetric
across ranks) and only under `jax.process_count() > 1`, so
single-process behavior is bit-identical with the module never
imported.

Gang-telemetry riders (PR 20): when obs is armed, each vote vector
carries a third int32 — a 28-bit prefix of this rank's trace id — so
the allgathered matrix correlates every rank's trace file; each rank
also emits one flow-event leg per vote (shared `(cat, id)` =
`("gang-vote", rounds+1)`), so the merged gang trace renders the vote
as an arrow across rank tracks.  Every raise path attaches
`err.gang_incident`, a deterministic digest of the allgathered vote
content — identical bytes on every rank, so the gang agrees on one
incident id with no extra message (obs/gang.py dumps the distributed
postmortem under it).  Fakes that allgather 2-wide vectors keep
working: the extra column is read only when present.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from libgrape_lite_tpu.guard.monitor import (
    DivergenceError,
    GuardError,
    InvariantBreachError,
)

VOTE_HEALTHY = 0
VOTE_INVARIANT = 1
VOTE_DIVERGENCE = 2
VOTE_FAULT = 3
VOTE_ERROR = 4

_VOTE_NAMES = {
    VOTE_HEALTHY: "healthy",
    VOTE_INVARIANT: "invariant breach",
    VOTE_DIVERGENCE: "divergence",
    VOTE_FAULT: "injected fault",
    VOTE_ERROR: "host-side error",
}


class RemoteBreachError(GuardError):
    """Another rank voted a halt at this superstep; this rank is
    healthy and halts in lockstep instead of blocking in the next
    collective.  `.bundle` names the voting ranks and their verdict
    codes; `.gang_incident` (when set) is the gang-shared incident id
    the distributed flight recorder dumps under."""


def _trace_word() -> int:
    """28-bit prefix of this rank's trace id (0 disarmed) — rides the
    int32 vote vector so the allgathered matrix names every rank's
    trace file."""
    try:
        from libgrape_lite_tpu.obs.gang import trace_word

        return trace_word()
    except Exception:
        return 0


def classify_breach_error(err: Optional[BaseException]) -> int:
    """The vote code for a caught hazard-hook error (the specific
    guard verdicts keep their identity across the wire; anything else
    is a host-side error)."""
    if err is None:
        return VOTE_HEALTHY
    if isinstance(err, DivergenceError):
        return VOTE_DIVERGENCE
    if isinstance(err, InvariantBreachError):
        return VOTE_INVARIANT
    from libgrape_lite_tpu.ft.faults import InjectedFault

    if isinstance(err, InjectedFault):
        return VOTE_FAULT
    return VOTE_ERROR


class BreachVote:
    """One breach-vote endpoint per process.  `allgather`, `rank` and
    `nprocs` are injectable so the quorum logic is unit-testable in
    one process."""

    def __init__(self, *, rank: Optional[int] = None,
                 nprocs: Optional[int] = None, allgather=None):
        import jax

        self.rank = jax.process_index() if rank is None else int(rank)
        self.nprocs = (
            jax.process_count() if nprocs is None else int(nprocs)
        )
        if allgather is None:
            from libgrape_lite_tpu.parallel.comm_spec import (
                host_allgather,
            )

            allgather = host_allgather
        self._allgather = allgather

    @classmethod
    def for_current_process(cls) -> Optional["BreachVote"]:
        """The process's vote endpoint, or None single-process (the
        caller skips voting entirely — zero overhead, bit-identical
        behavior)."""
        import jax

        if jax.process_count() <= 1:
            return None
        return cls()

    def _incident(self, votes, rounds: int) -> Optional[str]:
        """Deterministic gang-shared incident id over the allgathered
        vote matrix (identical bytes on every rank)."""
        try:
            from libgrape_lite_tpu.obs.gang import incident_id

            return incident_id({
                "kind": "breach_vote",
                "rounds": int(rounds),
                "votes": [[int(x) for x in row]
                          for row in np.asarray(votes).tolist()],
            })
        except Exception:
            return None

    def _emit_flow(self, rounds: int, halted: bool) -> None:
        """One flow-event leg for this vote: every rank shares
        `(cat="gang-vote", id=rounds+1)`, so the merged gang trace
        draws the vote as one arrow across the rank tracks."""
        try:
            from libgrape_lite_tpu import obs

            tr = obs.tracer()
            if not tr.enabled:
                return
            phase = ("s" if self.rank == 0 else
                     "f" if self.rank == self.nprocs - 1 else "t")
            tr.flow("breach_vote", flow_id=int(rounds) + 1,
                    phase=phase, cat="gang-vote",
                    round=int(rounds), halted=bool(halted))
        except Exception:
            pass

    def round_vote(self, rounds: int,
                   err: Optional[BaseException] = None) -> None:
        """Exchange this superstep's verdict with every rank.  Always
        raises when any rank (this one included) voted unhealthy:
        `err` re-raised locally, `RemoteBreachError` on healthy ranks.
        Returns normally only on a unanimous healthy vote.  Every
        raised (or re-raised) error carries `.gang_incident`."""
        code = classify_breach_error(err)
        votes = np.asarray(self._allgather(
            np.asarray([code, int(rounds), _trace_word()], np.int32)
        ))
        if votes.shape[0] != self.nprocs:
            e = RemoteBreachError(
                f"breach vote returned {votes.shape[0]} rows for "
                f"{self.nprocs} processes",
                {"rounds": int(rounds)},
            )
            e.gang_incident = self._incident(votes, rounds)
            raise e
        codes = votes[:, 0]
        rds = votes[:, 1]
        healthy = (err is None and np.all(rds == int(rounds))
                   and not np.any(codes != VOTE_HEALTHY))
        self._emit_flow(rounds, halted=not healthy)
        if err is not None:
            # every sibling saw the vote and is halting too; the
            # breaching rank keeps its own (more specific) error
            try:
                err.gang_incident = self._incident(votes, rounds)
            except Exception:  # exotic errors may reject attributes
                pass
            raise err
        if not np.all(rds == int(rounds)):
            e = RemoteBreachError(
                "breach vote out of lockstep: per-rank supersteps "
                f"{rds.tolist()} (this rank {self.rank} at "
                f"{int(rounds)})",
                {"rounds": rds.tolist(), "codes": codes.tolist()},
            )
            e.gang_incident = self._incident(votes, rounds)
            raise e
        bad = np.nonzero(codes != VOTE_HEALTHY)[0]
        if bad.size:
            detail = ", ".join(
                f"rank {int(r)}: "
                f"{_VOTE_NAMES.get(int(codes[r]), int(codes[r]))}"
                for r in bad
            )
            e = RemoteBreachError(
                f"halt voted at superstep {int(rounds)}: {detail} "
                f"(this rank {self.rank} is healthy and halts in "
                "lockstep)",
                {
                    "rounds": int(rounds),
                    "ranks": [int(r) for r in bad],
                    "codes": [int(codes[r]) for r in bad],
                },
            )
            e.gang_incident = self._incident(votes, rounds)
            raise e
