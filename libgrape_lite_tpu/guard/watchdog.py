"""Divergence watchdog: carry-digest cycle proof + residual stagnation.

The superstep is a *deterministic* function of the carry (XLA trace,
fixed reduction order).  So if the carry digest at round r equals the
digest at round r0 < r, the run is provably in an infinite cycle of
period r - r0 — state r+1 will equal state r0+1, and so on forever.
One repeat is a proof, not a heuristic (modulo digest collisions; the
digest below keeps 64 bits per carry leaf, so a false cycle verdict
needs a 2^-64 event per leaf).

Residual stagnation is the heuristic companion for float carries whose
digests never repeat but whose residual (max |Δ| between consecutive
probes) stops improving: a PageRank-like iteration whose residual has
not made a new minimum in `window` probes is burning rounds without
converging.  The window is generous by default (a long-diameter
BFS/SSSP legitimately plateaus its residual for `diameter` rounds) and
0 disables the check; cycle detection stays on regardless.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax


def _u32_words(v) -> jnp.ndarray:
    """Flatten one carry leaf to its uint32 bit-words (exact: two
    states digest equal iff their bytes are equal, leaf by leaf)."""
    v = jnp.asarray(v)
    if v.dtype == jnp.bool_ or v.dtype.itemsize < 4:
        # sub-word leaves digest by value, which is still injective
        return v.astype(jnp.uint32).reshape(-1)
    return lax.bitcast_convert_type(v, jnp.uint32).reshape(-1)


def carry_digest(carry: Dict) -> jnp.ndarray:
    """[2 * nleaves] uint32 digest of the carry, order-sensitive
    within each leaf: two independent position-weighted wrapping sums
    (64 digest bits per leaf), per leaf in sorted-key order.  Plain
    multiply-add reductions only — XLA lowers them everywhere, unlike
    custom xor reduce computations.  Cheap enough to run every probe on
    device; fetched to the host as a hashable tuple."""
    words = []
    for k in sorted(carry):
        bits = _u32_words(carry[k])
        pos = jnp.arange(bits.shape[0], dtype=jnp.uint32)
        # Knuth/Murmur odd multipliers make each sum order-sensitive
        # and mutually independent
        w1 = pos * jnp.uint32(2654435761) + jnp.uint32(1)
        w2 = pos * jnp.uint32(0x85EBCA77) + jnp.uint32(0x9E3779B1)
        mixed = bits ^ (bits >> 16)
        words.append(jnp.sum(bits * w1))  # uint32 wraparound
        words.append(jnp.sum(mixed * w2))
    return jnp.stack(words)


def digest_hex(digest: Tuple[int, ...]) -> str:
    return "".join(f"{int(w) & 0xFFFFFFFF:08x}" for w in digest)


class DivergenceWatchdog:
    """Observes (round, digest, residual) at every probe and returns a
    verdict dict when the run provably cycles or heuristically
    stagnates; None while healthy.  `reset()` after a rollback —
    replayed rounds would otherwise re-present digests the history
    already holds and fire a false cycle verdict."""

    def __init__(self, stagnation_window: int = 256):
        self.stagnation_window = stagnation_window
        self._seen: Dict[Tuple[int, ...], int] = {}
        self._best_residual: Optional[float] = None
        self._stale_probes = 0

    def reset(self) -> None:
        self._seen.clear()
        self._best_residual = None
        self._stale_probes = 0

    def observe(
        self,
        rounds: int,
        digest: Tuple[int, ...],
        residual: Optional[float] = None,
    ) -> Optional[dict]:
        first = self._seen.get(digest)
        if first is not None:
            return {
                "kind": "oscillation",
                "period": rounds - first,
                "first_seen_round": first,
                "round": rounds,
                "detail": (
                    f"carry digest at superstep {rounds} repeats superstep "
                    f"{first}: the loop is in a provable cycle of period "
                    f"{rounds - first} and will never converge"
                ),
            }
        self._seen[digest] = rounds
        if residual is not None and self.stagnation_window > 0:
            if (
                self._best_residual is None
                or (np.isfinite(residual) and residual < self._best_residual)
            ):
                self._best_residual = (
                    float(residual) if np.isfinite(residual) else None
                )
                self._stale_probes = 0
            else:
                self._stale_probes += 1
                if self._stale_probes >= self.stagnation_window:
                    return {
                        "kind": "stagnation",
                        "round": rounds,
                        "best_residual": self._best_residual,
                        "stale_probes": self._stale_probes,
                        "detail": (
                            f"residual has not improved on "
                            f"{self._best_residual!r} for "
                            f"{self._stale_probes} probes "
                            f"(window {self.stagnation_window})"
                        ),
                    }
        return None
