"""App-declared runtime invariants.

An `Invariant` is a named device-side predicate over consecutive
carries: `fn(dev_frag, prev_carry, cur_carry) -> (ok, measure)` where
`ok` is a scalar bool and `measure` a scalar f32 the diagnostic bundle
records (typically the violating-element count or the error
magnitude).  Predicates are traced into ONE jitted probe per query
(guard/monitor.py), so each evaluation is a single device dispatch.

`requires` names the carry keys the predicate reads; the monitor drops
invariants whose keys are absent from the actual carry (a subclass
with different state must not inherit a probe that would KeyError
mid-trace).

Soundness notes baked into the builders:

* comparisons are NaN-rejecting where it matters — `in_range(lo=0)`
  catches NaN (NaN >= 0 is False) while `monotone_non_increasing`
  alone would NOT (NaN > x is also False); pair them.
* padded rows must satisfy every invariant in a healthy run (pad dist
  = +inf, pad labels = INT32_MAX, pad rank = 0), so predicates scan
  the whole carry unmasked — corruption in a padded row is still
  corruption.
* CDLP labels are NOT monotone (mode adoption can raise a label);
  CDLP declares range-membership instead — see models/cdlp.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Tuple

import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class Invariant:
    name: str
    fn: Callable  # (dev_frag, prev, cur) -> (ok scalar, measure scalar)
    requires: Tuple[str, ...]
    description: str = field(default="")

    def check(self, dev, prev, cur):
        ok, measure = self.fn(dev, prev, cur)
        return jnp.asarray(ok, jnp.bool_), jnp.asarray(measure, jnp.float32)


def _count_invariant(name, key, bad_fn, description):
    def fn(dev, prev, cur):
        nbad = bad_fn(prev, cur).sum().astype(jnp.int32)
        return nbad == 0, nbad.astype(jnp.float32)

    return Invariant(name, fn, (key,), description)


def no_nan(key: str) -> Invariant:
    """No NaN anywhere in a float leaf (the generic float-carry guard:
    +/-inf may be a legitimate sentinel, NaN never is)."""
    return _count_invariant(
        f"no_nan({key})", key,
        lambda prev, cur: jnp.isnan(cur[key]),
        f"float carry {key!r} must be NaN-free",
    )


def finite(key: str) -> Invariant:
    """Strictly finite float leaf (no NaN, no +/-inf)."""
    return _count_invariant(
        f"finite({key})", key,
        lambda prev, cur: ~jnp.isfinite(cur[key]),
        f"float carry {key!r} must be finite",
    )


def in_range(key: str, lo=None, hi=None) -> Invariant:
    """Every element within [lo, hi] (either bound optional).  NaN
    fails any given bound, so this doubles as a NaN check."""

    def bad(prev, cur):
        v = cur[key]
        ok = jnp.ones(v.shape, bool)
        if lo is not None:
            ok = jnp.logical_and(ok, v >= jnp.asarray(lo, v.dtype))
        if hi is not None:
            ok = jnp.logical_and(ok, v <= jnp.asarray(hi, v.dtype))
        return ~ok

    bounds = f"[{'-inf' if lo is None else lo}, {'inf' if hi is None else hi}]"
    return _count_invariant(
        f"in_range({key})", key, bad,
        f"carry {key!r} must lie in {bounds}",
    )


def monotone_non_increasing(key: str) -> Invariant:
    """No element may grow between consecutive probes (min-propagation
    carries: SSSP/BFS distances, WCC labels).  Holds across a probe
    cadence > 1 too — monotonicity is transitive.  NaN-blind by itself
    (NaN > x is False); pair with `in_range`/`no_nan`."""
    return _count_invariant(
        f"monotone_non_increasing({key})", key,
        lambda prev, cur: cur[key] > prev[key],
        f"carry {key!r} may only decrease between supersteps",
    )


def monotone_non_decreasing(key: str) -> Invariant:
    """No element may shrink between consecutive probes (peeling-level
    counters, accumulating sums).  Transitive across cadence > 1 like
    its mirror; NaN-blind by itself — pair with a range/NaN check."""
    return _count_invariant(
        f"monotone_non_decreasing({key})", key,
        lambda prev, cur: cur[key] < prev[key],
        f"carry {key!r} may only increase between supersteps",
    )


def set_once(key: str, unset) -> Invariant:
    """Elements may change only FROM the `unset` sentinel: once a
    value is pinned it must never change again (core numbers, first
    -touch labels).  A corrupted pinned element therefore trips on the
    next probe even when the corruption is in-range."""
    return _count_invariant(
        f"set_once({key})", key,
        lambda prev, cur: jnp.logical_and(
            cur[key] != prev[key],
            prev[key] != jnp.asarray(unset, prev[key].dtype),
        ),
        f"carry {key!r} may only change from its unset value {unset!r}",
    )


def default_invariants(app, frag, state) -> list:
    """The floor every app gets for free: NaN-free float carries.
    (The active-vote range check `0 <= active <= vnum` is host-side
    and lives in the monitor.)  Ephemeral leaves are trace inputs, not
    loop state — excluded."""
    eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
    out = []
    for k in sorted(state):
        if k in eph:
            continue
        if np.dtype(state[k].dtype).kind == "f":
            out.append(no_nan(k))
    return out
