"""Strict-tile SpMV — the Pallas analogue of the reference's LBSTRICT
edge-balanced kernel (`grape/cuda/parallel/parallel_engine.h:847-1013`).

The framework's default SpMV is gather + XLA `segment_sum`
(ops/segment.py).  That path's TPU lowering is a sorted scatter-add;
its weakness is the scatter's serialization on hot rows.  This kernel
replaces the scatter with MXU work:

  * edges (sorted by row, as every CSR here stores them) are cut into
    fixed tiles of `tile` edges — exact edge balance, the strict
    policy's defining property;
  * each tile's row span [row_lo, row_lo + rmax) is known on the host
    (`plan_tiles`); `rmax` is the worst span over tiles;
  * a Pallas program per tile builds the one-hot indicator
    `[tile, rmax]` (edge e hits local row src[e]-row_lo) and contracts
    it with the per-edge values on the MXU — per-tile partial row sums,
    no scatter;
  * a single XLA scatter-add of `[num_tiles, rmax]` partials (≪ E
    elements) folds tile boundaries.

The tradeoff is explicit: MXU MACs per tile = tile × rmax.  On
hub-dominated tiles (power-law graphs) rmax is tiny and the kernel is
pure wins; on degree-1 tails rmax → tile and the indicator matmul
wastes FLOPs.  `segment_sum_auto` + `plan_for_app` pick per-shape: the
kernel when the planned rmax is small relative to the tile (dense
rows, `strict_worthwhile`), the XLA path otherwise — the same
adaptivity the reference gets from choosing cm/wm/strict per app.
PageRank's pull consumes this (models/pagerank.py); `GRAPE_SPMV`
(auto|strict|xla) overrides the choice for A/B runs.

A/B-measure with `scripts/spmv_ab.py` on real TPU before changing any
default (VERDICT r1 next-round item 2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


LANE = 128  # vector lane width: tile row spans must lane-align


def _align_rmax(span: int) -> int:
    """Lane-align a tile row span so the kernel's [tile, rmax] matmul
    output tiles cleanly — the single sizing rule for every path
    through plan_tiles (the empty-edge case included, which used to
    hardcode the literal)."""
    return max(LANE, -(-span // LANE) * LANE)


def plan_tiles(edge_src_sorted: np.ndarray, tile: int, vp: int):
    """Host-side strict tiling of a row-sorted edge array (padding rows
    `vp` included — they land in the sliced-off overflow row).

    Returns (row_lo [num_tiles] int32, rmax int, num_tiles int).
    """
    e = len(edge_src_sorted)
    if e == 0:
        # degenerate shard: one all-pad tile at the minimal aligned
        # span (derived, not hardcoded — plan_for_app additionally
        # rejects fully-empty fragments so no indicator matmul runs
        # for zero real edges)
        return np.zeros(1, dtype=np.int32), _align_rmax(1), 1
    # span planning must ignore pad edges (src == vp): a boundary tile
    # mixing the last real row with pads would otherwise inflate rmax to
    # ~vp, and the worst span sizes EVERY tile's [tile, rmax] matmul.
    # Pad edges clamp to the last real row for planning; in the kernel
    # their one-hot row is row_lo + (vp - row_lo) >= the clamp point, so
    # they only ever credit the sliced-off overflow row.
    real = edge_src_sorted[edge_src_sorted < vp]
    last_real = int(real[-1]) if len(real) else 0
    src_plan = np.minimum(edge_src_sorted, last_real)
    num_tiles = -(-e // tile)
    starts = np.arange(num_tiles, dtype=np.int64) * tile
    ends = np.minimum(starts + tile, e) - 1
    row_lo = src_plan[starts].astype(np.int32)
    row_hi = src_plan[ends].astype(np.int32)
    rmax = _align_rmax(int((row_hi - row_lo).max()) + 1)
    return row_lo, rmax, num_tiles


def _spmv_tile_kernel(row_lo_ref, src_ref, val_ref, out_ref, *, rmax):
    t = pl.program_id(0)
    row_lo = row_lo_ref[t]
    src = src_ref[0]  # [1, tile] int32 (block [1, 1, tile])
    val = val_ref[0].astype(jnp.float32)  # [1, tile]
    tile = src.shape[-1]
    # local row of each edge, one-hot against the tile's row window
    local = (src - row_lo).reshape(tile, 1)
    rows = jax.lax.broadcasted_iota(jnp.int32, (tile, rmax), 1)
    onehot = (local == rows).astype(jnp.float32)
    # [1, tile] @ [tile, rmax] on the MXU -> per-row partial sums
    out_ref[0] = jnp.dot(val, onehot, preferred_element_type=jnp.float32)


@functools.partial(
    jax.jit, static_argnames=("tile", "rmax", "num_tiles", "vp", "interpret")
)
def _spmv_partials(values, edge_src, row_lo, tile, rmax, num_tiles, vp,
                   interpret=False):
    e_pad = num_tiles * tile
    pad = e_pad - values.shape[0]
    if pad:
        # padded edges carry value 0 into row `vp` (overflow)
        values = jnp.concatenate([values, jnp.zeros((pad,), values.dtype)])
        edge_src = jnp.concatenate(
            [edge_src, jnp.full((pad,), vp, edge_src.dtype)]
        )
    # Mosaic requires the last two block dims to be (8,128)-divisible
    # or equal to the array dims — a singleton middle dim satisfies
    # that for per-tile [1, tile] blocks (r1 shipped (1, tile) 2-D
    # blocks, which never compiled on hardware; tests/
    # test_pallas_lowering.py now guards this offline)
    grid_spec = pl.GridSpec(
        grid=(num_tiles,),
        in_specs=[
            pl.BlockSpec((num_tiles,), lambda i: (0,)),
            pl.BlockSpec((1, 1, tile), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, tile), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rmax), lambda i: (i, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_spmv_tile_kernel, rmax=rmax),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_tiles, 1, rmax), jnp.float32),
        interpret=interpret,
    )(
        row_lo,
        edge_src.astype(jnp.int32).reshape(num_tiles, 1, tile),
        values.reshape(num_tiles, 1, tile),
    )
    return out.reshape(num_tiles, rmax)


def spmv_strict(values, edge_src, row_lo, vp: int, tile: int, rmax: int,
                interpret: bool | None = None):
    """Strict-tile segment-sum of `values` by sorted `edge_src` into
    [vp] rows (drop-in for ops.segment.segment_reduce(..., "sum") on
    sorted float inputs).  `row_lo` may be host numpy or a traced
    per-shard array (shard_map callers pass their slice).
    `interpret=None` auto-selects: compiled on TPU, interpreter
    elsewhere (CPU backends can't lower Pallas)."""
    if interpret is None:
        from libgrape_lite_tpu.ops.pallas_kernels import use_pallas

        interpret = not use_pallas()
    num_tiles = row_lo.shape[0]
    partials = _spmv_partials(
        values, edge_src, jnp.asarray(row_lo), tile, rmax, num_tiles, vp,
        interpret=interpret,
    )
    # fold tile partials: rows of tile t live at row_lo[t] + [0, rmax)
    idx = jnp.asarray(row_lo, jnp.int32)[:, None] + jnp.arange(
        rmax, dtype=jnp.int32
    )
    idx = jnp.minimum(idx, vp)  # clamp into the overflow row
    out = jnp.zeros((vp + 1,), jnp.float32)
    out = out.at[idx.reshape(-1)].add(partials.reshape(-1))
    return out[:vp]


def strict_worthwhile(rmax: int, tile: int) -> bool:
    """Adoption heuristic: the indicator matmul costs tile*rmax MACs
    for tile useful adds — accept up to 8 lanes of row window per
    128-edge MXU pass (hub-heavy tiles), reject degree-1 tails."""
    return rmax * 16 <= tile


_PLAN_CACHE: "weakref.WeakKeyDictionary" = None  # set on first use


def plan_for_app(frag, vp: int, dtype, tile: int = 2048,
                 mode: str | None = None):
    """Host-side SpMV planning for a fragment's in-edge array: returns
    (row_lo [fnum, num_tiles] int32, tile, rmax) when the strict kernel
    should serve this app's segment-sums, else None (XLA `segment_sum`).

    Selection (`GRAPE_SPMV` env: auto|strict|xla, default auto):
      * `xla` — never;
      * `strict` — always (A/B runs; interpret-mode off-TPU);
      * `auto` — only on a real TPU backend, float32 values (the MXU
        path accumulates in f32; f64 states keep XLA), and
        `strict_worthwhile` on the worst tile span.

    The cheap mode/backend/dtype rejections run BEFORE the O(E)
    device-to-host copy + tile scan, and accepted plans are cached per
    fragment — queries repeat, topology does not.
    """
    import os
    import weakref

    mode = mode or os.environ.get("GRAPE_SPMV", "auto")
    if mode == "xla":
        return None
    if mode != "strict":
        from libgrape_lite_tpu.ops.pallas_kernels import use_pallas

        if not use_pallas():
            return None
        if np.dtype(dtype) != np.float32:
            return None

    global _PLAN_CACHE
    if _PLAN_CACHE is None:
        _PLAN_CACHE = weakref.WeakKeyDictionary()
    key = (tile, vp)
    cached = _PLAN_CACHE.get(frag, {}).get(key)
    if cached is None:
        edge_src_stacked = np.asarray(frag.dev.ie.edge_src)
        fnum = edge_src_stacked.shape[0]
        if not (edge_src_stacked < vp).any():
            # zero real edges on every shard: a [tile, rmax] indicator
            # matmul for nothing — let XLA's trivial segment_sum serve
            _PLAN_CACHE.setdefault(frag, {})[key] = False
            return None
        plans = [
            plan_tiles(edge_src_stacked[f], tile, vp) for f in range(fnum)
        ]
        rmax = max(p[1] for p in plans)
        row_lo = np.stack([p[0] for p in plans]).astype(np.int32)
        cached = (row_lo, tile, rmax)
        _PLAN_CACHE.setdefault(frag, {})[key] = cached
    if cached is False:  # cached empty-fragment rejection
        return None
    row_lo, tile, rmax = cached
    if mode != "strict" and not strict_worthwhile(rmax, tile):
        return None
    return row_lo, tile, rmax


def segment_sum_auto(values, edge_src, vp: int, plan=None):
    """Sorted segment-sum routed per the host plan: the strict-tile
    Pallas kernel when `plan` is a (row_lo_local, tile, rmax) triple
    (row_lo_local = this shard's [num_tiles] slice), the XLA
    gather+segment_sum otherwise."""
    if plan is None:
        from libgrape_lite_tpu.ops.segment import segment_reduce

        return segment_reduce(values, edge_src, vp, "sum")
    row_lo, tile, rmax = plan
    return spmv_strict(values, edge_src, row_lo, vp, tile, rmax)
