"""Pallas TPU kernels for the hot ops.

The framework's compute is mostly XLA-fused gathers + segment
reductions (ops/segment.py); the ops that benefit from hand-written
kernels are the *bitmap* ones — LCC / k-clique set intersection, where
the working set is a [chunk, words] tile of packed adjacency rows and
the op is AND + population_count + row-reduce.  The reference's
analogue is its SSE/STTNI intersection kernels (`lcc_opt.h:26-41`) and
the CUDA warp intersections (`cuda/utils/dev_utils.h`).

`intersect_count` tiles the edge chunk over a 1-D grid; each program
ANDs two row tiles resident in VMEM and reduces popcounts on the VPU —
no HBM round-trip for the intermediate AND, which is what the fallback
`jnp` path materialises.  Wired behind `use_pallas()` (TPU-only;
tests exercise interpret mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _intersect_kernel(a_ref, b_ref, o_ref):
    a = a_ref[...]
    b = b_ref[...]
    cnt = lax.population_count(a & b).astype(jnp.int32)
    # pin the accumulator dtype: under x64, sum() promotes int32 to
    # int64, which the int32 output ref rejects
    o_ref[...] = cnt.sum(axis=1, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def intersect_count(a, b, block: int = 512, interpret: bool = False):
    """Row-wise |a_i AND b_i| popcount for packed uint32 bitmaps.

    a, b: [n, words] uint32 -> [n] int32.  `n` must be a multiple of
    `block` (callers pad; edge chunks already are).
    """
    n, words = a.shape
    if n % block != 0:
        raise ValueError(f"rows {n} not a multiple of block {block}")
    grid = (n // block,)
    return pl.pallas_call(
        _intersect_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, words), lambda i: (i, 0)),
            pl.BlockSpec((block, words), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        interpret=interpret,
    )(a, b)


def row_and_popcount(a, b, block: int = 512):
    """Dispatcher used by the LCC/k-clique kernels: the Pallas kernel on
    TPU when the tile shape allows, the XLA-fused path otherwise."""
    n = a.shape[0]
    if use_pallas() and n % block == 0:
        return intersect_count(a, b, block=block)
    return lax.population_count(a & b).sum(axis=1, dtype=jnp.int32)


def use_pallas() -> bool:
    """Pallas kernels are enabled on real TPU backends only (the CPU
    fallback is the fused jnp path, which XLA handles well)."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
