"""Pallas TPU kernels for the hot ops.

The framework's compute is mostly XLA-fused gathers + segment
reductions (ops/segment.py); the ops that benefit from hand-written
kernels are the *bitmap* ones — LCC / k-clique set intersection, where
the working set is a [chunk, words] tile of packed adjacency rows and
the op is AND + population_count + row-reduce.  The reference's
analogue is its SSE/STTNI intersection kernels (`lcc_opt.h:26-41`) and
the CUDA warp intersections (`cuda/utils/dev_utils.h`).

`intersect_count` tiles the edge chunk over a 1-D grid; each program
ANDs two row tiles resident in VMEM and reduces popcounts on the VPU —
no HBM round-trip for the intermediate AND, which is what the fallback
`jnp` path materialises.  Wired behind `use_pallas()` (TPU-only;
tests exercise interpret mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _intersect_kernel(a_ref, b_ref, o_ref):
    a = a_ref[...]
    b = b_ref[...]
    cnt = lax.population_count(a & b).astype(jnp.int32)
    # pin the accumulator dtype: under x64, sum() promotes int32 to
    # int64, which the int32 output ref rejects
    o_ref[...] = cnt.sum(axis=1, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def intersect_count(a, b, block: int = 512, interpret: bool = False):
    """Row-wise |a_i AND b_i| popcount for packed uint32 bitmaps.

    a, b: [n, words] uint32 -> [n] int32.  `n` must be a multiple of
    `block` (callers pad; edge chunks already are).
    """
    n, words = a.shape
    if n % block != 0:
        raise ValueError(f"rows {n} not a multiple of block {block}")
    grid = (n // block,)
    return pl.pallas_call(
        _intersect_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, words), lambda i: (i, 0)),
            pl.BlockSpec((block, words), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        interpret=interpret,
    )(a, b)


def row_and_popcount(a, b, block: int = 512):
    """Dispatcher used by the LCC/k-clique kernels: the Pallas kernel on
    TPU when the tile shape allows, the XLA-fused path otherwise."""
    n = a.shape[0]
    if use_pallas() and n % block == 0:
        return intersect_count(a, b, block=block)
    return lax.population_count(a & b).sum(axis=1, dtype=jnp.int32)


def use_pallas() -> bool:
    """Pallas kernels are enabled on real TPU backends only (the CPU
    fallback is the fused jnp path, which XLA handles well)."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


_CAP_PROBE = r"""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def probe(name, kernel, *shapes):
    try:
        args = [jax.ShapeDtypeStruct(s, d) for s, d in shapes]
        jax.jit(lambda *a: pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(shapes[0][0], shapes[0][1]),
        )(*a)).trace(*args).lower(lowering_platforms=("tpu",))
        print(f"CAP {name} ok")
    except Exception as e:
        print(f"CAP {name} fail {type(e).__name__}")

def k_sublane_gather(x_ref, i_ref, o_ref):
    o_ref[...] = jnp.take_along_axis(
        x_ref[...], i_ref[...].astype(jnp.int32), axis=0
    )

def k_int_reduce(x_ref, i_ref, o_ref):
    o_ref[...] = (
        x_ref[...]
        + jnp.sum(i_ref[...].astype(jnp.int32), axis=1,
                  keepdims=True).astype(x_ref.dtype)
    )

def k_lane_gather(x_ref, i_ref, o_ref):
    o_ref[...] = jnp.take_along_axis(
        x_ref[...], i_ref[...].astype(jnp.int32), axis=1
    )

def k_mxu_dot(x_ref, y_ref, o_ref):
    o_ref[...] = jnp.dot(x_ref[...], y_ref[...],
                         preferred_element_type=jnp.float32)

probe("sublane_gather", k_sublane_gather,
      ((8, 128), jnp.float32), ((8, 128), jnp.int16))
probe("lane_gather", k_lane_gather,
      ((8, 128), jnp.float32), ((8, 128), jnp.int8))
probe("int_reduce", k_int_reduce,
      ((8, 128), jnp.float32), ((8, 128), jnp.int32))
probe("mxu_dot", k_mxu_dot,
      ((128, 128), jnp.float32), ((128, 128), jnp.float32))
"""


@functools.lru_cache(maxsize=None)
def mosaic_lowering_caps() -> dict:
    """Probe which Mosaic lowerings THIS jax build supports, offline
    (client-side `.lower(lowering_platforms=('tpu',))`, no hardware).

    Some jax builds ship a Pallas TPU lowering that refuses primitives
    real TPU releases handle (the session's build rejects even the
    shape-matched sublane `take_along_axis` and integer reductions).
    The offline lowering regressions skip — with the missing capability
    named — instead of failing on environment, while still failing
    loudly on a REAL kernel regression when the build can lower.  Runs
    in a subprocess with the axon plugin disabled (its sitecustomize
    backend init can hang when the tunnel is down)."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, "-c", _CAP_PROBE],
            capture_output=True, text=True, timeout=600, env=env,
        )
    except Exception:
        return {}
    caps = {}
    for line in r.stdout.splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[0] == "CAP":
            caps[parts[1]] = parts[2] == "ok"
    return caps
