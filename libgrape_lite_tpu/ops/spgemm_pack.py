"""Tiled masked SpGEMM for triangle-style workloads on the MXU.

ROADMAP item 5a: the GraphBLAS triangle-count formulation
``B = (A · Aᵀ) ∘ A`` over the degree-oriented DAG, lowered the way the
pack machinery lowers SpMV — all irregularity compiled into static
streams at plan time, the per-round dataflow dense vector/matrix work.

Formulation (the output-stationary form of masked SpGEMM): with ``D``
the deduplicated degree-oriented adjacency (v → u iff (deg, id) orders
v and u; every triangle {v, u, w} has exactly one labeling v → u,
v → w, u → w), the masked product only needs entries where the MASK is
nonzero — and the mask IS the oriented edge list.  So the plan
enumerates mask edges directly and tiles the CONTRACTION dimension:

  * the w-space (list members) is COMPACTED and popularity-sorted at
    plan time, then cut into 128-lane K-tiles; D ships as a packed
    bitmap ``[rows, nK * 4] uint32`` over that compacted space — the
    [128, 128]-bit adjacency tile is the storage unit;
  * one work ITEM = (mask edge (v, u), K-tile k).  Plan-time tile
    pruning emits an item only when BOTH operand rows have bits in
    tile k (skip empty A-row × A-col tile products) — on power-law
    graphs this prunes the vast majority of the n/128 candidate tiles
    per edge (bench RMAT-16: 4.5 items/edge vs 135 K-tiles);
  * the kernel processes items in chunks of ``cfg.chunk``: gather the
    two packed rows' k-tile words, expand to dense uint8 [chunk, 128]
    blocks, AND them, and reduce the hit block to per-edge counts with
    one ``[chunk, 128] @ [128, 128]`` matmul — the same MXU lowering
    shape PR 4 validated for the pack scan (a VPU tree-reduce would
    work too; the matmul keeps the reduction off the vector unit);
  * credits scatter per item: ``cnt`` to the apex v and middle u pids,
    the hit VECTOR to the far-end pids of tile k (a static
    colspace → pid table row) — the same 3-credit algebra as the
    popcount kernel's oe + ie passes, so per-vertex triangle counts
    are INTEGER-IDENTICAL to the intersect backend by construction
    (triangle enumeration is orientation-agnostic; each triangle is
    found exactly once, at its unique DAG (v, u) edge).

Sharding: items are partitioned by the apex fragment; each shard ships
a sub-bitmap holding only the rows its items reference, plus its item
streams padded to the cross-shard max (shard_map needs one static
program).  Credits accumulate in a pid-indexed vector folded by one
``psum`` — exactly the popcount kernel's credit exchange.

Cost: the static op-budget ledger carries the PR 4 split columns
(``vpu_ops`` / ``mxu_ops`` / ``hbm_bytes``) under conventions mirrored
(and independently recounted) by scripts/pack_cost_model.py.  The
popcount intersect pays 3 · n_pad/32 word-ops per edge per pass —
linear in VERTEX COUNT, the six-LDBC breadth ceiling this primitive
lifts: the item count scales with the pruned tile products instead
(arxiv 2311.03826's structured-SpGEMM framing; the per-tile pricing
discipline follows SparseP, arxiv 2201.05072).

`GRAPE_LCC_BACKEND` = intersect | spgemm | auto selects the LCC
backend; `auto` prices both ledgers at the pack cost model's rates.
Declines are RECORDED in SPGEMM_STATS — never silent.
"""

from __future__ import annotations

import itertools
import os
from dataclasses import dataclass, field

import numpy as np

C = 128          # lane width == K-tile width (one [128,128]-bit tile)
WPT = C // 32    # uint32 words per bitmap row per K-tile

# Rate assumptions for the auto backend pricing come from the shared
# RateProfile (ops/calibration.py) — the same rates every other priced
# surface reads, fitted or pinned.  Only the op-count CONVENTIONS below
# stay literal: the recount gate in scripts/pack_cost_model compares op
# counts (rates cancel in the mismatch), so sharing rates is safe while
# sharing counts would make the gate tautological.

# modeled per-item op counts (counting conventions, shared with the
# independent recount in scripts/pack_cost_model.spgemm_recount — a
# drift here must trip the 5% gate there, so do not import these from
# the recount side):
#   * expand: 6 plane-rows of 128 lanes (two operands x shift / mask /
#     lane-select of the 4 packed words into the dense uint8 block);
#   * mask_and: 2 planes (the AND and the item-validity select);
#   * far_scatter: 1 plane (the [128]-lane hit-vector scatter-add);
#   * tail: 1 plane (count cast + apex/middle scalar scatters, priced
#     at one plane per item — scalar work rides the vector epilogue);
#   * count-reduce: ONE [chunk,128] @ [128,128] matmul row per item =
#     128 MXU output elements (`mxu` column);
#   * gather_rows: 2 per item (the two packed bitmap row fetches).
_ITEM_VPU_PLANES = {"expand": 6, "mask_and": 2, "far_scatter": 1,
                    "tail": 1}
_ITEM_VPU = sum(_ITEM_VPU_PLANES.values())   # 10 planes x 128 lanes
_ITEM_MXU = C
_ITEM_GATHER_ROWS = 2

_SPGEMM_SCHEMA_VERSION = 1


@dataclass(frozen=True)
class SpGemmConfig:
    """chunk = items per kernel step (the fori_loop body's [chunk, 128]
    working set; GRAPE_SPGEMM_CHUNK overrides).  Part of the plan
    geometry: the item streams are padded to a chunk multiple."""

    chunk: int = 1024

    def __post_init__(self):
        if not (0 < self.chunk <= (1 << 20)):
            raise ValueError(
                f"chunk={self.chunk} not in (0, {1 << 20}]"
            )

    @staticmethod
    def from_env() -> "SpGemmConfig":
        spec = os.environ.get("GRAPE_SPGEMM_CHUNK", "")
        if not spec:
            return SpGemmConfig()
        try:
            return SpGemmConfig(chunk=int(spec))
        except ValueError as e:
            raise ValueError(
                f"GRAPE_SPGEMM_CHUNK={spec!r}: expected a positive int"
            ) from e


_PLAN_COUNTER = itertools.count()


@dataclass
class SpGemmPlan:
    """Static streams + ledger for one fragment's masked SpGEMM."""

    n_pad: int
    fnum: int
    vp: int
    n_ktiles: int                 # compacted-colspace tiles (K dim)
    words: int                    # uint32 words per bitmap row
    items: int                    # real work items across shards
    p_pad: int                    # per-shard padded item count
    rows_pad: int                 # per-shard padded bitmap height
    mask_edges: int               # kept oriented (dedup) edges
    orientation: str              # "lo" | "hi" (threshold forces hi)
    degree_threshold: int
    cfg: SpGemmConfig = field(default_factory=SpGemmConfig)
    # [fnum, ...] stacked device streams (None for plan_only plans)
    host_streams: dict | None = None
    ledger: dict = field(default_factory=dict)
    stats: dict = field(default_factory=dict)
    uid: int = field(default_factory=lambda: next(_PLAN_COUNTER))


# stream-name -> dtype table (fingerprinted in the disk-cache digest,
# like spmv_pack._STREAM_DTYPES)
_SG_DTYPES = {
    "bm": "uint32", "vrow": "int32", "urow": "int32", "kt": "int32",
    "apex": "int32", "mid": "int32", "valid": "int8", "colpid": "int32",
}


def _ledger_from_counts(items: int, mask_edges: int, n_chunks: int,
                        hbm_bytes: int) -> dict:
    """The op-budget ledger under the conventions above — the same
    shape spmv_pack.plan_ledger emits (split engine columns, per-stage
    attribution, one level), so Worker.pack_ledger and the bench
    consume both interchangeably."""
    per_stage = {
        k: v * C * items for k, v in _ITEM_VPU_PLANES.items()
    }
    vpu = sum(per_stage.values())
    mxu = _ITEM_MXU * items
    gr = _ITEM_GATHER_ROWS * items
    totals = {
        "vpu_ops": vpu, "mxu_ops": mxu, "gather_rows": gr,
        "hbm_bytes": hbm_bytes, "blocks": n_chunks,
        "per_stage": per_stage,
    }
    return {
        "edges": mask_edges,
        "levels": [{
            "level": 0, "blocks": n_chunks, "has_gather": True,
            "vpu_ops": vpu, "mxu_ops": mxu, "gather_rows": gr,
            "hbm_bytes": hbm_bytes, "per_stage": per_stage,
        }],
        "totals": totals,
    }


def _oriented_mask_edges(frag, degree_threshold: int):
    """Host-side oriented dedup edge list in GLOBAL pids, matching
    models/lcc.py's traced `oriented(oe, True)` rule exactly:

      * degree = out-degree incl. multiplicity (lcc_context degree);
      * dedup + self-loop drop (build_csr sorts, np.unique here);
      * threshold > 0 keeps the reference's "hi" orientation (the
        filter semantics of lcc.h:234-243 are DEFINED on lower-degree
        neighbor lists: a filtered OWNER contributes no list) and
        drops rows of filtered owners;
      * threshold == 0 orients "lo" (toward the higher (deg, id)
        endpoint): triangle enumeration is orientation-agnostic, and
        under "lo" the compacted column space concentrates on hubs —
        fewer K-tiles, denser pruning.

    Returns (v, u, deg) with v, u int64 pid arrays row-major sorted.
    """
    fnum, vp = frag.fnum, frag.vp
    n_pad = fnum * vp
    deg = np.zeros(n_pad, dtype=np.int64)
    vs, us = [], []
    for f in range(fnum):
        h = frag.host_oe[f]
        deg[f * vp:(f + 1) * vp] = np.diff(h.indptr)
        e = h.num_edges
        vs.append(f * vp + np.asarray(h.edge_src[:e], dtype=np.int64))
        us.append(np.asarray(h.edge_nbr[:e], dtype=np.int64))
    v = np.concatenate(vs) if vs else np.zeros(0, np.int64)
    u = np.concatenate(us) if us else np.zeros(0, np.int64)
    keep = v != u
    v, u = v[keep], u[keep]
    if len(v):
        pairs = np.unique(np.stack([v, u], 1), axis=0)
        v, u = pairs[:, 0], pairs[:, 1]
    thr = int(degree_threshold)
    if thr > 0:
        k = (deg[u] < deg[v]) | ((deg[u] == deg[v]) & (u < v))
        k &= deg[v] <= thr
        orientation = "hi"
    else:
        k = (deg[u] > deg[v]) | ((deg[u] == deg[v]) & (u > v))
        orientation = "lo"
    return v[k], u[k], deg, orientation


def plan_spgemm(frag, degree_threshold: int = 0,
                cfg: SpGemmConfig | None = None,
                plan_only: bool = False) -> SpGemmPlan:
    """Build the static masked-SpGEMM plan for `frag`.

    `plan_only=True` computes geometry, item counts and the ledger
    WITHOUT materializing device streams — the bench's modeled A/B at
    full bench geometry plans this way (the executed lane geometry
    ships real streams and is recount-gated)."""
    cfg = cfg or SpGemmConfig.from_env()
    fnum, vp = frag.fnum, frag.vp
    n_pad = fnum * vp
    v, u, deg, orientation = _oriented_mask_edges(frag, degree_threshold)
    return _plan_from_oriented(
        v, u, n_pad, fnum, vp, orientation, int(degree_threshold), cfg,
        plan_only,
    )


def plan_spgemm_edges(src, dst, n_vertices: int,
                      degree_threshold: int = 0,
                      cfg: SpGemmConfig | None = None,
                      plan_only: bool = True) -> SpGemmPlan:
    """Plan from a RAW undirected edge list (no fragment build) —
    host-side harnesses: the bench's modeled A/B at full bench
    geometry plans this way (plan_only).  Symmetrizes, dedups, drops
    self-loops and orients exactly like the fragment path (degree =
    symmetrized adjacency degree incl. multiplicity)."""
    cfg = cfg or SpGemmConfig.from_env()
    vp = -(-int(n_vertices) // C) * C
    a = np.concatenate([np.asarray(src, np.int64),
                        np.asarray(dst, np.int64)])
    b = np.concatenate([np.asarray(dst, np.int64),
                        np.asarray(src, np.int64)])
    keep = a != b
    a, b = a[keep], b[keep]
    deg = np.bincount(a, minlength=vp)
    if len(a):
        pairs = np.unique(np.stack([a, b], 1), axis=0)
        a, b = pairs[:, 0], pairs[:, 1]
    thr = int(degree_threshold)
    if thr > 0:
        k = (deg[b] < deg[a]) | ((deg[b] == deg[a]) & (b < a))
        k &= deg[a] <= thr
        orientation = "hi"
    else:
        k = (deg[b] > deg[a]) | ((deg[b] == deg[a]) & (b > a))
        orientation = "lo"
    return _plan_from_oriented(
        a[k], b[k], vp, 1, vp, orientation, thr, cfg, plan_only
    )


def _plan_from_oriented(v, u, n_pad, fnum, vp, orientation, thr,
                        cfg: SpGemmConfig, plan_only: bool) -> SpGemmPlan:
    E = len(v)
    # ---- compacted, popularity-sorted column (w) space ----
    colcnt = np.bincount(u, minlength=n_pad)
    cols = np.argsort(-colcnt, kind="stable")
    cols = cols[colcnt[cols] > 0]
    colmap = np.full(n_pad, -1, dtype=np.int64)
    colmap[cols] = np.arange(len(cols))
    n_ktiles = max(1, -(-len(cols) // C))
    words = n_ktiles * WPT

    # ---- bitmap row space: vertices with oriented out-edges ----
    rowcnt = np.bincount(v, minlength=n_pad)
    rows = np.flatnonzero(rowcnt > 0)
    rowmap = np.full(n_pad, -1, dtype=np.int64)
    rowmap[rows] = np.arange(len(rows))
    n_rows = max(1, len(rows))

    # ---- per-row K-tile incidence (u64 bitset) for pruning ----
    kt_of_u = colmap[u] // C
    kwords = (n_ktiles + 63) // 64
    ktbm = np.zeros((n_rows, kwords), dtype=np.uint64)
    rk = np.unique(rowmap[v] * n_ktiles + kt_of_u)
    rr, kk = rk // n_ktiles, rk % n_ktiles
    np.bitwise_or.at(
        ktbm, (rr, kk // 64),
        np.uint64(1) << (kk % 64).astype(np.uint64),
    )

    # items: per mask edge, the K-tiles where BOTH rows have bits
    # (u ∉ rowspace has no list -> no items; the edge contributes 0)
    vr_all = rowmap[v]
    ur_all = rowmap[u]
    has_u = ur_all >= 0
    items = 0
    items_by_fid = np.zeros(fnum, dtype=np.int64)
    item_e: list = []
    item_k: list = []
    step = max(1, (1 << 24) // max(n_ktiles, 1))
    sel = np.flatnonzero(has_u)
    for lo in range(0, len(sel), step):
        s = sel[lo:lo + step]
        both = ktbm[vr_all[s]] & ktbm[ur_all[s]]
        bits = (
            (both[:, :, None] >> np.arange(64, dtype=np.uint64)) & 1
        ).astype(bool).reshape(len(s), kwords * 64)[:, :n_ktiles]
        per_edge = bits.sum(axis=1).astype(np.int64)
        np.add.at(items_by_fid, (v[s] // vp).astype(np.int64), per_edge)
        if plan_only:
            items += int(per_edge.sum())
        else:
            ei, ki = np.nonzero(bits)
            items += len(ei)
            item_e.append(s[ei])
            item_k.append(ki.astype(np.int64))

    stats = {
        "mask_edges": E, "items": items,
        "items_per_edge": round(items / max(1, E), 3),
        "n_ktiles": n_ktiles, "colspace": int(len(cols)),
        "rowspace": int(len(rows)), "orientation": orientation,
    }

    if plan_only:
        # byte model mirrors the materialized layout: item streams pad
        # to the PER-SHARD max (not the total — billing fnum x total
        # would inflate the spgemm HBM cost ~fnum-fold and bias the
        # auto decision toward intersect); the stacked sub-bitmap is
        # modeled at the full height once (a lower bound — hub rows
        # duplicate across shards in the shipped form)
        rows_pad = n_rows
        p_max = int(items_by_fid.max()) if fnum > 1 else items
        p_pad = max(cfg.chunk,
                    -(-max(1, p_max) // cfg.chunk) * cfg.chunk)
        hbm = (rows_pad * words * 4
               + fnum * p_pad * (5 * 4 + 1)
               + fnum * n_ktiles * C * 4)
        n_chunks = fnum * (p_pad // cfg.chunk)
        return SpGemmPlan(
            n_pad=n_pad, fnum=fnum, vp=vp, n_ktiles=n_ktiles,
            words=words, items=items, p_pad=p_pad, rows_pad=rows_pad,
            mask_edges=E, orientation=orientation, degree_threshold=thr,
            cfg=cfg, host_streams=None,
            ledger=_ledger_from_counts(items, E, n_chunks, hbm),
            stats=stats,
        )

    e_idx = (np.concatenate(item_e) if item_e
             else np.zeros(0, np.int64))
    k_idx = (np.concatenate(item_k) if item_k
             else np.zeros(0, np.int64))

    # ---- packed adjacency bitmap over the compacted colspace ----
    bm = np.zeros((n_rows, words), dtype=np.uint32)
    cw = colmap[u]
    np.bitwise_or.at(
        bm, (rowmap[v], (cw // 32).astype(np.int64)),
        (np.uint32(1) << (cw % 32).astype(np.uint32)),
    )

    # colspace block -> pid table (far-end credit scatter targets);
    # padding lanes hit the n_pad sink row
    colpid = np.full(n_ktiles * C, n_pad, dtype=np.int32)
    colpid[:len(cols)] = cols.astype(np.int32)

    # ---- partition items by apex fragment, build per-shard streams ----
    fid_of = (v[e_idx] // vp).astype(np.int64) if len(e_idx) else \
        np.zeros(0, np.int64)
    per_shard = [np.flatnonzero(fid_of == f) for f in range(fnum)]
    p_real = [len(s) for s in per_shard]
    p_max = max([1] + p_real)
    p_pad = -(-p_max // cfg.chunk) * cfg.chunk

    sub_rows = []
    for f in range(fnum):
        s = per_shard[f]
        need = np.unique(np.concatenate([
            vr_all[e_idx[s]], ur_all[e_idx[s]],
        ])) if len(s) else np.zeros(0, np.int64)
        sub_rows.append(need)
    rows_pad = max(1, max(len(r) for r in sub_rows))

    st = {
        "bm": np.zeros((fnum, rows_pad, words), np.uint32),
        "vrow": np.zeros((fnum, p_pad), np.int32),
        "urow": np.zeros((fnum, p_pad), np.int32),
        "kt": np.zeros((fnum, p_pad), np.int32),
        "apex": np.full((fnum, p_pad), n_pad, np.int32),
        "mid": np.full((fnum, p_pad), n_pad, np.int32),
        "valid": np.zeros((fnum, p_pad), np.int8),
        "colpid": np.tile(colpid, (fnum, 1)),
    }
    for f in range(fnum):
        s = per_shard[f]
        if not len(s):
            continue
        need = sub_rows[f]
        local = np.full(n_rows, 0, dtype=np.int64)
        local[need] = np.arange(len(need))
        st["bm"][f, :len(need)] = bm[need]
        n = len(s)
        ei = e_idx[s]
        st["vrow"][f, :n] = local[vr_all[ei]].astype(np.int32)
        st["urow"][f, :n] = local[ur_all[ei]].astype(np.int32)
        st["kt"][f, :n] = k_idx[s].astype(np.int32)
        st["apex"][f, :n] = v[ei].astype(np.int32)
        st["mid"][f, :n] = u[ei].astype(np.int32)
        st["valid"][f, :n] = 1

    hbm = sum(int(a.nbytes) for a in st.values())
    n_chunks = fnum * (p_pad // cfg.chunk)
    stats["item_imbalance"] = round(
        p_max / max(1.0, items / max(1, fnum)), 3
    )
    return SpGemmPlan(
        n_pad=n_pad, fnum=fnum, vp=vp, n_ktiles=n_ktiles, words=words,
        items=items, p_pad=p_pad, rows_pad=rows_pad, mask_edges=E,
        orientation=orientation, degree_threshold=thr, cfg=cfg,
        host_streams=st,
        ledger=_ledger_from_counts(items, E, n_chunks, hbm),
        stats=stats,
    )


# --------------------------------------------------------------------------
# device executor
# --------------------------------------------------------------------------


def spgemm_credits(state: dict, prefix: str, n_pad: int, chunk: int):
    """Traced per-shard credit pass: returns the [n_pad] int32 partial
    triangle-credit vector (apex + middle + far contributions of this
    shard's items; caller psums across shards).

    Stage per chunk: gather the two packed rows' K-tile words, expand
    to dense uint8 [chunk, 128] blocks, AND + validity-mask, count via
    the [chunk, 128] @ [128, 128] matmul (the PR 4 MXU lowering
    shape), scatter cnt to apex/middle pids and the hit vector to the
    tile's far-end pids."""
    import jax.numpy as jnp
    from jax import lax

    bm = state[prefix + "bm"]
    vrow = state[prefix + "vrow"]
    urow = state[prefix + "urow"]
    kt = state[prefix + "kt"]
    apex = state[prefix + "apex"]
    mid = state[prefix + "mid"]
    valid = state[prefix + "valid"]
    colpid = state[prefix + "colpid"]
    p = vrow.shape[0]
    n_chunks = p // chunk
    # count-reduce operand: ones in column 0 — the matmul emits the
    # row sums in lane 0 (output shape [chunk, 128], the validated
    # [B,128] @ [128,128] form)
    ones = jnp.zeros((C, C), jnp.float32).at[:, 0].set(1.0)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    wiota = jnp.arange(WPT, dtype=jnp.int32)
    liota = jnp.arange(C, dtype=jnp.int32)

    def body(i, cred):
        def sl(a):
            return lax.dynamic_slice(a, (i * chunk,), (chunk,))

        vr, ur, k, ap, md, vd = (
            sl(vrow), sl(urow), sl(kt), sl(apex), sl(mid), sl(valid)
        )
        wcol = k[:, None] * WPT + wiota[None, :]
        vw = bm[vr[:, None], wcol]                       # [c, WPT] u32
        uw = bm[ur[:, None], wcol]
        vb = ((vw[:, :, None] >> shifts) & 1).reshape(chunk, C)
        ub = ((uw[:, :, None] >> shifts) & 1).reshape(chunk, C)
        hits = (vb & ub).astype(jnp.float32)
        hits = hits * vd[:, None].astype(jnp.float32)
        cnt = jnp.dot(
            hits, ones, preferred_element_type=jnp.float32
        )[:, 0].astype(jnp.int32)
        cred = cred.at[ap].add(cnt)
        cred = cred.at[md].add(cnt)
        far = colpid[k[:, None] * C + liota[None, :]]    # [c, C] pids
        cred = cred.at[far.reshape(-1)].add(
            hits.astype(jnp.int32).reshape(-1)
        )
        return cred

    cred = jnp.zeros((n_pad + 1,), jnp.int32)
    cred = lax.fori_loop(0, n_chunks, body, cred)
    return cred[:n_pad]


# --------------------------------------------------------------------------
# dispatch resolution: per-fragment cache + persistent plan cache
# --------------------------------------------------------------------------


class SpGemmDispatch:
    """Resolved spgemm backend for one fragment: the plan plus the
    state-entry plumbing (streams ride as ephemeral [fnum, ...] state
    leaves, the spmv_pack PackDispatch convention)."""

    def __init__(self, plan: SpGemmPlan, prefix: str = "sg_"):
        self.plan = plan
        self.prefix = prefix

    @property
    def uid(self) -> int:
        return self.plan.uid

    @property
    def chunk(self) -> int:
        return self.plan.cfg.chunk

    def state_entries(self) -> dict:
        assert self.plan.host_streams is not None, \
            "plan_only plans ship no streams"
        return {
            self.prefix + k: v for k, v in self.plan.host_streams.items()
        }

    def state_keys(self):
        return [self.prefix + k for k in _SG_DTYPES]

    def ledger(self) -> dict:
        return self.plan.ledger

    def credits(self, state: dict):
        return spgemm_credits(
            state, self.prefix, self.plan.n_pad, self.chunk
        )


def resolve_spgemm_dispatch(frag, degree_threshold: int = 0,
                            cfg: SpGemmConfig | None = None,
                            prefix: str = "sg_") -> SpGemmDispatch:
    """Resolve (and cache) the spgemm plan for `frag`: per-fragment
    memo first, then the persistent plan cache (GRAPE_PACK_PLAN_CACHE,
    `spgemmplan_*` entries — digest-disjoint from pack plans by
    construction), then the host planner.  Counters in SPGEMM_STATS
    mirror spmv_pack.PLAN_STATS."""
    from libgrape_lite_tpu.ops.spmv_pack import _frag_cache

    cfg = cfg or SpGemmConfig.from_env()
    per_frag = _frag_cache(frag)
    key = ("spgemm", cfg, int(degree_threshold))
    if key in per_frag:
        SPGEMM_STATS["frag_cache_hits"] += 1
        return SpGemmDispatch(per_frag[key], prefix)
    v, u, deg, orientation = _oriented_mask_edges(frag, degree_threshold)
    plan = _load_cached_plan(v, u, frag, degree_threshold, cfg)
    if plan is not None:
        SPGEMM_STATS["disk_cache_hits"] += 1
    else:
        SPGEMM_STATS["planned"] += 1
        plan = _plan_from_oriented(
            v, u, frag.fnum * frag.vp, frag.fnum, frag.vp, orientation,
            int(degree_threshold), cfg, plan_only=False,
        )
        _save_cached_plan(plan, v, u, frag, degree_threshold, cfg)
    per_frag[key] = plan
    return SpGemmDispatch(plan, prefix)


def _spgemm_digest(v, u, frag, thr: int, cfg: SpGemmConfig) -> str:
    """Content key for cached spgemm plans.  `backend: spgemm` and the
    spgemm schema version are IN the digest (and the filename prefix
    differs), so a pack plan and a spgemm plan can never share a disk
    entry even for identical edge streams."""
    import hashlib

    from libgrape_lite_tpu.ft.fingerprint import stable_config_digest

    fp = stable_config_digest({
        "backend": "spgemm",
        "schema": _SPGEMM_SCHEMA_VERSION,
        "chunk": cfg.chunk,
        "thr": int(thr),
        "fnum": frag.fnum,
        "vp": frag.vp,
        "stream_dtypes": _SG_DTYPES,
    })
    h = hashlib.sha256()
    h.update(fp.encode())
    h.update(np.ascontiguousarray(v, np.int64).tobytes())
    h.update(np.ascontiguousarray(u, np.int64).tobytes())
    return h.hexdigest()[:24]


def _plan_cache_path(v, u, frag, thr, cfg):
    root = os.environ.get("GRAPE_PACK_PLAN_CACHE")
    if not root:
        return None
    return os.path.join(
        root, f"spgemmplan_{_spgemm_digest(v, u, frag, thr, cfg)}.npz"
    )


def _save_cached_plan(plan: SpGemmPlan, v, u, frag, thr, cfg):
    import json

    path = _plan_cache_path(v, u, frag, thr, cfg)
    if path is None or plan.host_streams is None:
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    meta = {
        "n_pad": plan.n_pad, "fnum": plan.fnum, "vp": plan.vp,
        "n_ktiles": plan.n_ktiles, "words": plan.words,
        "items": plan.items, "p_pad": plan.p_pad,
        "rows_pad": plan.rows_pad, "mask_edges": plan.mask_edges,
        "orientation": plan.orientation,
        "degree_threshold": plan.degree_threshold,
        "chunk": plan.cfg.chunk,
        "ledger": plan.ledger, "stats": plan.stats,
    }
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            __meta=np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8
            ).copy(),
            **plan.host_streams,
        )
    os.replace(tmp, path)


def _load_cached_plan(v, u, frag, thr, cfg) -> SpGemmPlan | None:
    import json

    path = _plan_cache_path(v, u, frag, thr, cfg)
    if path is None or not os.path.exists(path):
        return None
    try:
        z = np.load(path)
        meta = json.loads(bytes(z["__meta"]))
        if meta["chunk"] != cfg.chunk:
            return None
        streams = {k: z[k] for k in z.files if k != "__meta"}
        return SpGemmPlan(
            n_pad=meta["n_pad"], fnum=meta["fnum"], vp=meta["vp"],
            n_ktiles=meta["n_ktiles"], words=meta["words"],
            items=meta["items"], p_pad=meta["p_pad"],
            rows_pad=meta["rows_pad"], mask_edges=meta["mask_edges"],
            orientation=meta["orientation"],
            degree_threshold=meta["degree_threshold"], cfg=cfg,
            host_streams=streams, ledger=meta["ledger"],
            stats=meta["stats"],
        )
    except Exception:
        return None  # corrupt/stale cache entries are rebuilt


# --------------------------------------------------------------------------
# backend selection + stats
# --------------------------------------------------------------------------


# resolve-path counters + the decision/decline record.  `declines` and
# `decisions` are bounded lists of structured records — every backend
# request that does NOT engage spgemm leaves a trace here, never a
# silent fallback.  Federated as "spgemm" (obs/federation.py): a dict
# subclass, so the mutation sites below are unchanged.
from libgrape_lite_tpu.obs.federation import FederatedStats as _FedStats

SPGEMM_STATS = _FedStats("spgemm", {
    "planned": 0, "frag_cache_hits": 0, "disk_cache_hits": 0,
    "auto_spgemm": 0, "auto_intersect": 0,
    "declines": [], "decisions": [],
})
_STATS_CAP = 64


def spgemm_stats() -> dict:
    """Snapshot of the spgemm resolve/decision counters (copy)."""
    out = dict(SPGEMM_STATS)
    out["declines"] = list(SPGEMM_STATS["declines"])
    out["decisions"] = list(SPGEMM_STATS["decisions"])
    return out


def _record(kind: str, rec: dict):
    lst = SPGEMM_STATS[kind]
    if len(lst) >= _STATS_CAP:
        del lst[0]
    lst.append(rec)


def record_decline(app: str, reason: str, requested: str):
    """A backend request that falls back to intersect — RECORDED, and
    vlogged, never silent."""
    from libgrape_lite_tpu.utils import logging as glog

    _record("declines", {
        "app": app, "reason": reason, "requested": requested,
    })
    glog.log_info(
        "spgemm backend declined for %s (requested %s): %s",
        app, requested, reason,
    )


def lcc_backend_mode() -> str:
    mode = os.environ.get("GRAPE_LCC_BACKEND", "intersect")
    if mode not in ("intersect", "spgemm", "auto"):
        raise ValueError(
            f"GRAPE_LCC_BACKEND={mode!r}: expected 'intersect', "
            "'spgemm' or 'auto'"
        )
    return mode


def intersect_ledger(frag, chunk: int) -> dict:
    """Modeled popcount-intersect cost for models/lcc.py's kernel on
    this fragment's geometry: per ring step (fnum of them) the kernel
    sweeps every padded oe chunk (apex + middle pass) and ie chunk
    (far-end pass), each slot paying 3 word-ops per bitmap word (AND,
    popcount, reduce) over n_pad/32 words.  Bytes: the two packed
    bitmap families resident per shard plus the rotating block
    traffic."""
    ep_oe = len(frag.host_oe[0].edge_src)
    ep_ie = len((frag.host_ie or frag.host_oe)[0].edge_src)
    return intersect_ledger_geom(
        frag.fnum * frag.vp, ep_oe, ep_ie, frag.fnum, frag.vp, chunk
    )


def intersect_ledger_geom(n_pad: int, ep_oe: int, ep_ie: int,
                          fnum: int, vp: int, chunk: int) -> dict:
    """`intersect_ledger` on raw geometry (no fragment) — the bench's
    modeled A/B at full bench geometry prices this way."""
    words = (n_pad + 31) // 32
    c_oe = max(1, min(chunk, ep_oe))
    c_ie = max(1, min(chunk, ep_ie))
    slots = (max(1, -(-ep_oe // c_oe)) * c_oe
             + max(1, -(-ep_ie // c_ie)) * c_ie)
    word_ops = fnum * fnum * slots * 3 * words
    hbm = fnum * (2 * vp * words * 4)
    return {
        "word_ops": word_ops,
        "word_ops_per_edge": round(word_ops / max(1, fnum * ep_oe), 1),
        "hbm_bytes": hbm,
        "words": words,
        "chunk": chunk,
    }


def price_backends(spgemm_ledger: dict, intersect: dict,
                   profile=None) -> dict:
    """Modeled seconds for both backends at the shared profile rates
    (the pack cost model's conventions: VPU lanes + MXU elems + gather
    rows summed, HBM concurrent).  `profile` defaults to the active
    RateProfile — a fitted profile re-prices the auto choice."""
    from libgrape_lite_tpu.ops.calibration import active_profile

    p = profile or active_profile()
    t = spgemm_ledger["totals"]
    sp = max(
        t["vpu_ops"] / p.vpu_lanes_per_cycle / p.clock_hz
        + t["mxu_ops"] * p.mxu_cyc_per_elem / p.clock_hz
        + t["gather_rows"] / p.gather_rows_per_cycle / p.clock_hz,
        t["hbm_bytes"] / p.hbm_bps,
    )
    it = max(
        intersect["word_ops"] / p.vpu_lanes_per_cycle / p.clock_hz,
        intersect["hbm_bytes"] / p.hbm_bps,
    )
    return {
        "t_spgemm_s": sp, "t_intersect_s": it,
        "spgemm_wins": bool(sp < it),
        "profile": p.label(),
    }


def resolve_lcc_backend(app_name: str, frag,
                        degree_threshold: int = 0,
                        chunk: int = 4096,
                        supported: bool = True,
                        unsupported_reason: str = "") -> str:
    """The GRAPE_LCC_BACKEND resolution an LCC-family app runs at
    init_state: returns "intersect" or "spgemm", recording every
    non-intersect request's outcome in SPGEMM_STATS.

    `supported=False` (lcc_beta's merge kernel, lcc_directed's
    direction-weighted counts) always yields intersect — with a
    RECORDED decline when the env asked for spgemm/auto."""
    mode = lcc_backend_mode()
    if mode == "intersect":
        return "intersect"
    if not supported:
        record_decline(app_name, unsupported_reason or
                       "app has no spgemm lowering", mode)
        return "intersect"
    if getattr(frag, "dyn_overlay", None) is not None:
        record_decline(
            app_name,
            "dyn overlay attached: the host-planned bitmap would go "
            "stale against staged deltas", mode,
        )
        return "intersect"
    if mode == "spgemm":
        _record("decisions", {
            "app": app_name, "mode": mode, "backend": "spgemm",
        })
        return "spgemm"
    # auto: price both from the ledgers.  The pricing plan is memoized
    # in the per-fragment cache (keyed like the engaged plan, with a
    # "price" tag) so serve-style Worker churn re-prices for free; an
    # already-engaged materialized plan is reused directly — its
    # ledger is the exact one the recount gate validates
    from libgrape_lite_tpu.ops.spmv_pack import _frag_cache

    cfg = SpGemmConfig.from_env()
    per_frag = _frag_cache(frag)
    plan = per_frag.get(("spgemm", cfg, int(degree_threshold)))
    if plan is None:
        price_key = ("spgemm-price", cfg, int(degree_threshold))
        plan = per_frag.get(price_key)
        if plan is None:
            plan = plan_spgemm(frag, degree_threshold, cfg=cfg,
                               plan_only=True)
            per_frag[price_key] = plan
    prices = price_backends(plan.ledger, intersect_ledger(frag, chunk))
    backend = "spgemm" if prices["spgemm_wins"] else "intersect"
    SPGEMM_STATS["auto_spgemm" if prices["spgemm_wins"]
                 else "auto_intersect"] += 1
    rec = {
        "app": app_name, "mode": "auto", "backend": backend,
        "t_spgemm_s": round(prices["t_spgemm_s"], 6),
        "t_intersect_s": round(prices["t_intersect_s"], 6),
        "items": plan.items, "mask_edges": plan.mask_edges,
        "profile": prices["profile"],
    }
    _record("decisions", rec)
    if backend == "intersect":
        record_decline(
            app_name,
            f"auto: modeled intersect {prices['t_intersect_s']:.2e}s "
            f"beats spgemm {prices['t_spgemm_s']:.2e}s", mode,
        )
    return backend
