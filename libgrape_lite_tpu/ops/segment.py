"""Segment reductions — the TPU ForEachEdge.

The reference parallelises per-edge work with its CPU ParallelEngine
(`grape/parallel/parallel_engine.h:32-719`) and the CUDA load-balancing
kernel catalog (`grape/cuda/parallel/parallel_engine.h:42-1444`,
cm/wm/cta/strict policies).  On TPU the same problem — distribute
variable-degree adjacency work evenly — is solved by *edge-major*
layout: per-edge values keyed by their row id, reduced with XLA segment
ops, which lower to sorted-scatter kernels the compiler tiles evenly.
A Pallas row-blocked variant lives alongside for the hot SpMV path.
"""

from __future__ import annotations

import jax.numpy as jnp
import jax.ops as jops


def segment_reduce(values, segment_ids, num_rows: int, kind: str = "sum",
                   sorted_ids: bool = True):
    """Reduce `values` by `segment_ids` into `num_rows` rows.

    Ids equal to `num_rows` (padding convention) land in an overflow row
    that is sliced off — mirroring the reference's convention of routing
    invalid work to a trash slot rather than branching.

    `sorted_ids` defaults True because CSR edge arrays are built sorted
    by row (graph/csr.py) — XLA lowers sorted segment reductions to a
    cheaper scan-style kernel than the general scatter.
    """
    fn = {
        "sum": jops.segment_sum,
        "min": jops.segment_min,
        "max": jops.segment_max,
        "prod": jops.segment_prod,
    }[kind]
    out = fn(
        values, segment_ids, num_segments=num_rows + 1,
        indices_are_sorted=sorted_ids,
    )
    return out[:num_rows]
