"""Pack-gather SpMV: sorted segment-sum at vector-unit rate on TPU.

Replaces the XLA gather + segment_sum pull (measured ~8.7 ns/element
EACH on real v5e hardware — docs/PERF_NOTES.md) with a fully static
Pallas pipeline in which every data movement is a lane gather, a
sublane gather, or a static 3-stage shuffle (ops/route3.py):

  per block [SUB, 128] of edge slots (host-planned, static):
    1. GATHER   x values: non-hub edges sit at a slot whose lane is
       the XOR-mixed `_lane_mix(col)` (plain col%128 is skewed on
       Kronecker ids), so ONE sublane dynamic_gather from the
       VMEM-resident, lane-mixed x-table [SUB, 128] (pass p holds
       x[p*SUB*128:(p+1)*SUB*128]) fetches x[col] for the whole block; hub columns (the top-HUB
       most referenced, which would overflow lane capacity) read a
       tiny [HUB/128, 128] register table via lane gathers + selects.
    2. ROUTE    gathered values back to CSR (row-sorted) slot order —
       a static 3-stage shuffle.
    3. SCAN     segmented sums over the flattened block.  Default
       (GRAPE_PACK_SCAN=mxu): MXU prefix sums — a [SUB,128] @
       tri[128,128] triangular matmul per row, a chained per-group
       inter-row tail prefix, and segment restoration through two
       static gathers against host-planned start planes (ps/bk) —
       flat 10 VPU ops/slot with the heavy lifting on the matrix
       unit.  Fallback (=shift, and always for min/max semirings):
       ceil(log2(max_seglen)) span-aware shift-add stages against a
       static segment-start flag stream.  Engagement is per level by
       modeled cost (see _decide_level_scan).
    4. EXTRACT  each row's last-slot scan value (= the row's partial
       sum within the block) into a compact [OUT_SUB, 128] stream —
       another static shuffle.
  fold levels: the per-block partial streams are grouped (<= SUB //
  OUT_SUB streams per group, bounded by output capacity), re-sorted by
  row with a static shuffle, and reduced by the same scan+extract
  kernel — recursively, until one block remains; the final level's
  extraction targets slot == row id, so the result lands as the dense
  [vp] output with no scatter of any kind.

The reference counterpart is the CUDA LB-kernel catalog
(`grape/cuda/parallel/parallel_engine.h:42-1444`) — the machinery that
makes per-edge work run at hardware rate.  On TPU that machinery is
this file: all irregularity is compiled into static routes at plan
time; the per-round dataflow is dense vector work.

Plans are built once per (fragment, dtype) and reused every round;
planning cost is O(E log) numpy (cacheable alongside the fragment
serialization cache).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from libgrape_lite_tpu.ops.route3 import (
    Route3,
    plan_lane_aligned_rows,
    plan_route,
)

C = 128


def _compose_enabled() -> bool:
    """Route composition (upstream extraction lands directly in the
    downstream fold's sorted layout, collapsing the fold merge route to
    one sublane move) is on by default; GRAPE_PACK_COMPOSE=0 reverts to
    the generic 3-stage fold routes for A/B and debugging."""
    import os

    return os.environ.get("GRAPE_PACK_COMPOSE", "1") not in ("0", "")


def _scan_mode() -> str:
    """Segmented-scan backend: "mxu" (default) restores segment sums
    from MXU triangular-matmul prefix sums; "shift" is the log-stage
    shift-add ladder kept as the A/B fallback (GRAPE_PACK_SCAN=shift).
    Engagement is per LEVEL and only where the modeled VPU cost wins
    (see _decide_level_scan) — shallow-ladder blocks keep the shift
    form even in mxu mode, and min/max semirings always run the ladder
    (a matmul cannot evaluate a tropical prefix)."""
    import os

    mode = os.environ.get("GRAPE_PACK_SCAN", "mxu")
    if mode not in ("mxu", "shift"):
        raise ValueError(
            f"GRAPE_PACK_SCAN={mode!r}: expected 'mxu' or 'shift'"
        )
    return mode


def _scan_stages_for(rows_sorted: np.ndarray) -> int:
    """ceil(log2(max segment run)) — the number of shift-combine scan
    stages that provably reach every segment's start.  After S stages
    the flag window spans 2^S slots, so any position whose segment
    start lies within max_seglen-1 <= 2^S - 1 behind it is fully
    blocked; every further stage combines with the exact identity and
    is a bit-exact no-op.  Zero stages when every segment has length 1
    (degree-1 tails; the scan is the identity)."""
    e = len(rows_sorted)
    if e == 0:
        return 0
    ch = np.nonzero(np.diff(rows_sorted))[0]
    bounds = np.concatenate([[-1], ch, [e - 1]])
    max_run = int(np.diff(bounds).max())
    return max(0, int(np.ceil(np.log2(max(1, max_run)))))


def _mxu_group_rows(sub: int) -> int:
    """Sublane-group height of the MXU scan's inter-row carry: 128-row
    groups when they tile `sub` evenly (the [128, 128] matmul operand
    the MXU is built for), else one group spanning the whole block
    (tiny test geometries)."""
    return 128 if sub % 128 == 0 else sub


def _mxu_scan_meta(rows_sorted: np.ndarray, sub: int):
    """Static restoration planes for the MXU segmented scan of one
    block over CSR-sorted rows (see the mxu branch of _kernel_body for
    the device-side consumption):

      ps [sub, C] int8: per slot, the lane of its segment's start when
         the segment starts IN this row (the in-row restore subtracts
         the exclusive row prefix at that lane); 0 for slots whose
         segment carried in from an earlier row (exclusive prefix at
         lane 0 is exactly 0, so they subtract nothing); the slot's OWN
         lane for invalid slots (self-isolating: rseg degenerates to
         the slot's raw value, which nothing downstream reads).
      bk [sub, C] int: per slot, how many rows back its segment
         started (0 when it starts in-row); the carry restoration
         subtracts the exact full row-tail prefix W at row `r - bk`
         (W[r] - W[r] = 0 for in-row segments — no mask plane).

    The ladder-path flag `f0 = (ps == lane) & (bk == 0)` recovers the
    shift scan's segment-start-or-invalid flag exactly (min/max kinds
    run the ladder off these same planes), so mxu blocks ship ps/bk
    INSTEAD of the flag plane."""
    e = len(rows_sorted)
    slots = sub * C
    lane = (np.arange(slots, dtype=np.int64) % C)
    ps = lane.copy()
    bk = np.zeros(slots, dtype=np.int64)
    if e:
        i = np.arange(e, dtype=np.int64)
        s = np.ones(e, dtype=bool)
        s[1:] = rows_sorted[1:] != rows_sorted[:-1]
        start = np.maximum.accumulate(np.where(s, i, 0))
        srow, slane = start // C, start % C
        r = i // C
        same = srow == r
        ps[:e] = np.where(same, slane, 0)
        bk[:e] = np.where(same, 0, r - srow)
    return ps.reshape(sub, C).astype(np.int8), bk.reshape(sub, C)


# Modeled per-slot VPU ops of the MXU scan (matmuls priced in the
# separate mxu column): exclusive-rowcum subtract, ps gather, in-row
# subtract, the W group concat + chained base add, SR iota + subtract,
# W gather, carry subtract, final add.  Flat — the full-prefix carry
# has no span-dependent ladder.
_MXU_SCAN_VPU = 10
# MXU matmul output planes per block: the lane cumsum, the per-group
# tail broadcasts, and the per-group exclusive tail prefixes.
_MXU_SCAN_PLANES = 3


def _decide_level_scan(blocks) -> bool:
    """Engage the MXU scan for a level iff GRAPE_PACK_SCAN=mxu and the
    summed modeled VPU cost across the level's blocks beats the shift
    ladder's (3 ops per span-aware stage, plus the flag compare the
    mxu form drops).  Per level, not per block: a level's blocks share
    stacked streams and one kernel family, so the scan form must be
    uniform within it."""
    if _scan_mode() != "mxu" or not blocks:
        return False
    shift = sum(3 * b.scan_stages + 1 for b in blocks)
    return _MXU_SCAN_VPU * len(blocks) < shift


def _lane_mix(local: np.ndarray) -> np.ndarray:
    """Static lane assignment for a pass-local column id.

    Plain `col % 128` is pathologically skewed on Kronecker/RMAT
    graphs: high-degree ids have many trailing zero bits, so lane 0
    receives ~8x its share and blocks cut at ~12% fill.  XOR-folding
    the next id bits into the lane decorrelates degree from lane while
    staying a bijection per table row (a per-row constant XOR), so the
    kernel recovers the layout with one computed lane gather on the
    x-table (`tab[r, l] = x[r*128 + (l ^ mix(r))]`)."""
    r = local >> 7
    return (local ^ r ^ (r >> 7)) & (C - 1)


def _row_mix(r):
    """The per-table-row XOR constant of `_lane_mix` (kernel side)."""
    return (r ^ (r >> 7)) & (C - 1)


@dataclass(frozen=True)
class PackConfig:
    # sub=2048 keeps the worst gather-level VMEM residency (streams
    # double-buffered + x-table + f32 temps) within the ~16 MB/core
    # budget of v5e — see vmem_bytes(); sub=4096 overflows it
    sub: int = 2048        # sublane rows per block (block = sub*128 slots)
    out_sub: int = 512     # sublane rows per compact output block
    # hub=4096 (r7, was 1024): the padded-hub-table read costs two
    # shape-matched gathers REGARDLESS of hub size (the old register
    # loop scaled with hub//C, which is why 1024 was chosen), and a
    # 4x hub absorbs enough Kronecker skew to lift gather-block fill
    # from ~67% to ~87% at bench geometry (1.5 -> 1.15 slots/edge) —
    # every per-slot stream byte and VPU op scales down with it.
    # out_sub=1024 was probed and REJECTED: the distinct-rows cap is
    # not the binding cutter (block counts unchanged) and halving the
    # fold group_cap balloons the fold hierarchy (26.8 -> 32.9 B/edge).
    hub: int = 4096        # hub table size (multiple of 128)

    def __post_init__(self):
        # sub/hub index streams are int16 and hub rows split into
        # [hub/128, 128] register tiles — enforce the ranges the device
        # dtypes silently assume (ADVICE r2: a sub > 32767 would wrap
        # on astype(int16) with no error)
        if not (0 < self.sub <= 32767):
            raise ValueError(f"sub={self.sub} not in (0, 32767]")
        if not (0 < self.hub <= 32767) or self.hub % C:
            raise ValueError(
                f"hub={self.hub} must be a positive multiple of {C} "
                "<= 32767"
            )
        if self.hub // C > self.sub:
            # the hub read is two dynamic gathers from a hub table
            # padded to [sub, C] (Mosaic's sublane gather requires
            # table shape == index shape); a hub taller than the block
            # cannot pad down
            raise ValueError(
                f"hub={self.hub} needs {self.hub // C} register rows "
                f"> sub={self.sub}"
            )
        if not (0 < self.out_sub <= self.sub):
            raise ValueError(
                f"out_sub={self.out_sub} not in (0, sub={self.sub}]"
            )

    @property
    def slots(self) -> int:
        return self.sub * C

    @staticmethod
    def from_env() -> "PackConfig":
        """Default config, overridable via GRAPE_PACK_CFG
        ("sub=64,out_sub=16,hub=128").  Lets harnesses (dryrun, probes)
        shrink the plan geometry through the real call path instead of
        monkeypatching the planner (VERDICT r4 weak #5)."""
        import os

        spec = os.environ.get("GRAPE_PACK_CFG", "")
        if not spec:
            return PackConfig()
        parts = [p for p in spec.split(",") if p]
        if any("=" not in p for p in parts):
            raise ValueError(
                f"GRAPE_PACK_CFG={spec!r}: expected comma-separated "
                "key=value tokens (e.g. 'sub=64,out_sub=16,hub=128')"
            )
        kv = dict(p.split("=", 1) for p in parts)
        allowed = {"sub", "out_sub", "hub"}
        bad = set(kv) - allowed
        if bad:
            raise ValueError(f"GRAPE_PACK_CFG unknown keys: {sorted(bad)}")
        return PackConfig(**{k: int(v) for k, v in kv.items()})

    @property
    def max_distinct(self) -> int:
        return self.out_sub * C

    def vmem_bytes(self, has_gather: bool, has_w: bool,
                   out_sub: int | None = None) -> int:
        """Worst-case VMEM residency estimate for one level's kernel:
        grid-varying streams are double-buffered by the Pallas
        pipeline (x2); grid-invariant tables buffer once; plus the f32
        working set (routed block, scan value+flag planes, one int32
        upcast of an index stream at a time).  An estimate, not a
        Mosaic quote — plan_pack warns when it exceeds
        GRAPE_PACK_VMEM_BUDGET (default 14 MiB)."""
        o = self.out_sub if out_sub is None else out_sub
        ermid = max(self.sub, o)
        varying = (
            self.sub * C * (1 + 2 + 1)       # l1 i8, s2 i16, l3 i8
            # flags i8, or ps i8 + bk priced at its WIDENED i16 form
            # (deep segments value-widen bk; the estimate must cover
            # the worst engaged level, not the narrow best case)
            + self.sub * C * 3
            + ermid * C * (1 + 2)            # el1 i8, es2 i16
            + o * C * 1                      # el3 i8
            + o * C * 4                      # out f32
        )
        if has_gather:
            varying += self.sub * C * 2        # gidx i16
            if has_w:
                varying += self.sub * C * 4    # w f32
        else:
            varying += self.sub * C * 4        # fold input vals f32
        # x-table + hub table padded to [sub, C] (shape-matched gather)
        invariant = 2 * self.sub * C * 4 if has_gather else 0
        temps = (self.sub * C * 4) * 3 + ermid * C * 4
        return 2 * varying + invariant + temps


@dataclass
class BlockPlan:
    """Static arrays for one [sub, 128] kernel block."""

    # gather stage (None on fold levels)
    sub_idx: Optional[np.ndarray]  # [sub, C] int16: x-table row per slot
    hub_sel: Optional[np.ndarray]  # [sub, C] int16: hub idx, -1 if not hub
    # CSR-restore / merge route (pack slots -> row-sorted slots); None
    # when `route_rows` carries the composed lane-preserving form
    route: Optional[Route3]
    flags: np.ndarray              # [sub, C] int8: bit0 valid, bit1 seg start
    # extraction route (scanned slots -> compact out slots); None on
    # final blocks, which use per-row-range `tiles` instead
    eroute: Optional[Route3]
    out_rows: np.ndarray           # [out_slots] int64 row id per out slot
    out_valid: np.ndarray          # [out_slots] bool
    n_edges: int = 0
    n_inputs: int = 1              # fold levels: streams concatenated
    w: Optional[np.ndarray] = None  # [sub, C] f32 edge weights, CSR order
    # final blocks: one (Route3, valid[tile_sub*C]) per vp row-range
    # tile, so the extraction kernel touches <= tile_sub*C output rows
    # at a time (a monolithic [vp//128, 128] extraction blows VMEM at
    # bench vp)
    tiles: Optional[List] = None
    # span-aware scan: stages the kernel unrolls for this block
    # (= ceil(log2(max segment run)); further stages are exact no-ops)
    scan_stages: int = 0
    # MXU scan restoration planes (see _mxu_scan_meta); ps/bk ship in
    # place of `flags` when the level engages the mxu scan
    scan_mxu: bool = False
    ps: Optional[np.ndarray] = None   # [sub, C] int8 in-row start lane
    bk: Optional[np.ndarray] = None   # [sub, C] int row backspan
    # composed merge route: [sub, C] int source-row plane (one sublane
    # gather) replacing the generic 3-stage `route` on fold levels whose
    # upstream extractions were rewritten to land lane-aligned
    route_rows: Optional[np.ndarray] = None
    # planner-only: scan slots of this block's segment-last elements
    # (the extraction sources) — consumed when a downstream fold level
    # composes this block's eroute with its merge permutation
    e_src: Optional[np.ndarray] = None
    # static op-budget ledger: exact per-stage vector-ALU op counts
    # (see _LEDGER_CONVENTIONS in scripts/pack_cost_model.py)
    ledger: dict = field(default_factory=dict)


@dataclass
class LevelPlan:
    """One pallas_call: a list of equally-shaped blocks."""

    cfg: PackConfig
    blocks: List[BlockPlan]
    has_gather: bool
    pass_base: int = 0             # x-table offset (gather levels)
    out_sub: int = 0               # output rows per block
    tile_sub: int = 0              # final level: rows per extraction tile


def _block_op_ledger(cfg: PackConfig, *, gather: bool, scan_stages: int,
                     route_moves: int, out_sub: int = 0,
                     n_tiles: int = 0, tile_sub: int = 0,
                     scan_mxu: bool = False) -> dict:
    """Exact per-engine op counts for one block, by stage.  Counting
    conventions (shared with scripts/pack_cost_model.py, which verifies
    them independently from the shipped stream arrays):

      * one VPU op = one full-width vector operation over the
        operand's [rows, 128] plane, priced `rows * 128` lanes; the
        per-stage entries below are all VPU ops;
      * one MXU elem (`mxu` entry) = one element of a triangular /
        broadcast matmul OUTPUT plane ([B,128] @ [128,128], the one
        cumsum form Mosaic lowers — priced at the measured 0.008
        cyc/elem for B >= 512 in scripts/pack_cost_model.py);
      * gather overlay: 3 ops — the per-row hub-group lane reduce and
        the two shape-matched hub-table gathers (the x-table sublane
        dynamic_gather itself is priced separately as `gather_rows` —
        its rate is the hardware unknown the probe measures).  The
        merged gidx plane's hub decode and the final select ride
        inside this price, as the r6 register-loop selects did;
      * route: one op per take_along_axis stage, priced at that
        stage's operand height (generic Route3: l1/s2 at r_mid, l3 at
        r_dst; composed lane-aligned form: one sublane gather at sub);
      * flags: the one segment-flag compare (`flags != 1`) — shift
        levels only; mxu levels ship ps/bk restoration planes and run
        no flag pass in the sum semiring (min/max fall back to the
        ladder and pay a 3-op flag derivation NOT priced here: the
        ledger prices the sum pipeline the bench runs);
      * scan: shift levels: 3 ops (shift, select, combine) per
        span-aware unrolled stage; mxu levels: a FLAT `_MXU_SCAN_VPU`
        (= 10) restoration ops per slot — the full-prefix inter-row
        carry has no span-dependent ladder — with the matmuls landing
        in the `mxu` column as `_MXU_SCAN_PLANES` (= 3) output planes;
      * extract: the eroute stages (the out-validity select is gone:
        unrouted compact slots carry garbage that is its own flagged
        segment downstream, the same isolation proof that removed the
        scan's validity select in r6), or the per-row-range tile
        routes on final blocks (whose validity select SURVIVES — tile
        outputs are summed straight into the dense result);
      * fold-input assembly (concat / disjoint-slot merge) runs in XLA
        outside the kernels and is excluded, as it always was.
    """
    slots = cfg.sub * C
    led = {
        "overlay": 3 * slots if gather else 0,
        "route": route_moves * slots,
        "flags": 0 if scan_mxu else slots,
        "scan": (_MXU_SCAN_VPU if scan_mxu
                 else 3 * scan_stages) * slots,
        "mxu": _MXU_SCAN_PLANES * slots if scan_mxu else 0,
    }
    if n_tiles:
        led["extract"] = n_tiles * (2 * slots + 2 * tile_sub * C)
    elif out_sub:
        r_mid = max(cfg.sub, out_sub)
        led["extract"] = 2 * r_mid * C + out_sub * C
    else:
        led["extract"] = 0
    led["gather_rows"] = slots if gather else 0
    return led


def _reledger_block(cfg: PackConfig, blk: "BlockPlan") -> dict:
    """Recompute a block's ledger from its own planned structure —
    used when a post-pass changes scan parameters (level-wide mxu
    engagement, multi-shard stage unification)."""
    return _block_op_ledger(
        cfg,
        gather=blk.sub_idx is not None,
        scan_stages=blk.scan_stages,
        route_moves=1 if blk.route_rows is not None else 3,
        out_sub=(blk.eroute.l3.shape[0] if blk.eroute is not None
                 else 0),
        n_tiles=len(blk.tiles) if blk.tiles is not None else 0,
        tile_sub=(blk.tiles[0][1].shape[0] // C
                  if blk.tiles else 0),
        scan_mxu=blk.scan_mxu,
    )


def _apply_level_scan_mode(cfg: PackConfig, blocks) -> None:
    """Set the level-uniform scan form on `blocks` (mxu iff modeled
    cheaper under GRAPE_PACK_SCAN=mxu) and refresh their ledgers."""
    mxu = _decide_level_scan(blocks)
    for b in blocks:
        b.scan_mxu = mxu
        b.ledger = _reledger_block(cfg, b)


def _ledger_of_levels(shard_levels, n_cols: int, cfg: PackConfig) -> dict:
    """Aggregate the per-block op ledgers of a plan (list over shards
    of its ordered LevelPlans, final level last) into the static
    op-budget ledger: exact ALU op / gather-row / HBM-byte counts per
    level and in total, under the conventions of _block_op_ledger.
    HBM bytes are the shipped stream tables (post dtype-narrowing, from
    the real device stacks) plus one x pass-window load per gather
    level — the same accounting the r4 cost model used."""
    n_lv = len(shard_levels[0])
    out_levels = []
    totals = {"vpu_ops": 0, "mxu_ops": 0, "gather_rows": 0,
              "hbm_bytes": 0, "blocks": 0}
    per_stage_tot: dict = {}
    edges = 0
    for li in range(n_lv):
        per_stage: dict = {}
        gr = 0
        mxu = 0
        hbm = 0
        nbl = 0
        has_gather = shard_levels[0][li].has_gather
        for lvs in shard_levels:
            lv = lvs[li]
            nbl += len(lv.blocks)
            for b in lv.blocks:
                for k, v in b.ledger.items():
                    if k == "gather_rows":
                        gr += int(v)
                    elif k == "mxu":
                        mxu += int(v)
                    else:
                        per_stage[k] = per_stage.get(k, 0) + int(v)
                if lv.has_gather:
                    edges += int(b.n_edges)
            if lv.blocks:
                hbm += sum(
                    int(n) for n in
                    _stack_blocks(lv, nbytes_only=True).values()
                )
            if lv.has_gather:
                hbm += min(n_cols, cfg.slots * len(lv.blocks)) * 4
        vpu = sum(per_stage.values())
        out_levels.append({
            "level": li, "blocks": nbl, "has_gather": bool(has_gather),
            "vpu_ops": vpu, "mxu_ops": mxu, "gather_rows": gr,
            "hbm_bytes": hbm, "per_stage": per_stage,
        })
        totals["vpu_ops"] += vpu
        totals["mxu_ops"] += mxu
        totals["gather_rows"] += gr
        totals["hbm_bytes"] += hbm
        totals["blocks"] += nbl
        for k, v in per_stage.items():
            per_stage_tot[k] = per_stage_tot.get(k, 0) + v
    return {
        "edges": edges,
        "levels": out_levels,
        "totals": {**totals, "per_stage": per_stage_tot},
    }


def plan_ledger(plan) -> dict:
    """The static op-budget ledger of a PackPlan or MultiPackPlan."""
    if isinstance(plan, MultiPackPlan):
        if plan.ledger is None:
            raise ValueError("MultiPackPlan carries no ledger")
        return plan.ledger
    levels = list(plan.levels)
    if plan.final is not None and plan.final.blocks:
        levels = levels + [plan.final]
    return _ledger_of_levels([levels], plan.n_cols, plan.cfg)


_PLAN_COUNTER = itertools.count()


@dataclass
class PackPlan:
    vp: int                        # output length (padded, multiple of 128)
    n_cols: int                    # gather-table length
    cfg: PackConfig
    hub_cols: np.ndarray           # [hub] int64 column ids (padded with 0)
    levels: List[LevelPlan] = field(default_factory=list)
    final: Optional[LevelPlan] = None  # single-block level -> [vp]
    # unique id: apps bake it into trace keys so a cached runner is
    # never reused with a different fragment's closed-over plan
    uid: int = field(default_factory=lambda: next(_PLAN_COUNTER))

    # device-side constant streams, materialized lazily per backend
    _device: dict = field(default_factory=dict)


# --------------------------------------------------------------------------
# host planning
# --------------------------------------------------------------------------


def _hub_row_margin(cfg: PackConfig) -> int:
    """Slot capacity reserved for the row-aligned hub assignment: hub
    edges are placed group-sorted with each kernel row taking entries
    of a SINGLE 128-entry hub group (the lane-uniform row index the
    two-gather hub read requires — see _plan_gather_block); a group
    change mid-row skips the row's remaining holes, wasting at most
    (groups - 1) * (C - 1) slots per block.  hub // C <= sub by
    PackConfig validation, so the margin always leaves >= sub slots."""
    return (cfg.hub // C) * (C - 1)


def _cut_blocks(rows, local_cols, hub_mask, cfg: PackConfig):
    """Split CSR-ordered edges into block ranges such that per block:
    no mixed lane exceeds `sub` non-hub edges, slots (plus the hub
    row-alignment margin when hub edges are present) <= sub*128, and
    distinct rows <= max_distinct.  Returns list of (lo, hi).

    O(E): per-lane edge position lists + segment-start prefix counts
    give each cut point in O(1)."""
    e = len(rows)
    cap = cfg.slots - (_hub_row_margin(cfg) if hub_mask.any() else 0)
    lane = np.where(hub_mask, -1, _lane_mix(local_cols))
    # per-lane position lists: pos_by_lane[l] = sorted edge indices in l
    order = np.argsort(lane, kind="stable")
    lane_sorted = lane[order]
    lane_starts = np.searchsorted(lane_sorted, np.arange(C))
    lane_ends = np.searchsorted(lane_sorted, np.arange(C), side="right")
    pos_by_lane = [order[lane_starts[l]:lane_ends[l]] for l in range(C)]

    seg_start = np.ones(e, dtype=np.int64)
    seg_start[1:] = rows[1:] != rows[:-1]
    cum_start = np.concatenate([[0], np.cumsum(seg_start)])

    cuts = []
    lo = 0
    while lo < e:
        hi = min(e, lo + cap)
        # lane overflow: for each lane, the (rank_at_lo + sub)-th edge
        # of that lane is the first infeasible position
        for l in range(C):
            pl = pos_by_lane[l]
            r0 = np.searchsorted(pl, lo)
            if r0 + cfg.sub < len(pl):
                hi = min(hi, int(pl[r0 + cfg.sub]))
        # distinct-rows bound: distinct([lo,hi)) = 1 + cum_start[hi]
        # - cum_start[lo+1]  (the row at lo counts whether or not it is
        # a recorded segment start); keep the largest feasible hi
        target = cum_start[lo + 1] + cfg.max_distinct - 1
        hi_feas = int(np.searchsorted(cum_start, target, side="right")) - 1
        hi = min(hi, max(lo + 1, hi_feas))
        cuts.append((lo, hi))
        lo = hi
    return cuts


def _plan_gather_block(rows, cols, hub_idx, base, cfg: PackConfig,
                       w=None):
    """Plan one gather block from its CSR-ordered edge slice.

    hub_idx: int32 per edge, -1 if the edge reads the pass table,
    else its index into the hub table.  `base` is the pass's x offset.
    `w`: optional per-edge weights (same slice), stored in CSR slot
    order for post-route application.
    """
    e = len(rows)
    sub = cfg.sub
    is_hub = hub_idx >= 0

    # ---- slot assignment: non-hub lane = mixed lane; hub fills holes ----
    lane = np.where(is_hub, -1, _lane_mix(cols - base)).astype(np.int64)
    slot = np.full(e, -1, dtype=np.int64)
    # positions of non-hub edges within their lane column (stable)
    nh = np.nonzero(~is_hub)[0]
    order = np.argsort(lane[nh], kind="stable")
    lane_sorted = lane[nh][order]
    pos_in_lane = np.arange(len(nh)) - np.searchsorted(
        lane_sorted, lane_sorted
    )
    slot[nh[order]] = pos_in_lane * C + lane_sorted
    assert (pos_in_lane < sub).all(), "lane overflow despite block cut"
    # hub edges take remaining slots (any lane), GROUP-SORTED and
    # row-aligned: every kernel row's hub slots read entries of one
    # 128-entry hub group, so the kernel's hub-table row index is
    # lane-uniform per row and the two shape-matched gathers compose
    # correctly (a per-slot row plane would read the row index at the
    # POST-lane-gather position — wrong whenever rows mix groups).  A
    # group change mid-row skips the row's remaining holes; the block
    # cutter reserved capacity for exactly that (_hub_row_margin).
    hub_e = np.nonzero(is_hub)[0]
    if len(hub_e):
        used = np.zeros(sub * C, dtype=bool)
        used[slot[nh]] = True
        free = np.nonzero(~used)[0]
        order_h = np.argsort(hub_idx[hub_e] >> 7, kind="stable")
        hub_sorted = hub_e[order_h]
        grp = hub_idx[hub_sorted] >> 7
        bounds = np.concatenate(
            [[0], np.nonzero(np.diff(grp))[0] + 1, [len(grp)]]
        )
        fi = 0
        for gi in range(len(bounds) - 1):
            k, k2 = int(bounds[gi]), int(bounds[gi + 1])
            take = k2 - k
            assert fi + take <= len(free), \
                "hub row-alignment margin exhausted despite block cut"
            slot[hub_sorted[k:k2]] = free[fi:fi + take]
            fi += take
            # a group must not share a row with the next: skip the
            # last used row's remaining holes
            if fi and fi < len(free):
                last_row = free[fi - 1] // C
                while fi < len(free) and free[fi] // C == last_row:
                    fi += 1
        # the invariant the kernel's lane-uniform row index relies on
        hrows = slot[hub_sorted] // C
        gmin = np.full(sub, np.iinfo(np.int64).max)
        gmax = np.full(sub, -1, dtype=np.int64)
        np.minimum.at(gmin, hrows, grp)
        np.maximum.at(gmax, hrows, grp)
        occ = gmax >= 0
        assert (gmax[occ] == gmin[occ]).all(), \
            "a kernel row mixes hub groups"
    assert (slot >= 0).all()

    # ---- gather streams ----
    sub_idx = np.zeros((sub, C), dtype=np.int16)
    hub_sel = np.full((sub, C), -1, dtype=np.int16)
    srow, slane = slot // C, slot % C
    tab_row = np.where(is_hub, 0, (cols - base) >> 7)
    assert (tab_row >= 0).all() and (tab_row < sub).all()
    sub_idx[srow, slane] = tab_row.astype(np.int16)
    hub_sel[srow[is_hub], slane[is_hub]] = hub_idx[is_hub].astype(np.int16)

    # ---- CSR-restore route: pack slot -> CSR slot i ----
    route = plan_route(slot, np.arange(e, dtype=np.int64), sub, sub)

    # ---- flags for the segmented scan over CSR order ----
    flags = np.zeros((sub, C), dtype=np.int8)
    csr_r, csr_l = np.arange(e) // C, np.arange(e) % C
    seg_start = np.ones(e, dtype=bool)
    seg_start[1:] = rows[1:] != rows[:-1]
    flags[csr_r, csr_l] = 1 | (seg_start.astype(np.int8) << 1)

    # ---- extraction: each row's last CSR slot -> compact out slot ----
    last = np.ones(e, dtype=bool)
    last[:-1] = rows[1:] != rows[:-1]
    src = np.nonzero(last)[0]
    d = len(src)
    assert d <= cfg.max_distinct
    eroute = plan_route(
        src, np.arange(d, dtype=np.int64), sub, cfg.out_sub
    )
    out_rows = np.zeros(cfg.out_sub * C, dtype=np.int64)
    out_rows[:d] = rows[src]
    out_valid = np.zeros(cfg.out_sub * C, dtype=bool)
    out_valid[:d] = True

    w_block = None
    if w is not None:
        w_block = np.zeros((sub, C), dtype=np.float32)
        w_block[csr_r, csr_l] = w.astype(np.float32)

    stages = _scan_stages_for(rows)
    ps, bk = _mxu_scan_meta(rows, sub)
    return BlockPlan(
        sub_idx=sub_idx, hub_sel=hub_sel, route=route, flags=flags,
        eroute=eroute, out_rows=out_rows, out_valid=out_valid, n_edges=e,
        w=w_block, scan_stages=stages, e_src=src,
        ps=ps, bk=bk,
        ledger=_block_op_ledger(cfg, gather=True, scan_stages=stages,
                                route_moves=3, out_sub=cfg.out_sub),
    )


def _group_prep(grp):
    """Concatenate a group's stream metadata and compute the merge
    permutation (valid slots, stably sorted by row — the tie-break that
    keeps the fold's combine order, and hence every f32 bit,
    unchanged).  Computed ONCE per group and shared by the feasibility
    probe and the block planner (the argsort is the planner's unit of
    work; doubling it doubled cache-cold plan time for nothing)."""
    in_rows = np.concatenate([r for r, _, _ in grp])
    in_valid = np.concatenate([v for _, v, _ in grp])
    val = np.nonzero(in_valid)[0]
    order = val[np.argsort(in_rows[val], kind="stable")]
    return in_rows, in_valid, order


def _aligned_feasible(grp, cfg: PackConfig, prep=None) -> bool:
    """True when this group's upstream extractions can be rewritten so
    the merge route is lane-preserving: per input stream, no merged
    lane may receive more than out_sub of that stream's elements (each
    stream is an [out_sub, C] block — out_sub rows of sublane capacity
    per lane)."""
    sl = cfg.max_distinct
    _, _, order = prep if prep is not None else _group_prep(grp)
    e = len(order)
    if e == 0:
        return True
    lanes = np.arange(e, dtype=np.int64) % C
    stream_of = order // sl
    counts = np.bincount(stream_of * C + lanes,
                         minlength=len(grp) * C)
    return int(counts.max()) <= cfg.out_sub


def _rewrite_upstream_aligned(grp, order, cfg: PackConfig) -> np.ndarray:
    """Compose each producer's extraction route with this group's merge
    permutation: producers re-extract straight into lane-aligned
    compact slots (same lane as the element's final merged slot), so
    the merge itself collapses to ONE sublane gather.  Mutates the
    producer BlockPlans (fresh eroute/out_rows/out_valid) and returns
    the consumer's [sub, C] source-row plane.

    Bit-exactness: `order` (the merge permutation) is computed from the
    ORIGINAL compact layouts, so every element's final slot — and hence
    the scan tree and extracted values — is unchanged; only the
    intermediate compact placement moves."""
    sl = cfg.max_distinct
    e = len(order)
    i = np.arange(e, dtype=np.int64)
    j_of = order // sl
    q_old = order % sl
    lam = i % C
    # rank within (stream, lane), in final-slot order (i ascending)
    key = j_of * C + lam
    ord2 = np.argsort(key, kind="stable")
    sorted_key = key[ord2]
    starts = np.searchsorted(sorted_key, sorted_key)
    ranks = np.empty(e, dtype=np.int64)
    ranks[ord2] = np.arange(e, dtype=np.int64) - starts
    q_new = ranks * C + lam

    # the merged route is lane-preserving by construction; the helper
    # re-checks that invariant and emits the single-move row plane
    route_rows = plan_lane_aligned_rows(j_of * sl + q_new, i, cfg.sub)

    for j, (r, v, blk) in enumerate(grp):
        m = j_of == j
        d_j = int(m.sum())
        if d_j == 0:
            continue
        newq = np.empty(d_j, dtype=np.int64)
        # the producer's compact slots are the prefix 0..d_j-1, in the
        # same order as its e_src extraction sources
        newq[q_old[m]] = q_new[m]
        assert blk.e_src is not None and len(blk.e_src) == d_j
        blk.eroute = plan_route(blk.e_src, newq, cfg.sub, cfg.out_sub)
        nr = np.zeros(sl, dtype=np.int64)
        nv = np.zeros(sl, dtype=bool)
        nr[newq] = r[:d_j]
        nv[newq] = True
        blk.out_rows = nr
        blk.out_valid = nv
    return route_rows


def _plan_fold_block(grp, cfg: PackConfig, out_sub: int,
                     final_by_row: bool, tile_sub: int = 0,
                     aligned: bool = False, prep=None):
    """Plan one fold block over a group of input streams
    [(out_rows, out_valid, producer BlockPlan)]: the merge route sorts
    valid slots by (row, original position), scan folds them, and
    extraction emits one slot per distinct row (or slot==row when
    `final_by_row`, split into `tile_sub`-row range tiles so each
    extraction kernel program stays within VMEM).  With `aligned`, the
    producers' extractions are rewritten (route composition) and the
    merge route ships as a single sublane-gather plane instead of a
    3-stage Route3."""
    sub = cfg.sub
    in_rows, in_valid, order = (
        prep if prep is not None else _group_prep(grp)
    )
    pad = cfg.slots - len(in_rows)
    assert pad >= 0
    if pad:
        # pad slots are invalid and trailing, so `order` (computed on
        # the unpadded concat) indexes identically into the padded form
        in_rows = np.concatenate([in_rows, np.zeros(pad, np.int64)])
        in_valid = np.concatenate([in_valid, np.zeros(pad, bool)])
    e = len(order)
    if aligned:
        route = None
        route_rows = _rewrite_upstream_aligned(grp, order, cfg)
        route_moves = 1
    else:
        route = plan_route(order, np.arange(e, dtype=np.int64), sub, sub)
        route_rows = None
        route_moves = 3

    rows_sorted = in_rows[order]
    flags = np.zeros((sub, C), dtype=np.int8)
    csr_r, csr_l = np.arange(e) // C, np.arange(e) % C
    seg_start = np.ones(e, dtype=bool)
    seg_start[1:] = rows_sorted[1:] != rows_sorted[:-1]
    flags[csr_r, csr_l] = 1 | (seg_start.astype(np.int8) << 1)
    stages = _scan_stages_for(rows_sorted)
    ps, bk = _mxu_scan_meta(rows_sorted, sub)

    last = np.ones(e, dtype=bool)
    last[:-1] = rows_sorted[1:] != rows_sorted[:-1]
    src = np.nonzero(last)[0]
    d = len(src)
    if final_by_row:
        dst = rows_sorted[src]
        assert d == len(np.unique(dst))
        out_rows = np.arange(out_sub * C, dtype=np.int64)
        out_valid = np.zeros(out_sub * C, dtype=bool)
        out_valid[dst] = True
        # per-row-range extraction tiles (tile_sub rows each)
        tile_sub = tile_sub or out_sub
        n_tiles = -(-out_sub // tile_sub)
        tiles = []
        for t in range(n_tiles):
            lo = t * tile_sub * C
            hi = lo + tile_sub * C
            m = (dst >= lo) & (dst < hi)
            er = plan_route(src[m], dst[m] - lo, sub, tile_sub)
            ev = np.zeros(tile_sub * C, dtype=bool)
            ev[dst[m] - lo] = True
            tiles.append((er, ev))
        return BlockPlan(
            sub_idx=None, hub_sel=None, route=route, flags=flags,
            eroute=None, out_rows=out_rows, out_valid=out_valid,
            n_edges=e, tiles=tiles, scan_stages=stages,
            route_rows=route_rows, ps=ps, bk=bk,
            ledger=_block_op_ledger(cfg, gather=False, scan_stages=stages,
                                    route_moves=route_moves,
                                    n_tiles=n_tiles, tile_sub=tile_sub),
        )
    assert d <= out_sub * C
    dst = np.arange(d, dtype=np.int64)
    out_rows = np.zeros(out_sub * C, dtype=np.int64)
    out_rows[:d] = rows_sorted[src]
    out_valid = np.zeros(out_sub * C, dtype=bool)
    out_valid[:d] = True
    eroute = plan_route(src, dst, sub, out_sub)
    return BlockPlan(
        sub_idx=None, hub_sel=None, route=route, flags=flags,
        eroute=eroute, out_rows=out_rows, out_valid=out_valid, n_edges=e,
        scan_stages=stages, route_rows=route_rows, e_src=src,
        ps=ps, bk=bk,
        ledger=_block_op_ledger(cfg, gather=False, scan_stages=stages,
                                route_moves=route_moves, out_sub=out_sub),
    )


# final extraction runs in row-range tiles of this many sublane rows,
# so its VMEM residency is bounded regardless of vp; the vp ceiling is
# then set by HBM (per-final-block tile-route storage is O(vp)) rather
# than by one monolithic [vp//128, 128] extraction block
_FINAL_TILE_SUB = 2048
_MAX_VP_SUB = 65536  # vp <= 65536*128 (8.4M rows) per plan/shard


def _plan_shard_gather(edge_row, edge_col, vp, n_cols, cfg: PackConfig,
                       edge_w=None):
    """Gather levels + hub table for one shard's CSR-sorted edge list.
    Returns (levels: dict pass_idx -> LevelPlan, hub_cols_padded) —
    passes with no edges get no entry (plan_pack_multi pads them when
    another shard does populate the pass)."""
    # hub columns: the most-referenced ones (these overflow per-lane
    # capacity in the packed layout; they read a register table instead)
    counts = np.bincount(edge_col, minlength=n_cols)
    hub = min(cfg.hub, n_cols)
    hub_cols = np.argsort(-counts, kind="stable")[:hub].astype(np.int64)
    hub_lut = np.full(n_cols, -1, dtype=np.int32)
    hub_lut[hub_cols] = np.arange(hub, dtype=np.int32)
    hub_cols_padded = np.zeros(cfg.hub, dtype=np.int64)
    hub_cols_padded[:hub] = hub_cols

    hub_idx_all = hub_lut[edge_col]

    from concurrent.futures import ThreadPoolExecutor

    span = cfg.sub * C
    n_pass = max(1, -(-n_cols // span))
    levels: dict[int, LevelPlan] = {}
    # `with` guarantees worker threads are reaped even when block
    # planning raises (ADVICE r2: the bare shutdown leaked them)
    with ThreadPoolExecutor() as pool:
        for p in range(n_pass):
            base = p * span
            # hub edges join the pass of their column so every edge
            # lives in exactly one pass (their table entry is ignored
            # anyway)
            if n_pass > 1:
                in_pass = (edge_col >= base) & (edge_col < base + span)
            else:
                in_pass = np.ones(len(edge_col), dtype=bool)
            sel = np.nonzero(in_pass)[0]
            if len(sel) == 0:
                continue
            rows, cols = edge_row[sel], edge_col[sel]
            hub_idx = hub_idx_all[sel]
            w_sel = edge_w[sel] if edge_w is not None else None
            cuts = _cut_blocks(rows, cols - base, hub_idx >= 0, cfg)
            # block planning is route-heavy numpy (argsort-dominated,
            # GIL-friendly): thread it
            blocks = list(pool.map(
                lambda lohi, rows=rows, cols=cols, hub_idx=hub_idx,
                       w_sel=w_sel, base=base: _plan_gather_block(
                    rows[lohi[0]:lohi[1]], cols[lohi[0]:lohi[1]],
                    hub_idx[lohi[0]:lohi[1]], base, cfg,
                    w_sel[lohi[0]:lohi[1]] if w_sel is not None else None,
                ),
                cuts,
            ))
            levels[p] = LevelPlan(
                cfg=cfg, blocks=blocks, has_gather=True, pass_base=base,
                out_sub=cfg.out_sub,
            )
    return levels, hub_cols_padded


def _empty_gather_block(cfg: PackConfig, base: int, has_w: bool):
    """A no-edge gather block (pads shards to uniform block counts
    under shard_map: all flags invalid, all outputs masked)."""
    z = np.zeros(0, dtype=np.int64)
    return _plan_gather_block(
        z, z, np.zeros(0, dtype=np.int32), base, cfg,
        np.zeros(0, dtype=np.float32) if has_w else None,
    )


def _level_streams(levels):
    out = []
    for lv in levels:
        for b in lv.blocks:
            out.append((b.out_rows, b.out_valid, b))
    return out


def _plan_mid_folds(streams, cfg: PackConfig):
    """Contract streams with fold levels while they help (data-dependent
    grouping — single-shard plans only).  Returns (levels, streams)."""
    group_cap = cfg.sub // cfg.out_sub
    levels = []
    depth = 0
    compose = _compose_enabled()
    # mid folds: contract while they help (already-compact streams,
    # e.g. degree-1 tails, cannot contract — the multi-block final
    # level absorbs them instead, having no distinct-rows limit)
    while sum(len(r) for r, _, _ in streams) > cfg.slots:
        grps = []
        i = 0
        while i < len(streams):
            grp = []
            slots = 0
            distinct = set()
            while (i < len(streams) and len(grp) < group_cap
                   and slots + len(streams[i][0]) <= cfg.slots):
                r, v, _ = streams[i]
                u = set(np.unique(r[v]).tolist())
                if grp and len(distinct | u) > cfg.max_distinct:
                    break
                distinct |= u
                grp.append(streams[i])
                slots += len(r)
                i += 1
            grps.append(grp)
        if 2 * len(grps) > len(streams):
            # weak contraction (< 2x — overlapping row ranges hit the
            # distinct-rows cap): a further fold level would ship a
            # full set of merge/extraction streams for almost no
            # reduction, while the final level absorbs the same
            # streams at the same block count (r7: the bench chain
            # spent two levels shrinking 50 -> 34 -> 33 blocks, ~3.3
            # HBM B/edge for nothing) — hand over to the final level
            break
        # route composition engages per level (kernel structure must be
        # uniform across a level's blocks)
        preps = [_group_prep(g) for g in grps]
        aligned = compose and all(
            _aligned_feasible(g, cfg, p) for g, p in zip(grps, preps)
        )
        blocks = []
        nxt = []
        for grp, prep in zip(grps, preps):
            blk = _plan_fold_block(grp, cfg, cfg.out_sub,
                                   final_by_row=False, aligned=aligned,
                                   prep=prep)
            blk.n_inputs = len(grp)
            blocks.append(blk)
            nxt.append((blk.out_rows, blk.out_valid, blk))
        levels.append(LevelPlan(cfg=cfg, blocks=blocks, has_gather=False,
                                out_sub=cfg.out_sub))
        streams = nxt
        depth += 1
        assert depth < 8, "fold recursion failed to converge"
    return levels, streams


def _final_groups(streams, cfg: PackConfig):
    """Capacity-only grouping of the final level's input streams —
    data-independent, so multi-shard plans built from uniform stream
    counts get uniform structure."""
    grps = []
    i = 0
    while i < len(streams):
        grp = []
        slots = 0
        while i < len(streams) and slots + len(streams[i][0]) <= cfg.slots:
            grp.append(streams[i])
            slots += len(streams[i][0])
            i += 1
        if not grp:  # single stream larger than a block cannot happen
            raise AssertionError("stream exceeds block capacity")
        grps.append(grp)
    return grps


def _plan_final_level(streams, vp, cfg: PackConfig,
                      aligned: bool | None = None,
                      preps=None) -> LevelPlan:
    """Final level: multi-block, each block scan-folds its streams and
    extracts straight into the dense [vp] layout (slot == row id) in
    row-range tiles; block outputs are summed by the caller, so
    overlapping rows across final blocks are fine.  `aligned=None`
    decides route composition from this stream set alone; multi-shard
    planning passes the all-shard AND so the skeleton stays uniform."""
    vp_sub = vp // C
    tile_sub = min(vp_sub, _FINAL_TILE_SUB)
    from concurrent.futures import ThreadPoolExecutor

    grps = _final_groups(streams, cfg)
    if preps is None:
        preps = [_group_prep(g) for g in grps]
    if aligned is None:
        aligned = _compose_enabled() and all(
            _aligned_feasible(g, cfg, p) for g, p in zip(grps, preps)
        )

    def build(grp_prep):
        grp, prep = grp_prep
        blk = _plan_fold_block(grp, cfg, vp_sub, final_by_row=True,
                               tile_sub=tile_sub, aligned=aligned,
                               prep=prep)
        blk.n_inputs = len(grp)
        return blk

    with ThreadPoolExecutor() as pool:
        fblocks = list(pool.map(build, list(zip(grps, preps))))
    return LevelPlan(cfg=cfg, blocks=fblocks, has_gather=False,
                     out_sub=vp_sub, tile_sub=tile_sub)


def plan_pack(edge_row: np.ndarray, edge_col: np.ndarray, vp: int,
              n_cols: int, cfg: PackConfig = PackConfig(),
              edge_w: np.ndarray | None = None) -> PackPlan:
    """Build the full static plan for `y[r] = sum_e x[col[e]]` over
    CSR-sorted edges with `vp` output rows and `n_cols` x entries.

    `vp` must be a multiple of 128 and <= 65536*128 rows per plan
    (the per-final-block tile-route storage is O(vp) in HBM; shard
    larger graphs)."""
    edge_row = np.asarray(edge_row, dtype=np.int64)
    edge_col = np.asarray(edge_col, dtype=np.int64)
    assert vp % C == 0
    if vp // C > _MAX_VP_SUB:
        raise ValueError(
            f"vp={vp} exceeds {_MAX_VP_SUB * C} rows per plan; "
            "shard the graph"
        )
    assert (np.diff(edge_row) >= 0).all(), "edges must be row-sorted"

    glevels, hub_cols_padded = _plan_shard_gather(
        edge_row, edge_col, vp, n_cols, cfg, edge_w
    )
    plan = PackPlan(vp=vp, n_cols=n_cols, cfg=cfg,
                    hub_cols=hub_cols_padded)
    plan.levels = [glevels[p] for p in sorted(glevels)]

    streams = _level_streams(plan.levels)
    fold_levels, streams = _plan_mid_folds(streams, cfg)
    plan.levels += fold_levels
    plan.final = _plan_final_level(streams, vp, cfg)
    for lv in list(plan.levels) + [plan.final]:
        _apply_level_scan_mode(cfg, lv.blocks)
    _warn_vmem(cfg, has_w=edge_w is not None,
               final_out_sub=plan.final.tile_sub)
    return plan


def _warn_vmem(cfg: PackConfig, has_w: bool, final_out_sub: int = 0):
    """Warn once per (cfg, shape class) when the estimated per-kernel
    VMEM residency exceeds the budget (GRAPE_PACK_VMEM_BUDGET bytes,
    default 14 MiB of the ~16 MiB/core on v5e)."""
    import os
    import warnings

    budget = int(os.environ.get("GRAPE_PACK_VMEM_BUDGET", 14 << 20))
    worst = max(
        cfg.vmem_bytes(has_gather=True, has_w=has_w),
        cfg.vmem_bytes(has_gather=False, has_w=False,
                       out_sub=final_out_sub or cfg.out_sub),
    )
    if worst > budget:
        key = (cfg.sub, cfg.out_sub, cfg.hub, has_w, final_out_sub)
        if key not in _VMEM_WARNED:
            _VMEM_WARNED.add(key)
            warnings.warn(
                f"pack plan estimated VMEM {worst / 2**20:.1f} MiB exceeds "
                f"budget {budget / 2**20:.1f} MiB (sub={cfg.sub}, "
                f"final_out_sub={final_out_sub}); the kernel may fail "
                "Mosaic VMEM allocation — shrink PackConfig.sub or shard "
                "the graph",
                stacklevel=3,
            )


_VMEM_WARNED: set = set()


# --------------------------------------------------------------------------
# numpy reference executor (the kernel's semantics, stage for stage)
# --------------------------------------------------------------------------


# reduction semirings: (combine, identity, weight-combine).  `min`/`max`
# pair with ADDITIVE edge weights (the tropical semiring SSSP/BFS
# relaxation x[nbr] + w); `sum` pairs with multiplicative weights.
_KINDS = {
    "sum": (np.add, 0.0, np.multiply),
    "min": (np.minimum, np.inf, np.add),
    "max": (np.maximum, -np.inf, np.add),
}


def _jnp_kind(kind):
    """The jnp (combine, identity, weight-combine) triple, mirroring
    _KINDS so the kernel and numpy reference cannot drift."""
    import jax.numpy as jnp

    return {
        "sum": (jnp.add, 0.0, jnp.multiply),
        "min": (jnp.minimum, np.inf, jnp.add),
        "max": (jnp.maximum, -np.inf, jnp.add),
    }[kind]


def _scan_np(v, f, kind, stages: int | None = None):
    """Segmented inclusive scan over flattened [sub, C] row-major order
    via shift-combine stages — mirrors the kernel exactly.  `stages`
    truncates the unroll (span-aware scans: beyond
    ceil(log2(max_seglen)) every stage combines with the identity, so
    truncation is bit-exact); None runs the full log2(n) ladder."""
    op, ident, _ = _KINDS[kind]
    sub = v.shape[0]
    n = sub * C
    vf = v.reshape(n).copy()
    ff = f.reshape(n).copy().astype(bool)
    s = 1
    done = 0
    while s < n and (stages is None or done < stages):
        carry = np.where(ff[s:], ident, vf[:-s])
        vf[s:] = op(vf[s:], carry)
        ff[s:] = ff[s:] | ff[:-s]
        s *= 2
        done += 1
    return vf.reshape(sub, C)


def _scan_np_mxu(v, ps, bk):
    """Numpy mirror of the kernel's MXU segmented scan (sum semiring
    only — min/max cannot ride a matmul prefix and fall back to the
    shift ladder with flags derived from ps/bk).  Stage for stage:

      1. per-row inclusive lane cumsum `rowcum = v @ tri` (the ONE
         cumsum form that lowers in Pallas TPU; exclusive form by
         subtracting v), then the in-row restore subtracts the
         exclusive prefix at each slot's static start lane `ps` —
         exactly 0 for slots whose segment carried in from an earlier
         row (ps = 0 → exclusive prefix at lane 0) — giving `rseg`,
         each slot's sum back to its segment start within the row;
      2. the FULL exclusive row prefix W of the per-row trailing
         -segment totals (`tail = rseg @ E127`, a lane-127 broadcast
         matmul): per 128-row sublane group, `Lexc @ tail` on the MXU
         plus a [1, C] running base chained across groups — full
         prefixes NEST, so W[r] - W[r'] is the exact tail sum over
         rows [r', r) with no span-dependent ladder;
      3. restoration: every slot adds `W[r] - W[r - bk]` — the
         carried-in part of its segment (bk = 0 slots subtract W at
         their own row and add exactly 0, so no mask plane exists).

    NOT bit-identical to the shift ladder on arbitrary floats (a
    prefix difference rounds differently from a direct tree sum —
    both are valid f32 segment sums); identical on integer-valued
    data below the mantissa (any summation order is exact), which is
    what the parity pin in tests/test_pack_budget.py uses.

    NON-FINITE CAVEAT: prefix differences propagate non-finite values
    ACROSS segments — one +/-inf or NaN element poisons every later
    segment of its block with NaN (inf - inf), where the ladder
    isolates it to its own segment.  Sum-kind callers with possibly
    non-finite inputs must use GRAPE_PACK_SCAN=shift; the min/max
    tropical kinds (the ones that legitimately carry inf sentinels —
    SSSP/BFS/WCC) always run the ladder and are unaffected.  Pinned
    by tests/test_pack_budget.py::test_mxu_nonfinite_caveat."""
    sub = v.shape[0]
    dt = v.dtype
    tri = np.triu(np.ones((C, C), dtype=dt))
    rowcum = v @ tri
    rowcum_exc = rowcum - v
    sub1 = np.take_along_axis(rowcum_exc, ps.astype(np.int64), axis=1)
    rseg = rowcum - sub1
    gr = _mxu_group_rows(sub)
    e_last = np.zeros((C, C), dtype=dt)
    e_last[C - 1, :] = 1
    lexc = np.tril(np.ones((gr, gr), dtype=dt), -1)
    w = np.empty_like(v)
    base = np.zeros((1, v.shape[1]), dtype=dt)
    for g in range(sub // gr):
        sl = slice(g * gr, (g + 1) * gr)
        tail_g = rseg[sl] @ e_last
        s_exc_g = lexc @ tail_g
        w[sl] = s_exc_g + base
        base = base + (s_exc_g[gr - 1:gr] + tail_g[gr - 1:gr])
    srrow = np.arange(sub, dtype=np.int64)[:, None] - bk.astype(np.int64)
    return rseg + (w - np.take_along_axis(w, srrow, axis=0))


def _mxu_f0_np(ps, bk):
    """The shift ladder's segment-start-or-invalid flag, recovered
    from the mxu planes (min/max kinds on an mxu level): a slot is a
    start iff its in-row restore points at itself with no row carry;
    invalid slots encode ps = own lane, bk = 0 — also starts."""
    lane = np.arange(C, dtype=np.int64)[None, :]
    return ((ps.astype(np.int64) == lane)
            & (bk.astype(np.int64) == 0)).astype(np.float64)


def _exec_block_np(plan: PackPlan, lv: LevelPlan, blk: BlockPlan, x,
                   x_hub, in_vals, kind="sum"):
    from libgrape_lite_tpu.ops.route3 import apply_route3_np

    op, ident, wop = _KINDS[kind]
    cfg = lv.cfg
    if lv.has_gather:
        tab = np.zeros((cfg.sub, C), dtype=x.dtype)
        src = x[lv.pass_base: lv.pass_base + cfg.slots]
        tab.reshape(-1)[: len(src)] = src
        # lane-mix shuffle: tab_mixed[r, l] = tab[r, l ^ mix(r)]
        rr = np.arange(cfg.sub)[:, None]
        ll = np.arange(C)[None, :]
        tab = np.take_along_axis(
            tab, (ll ^ _row_mix(rr)).astype(np.int64), axis=1
        )
        v_tab = np.take_along_axis(
            tab, blk.sub_idx.astype(np.int64), axis=0
        )
        hub_tab = x_hub.reshape(cfg.hub // C, C)
        hs = blk.hub_sel.astype(np.int64)
        hs_c = np.maximum(hs, 0)
        v_hub = hub_tab[hs_c >> 7, hs_c & (C - 1)]
        vals = np.where(hs >= 0, v_hub, v_tab)
    else:
        vals = in_vals
    # route to row-sorted order (composed plans ship the fold merge as
    # a single sublane-gather plane; values at invalid slots are
    # arbitrary but each is its own flagged segment, so they can never
    # combine into — or be extracted as — a real row's value)
    if blk.route_rows is not None:
        routed = np.take_along_axis(
            vals.astype(np.float64),
            blk.route_rows.astype(np.int64), axis=0,
        )
    else:
        routed = apply_route3_np(vals.astype(np.float64), blk.route)
    if lv.has_gather and blk.w is not None:
        routed = wop(routed, blk.w.astype(np.float64))
    if blk.scan_mxu and kind == "sum":
        cs = _scan_np_mxu(routed, blk.ps, blk.bk)
    else:
        f0 = (_mxu_f0_np(blk.ps, blk.bk) if blk.scan_mxu
              else (blk.flags != 1).astype(np.float64))
        cs = _scan_np(routed, f0, kind, blk.scan_stages)
    if blk.tiles is not None:
        # final block: per-row-range extraction tiles concatenate into
        # the dense [vp] layout
        parts = []
        for er, ev in blk.tiles:
            ex = apply_route3_np(cs, er)
            tsub = ev.shape[0] // C
            parts.append(
                np.where(ev.reshape(tsub, C), ex, ident)
            )
        return np.concatenate(parts, axis=0)
    out = apply_route3_np(cs, blk.eroute)
    ovalid = blk.out_valid.reshape(lv.out_sub, C)
    return np.where(ovalid, out, ident)


def exec_plan_np(plan: PackPlan, x: np.ndarray, kind="sum") -> np.ndarray:
    """Numpy reference of the whole pipeline."""
    op, ident, _ = _KINDS[kind]
    x_hub = x[plan.hub_cols]
    streams = []
    lvls = list(plan.levels)
    gather_levels = [lv for lv in lvls if lv.has_gather]
    fold_levels = [lv for lv in lvls if not lv.has_gather]
    for lv in gather_levels:
        for blk in lv.blocks:
            streams.append(
                _exec_block_np(plan, lv, blk, x, x_hub, None,
                               kind).reshape(-1)
            )
    for lv in fold_levels:
        nxt = []
        i = 0
        for blk in lv.blocks:
            k = blk.n_inputs
            vals = np.concatenate(streams[i:i + k])
            i += k
            pad = lv.cfg.slots - len(vals)
            if pad:
                vals = np.concatenate([vals, np.full(pad, ident)])
            nxt.append(
                _exec_block_np(
                    plan, lv, blk, None, None,
                    vals.reshape(lv.cfg.sub, C), kind,
                ).reshape(-1)
            )
        streams = nxt
    y = np.full(plan.vp, ident, dtype=np.float64)
    i = 0
    for blk in plan.final.blocks:
        k = blk.n_inputs
        vals = np.concatenate(streams[i:i + k])
        i += k
        pad = plan.cfg.slots - len(vals)
        if pad:
            vals = np.concatenate([vals, np.full(pad, ident)])
        out = _exec_block_np(plan, plan.final, blk, None, None,
                             vals.reshape(plan.cfg.sub, C), kind)
        y = op(y, out.reshape(-1)[: plan.vp])
    return y


# --------------------------------------------------------------------------
# device executor (Pallas TPU kernels; interpret mode off-TPU)
# --------------------------------------------------------------------------


def _kernel_body(lv_has_gather: bool, sub: int, out_sub: int,
                 n_stages: int, kind: str = "sum", has_w: bool = False,
                 extract: bool = True, aligned: bool = False,
                 scan_mxu: bool = False):
    """Build the kernel function for one scan group (shapes static).

    `n_stages` is the group's span-aware scan unroll — blocks are
    batched into pallas_calls by their planned stage count, so a
    degree-1 tail block runs 0 shift-combine stages while a hub-heavy
    block runs the full ladder.  `aligned` selects the composed fold
    path: the merge route arrives as ONE sublane-gather plane (rr)
    instead of a 3-stage Route3.  `scan_mxu` selects the MXU scan
    level form: the segment restoration planes (ps, bk) arrive in
    place of the flag plane; the sum semiring rides the triangular
    -matmul prefix (see _scan_np_mxu for the math), min/max run the
    shift ladder with the flag derived as `(ps == lane) & (bk == 0)`
    (a matmul cannot evaluate a tropical prefix)."""
    import jax
    import jax.numpy as jnp

    op, ident, wop = _jnp_kind(kind)
    use_mxu = scan_mxu and kind == "sum"

    def scan_segmented(v, f):
        s = 1
        for _ in range(n_stages):
            if s < C:
                rolled_v = jnp.roll(v, s, axis=1)
                rolled_f = jnp.roll(f, s, axis=1)
                prev_v = jnp.concatenate(
                    [jnp.full((1, C), ident, v.dtype), rolled_v[:-1]],
                    axis=0,
                )
                prev_f = jnp.concatenate(
                    [jnp.ones((1, C), f.dtype), rolled_f[:-1]], axis=0
                )
                lane = jax.lax.broadcasted_iota(jnp.int32, (sub, C), 1)
                sh_v = jnp.where(lane < s, prev_v, rolled_v)
                sh_f = jnp.where(lane < s, prev_f, rolled_f)
            else:
                k = s // C
                sh_v = jnp.concatenate(
                    [jnp.full((k, C), ident, v.dtype), v[:-k]], axis=0
                )
                sh_f = jnp.concatenate(
                    [jnp.ones((k, C), f.dtype), f[:-k]], axis=0
                )
            v = op(v, jnp.where(f > 0, jnp.full_like(v, ident), sh_v))
            f = jnp.maximum(f, sh_f)
            s *= 2
        return v

    def scan_mxu_sum(v, ps, bk):
        """Segment sums from MXU prefix sums (see _scan_np_mxu)."""
        tri = (jax.lax.broadcasted_iota(jnp.int32, (C, C), 0)
               <= jax.lax.broadcasted_iota(jnp.int32, (C, C), 1)
               ).astype(v.dtype)
        rowcum = jnp.dot(v, tri, preferred_element_type=v.dtype)
        rowcum_exc = rowcum - v
        sub1 = jnp.take_along_axis(rowcum_exc, ps, axis=1)
        rseg = rowcum - sub1
        gr = _mxu_group_rows(sub)
        e_last = (jax.lax.broadcasted_iota(jnp.int32, (C, C), 0)
                  == (C - 1)).astype(v.dtype)
        lexc = (jax.lax.broadcasted_iota(jnp.int32, (gr, gr), 1)
                < jax.lax.broadcasted_iota(jnp.int32, (gr, gr), 0)
                ).astype(v.dtype)
        parts = []
        base = jnp.zeros((1, C), v.dtype)
        for g in range(sub // gr):
            rg = rseg[g * gr:(g + 1) * gr]
            tail_g = jnp.dot(rg, e_last,
                             preferred_element_type=v.dtype)
            s_exc_g = jnp.dot(lexc, tail_g,
                              preferred_element_type=v.dtype)
            parts.append(s_exc_g + base)
            base = base + (s_exc_g[gr - 1:gr] + tail_g[gr - 1:gr])
        w_pref = (jnp.concatenate(parts, axis=0) if len(parts) > 1
                  else parts[0])
        row = jax.lax.broadcasted_iota(jnp.int32, (sub, C), 0)
        g_w = jnp.take_along_axis(w_pref, row - bk, axis=0)
        return rseg + (w_pref - g_w)

    from libgrape_lite_tpu.ops.route3 import apply_route3

    def scan_part(vals, w_ref, route_refs, scan_refs):
        """Shared route -> segmented scan.  Values at invalid slots are
        left unmasked: every invalid slot is its own flagged segment
        (flags==0 -> f0=1; mxu planes encode ps=lane, bk=0 -> same),
        so garbage there can neither combine into a real segment nor
        be extracted — the old per-slot validity select was a no-op on
        every observable output."""
        if aligned:
            (rr_ref,) = route_refs
            routed = jnp.take_along_axis(
                vals, rr_ref[0].astype(jnp.int32), axis=0
            )
        else:
            l1_ref, s2_ref, l3_ref = route_refs
            routed = apply_route3(vals, l1_ref[0], s2_ref[0], l3_ref[0])
        if w_ref is not None:
            routed = wop(routed, w_ref[0])
        if scan_mxu:
            ps_ref, bk_ref = scan_refs
            ps = ps_ref[0].astype(jnp.int32)
            bk = bk_ref[0].astype(jnp.int32)
            if use_mxu:
                return scan_mxu_sum(routed, ps, bk)
            lane = jax.lax.broadcasted_iota(jnp.int32, (sub, C), 1)
            f0 = jnp.logical_and(ps == lane, bk == 0)
            return scan_segmented(routed, f0.astype(routed.dtype))
        (flags_ref,) = scan_refs
        f0 = (flags_ref[0].astype(jnp.int32) != 1).astype(vals.dtype)
        return scan_segmented(routed, f0)

    def tail(vals, w_ref, route_refs, scan_refs,
             el1_ref, es2_ref, el3_ref, out_ref):
        """Shared route -> segmented scan -> extraction epilogue.
        No out-validity select: unrouted compact slots carry garbage
        that stays its own flagged segment downstream."""
        cs = scan_part(vals, w_ref, route_refs, scan_refs)
        out_ref[0] = apply_route3(cs, el1_ref[0], es2_ref[0],
                                  el3_ref[0])

    def gather_vals(tab_ref, hubtab_ref, gidx_ref):
        tab = tab_ref[...]
        # undo the lane mix: tab_mixed[r, l] = tab[r, l ^ mix(r)]
        rr = jax.lax.broadcasted_iota(jnp.int32, (sub, C), 0)
        ll = jax.lax.broadcasted_iota(jnp.int32, (sub, C), 1)
        tab = jnp.take_along_axis(tab, ll ^ _row_mix(rr), axis=1)
        idx = gidx_ref[0].astype(jnp.int32)
        v_tab = jnp.take_along_axis(tab, jnp.maximum(idx, 0), axis=0)
        # hub slots encode -1 - hub_idx; the hub table is padded to
        # [sub, C] so its read is two shape-matched dynamic gathers
        # instead of a hub//C register loop.  The sublane gather's row
        # index MUST be lane-uniform (the subsequent lane gather would
        # otherwise read the row plane at post-permutation positions);
        # the planner guarantees each kernel row holds hub entries of
        # ONE 128-entry group, recovered here with a lane-wise max
        # (non-hub slots carry hs < 0 and never win; all-non-hub rows
        # read group 0 garbage that the final select discards).
        hs = -1 - idx
        hs_c = jnp.maximum(hs, 0)
        grp_row = jnp.max(hs, axis=1, keepdims=True)
        rowp = jnp.broadcast_to(
            jnp.maximum(grp_row, 0) >> 7, (sub, C)
        )
        ht = jnp.take_along_axis(hubtab_ref[...], rowp, axis=0)
        v_hub = jnp.take_along_axis(ht, hs_c & (C - 1), axis=1)
        return jnp.where(hs >= 0, v_hub, v_tab)

    if not extract:
        # final-level phase A: fold-scan only; phase B extracts per
        # row-range tile from the scanned plane
        if aligned:
            def kernel(vals_ref, rr_ref, *scan_refs):
                out_ref = scan_refs[-1]
                out_ref[0] = scan_part(vals_ref[0], None, (rr_ref,),
                                       scan_refs[:-1])
        else:
            def kernel(vals_ref, l1_ref, s2_ref, l3_ref, *scan_refs):
                out_ref = scan_refs[-1]
                out_ref[0] = scan_part(vals_ref[0], None,
                                       (l1_ref, s2_ref, l3_ref),
                                       scan_refs[:-1])

        return kernel

    if lv_has_gather and has_w:
        def kernel(tab_ref, hubtab_ref, gidx_ref, w_ref, *rest):
            route_refs, scan_refs, ext = _split_refs(rest, aligned,
                                                     scan_mxu)
            tail(gather_vals(tab_ref, hubtab_ref, gidx_ref), w_ref,
                 route_refs, scan_refs, *ext)
    elif lv_has_gather:
        def kernel(tab_ref, hubtab_ref, gidx_ref, *rest):
            route_refs, scan_refs, ext = _split_refs(rest, aligned,
                                                     scan_mxu)
            tail(gather_vals(tab_ref, hubtab_ref, gidx_ref), None,
                 route_refs, scan_refs, *ext)
    else:
        def kernel(vals_ref, *rest):
            route_refs, scan_refs, ext = _split_refs(rest, aligned,
                                                     scan_mxu)
            tail(vals_ref[0], None, route_refs, scan_refs, *ext)

    return kernel


def _split_refs(rest, aligned: bool, scan_mxu: bool):
    """Split a kernel's trailing positional refs into (route_refs,
    scan_refs, extraction refs + out_ref) per the level form."""
    n_route = 1 if aligned else 3
    n_scan = 2 if scan_mxu else 1
    return (
        tuple(rest[:n_route]),
        tuple(rest[n_route:n_route + n_scan]),
        tuple(rest[n_route + n_scan:]),
    )


def _extract_kernel_body(kind: str = "sum"):
    """Final-level phase B: extract one row-range tile from a scanned
    block (grid (block, tile); the scanned plane stays resident across
    the tile dimension)."""
    _, ident, _ = _jnp_kind(kind)

    def kernel(cs_ref, el1_ref, es2_ref, el3_ref, eval_ref, out_ref):
        import jax.numpy as jnp
        from libgrape_lite_tpu.ops.route3 import apply_route3

        ex = apply_route3(cs_ref[0], el1_ref[0, 0], es2_ref[0, 0],
                          el3_ref[0, 0])
        out_ref[0, 0] = jnp.where(eval_ref[0, 0] > 0, ex,
                                  jnp.full_like(ex, ident))

    return kernel


def _stage_order(blocks):
    """Stable ordering of a level's blocks by scan stage count — the
    device executor batches same-stage blocks into one pallas_call, so
    the stacked streams ship in this order (skel.order maps back)."""
    return np.argsort([b.scan_stages for b in blocks], kind="stable")


def _narrowed_dtype(arrs, dtype):
    """Widen rather than wrap when a stream outgrows its narrow dtype
    (the final level's es2 rows scale with vp//128, which PackConfig
    cannot bound; mxu bk planes scale with segment row span).  Widens
    to the NARROWEST integer type that holds the level's actual value
    range — the ledger prices every shipped table at this dtype."""
    if np.issubdtype(dtype, np.integer):
        lo = min(int(a.min()) for a in arrs)
        hi = max(int(a.max()) for a in arrs)
        for cand in (dtype, np.dtype(np.int16), np.dtype(np.int32)):
            info = np.iinfo(cand)
            if lo >= info.min and hi <= info.max:
                return np.dtype(cand)
        return np.dtype(np.int64)
    return np.dtype(dtype)


def _stack_blocks(lv: LevelPlan, nbytes_only: bool = False):
    """Stack a level's static block arrays into device-ready numpy, in
    scan-stage-sorted block order (see _stage_order).

    Index streams stay narrow on device (lane ids int8, row ids int16 —
    ADVICE r2: int32 streams double the VMEM bill for nothing); the
    kernel upcasts to int32 at each use site.  Lane ids are < 128 and
    block row ids < 32768 by PackConfig validation; widening is decided
    by _narrowed_dtype.

    `nbytes_only` returns each stream's exact shipped byte count
    instead of the arrays — the op-budget ledger prices HBM from the
    same dtype decisions without paying for a second full copy of
    hundreds of MB of stream tables."""
    import numpy as np

    blocks = [lv.blocks[i] for i in _stage_order(lv.blocks)]

    def st(name, get):
        arrs = [np.asarray(get(b)) for b in blocks]
        dtype = _narrowed_dtype(arrs, np.dtype(_STREAM_DTYPES[name]))
        if nbytes_only:
            return sum(a.size for a in arrs) * dtype.itemsize
        return np.stack(arrs).astype(dtype)

    if blocks[0].route_rows is not None:
        # composed fold level: the merge route is one sublane-gather
        # row plane — 3x fewer index streams than a generic Route3
        d = {"rr": st("rr", lambda b: b.route_rows)}
    else:
        d = {
            "l1": st("l1", lambda b: b.route.l1),
            "s2": st("s2", lambda b: b.route.s2),
            "l3": st("l3", lambda b: b.route.l3),
        }
    if blocks[0].scan_mxu:
        # mxu scan levels ship the restoration planes instead of the
        # flag plane (the ladder flag is derivable: ps==lane & bk==0)
        d["ps"] = st("ps", lambda b: b.ps)
        d["bk"] = st("bk", lambda b: b.bk)
    else:
        d["flags"] = st("flags", lambda b: b.flags)
    if lv.blocks[0].tiles is not None:
        # final level: per-row-range tile extraction routes
        def tst(name, get):
            arrs = [np.asarray(get(t)) for b in blocks for t in b.tiles]
            dtype = _narrowed_dtype(arrs, np.dtype(_STREAM_DTYPES[name]))
            if nbytes_only:
                return sum(a.size for a in arrs) * dtype.itemsize
            nt = len(blocks[0].tiles)
            out = np.stack(arrs).reshape(
                (len(blocks), nt) + arrs[0].shape
            )
            return out.astype(dtype)

        d["tel1"] = tst("tel1", lambda t: t[0].l1)
        d["tes2"] = tst("tes2", lambda t: t[0].s2)
        d["tel3"] = tst("tel3", lambda t: t[0].l3)
        d["teval"] = tst(
            "teval", lambda t: t[1].reshape(lv.tile_sub, C)
        )
    else:
        # no out-validity plane: unrouted compact slots carry garbage
        # that downstream levels isolate as its own flagged segment
        # (same proof that removed the scan's validity select in r6)
        d["el1"] = st("el1", lambda b: b.eroute.l1)
        d["es2"] = st("es2", lambda b: b.eroute.s2)
        d["el3"] = st("el3", lambda b: b.eroute.l3)
    if lv.has_gather:
        # one merged index plane: >= 0 is the x-table row, < 0 encodes
        # the hub slot as -1 - hub_idx (halves the gather index bytes
        # vs the old separate sub_idx/hub_sel pair)
        d["gidx"] = st(
            "gidx",
            lambda b: np.where(
                b.hub_sel >= 0,
                -1 - b.hub_sel.astype(np.int32),
                b.sub_idx.astype(np.int32),
            ),
        )
        if lv.blocks[0].w is not None:
            d["w"] = st("w", lambda b: b.w)
    return d


@dataclass(frozen=True)
class LevelSkel:
    """The static structure of one level — everything the executor
    needs besides the stream arrays themselves.  Under shard_map every
    shard runs the SAME skeleton (plan_pack_multi pads shards and
    unifies per-block scan stages to make that true); the streams
    arrive as per-shard inputs."""

    has_gather: bool
    is_final: bool
    nb: int
    out_sub: int            # compact out rows (vp//128 on the final)
    tile_sub: int           # final: rows per extraction tile (else 0)
    pass_idx: int           # gather: index into the x pass stack
    has_w: bool
    n_inputs: tuple         # per block: input streams consumed
    # span-aware scan batching: ((stages, nblocks), ...) over the
    # stage-sorted block order the streams ship in, and the map from
    # sorted position back to original block index
    scan_groups: tuple = ()
    order: tuple = ()
    # composed fold level: merge route ships as one sublane-gather
    # plane ("rr") instead of a 3-stage Route3
    aligned: bool = False
    # MXU scan level: ps/bk restoration planes ship instead of flags;
    # kind=="sum" rides the triangular-matmul prefix, min/max the
    # ladder with the derived flag
    mxu: bool = False


def _skel_of(lv: LevelPlan, span: int) -> LevelSkel:
    order = tuple(int(i) for i in _stage_order(lv.blocks))
    groups: list[list[int]] = []
    for pos in order:
        s = int(lv.blocks[pos].scan_stages)
        if groups and groups[-1][0] == s:
            groups[-1][1] += 1
        else:
            groups.append([s, 1])
    return LevelSkel(
        has_gather=lv.has_gather,
        is_final=lv.blocks[0].tiles is not None if lv.blocks else False,
        nb=len(lv.blocks),
        out_sub=lv.out_sub,
        tile_sub=lv.tile_sub,
        pass_idx=lv.pass_base // span if lv.has_gather else 0,
        has_w=lv.has_gather and lv.blocks[0].w is not None,
        n_inputs=tuple(b.n_inputs for b in lv.blocks),
        scan_groups=tuple((s, c) for s, c in groups),
        order=order,
        aligned=bool(lv.blocks
                     and lv.blocks[0].route_rows is not None),
        mxu=bool(lv.blocks and lv.blocks[0].scan_mxu),
    )


def _level_device(plan: PackPlan, key, lv: LevelPlan):
    import jax.numpy as jnp

    if key not in plan._device:
        plan._device[key] = {
            k: jnp.asarray(v) for k, v in _stack_blocks(lv).items()
        }
    return plan._device[key]


def _run_level_dev(cfg: PackConfig, skel: LevelSkel, dev, x_tab, hub_tab,
                   in_streams, interpret: bool, kind: str = "sum"):
    """Run one level's pallas_call(s) from its skeleton + stream dict;
    returns list of per-block flat output streams (traced jnp arrays)
    in ORIGINAL block order (downstream consumption order and the
    final summation order are bit-load-bearing).

    Blocks are batched by their span-aware scan stage count — one
    pallas_call per (stages, count) group over the stage-sorted stream
    stacks, so each group unrolls exactly the stages its segments need.
    Final levels run two phases: a fold-scan over each block, then a
    (block, row-tile) extraction grid whose VMEM residency is bounded
    by tile_sub regardless of vp."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nb = skel.nb
    sub, out_sub = cfg.sub, skel.out_sub
    has_w = skel.has_gather and skel.has_w
    max_stages = max(1, int(np.ceil(np.log2(sub * C))))
    groups = skel.scan_groups or ((max_stages, nb),)
    order = skel.order or tuple(range(nb))

    def bspec(shape_sub):
        return pl.BlockSpec((1, shape_sub, C), lambda i: (i, 0, 0))

    def fold_input_list():
        # assemble the ragged fold inputs into per-block [sub, C]
        # planes, original block order (all offsets static; these are
        # plain XLA concats/reshapes)
        parts = []
        off = 0
        for k in skel.n_inputs:
            segs = in_streams[off:off + k]
            ln = sum(s.shape[0] for s in segs)
            pad = cfg.slots - ln
            if pad:
                ident = _KINDS[kind][1]
                segs = segs + [
                    jnp.full((pad,), ident, segs[0].dtype)
                ]
            parts.append(jnp.concatenate(segs).reshape(sub, C))
            off += k
        return parts

    if skel.aligned:
        route_in = [dev["rr"]]
        route_specs = [bspec(sub)]
    else:
        rmid = dev["s2"].shape[-2]
        route_in = [dev["l1"], dev["s2"], dev["l3"]]
        route_specs = [bspec(rmid), bspec(rmid), bspec(sub)]
    if skel.mxu:
        route_in += [dev["ps"], dev["bk"]]
        route_specs += [bspec(sub), bspec(sub)]
    else:
        route_in.append(dev["flags"])
        route_specs.append(bspec(sub))

    def unsort(outs_sorted):
        outs = [None] * nb
        for spos, o in enumerate(outs_sorted):
            outs[order[spos]] = o
        return outs

    if skel.is_final:
        # ---- phase A: fold-scan each block to its scanned plane ----
        parts = fold_input_list()
        parts_sorted = [parts[i] for i in order]
        cs_sorted = []
        off = 0
        for stages, cnt in groups:
            scan_kernel = _kernel_body(False, sub, sub, stages,
                                       kind, False, extract=False,
                                       aligned=skel.aligned,
                                       scan_mxu=skel.mxu)
            cs = pl.pallas_call(
                scan_kernel,
                grid=(cnt,),
                in_specs=[bspec(sub)] + route_specs,
                out_specs=bspec(sub),
                out_shape=jax.ShapeDtypeStruct((cnt, sub, C),
                                               jnp.float32),
                interpret=interpret,
            )(jnp.stack(parts_sorted[off:off + cnt]),
              *[a[off:off + cnt] for a in route_in])
            cs_sorted.extend(cs[b] for b in range(cnt))
            off += cnt

        # ---- phase B: extract row-range tiles (tile streams are
        # stacked in the same stage-sorted order) ----
        nt = dev["tel1"].shape[1]
        tile_sub = skel.tile_sub
        ermid = dev["tes2"].shape[-2]
        ex_kernel = _extract_kernel_body(kind)

        def tspec(shape_sub):
            return pl.BlockSpec(
                (1, 1, shape_sub, C), lambda i, j: (i, j, 0, 0)
            )

        out = pl.pallas_call(
            ex_kernel,
            grid=(nb, nt),
            in_specs=[
                pl.BlockSpec((1, sub, C), lambda i, j: (i, 0, 0)),
                tspec(ermid), tspec(ermid), tspec(tile_sub),
                tspec(tile_sub),
            ],
            out_specs=tspec(tile_sub),
            out_shape=jax.ShapeDtypeStruct(
                (nb, nt, tile_sub, C), jnp.float32
            ),
            interpret=interpret,
        )(jnp.stack(cs_sorted), dev["tel1"], dev["tes2"], dev["tel3"],
          dev["teval"])
        return unsort([out[b].reshape(-1) for b in range(nb)])

    ermid = dev["es2"].shape[-2]
    common_in = route_in + [
        dev["el1"], dev["es2"], dev["el3"],
    ]
    common_specs = route_specs + [
        bspec(ermid), bspec(ermid), bspec(out_sub),
    ]

    if skel.has_gather:
        stacked = [dev["gidx"]]
        stacked_specs = [bspec(sub)]
        if has_w:
            stacked.append(dev["w"])
            stacked_specs.append(bspec(sub))
        stacked += common_in
        stacked_specs += common_specs
        invariant = [x_tab, hub_tab]
        inv_specs = [
            pl.BlockSpec((sub, C), lambda i: (0, 0)),
            pl.BlockSpec((sub, C), lambda i: (0, 0)),
        ]
        parts_sorted = None
    else:
        stacked = common_in
        stacked_specs = common_specs
        invariant = []
        inv_specs = []
        parts = fold_input_list()
        parts_sorted = [parts[i] for i in order]

    outs_sorted = []
    off = 0
    for stages, cnt in groups:
        kernel = _kernel_body(skel.has_gather, sub, out_sub,
                              stages, kind, has_w, aligned=skel.aligned,
                              scan_mxu=skel.mxu)
        args = list(invariant)
        specs = list(inv_specs)
        if parts_sorted is not None:
            args.append(jnp.stack(parts_sorted[off:off + cnt]))
            specs.append(bspec(sub))
        args += [a[off:off + cnt] for a in stacked]
        specs += stacked_specs
        out = pl.pallas_call(
            kernel,
            grid=(cnt,),
            in_specs=specs,
            out_specs=bspec(out_sub),
            out_shape=jax.ShapeDtypeStruct((cnt, out_sub, C),
                                           jnp.float32),
            interpret=interpret,
        )(*args)
        outs_sorted.extend(out[b].reshape(-1) for b in range(cnt))
        off += cnt
    return unsort(outs_sorted)


def _exec_levels(x, cfg: PackConfig, vp: int, n_cols: int, level_list,
                 hub_cols, kind: str, interpret: bool | None):
    """Run the whole pipeline given [(LevelSkel, stream dict)] with the
    final level last.  `hub_cols` is a [cfg.hub] index array (traced or
    constant).  This is the shared engine behind the closed-over
    single-shard path and the streams-from-state multi-shard path."""
    import jax.numpy as jnp

    if interpret is None:
        from libgrape_lite_tpu.ops.pallas_kernels import use_pallas

        interpret = not use_pallas()

    x = jnp.asarray(x, jnp.float32)
    if not level_list:
        # zero-edge plan: nothing to gather or fold
        return jnp.full((vp,), _KINDS[kind][1], jnp.float32)

    span = cfg.slots
    n_pass = max(1, -(-n_cols // span))
    x_pad = jnp.concatenate(
        [x, jnp.zeros((n_pass * span - n_cols,), x.dtype)]
    ) if n_pass * span != n_cols else x
    x_passes = x_pad.reshape(n_pass, cfg.sub, C)
    # hub table padded to [sub, C]: Mosaic's sublane dynamic gather
    # requires table shape == index shape, so the kernel reads hubs
    # with two shape-matched gathers instead of a register loop
    hub_tab = jnp.concatenate([
        x[hub_cols].reshape(cfg.hub // C, C),
        jnp.zeros((cfg.sub - cfg.hub // C, C), x.dtype),
    ]) if cfg.sub > cfg.hub // C else x[hub_cols].reshape(cfg.sub, C)

    streams = []
    for skel, dev in level_list[:-1]:
        if skel.has_gather:
            streams += _run_level_dev(
                cfg, skel, dev, x_passes[skel.pass_idx], hub_tab, None,
                interpret, kind,
            )
        else:
            streams = _run_level_dev(cfg, skel, dev, None, None,
                                     streams, interpret, kind)
    fskel, fdev = level_list[-1]
    outs = _run_level_dev(cfg, fskel, fdev, None, None, streams,
                          interpret, kind)
    op, _, _ = _jnp_kind(kind)
    y = outs[0]
    for o in outs[1:]:
        y = op(y, o)
    return y[:vp]


def segment_reduce_pack(x, plan: PackPlan, kind: str = "sum",
                        interpret: bool | None = None):
    """Run the full pack-gather segment-reduce pipeline: y[vp] f32.

    kind selects the semiring: "sum" (weights multiply — classic
    SpMV), "min"/"max" (weights add — the tropical relaxation of
    SSSP/BFS; rows with no edges yield the identity, matching
    jax.ops.segment_min).  One plan serves every kind.  "sum" under
    the default MXU scan assumes FINITE inputs (prefix differences
    spread a non-finite value across its block — see _scan_np_mxu);
    min/max carry inf sentinels safely (they always run the ladder).

    Usable inside jit; all static structure is closed over as device
    constants.  `interpret=None` auto-selects compiled-on-TPU.
    """
    import jax.numpy as jnp

    if not plan.final or not plan.final.blocks:
        return jnp.full((plan.vp,), _KINDS[kind][1], jnp.float32)

    span = plan.cfg.slots
    level_list = []
    for li, lv in enumerate(plan.levels):
        key = ("g" if lv.has_gather else "f", li)
        level_list.append((_skel_of(lv, span), _level_device(plan, key, lv)))
    level_list.append((
        _skel_of(plan.final, span),
        _level_device(plan, ("final",), plan.final),
    ))
    return _exec_levels(x, plan.cfg, plan.vp, plan.n_cols, level_list,
                        jnp.asarray(plan.hub_cols), kind, interpret)


def segment_sum_pack(x, plan: PackPlan, interpret: bool | None = None):
    """Back-compat alias: segment_reduce_pack(kind="sum")."""
    return segment_reduce_pack(x, plan, "sum", interpret)


# --------------------------------------------------------------------------
# multi-shard plans: uniform structure + per-shard streams
# --------------------------------------------------------------------------


@dataclass
class MultiPackPlan:
    """Per-shard pack plans with one shared skeleton.

    Under `shard_map` every device runs the same traced program, so
    the level/block structure must be identical across shards; the
    shard-specific stream arrays are stacked `[fnum, ...]` and flow in
    as sharded state inputs (the app declares them `ephemeral_keys`).
    The reference analogue: the CUDA LB kernels run the same grid on
    every GPU of the mesh (`cuda/parallel/parallel_engine.h:989-1013`)
    with per-GPU data."""

    vp: int
    n_cols: int
    cfg: PackConfig
    fnum: int
    skels: List[LevelSkel]               # ordered; final level last
    host_streams: dict                   # name -> [fnum, ...] numpy
    uid: int = field(default_factory=lambda: next(_PLAN_COUNTER))
    # static op-budget ledger (summed across shards; see plan_ledger)
    ledger: Optional[dict] = None

    def state_entries(self, prefix: str) -> dict:
        """Numpy state entries ([fnum, ...] leaves) to merge into the
        app's init state; list them in the app's `ephemeral_keys`."""
        return {prefix + k: v for k, v in self.host_streams.items()}

    def state_keys(self, prefix: str):
        return [prefix + k for k in self.host_streams]


def plan_pack_multi(shards, vp: int, n_cols: int,
                    cfg: PackConfig = PackConfig()) -> MultiPackPlan:
    """Build per-shard plans with a uniform skeleton.

    shards: per fragment (rows, cols, w-or-None) CSR-sorted edge lists
    (rows are shard-local in [0, vp); cols index the gathered
    [n_cols] state).  Gather-level block counts are padded to the
    per-pass maximum with empty blocks; mid folds are skipped (their
    grouping is data-dependent) — the capacity-grouped final level
    absorbs the streams uniformly."""
    assert vp % C == 0
    if vp // C > _MAX_VP_SUB:
        raise ValueError(
            f"vp={vp} exceeds {_MAX_VP_SUB * C} rows per shard plan"
        )
    fnum = len(shards)
    has_w = shards[0][2] is not None
    span = cfg.slots

    per_gather = []
    hubs = []
    for rows, cols, w in shards:
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        assert (np.diff(rows) >= 0).all(), "edges must be row-sorted"
        assert (w is None) == (not has_w), "weighted-ness must be uniform"
        glv, hub = _plan_shard_gather(rows, cols, vp, n_cols, cfg, w)
        per_gather.append(glv)
        hubs.append(hub)

    pass_idxs = sorted({p for glv in per_gather for p in glv})
    levels_per_shard: list[list[LevelPlan]] = [[] for _ in range(fnum)]
    for p in pass_idxs:
        nb = max(
            len(glv[p].blocks) if p in glv else 0 for glv in per_gather
        )
        for f, glv in enumerate(per_gather):
            lv = glv.get(p)
            if lv is None:
                lv = LevelPlan(cfg=cfg, blocks=[], has_gather=True,
                               pass_base=p * span, out_sub=cfg.out_sub)
            while len(lv.blocks) < nb:
                lv.blocks.append(_empty_gather_block(cfg, p * span,
                                                     has_w))
            levels_per_shard[f].append(lv)

    # route composition must produce ONE skeleton: engage the aligned
    # final level only when every shard's stream set is feasible.
    # Group preps (the per-group merge argsort) are computed once per
    # shard and shared with the final-level planner below.
    per_shard_streams = [
        _level_streams(levels_per_shard[f]) for f in range(fnum)
    ]
    per_shard_groups = [
        _final_groups(s, cfg) for s in per_shard_streams
    ]
    per_shard_preps = [
        [_group_prep(g) for g in grps] for grps in per_shard_groups
    ]
    aligned_final = _compose_enabled() and all(
        _aligned_feasible(g, cfg, p)
        for grps, preps in zip(per_shard_groups, per_shard_preps)
        for g, p in zip(grps, preps)
    )
    all_levels: list[list[LevelPlan]] = []
    for f in range(fnum):
        final = _plan_final_level(per_shard_streams[f], vp, cfg,
                                  aligned=aligned_final,
                                  preps=per_shard_preps[f])
        all_levels.append(levels_per_shard[f] + [final])
    # span-aware scans unroll a static stage count; under shard_map all
    # shards run one traced program, so unify each block's stages to
    # the per-block max across shards (extra stages are bit-exact
    # no-ops for the shard that needed fewer), then decide the
    # level-wide scan form from the ALL-shard block set so every
    # shard's skeleton engages identically
    for li in range(len(all_levels[0])):
        for bj in range(len(all_levels[0][li].blocks)):
            s = max(all_levels[f][li].blocks[bj].scan_stages
                    for f in range(fnum))
            for f in range(fnum):
                all_levels[f][li].blocks[bj].scan_stages = s
        blocks_all = [b for f in range(fnum)
                      for b in all_levels[f][li].blocks]
        mxu = _decide_level_scan(blocks_all)
        for b in blocks_all:
            b.scan_mxu = mxu
            b.ledger = _reledger_block(cfg, b)

    if not pass_idxs:
        # zero edges on every shard
        return MultiPackPlan(
            vp=vp, n_cols=n_cols, cfg=cfg, fnum=fnum, skels=[],
            host_streams={"hub_cols": np.stack(hubs)},
        )

    skels = [_skel_of(lv, span) for lv in all_levels[0]]
    for f in range(1, fnum):
        got = [_skel_of(lv, span) for lv in all_levels[f]]
        assert got == skels, (
            f"shard {f} skeleton diverged from shard 0 — "
            "plan_pack_multi padding is broken"
        )

    host_streams = {}
    for i in range(len(skels)):
        per_shard = [_stack_blocks(all_levels[f][i]) for f in range(fnum)]
        for name in per_shard[0]:
            arrs = [d[name] for d in per_shard]
            dt = np.result_type(*[a.dtype for a in arrs])
            host_streams[f"L{i}_{name}"] = np.stack(
                [a.astype(dt) for a in arrs]
            )
    host_streams["hub_cols"] = np.stack(hubs)
    _warn_vmem(cfg, has_w=has_w, final_out_sub=all_levels[0][-1].tile_sub)
    return MultiPackPlan(
        vp=vp, n_cols=n_cols, cfg=cfg, fnum=fnum, skels=skels,
        host_streams=host_streams,
        ledger=_ledger_of_levels(all_levels, n_cols, cfg),
    )


def segment_reduce_pack_sharded(x, mplan: MultiPackPlan, streams: dict,
                                kind: str = "sum",
                                interpret: bool | None = None,
                                prefix: str = ""):
    """The multi-shard executor: runs inside shard_map with this
    shard's squeezed stream arrays (pulled from the app state by the
    caller, keys as produced by `state_entries(prefix)`)."""
    level_list = []
    for i, skel in enumerate(mplan.skels):
        dev = {}
        want = f"{prefix}L{i}_"
        for k, v in streams.items():
            if k.startswith(want):
                dev[k[len(want):]] = v
        level_list.append((skel, dev))
    return _exec_levels(
        x, mplan.cfg, mplan.vp, mplan.n_cols, level_list,
        streams[prefix + "hub_cols"], kind, interpret,
    )


# --------------------------------------------------------------------------
# fragment-level entry point
# --------------------------------------------------------------------------

_FRAG_PLAN_CACHE = None
_INELIGIBLE_WARNED: set = set()


def warn_pack_ineligible(app_name: str, reason: str):
    """GRAPE_SPMV=pack was requested but the app fell back to XLA —
    say so once (ADVICE r2: a silent fallback lets an explicit pack
    A/B quietly measure the wrong path).  GRAPE_SPMV_STRICT=1 turns
    the fallback into an error for benchmark harnesses."""
    import os
    import warnings

    key = (app_name, reason)
    if os.environ.get("GRAPE_SPMV_STRICT"):
        raise RuntimeError(
            f"GRAPE_SPMV=pack requested but {app_name} is ineligible: "
            f"{reason} (GRAPE_SPMV_STRICT=1)"
        )
    if key not in _INELIGIBLE_WARNED:
        _INELIGIBLE_WARNED.add(key)
        warnings.warn(
            f"GRAPE_SPMV=pack requested but {app_name} falls back to the "
            f"XLA path: {reason}",
            stacklevel=3,
        )


def _frag_cache(frag):
    global _FRAG_PLAN_CACHE
    import weakref

    if _FRAG_PLAN_CACHE is None:
        _FRAG_PLAN_CACHE = weakref.WeakKeyDictionary()
    return _FRAG_PLAN_CACHE.setdefault(frag, {})


def _shard_edges(frag, fid: int, with_weights: bool, direction: str,
                 cols_override=None, row_mask=None):
    csrs = frag.host_ie if direction == "ie" else frag.host_oe
    h = csrs[fid] if csrs else (frag.host_oe[fid])
    mask = h.edge_mask
    if row_mask is not None:
        # boundary/interior sub-plan (superstep pipelining, r9): keep
        # only edges whose destination row is in this partition — the
        # original CSR order is preserved, so each surviving row's
        # fold sees its candidates in the serial order
        safe_src = np.minimum(h.edge_src.astype(np.int64), frag.vp - 1)
        mask = np.logical_and(mask, np.asarray(row_mask[fid])[safe_src])
    rows = h.edge_src[mask].astype(np.int64)
    if cols_override is not None:
        cols = np.asarray(cols_override[fid])[mask].astype(np.int64)
    else:
        cols = h.edge_nbr[mask].astype(np.int64)
    w = None
    if with_weights:
        if h.edge_w is None:
            return None
        w = h.edge_w[mask]
    return rows, cols, w


def plan_pack_for_fragment(frag, cfg: PackConfig = PackConfig(),
                           with_weights: bool = False,
                           direction: str = "ie"):
    """Build (and cache per fragment) the single-shard pack plan for
    `frag`'s dense pull: rows = local edge_src, cols = pid edge_nbr
    into the gathered [fnum*vp] state; `with_weights` bakes the f32
    edge-weight stream in (the tropical SSSP relaxation).  Multi-shard
    fragments use `plan_pack_multi_for_fragment` (uniform skeleton +
    per-shard streams) instead."""
    if frag.fnum != 1:
        return None
    per_frag = _frag_cache(frag)
    key = (cfg, with_weights, direction, "single", _scan_mode())
    if key in per_frag:
        return per_frag[key]
    shard = _shard_edges(frag, 0, with_weights, direction)
    if shard is None:
        return None
    rows, cols, w = shard
    plan = plan_pack(rows, cols, frag.vp, frag.fnum * frag.vp, cfg,
                     edge_w=w)
    per_frag[key] = plan
    return plan


def plan_pack_multi_for_fragment(frag, cfg: PackConfig = PackConfig(),
                                 with_weights: bool = False,
                                 direction: str = "ie"):
    """Build (and cache per fragment) the MultiPackPlan covering every
    shard of `frag` — the pack path's multi-chip form (VERDICT r2
    missing #2: the perf path and the mesh must compose)."""
    per_frag = _frag_cache(frag)
    key = (cfg, with_weights, direction, "multi", _scan_mode())
    if key in per_frag:
        return per_frag[key]
    shards = []
    for f in range(frag.fnum):
        shard = _shard_edges(frag, f, with_weights, direction)
        if shard is None:
            return None
        shards.append(shard)
    mplan = plan_pack_multi(shards, frag.vp, frag.fnum * frag.vp, cfg)
    per_frag[key] = mplan
    return mplan


def pack_plan_to_multi(plan: PackPlan) -> MultiPackPlan:
    """Convert a single-shard PackPlan into the skeleton + streams form
    (fnum=1), which is what PackDispatch executes and the plan cache
    persists — the mid-fold levels the single-shard planner builds
    carry over as ordinary fold skeleton entries."""
    span = plan.cfg.slots
    if not plan.final or not plan.final.blocks:
        return MultiPackPlan(
            vp=plan.vp, n_cols=plan.n_cols, cfg=plan.cfg, fnum=1,
            skels=[], host_streams={"hub_cols": plan.hub_cols[None]},
        )
    skels, streams = [], {}
    for i, lv in enumerate(list(plan.levels) + [plan.final]):
        skels.append(_skel_of(lv, span))
        for k, v in _stack_blocks(lv).items():
            streams[f"L{i}_{k}"] = v[None]
    streams["hub_cols"] = plan.hub_cols[None]
    return MultiPackPlan(
        vp=plan.vp, n_cols=plan.n_cols, cfg=plan.cfg, fnum=1,
        skels=skels, host_streams=streams, ledger=plan_ledger(plan),
    )


class PackDispatch:
    """One resolved pack backend for a (fragment, direction) pull, so
    apps dispatch through one object instead of duplicating the fnum
    branch (PageRank/SSSP/WCC/BFS all share this).

    mode "const": single-shard — stream tables close over the trace as
    device constants (cached here), no state plumbing.
    mode "state": multi-shard — per-shard streams ride in as sharded
    ephemeral state leaves (closing over them under shard_map would
    replicate every shard's tables to every device)."""

    def __init__(self, mplan: MultiPackPlan, mode: str, prefix: str):
        assert mode in ("const", "state")
        self.mplan = mplan
        self.mode = mode
        self.prefix = prefix
        self._const = None

    @property
    def uid(self) -> int:
        return self.mplan.uid

    def ledger(self) -> Optional[dict]:
        """The plan's static op-budget ledger (None for plans loaded
        from a pre-ledger cache entry — impossible under the current
        schema, kept for safety)."""
        return self.mplan.ledger

    def state_entries(self) -> dict:
        """Ephemeral state leaves ([fnum, ...] numpy) the app must merge
        into its init state (empty on the const path)."""
        if self.mode == "const":
            return {}
        return self.mplan.state_entries(self.prefix)

    def reduce(self, x, state, kind: str = "sum",
               interpret: bool | None = None):
        """y[vp] = segment-reduce of x over the planned edges."""
        if self.mode == "const":
            import jax.numpy as jnp

            if self._const is None:
                self._const = {
                    k: jnp.asarray(v[0])
                    for k, v in self.mplan.host_streams.items()
                }
            return segment_reduce_pack_sharded(
                x, self.mplan, self._const, kind, interpret, prefix=""
            )
        streams = {
            k: state[k] for k in self.mplan.state_keys(self.prefix)
        }
        return segment_reduce_pack_sharded(
            x, self.mplan, streams, kind, interpret, prefix=self.prefix
        )


# resolve-path counters: how often a pack resolve was served from the
# per-fragment cache vs the on-disk plan cache vs the O(E log E)
# planner.  serve/ pins "a session's second query performs ZERO pack
# planning" on `planned` staying flat (tests/test_serve.py).
# Federated as "plan" (obs/federation.py): a dict subclass, so the
# hot-path `PLAN_STATS[...] += 1` sites below are unchanged.
from libgrape_lite_tpu.obs.federation import FederatedStats as _FedStats

PLAN_STATS = _FedStats("plan", {
    "frag_cache_hits": 0, "disk_cache_hits": 0, "planned": 0,
})


def plan_stats() -> dict:
    """Snapshot of the resolve-path counters (copy — mutation-safe).
    When a superstep pipeline has been resolved (GRAPE_PIPELINE,
    parallel/pipeline.py), the snapshot additionally carries its
    boundary/interior vertex+edge counts per fragment under
    "pipeline" — the boundary-set stats surfaced everywhere the plan
    is (Worker.pack_ledger, trace_report)."""
    out = dict(PLAN_STATS)
    try:
        from libgrape_lite_tpu.parallel.pipeline import PIPELINE_STATS

        if PIPELINE_STATS["last_stats"] is not None:
            out["pipeline"] = {
                "resolved": PIPELINE_STATS["resolved"],
                "declined": PIPELINE_STATS["declined"],
                **PIPELINE_STATS["last_stats"],
            }
    except ImportError:  # pragma: no cover — circular-import safety
        pass
    return out


def resolve_pack_dispatch(frag, cfg: PackConfig | None = None,
                          with_weights: bool = False,
                          direction: str = "ie",
                          prefix: str = "pk_",
                          mirror=None,
                          role: str = "full",
                          row_mask=None):
    """Resolve the pack backend for `frag`: a PackDispatch, or None if
    no plan is buildable (caller should warn_pack_ineligible).  Checks
    the persistent plan cache (GRAPE_PACK_PLAN_CACHE) before running
    the O(E log E) host planner, and saves fresh plans into it.

    `mirror` (a parallel.mirror.MirrorPlan for the same direction)
    composes the plan with the mirror-compressed exchange: columns are
    the compact remapped ones and the gather table covers only
    vp + fnum*m entries instead of fnum*vp.

    `role`/`row_mask` (superstep pipelining, r9): "boundary" /
    "interior" sub-plans cover only edges whose destination row is in
    `row_mask` [fnum, vp], so the SpMV can run the boundary slice
    first and overlap the exchange with the interior slice.  The role
    is part of BOTH the per-fragment cache key and the v3 plan-cache
    digest — the disk cache must never serve a serial (full) plan to
    a pipelined run or vice versa, even if a future filter made their
    edge streams collide."""
    cfg = cfg or PackConfig.from_env()
    per_frag = _frag_cache(frag)
    key = (cfg, with_weights, direction, "dispatch",
           mirror.uid if mirror is not None else 0, _scan_mode(), role)
    if key in per_frag:
        mplan = per_frag[key]
        PLAN_STATS["frag_cache_hits"] += 1
        return PackDispatch(
            mplan, "const" if frag.fnum == 1 else "state", prefix
        )

    cols_override = mirror.nbr_compact if mirror is not None else None
    # 2-D vertex-cut tiles (fragment/vertexcut.py) gather from the
    # LOCAL [vc] column-broadcast chunk, not the [fnum*vp] all-gather
    # table — the fragment declares its pass-table width
    tile_cols = getattr(frag, "pack_n_cols", None)
    n_cols = (
        mirror.n_compact if mirror is not None
        else tile_cols if tile_cols is not None
        else frag.fnum * frag.vp
    )
    shards = []
    for f in range(frag.fnum):
        shard = _shard_edges(frag, f, with_weights, direction,
                             cols_override, row_mask)
        if shard is None:
            return None
        shards.append(shard)

    mplan = _load_cached_mplan(shards, frag.vp, n_cols, cfg, role)
    if mplan is not None:
        PLAN_STATS["disk_cache_hits"] += 1
    else:
        PLAN_STATS["planned"] += 1
        if row_mask is not None or tile_cols is not None:
            # sub-plans and per-tile plans always take the multi
            # planner (uniform skeleton over the per-shard streams)
            mplan = plan_pack_multi(shards, frag.vp, n_cols, cfg)
        elif mirror is not None:
            mplan = plan_pack_multi(shards, frag.vp, n_cols, cfg)
        elif frag.fnum == 1:
            plan = plan_pack_for_fragment(frag, cfg, with_weights,
                                          direction)
            if plan is None:
                return None
            mplan = pack_plan_to_multi(plan)
        else:
            mplan = plan_pack_multi_for_fragment(frag, cfg, with_weights,
                                                 direction)
            if mplan is None:
                return None
        _save_cached_mplan(mplan, shards, role)
    per_frag[key] = mplan
    return PackDispatch(
        mplan, "const" if frag.fnum == 1 else "state", prefix
    )


# ---- persistent plan cache (VERDICT r2 next #5) --------------------------
#
# The reference amortises load-time work with a content-addressed
# fragment cache (`basic_fragment_loader_base.h:127-242`); pack plans
# are the analogous load-time product here.  Keyed by a digest of the
# exact edge streams + geometry + schema version, stored as one .npz of
# the stacked stream tables under $GRAPE_PACK_PLAN_CACHE.

_PLAN_SCHEMA_VERSION = 3

# the narrow target dtype of every shipped stream table, in one place
# so the plan-cache digest fingerprints the dtype layout a plan was
# built with — widening beyond the target is value-driven
# (_narrowed_dtype) and thus already a function of the digested edge
# streams
_STREAM_DTYPES = {
    "rr": "int16", "l1": "int8", "s2": "int16", "l3": "int8",
    "flags": "int8", "ps": "int8", "bk": "int8",
    "el1": "int8", "es2": "int16", "el3": "int8",
    "tel1": "int8", "tes2": "int16", "tel3": "int8", "teval": "int8",
    "gidx": "int16", "w": "float32",
}


def _shards_digest(shards, vp: int, n_cols: int, cfg: PackConfig,
                   role: str = "full") -> str:
    """Content key for cached plans.  The config prefix fingerprints
    the FULL PackConfig (every dataclass field, so a future knob can't
    silently alias two configs), the input stream dtypes, the shipped
    stream dtype table, the schema version and the planner modes —
    including GRAPE_PACK_SCAN, so a scan-mode flip invalidates stale
    cached plans instead of loading ones whose shipped planes belong
    to the other kernel family, and the pipeline `role`
    (full/boundary/interior), so the cache can never hand a serial
    plan to a pipelined run even if the filtered edge streams were to
    coincide (r9; the threshold decision IS the role)."""
    import dataclasses
    import hashlib

    from libgrape_lite_tpu.ft.fingerprint import stable_config_digest

    cfg_fp = stable_config_digest({
        "schema": _PLAN_SCHEMA_VERSION,
        "cfg": dataclasses.asdict(cfg),
        "final_tile_sub": _FINAL_TILE_SUB,
        "compose": _compose_enabled(),
        "scan": _scan_mode(),
        "role": role,
        "stream_dtypes": _STREAM_DTYPES,
        "vp": vp,
        "n_cols": n_cols,
        "dtypes": [
            [str(np.asarray(r).dtype), str(np.asarray(c).dtype),
             None if w is None else str(np.asarray(w).dtype)]
            for r, c, w in shards
        ],
    })
    h = hashlib.sha256()
    h.update(cfg_fp.encode())
    for rows, cols, w in shards:
        h.update(np.ascontiguousarray(rows, np.int64).tobytes())
        h.update(np.ascontiguousarray(cols, np.int64).tobytes())
        h.update(b"w" if w is not None else b"-")
        if w is not None:
            h.update(np.ascontiguousarray(w, np.float32).tobytes())
    return h.hexdigest()[:24]


def _plan_cache_path(shards, vp, n_cols, cfg, role: str = "full"):
    import os

    root = os.environ.get("GRAPE_PACK_PLAN_CACHE")
    if not root:
        return None
    return os.path.join(
        root,
        f"packplan_{_shards_digest(shards, vp, n_cols, cfg, role)}.npz",
    )


def _save_cached_mplan(mplan: MultiPackPlan, shards, role: str = "full"):
    import dataclasses
    import json
    import os

    path = _plan_cache_path(shards, mplan.vp, mplan.n_cols, mplan.cfg,
                            role)
    if path is None:
        return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    meta = {
        "vp": mplan.vp,
        "n_cols": mplan.n_cols,
        "fnum": mplan.fnum,
        "cfg": [mplan.cfg.sub, mplan.cfg.out_sub, mplan.cfg.hub],
        "skels": [dataclasses.asdict(s) for s in mplan.skels],
        "ledger": mplan.ledger,
    }
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            __meta=np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8
            ).copy(),
            **mplan.host_streams,
        )
    os.replace(tmp, path)


def _load_cached_mplan(shards, vp, n_cols, cfg, role: str = "full"):
    import json
    import os

    path = _plan_cache_path(shards, vp, n_cols, cfg, role)
    if path is None or not os.path.exists(path):
        return None
    try:
        z = np.load(path)
        meta = json.loads(bytes(z["__meta"]))
        if (meta["vp"], meta["n_cols"]) != (vp, n_cols):
            return None
        skels = [
            LevelSkel(**{
                **d,
                "n_inputs": tuple(d["n_inputs"]),
                "scan_groups": tuple(
                    (int(s), int(c)) for s, c in d.get("scan_groups", ())
                ),
                "order": tuple(int(i) for i in d.get("order", ())),
            })
            for d in meta["skels"]
        ]
        streams = {k: z[k] for k in z.files if k != "__meta"}
        return MultiPackPlan(
            vp=vp, n_cols=n_cols, cfg=cfg, fnum=meta["fnum"],
            skels=skels, host_streams=streams,
            ledger=meta.get("ledger"),
        )
    except Exception:
        return None  # corrupt/stale cache entries are rebuilt
