"""Self-calibrating cost ledger (r17, ROADMAP item 3).

Every auto-selector in the stack prices its decision from a cost
model — the 1-D/2-D partition ledger (fragment/partition.py), the
``GRAPE_LCC_BACKEND=auto`` intersect-vs-spgemm choice
(ops/spgemm_pack.py), the pipeline engage model
(parallel/pipeline.overlap_model), autopilot admission
(autopilot/admission.py), the fleet HBM budget (fleet/budget.py) and
the analytic SpMV model (scripts/pack_cost_model.py).  Until r17 each
carried its own private copy of the hand-pinned v5e rates; this
module makes ONE :class:`RateProfile` the single source of pricing
constants, and adds the machinery to *fit* those rates from measured
device walls instead of faith (the SparseP discipline: measured-rate-
driven selection, applied to the whole selector family):

* :func:`default_profile` — the ``"v5e-pinned"`` profile, bit-for-bit
  the constants every consumer shipped with through r16.  With no
  profile configured nothing changes: every decision and every
  byte-identity pin is unchanged by construction.
* :func:`active_profile` — the profile consumers price from:
  ``GRAPE_RATE_PROFILE=<path>`` loads a schema-validated JSON profile
  (a bad file is a LOUD error, never a silent fallback to pinned).
* :func:`fit_rates` — weighted least squares over measured samples:
  the ledger recount columns (``vpu_ops`` / ``mxu_ops`` /
  ``gather_rows`` / ``hbm_bytes``) are the regressors, the
  sync-before-close wall is the response.  The recount discipline
  means the design matrix is *exact* — the fit's only noise is the
  wall measurement.  Ill-conditioned sample sets FAIL loudly
  (:class:`CalibrationError`); the fitter never silently
  extrapolates a rate the samples cannot identify.
* :func:`microbench_samples` — the seeded sweep: real jitted pack
  SpMV (both scan modes) and masked-SpGEMM dispatches across a small
  geometry grid, walls taken sync-before-close
  (``block_until_ready``), regressors read from each plan's shipped
  op-budget ledger.
* :func:`harvest_dispatch` / :func:`harvested_samples` — live
  harvest: the telemetry plane's per-dispatch ``device_us`` stage
  stamp (serve/session.py) joined to the dispatching worker's
  already-shipped pack-ledger recount.  Armed via
  ``GRAPE_CALIBRATE_HARVEST=1``; disarmed it is one cached env read.
* :func:`drift_report` — modeled-vs-measured drift per priced
  surface under a profile; the bench ``calibration`` lane and
  ``calibrate --check`` exit 2 past :data:`DRIFT_TOLERANCE`,
  turning "the model is stale" from silent mispricing of every
  auto-selector into a failed gate.

The calibration wall model is the ADDITIVE form

    wall = dispatch_overhead + vpu/(lanes*clock) + mxu*cyc/clock
         + gather/(rows_per_cycle*clock) + hbm_bytes/hbm_bps

— conservative (no compute/HBM overlap assumed), linear in the
regressors, and therefore exactly fittable.  The analytic
MTEPS bracket in scripts/pack_cost_model.py keeps its
``max(compute, hbm)`` form for reporting; both read their rates from
the same profile.  docs/CALIBRATION.md is the user guide.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

PROFILE_ENV = "GRAPE_RATE_PROFILE"
HARVEST_ENV = "GRAPE_CALIBRATE_HARVEST"
PROFILE_SCHEMA_VERSION = 1

#: modeled-vs-measured drift past this fraction fails the gate
#: (the same 5% the pack op-budget ledger recount gates at)
DRIFT_TOLERANCE = 0.05

#: column-normalized design matrices worse than this are refused —
#: the samples cannot separate the requested rates
COND_LIMIT = 1e6

#: the regressor columns a sample may carry, in fit order
REGRESSORS = ("const", "vpu_ops", "mxu_ops", "gather_rows",
              "hbm_bytes")


class CalibrationError(RuntimeError):
    """A sample set that cannot honestly identify the requested rates
    (rank-deficient, ill-conditioned, or yielding a non-positive
    rate) or a profile file that fails schema validation."""


@dataclass(frozen=True)
class RateProfile:
    """THE pricing constants — one source, every consumer.

    The default instance IS the hand-pinned v5e model every module
    shipped with through r16; a fitted instance carries the backend
    fingerprint it was measured on plus fit provenance.  `unfitted`
    names rate fields a fit inherited from its base profile instead
    of identifying from samples (recorded, never silent)."""

    name: str = "v5e-pinned"
    clock_hz: float = 940e6            # v5e core clock
    vpu_lanes_per_cycle: float = 1024.0  # one (8,128) vreg op/cycle
    mxu_cyc_per_elem: float = 0.008    # verified tri-matmul cumsum rate
    hbm_bps: float = 819e9             # v5e HBM bandwidth
    ici_bps: float = 9e10              # ~2x45 GB/s v5e ICI links
    gather_rows_per_cycle: float = 128.0  # sublane gather, "row" point
    #: the probe's gather-rate bracket (slots/cycle): vreg = a full
    #: (8,128) vector per cycle, row = one 128-lane row per cycle,
    #: unroll = Mosaic ~8-way select fallback
    gather_rates: Dict[str, float] = field(default_factory=lambda: {
        "vreg": 1024.0, "row": 128.0, "unroll": 16.0,
    })
    #: per-exchange-mode byte rates (all ICI on the pinned profile;
    #: a fitted profile may separate them)
    exchange_bps: Dict[str, float] = field(default_factory=lambda: {
        "gather": 9e10, "mirror": 9e10, "vc2d": 9e10,
    })
    hbm_capacity_bytes: int = 16 << 30  # one v5e chip
    dispatch_overhead_s: float = 0.0   # per-dispatch fixed cost (fit)
    fingerprint: str = "pinned"        # backend it was fitted on
    fitted: bool = False
    source: str = "pinned"             # pinned | microbench | harvest
    residual: float = 0.0              # fit RMS relative error
    unfitted: Tuple[str, ...] = ()

    # ---- pricing ---------------------------------------------------------

    def wall_s(self, sample: dict) -> float:
        """The additive calibration wall model for one sample of
        ledger-recount columns (absent columns price as zero)."""
        clk = self.clock_hz
        return (
            self.dispatch_overhead_s * float(sample.get("const", 1))
            + float(sample.get("vpu_ops", 0))
            / self.vpu_lanes_per_cycle / clk
            + float(sample.get("mxu_ops", 0))
            * self.mxu_cyc_per_elem / clk
            + float(sample.get("gather_rows", 0))
            / self.gather_rows_per_cycle / clk
            + float(sample.get("hbm_bytes", 0)) / self.hbm_bps
        )

    def label(self) -> str:
        """The fingerprint label decision records carry — a decision
        made under a stale profile is attributable in
        PARTITION_STATS / PIPELINE_STATS / SPGEMM_STATS / autopilot
        records."""
        return f"{self.name}@{self.fingerprint}"

    # ---- (de)serialization ----------------------------------------------

    def as_dict(self) -> dict:
        return {
            "schema": PROFILE_SCHEMA_VERSION,
            "name": self.name,
            "clock_hz": self.clock_hz,
            "vpu_lanes_per_cycle": self.vpu_lanes_per_cycle,
            "mxu_cyc_per_elem": self.mxu_cyc_per_elem,
            "hbm_bps": self.hbm_bps,
            "ici_bps": self.ici_bps,
            "gather_rows_per_cycle": self.gather_rows_per_cycle,
            "gather_rates": dict(self.gather_rates),
            "exchange_bps": dict(self.exchange_bps),
            "hbm_capacity_bytes": int(self.hbm_capacity_bytes),
            "dispatch_overhead_s": self.dispatch_overhead_s,
            "fingerprint": self.fingerprint,
            "fitted": self.fitted,
            "source": self.source,
            "residual": self.residual,
            "unfitted": list(self.unfitted),
        }

    @staticmethod
    def from_dict(d: dict) -> "RateProfile":
        errors = validate_profile(d)
        if errors:
            raise CalibrationError(
                "invalid rate profile: " + "; ".join(errors)
            )
        return RateProfile(
            name=d["name"],
            clock_hz=float(d["clock_hz"]),
            vpu_lanes_per_cycle=float(d["vpu_lanes_per_cycle"]),
            mxu_cyc_per_elem=float(d["mxu_cyc_per_elem"]),
            hbm_bps=float(d["hbm_bps"]),
            ici_bps=float(d["ici_bps"]),
            gather_rows_per_cycle=float(d["gather_rows_per_cycle"]),
            gather_rates={k: float(v)
                          for k, v in d["gather_rates"].items()},
            exchange_bps={k: float(v)
                          for k, v in d["exchange_bps"].items()},
            hbm_capacity_bytes=int(d["hbm_capacity_bytes"]),
            dispatch_overhead_s=float(d["dispatch_overhead_s"]),
            fingerprint=d["fingerprint"],
            fitted=bool(d["fitted"]),
            source=d["source"],
            residual=float(d["residual"]),
            unfitted=tuple(d.get("unfitted", [])),
        )


#: profile schema: field -> (type tuple, positivity required).  bool
#: is an int subclass and is REJECTED in every numeric field (the
#: check_bench_schema discipline).
_NUM = (int, float)
_PROFILE_FIELDS = {
    "schema": (int, False),
    "name": (str, False),
    "clock_hz": (_NUM, True),
    "vpu_lanes_per_cycle": (_NUM, True),
    "mxu_cyc_per_elem": (_NUM, True),
    "hbm_bps": (_NUM, True),
    "ici_bps": (_NUM, True),
    "gather_rows_per_cycle": (_NUM, True),
    "gather_rates": (dict, False),
    "exchange_bps": (dict, False),
    "hbm_capacity_bytes": (_NUM, True),
    "dispatch_overhead_s": (_NUM, False),  # zero is legal
    "fingerprint": (str, False),
    "fitted": (bool, False),
    "source": (str, False),
    "residual": (_NUM, False),
    "unfitted": (list, False),
}
_EXCHANGE_MODES = ("gather", "mirror", "vc2d")


def validate_profile(d) -> List[str]:
    """Schema errors for one profile dict (empty = valid): required
    fields, numeric types with bool rejected, positive rates, the
    exchange-mode keys, unknown keys are errors."""
    errors: List[str] = []
    if not isinstance(d, dict):
        return [f"profile must be a dict, got {type(d).__name__}"]
    for key, (typ, positive) in _PROFILE_FIELDS.items():
        if key not in d:
            errors.append(f"missing field {key!r}")
            continue
        v = d[key]
        if typ is not bool and isinstance(v, bool):
            errors.append(f"{key}: bool is not a number")
            continue
        if not isinstance(v, typ):
            errors.append(
                f"{key}: expected {getattr(typ, '__name__', typ)}, "
                f"got {type(v).__name__}"
            )
            continue
        if positive and not (isinstance(v, _NUM) and v > 0
                             and np.isfinite(v)):
            errors.append(f"{key}: must be a positive finite number")
    for key in d:
        if key not in _PROFILE_FIELDS:
            errors.append(f"unknown field {key!r}")
    if isinstance(d.get("schema"), int) and not isinstance(
            d.get("schema"), bool) and d["schema"] != \
            PROFILE_SCHEMA_VERSION:
        errors.append(
            f"schema {d['schema']} != {PROFILE_SCHEMA_VERSION}"
        )
    for dk in ("gather_rates", "exchange_bps"):
        sub = d.get(dk)
        if not isinstance(sub, dict):
            continue
        for k, v in sub.items():
            if isinstance(v, bool) or not isinstance(v, _NUM) \
                    or not (v > 0 and np.isfinite(v)):
                errors.append(
                    f"{dk}[{k!r}]: must be a positive finite number"
                )
        if dk == "exchange_bps":
            for mode in _EXCHANGE_MODES:
                if mode not in sub:
                    errors.append(f"exchange_bps missing mode {mode!r}")
    uf = d.get("unfitted")
    if isinstance(uf, list):
        for x in uf:
            if not isinstance(x, str):
                errors.append("unfitted entries must be strings")
                break
    return errors


_DEFAULT = RateProfile()


def default_profile() -> RateProfile:
    """The ``"v5e-pinned"`` profile — bit-for-bit the constants every
    pricing consumer shipped with through r16."""
    return _DEFAULT


def backend_fingerprint() -> str:
    """``platform:device_kind`` of device 0 — the key a persisted
    profile is valid for.  Falls back to ``unknown:unknown`` when no
    backend is reachable (a profile fitted there says so)."""
    try:
        import jax

        dev = jax.devices()[0]
        return f"{dev.platform}:{getattr(dev, 'device_kind', '?')}"
    except Exception:
        return "unknown:unknown"


def save_profile(profile: RateProfile, path: str) -> str:
    """Write one schema-validated profile JSON (atomic replace)."""
    d = profile.as_dict()
    errors = validate_profile(d)
    if errors:
        raise CalibrationError(
            "refusing to save an invalid profile: " + "; ".join(errors)
        )
    dirpath = os.path.dirname(os.path.abspath(path))
    os.makedirs(dirpath, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(d, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_profile(path: str) -> RateProfile:
    """Load + schema-validate one profile JSON.  Errors are LOUD
    (CalibrationError) — a configured-but-broken profile must never
    silently downgrade every auto-selector to the pinned rates."""
    try:
        with open(path) as f:
            d = json.load(f)
    except OSError as e:
        raise CalibrationError(
            f"cannot read rate profile {path!r}: {e}"
        ) from e
    except json.JSONDecodeError as e:
        raise CalibrationError(
            f"rate profile {path!r} is not valid JSON: {e}"
        ) from e
    return RateProfile.from_dict(d)


_ACTIVE_CACHE: Dict[Tuple[str, float], RateProfile] = {}


def active_profile() -> RateProfile:
    """The profile every consumer prices from: the file named by
    ``GRAPE_RATE_PROFILE`` (mtime-memoized), else the pinned default.
    Read LIVE at every call — arming/swapping a profile mid-process
    (tests, the serve loop) must take effect on the next decision."""
    path = os.environ.get(PROFILE_ENV, "")
    if not path:
        return _DEFAULT
    try:
        key = (os.path.abspath(path), os.path.getmtime(path))
    except OSError as e:
        raise CalibrationError(
            f"GRAPE_RATE_PROFILE={path!r} is not readable: {e}"
        ) from e
    prof = _ACTIVE_CACHE.get(key)
    if prof is None:
        prof = load_profile(path)
        _ACTIVE_CACHE.clear()  # one live file; old mtimes are dead
        _ACTIVE_CACHE[key] = prof
    return prof


def profile_label(profile: Optional[RateProfile] = None) -> str:
    """Label of `profile` (default: the active one) for decision
    records."""
    return (profile or active_profile()).label()


# ---- fitting -------------------------------------------------------------

#: coefficient of regressor r, under profile p
_COEFF_OF = {
    "const": lambda p: p.dispatch_overhead_s,
    "vpu_ops": lambda p: 1.0 / (p.vpu_lanes_per_cycle * p.clock_hz),
    "mxu_ops": lambda p: p.mxu_cyc_per_elem / p.clock_hz,
    "gather_rows": lambda p: 1.0 / (p.gather_rows_per_cycle
                                    * p.clock_hz),
    "hbm_bytes": lambda p: 1.0 / p.hbm_bps,
}


def _profile_with_coeff(profile: RateProfile, reg: str,
                        coeff: float) -> RateProfile:
    clk = profile.clock_hz
    if reg == "const":
        return replace(profile, dispatch_overhead_s=coeff)
    if reg == "vpu_ops":
        return replace(profile, vpu_lanes_per_cycle=1.0 / (coeff * clk))
    if reg == "mxu_ops":
        return replace(profile, mxu_cyc_per_elem=coeff * clk)
    if reg == "gather_rows":
        rate = 1.0 / (coeff * clk)
        return replace(profile, gather_rows_per_cycle=rate,
                       gather_rates={**profile.gather_rates,
                                     "row": rate})
    if reg == "hbm_bytes":
        return replace(profile, hbm_bps=1.0 / coeff)
    raise ValueError(f"unknown regressor {reg!r}")


@dataclass(frozen=True)
class FitResult:
    profile: RateProfile
    regressors: Tuple[str, ...]
    coefficients: Dict[str, float]
    residual: float          # RMS relative error over the samples
    cond: float              # condition of the normalized design
    samples: int


def fit_rates(samples: Sequence[dict],
              regressors: Sequence[str] = ("const", "vpu_ops",
                                           "mxu_ops", "hbm_bytes"),
              base: Optional[RateProfile] = None,
              name: str = "fitted",
              source: str = "microbench") -> FitResult:
    """Weighted least squares of measured walls over ledger columns.

    Each sample: ``{"wall_s": measured, "surface": str, <columns>}``.
    Rows are weighted by ``1/wall`` so the fit minimizes RELATIVE
    error (an absolute fit lets the largest dispatch dominate and the
    small ones drift past the gate).  Columns NOT in `regressors`
    (and requested columns with no variation in the samples) are
    priced at the `base` profile's rates and subtracted from the
    response first — those rates are inherited and RECORDED in
    ``profile.unfitted``, never silently invented.

    Raises :class:`CalibrationError` when the sample set cannot
    identify the requested rates: fewer samples than live columns,
    rank deficiency / condition past :data:`COND_LIMIT`, or a fitted
    rate that comes out non-positive (collinear columns pushing mass
    onto each other).  The fitter must fail loudly, never silently
    extrapolate."""
    base = base or default_profile()
    for r in regressors:
        if r not in REGRESSORS:
            raise ValueError(f"unknown regressor {r!r}")
    samples = list(samples)
    if not samples:
        raise CalibrationError("no samples to fit")
    y = np.array([float(s["wall_s"]) for s in samples])
    if not np.all(np.isfinite(y)) or np.any(y <= 0):
        raise CalibrationError(
            "measured walls must be positive finite seconds"
        )

    def col(reg: str) -> np.ndarray:
        if reg == "const":
            return np.ones(len(samples))
        return np.array([float(s.get(reg, 0)) for s in samples])

    live = [r for r in regressors if np.any(col(r) != 0)]
    dead = [r for r in regressors if r not in live]
    inherited = [r for r in REGRESSORS
                 if r not in live and np.any(col(r) != 0)]
    if not live:
        raise CalibrationError("every requested column is zero")
    if len(samples) < len(live):
        raise CalibrationError(
            f"{len(samples)} samples cannot identify {len(live)} "
            f"rates ({', '.join(live)}) — extend the sweep"
        )
    # response minus the base-priced contribution of inherited columns
    y_adj = y.copy()
    for r in inherited:
        y_adj -= col(r) * _COEFF_OF[r](base)
    if np.any(y_adj <= 0):
        raise CalibrationError(
            "inherited-rate contributions exceed the measured walls "
            f"(inherited: {', '.join(inherited)}) — the base profile "
            "overprices these samples; fit those columns too"
        )
    A = np.stack([col(r) for r in live], axis=1)
    w = 1.0 / y  # relative-error weighting
    Aw = A * w[:, None]
    yw = y_adj * w
    norms = np.linalg.norm(Aw, axis=0)
    if np.any(norms == 0):
        raise CalibrationError("degenerate design column")
    cond = float(np.linalg.cond(Aw / norms))
    if not np.isfinite(cond) or cond > COND_LIMIT:
        raise CalibrationError(
            f"design matrix condition {cond:.3g} past {COND_LIMIT:g} "
            f"— the samples cannot separate ({', '.join(live)}); "
            "vary the geometry mix (scan modes, spgemm, sizes)"
        )
    coef_n, _, rank, _ = np.linalg.lstsq(Aw / norms, yw, rcond=None)
    if rank < len(live):
        raise CalibrationError(
            f"rank-deficient design ({rank} < {len(live)})"
        )
    coef = coef_n / norms
    for r, c in zip(live, coef):
        if r != "const" and c <= 0:
            raise CalibrationError(
                f"fitted coefficient for {r} is non-positive "
                f"({c:.3g}) — collinear samples; extend the sweep or "
                f"drop {r} from the regressors"
            )
    if "const" in live and coef[live.index("const")] <= 0:
        # a (slightly) negative intercept is measurement noise, but a
        # negative overhead must never ship in a profile — and just
        # clamping it to zero leaves the OTHER coefficients fit
        # against an intercept that no longer exists (every modeled
        # wall then overshoots by the absorbed mass), so refit the
        # model without the const column instead
        return fit_rates(
            samples,
            regressors=[r for r in regressors if r != "const"],
            base=base, name=name, source=source,
        )
    profile = base
    coeffs = {}
    for r, c in zip(live, coef):
        coeffs[r] = float(c)
        profile = _profile_with_coeff(profile, r, float(c))
    modeled = np.array([profile.wall_s(s) for s in samples])
    residual = float(np.sqrt(np.mean(((modeled - y) / y) ** 2)))
    profile = replace(
        profile, name=name, source=source, fitted=True,
        fingerprint=backend_fingerprint(), residual=residual,
        unfitted=tuple(sorted(
            r for r in set(inherited) | set(dead) if r != "const")),
    )
    return FitResult(
        profile=profile, regressors=tuple(live),
        coefficients=coeffs, residual=residual, cond=cond,
        samples=len(samples),
    )


#: the driver's regressor fallback chain: richest model first, each
#: step drops the column CPU walls most often cannot identify (HBM —
#: cached; gather — collinear with vpu on the padded plans; MXU — a
#: fixed fraction of vpu on the spgemm surface).  Dropped columns are
#: inherited + recorded, never silent.
REGRESSOR_FALLBACK: Tuple[Tuple[str, ...], ...] = (
    ("const", "vpu_ops", "mxu_ops", "gather_rows", "hbm_bytes"),
    ("const", "vpu_ops", "mxu_ops", "hbm_bytes"),
    ("const", "vpu_ops", "mxu_ops"),
    ("const", "vpu_ops"),
)


def fit_rates_auto(samples: Sequence[dict],
                   base: Optional[RateProfile] = None,
                   name: str = "fitted",
                   source: str = "microbench") -> Tuple[FitResult,
                                                        List[str]]:
    """`fit_rates` down the :data:`REGRESSOR_FALLBACK` chain: the
    richest rate set the samples can honestly identify wins.  Returns
    (fit, notes) where notes records every rejected step and why —
    the driver prints them, so a degraded fit is visible.  Raises the
    LAST step's CalibrationError when even (const, vpu) cannot fit."""
    notes: List[str] = []
    last: Optional[CalibrationError] = None
    for regs in REGRESSOR_FALLBACK:
        try:
            fit = fit_rates(samples, regressors=regs, base=base,
                            name=name, source=source)
            return fit, notes
        except CalibrationError as e:
            notes.append(f"{'+'.join(regs)}: {e}")
            last = e
    raise last  # type: ignore[misc]


def default_min_wall_s() -> float:
    """Samples with walls under this are excluded from a fit: on the
    CPU backend a sub-20ms jitted dispatch is scheduler noise, not a
    rate measurement (the padded SpMV plans land there); on real
    accelerators hardware walls are deterministic down to µs, so
    nothing is dropped."""
    try:
        import jax

        if jax.devices()[0].platform == "cpu":
            return 0.020
    except Exception:
        pass
    return 0.0


SAMPLES_SCHEMA_VERSION = 1


def save_samples(samples: Sequence[dict], path: str) -> str:
    """Persist one measured sample set (the sweep the profile was
    fitted from) — `calibrate --check --samples` and the bench
    `calibration` lane evaluate drift against the RECORDED
    measurement instead of re-racing a noisy scheduler in CI."""
    doc = {"schema": SAMPLES_SCHEMA_VERSION,
           "fingerprint": backend_fingerprint(),
           "samples": [dict(s) for s in samples]}
    dirpath = os.path.dirname(os.path.abspath(path))
    os.makedirs(dirpath, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_samples(path: str) -> List[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CalibrationError(
            f"cannot read calibration samples {path!r}: {e}"
        ) from e
    if not isinstance(doc, dict) or not isinstance(
            doc.get("samples"), list):
        raise CalibrationError(
            f"calibration samples {path!r}: expected "
            "{schema, fingerprint, samples: [...]}"
        )
    out = []
    for i, s in enumerate(doc["samples"]):
        if not isinstance(s, dict) or "wall_s" not in s:
            raise CalibrationError(
                f"calibration samples {path!r}: entry {i} has no "
                "wall_s"
            )
        w = s["wall_s"]
        if isinstance(w, bool) or not isinstance(w, _NUM) or w <= 0:
            raise CalibrationError(
                f"calibration samples {path!r}: entry {i} wall_s "
                "must be a positive number"
            )
        out.append(dict(s))
    return out


def drift_report(profile: RateProfile,
                 samples: Sequence[dict]) -> dict:
    """Modeled-vs-measured drift of `profile` over `samples`, per
    priced surface (the ``surface`` tag each sample carries) and
    overall.  Per surface the drift is the AGGREGATE
    ``|sum(modeled) - sum(measured)| / sum(measured)`` — the bias the
    auto-selectors would price with; ``max_sample_drift_pct`` is
    reported for forensics but the gate rides the aggregate."""
    by: Dict[str, Dict[str, float]] = {}
    worst_sample = 0.0
    for s in samples:
        surf = s.get("surface", "unknown")
        m = profile.wall_s(s)
        t = float(s["wall_s"])
        e = by.setdefault(surf, {"modeled_s": 0.0, "measured_s": 0.0,
                                 "samples": 0})
        e["modeled_s"] += m
        e["measured_s"] += t
        e["samples"] += 1
        worst_sample = max(worst_sample, abs(m - t) / t)
    max_drift = 0.0
    for surf, e in by.items():
        drift = (abs(e["modeled_s"] - e["measured_s"])
                 / max(e["measured_s"], 1e-12))
        e["drift_pct"] = round(drift * 100.0, 3)
        max_drift = max(max_drift, drift)
    return {
        "profile": profile.label(),
        "surfaces": by,
        "drift_pct": round(max_drift * 100.0, 3),
        "max_sample_drift_pct": round(worst_sample * 100.0, 3),
        "drift_ok": bool(max_drift <= DRIFT_TOLERANCE),
        "tolerance_pct": DRIFT_TOLERANCE * 100.0,
    }


# ---- seeded micro-bench sweep --------------------------------------------


def _bench_fragment(scale: int, ef: int, seed: int):
    """A tiny fnum=1 edge-cut fragment for one RMAT-ish draw (the
    test-suite construction: CommSpec + MapPartitioner + build)."""
    from libgrape_lite_tpu.fragment.edgecut import (
        ShardedEdgecutFragment,
    )
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.vertex_map.partitioner import MapPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    rng = np.random.default_rng(seed)
    n = 1 << scale
    e = n * ef
    # hub-skewed draw so plans exercise the hub tier + fold levels
    src = np.minimum(
        rng.integers(0, n, e),
        rng.integers(0, n, e),
    ).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    oids = np.arange(n, dtype=np.int64)
    comm = CommSpec(fnum=1)
    vm = VertexMap.build(oids, MapPartitioner(1, oids))
    return ShardedEdgecutFragment.build(
        comm, vm, src, dst, None, directed=False,
    )


def _timed_call(fn, args, repeats: int) -> float:
    """Best-of-`repeats` sync-before-close wall of one jitted call
    (first call compiles and is discarded)."""
    import time

    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def _spmv_sample(scale: int, ef: int, seed: int, scan_mode: str,
                 repeats: int) -> Optional[dict]:
    import jax
    import jax.numpy as jnp

    from libgrape_lite_tpu.ops.spmv_pack import (
        resolve_pack_dispatch,
    )

    prev = os.environ.get("GRAPE_PACK_SCAN")
    os.environ["GRAPE_PACK_SCAN"] = scan_mode
    try:
        frag = _bench_fragment(scale, ef, seed)
        disp = resolve_pack_dispatch(frag)
        if disp is None:
            return None
        led = disp.ledger()
        if not led:
            return None
        fn = jax.jit(lambda x: disp.reduce(x, {}, "sum"))
        x = jnp.asarray(
            np.random.default_rng(seed + 1).normal(
                size=frag.vp
            ).astype(np.float32)
        )
        wall = _timed_call(fn, (x,), repeats)
        t = led["totals"]
        return {
            "surface": "spmv",
            "geometry": f"s{scale}ef{ef}:{scan_mode}",
            "wall_s": wall,
            "vpu_ops": int(t["vpu_ops"]),
            "mxu_ops": int(t["mxu_ops"]),
            "gather_rows": int(t["gather_rows"]),
            "hbm_bytes": int(t["hbm_bytes"]),
        }
    finally:
        if prev is None:
            os.environ.pop("GRAPE_PACK_SCAN", None)
        else:
            os.environ["GRAPE_PACK_SCAN"] = prev


def _spgemm_sample(scale: int, ef: int, seed: int,
                   repeats: int) -> Optional[dict]:
    import jax

    from libgrape_lite_tpu.ops.spgemm_pack import (
        resolve_spgemm_dispatch,
    )

    frag = _bench_fragment(scale, ef, seed)
    try:
        disp = resolve_spgemm_dispatch(frag)
    except Exception:
        return None
    led = disp.ledger()
    if not led or not disp.plan.items:
        return None
    # host_streams entries carry a leading [fnum] shard axis; the
    # credits pass is the traced PER-SHARD program (fnum=1 here)
    entries = {k: np.asarray(v)[0]
               for k, v in disp.state_entries().items()}

    def run(state):
        return disp.credits(state)

    fn = jax.jit(run)
    wall = _timed_call(fn, (entries,), repeats)
    t = led["totals"]
    return {
        "surface": "spgemm",
        "geometry": f"s{scale}ef{ef}",
        "wall_s": wall,
        "vpu_ops": int(t["vpu_ops"]),
        "mxu_ops": int(t["mxu_ops"]),
        "gather_rows": int(t["gather_rows"]),
        "hbm_bytes": int(t["hbm_bytes"]),
    }


def microbench_samples(scales: Sequence[int] = (8, 9, 10),
                       ef: int = 8, seed: int = 7,
                       repeats: int = 3,
                       scan_modes: Sequence[str] = ("shift", "mxu"),
                       spgemm: bool = True) -> List[dict]:
    """The seeded sweep: pack SpMV (per scan mode — shift levels ship
    zero MXU planes, mxu levels a fixed 3/slot, so the two modes
    decorrelate the vpu/mxu columns) and masked-SpGEMM dispatches
    across a small geometry grid.  Exchange dispatches need a >1
    device mesh; on a 1-device backend the exchange rates stay
    inherited (recorded in ``profile.unfitted`` by the fit)."""
    samples: List[dict] = []
    for i, scale in enumerate(scales):
        for mode in scan_modes:
            s = _spmv_sample(scale, ef, seed + 13 * i, mode, repeats)
            if s is not None:
                samples.append(s)
        if spgemm:
            s = _spgemm_sample(scale, ef, seed + 13 * i, repeats)
            if s is not None:
                samples.append(s)
    return samples


# ---- live harvest --------------------------------------------------------

_HARVEST: List[dict] = []
_HARVEST_MAX = 4096


def harvest_armed() -> bool:
    return os.environ.get(HARVEST_ENV, "") in ("1", "true", "on")


def harvest_dispatch(stages: Optional[dict], totals: Optional[dict],
                     rounds: int) -> Optional[dict]:
    """Join one dispatch's telemetry stage stamp (``device_us``) to
    its worker's shipped pack-ledger recount: the ledger totals are
    per ROUND, the device stamp covers the whole fused while_loop, so
    the regressor columns scale by `rounds`.  Returns the sample (and
    appends it to the harvest buffer), or None when the dispatch
    carries no usable stamp/ledger."""
    if not stages or not totals or rounds <= 0:
        return None
    device_us = stages.get("device_us", 0)
    if not device_us or device_us <= 0:
        return None
    sample = {
        "surface": "harvest",
        "wall_s": device_us / 1e6,
        "vpu_ops": int(totals.get("vpu_ops", 0)) * rounds,
        "mxu_ops": int(totals.get("mxu_ops", 0)) * rounds,
        "gather_rows": int(totals.get("gather_rows", 0)) * rounds,
        "hbm_bytes": int(totals.get("hbm_bytes", 0)) * rounds,
    }
    if sample["vpu_ops"] == 0 and sample["hbm_bytes"] == 0:
        return None
    _HARVEST.append(sample)
    if len(_HARVEST) > _HARVEST_MAX:
        del _HARVEST[: _HARVEST_MAX // 2]
    return sample


def harvest_overlap(plan_brief: Optional[dict],
                    measured_round_us: float,
                    rounds: int) -> Optional[dict]:
    """Overlap-truth reconciliation row: the truth meter's measured
    per-round device wall joined against the pipeline brief's edge /
    exchange-byte columns (obs/truth.py is the producer).  The row
    rides the same harvest buffer `fit_rates` consumes — surface
    ``overlap`` — and additionally carries the plan uid and the
    modeled per-round hidden µs so a later fit (or a human) can see
    exactly which modeled claim the wall was reconciled against."""
    if not plan_brief or rounds <= 0:
        return None
    if not measured_round_us or measured_round_us <= 0:
        return None
    edges = (int(plan_brief.get("boundary_edges", 0))
             + int(plan_brief.get("interior_edges", 0)))
    sample = {
        "surface": "overlap",
        "plan_uid": plan_brief.get("plan_uid") or "-",
        "wall_s": measured_round_us * rounds / 1e6,
        "vpu_ops": edges * rounds,
        "mxu_ops": 0,
        "gather_rows": 0,
        "hbm_bytes": int(plan_brief.get("exchange_bytes", 0)) * rounds,
        "modeled_hidden_us_per_round": float(
            plan_brief.get("hidden_us_per_round") or 0.0),
    }
    if sample["vpu_ops"] == 0 and sample["hbm_bytes"] == 0:
        return None
    _HARVEST.append(sample)
    if len(_HARVEST) > _HARVEST_MAX:
        del _HARVEST[: _HARVEST_MAX // 2]
    return sample


def harvest_from_worker(worker, stages: Optional[dict],
                        rounds: int) -> Optional[dict]:
    """The serve-session hook: pull the dispatching worker's merged
    pack-ledger totals and harvest the stamp (no-op when the worker
    has no pack ledger — XLA-path apps ship no recount columns)."""
    try:
        led = worker.pack_ledger()
    except Exception:
        return None
    totals = (led or {}).get("totals")
    if not totals:
        return None
    return harvest_dispatch(stages, totals, rounds)


def harvested_samples() -> List[dict]:
    return list(_HARVEST)


def reset_harvest() -> None:
    del _HARVEST[:]


# federated as "calibration" (obs/federation.py): harvest depth +
# the active profile label, visible to the live exporter
from libgrape_lite_tpu.obs import federation as _federation  # noqa: E402


def _calibration_snapshot() -> dict:
    return {
        "harvested": len(_HARVEST),
        "armed": harvest_armed(),
        "profile": os.environ.get(PROFILE_ENV, "") or "v5e-pinned",
    }


_federation.register("calibration", _calibration_snapshot,
                     reset_harvest, module=__name__)
