from libgrape_lite_tpu.ops.segment import segment_reduce
