"""Static 3-stage shuffle: arbitrary within-block permutations on TPU.

TPU vector units move data fast only along two axes: within a sublane
row (lane gather, `take_along_axis(axis=1)`) and within a lane column
(sublane gather, `take_along_axis(axis=0)`, which Mosaic lowers when
table and index shapes match).  An ARBITRARY static permutation of an
[R, 128] block factors into three such moves — row-perm, column-perm,
row-perm — exactly a rearrangeable 3-stage Clos network
(Slepian-Duguid): route element e (src slot -> dst slot) through a
"middle lane" m(e) such that every source row uses each middle lane
once and every middle lane hits each destination row once.  Such an
assignment always exists: it is an edge coloring of the C-regular
bipartite multigraph (src rows x dst rows) with C = 128 colors, which
Koenig's theorem guarantees.  We compute it with the classic Euler
-split recursion, fully vectorized (orbit labels by pointer doubling
instead of walking cycles).

This is the data-movement backbone of the pack-gather SpMV
(`ops/spmv_pack.py`); the reference's counterpart machinery is the
CUDA load-balancing/shuffle layer (`grape/cuda/parallel/
parallel_engine.h`, `grape/cuda/utils/shuffle.h`) — redesigned here
for a machine whose fast paths are lane/sublane moves, not warp
shuffles.

Host API
--------
  plan_route(src_slot, dst_slot, R_src, R_dst, C=128) -> Route3
     src_slot/dst_slot: int64 flat slot ids (row*C + lane), one entry
     per routed element; unrouted destination slots receive garbage and
     must be masked by the caller.  Requires len <= R_src*C and
     <= R_dst*C; elements per src row and per dst row each <= C.

Composition invariant
---------------------
Routes COMPOSE at plan time for free: applying Route3 `a` then Route3
`b` equals the single route planned from the composed slot mapping
(`compose_routes(a, b)`), because the composed mapping is again a
partial injection on [R, C] blocks and Koenig's theorem guarantees its
3-stage factorization exists for ANY such mapping.  Device cost of the
composed route is 3 moves regardless of how many routes were fused —
this is what lets the pack planner (ops/spmv_pack.py) land extraction
outputs directly in the next fold level's sorted layout, collapsing
the fold-level merge route to a single lane-preserving sublane gather
(`plan_lane_aligned_rows`).  When a route IS lane-preserving
(lane(dst) == lane(src) for every element), ship only the [R_dst, C]
row-index plane and pay 1 move instead of 3.

Kernel API
----------
  apply_route3(x, route_arrays...) inside a Pallas kernel, where the
  three int32 index blocks are fed as ordinary VMEM inputs.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class Route3(NamedTuple):
    """Static routing program for one [R_src,C] -> [R_dst,C] shuffle.

    l1 [R_src, C]: stage-1 lane gather (within src rows): stage1[r, m]
       = x[r, l1[r, m]] — moves each element to its middle lane m.
    s2 [R_mid, C]: stage-2 sublane gather on the stage-1 result padded
       /sliced to R_mid = max(R_src, R_dst) rows: stage2[r, m] =
       stage1[s2[r, m], m] — moves along the middle lane to the
       destination row.
    l3 [R_dst, C]: stage-3 lane gather (within dst rows): out[r, c] =
       stage2[r, l3[r, c]].
    valid [R_dst, C] bool: True where the dst slot received a routed
       element (callers mask the rest).
    """

    l1: np.ndarray
    s2: np.ndarray
    l3: np.ndarray
    valid: np.ndarray

    @property
    def r_mid(self) -> int:
        return self.s2.shape[0]


def _orbit_min_label(nxt: np.ndarray) -> np.ndarray:
    """Min element index over each orbit of the permutation `nxt`,
    by pointer doubling (O(E log E), no Python-level cycle walks)."""
    lab = np.arange(len(nxt), dtype=np.int64)
    jump = nxt.astype(np.int64)
    # after k rounds lab[i] = min over {i, nxt(i), ..., nxt^(2^k-1)(i)}
    steps = max(1, int(np.ceil(np.log2(max(2, len(nxt))))))
    for _ in range(steps):
        lab = np.minimum(lab, lab[jump])
        jump = jump[jump]
    return lab


def _pair_within(groups: np.ndarray) -> np.ndarray:
    """Pair consecutive incidences of each group value (all group
    multiplicities even): returns for each element the index of its
    partner.  Vectorized via stable argsort."""
    order = np.argsort(groups, kind="stable")
    partner_sorted = np.arange(len(groups), dtype=np.int64)
    partner_sorted[0::2] = order[1::2]
    partner_sorted[1::2] = order[0::2]
    partner = np.empty(len(groups), dtype=np.int64)
    partner[order] = partner_sorted
    return partner


def _euler_split(src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    """2-color the edges of a bipartite multigraph in which every
    vertex has EVEN degree, such that each vertex's edges split evenly
    between colors.  Returns bool color per edge.

    Pair edges at each src vertex and at each dst vertex; the pairing
    graph decomposes into even-length cycles alternating src/dst
    pairings.  pi = dst_pair(src_pair(.)) jumps two steps, so each
    cycle splits into two pi-orbits that must take opposite colors;
    src_pair maps an orbit onto its partner, giving a consistent,
    fully vectorized coloring rule: color = [orbit label < partner
    orbit label].
    """
    src_pair = _pair_within(src)
    dst_pair = _pair_within(dst)
    pi = dst_pair[src_pair]
    lab = _orbit_min_label(pi)
    partner_lab = lab[src_pair]
    # labels differ because src_pair always crosses to the other orbit
    return lab < partner_lab


def _edge_color(src: np.ndarray, dst: np.ndarray, C: int) -> np.ndarray:
    """Color edges of a C-regular bipartite multigraph with C colors
    (Koenig), by Euler-split recursion.  C must be a power of two and
    every vertex degree exactly C."""
    colors = np.zeros(len(src), dtype=np.int32)
    stack = [(np.arange(len(src), dtype=np.int64), C, 0)]
    while stack:
        ids, c, base = stack.pop()
        if c == 1:
            colors[ids] = base
            continue
        half = _euler_split(src[ids], dst[ids])
        stack.append((ids[half], c // 2, base))
        stack.append((ids[~half], c // 2, base + c // 2))
    return colors


def plan_route(
    src_slot: np.ndarray,
    dst_slot: np.ndarray,
    r_src: int,
    r_dst: int,
    c: int = 128,
) -> Route3:
    """Compute the 3-stage routing for `out.flat[dst_slot] =
    x.flat[src_slot]` over blocks [r_src, c] -> [r_dst, c].

    Each src slot and each dst slot may appear at most once.  Holes on
    either side are padded internally with dummy elements; dst holes
    are reported in `valid`.
    """
    src_slot = np.asarray(src_slot, dtype=np.int64)
    dst_slot = np.asarray(dst_slot, dtype=np.int64)
    if len(src_slot) != len(dst_slot):
        raise ValueError("src/dst length mismatch")
    r_mid = max(r_src, r_dst)

    src_row = src_slot // c
    dst_row = dst_slot // c

    # pad to exact C-regularity on both sides with dummy elements:
    # dummies pair leftover src-row capacity with leftover dst-row
    # capacity (total capacity r_mid*c on both sides)
    src_cnt = np.bincount(src_row, minlength=r_mid)
    dst_cnt = np.bincount(dst_row, minlength=r_mid)
    if (src_cnt > c).any():
        raise ValueError("a source row holds more than C elements")
    if (dst_cnt > c).any():
        raise ValueError("a destination row holds more than C elements")
    pad_src_row = np.repeat(
        np.arange(r_mid, dtype=np.int64), (c - src_cnt).astype(np.int64)
    )
    pad_dst_row = np.repeat(
        np.arange(r_mid, dtype=np.int64), (c - dst_cnt).astype(np.int64)
    )
    assert len(pad_src_row) == len(pad_dst_row)

    all_src_row = np.concatenate([src_row, pad_src_row])
    all_dst_row = np.concatenate([dst_row, pad_dst_row])
    real = np.zeros(len(all_src_row), dtype=bool)
    real[: len(src_slot)] = True

    m = _edge_color(all_src_row, all_dst_row, c)

    # dummy elements also need concrete src/dst lanes: give each padded
    # row's dummies the lanes its real elements left unused
    def _fill_lanes(rows, slots_real_rows, slots_real_lanes):
        used = np.zeros((r_mid, c), dtype=bool)
        used[slots_real_rows, slots_real_lanes] = True
        free_r, free_l = np.nonzero(~used)
        order = np.argsort(free_r, kind="stable")
        free_r, free_l = free_r[order], free_l[order]
        # rows of dummies arrive sorted too (np.repeat order)
        assert (free_r == rows).all()
        return free_l

    pad_src_lane = _fill_lanes(pad_src_row, src_row, src_slot % c)
    pad_dst_lane = _fill_lanes(pad_dst_row, dst_row, dst_slot % c)
    all_src_lane = np.concatenate([src_slot % c, pad_src_lane])
    all_dst_lane = np.concatenate([dst_slot % c, pad_dst_lane])

    # build the three index arrays
    l1 = np.zeros((r_mid, c), dtype=np.int32)  # [src_row, m] -> src lane
    l1[all_src_row, m] = all_src_lane
    s2 = np.zeros((r_mid, c), dtype=np.int32)  # [dst_row, m] -> src row
    s2[all_dst_row, m] = all_src_row
    l3 = np.zeros((r_mid, c), dtype=np.int32)  # [dst_row, lane] -> m
    l3[all_dst_row, all_dst_lane] = m
    valid = np.zeros((r_mid, c), dtype=bool)
    valid[dst_row, dst_slot % c] = True

    return Route3(l1=l1, s2=s2, l3=l3[:r_dst], valid=valid[:r_dst])


def route_slot_map(rt: Route3, c: int = 128):
    """Recover the (src_slot, dst_slot) partial injection a Route3
    realizes: route an iota of flat slot ids and read the valid dst
    slots.  Entries sourced from internal pad rows never appear (pads
    only ever feed invalid dst slots)."""
    r_mid = rt.s2.shape[0]
    iota = np.arange(r_mid * c, dtype=np.int64).reshape(r_mid, c)
    routed = apply_route3_np(iota, rt)
    dst_slot = np.nonzero(rt.valid.reshape(-1))[0]
    src_slot = routed.reshape(-1)[dst_slot]
    return src_slot, dst_slot


def compose_routes(a: Route3, b: Route3, c: int = 128) -> Route3:
    """The single Route3 equal to applying `a` then `b`.

    Composition restricts to dst slots of `b` whose source was a VALID
    dst of `a` (b may route a-holes; those carry garbage under
    sequential application and are dropped — callers were required to
    mask them anyway).  r_src is a's middle height (>= its true source
    height; apply_route3* zero-pads shorter inputs), r_dst is b's."""
    a_src, a_dst = route_slot_map(a, c)
    b_src, b_dst = route_slot_map(b, c)
    # a_dst -> a_src lookup over b's source slots
    lut = np.full(a.valid.shape[0] * c, -1, dtype=np.int64)
    lut[a_dst] = a_src
    b_src_ok = b_src < len(lut)
    comp_src = np.where(b_src_ok, lut[np.minimum(b_src, len(lut) - 1)],
                        -1)
    keep = comp_src >= 0
    return plan_route(
        comp_src[keep], b_dst[keep], a.s2.shape[0], b.l3.shape[0], c
    )


def plan_lane_aligned_rows(src_slot: np.ndarray, dst_slot: np.ndarray,
                           r_dst: int, c: int = 128) -> np.ndarray:
    """The 1-move form of a LANE-PRESERVING mapping: a [r_dst, c] row
    index plane for `take_along_axis(x, rows, axis=0)` (a sublane
    gather — fan-out allowed, unlike a full Route3).  Requires
    lane(src) == lane(dst) for every element; unrouted dst slots read
    row 0 (callers mask via their flag/valid planes)."""
    src_slot = np.asarray(src_slot, dtype=np.int64)
    dst_slot = np.asarray(dst_slot, dtype=np.int64)
    if ((src_slot % c) != (dst_slot % c)).any():
        raise ValueError("mapping is not lane-preserving")
    rows = np.zeros((r_dst, c), dtype=np.int32)
    rows[dst_slot // c, dst_slot % c] = src_slot // c
    return rows


def apply_route3_np(x: np.ndarray, rt: Route3) -> np.ndarray:
    """Numpy reference of the kernel-side application (for tests)."""
    r_src, c = x.shape
    xm = x
    if rt.s2.shape[0] > r_src:
        xm = np.concatenate(
            [x, np.zeros((rt.s2.shape[0] - r_src, c), x.dtype)]
        )
    s1 = np.take_along_axis(xm, rt.l1, axis=1)
    s2 = np.take_along_axis(s1, rt.s2, axis=0)
    s3 = np.take_along_axis(s2[: rt.l3.shape[0]], rt.l3, axis=1)
    return s3


def apply_route3(x, l1, s2, l3):
    """Kernel-side application with jnp ops (usable in Pallas TPU
    kernels and in interpret mode).  `x` [r_src, c] is zero-padded to
    the middle height; index arrays are the Route3 fields (narrow int
    blocks, upcast to int32 here — they ship int8/int16 to halve VMEM).
    Returns [r_dst, c] — mask with Route3.valid."""
    import jax.numpy as jnp

    r_mid, c = s2.shape
    r_src = x.shape[0]
    if r_mid > r_src:
        x = jnp.concatenate(
            [x, jnp.zeros((r_mid - r_src, c), x.dtype)], axis=0
        )
    s1 = jnp.take_along_axis(x, l1.astype(jnp.int32), axis=1)
    s2v = jnp.take_along_axis(s1, s2.astype(jnp.int32), axis=0)
    r_dst = l3.shape[0]
    return jnp.take_along_axis(s2v[:r_dst], l3.astype(jnp.int32), axis=1)
