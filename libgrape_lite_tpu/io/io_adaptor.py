"""File IO with byte-range partial reads.

Re-design of `grape/io/local_io_adaptor.{h,cc}` (332 LoC): the reference
splits a file into per-worker byte ranges (`SetPartialRead(worker_id,
worker_num)`, `local_io_adaptor.h:49`) and each MPI rank parses its slice.
The TPU build loads on the host; partial reads are still useful for
multi-host slices and for bounding peak memory, so the same API is kept.
Ranges are aligned to line boundaries by scanning forward to the next
newline, exactly like the reference.
"""

from __future__ import annotations

import os


class LocalIOAdaptor:
    def __init__(self, location: str):
        self.location = location
        self._f = None
        self._start = 0
        self._end = None

    def open(self):
        self._f = open(self.location, "rb")
        if self._end is None:
            self._end = os.path.getsize(self.location)
        return self

    def set_partial_read(self, index: int, total_parts: int) -> None:
        """Restrict subsequent reads to part `index` of `total_parts`,
        aligned to line boundaries (reference `local_io_adaptor.cc`
        SetPartialRead/seek logic)."""
        size = os.path.getsize(self.location)
        chunk = size // total_parts
        start = chunk * index
        end = size if index == total_parts - 1 else chunk * (index + 1)
        if self._f is None:
            self.open()
        f = self._f
        # advance start to the next newline (unless at file start)
        if start > 0:
            f.seek(start - 1)
            f.readline()
            start = f.tell()
        # advance end to include the line spanning the boundary
        if end < size:
            f.seek(end - 1)
            f.readline()
            end = f.tell()
        self._start, self._end = start, end

    def read_bytes(self) -> bytes:
        if self._f is None:
            self.open()
        self._f.seek(self._start)
        return self._f.read(self._end - self._start)

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self.open()

    def __exit__(self, *exc):
        self.close()
        return False
