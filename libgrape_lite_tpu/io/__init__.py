from libgrape_lite_tpu.io.io_adaptor import LocalIOAdaptor
from libgrape_lite_tpu.io.line_parser import TSVLineParser, read_edge_file, read_vertex_file
