"""ctypes binding to the native C++ loader (native/loader.cc).

The shared library is built lazily with `make -C native` on first use;
all callers fall back to the Python/pandas parser when the toolchain or
build is unavailable (`read_edge_file` handles the dispatch).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libgrape_tpu_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("GRAPE_TPU_NO_NATIVE"):
            return None
        src = os.path.join(_NATIVE_DIR, "loader.cc")
        stale = not os.path.exists(_SO_PATH) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_SO_PATH)
        )
        if stale:
            # a stale .so silently loses every symbol group added since
            # it was built (make is incremental, so this is cheap)
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception:
                if not os.path.exists(_SO_PATH):
                    return None  # no prebuilt fallback at all
                import warnings

                warnings.warn(
                    "native library rebuild failed; loading the stale "
                    f"{_SO_PATH} — newer symbol groups (and their "
                    "speedups) may be unavailable",
                    RuntimeWarning,
                )
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.gl_parse.restype = ctypes.c_void_p
        lib.gl_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.gl_num_rows.restype = ctypes.c_int64
        lib.gl_num_rows.argtypes = [ctypes.c_void_p]
        for name in ("gl_col0", "gl_col1"):
            fn = getattr(lib, name)
            fn.restype = ctypes.POINTER(ctypes.c_int64)
            fn.argtypes = [ctypes.c_void_p]
        lib.gl_colw.restype = ctypes.POINTER(ctypes.c_double)
        lib.gl_colw.argtypes = [ctypes.c_void_p]
        lib.gl_all_weighted.restype = ctypes.c_int
        lib.gl_all_weighted.argtypes = [ctypes.c_void_p]
        lib.gl_free.restype = None
        lib.gl_free.argtypes = [ctypes.c_void_p]
        try:
            # a stale prebuilt .so may predate gl_sort_edges: degrade to
            # parser-only rather than crashing every native call
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.gl_sort_edges.restype = None
            lib.gl_sort_edges.argtypes = [
                i64p, i64p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                i64p, i64p, ctypes.c_void_p, i64p,
            ]
            lib._gl_has_sort = True
        except AttributeError:
            lib._gl_has_sort = False
        try:
            # vertex-map acceleration (id table + MPH), added round 2
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.gl_ht_build.restype = ctypes.c_void_p
            lib.gl_ht_build.argtypes = [i64p, ctypes.c_int64]
            lib.gl_ht_insert.restype = None
            lib.gl_ht_insert.argtypes = [
                ctypes.c_void_p, i64p, ctypes.c_int64, ctypes.c_void_p,
            ]
            lib.gl_ht_lookup.restype = None
            lib.gl_ht_lookup.argtypes = [
                ctypes.c_void_p, i64p, ctypes.c_int64, i64p,
            ]
            lib.gl_ht_size.restype = ctypes.c_int64
            lib.gl_ht_size.argtypes = [ctypes.c_void_p]
            lib.gl_ht_oids.restype = None
            lib.gl_ht_oids.argtypes = [ctypes.c_void_p, i64p]
            lib.gl_ht_free.restype = None
            lib.gl_ht_free.argtypes = [ctypes.c_void_p]
            lib.gl_mph_build.restype = ctypes.c_void_p
            lib.gl_mph_build.argtypes = [i64p, ctypes.c_int64]
            lib.gl_mph_pos.restype = None
            lib.gl_mph_pos.argtypes = [
                ctypes.c_void_p, i64p, ctypes.c_int64, i64p,
            ]
            lib.gl_mph_bits.restype = ctypes.c_double
            lib.gl_mph_bits.argtypes = [ctypes.c_void_p]
            lib.gl_mph_free.restype = None
            lib.gl_mph_free.argtypes = [ctypes.c_void_p]
            lib._gl_has_vm = True
        except AttributeError:
            lib._gl_has_vm = False
        try:
            # varint decode (fragment-cache wire format), added round 4
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
            u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
            lib.gl_varint_count.restype = ctypes.c_int64
            lib.gl_varint_count.argtypes = [u8p, ctypes.c_int64]
            lib.gl_varint_decode.restype = ctypes.c_int64
            lib.gl_varint_decode.argtypes = [
                u8p, ctypes.c_int64, u64p, ctypes.c_int64, ctypes.c_int,
            ]
            lib.gl_varint_size.restype = ctypes.c_int64
            lib.gl_varint_size.argtypes = [
                u64p, ctypes.c_int64, ctypes.c_int,
            ]
            lib.gl_varint_encode.restype = ctypes.c_int64
            lib.gl_varint_encode.argtypes = [
                u64p, ctypes.c_int64, u8p, ctypes.c_int64, ctypes.c_int,
            ]
            lib._gl_has_varint = True
        except AttributeError:
            lib._gl_has_varint = False
        try:
            # float byte-plane transpose (garc weight streams), round 5
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
            lib.gl_byte_split.restype = None
            lib.gl_byte_split.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int, u8p,
            ]
            lib.gl_byte_join.restype = None
            lib.gl_byte_join.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int, u8p,
            ]
            lib._gl_has_bytesplit = True
        except AttributeError:
            lib._gl_has_bytesplit = False
        _lib = lib
        return _lib


def byte_split(a: np.ndarray) -> np.ndarray:
    """[n] itemsize-wide array -> [itemsize, n] uint8 planes (native
    transpose when available; numpy reshape fallback)."""
    n, itemsize = len(a), a.dtype.itemsize
    flat = np.ascontiguousarray(a).view(np.uint8).reshape(-1)
    lib = _load()
    if lib is not None and getattr(lib, "_gl_has_bytesplit", False) and n:
        out = np.empty(itemsize * n, dtype=np.uint8)
        lib.gl_byte_split(flat, n, itemsize, out)
        return out.reshape(itemsize, n)
    return flat.reshape(n, itemsize).T.copy()


def byte_join(planes: np.ndarray, dtype) -> np.ndarray:
    """Inverse of byte_split: [itemsize, n] uint8 planes -> [n] dtype."""
    itemsize, n = planes.shape
    assert np.dtype(dtype).itemsize == itemsize
    lib = _load()
    if lib is not None and getattr(lib, "_gl_has_bytesplit", False) and n:
        out = np.empty(itemsize * n, dtype=np.uint8)
        lib.gl_byte_join(np.ascontiguousarray(planes).reshape(-1), n,
                         itemsize, out)
        return out.view(dtype)
    return np.ascontiguousarray(planes.T).reshape(-1).view(dtype)


def varint_encode_native(vals: np.ndarray, delta: bool) -> bytes | None:
    """Native LEB128 (optionally delta) encode; None when unavailable."""
    lib = _load()
    if lib is None or not getattr(lib, "_gl_has_varint", False):
        return None
    v = np.ascontiguousarray(vals, dtype=np.uint64)
    if len(v) == 0:
        return b""
    size = lib.gl_varint_size(v, len(v), 1 if delta else 0)
    out = np.empty(size, dtype=np.uint8)
    got = lib.gl_varint_encode(v, len(v), out, size, 1 if delta else 0)
    if got != size:
        return None
    return out.tobytes()


def varint_decode_native(buf: bytes, delta: bool) -> np.ndarray | None:
    """Native LEB128 (optionally delta-accumulated) decode; None when
    the library is unavailable (callers fall back to numpy)."""
    lib = _load()
    if lib is None or not getattr(lib, "_gl_has_varint", False):
        return None
    b = np.frombuffer(buf, dtype=np.uint8)
    if len(b) == 0:
        return np.zeros(0, dtype=np.uint64)
    n = lib.gl_varint_count(b, len(b))
    out = np.empty(n, dtype=np.uint64)
    got = lib.gl_varint_decode(b, len(b), out, n, 1 if delta else 0)
    if got != n:
        # gl_varint_decode returns -1 or the exact count, so this is
        # unambiguously a truncated/overlong stream — the numpy
        # fallback would silently drop the trailing value instead
        raise ValueError(
            f"corrupt varint stream: decoded {got} of {n} values"
        )
    return out


def _as_i64(a) -> np.ndarray | None:
    """Contiguous int64 view of an integer array; None for non-integer
    oid dtypes (string-keyed graphs keep the Python paths)."""
    arr = np.asarray(a)
    if not np.issubdtype(arr.dtype, np.integer):
        return None
    return np.ascontiguousarray(arr, dtype=np.int64)


class NativeIdTable:
    """Open-addressing oid->lid table (native IdTable; the reference
    `IdIndexer`, grape/graph/id_indexer.h)."""

    def __init__(self, lib, handle):
        self._lib = lib
        self._h = handle

    @classmethod
    def build(cls, oids: np.ndarray) -> "NativeIdTable | None":
        lib = _load()
        if lib is None or not getattr(lib, "_gl_has_vm", False):
            return None
        o = _as_i64(oids)
        if o is None:
            return None
        h = lib.gl_ht_build(o, len(o))
        return cls(lib, h) if h else None

    def insert(self, oids: np.ndarray) -> np.ndarray:
        """Arrival-order setdefault; returns the lid of each input.
        Raises TypeError for non-integer oids (callers that allow mixed
        dtypes must check before inserting)."""
        o = _as_i64(oids)
        if o is None:
            raise TypeError("NativeIdTable.insert: non-integer oids")
        out = np.empty(len(o), dtype=np.int64)
        self._lib.gl_ht_insert(self._h, o, len(o), out.ctypes.data)
        return out

    def lookup(self, oids: np.ndarray) -> np.ndarray:
        o = _as_i64(oids)
        if o is None:
            # a non-integer query can never be in an int64 table
            return np.full(len(np.asarray(oids)), -1, dtype=np.int64)
        out = np.empty(len(o), dtype=np.int64)
        self._lib.gl_ht_lookup(self._h, o, len(o), out)
        return out

    def size(self) -> int:
        return int(self._lib.gl_ht_size(self._h))

    def oids(self) -> np.ndarray:
        out = np.empty(self.size(), dtype=np.int64)
        self._lib.gl_ht_oids(self._h, out)
        return out

    def __del__(self):
        h, self._h = self._h, None
        if h and self._lib is not None:
            self._lib.gl_ht_free(h)


class NativeMph:
    """Minimal perfect hash over int64 keys (native PTHash-style build;
    the reference `pthash_idxer.h` + thirdparty/pthash)."""

    def __init__(self, lib, handle, n):
        self._lib = lib
        self._h = handle
        self._n = n

    @classmethod
    def build(cls, keys: np.ndarray) -> "NativeMph | None":
        lib = _load()
        if lib is None or not getattr(lib, "_gl_has_vm", False):
            return None
        k = _as_i64(keys)
        if k is None or len(k) == 0:
            return None
        h = lib.gl_mph_build(k, len(k))
        return cls(lib, h, len(k)) if h else None

    def positions(self, keys: np.ndarray) -> np.ndarray:
        """[0, n) position per key; arbitrary for unknown keys (callers
        verify against their lid->oid array)."""
        k = _as_i64(keys)
        out = np.empty(len(k), dtype=np.int64)
        self._lib.gl_mph_pos(self._h, k, len(k), out)
        return out

    def bits_per_key(self) -> float:
        return float(self._lib.gl_mph_bits(self._h))

    def __del__(self):
        h, self._h = self._h, None
        if h and self._lib is not None:
            self._lib.gl_mph_free(h)


def available() -> bool:
    return _load() is not None


def sort_edges_native(src, nbr, w, num_rows: int, num_cols: int):
    """Stable (src, nbr) counting sort + indptr via the C++ helper;
    returns (src_sorted, nbr_sorted, w_sorted|None, indptr) or None when
    the native library is unavailable."""
    lib = _load()
    if lib is None or not getattr(lib, "_gl_has_sort", False):
        return None
    src64 = np.ascontiguousarray(src, dtype=np.int64)
    nbr64 = np.ascontiguousarray(nbr, dtype=np.int64)
    n = len(src64)
    if n:
        # the C counting sort indexes raw ids — validate here so an
        # upstream bug raises instead of corrupting the heap
        if int(src64.min()) < 0 or int(src64.max()) >= num_rows:
            raise ValueError("sort_edges_native: src id out of range")
        if int(nbr64.min()) < 0 or int(nbr64.max()) >= num_cols:
            raise ValueError("sort_edges_native: nbr id out of range")
    w64 = None if w is None else np.ascontiguousarray(w, dtype=np.float64)
    out_src = np.empty(n, dtype=np.int64)
    out_nbr = np.empty(n, dtype=np.int64)
    out_w = np.empty(n, dtype=np.float64) if w is not None else None
    indptr = np.empty(num_rows + 1, dtype=np.int64)
    lib.gl_sort_edges(
        src64, nbr64,
        w64.ctypes.data if w64 is not None else None,
        n, num_rows, num_cols,
        out_src, out_nbr,
        out_w.ctypes.data if out_w is not None else None,
        indptr,
    )
    return out_src, out_nbr, out_w, indptr


def parse_file_native(path: str, ncols: int, weighted: bool):
    """Returns (col0 int64, col1 int64 | None, w float64 | None) or None
    when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    handle = lib.gl_parse(path.encode(), ncols, int(weighted), 0)
    if not handle:
        raise FileNotFoundError(path)
    try:
        n = lib.gl_num_rows(handle)
        if n == 0:  # empty vectors return NULL data pointers
            return (
                np.zeros(0, np.int64),
                np.zeros(0, np.int64) if ncols >= 2 else None,
                np.zeros(0, np.float64) if weighted else None,
            )
        c0 = np.ctypeslib.as_array(lib.gl_col0(handle), shape=(n,)).copy()
        c1 = (
            np.ctypeslib.as_array(lib.gl_col1(handle), shape=(n,)).copy()
            if ncols >= 2
            else None
        )
        w = None
        if weighted:
            # all-rows-weighted or the file has no weight column — in the
            # latter case behave like the python parser (w = None)
            if lib.gl_all_weighted(handle):
                w = np.ctypeslib.as_array(lib.gl_colw(handle), shape=(n,)).copy()
    finally:
        lib.gl_free(handle)
    return c0, c1, w
