"""ctypes binding to the native C++ loader (native/loader.cc).

The shared library is built lazily with `make -C native` on first use;
all callers fall back to the Python/pandas parser when the toolchain or
build is unavailable (`read_edge_file` handles the dispatch).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libgrape_tpu_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("GRAPE_TPU_NO_NATIVE"):
            return None
        if not os.path.exists(_SO_PATH):
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.gl_parse.restype = ctypes.c_void_p
        lib.gl_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.gl_num_rows.restype = ctypes.c_int64
        lib.gl_num_rows.argtypes = [ctypes.c_void_p]
        for name in ("gl_col0", "gl_col1"):
            fn = getattr(lib, name)
            fn.restype = ctypes.POINTER(ctypes.c_int64)
            fn.argtypes = [ctypes.c_void_p]
        lib.gl_colw.restype = ctypes.POINTER(ctypes.c_double)
        lib.gl_colw.argtypes = [ctypes.c_void_p]
        lib.gl_all_weighted.restype = ctypes.c_int
        lib.gl_all_weighted.argtypes = [ctypes.c_void_p]
        lib.gl_free.restype = None
        lib.gl_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def parse_file_native(path: str, ncols: int, weighted: bool):
    """Returns (col0 int64, col1 int64 | None, w float64 | None) or None
    when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    handle = lib.gl_parse(path.encode(), ncols, int(weighted), 0)
    if not handle:
        raise FileNotFoundError(path)
    try:
        n = lib.gl_num_rows(handle)
        if n == 0:  # empty vectors return NULL data pointers
            return (
                np.zeros(0, np.int64),
                np.zeros(0, np.int64) if ncols >= 2 else None,
                np.zeros(0, np.float64) if weighted else None,
            )
        c0 = np.ctypeslib.as_array(lib.gl_col0(handle), shape=(n,)).copy()
        c1 = (
            np.ctypeslib.as_array(lib.gl_col1(handle), shape=(n,)).copy()
            if ncols >= 2
            else None
        )
        w = None
        if weighted:
            # all-rows-weighted or the file has no weight column — in the
            # latter case behave like the python parser (w = None)
            if lib.gl_all_weighted(handle):
                w = np.ctypeslib.as_array(lib.gl_colw(handle), shape=(n,)).copy()
    finally:
        lib.gl_free(handle)
    return c0, c1, w
