"""TSV line parsing for LDBC .v/.e files.

Re-design of `grape/io/line_parser_base.h` + `tsv_line_parser.h`: instead
of a virtual per-line parser driven by each MPI rank, the host parses
whole byte ranges columnarly (pandas C engine when available, numpy
fallback) — orders of magnitude faster in Python and the natural feed for
building padded device tensors.
"""

from __future__ import annotations

import io as _io

import numpy as np

try:
    import pandas as _pd
except Exception:  # pragma: no cover
    _pd = None


class TSVLineParser:
    """Parses whitespace-separated `src dst [edata]` / `oid [vdata]` lines."""

    def parse_edges(self, data: bytes, has_edata: bool):
        return _parse_columns(data, 2, 3 if has_edata else 2)

    def parse_vertices(self, data: bytes):
        return _parse_columns(data, 1, 1)


# chunked-parallel fallback threshold: below this a single parse wins
_PAR_MIN_BYTES = 32 << 20


def _parse_columns_parallel(data: bytes, int_cols: int, want_cols: int):
    """Host-pool form of the native loader's thread-chunk parse
    (`native/loader.cc`; reference `grape/io/local_io_adaptor.cc`
    partial reads): split on line boundaries, one pool task per chunk
    (pandas' C engine releases the GIL, so chunks parse concurrently),
    concatenate columns."""
    import os

    from libgrape_lite_tpu.utils.thread_pool import ThreadPool

    nt = min(os.cpu_count() or 1, 8)
    if nt <= 1 or len(data) < _PAR_MIN_BYTES:
        return _parse_columns(data, int_cols, want_cols)
    step = len(data) // nt
    bounds = [0]
    for i in range(1, nt):
        cut = data.find(b"\n", i * step)
        bounds.append(len(data) if cut < 0 else cut + 1)
    bounds.append(len(data))
    chunks = [
        data[a:b] for a, b in zip(bounds, bounds[1:]) if b > a
    ]
    pool = ThreadPool(len(chunks))
    try:
        parts = pool.for_each(
            lambda c: _parse_columns(c, int_cols, want_cols), chunks
        )
    finally:
        pool.shutdown()
    parts = [p for p in parts if p and len(p[0])]
    if not parts:
        return _parse_columns(b"", int_cols, want_cols)
    # a chunk of all-2-field lines in a weighted file yields fewer
    # columns; pad with NaN (the single-parse semantics) rather than
    # silently dropping the column file-wide.  Only float columns
    # (index >= int_cols) may be padded: NaN-padding an int id column
    # would float64-degrade oids above 2^53 — a chunk missing an id
    # column is malformed input, so reparse serially to surface it.
    ncol = max(len(p) for p in parts)
    if any(len(p) < min(ncol, int_cols) for p in parts):
        return _parse_columns(data, int_cols, want_cols)
    padded = [
        list(p) + [
            np.full(len(p[0]), np.nan) for _ in range(ncol - len(p))
        ]
        for p in parts
    ]
    return [
        np.concatenate([p[i] for p in padded]) for i in range(ncol)
    ]


def _parse_columns(data: bytes, int_cols: int, want_cols: int):
    """Parse whitespace table; the first `int_cols` columns keep full
    int64 precision (oids above 2^53 must not round-trip through
    float64 — the reference parses oids as integers,
    `tsv_line_parser.h`)."""
    if _pd is not None:
        try:
            df = _pd.read_csv(
                _io.BytesIO(data),
                sep=r"\s+",
                header=None,
                comment="#",
                engine="c",
            )
        except _pd.errors.EmptyDataError:
            # nothing but comments/blank lines in this (chunk of the)
            # file — yield well-typed empty columns
            return [
                np.zeros(0, np.int64 if i < int_cols else np.float64)
                for i in range(want_cols)
            ]
        cols = []
        for i in range(min(want_cols, df.shape[1])):
            c = df.iloc[:, i].to_numpy()
            if i < int_cols:
                # pandas NaN-fills short rows and astype(int64) would
                # turn NaN into INT64_MIN silently — a missing id
                # field must be an error, not a bogus vertex
                if c.dtype.kind == "f" and np.isnan(c).any():
                    raise ValueError(
                        f"malformed input: id column {i} has missing "
                        "fields"
                    )
                cols.append(c.astype(np.int64))
            else:
                cols.append(c.astype(np.float64))
        return cols
    # numpy fallback: two passes to keep id precision
    ids = np.loadtxt(
        _io.BytesIO(data), dtype=np.int64, comments="#", ndmin=2,
        usecols=range(int_cols),
    )
    cols = [ids[:, i] for i in range(int_cols)]
    if want_cols > int_cols:
        try:
            extra = np.loadtxt(
                _io.BytesIO(data), dtype=np.float64, comments="#", ndmin=2,
                usecols=range(int_cols, want_cols),
            )
            cols.extend(extra[:, i] for i in range(extra.shape[1]))
        except (ValueError, IndexError):
            pass
    return cols


def _parse_string_table(data: bytes, id_cols: int, weighted: bool):
    """String-oid parse (reference `--string_id`, load_tests.cc:45):
    id columns stay str objects; a trailing weight parses as float."""
    if _pd is None:
        rows = [
            line.split()
            for line in data.decode().splitlines()
            if line.strip() and not line.lstrip().startswith("#")
        ]
        cols = list(zip(*rows)) if rows else [[]] * (id_cols + weighted)
        out = [np.asarray(cols[i], dtype=object) for i in range(id_cols)]
        if weighted and len(cols) > id_cols:
            out.append(np.asarray(cols[id_cols], dtype=np.float64))
        return out
    df = _pd.read_csv(
        _io.BytesIO(data), sep=r"\s+", header=None, comment="#",
        engine="c", dtype=str,
    )
    out = [df.iloc[:, i].to_numpy(dtype=object) for i in range(id_cols)]
    if weighted and df.shape[1] > id_cols:
        out.append(df.iloc[:, id_cols].to_numpy().astype(np.float64))
    return out


def read_vertex_file(path: str, string_id: bool = False) -> np.ndarray:
    """Read a .v file; returns oids (int64, or str objects with
    string_id)."""
    from libgrape_lite_tpu.io.native import parse_file_native

    if not string_id:
        nat = parse_file_native(path, 1, False)
        if nat is not None:
            return nat[0]
    with open(path, "rb") as f:
        data = f.read()
    if string_id:
        return _parse_string_table(data, 1, False)[0]
    return _parse_columns_parallel(data, 1, 1)[0]


def read_edge_file(path: str, weighted: bool, string_id: bool = False):
    """Read a .e file; returns (src_oid, dst_oid, weight|None).

    Fast path: the native mmap+multithread parser (native/loader.cc,
    the analogue of the reference's C++ partial-read loaders); fallback:
    pandas/numpy columnar parse.  string_id keeps endpoint columns as
    str objects (reference --string_id)."""
    from libgrape_lite_tpu.io.native import parse_file_native

    if string_id:
        with open(path, "rb") as f:
            data = f.read()
        cols = _parse_string_table(data, 2, weighted)
        return cols[0], cols[1], cols[2] if len(cols) > 2 else None

    nat = parse_file_native(path, 2, weighted)
    if nat is not None:
        return nat
    with open(path, "rb") as f:
        data = f.read()
    cols = _parse_columns_parallel(data, 2, 3 if weighted else 2)
    src, dst = cols[0], cols[1]
    w = cols[2] if (weighted and len(cols) > 2) else None
    return src, dst, w
