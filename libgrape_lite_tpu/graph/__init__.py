from libgrape_lite_tpu.graph.csr import CSR, build_csr
