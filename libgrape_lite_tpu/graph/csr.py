"""Padded CSR storage.

Re-design of `grape/graph/immutable_csr.h:36-381` for XLA: a CSR here is
a *statically shaped* struct of arrays.  On top of the classic
`indptr` we keep the expanded per-edge source row (`edge_src`) so that
per-edge compute lowers to gather + `segment_sum/min/max` — the TPU
analogue of the reference CUDA engine's edge-balanced load-balancing
kernels (`grape/cuda/parallel/parallel_engine.h:621-1100`): work is
partitioned over *edges*, never over variable-degree vertex loops.

Padding contract:
  * vertex rows are padded to `num_rows` (power of two);
  * edges are padded to `num_edges_padded`; padded edges have
    `edge_src = num_rows` (an overflow segment sliced off by consumers),
    `edge_nbr = 0` and `edge_mask = False`.

Adjacency is sorted by (src, nbr) — the reference sorts neighbor lists
too (`immutable_csr.h:46-120`), which gives deterministic reductions.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


class CSRValidationError(ValueError):
    """The CSR violates its structural contract (see CSR.validate):
    computing on it would produce wrong results, not a crash."""


@dataclass
class CSR:
    """Host-side (numpy) padded CSR for one fragment."""

    indptr: np.ndarray  # [num_rows + 1] int32
    edge_src: np.ndarray  # [Ep] int32, local row id; pad = num_rows
    edge_nbr: np.ndarray  # [Ep] int64/int32, neighbor *global padded id*
    edge_w: np.ndarray | None  # [Ep] float, 0-padded
    edge_mask: np.ndarray  # [Ep] bool
    num_rows: int
    num_edges: int  # real edge count

    @property
    def degree(self) -> np.ndarray:
        return np.diff(self.indptr)

    def validate(self, name: str = "csr", n_pad: int | None = None) -> None:
        """Check every structural invariant of the padding contract and
        raise `CSRValidationError` naming the first violation.  Wired
        into the loader behind GRAPE_VALIDATE_LOAD=1 — a malformed or
        tampered input (especially a deserialized cache) must fail
        loudly here instead of silently computing garbage.

        `n_pad` bounds neighbor ids (fnum * vp) when the caller knows
        the global padded id space."""

        def bad(why: str):
            raise CSRValidationError(f"{name}: {why}")

        ip, src, nbr, mask = (
            np.asarray(self.indptr), np.asarray(self.edge_src),
            np.asarray(self.edge_nbr), np.asarray(self.edge_mask),
        )
        ep = len(src)
        ne = self.num_edges
        if ip.shape != (self.num_rows + 1,):
            bad(
                f"indptr shape {ip.shape} != (num_rows + 1,) = "
                f"({self.num_rows + 1},)"
            )
        if len(nbr) != ep or len(mask) != ep:
            bad(
                f"edge stream lengths disagree: src={ep} nbr={len(nbr)} "
                f"mask={len(mask)}"
            )
        if self.edge_w is not None and len(self.edge_w) != ep:
            bad(f"weight stream length {len(self.edge_w)} != {ep}")
        if not (0 <= ne <= ep):
            bad(f"num_edges={ne} outside [0, {ep}]")
        if ip.size and ip[0] != 0:
            bad(f"indptr[0] = {ip[0]} != 0")
        if np.any(np.diff(ip) < 0):
            r = int(np.argmax(np.diff(ip) < 0))
            bad(f"indptr is not monotone non-decreasing (row {r})")
        if ip.size and ip[-1] != ne:
            bad(
                f"degree/edge-count disagreement: indptr[-1] = "
                f"{int(ip[-1])} != num_edges = {ne}"
            )
        real_src = src[:ne]
        if ne and (real_src.min() < 0 or real_src.max() >= self.num_rows):
            bad(
                f"edge_src out of range: [{real_src.min()}, "
                f"{real_src.max()}] not within [0, {self.num_rows})"
            )
        if np.any(np.diff(real_src) < 0):
            bad("edge_src is not sorted (adjacency must be (src, nbr) "
                "ordered)")
        # per-row extents must agree with the expanded src stream
        counts = np.bincount(real_src, minlength=self.num_rows) if ne \
            else np.zeros(self.num_rows, dtype=np.int64)
        if not np.array_equal(counts, np.diff(ip)):
            r = int(np.argmax(counts != np.diff(ip)))
            bad(
                f"row {r}: indptr degree {int(np.diff(ip)[r])} != "
                f"edge_src count {int(counts[r])}"
            )
        if np.any(src[ne:] != self.num_rows):
            bad(f"padded edge_src must equal num_rows ({self.num_rows})")
        if not mask[:ne].all():
            bad("edge_mask False on a real edge")
        if mask[ne:].any():
            bad("edge_mask True on a padded edge")
        real_nbr = nbr[:ne]
        if ne and real_nbr.min() < 0:
            bad(f"negative neighbor id {int(real_nbr.min())}")
        if ne and n_pad is not None and real_nbr.max() >= n_pad:
            bad(
                f"neighbor id {int(real_nbr.max())} outside the global "
                f"padded id space [0, {n_pad})"
            )
        if self.edge_w is not None and ne:
            w = np.asarray(self.edge_w[:ne])
            if np.isnan(w).any():
                bad(f"{int(np.isnan(w).sum())} NaN edge weight(s)")


def build_csr(
    src_lid: np.ndarray,
    nbr_pid: np.ndarray,
    weights: np.ndarray | None,
    num_rows: int,
    num_edges_padded: int,
    nbr_dtype=np.int32,
) -> CSR:
    """Two-pass build (degree count then fill), like the reference's
    parallel builder (`immutable_csr.h:46-120`) but vectorised."""
    e = len(src_lid)
    if e > num_edges_padded:
        raise ValueError(f"edge overflow: {e} > {num_edges_padded}")

    nat = None
    if e >= 1 << 17:  # counting sort beats lexsort on big shards
        from libgrape_lite_tpu.io.native import sort_edges_native

        num_cols = int(np.asarray(nbr_pid).max(initial=0)) + 1
        # counting-sort work and memory are O(num_cols); only profitable
        # when the id space is comparable to the edge count (and the
        # counting array stays modest)
        if num_cols <= min(16 * e, 1 << 25):
            nat = sort_edges_native(
                src_lid, nbr_pid, weights, num_rows, num_cols
            )
    if nat is not None:
        s64, n64, w64, ip64 = nat
        src_sorted = s64.astype(np.int32)
        nbr_sorted = n64.astype(nbr_dtype)
        w_sorted = (
            None if weights is None
            else w64.astype(np.asarray(weights).dtype)
        )
        indptr = ip64.astype(np.int32)
    else:
        order = np.lexsort((nbr_pid, src_lid))
        src_sorted = np.asarray(src_lid)[order].astype(np.int32)
        nbr_sorted = np.asarray(nbr_pid)[order].astype(nbr_dtype)
        w_sorted = None if weights is None else np.asarray(weights)[order]

        counts = np.bincount(src_sorted, minlength=num_rows)
        indptr = np.zeros(num_rows + 1, dtype=np.int32)
        np.cumsum(counts, out=indptr[1:])

    pad = num_edges_padded - e
    edge_src = np.concatenate(
        [src_sorted, np.full(pad, num_rows, dtype=np.int32)]
    )
    edge_nbr = np.concatenate([nbr_sorted, np.zeros(pad, dtype=nbr_dtype)])
    edge_w = (
        None
        if w_sorted is None
        else np.concatenate([w_sorted, np.zeros(pad, dtype=w_sorted.dtype)])
    )
    edge_mask = np.concatenate(
        [np.ones(e, dtype=bool), np.zeros(pad, dtype=bool)]
    )
    return CSR(indptr, edge_src, edge_nbr, edge_w, edge_mask, num_rows, e)
