"""Replica routing: one graph resident R times, one front router.

The same graph loaded as R replica `ServeSession`s (each with its own
fragment copy — `fragment.mutation.replicate_fragment` rebuilds from
the retained edge list, deterministically, so replicas answer
byte-identically) behind a front router:

* **least-outstanding routing** — `submit` picks the routable replica
  with the fewest outstanding queries (ties broken by replica index,
  so scripted streams stay deterministic) and records per-replica
  served/ok/latency accounting (`Replica.summary` — the per-replica
  qps@p99 the ROADMAP names as the target bench).

* **graph-version fence** — the router carries a fence version,
  bumped at every `ingest`.  An ingest is a fleet-wide barrier: every
  routable replica drains (its in-flight queries land on the
  pre-delta graph), then applies the SAME delta chunk and adopts the
  new fence.  A query is only ever routed to a replica whose version
  matches the fence, and a routable replica at the wrong version is a
  LOUD `FenceViolationError` at both submit and pump time — no result
  may ever mix versions.

* **drain** (fleet/drain.py) — `drain(replica)` rides the async
  pump's quiesce barrier: stop routing, finish every admitted query
  (zero drops), run the offline work (repack/reshard/catch-up
  ingest), rejoin at the fenced version.

Each replica gets an `AsyncServePump` (window=1 by default — the
synchronous discipline, byte-identical by the r12 pin — deeper
windows compose) whose quiesce barrier IS the drain primitive.

docs/FLEET.md is the user guide; the CLI surface is
`serve --replicas R [--drain_at K]`.
"""

from __future__ import annotations

from typing import List, Optional

from libgrape_lite_tpu import obs
from libgrape_lite_tpu.fleet.budget import FLEET_STATS


class FenceError(RuntimeError):
    """No routable replica is available at the current fence."""


class FenceViolationError(RuntimeError):
    """A routable replica's graph version diverged from the fence —
    dispatching to it could mix results across graph versions."""


class Replica:
    """One resident copy of the graph: its session, pump, version,
    and accounting."""

    def __init__(self, idx: int, session, window: int = 1):
        self.idx = idx
        self.session = session
        self.pump = session.async_pump(window=window)
        self.version = 0
        self.routable = True
        self.outstanding = 0
        self.catchup: List[tuple] = []  # (fence, ops, force) missed
        self.served = 0
        self.ok = 0
        self.latencies: List[float] = []
        self.drains = 0

    def summary(self, wall_s: Optional[float] = None) -> dict:
        from libgrape_lite_tpu.serve.queue import latency_summary_ms

        lat = latency_summary_ms(self.latencies)
        out = {
            "served": self.served,
            "ok": self.ok,
            "p50_ms": lat["p50_ms"],
            "p99_ms": lat["p99_ms"],
            "version": self.version,
            "drains": self.drains,
        }
        if wall_s:
            out["qps"] = round(self.served / wall_s, 2)
        return out


class FleetRouter:
    """Front router over R replica sessions (see module docstring)."""

    def __init__(self, sessions, *, window: int = 1):
        if not sessions:
            raise ValueError("router needs at least one replica session")
        self._window = int(window)
        self.replicas = [
            Replica(i, s, window) for i, s in enumerate(sessions)
        ]
        self.fence = 0
        self._live: List[tuple] = []  # (QueryRequest, Replica)
        self.stats = {"routed": 0, "ingests": 0, "drains": 0}
        # shared result cache (autopilot/cache.py), attach_cache-wired:
        # the fence IS its invalidation epoch
        self.cache = None

    # ---- elasticity (autopilot/scaler.py) ---------------------------------

    def add_replica(self, session) -> Replica:
        """Join a NEW replica session at the current fence — the
        autoscaler's scale-up actuator.  The session must hold a
        content-identical copy of the current graph
        (`fragment.mutation.replicate_fragment` of a live replica's
        fragment — deterministic, so the newcomer answers
        byte-identically).  Routable immediately; recorded in
        FLEET_STATS like every drain/rejoin."""
        r = Replica(len(self.replicas), session, self._window)
        r.version = self.fence
        self.replicas.append(r)
        if self.cache is not None:
            session.attach_result_cache(
                self.cache, epoch=lambda: self.fence
            )
        FLEET_STATS.record("add_replica", replica=r.idx,
                           fence=self.fence)
        return r

    def attach_cache(self, cache) -> None:
        """Share one ResultCache (autopilot/cache.py) across every
        replica, with the router fence as the invalidation epoch: a
        hit computed by ANY replica is valid fleet-wide (replicas are
        byte-identical at a fence), and `ingest` reaps the stale
        epoch wholesale after bumping it."""
        self.cache = cache
        for r in self.replicas:
            r.session.attach_result_cache(
                cache, epoch=lambda: self.fence
            )

    # ---- routing ----------------------------------------------------------

    def _routable(self) -> List[Replica]:
        out = [r for r in self.replicas if r.routable]
        for r in out:
            self._check_fence(r)
        return out

    def _check_fence(self, r: Replica) -> None:
        if r.version != self.fence:
            from libgrape_lite_tpu.obs.recorder import RECORDER

            RECORDER.trigger(
                "fence_violation",
                extra={"replica": r.idx, "replica_version": r.version,
                       "fence": self.fence},
            )
            raise FenceViolationError(
                f"replica {r.idx} is routable at graph version "
                f"{r.version} but the fence is {self.fence} — "
                "results would mix graph versions"
            )

    def submit(self, app_key: str, args: dict | None = None, **kw):
        """Route one query to the least-outstanding routable replica
        (fence-checked) and return its QueryRequest."""
        cands = self._routable()
        if not cands:
            raise FenceError(
                "no routable replica (all draining?) — rejoin one "
                "before submitting"
            )
        pick = min(cands, key=lambda r: (r.outstanding, r.idx))
        req = pick.session.submit(app_key, args, **kw)
        pick.outstanding += 1
        self._live.append((req, pick))
        self.stats["routed"] += 1
        tr = obs.tracer()
        if tr.enabled:
            obs.metrics().gauge(
                f"grape_fleet_outstanding_r{pick.idx}"
            ).set(pick.outstanding)
        return req

    def _collect(self) -> None:
        """Bind completed requests back to their replica accounting."""
        still = []
        for req, r in self._live:
            if req.done:
                r.outstanding -= 1
                r.served += 1
                r.ok += int(bool(req.result.ok))
                r.latencies.append(req.result.latency_s)
            else:
                still.append((req, r))
        self._live = still

    # ---- driving ----------------------------------------------------------

    def pump(self) -> List:
        """One pass: pump every routable replica once (fence-checked),
        collect accounting, return this step's results.  Each
        replica's interval lands on its own trace row
        (tracer.replica_tid) when obs is armed."""
        out = []
        tr = obs.tracer()
        for r in self._routable():
            with tr.span("fleet_pump", replica=r.idx,
                         outstanding=r.outstanding) as sp:
                got = r.pump.pump(force=True)
            if tr.enabled and got:
                tr.emit_span_raw(
                    "fleet_replica", t0_ns=sp.t0_ns, dur_ns=sp.dur_ns,
                    tid=tr.replica_tid(r.idx), replica=r.idx,
                    results=len(got),
                )
            out.extend(got)
        self._collect()
        return out

    def drain(self) -> List:
        """Drain every ROUTABLE replica's queue + window (a draining
        replica is finished separately by fleet/drain.py)."""
        out = []
        tr = obs.tracer()
        for r in self._routable():
            with tr.span("fleet_pump", replica=r.idx,
                         outstanding=r.outstanding) as sp:
                got = r.pump.drain()
            if tr.enabled and got:
                tr.emit_span_raw(
                    "fleet_replica", t0_ns=sp.t0_ns, dur_ns=sp.dur_ns,
                    tid=tr.replica_tid(r.idx), replica=r.idx,
                    results=len(got),
                )
            out.extend(got)
        self._collect()
        return out

    # ---- dyn ingest: the version fence -------------------------------------

    def ingest(self, ops, *, force_repack: bool = False) -> dict:
        """Broadcast one delta chunk behind the version fence.

        Barrier first: every routable replica drains, so every query
        admitted before this call lands on the pre-delta graph —
        queries and ingests interleave identically at any replica
        count, which is what makes an R=2 run byte-identical to the
        R=1 run (the drain drill's identity argument).  Then the
        fence bumps, every routable replica applies the SAME ops
        (dyn/ broadcast — overlay-only ingests stay zero-recompile
        per replica), and draining replicas log the chunk for their
        offline catch-up."""
        from libgrape_lite_tpu.dyn.ingest import broadcast_ingest

        self.drain()
        self.fence += 1
        ops = list(ops)
        live = [r for r in self.replicas if r.routable]
        reports = broadcast_ingest(
            [r.session for r in live], ops, force_repack=force_repack
        )
        for r in self.replicas:
            if r.routable:
                r.version = self.fence
            else:
                r.catchup.append((self.fence, ops, force_repack))
        self.stats["ingests"] += 1
        if self.cache is not None:
            # the fence moved: the previous epoch's cached answers are
            # answers about a graph that no longer exists — reap them
            # wholesale (lookups at the new fence structurally miss
            # them anyway; this frees the memory and counts the kill)
            self.cache.invalidate_stale(self.fence)
        tr = obs.tracer()
        if tr.enabled:
            tr.instant(
                "fleet_ingest", fence=self.fence, ops=len(ops),
                applied=len(reports),
                deferred=len(self.replicas) - len(reports),
            )
        return {
            "fence": self.fence,
            "applied_replicas": len(reports),
            "reports": reports,
        }

    # ---- drain lifecycle (fleet/drain.py) ---------------------------------

    def begin_drain(self, idx: int, *, offline=None) -> dict:
        from libgrape_lite_tpu.fleet.drain import begin_drain

        return begin_drain(self, idx, offline=offline)

    def rejoin(self, idx: int) -> dict:
        from libgrape_lite_tpu.fleet.drain import rejoin

        return rejoin(self, idx)

    def drain_replica(self, idx: int, *, offline=None) -> dict:
        from libgrape_lite_tpu.fleet.drain import drain_replica

        return drain_replica(self, idx, offline=offline)

    def summary(self, wall_s: Optional[float] = None) -> dict:
        return {
            "fence": self.fence,
            "stats": dict(self.stats),
            "replicas": {
                f"r{r.idx}": r.summary(wall_s) for r in self.replicas
            },
        }


def run_fleet_script(target, queries, *, manager=None, tenant_of=None,
                     delta_ops=None, ingest_every: int = 8,
                     drain_at: Optional[int] = None,
                     drain_idx: int = 0, offline=None,
                     submit_kwargs: Optional[dict] = None) -> List:
    """The deterministic fleet driver shared by the CLI, bench.py and
    the tests: submit `queries` ([(app_key, args)] in order) in
    groups of `ingest_every`, complete each group (a fleet-wide
    barrier), then broadcast the next delta chunk — so the
    query <-> graph-version interleave (and therefore every result
    byte) is identical at ANY replica count, window depth or tenant
    split.  `drain_at` begins draining replica `drain_idx` before
    that query index is submitted; the replica rejoins after the NEXT
    ingest barrier (its catch-up log is then non-trivial) or at the
    end of the stream.  Returns the tickets/requests in submit order.

    `target` is a FleetRouter or a bare ServeSession; with `manager`,
    submissions go through the tenancy front (`tenant_of(i, app_key)`
    names query i's tenant) and completion runs the WRR pump.
    `submit_kwargs` (e.g. {"max_rounds": 3, "guard": "halt"}) rides on
    EVERY submit, so stream-wide limits reach the underlying queue
    exactly as they do on the plain serve path."""
    delta_ops = list(delta_ops or [])
    submit_kwargs = dict(submit_kwargs or {})
    router = target if hasattr(target, "replicas") else None
    n_groups = max(1, -(-len(queries) // max(1, ingest_every)))
    chunk = -(-len(delta_ops) // n_groups) if delta_ops else 0
    oi = 0
    draining = False

    def complete():
        if manager is not None:
            manager.drain()
        elif router is not None:
            router.drain()
        else:
            target.drain()

    reqs = []
    for i, (app_key, args) in enumerate(queries):
        if drain_at is not None and i == drain_at and router is not None:
            complete()  # the manager lane must be empty before we stop
            router.begin_drain(drain_idx, offline=offline)
            draining = True
        if manager is not None:
            reqs.append(
                manager.submit(tenant_of(i, app_key), app_key, args,
                               **submit_kwargs)
            )
        else:
            reqs.append(target.submit(app_key, args, **submit_kwargs))
        if (i + 1) % max(1, ingest_every) == 0:
            complete()
            if oi < len(delta_ops):
                ingest = (router or target).ingest
                ingest(delta_ops[oi:oi + chunk])
                oi += chunk
                if draining:
                    router.rejoin(drain_idx)
                    draining = False
    complete()
    while oi < len(delta_ops):
        ingest = (router or target).ingest
        ingest(delta_ops[oi:oi + chunk])
        oi += chunk
    if draining:
        router.rejoin(drain_idx)
    complete()
    return reqs
