"""fleet/ — the multi-tenant serving fleet (ROADMAP item 2b/2c).

One process, N resident (graph x app) sessions, R replicas, one HBM
budget:

* **budget.py** — price each session's device footprint from the
  ledgers that already exist (CSR bytes, pack/spgemm plan streams,
  dyn overlay planes, resident runner buffers) and drive
  admission/eviction with a cost-weighted LRU under
  GRAPE_FLEET_HBM_BYTES; every decision recorded in `FLEET_STATS`.
* **tenancy.py** — `FleetManager`: N tenants with weighted
  round-robin fairness feeding their sessions, per-tenant breach
  isolation (tenants never share a batched dispatch), and
  evict/re-admit through `ServeSession.release_device` /
  `restore_device` — re-admission is zero pack re-planning and zero
  XLA recompiles (the host plan caches stay warm).
* **router.py / drain.py** — `FleetRouter`: the same graph resident
  R times behind a least-outstanding front, dyn ingest broadcast
  behind a graph-version fence (no result may ever mix versions —
  violations are loud), and `drain(replica)` on the async pump's
  quiesce barrier: stop routing, finish every admitted query, run
  repack/reshard/ingest offline, rejoin at the fenced version — zero
  dropped queries, byte-identical results.

docs/FLEET.md is the user guide; the CLI surface is
`python -m libgrape_lite_tpu.cli serve --tenants ... --replicas R
--drain_at K`, and bench.py's `fleet` block reports sustained
qps@p99 PER REPLICA with concurrent ingest and a mid-run drain.
"""

from libgrape_lite_tpu.fleet.budget import (
    FLEET_STATS,
    FleetBudget,
    Footprint,
    fragment_bytes,
    overlay_bytes,
    plan_stream_bytes,
    runner_bytes,
    session_footprint,
    target_footprint,
)
from libgrape_lite_tpu.fleet.drain import (
    begin_drain,
    drain_replica,
    rejoin,
    rejoin_lost,
)
from libgrape_lite_tpu.fleet.router import (
    FenceError,
    FenceViolationError,
    FleetRouter,
    Replica,
    run_fleet_script,
)
from libgrape_lite_tpu.fleet.tenancy import (
    FleetAdmissionError,
    FleetManager,
    Tenant,
    TenantTicket,
)

__all__ = [
    "FLEET_STATS",
    "FenceError",
    "FenceViolationError",
    "FleetAdmissionError",
    "FleetBudget",
    "FleetManager",
    "FleetRouter",
    "Footprint",
    "Replica",
    "Tenant",
    "TenantTicket",
    "begin_drain",
    "drain_replica",
    "fragment_bytes",
    "overlay_bytes",
    "plan_stream_bytes",
    "rejoin",
    "rejoin_lost",
    "run_fleet_script",
    "runner_bytes",
    "session_footprint",
    "target_footprint",
]
