"""Fleet budget: price resident sessions, decide admission/eviction.

A serving fleet multiplexes N resident (graph x app) sessions over ONE
HBM budget.  This module is the only place the tenancy trade-off
lives, and it prices footprints from the ledgers that already exist
rather than inventing a new byte model:

  * **fragment bytes** — the stacked device CSRs + per-vertex planes,
    priced from their HOST twins (`ShardedEdgecutFragment.host_oe/ie`,
    the same geometry `_check_hbm_budget` bills at load time), so an
    EVICTED session prices identically to a resident one;
  * **plan-stream bytes** — every pack / spgemm plan resolved for the
    fragment (`spmv_pack._frag_cache`), the `host_streams` tables the
    multi-shard path ships as ephemeral state;
  * **overlay bytes** — the dyn delta overlay's dense
    [fnum, capacity] side planes (dyn/ingest.py);
  * **runner bytes** — the resident workers' retained result carries
    (`Worker._result_state`), the buffers `Worker.release_buffers`
    drops on eviction.

Admission is SparseP-style cost-model-driven, not a hand-tuned
watermark: `FleetBudget.admit` fits the priced footprint under the
capacity (GRAPE_FLEET_HBM_BYTES, default GRAPE_HBM_BYTES, default one
v5e chip's 16 GiB; 0 disables like the loader's gate) and, when it
does not fit, evicts **cost-weighted LRU** victims — the resident
maximizing `idle_seconds * freeable_bytes / weight` goes first, so
cold, large, low-priority tenants pay before hot or heavy-weighted
ones.  Fragments SHARED between residents are billed once and are
only freeable when their last resident leaves.  Every decision —
admit, evict, re-admit, reject — is recorded in `FLEET_STATS` with
its prices, in the PARTITION_STATS/PUMP_STATS recorded-decision
style: a fleet that silently thrashed or refused a tenant is visible
in one dict instead of a wall-clock mystery.

docs/FLEET.md is the user guide.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from libgrape_lite_tpu.ops.calibration import default_profile

#: capacity env knob; falls back to the loader's GRAPE_HBM_BYTES gate
FLEET_HBM_ENV = "GRAPE_FLEET_HBM_BYTES"
#: one chip's HBM, from the shared RateProfile (pinned: one v5e)
DEFAULT_HBM_BYTES = default_profile().hbm_capacity_bytes


class FleetStats:
    """Every fleet decision, counted and bounded (the PUMP_STATS
    discipline applied to tenancy/routing): admissions, evictions,
    re-admissions, rejections, drains — each with the prices/reasons
    that drove it."""

    MAX_EVENTS = 256

    def __init__(self):
        self.admits = 0
        self.evictions = 0
        self.readmits = 0
        self.rejects = 0
        self.drains = 0
        self.rejoins = 0
        self.events: List[dict] = []

    def _record(self, ev: dict) -> None:
        self.events.append(ev)
        if len(self.events) > self.MAX_EVENTS:
            del self.events[: self.MAX_EVENTS // 2]

    def record(self, kind: str, **detail) -> None:
        if kind == "admit":
            self.admits += 1
        elif kind == "evict":
            self.evictions += 1
        elif kind == "readmit":
            self.readmits += 1
        elif kind == "reject":
            self.rejects += 1
        elif kind == "drain":
            self.drains += 1
        elif kind == "rejoin":
            self.rejoins += 1
        self._record({"kind": kind, **detail})

    def snapshot(self) -> dict:
        return {
            "admits": self.admits, "evictions": self.evictions,
            "readmits": self.readmits, "rejects": self.rejects,
            "drains": self.drains, "rejoins": self.rejoins,
        }

    def reset(self) -> None:
        self.__init__()


#: module-level record shared by every budget/manager/router in the
#: process (like PUMP_STATS): tests/bench read it, reset() between runs
FLEET_STATS = FleetStats()

# federated as "fleet" (obs/federation.py): the class keeps its own
# snapshot()/reset() protocol; the federation just routes to it
from libgrape_lite_tpu.obs import federation as _federation  # noqa: E402

_federation.register("fleet", FLEET_STATS.snapshot, FLEET_STATS.reset,
                     module=__name__)


# ---- footprint pricing ----------------------------------------------------


def fragment_bytes(frag) -> int:
    """Device bytes of one sharded fragment, priced from the host CSR
    twins (identical shapes/dtypes to the stacked device arrays), so
    the price is the same whether the fragment is currently resident
    or evicted.  Undirected fragments alias ie onto oe and pay once,
    like the device build.

    A vertex-cut (2-D SUMMA) fragment is priced from its host tile
    buffers instead: its `host_ie`/`host_oe` are DERIVED per-tile COO
    views that never ship to the device, so pricing them would charge
    the fleet for bytes that are never placed."""
    tiles = getattr(frag, "_host_tiles", None)
    if tiles is not None:
        s_arr, d_arr, w_arr, m_arr = tiles
        total = s_arr.nbytes + d_arr.nbytes + m_arr.nbytes
        if w_arr is not None:
            total += w_arr.nbytes
        # per-device vertex planes: carry mask [k*vc] (bool) on the
        # row axis + oid plane (i64) + ivnum scalar per tile
        k, vc = frag.k, frag.vc
        total += k * k * (k * vc) * 1 + frag.fnum * (8 * frag.vp + 4)
        return int(total)

    def csr(csrs):
        b = 0
        for c in csrs:
            b += c.indptr.nbytes + c.edge_src.nbytes
            b += c.edge_nbr.nbytes + c.edge_mask.nbytes
            if c.edge_w is not None:
                b += c.edge_w.nbytes
        return b

    total = csr(frag.host_oe)
    aliased = frag.host_ie is frag.host_oe
    if not aliased:
        total += csr(frag.host_ie)
    # ivnum + inner_mask + oids(i64) + degree plane(s)
    fnum, vp = frag.fnum, frag.vp
    total += fnum * 4 + fnum * vp * (1 + 8 + 4 + (0 if aliased else 4))
    return total


def plan_stream_bytes(frag) -> int:
    """Bytes of every pack/spgemm plan resolved for `frag` — the
    `host_streams` tables the multi-shard dispatch ships as ephemeral
    state leaves (spmv_pack `MultiPackPlan` and spgemm `SpGemmPlan`
    entries share one per-fragment cache)."""
    from libgrape_lite_tpu.ops.spmv_pack import _frag_cache

    seen, total = set(), 0
    for plan in _frag_cache(frag).values():
        streams = getattr(plan, "host_streams", None)
        if not isinstance(streams, dict) or id(plan) in seen:
            continue
        seen.add(id(plan))
        total += sum(
            v.nbytes for v in streams.values() if hasattr(v, "nbytes")
        )
    return total


def overlay_bytes(frag) -> int:
    """Bytes of the attached dyn delta overlay's dense side planes."""
    ov = getattr(frag, "dyn_overlay", None)
    if ov is None:
        return 0
    sides = [ov.ie] if ov.oe is ov.ie else [ov.ie, ov.oe]
    return sum(
        s.src.nbytes + s.nbr.nbytes + s.w.nbytes + s.mask.nbytes
        for s in sides
    )


def runner_bytes(session) -> int:
    """Device bytes retained by the session's resident workers — the
    last result carries `Worker.release_buffers` drops on eviction."""
    total = 0
    for w in getattr(session, "_workers", {}).values():
        st = getattr(w, "_result_state", None)
        if isinstance(st, dict):
            total += sum(
                v.nbytes for v in st.values() if hasattr(v, "nbytes")
            )
    return total


@dataclass
class Footprint:
    """One resident target's priced device footprint.  `frag_keys`
    identifies the fragment objects so the budget can bill a SHARED
    fragment once across tenants (and refuse to free it while a
    sibling still serves from it)."""

    frag_bytes: int = 0
    plan_bytes: int = 0
    overlay_bytes: int = 0
    runner_bytes: int = 0
    frag_keys: Dict[int, int] = field(default_factory=dict)  # id -> bytes

    @property
    def total(self) -> int:
        return (self.frag_bytes + self.plan_bytes
                + self.overlay_bytes + self.runner_bytes)

    @property
    def private_bytes(self) -> int:
        """Everything except the (possibly shared) fragment arrays."""
        return self.total - self.frag_bytes

    def as_dict(self) -> dict:
        return {
            "frag_bytes": self.frag_bytes,
            "plan_bytes": self.plan_bytes,
            "overlay_bytes": self.overlay_bytes,
            "runner_bytes": self.runner_bytes,
            "total": self.total,
        }


def session_footprint(session) -> Footprint:
    """Price one ServeSession from the existing ledgers (see module
    docstring for the four components)."""
    frag = session.fragment
    fb = fragment_bytes(frag)
    return Footprint(
        frag_bytes=fb,
        plan_bytes=plan_stream_bytes(frag),
        overlay_bytes=overlay_bytes(frag),
        runner_bytes=runner_bytes(session),
        frag_keys={id(frag): fb},
    )


def target_footprint(target) -> Footprint:
    """Price a tenancy target: a ServeSession, or a FleetRouter whose
    replicas are priced per replica session (each replica holds its
    own fragment copy, so nothing dedupes here unless replicas share)."""
    replicas = getattr(target, "replicas", None)
    if replicas is None:
        return session_footprint(target)
    out = Footprint()
    for r in replicas:
        fp = session_footprint(r.session)
        out.plan_bytes += fp.plan_bytes
        out.overlay_bytes += fp.overlay_bytes
        out.runner_bytes += fp.runner_bytes
        for k, b in fp.frag_keys.items():
            if k not in out.frag_keys:
                out.frag_keys[k] = b
                out.frag_bytes += b
    return out


# ---- the budget -----------------------------------------------------------


@dataclass
class _Resident:
    footprint: Footprint
    weight: float
    last_use: float
    evictable: bool


class FleetBudget:
    """Admission/eviction under one HBM byte budget (see module
    docstring for the policy).  The budget only DECIDES; releasing the
    actual device buffers is the caller's job via the `evict` callback
    (FleetManager points it at `ServeSession.release_device`)."""

    def __init__(self, capacity_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        if capacity_bytes is None:
            capacity_bytes = int(os.environ.get(
                FLEET_HBM_ENV,
                os.environ.get("GRAPE_HBM_BYTES", DEFAULT_HBM_BYTES),
            ))
        self.capacity = int(capacity_bytes)  # 0 = unlimited
        self._clock = clock
        self.residents: Dict[str, _Resident] = {}

    # ---- accounting -------------------------------------------------------

    def used_bytes(self) -> int:
        """Total resident bytes with shared fragments billed once."""
        total, seen = 0, set()
        for r in self.residents.values():
            total += r.footprint.private_bytes
            for k, b in r.footprint.frag_keys.items():
                if k not in seen:
                    seen.add(k)
                    total += b
        return total

    def _freeable_bytes(self, name: str) -> int:
        """Bytes actually recovered by evicting `name`: its private
        bytes plus any of its fragments no OTHER resident shares."""
        r = self.residents[name]
        freeable = r.footprint.private_bytes
        for k, b in r.footprint.frag_keys.items():
            shared = any(
                k in o.footprint.frag_keys
                for n, o in self.residents.items() if n != name
            )
            if not shared:
                freeable += b
        return freeable

    def _marginal_bytes(self, footprint: Footprint) -> int:
        """Admission cost of a footprint given what is already
        resident (shared fragments are already paid for)."""
        cost = footprint.private_bytes
        for k, b in footprint.frag_keys.items():
            shared = any(
                k in r.footprint.frag_keys
                for r in self.residents.values()
            )
            if not shared:
                cost += b
        return cost

    def touch(self, name: str) -> None:
        if name in self.residents:
            self.residents[name].last_use = self._clock()

    # ---- decisions --------------------------------------------------------

    def _pick_victim(self) -> Optional[str]:
        """Cost-weighted LRU: the evictable resident maximizing
        idle_seconds * freeable_bytes / weight (ties: insertion
        order).  None when nothing can be evicted."""
        now = self._clock()
        best, best_score = None, -1.0
        for name, r in self.residents.items():
            if not r.evictable:
                continue
            idle = max(now - r.last_use, 1e-9)
            score = idle * self._freeable_bytes(name) / max(r.weight, 1e-9)
            if score > best_score:
                best, best_score = name, score
        return best

    def admit(self, name: str, footprint: Footprint, *,
              weight: float = 1.0, evictable: bool = True,
              evict: Optional[Callable[[str], None]] = None) -> dict:
        """Admit `name` under the budget, evicting cost-weighted-LRU
        victims as needed (each via the `evict` callback, then
        released here).  Returns the recorded decision dict; a reject
        (nothing left to evict and still over budget) is recorded AND
        returned with admitted=False — never silent, the caller
        decides whether to raise."""
        # re-pricing an already-resident tenant: pop the old entry so
        # the marginal cost computes fresh, but KEEP it around — a
        # reject must put it back (the tenant is still resident at
        # its old footprint; dropping it would under-count used_bytes
        # forever after)
        prior = self.residents.pop(name, None)
        readmit = prior is not None
        evicted: List[dict] = []
        while (self.capacity
               and self.used_bytes() + self._marginal_bytes(footprint)
               > self.capacity):
            victim = self._pick_victim()
            if victim is None:
                if prior is not None:
                    self.residents[name] = prior
                decision = {
                    "admitted": False, "name": name,
                    "asked_bytes": footprint.total,
                    "used_bytes": self.used_bytes(),
                    "capacity": self.capacity,
                    "evicted": evicted,
                    "reason": "over budget with no evictable resident",
                }
                FLEET_STATS.record("reject", **decision)
                return decision
            freed = self._freeable_bytes(victim)
            if evict is not None:
                evict(victim)
            del self.residents[victim]
            ev = {"name": victim, "freed_bytes": freed,
                  "for": name}
            evicted.append(ev)
            FLEET_STATS.record("evict", **ev)
        self.residents[name] = _Resident(
            footprint=footprint, weight=float(weight),
            last_use=self._clock(), evictable=evictable,
        )
        decision = {
            "admitted": True, "name": name,
            "bytes": footprint.total,
            "used_bytes": self.used_bytes(),
            "capacity": self.capacity,
            "evicted": evicted,
        }
        FLEET_STATS.record("readmit" if readmit else "admit", **decision)
        return decision

    def release(self, name: str, reason: str = "release") -> None:
        if name in self.residents:
            freed = self._freeable_bytes(name)
            del self.residents[name]
            FLEET_STATS.record(
                "evict", name=name, freed_bytes=freed, reason=reason,
            )

    def snapshot(self) -> dict:
        return {
            "capacity": self.capacity,
            "used_bytes": self.used_bytes(),
            "residents": {
                n: {**r.footprint.as_dict(), "weight": r.weight,
                    "evictable": r.evictable}
                for n, r in self.residents.items()
            },
        }
