"""Zero-downtime replica drain: stop routing, quiesce, work, rejoin.

The drain primitive the ROADMAP named (item 2c): take one replica out
of rotation for repack/reshard/offline ingest WITHOUT dropping
traffic, built on the async pump's quiesce barrier (PR 12) and the
router's graph-version fence:

  1. **stop routing** — the replica leaves the candidate set; new
     queries spread over its siblings (`FleetRouter.submit` routes
     least-outstanding among the remaining replicas).
  2. **quiesce** — every query ALREADY admitted to the replica runs
     to completion through its pump's drain (forced partial batches);
     zero queries are dropped, by construction.
  3. **offline work** — the caller's `offline(session)` hook runs
     against the idle replica: fold the dyn overlay into a rebuilt
     CSR (`session.dyn.fold_now`), repartition, reshard — anything
     that would have stalled the serving path.  This is the host-side
     gather/scatter + vertex-map-rebuild migration step of the
     distributed-memory permutation/assignment primitives
     (arXiv 2509.20776), run where nobody is waiting on it.
  4. **rejoin** — the catch-up log (every fence bump the replica
     missed, with its ops) replays IN ORDER, so the replica's graph
     content is identical to its siblings' (the overlay/rebuild
     byte-identity contract of dyn/ makes representation differences
     invisible); the fence versions must line up or rejoin raises
     `FenceViolationError` — a replica can never rejoin at a stale
     version.

The drain drill (tests/test_fleet.py, bench `fleet` block): R=2
serving a 64-query stream with concurrent ingest, one replica drained
mid-stream — zero dropped queries, every per-query result
byte-identical to the undrained R=1 run.
"""

from __future__ import annotations

import time

from libgrape_lite_tpu import obs
from libgrape_lite_tpu.fleet.budget import FLEET_STATS
from libgrape_lite_tpu.fleet.router import FenceViolationError


def begin_drain(router, idx: int, *, offline=None) -> dict:
    """Phase 1-3: stop routing, quiesce (zero drops), run the offline
    work.  The replica stays OUT of rotation until `rejoin` — deltas
    ingested meanwhile accumulate in its catch-up log."""
    r = router.replicas[idx]
    if not r.routable:
        raise ValueError(f"replica {idx} is already draining")
    if len([x for x in router.replicas if x.routable]) < 2:
        raise ValueError(
            f"cannot drain replica {idx}: it is the last routable "
            "replica — traffic would drop"
        )
    t0 = time.perf_counter()
    r.routable = False
    tr = obs.tracer()
    if tr.enabled:
        tr.instant(
            "fleet_drain_begin", replica=idx,
            outstanding=r.outstanding,
            pending=r.session.queue.pending(),
        )
    # quiesce: finish everything this replica already admitted
    drained = r.pump.drain()
    router._collect()
    if offline is not None:
        offline(r.session)
    wall = time.perf_counter() - t0
    r.drains += 1
    router.stats["drains"] += 1
    report = {
        "replica": idx,
        "drained_queries": len(drained),
        "offline": offline is not None,
        "wall_s": round(wall, 4),
    }
    FLEET_STATS.record("drain", **report)
    return report


def rejoin(router, idx: int) -> dict:
    """Phase 4: replay the catch-up log in fence order, verify the
    version lines up with the fence, and return to rotation."""
    r = router.replicas[idx]
    if r.routable:
        raise ValueError(f"replica {idx} is not draining")
    applied = 0
    for fence, ops, force in r.catchup:
        r.session.ingest(ops, force_repack=force)
        r.version = fence
        applied += len(ops)
    r.catchup = []
    if r.version != router.fence:
        # the fence only moves at ingest, and every ingest while we
        # were draining logged a catch-up entry — a mismatch here
        # means the log was tampered with or a version was skipped
        raise FenceViolationError(
            f"replica {idx} rejoining at version {r.version} but the "
            f"fence is {router.fence} — catch-up log incomplete"
        )
    r.routable = True
    tr = obs.tracer()
    if tr.enabled:
        tr.instant(
            "fleet_rejoin", replica=idx, fence=router.fence,
            catchup_ops=applied,
        )
    report = {"replica": idx, "catchup_ops": applied,
              "version": r.version}
    FLEET_STATS.record("rejoin", **report)
    return report


def rejoin_lost(router, checkpoint_dir: str, *, session_factory):
    """Process-loss rejoin (docs/FAULT_TOLERANCE.md, "Distributed
    resilience"): a replica lost to a dead rank cannot drain or replay
    a catch-up log — its in-memory state is gone.  What survives is
    the last committed sharded checkpoint.  This builds a REPLACEMENT
    replica from a live sibling's fragment (`replicate_fragment`, the
    same deterministic copy the autoscaler's scale-up uses), adds it
    to rotation at the current fence, and returns `(replica, meta)`
    where `meta` is the newest sharded snapshot's metadata — the
    caller resumes interrupted checkpointed queries via
    `Worker.resume`, which is reshard-aware (the snapshot restores
    onto the replacement's mesh even when the gang shrank)."""
    from libgrape_lite_tpu.fragment.mutation import replicate_fragment
    from libgrape_lite_tpu.ft.checkpoint import latest_meta

    meta = latest_meta(checkpoint_dir)
    if meta.get("layout") != "sharded":
        raise ValueError(
            f"rejoin_lost needs a sharded (multi-process) checkpoint "
            f"lineage; {checkpoint_dir!r} holds a "
            f"{meta.get('layout', 'single-file')!r} layout — use the "
            f"ordinary resume path for single-process loss"
        )
    live = [x for x in router.replicas if x.routable]
    if not live:
        raise ValueError(
            "rejoin_lost: no live replica to replicate a fragment from"
        )
    sess = session_factory(replicate_fragment(live[0].session.fragment))
    r = router.add_replica(sess)
    tr = obs.tracer()
    if tr.enabled:
        tr.instant(
            "fleet_rejoin_lost", replica=r.idx,
            ckpt_rounds=int(meta["rounds"]),
            ckpt_ranks=int(meta.get("ranks", 0)),
        )
    FLEET_STATS.record(
        "rejoin", replica=r.idx, lost_process=True,
        ckpt_rounds=int(meta["rounds"]),
        ckpt_ranks=int(meta.get("ranks", 0)),
    )
    return r, meta


def drain_replica(router, idx: int, *, offline=None) -> dict:
    """The one-call form: begin + rejoin immediately (no ingest can
    land in between, so the catch-up log is empty and the replica
    rejoins at the unchanged fence)."""
    report = begin_drain(router, idx, offline=offline)
    report["rejoin"] = rejoin(router, idx)
    return report
