"""Multi-tenant sessions under one budget: fairness, evict, re-admit.

A tenant is one (graph x app-mix) serving principal: it owns a
`ServeSession` (or shares a `FleetRouter` of replica sessions) plus
its own admission lane, fairness weight, and accounting.  The
`FleetManager` multiplexes N tenants over one process:

* **Admission lane + weighted round-robin fairness** — `submit`
  enqueues a `TenantTicket` on the tenant's own pending lane;
  `forward_round` moves tickets into the underlying session queues in
  WRR order (ceil(weight) tickets per tenant per cycle, insertion
  order within a cycle), so a tenant with a deep backlog can never
  starve a light one: any tenant with pending work is visited every
  cycle (the starvation bound tests/test_fleet.py pins).  Forwarded
  requests carry `tenant=` so the session compat key never coalesces
  two tenants into one batched dispatch — one tenant's poisoned lane
  cannot fail a batchmate tenant (breach isolation is structural, and
  pinned).

* **HBM-budget tenancy** — on first use (and on every use after an
  eviction) a tenant's priced footprint (fleet/budget.py) is admitted
  under the shared `FleetBudget`; when the budget must make room it
  evicts cost-weighted-LRU victims through
  `ServeSession.release_device` — device buffers freed, every host
  artifact (pack-plan caches, compiled runners, v3 disk cache) kept
  warm, so the victim's next use re-places buffers with ZERO pack
  re-planning and ZERO XLA recompiles.  Every decision lands in
  FLEET_STATS, never silent.

docs/FLEET.md is the user guide; the CLI surface is
`serve --tenants by_app|N`.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

from libgrape_lite_tpu import obs
from libgrape_lite_tpu.fleet.budget import (
    FLEET_STATS,
    FleetBudget,
    target_footprint,
)


class FleetAdmissionError(RuntimeError):
    """The budget rejected a tenant and nothing could be evicted."""


class TenantTicket:
    """One submitted-but-possibly-not-yet-forwarded query.  Once the
    WRR front forwards it, `request` binds the underlying
    QueryRequest and `result` proxies its outcome."""

    __slots__ = ("tenant", "app_key", "args", "kwargs", "request")

    def __init__(self, tenant: str, app_key: str, args: dict,
                 kwargs: dict):
        self.tenant = tenant
        self.app_key = app_key
        self.args = args
        self.kwargs = kwargs
        self.request = None  # QueryRequest once forwarded

    @property
    def forwarded(self) -> bool:
        return self.request is not None

    @property
    def done(self) -> bool:
        return self.request is not None and self.request.done

    @property
    def result(self):
        return None if self.request is None else self.request.result


class Tenant:
    """One serving principal: its target (session or router), weight,
    pending lane, and accounting."""

    def __init__(self, name: str, target, weight: float = 1.0):
        self.name = name
        self.target = target
        self.weight = float(weight)
        self.pending = deque()  # TenantTickets not yet forwarded
        self.tickets: List[TenantTicket] = []  # every ticket, in order
        self.admitted = False
        self.stats = {
            "submitted": 0, "forwarded": 0, "completed": 0,
            "ok": 0, "failed": 0, "readmits": 0,
        }

    @property
    def evictable(self) -> bool:
        """Routers are never evicted by the manager — their replicas
        are hot by definition (drain/ is their lifecycle surface)."""
        return hasattr(self.target, "release_device")

    def latencies(self) -> List[float]:
        return [
            t.result.latency_s for t in self.tickets
            if t.done and t.result.latency_s
        ]


class FleetManager:
    """N tenants, one budget, one process (see module docstring)."""

    def __init__(self, budget: Optional[FleetBudget] = None):
        self.budget = budget or FleetBudget()
        self.tenants: Dict[str, Tenant] = {}
        self.forward_order: List[str] = []  # tenant name per forward
        # optional control plane (autopilot/): when attached, every
        # manager pump ticks the observe->decide->act loop — scaling,
        # shedding, and caching ride the ordinary serve cadence
        self.autopilot = None

    def attach_autopilot(self, autopilot) -> None:
        """Own an Autoscaler (autopilot/scaler.py): `pump` ticks it
        once per pass, so the control loop runs at the serve cadence
        without its own thread.  The scaler's budget should be THIS
        manager's budget, so scale-ups and tenant admissions price
        against one capacity."""
        self.autopilot = autopilot

    def add_tenant(self, name: str, target, *,
                   weight: float = 1.0) -> Tenant:
        """Register a tenant over `target` (a ServeSession of its own,
        a session SHARED with other tenants — the budget bills the
        fragment once — or a FleetRouter).  Admission under the budget
        is deferred to first use, so adding N tenants never thrashes."""
        if name in self.tenants:
            raise ValueError(f"duplicate tenant {name!r}")
        t = Tenant(name, target, weight)
        self.tenants[name] = t
        return t

    # ---- budget integration ----------------------------------------------

    def _evict_cb(self, victim: str) -> None:
        """Release the victim's device footprint (called by the
        budget mid-admission).  A fragment shared with another
        RESIDENT tenant is left placed — only the victim's private
        buffers go."""
        t = self.tenants[victim]
        frag = getattr(t.target, "fragment", None)
        shared = any(
            getattr(o.target, "fragment", None) is frag
            and o.admitted and o.name != victim
            for o in self.tenants.values()
        ) if frag is not None else False
        t.target.release_device(release_fragment=not shared)
        t.admitted = False
        if obs.tracer().enabled:
            obs.metrics().counter("grape_fleet_evictions_total").inc()

    def ensure_resident(self, name: str) -> None:
        """Admit (or re-admit) a tenant before its work dispatches.
        A re-admission restores the device arrays from the warm host
        artifacts — zero re-planning, zero recompiles — and is
        counted in both the tenant stats and FLEET_STATS."""
        t = self.tenants[name]
        if t.admitted and getattr(t.target, "resident", True):
            self.budget.touch(name)
            return
        was_evicted = t.admitted is False and t.stats["forwarded"] > 0
        # decide FIRST, place buffers second: footprints price from
        # host twins, so the decision needs no device arrays — and a
        # reject must not leave the tenant's fragment re-placed in
        # HBM (the exact over-budget state the budget exists to
        # prevent)
        decision = self.budget.admit(
            name, target_footprint(t.target), weight=t.weight,
            evictable=t.evictable, evict=self._evict_cb,
        )
        if not decision["admitted"]:
            raise FleetAdmissionError(
                f"tenant {name!r} rejected: {decision['reason']} "
                f"(asked {decision['asked_bytes']}B, used "
                f"{decision['used_bytes']}B of {decision['capacity']}B)"
            )
        restore = getattr(t.target, "restore_device", None)
        if restore is not None:
            restore()
        t.admitted = True
        if was_evicted:
            t.stats["readmits"] += 1
            FLEET_STATS._record({"kind": "tenant_readmit", "name": name})
        if obs.tracer().enabled:
            obs.metrics().gauge("grape_fleet_resident_bytes").set(
                self.budget.used_bytes()
            )

    # ---- admission front + fairness ---------------------------------------

    def submit(self, tenant: str, app_key: str,
               args: dict | None = None, **kwargs) -> TenantTicket:
        t = self.tenants[tenant]
        ticket = TenantTicket(tenant, app_key, dict(args or {}), kwargs)
        t.pending.append(ticket)
        t.tickets.append(ticket)
        t.stats["submitted"] += 1
        return ticket

    def _forward(self, t: Tenant, ticket: TenantTicket) -> None:
        self.ensure_resident(t.name)
        self.budget.touch(t.name)
        ticket.request = t.target.submit(
            ticket.app_key, ticket.args, tenant=t.name,
            **ticket.kwargs,
        )
        t.stats["forwarded"] += 1
        self.forward_order.append(t.name)

    def forward_round(self) -> int:
        """One WRR cycle: every tenant with pending work forwards up
        to ceil(weight) tickets, in tenant-insertion order.  Returns
        the number forwarded (0 = nothing pending anywhere)."""
        n = 0
        for t in self.tenants.values():
            quota = max(1, int(-(-t.weight // 1)))
            while quota > 0 and t.pending:
                self._forward(t, t.pending.popleft())
                quota -= 1
                n += 1
        return n

    def _targets(self) -> List:
        """Unique underlying targets (tenants may share a session or a
        router — pump each exactly once per step)."""
        seen, out = set(), []
        for t in self.tenants.values():
            if id(t.target) not in seen:
                seen.add(id(t.target))
                out.append(t.target)
        return out

    def _account(self, results) -> None:
        for t in self.tenants.values():
            done = sum(1 for tk in t.tickets if tk.done)
            new = done - t.stats["completed"]
            if new:
                t.stats["completed"] = done
                t.stats["ok"] = sum(
                    1 for tk in t.tickets if tk.done and tk.result.ok
                )
                t.stats["failed"] = t.stats["completed"] - t.stats["ok"]

    def pump(self) -> List:
        """One fleet step: a WRR forward cycle, then one pump pass
        over every distinct target.  Returns this step's results.
        With an autopilot attached, one control tick runs after the
        pass (never raises — Autoscaler.tick contains its own acts)."""
        self.forward_round()
        out = []
        for target in self._targets():
            out.extend(target.pump(force=True)
                       if _takes_force(target) else target.pump())
        self._account(out)
        if self.autopilot is not None:
            self.autopilot.tick()
        return out

    def drain(self) -> List:
        """Forward + pump until every tenant lane and every target
        queue is empty.  Every pending ticket forwards first (WRR
        cycle by cycle — the queue ORDER is the fairness decision),
        then the targets drain: same-tenant requests coalesce into
        batches while a deep backlog still cannot push another
        tenant's work behind it."""
        out = []
        while any(t.pending for t in self.tenants.values()) or any(
            _target_busy(tg) for tg in self._targets()
        ):
            while self.forward_round():
                pass
            for target in self._targets():
                out.extend(target.drain())
            self._account(out)
        return out

    def snapshot(self) -> dict:
        from libgrape_lite_tpu.serve.queue import latency_summary_ms

        per_tenant = {}
        for t in self.tenants.values():
            lat = latency_summary_ms(t.latencies())
            per_tenant[t.name] = {
                **t.stats,
                "weight": t.weight,
                "resident": bool(
                    t.admitted and getattr(t.target, "resident", True)
                ),
                "p50_ms": lat["p50_ms"],
                "p99_ms": lat["p99_ms"],
            }
        out = {
            "tenants": per_tenant,
            "budget": self.budget.snapshot(),
            "fleet": FLEET_STATS.snapshot(),
        }
        if self.autopilot is not None:
            from libgrape_lite_tpu.autopilot.signals import (
                AUTOPILOT_STATS,
            )

            out["autopilot"] = AUTOPILOT_STATS.snapshot()
        return out


def _takes_force(target) -> bool:
    """ServeSession.pump forwards **kw to queue.pump(force=...);
    FleetRouter.pump takes no arguments."""
    return not hasattr(target, "replicas")


def _target_busy(target) -> bool:
    if hasattr(target, "replicas"):
        return any(
            r.session.queue.pending() or r.pump.inflight()
            for r in target.replicas
        )
    return bool(target.queue.pending())
