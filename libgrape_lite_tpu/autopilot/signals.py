"""Control signals: the autopilot's one read of the telemetry plane.

The autopilot (docs/AUTOPILOT.md) closes the observe->decide->act loop
over the serving fleet, and this module is the OBSERVE leg: one
`SignalReader.read()` snapshots the federation registry
(obs/federation.py) plus the live queue/router objects into a typed,
immutable `ControlSignals` view —

  * per-tenant error-budget burn from the ``slo`` namespace
    (obs/slo.py — breaches / (observed * budget_frac)),
  * queue depth and p50/p99 submit->dispatch wait from the admission
    queues (serve/queue.py records every popped request's wait),
  * per-replica outstanding / routable count / fence from the
    FleetRouter (fleet/router.py).

The reader keeps a bounded WINDOW of recent snapshots (`window`), and
the scaler's decide() demands a signal hold across the WHOLE window
before acting — the hysteresis that keeps one spike from flapping the
fleet up and down (docs/AUTOPILOT.md "Tuning").

Every autopilot counter lives in the federated ``autopilot``
namespace (`AUTOPILOT_STATS` — obs/federation.py EXPECTED), so the
exporter, the flight recorder, and `federation.self_check()` see the
control plane like any other subsystem.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import List, Optional, Tuple

from libgrape_lite_tpu.obs import federation as _federation
from libgrape_lite_tpu.obs.federation import FederatedStats

# importing the slo module registers the "slo" namespace, so a reader
# constructed before any objective is configured still snapshots a
# live (empty) surface instead of a missing one
from libgrape_lite_tpu.obs import slo as _slo  # noqa: F401

#: every decision the control plane takes, counted and bounded — the
#: PUMP_STATS/FLEET_STATS recorded-decision discipline: an autopilot
#: that silently flapped, shed, or refused to scale is visible in one
#: dict instead of a wall-clock mystery
AUTOPILOT_STATS = FederatedStats("autopilot", {
    "ticks": 0,
    "scale_ups": 0,
    "scale_downs": 0,
    "holds": 0,
    "shed": 0,
    "deferred": 0,
    "priced": 0,
    "cache_hits": 0,
    "cache_misses": 0,
    "cache_stores": 0,
    "cache_evictions": 0,
    "cache_invalidations": 0,
    "decisions": [],
})

#: bound on the recorded decision list (the FleetStats.MAX_EVENTS
#: discipline: long-lived processes must not grow without bound)
MAX_DECISIONS = 256


def record_decision(kind: str, **detail) -> None:
    """Append one bounded decision event and bump its counter."""
    counter = {
        "scale_up": "scale_ups",
        "scale_down": "scale_downs",
        "hold": "holds",
        "shed": "shed",
        "defer": "deferred",
    }.get(kind)
    if counter is not None:
        AUTOPILOT_STATS[counter] += 1
    ev = AUTOPILOT_STATS["decisions"]
    ev.append({"kind": kind, **detail})
    if len(ev) > MAX_DECISIONS:
        del ev[: MAX_DECISIONS // 2]


@dataclass(frozen=True)
class ControlSignals:
    """One immutable snapshot of the fleet's control inputs."""

    queue_depth: int            # pending requests across routable replicas
    outstanding: int            # admitted-but-unfinished across replicas
    wait_p50_ms: float          # recent submit->dispatch waits
    wait_p99_ms: float
    max_burn: float             # worst error-budget burn across keys
    burn_by_key: Tuple[Tuple[str, float], ...]  # sorted (key, burn)
    replicas: int               # routable replica count
    total_replicas: int         # routable + draining
    fence: int                  # router graph-version fence

    def burn_of(self, tenant: Optional[str]) -> float:
        """Burn of one tenant's objective key (0.0 when unknown)."""
        key = f"tenant:{tenant}"
        for k, v in self.burn_by_key:
            if k == key:
                return v
        return 0.0


#: how many recent waits feed the p50/p99 signal — a CURRENT load
#: signal, not a lifetime average (a long calm history must not mask
#: a fresh queue-wait spike)
WAIT_WINDOW = 64


class SignalReader:
    """Snapshot router + queues + the federation into ControlSignals.

    `router` is a FleetRouter (or None: a bare session is read as one
    permanent replica via `session=`).  `window` bounds the hysteresis
    deque the scaler's decide() consumes."""

    def __init__(self, router=None, session=None, window: int = 3):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.router = router
        self.session = session
        self.window = int(window)
        self._recent: deque = deque(maxlen=self.window)

    # ---- one snapshot -----------------------------------------------------

    def _sessions(self) -> List:
        if self.router is not None:
            return [r.session for r in self.router.replicas
                    if r.routable]
        return [self.session] if self.session is not None else []

    def read(self) -> ControlSignals:
        """Take one snapshot, append it to the hysteresis window, and
        return it.  Never raises — the control plane reads telemetry,
        it must not become a failure mode of the serve loop."""
        depth = 0
        waits: List[float] = []
        for s in self._sessions():
            q = s.queue
            depth += q.pending()
            waits.extend(q.admission_waits[-WAIT_WINDOW:])
        from libgrape_lite_tpu.serve.queue import latency_summary_ms

        lat = latency_summary_ms(waits)
        slo_view = _federation.snapshot("slo") or {}
        burn = dict(slo_view.get("burn_by_key") or {})
        if self.router is not None:
            routable = [r for r in self.router.replicas if r.routable]
            sig = ControlSignals(
                queue_depth=depth,
                outstanding=sum(r.outstanding for r in routable),
                wait_p50_ms=lat["p50_ms"],
                wait_p99_ms=lat["p99_ms"],
                max_burn=float(slo_view.get("max_burn") or 0.0),
                burn_by_key=tuple(sorted(burn.items())),
                replicas=len(routable),
                total_replicas=len(self.router.replicas),
                fence=self.router.fence,
            )
        else:
            sig = ControlSignals(
                queue_depth=depth,
                outstanding=0,
                wait_p50_ms=lat["p50_ms"],
                wait_p99_ms=lat["p99_ms"],
                max_burn=float(slo_view.get("max_burn") or 0.0),
                burn_by_key=tuple(sorted(burn.items())),
                replicas=1 if self.session is not None else 0,
                total_replicas=1 if self.session is not None else 0,
                fence=0,
            )
        self._recent.append(sig)
        return sig

    # ---- the hysteresis window --------------------------------------------

    @property
    def recent(self) -> Tuple[ControlSignals, ...]:
        """Oldest-first window of the last `window` snapshots."""
        return tuple(self._recent)

    @property
    def saturated(self) -> bool:
        """True once the window is full — decide() refuses to act on a
        part-filled window (one spike is not a trend)."""
        return len(self._recent) >= self.window

    def clear(self) -> None:
        self._recent.clear()
