"""Priced per-query admission: shed or defer tenants past budget.

`fleet/budget.py` already prices SESSIONS from the pack ledgers
(SparseP discipline: price from a cost model, never hand-tune a
watermark).  This module extends the same ledger geometry to
INDIVIDUAL queries: one round of a point query costs what the
fragment's resolved pack plan says it moves/computes
(`spmv_pack.plan_ledger` totals), scaled by the round limit — so the
admission controller knows what a request will cost BEFORE the fleet
pays for it.

The decide step is a pure function over (tenant burn, priced cost):

  * burn below `defer_burn`      -> admit;
  * past budget but under
    `shed_burn` (and affordable) -> **defer**: the request stays
    queued, but `AdmissionQueue._head_batch` serves in-budget tenants
    first — deferred work re-queues BEHIND them, never starves
    (an all-deferred queue still drains);
  * at/over `shed_burn`, or an
    over-budget tenant's request
    pricier than `max_cost`      -> **shed**: a loud failed
    ServeResult with ``reason=shed_over_budget``, counted and
    returned through `take_expired` exactly like `deadline_expired`
    — and it burns the tenant's error budget via `slo.observe`, like
    every other failure (the PR's queue.py bugfix).

Every decision is recorded in the federated ``autopilot`` namespace
(signals.record_decision), never silent.  docs/AUTOPILOT.md covers
the pricing model and tuning.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from libgrape_lite_tpu.autopilot.signals import (
    AUTOPILOT_STATS,
    record_decision,
)

#: rounds assumed for an unbounded request (max_rounds=None) — the
#: pricing must stay finite; callers with a real limit are priced
#: exactly
DEFAULT_PRICED_ROUNDS = 16


def query_cost(fragment, max_rounds: Optional[int] = None) -> float:
    """Estimated cost of one point query on `fragment`, in
    HBM-bytes-per-query: the resolved pack plans' per-round ledger
    bytes (`spmv_pack.plan_ledger` — the SAME totals the HBM budget
    prices sessions from) times the round limit.  Falls back to the
    fragment's CSR byte size per round when no plan has been resolved
    yet (a fresh session priced before its first query)."""
    rounds = int(max_rounds) if max_rounds else DEFAULT_PRICED_ROUNDS
    per_round = 0.0
    try:
        from libgrape_lite_tpu.ops.spmv_pack import (
            _frag_cache,
            plan_ledger,
        )

        for plan in _frag_cache(fragment).values():
            try:
                totals = plan_ledger(plan)["totals"]
                per_round = max(
                    per_round, float(totals.get("hbm_bytes", 0))
                )
            except Exception:
                continue
    except Exception:
        per_round = 0.0
    if per_round <= 0.0:
        from libgrape_lite_tpu.fleet.budget import fragment_bytes

        per_round = float(fragment_bytes(fragment))
    AUTOPILOT_STATS["priced"] += 1
    return per_round * rounds


def query_wall_s(fragment, max_rounds: Optional[int] = None,
                 profile=None) -> float:
    """Estimated WALL seconds of one point query on `fragment` under
    `profile` (default: the active RateProfile) — the widest resolved
    pack plan's full ledger columns priced through the profile's
    additive wall model, times the round limit.  0.0 when no plan has
    been resolved yet (the byte fallback has no op columns to price);
    byte-based `query_cost` stays the load-shaped metric, this is the
    latency-shaped one a fitted profile keeps honest."""
    from libgrape_lite_tpu.ops.calibration import active_profile

    p = profile or active_profile()
    rounds = int(max_rounds) if max_rounds else DEFAULT_PRICED_ROUNDS
    best = 0.0
    try:
        from libgrape_lite_tpu.ops.spmv_pack import (
            _frag_cache,
            plan_ledger,
        )

        for plan in _frag_cache(fragment).values():
            try:
                totals = plan_ledger(plan)["totals"]
            except Exception:
                continue
            best = max(best, p.wall_s(totals))
    except Exception:
        return 0.0
    return best * rounds


@dataclass(frozen=True)
class AdmissionConfig:
    """Thresholds of the shed/defer policy (docs/AUTOPILOT.md)."""

    # burn >= 1.0 means the error budget is spent; defer starts there
    defer_burn: float = 1.0
    # a tenant burning at 2x budget no longer gets device time at all
    shed_burn: float = 2.0
    # optional absolute cost ceiling (HBM bytes/query): an OVER-BUDGET
    # tenant's request pricier than this sheds instead of deferring —
    # in-budget tenants are never cost-gated (None disables)
    max_cost: Optional[float] = None
    # optional absolute WALL ceiling (seconds/query, priced from the
    # active RateProfile via `query_wall_s`): same over-budget-only
    # semantics as max_cost (None disables — the shipped default)
    max_cost_s: Optional[float] = None

    def __post_init__(self):
        if self.defer_burn <= 0:
            raise ValueError(
                f"defer_burn must be > 0, got {self.defer_burn}"
            )
        if self.shed_burn < self.defer_burn:
            raise ValueError(
                f"shed_burn ({self.shed_burn}) must be >= defer_burn "
                f"({self.defer_burn})"
            )


def decide_admission(burn: float, cost: float,
                     cfg: AdmissionConfig,
                     cost_s: float = 0.0) -> str:
    """Pure decide: 'admit' | 'defer' | 'shed' for one request of a
    tenant burning `burn` with priced cost `cost` (HBM bytes) and
    modeled wall `cost_s` (seconds, 0.0 = unpriced)."""
    if burn < cfg.defer_burn:
        return "admit"
    if burn >= cfg.shed_burn:
        return "shed"
    if cfg.max_cost is not None and cost > cfg.max_cost:
        return "shed"
    if cfg.max_cost_s is not None and cost_s > cfg.max_cost_s:
        return "shed"
    return "defer"


class AdmissionController:
    """The queue-side hook: `review(req)` prices one pending request,
    reads its tenant's burn from the SLO surface, and returns the
    decide verdict.  Wire with ``queue.admission = ctl.review`` (the
    ServeSession/FleetRouter attach helpers do this) — the queue's
    `_pop_ready` sweep then sheds/defers before coalescing.

    `cost_of` defaults to `query_cost` over `fragment`; pass a
    callable for tests (pure decide tables need no fragment)."""

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 fragment=None,
                 cost_of: Optional[Callable] = None):
        self.config = config or AdmissionConfig()
        self._fragment = fragment
        self._cost_of = cost_of

    def burn_of(self, tenant: Optional[str]) -> float:
        """Current burn of one tenant's objective key (0.0 when the
        tenant has no objective or nothing was observed yet)."""
        from libgrape_lite_tpu.obs.slo import SLO_STATS

        if tenant is None:
            return 0.0
        burn = SLO_STATS.get("burn_by_key") or {}
        return float(burn.get(f"tenant:{tenant}", 0.0))

    def cost_of(self, req) -> float:
        if self._cost_of is not None:
            return float(self._cost_of(req))
        if self._fragment is None:
            return 0.0
        return query_cost(self._fragment, req.max_rounds)

    def wall_of(self, req, profile) -> float:
        if self._cost_of is not None or self._fragment is None:
            return 0.0
        return query_wall_s(self._fragment, req.max_rounds,
                            profile=profile)

    def review(self, req) -> str:
        """'admit' | 'defer' | 'shed' for one queued request.  Records
        shed/defer decisions (admits are the steady state and only
        counted implicitly); never raises — an admission failure must
        not wedge the queue head."""
        from libgrape_lite_tpu.ops.calibration import active_profile

        try:
            prof = active_profile()
            burn = self.burn_of(req.tenant)
            cost = self.cost_of(req)
            cost_s = self.wall_of(req, prof)
            verdict = decide_admission(burn, cost, self.config,
                                       cost_s=cost_s)
        except Exception:
            return "admit"
        if verdict != "admit":
            record_decision(
                verdict, tenant=req.tenant or "", app=req.app_key,
                burn=round(burn, 4), cost=round(cost, 1),
                cost_s=round(cost_s, 6), profile=prof.label(),
            )
        return verdict
