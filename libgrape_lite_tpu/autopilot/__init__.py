"""autopilot/ — the self-operating fleet control plane (ROADMAP 2).

Closes the observe -> decide -> act loop the telemetry plane (PR 15)
and the fleet machinery (PR 13) left open:

  * `signals`   — SignalReader/ControlSignals: one typed snapshot of
    burn, queue depth/waits, and replica load, with the hysteresis
    window; the federated AUTOPILOT_STATS namespace.
  * `scaler`    — Autoscaler: pure `decide` over the window, `act`
    strictly through drain/rejoin/replicate under the HBM budget.
  * `admission` — AdmissionController: ledger-priced per-query cost,
    shed (`reason=shed_over_budget`) or defer tenants past their
    error budget.
  * `cache`     — ResultCache: fence-epoch result cache for point
    queries; a hit skips the device and still hits the SLO/trace
    surfaces.

docs/AUTOPILOT.md is the user guide; the CLI surface is
`serve --autopilot [--min_replicas N --max_replicas M
--cache_entries K]`.
"""

from libgrape_lite_tpu.autopilot.admission import (
    AdmissionConfig,
    AdmissionController,
    decide_admission,
    query_cost,
)
from libgrape_lite_tpu.autopilot.cache import (
    CACHE_KEY_FIELDS,
    ResultCache,
)
from libgrape_lite_tpu.autopilot.scaler import (
    Autoscaler,
    Decision,
    ScalerConfig,
    decide,
)
from libgrape_lite_tpu.autopilot.signals import (
    AUTOPILOT_STATS,
    ControlSignals,
    SignalReader,
    record_decision,
)

__all__ = [
    "AUTOPILOT_STATS",
    "AdmissionConfig",
    "AdmissionController",
    "Autoscaler",
    "CACHE_KEY_FIELDS",
    "ControlSignals",
    "Decision",
    "ResultCache",
    "ScalerConfig",
    "SignalReader",
    "decide",
    "decide_admission",
    "query_cost",
    "record_decision",
]
