"""Fence-epoch result cache: point queries that skip the device.

Point queries (the `batch_query_key` contract — sssp/bfs/khop/ppr/cn
sources) repeat heavily in user-shaped traffic, and a repeat of an
already-answered (graph, query) pair needs NO device work at all —
the cheapest qps multiplier available (ROADMAP item 2).

Soundness rests on two existing contracts:

  * the **key** carries every field of `policy.compat_key` (app, round
    limit, guard policy, non-lane args, lane-arg presence, tenant) —
    the same structural identity that gates batching; two requests
    with equal compat keys would compile to the SAME runner, so equal
    keys + equal source imply byte-identical answers.  grape-lint R9
    (`cache-key-completeness`) pins every call site to this shape.
  * the **epoch** is the fleet's graph-version fence
    (fleet/router.py; a bare session's ingest counter stands in for
    it).  Every ingest bumps the fence BEHIND a drain barrier, so an
    entry stored at fence F was computed on graph version F, a lookup
    at fence F' > F structurally misses, and `invalidate_stale(F')`
    drops the dead epoch wholesale.

A hit is not invisible: the serving layer still mints a ServeResult
with stage stamps, emits a `serve_query` span with ``cached=true``,
and runs `slo.observe` — SLOs and the trace see cached traffic like
any other (serve/session.py `_deliver_cached`).

Counters ride the federated ``autopilot`` namespace
(signals.AUTOPILOT_STATS) next to per-instance hit/miss fields.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

from libgrape_lite_tpu.autopilot.signals import AUTOPILOT_STATS

#: the identity a cache key must carry — every `policy.compat_key`
#: field plus the lane source and the fence epoch.  grape-lint R9
#: (analysis/astlint.py) anchors on this contract: a lookup()/store()
#: call site whose arguments do not name a compat key, a source, and
#: a fence is flagged as an incomplete cache key.
CACHE_KEY_FIELDS: Tuple[str, ...] = ("compat", "source", "fence")


class ResultCache:
    """Bounded LRU of (compat_key, source, fence) -> finished result.

    `capacity` bounds entries (LRU eviction, counted).  Thread-safe:
    the serving feeder thread may probe while the pump stores."""

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._entries: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0
        self.invalidations = 0

    def __len__(self) -> int:
        return len(self._entries)

    # ---- the keyed surface (grape-lint R9 audits every call site) ---------

    @staticmethod
    def _key(compat, source, fence):
        return (compat, source, int(fence))

    def lookup(self, compat, source, fence) -> Optional[tuple]:
        """Return `(values, rounds, terminate_code)` for a finished
        query of this exact identity at this fence, or None.  An
        unhashable key (exotic arg values) is a miss, never a raise —
        the cache must not become a failure mode of admission."""
        try:
            k = self._key(compat, source, fence)
            with self._lock:
                ent = self._entries.get(k)
                if ent is not None:
                    self._entries.move_to_end(k)
        except TypeError:
            ent = None
        if ent is None:
            self.misses += 1
            AUTOPILOT_STATS["cache_misses"] += 1
            return None
        self.hits += 1
        AUTOPILOT_STATS["cache_hits"] += 1
        return ent

    def store(self, compat, source, fence, result) -> bool:
        """Store one OK result under its full identity.  `result` is a
        ServeResult (values resolved lazily here — by store time the
        harvest already synced them).  Returns False when the result
        is not cacheable (failed, value-less, unhashable key)."""
        if result is None or not result.ok:
            return False
        if getattr(result, "deferred", False):
            # a lazy-harvest result (serve/pipeline.py
            # eager_values=False) is not forced here — storing must
            # never un-defer the very extraction the window hides
            return False
        try:
            vals = result.values
        except Exception:
            return False
        if vals is None:
            return False
        try:
            k = self._key(compat, source, fence)
            with self._lock:
                self._entries[k] = (
                    vals, result.rounds, result.terminate_code,
                )
                self._entries.move_to_end(k)
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
                    self.evictions += 1
                    AUTOPILOT_STATS["cache_evictions"] += 1
        except TypeError:
            return False
        self.stores += 1
        AUTOPILOT_STATS["cache_stores"] += 1
        return True

    # ---- epoch invalidation -----------------------------------------------

    def invalidate_stale(self, fence) -> int:
        """Drop every entry whose epoch differs from `fence` — the
        wholesale death of a stale epoch after an ingest bumped the
        fence (fleet/router.py calls this at the end of `ingest`).
        Returns the number of entries dropped (counted)."""
        fence = int(fence)
        with self._lock:
            stale = [k for k in self._entries if k[2] != fence]
            for k in stale:
                del self._entries[k]
        if stale:
            self.invalidations += len(stale)
            AUTOPILOT_STATS["cache_invalidations"] += len(stale)
        return len(stale)

    def snapshot(self) -> dict:
        return {
            "entries": len(self._entries),
            "capacity": self.capacity,
            "hits": self.hits,
            "misses": self.misses,
            "stores": self.stores,
            "evictions": self.evictions,
            "invalidations": self.invalidations,
        }
