"""Autoscaler: burn/depth signals in, drain/rejoin/replicate out.

The DECIDE leg is a pure function (`decide`) over the SignalReader's
hysteresis window — testable without a fleet, like the admission
decide.  The ACT leg (`Autoscaler.act`) only ever moves the fleet
through the shipped zero-drop machinery:

  * **scale up** — rejoin a previously-drained replica when one is
    parked (its catch-up log replays to the fence — the cheap path:
    every host artifact is still warm), else replicate a fresh
    fragment from a live replica (`replicate_fragment`: deterministic
    rebuild from the retained edge list, so the newcomer answers
    byte-identically) and `FleetRouter.add_replica` it at the current
    fence.  A pending dyn overlay is folded first (a counted forced
    repack) so the retained edge list IS the current graph.
  * **scale down** — `begin_drain` WITHOUT rejoin: the replica
    finishes every admitted query (zero drops), stops routing, and
    parks warm with a catch-up log — which is exactly what makes the
    next scale-up cheap.  The last routable replica can never be
    drained (fleet/drain.py guards it; decide holds at min_replicas
    before it gets there).

Guard rails: min/max replica bounds, a cooldown (ticks) after every
act so the fleet settles before the next move, the HBM budget
(fleet/budget.py — a scale-up that does not fit is a recorded hold,
never an OOM), and the hysteresis window (one spike never flaps the
fleet).  Every decision — scale_up / scale_down / hold, with its
reason — is recorded in the federated ``autopilot`` namespace.

docs/AUTOPILOT.md diagrams the loop and names the tuning knobs.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Callable, Optional, Sequence

from libgrape_lite_tpu.autopilot.signals import (
    ControlSignals,
    SignalReader,
    record_decision,
)


@dataclass(frozen=True)
class ScalerConfig:
    """Knobs of the scaling policy (docs/AUTOPILOT.md "Tuning")."""

    min_replicas: int = 1
    max_replicas: int = 4
    # hysteresis: the up/down condition must hold across this many
    # consecutive signal reads before the scaler acts
    window: int = 3
    # ticks to sit out after any act (the fleet needs a few pumps to
    # absorb a topology change before the signals mean anything)
    cooldown_ticks: int = 4
    # scale-up pressure: queue depth PER ROUTABLE REPLICA above this
    # is overload ...
    up_queue_depth: int = 8
    # ... or the p99 submit->dispatch wait above this (ms; 0 disables)
    up_wait_p99_ms: float = 0.0
    # ... or any tenant/app burning past this error-budget multiple
    # (0 disables; burn >= 1.0 means the budget is spent)
    up_burn: float = 0.0
    # scale-down calm: total depth at/below this AND nothing burning
    down_queue_depth: int = 0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})"
            )
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.cooldown_ticks < 0:
            raise ValueError(
                f"cooldown_ticks must be >= 0, got {self.cooldown_ticks}"
            )


@dataclass(frozen=True)
class Decision:
    """One decide() verdict: what to do, why, and the replica target."""

    action: str      # "scale_up" | "scale_down" | "hold"
    reason: str
    replicas: int    # routable count the decision saw
    target: int      # routable count the action aims for


def _overloaded(sig: ControlSignals, cfg: ScalerConfig) -> bool:
    per = sig.queue_depth / max(1, sig.replicas)
    if per > cfg.up_queue_depth:
        return True
    if cfg.up_wait_p99_ms and sig.wait_p99_ms > cfg.up_wait_p99_ms:
        return True
    if cfg.up_burn and sig.max_burn >= cfg.up_burn:
        return True
    return False


def _calm(sig: ControlSignals, cfg: ScalerConfig) -> bool:
    if sig.queue_depth > cfg.down_queue_depth:
        return False
    if sig.outstanding > 0:
        return False
    if cfg.up_burn and sig.max_burn >= cfg.up_burn:
        return False
    return True


def decide(window: Sequence[ControlSignals], cfg: ScalerConfig,
           *, cooldown: int = 0) -> Decision:
    """Pure policy: the hysteresis window in, one Decision out.

    `window` is oldest-first (SignalReader.recent); `cooldown` is the
    ticks left to sit out (an act younger than cooldown_ticks)."""
    if not window:
        return Decision("hold", "no_signals", 0, 0)
    cur = window[-1]
    n = cur.replicas
    if cooldown > 0:
        return Decision("hold", "cooldown", n, n)
    if len(window) < cfg.window:
        return Decision("hold", "window_filling", n, n)
    recent = list(window)[-cfg.window:]
    if all(_overloaded(s, cfg) for s in recent):
        if n >= cfg.max_replicas:
            return Decision("hold", "at_max_replicas", n, n)
        per = cur.queue_depth / max(1, n)
        if cfg.up_burn and cur.max_burn >= cfg.up_burn:
            why = f"burn {cur.max_burn:.2f} >= {cfg.up_burn}"
        elif per > cfg.up_queue_depth:
            why = (f"queue depth {cur.queue_depth} over "
                   f"{cfg.up_queue_depth}/replica x {n}")
        else:
            why = (f"wait p99 {cur.wait_p99_ms}ms > "
                   f"{cfg.up_wait_p99_ms}ms")
        return Decision("scale_up", why, n, n + 1)
    if all(_calm(s, cfg) for s in recent):
        if n <= cfg.min_replicas:
            return Decision("hold", "at_min_replicas", n, n)
        return Decision("scale_down", "sustained_idle", n, n - 1)
    return Decision("hold", "in_band", n, n)


class Autoscaler:
    """Observe (SignalReader) -> decide (pure) -> act (fleet moves).

    `session_factory(fragment)` builds a replica ServeSession around a
    freshly replicated fragment — without it, scale-up can only rejoin
    previously-drained replicas.  `budget` (FleetBudget) gates fresh
    replicas under the HBM capacity."""

    def __init__(self, router, config: Optional[ScalerConfig] = None,
                 *, session_factory: Optional[Callable] = None,
                 budget=None, reader: Optional[SignalReader] = None):
        self.router = router
        self.config = config or ScalerConfig()
        self.reader = reader or SignalReader(
            router, window=self.config.window
        )
        self._factory = session_factory
        self.budget = budget
        self.cooldown = 0

    # ---- the loop ---------------------------------------------------------

    def tick(self) -> Decision:
        """One control iteration: read, decide, act, record.  The
        serve loop calls this between pumps; it never raises (an act
        that fails becomes a recorded hold)."""
        from libgrape_lite_tpu.autopilot.signals import AUTOPILOT_STATS

        AUTOPILOT_STATS["ticks"] += 1
        self.reader.read()
        d = decide(self.reader.recent, self.config,
                   cooldown=self.cooldown)
        if self.cooldown > 0:
            self.cooldown -= 1
        if d.action != "hold":
            d = self.act(d)
        record_decision(d.action, reason=d.reason,
                        replicas=d.replicas, target=d.target,
                        fence=self.router.fence)
        return d

    # ---- the actuators ----------------------------------------------------

    def _routable(self):
        return [r for r in self.router.replicas if r.routable]

    def act(self, decision: Decision) -> Decision:
        """Execute one non-hold decision through the zero-drop fleet
        machinery.  Returns the decision actually taken (an act that
        cannot proceed — budget, guards, a failed replicate — demotes
        to a recorded hold)."""
        try:
            if decision.action == "scale_up":
                return self._scale_up(decision)
            if decision.action == "scale_down":
                return self._scale_down(decision)
        except Exception as e:  # the loop must outlive a failed act
            return replace(
                decision, action="hold",
                reason=f"act_failed: {type(e).__name__}: {e}",
            )
        return decision

    def _scale_up(self, decision: Decision) -> Decision:
        parked = [r for r in self.router.replicas if not r.routable]
        if parked:
            idx = parked[0].idx
            self.router.rejoin(idx)
            self.cooldown = self.config.cooldown_ticks
            return replace(
                decision, reason=decision.reason + f"; rejoined r{idx}"
            )
        if self._factory is None:
            return replace(decision, action="hold",
                           reason="no_session_factory")
        src = self._routable()[0].session
        if self.budget is not None and self.budget.capacity:
            from libgrape_lite_tpu.fleet.budget import session_footprint

            est = session_footprint(src).total
            if self.budget.used_bytes() + est > self.budget.capacity:
                return replace(
                    decision, action="hold",
                    reason=f"hbm_budget: +{est}B over capacity",
                )
        if src.dyn is not None and src.dyn.overlay_count:
            # fold the pending overlay so the retained edge list IS
            # the current graph — a counted forced repack on the
            # source, not a silent stale replica
            src.ingest([], force_repack=True)
        from libgrape_lite_tpu.fragment.mutation import (
            replicate_fragment,
        )

        sess = self._factory(replicate_fragment(src.fragment))
        r = self.router.add_replica(sess)
        self.cooldown = self.config.cooldown_ticks
        return replace(
            decision, reason=decision.reason + f"; added r{r.idx}"
        )

    def _scale_down(self, decision: Decision) -> Decision:
        routable = self._routable()
        if len(routable) <= max(1, self.config.min_replicas):
            return replace(decision, action="hold",
                           reason="at_min_replicas")
        victim = routable[-1]  # highest index: LIFO, deterministic
        self.router.begin_drain(victim.idx)
        self.cooldown = self.config.cooldown_ticks
        return replace(
            decision,
            reason=decision.reason + f"; drained r{victim.idx}",
        )
