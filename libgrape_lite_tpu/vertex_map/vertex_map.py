"""Global oid <-> gid directory.

Re-design of `grape/vertex_map/vertex_map.h:32-557`: a partitioner plus a
per-fragment idxer array; gid = IdParser(fid, lid).  Batch-vectorised for
the host load path.  Unlike the reference (one VertexMap per MPI process,
kept in sync by construction), the TPU build runs load on a single host
process per slice, so the directory is simply shared.
"""

from __future__ import annotations

from typing import List

import numpy as np

from libgrape_lite_tpu.utils.id_parser import IdParser
from libgrape_lite_tpu.vertex_map.idxer import IdxerBase, make_idxer
from libgrape_lite_tpu.vertex_map.partitioner import PartitionerBase


class VertexMap:
    def __init__(
        self,
        partitioner: PartitionerBase,
        idxers: List[IdxerBase],
        id_parser: IdParser,
    ):
        self.partitioner = partitioner
        self.idxers = idxers
        self.id_parser = id_parser
        self.fnum = len(idxers)
        self._string_keyed = None

    def is_string_keyed(self) -> bool:
        """True when oids are strings (--string_id graphs); cached."""
        if self._string_keyed is None:
            self._string_keyed = any(
                ix.size()
                and np.asarray(ix.get_oid(np.array([0]))).dtype.kind in "OUS"
                for ix in self.idxers
            )
        return self._string_keyed

    @classmethod
    def build(
        cls,
        oids: np.ndarray,
        partitioner: PartitionerBase,
        idxer_type: str = "hashmap",
        id_parser: IdParser | None = None,
    ) -> "VertexMap":
        """Builder (reference `VertexMapBuilder`, `vertex_map.h:146-220`):
        partition the oid universe, then build one idxer per fragment.
        lids within a fragment follow oid arrival order (vfile order),
        matching the reference's hashmap idxer."""
        fnum = partitioner.get_fnum()
        oids_arr = np.asarray(oids)
        if len(oids_arr) and len(np.unique(oids_arr)) != len(oids_arr):
            raise ValueError(
                "duplicate vertex oids in the vertex file — if the ids "
                "are strings, load with string_id=True (--string_id); a "
                "string file parsed as integers collapses to zeros"
            )
        fids = partitioner.get_partition_id(oids)
        idxers = []
        max_ivnum = 0
        for f in range(fnum):
            f_oids = np.asarray(oids)[fids == f]
            idxers.append(make_idxer(idxer_type, f_oids))
            max_ivnum = max(max_ivnum, len(f_oids))
        if id_parser is None:
            id_parser = IdParser(fnum, max(max_ivnum * 2, 2))
        return cls(partitioner, idxers, id_parser)

    # ---- directory queries (reference vertex_map.h:44-142) ----

    def get_fragment_id(self, oids: np.ndarray) -> np.ndarray:
        return self.partitioner.get_partition_id(oids)

    def get_gid(self, oids: np.ndarray) -> np.ndarray:
        """oid -> gid; -1 for unknown."""
        oids = np.asarray(oids)
        fids = self.partitioner.get_partition_id(oids)
        gids = np.full(len(oids), -1, dtype=np.int64)
        for f in range(self.fnum):
            m = fids == f
            if not m.any():
                continue
            lids = self.idxers[f].get_index(oids[m])
            g = self.id_parser.generate(np.int64(f), lids)
            g[lids < 0] = -1
            gids[m] = g
        return gids

    def get_oid(self, gids: np.ndarray) -> np.ndarray:
        gids = np.asarray(gids)
        fids = self.id_parser.get_fid(gids)
        lids = self.id_parser.get_lid(gids)
        res = (
            np.full(len(gids), -1, dtype=object)
            if self.is_string_keyed()
            else np.full(len(gids), -1, dtype=np.int64)
        )
        for f in range(self.fnum):
            m = fids == f
            if not m.any():
                continue
            res[m] = np.asarray(self.idxers[f].get_oid(lids[m]))
        return res

    def inner_vertex_num(self, fid: int) -> int:
        return self.idxers[fid].size()

    def total_vertex_num(self) -> int:
        return sum(ix.size() for ix in self.idxers)

    def inner_oids(self, fid: int) -> np.ndarray:
        lids = np.arange(self.idxers[fid].size())
        return np.asarray(self.idxers[fid].get_oid(lids))
