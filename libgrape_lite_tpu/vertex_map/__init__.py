from libgrape_lite_tpu.vertex_map.partitioner import (
    HashPartitioner,
    MapPartitioner,
    SegmentedPartitioner,
    VCPartitioner,
    make_partitioner,
)
from libgrape_lite_tpu.vertex_map.idxer import (
    HashMapIdxer,
    SortedArrayIdxer,
    LocalIdxer,
    PerfectHashIdxer,
    make_idxer,
)
from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
