"""Vertex partitioners: oid -> fragment id.

Re-design of `grape/vertex_map/partitioner.h:66-330`.  All partitioners
here are *vectorised*: they map whole numpy arrays of oids to fid arrays
in one shot (the reference maps one oid at a time per CPU thread; on the
TPU host path we batch).  Selected by `--partitioner_type`
(reference `examples/analytical_apps/flags.cc:46-48`, default "map").
"""

from __future__ import annotations

import numpy as np


class PartitionerBase:
    type_name = "base"

    def get_partition_id(self, oids: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def get_fnum(self) -> int:
        return self.fnum


class HashPartitioner(PartitionerBase):
    """fid = hash(oid) % fnum (reference `partitioner.h:66-100`).

    The reference hashes with `std::hash` on the integer itself for
    integral oids; we use a murmur-style mix (reference
    `grape/types.h:163-197` uses a murmur hasher for its idxers) so that
    consecutive ids spread across shards.
    """

    type_name = "hash"

    def __init__(self, fnum: int):
        self.fnum = fnum

    def get_partition_id(self, oids: np.ndarray) -> np.ndarray:
        arr = np.asarray(oids)
        if arr.dtype == object or arr.dtype.kind in "US":
            # string oids (reference hashes the string bytes): stable
            # crc32, hashed once per UNIQUE id, mapped back with
            # searchsorted so endpoint arrays (O(E)) stay vectorised
            import zlib

            uniq, inv = np.unique(arr, return_inverse=True)
            h = np.fromiter(
                (zlib.crc32(str(o).encode()) % self.fnum for o in uniq.tolist()),
                dtype=np.int64, count=len(uniq),
            )
            return h[inv]
        x = arr.astype(np.uint64, copy=True)
        # 64-bit murmur3 finalizer
        x ^= x >> np.uint64(33)
        x *= np.uint64(0xFF51AFD7ED558CCD)
        x ^= x >> np.uint64(33)
        x *= np.uint64(0xC4CEB9FE1A85EC53)
        x ^= x >> np.uint64(33)
        return (x % np.uint64(self.fnum)).astype(np.int64)


class MapPartitioner(PartitionerBase):
    """Explicit oid->fid map built from the vfile order: contiguous blocks
    of ceil(n/fnum) vertices (reference `partitioner.h:102-174`, block
    assignment at `:115-126`). This is the reference's default."""

    type_name = "map"

    def __init__(self, fnum: int, oid_list: np.ndarray):
        self.fnum = fnum
        n = len(oid_list)
        frag_vnum = (n + fnum - 1) // fnum
        fids = (np.arange(n, dtype=np.int64) // frag_vnum).astype(np.int64)
        self._o2f = dict(zip(np.asarray(oid_list).tolist(), fids.tolist()))

    def get_partition_id(self, oids: np.ndarray) -> np.ndarray:
        o2f = self._o2f
        return np.fromiter(
            (o2f.get(o, -1) for o in np.asarray(oids).tolist()),
            dtype=np.int64,
            count=len(oids),
        )


class SegmentedPartitioner(PartitionerBase):
    """Range partitioner over sorted oid space
    (reference `partitioner.h:175-243`): fid = searchsorted(boundaries, oid).
    """

    type_name = "segment"

    def __init__(self, fnum: int, sorted_oids: np.ndarray):
        self.fnum = fnum
        n = len(sorted_oids)
        frag_vnum = (n + fnum - 1) // fnum
        cuts = [sorted_oids[min(i * frag_vnum, n - 1)] for i in range(1, fnum)]
        self.boundaries = np.asarray(cuts)

    def get_partition_id(self, oids: np.ndarray) -> np.ndarray:
        return np.searchsorted(self.boundaries, np.asarray(oids), side="right").astype(
            np.int64
        )


class ExplicitPartitioner(PartitionerBase):
    """Arbitrary precomputed oid->fid assignment, vectorised via binary
    search over the sorted oid table.  Shared by the rebalancer and the
    deserialization path (any partitioner is reconstructible as one)."""

    type_name = "explicit"

    def __init__(self, oids: np.ndarray, fids: np.ndarray):
        self.fnum = int(np.asarray(fids).max()) + 1 if len(fids) else 1
        order = np.argsort(oids, kind="stable")
        self._sorted_oids = np.asarray(oids)[order]
        self._sorted_fids = np.asarray(fids)[order]

    def get_partition_id(self, oids: np.ndarray) -> np.ndarray:
        q = np.asarray(oids)
        pos = np.searchsorted(self._sorted_oids, q)
        pos_c = np.clip(pos, 0, len(self._sorted_oids) - 1)
        ok = self._sorted_oids[pos_c] == q
        return np.where(ok, self._sorted_fids[pos_c], -1).astype(np.int64)


class VCPartitioner(PartitionerBase):
    """2-D vertex-cut partitioner (reference `partitioner.h:269-330`):
    requires fnum = k^2; edge (src, dst) lands on fragment
    (src_chunk * k + dst_chunk); vertex masters are 1-D chunks.
    """

    type_name = "vc"

    def __init__(self, fnum: int, vnum: int):
        k = int(round(np.sqrt(fnum)))
        if k * k != fnum:
            raise ValueError(f"VCPartitioner needs fnum=k^2, got {fnum}")
        self.fnum = fnum
        self.k = k
        self.vnum = vnum
        self.chunk = (vnum + k - 1) // k

    def vertex_chunk(self, oids: np.ndarray) -> np.ndarray:
        return np.minimum(np.asarray(oids) // self.chunk, self.k - 1).astype(np.int64)

    def get_partition_id(self, oids: np.ndarray) -> np.ndarray:
        # master fragment of a vertex: diagonal placement (chunk, chunk)
        c = self.vertex_chunk(oids)
        return c * self.k + c

    def get_edge_partition(self, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
        return self.vertex_chunk(src) * self.k + self.vertex_chunk(dst)


def make_partitioner(kind: str, fnum: int, oid_list=None, vnum=None):
    if kind == "hash":
        return HashPartitioner(fnum)
    if kind == "map":
        if oid_list is None:
            raise ValueError("map partitioner needs the vfile oid list")
        return MapPartitioner(fnum, oid_list)
    if kind == "segment":
        if oid_list is None:
            raise ValueError("segment partitioner needs the oid list")
        return SegmentedPartitioner(fnum, np.sort(np.asarray(oid_list)))
    if kind == "vc":
        return VCPartitioner(fnum, vnum)
    raise ValueError(f"unknown partitioner type {kind!r}")
