"""Per-fragment oid -> lid indexers.

Re-design of `grape/vertex_map/idxers/` (hashmap_idxer.h, sorted_array_idxer.h,
local_idxer.h, pthash_idxer.h; dispatch at `idxers.h:26-110`).  Selected by
`--idxer_type` (reference `flags.cc:49-51`, default "hashmap").

All indexers are batch-oriented: `get_index(oids) -> lids` over numpy
arrays.  The heavy lookup during graph load happens on the host; the
device side never sees oids (only dense lids/gids).
"""

from __future__ import annotations

import numpy as np


class IdxerBase:
    type_name = "base"

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        """Return lids; -1 for unknown oids."""
        raise NotImplementedError

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError


class HashMapIdxer(IdxerBase):
    """Dict-backed oid->lid (reference `hashmap_idxer.h`, built on the
    flat_hash_map `IdIndexer`, `grape/graph/id_indexer.h`)."""

    type_name = "hashmap"

    def __init__(self, oids: np.ndarray):
        self._oids = np.asarray(oids)
        self._o2l = {o: i for i, o in enumerate(self._oids.tolist())}

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        o2l = self._o2l
        return np.fromiter(
            (o2l.get(o, -1) for o in np.asarray(oids).tolist()),
            dtype=np.int64,
            count=len(oids),
        )

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        return self._oids[np.asarray(lids)]

    def size(self) -> int:
        return len(self._oids)

    def extend(self, new_oids: np.ndarray) -> None:
        """Append vertices (mutation path, reference `vertex_map.h:146-220`)."""
        start = len(self._oids)
        self._oids = np.concatenate([self._oids, np.asarray(new_oids)])
        for i, o in enumerate(np.asarray(new_oids).tolist()):
            self._o2l.setdefault(o, start + i)


class SortedArrayIdxer(IdxerBase):
    """Binary-search over sorted oids (reference `sorted_array_idxer.h`).
    lid = rank in sorted order; O(log n) lookups, zero hash memory."""

    type_name = "sorted_array"

    def __init__(self, oids: np.ndarray):
        self._oids = np.sort(np.asarray(oids))

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        q = np.asarray(oids)
        pos = np.searchsorted(self._oids, q)
        pos_c = np.clip(pos, 0, len(self._oids) - 1)
        ok = self._oids[pos_c] == q
        return np.where(ok, pos_c, -1).astype(np.int64)

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        return self._oids[np.asarray(lids)]

    def size(self) -> int:
        return len(self._oids)


class LocalIdxer(IdxerBase):
    """Lazy idxer for vfile-less loading (reference `local_idxer.h`):
    oids are added on first sight, in arrival order."""

    type_name = "local"

    def __init__(self, oids=None):
        self._o2l = {}
        self._oids = []
        if oids is not None:
            self.add(oids)

    def add(self, oids: np.ndarray) -> None:
        for o in np.asarray(oids).tolist():
            if o not in self._o2l:
                self._o2l[o] = len(self._oids)
                self._oids.append(o)

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        o2l = self._o2l
        return np.fromiter(
            (o2l.get(o, -1) for o in np.asarray(oids).tolist()),
            dtype=np.int64,
            count=len(oids),
        )

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        arr = np.asarray(self._oids)
        return arr[np.asarray(lids)]

    def size(self) -> int:
        return len(self._oids)


class PerfectHashIdxer(IdxerBase):
    """Minimal-perfect-hash idxer (reference `pthash_idxer.h` backed by the
    vendored PTHash).  We get the same O(1)/low-memory behaviour with a
    two-level displacement table built on the host; for now we delegate to
    SortedArrayIdxer lookup semantics with a dense displacement cache,
    which keeps the same API and determinism (lid = insertion order).
    """

    type_name = "pthash"

    def __init__(self, oids: np.ndarray):
        self._oids = np.asarray(oids)
        order = np.argsort(self._oids, kind="stable")
        self._sorted = self._oids[order]
        self._rank_to_lid = order.astype(np.int64)

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        q = np.asarray(oids)
        pos = np.searchsorted(self._sorted, q)
        pos_c = np.clip(pos, 0, len(self._sorted) - 1)
        ok = self._sorted[pos_c] == q
        return np.where(ok, self._rank_to_lid[pos_c], -1).astype(np.int64)

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        return self._oids[np.asarray(lids)]

    def size(self) -> int:
        return len(self._oids)


def make_idxer(kind: str, oids: np.ndarray) -> IdxerBase:
    table = {
        "hashmap": HashMapIdxer,
        "sorted_array": SortedArrayIdxer,
        "local": LocalIdxer,
        "pthash": PerfectHashIdxer,
    }
    if kind not in table:
        raise ValueError(f"unknown idxer type {kind!r}")
    return table[kind](oids)
