"""Per-fragment oid -> lid indexers.

Re-design of `grape/vertex_map/idxers/` (hashmap_idxer.h, sorted_array_idxer.h,
local_idxer.h, pthash_idxer.h; dispatch at `idxers.h:26-110`).  Selected by
`--idxer_type` (reference `flags.cc:49-51`, default "hashmap").

All indexers are batch-oriented: `get_index(oids) -> lids` over numpy
arrays.  The heavy lookup during graph load happens on the host; the
device side never sees oids (only dense lids/gids).

Integer-keyed graphs route through the native C++ backends
(native/loader.cc: `gl_ht_*` open-addressing table — the reference
`IdIndexer`, grape/graph/id_indexer.h — and `gl_mph_*`, a PTHash-style
minimal perfect hash — the reference pthash_idxer.h + vendored
thirdparty/pthash).  String-keyed graphs and native-less environments
fall back to the pure-Python paths below.
"""

from __future__ import annotations

import numpy as np

from libgrape_lite_tpu.io.native import NativeIdTable, NativeMph


class IdxerBase:
    type_name = "base"

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        """Return lids; -1 for unknown oids."""
        raise NotImplementedError

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError


class HashMapIdxer(IdxerBase):
    """Hash-table oid->lid, lid = insertion order (reference
    `hashmap_idxer.h` over `IdIndexer`).  Native open-addressing table
    with threaded batch lookup when oids are integers."""

    type_name = "hashmap"

    def __init__(self, oids: np.ndarray):
        self._oids = np.asarray(oids)
        self._native = NativeIdTable.build(self._oids)
        self._o2l = (
            None
            if self._native is not None
            else {o: i for i, o in enumerate(self._oids.tolist())}
        )

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        if self._native is not None:
            return self._native.lookup(oids)
        o2l = self._o2l
        return np.fromiter(
            (o2l.get(o, -1) for o in np.asarray(oids).tolist()),
            dtype=np.int64,
            count=len(oids),
        )

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        return self._oids[np.asarray(lids)]

    def size(self) -> int:
        return len(self._oids)

    def extend(self, new_oids: np.ndarray) -> None:
        """Append vertices (mutation path, reference `vertex_map.h:146-220`)."""
        arr = np.asarray(new_oids)
        if self._native is not None:
            if np.issubdtype(arr.dtype, np.integer):
                self._native.insert(arr)
                self._oids = self._native.oids()
                return
            # oid dtype widened (e.g. string ids): drain to the dict path
            self._oids = self._native.oids()
            self._o2l = {o: i for i, o in enumerate(self._oids.tolist())}
            self._native = None
        fresh = []
        for o in np.asarray(new_oids).tolist():
            if o not in self._o2l:  # dedups across AND within the batch
                self._o2l[o] = len(self._oids) + len(fresh)
                fresh.append(o)
        if fresh:
            self._oids = np.concatenate(
                [self._oids, np.asarray(fresh, dtype=self._oids.dtype)]
            )


class SortedArrayIdxer(IdxerBase):
    """Binary-search over sorted oids (reference `sorted_array_idxer.h`).
    lid = rank in sorted order; O(log n) lookups, zero hash memory."""

    type_name = "sorted_array"

    def __init__(self, oids: np.ndarray):
        self._oids = np.sort(np.asarray(oids))

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        q = np.asarray(oids)
        pos = np.searchsorted(self._oids, q)
        pos_c = np.clip(pos, 0, len(self._oids) - 1)
        ok = self._oids[pos_c] == q
        return np.where(ok, pos_c, -1).astype(np.int64)

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        return self._oids[np.asarray(lids)]

    def size(self) -> int:
        return len(self._oids)


class LocalIdxer(IdxerBase):
    """Lazy idxer for vfile-less loading (reference `local_idxer.h`):
    oids are added on first sight, in arrival order."""

    type_name = "local"

    def __init__(self, oids=None):
        self._native = None
        self._o2l = {}
        self._py_oids = []
        if oids is not None:
            self.add(oids)

    def add(self, oids: np.ndarray) -> None:
        arr = np.asarray(oids)
        if self._native is None and not self._o2l:
            self._native = NativeIdTable.build(arr[:0])
        if self._native is not None and np.issubdtype(arr.dtype, np.integer):
            self._native.insert(arr)
            return
        if self._native is not None:
            # dtype changed mid-stream (string oids): drain to Python
            for o in self._native.oids().tolist():
                self._o2l.setdefault(o, len(self._py_oids))
                self._py_oids.append(o)
            self._native = None
        for o in arr.tolist():
            if o not in self._o2l:
                self._o2l[o] = len(self._py_oids)
                self._py_oids.append(o)

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        if self._native is not None:
            return self._native.lookup(oids)
        o2l = self._o2l
        return np.fromiter(
            (o2l.get(o, -1) for o in np.asarray(oids).tolist()),
            dtype=np.int64,
            count=len(oids),
        )

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        arr = (
            self._native.oids()
            if self._native is not None
            else np.asarray(self._py_oids)
        )
        return arr[np.asarray(lids)]

    def size(self) -> int:
        if self._native is not None:
            return self._native.size()
        return len(self._py_oids)


class PerfectHashIdxer(IdxerBase):
    """Minimal-perfect-hash idxer (reference `pthash_idxer.h` backed by
    the vendored PTHash).  lid = MPH position (like the reference, lid
    assignment is idxer-specific); membership of a query oid is verified
    against the lid->oid array, which GetOid needs anyway.  Falls back
    to sorted-array semantics when the native library is unavailable or
    oids are strings."""

    type_name = "pthash"

    def __init__(self, oids: np.ndarray):
        oids = np.asarray(oids)
        self._mph = NativeMph.build(oids)
        if self._mph is not None:
            pos = self._mph.positions(oids)
            table = np.empty(len(oids), dtype=np.int64)
            table[pos] = oids
            self._oid_by_lid = table
            self._sorted = None
            return
        # fallback: binary-search emulation (same API, not an MPH)
        self._oid_by_lid = oids
        order = np.argsort(oids, kind="stable")
        self._sorted = oids[order]
        self._rank_to_lid = order.astype(np.int64)

    def get_index(self, oids: np.ndarray) -> np.ndarray:
        q = np.asarray(oids)
        if self._mph is not None:
            if len(q) == 0 or not np.issubdtype(q.dtype, np.integer):
                return np.full(len(q), -1, dtype=np.int64)
            pos = self._mph.positions(q)
            ok = self._oid_by_lid[pos] == q
            return np.where(ok, pos, -1).astype(np.int64)
        pos = np.searchsorted(self._sorted, q)
        pos_c = np.clip(pos, 0, len(self._sorted) - 1)
        ok = self._sorted[pos_c] == q
        return np.where(ok, self._rank_to_lid[pos_c], -1).astype(np.int64)

    def get_oid(self, lids: np.ndarray) -> np.ndarray:
        return self._oid_by_lid[np.asarray(lids)]

    def size(self) -> int:
        return len(self._oid_by_lid)


def make_idxer(kind: str, oids: np.ndarray) -> IdxerBase:
    table = {
        "hashmap": HashMapIdxer,
        "sorted_array": SortedArrayIdxer,
        "local": LocalIdxer,
        "pthash": PerfectHashIdxer,
    }
    if kind not in table:
        raise ValueError(f"unknown idxer type {kind!r}")
    return table[kind](oids)
