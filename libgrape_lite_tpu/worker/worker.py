"""Superstep driver.

Re-design of `grape/worker/worker.h:48-232`: `Init` prepares the
fragment + message plumbing, `Query` runs PEval then iterates IncEval
until the termination vote fires, `Output` assembles results.

TPU mapping of the reference loop (`worker.h:104-146`):

  * the whole PEval + IncEval loop is ONE jitted function: a
    `lax.while_loop` whose carry is the app state pytree, executed under
    `shard_map` over the frag mesh axis;
  * `messages_.ToTerminate()`'s 2-int MPI_Allreduce
    (`parallel_message_manager.h:123-138`) is the `psum`-reduced
    `active` scalar the app returns each round;
  * per-round host logging (`worker.h:120-139`) is unavailable inside
    the fused loop by design — XLA owns the schedule; a debug mode
    (`fused=False`) drives rounds from the host instead, one jitted
    superstep per round, for parity with the reference's observable
    behavior.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from libgrape_lite_tpu import compat, obs
from libgrape_lite_tpu.app.base import AppBase, StepContext
from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
from libgrape_lite_tpu.parallel.comm_spec import FRAG_AXIS
from libgrape_lite_tpu.utils.types import state_struct

_INT32_MAX = np.iinfo(np.int32).max


def _squeeze_state(state, squeezed):
    return {
        k: (v[0] if k in squeezed else v) for k, v in state.items()
    }


def _unsqueeze_state(state, squeezed):
    return {
        k: (v[None] if k in squeezed else v) for k, v in state.items()
    }


def _squeeze_lane_state(state, squeezed):
    """Per-shard view of batched carry leaves: the lane axis leads, so
    sharded keys arrive as [B, 1, ...] blocks and squeeze axis 1."""
    return {
        k: (v[:, 0] if k in squeezed else v) for k, v in state.items()
    }


def _jit_with_chunk_digest(sm, state, eph):
    """Wrap a compiled guarded-chunk shard_map so the watchdog digest
    (and the stagnation residual) ride out as extra outputs of the
    SAME jitted dispatch — computed on the global post-collective
    carry, so they are value-identical to the monitor's own probe
    (same carry_digest function, same masked-residual rule) and the
    guarded-fused path pays no extra device dispatch for them (ROADMAP
    "Watchdog on device").  ONE wrapper shared by the serial and the
    software-pipelined chunk runners: the digest/residual contract is
    a consistent-cut guarantee (docs/PIPELINE.md), and two private
    copies of it could drift apart."""
    from libgrape_lite_tpu.guard.watchdog import carry_digest

    float_keys = sorted(
        k for k, v in state.items()
        if k not in eph and np.dtype(v.dtype).kind == "f"
    )

    def with_digest(frag_stacked, st, eph_state, active0, r0):
        out, rounds, active = sm(
            frag_stacked, st, eph_state, active0, r0
        )
        dig = carry_digest(out)
        if float_keys:
            diffs = [
                jnp.max(jnp.where(
                    jnp.isfinite(d), d, jnp.float32(0)
                ))
                for k in float_keys
                for d in [jnp.abs(
                    out[k].astype(jnp.float32)
                    - st[k].astype(jnp.float32)
                )]
            ]
            res = jnp.max(jnp.stack(diffs))
        else:
            res = jnp.float32(-1)
        return out, rounds, active, dig, res

    return jax.jit(with_digest)


class BatchDispatch:
    """One dispatched (possibly still in-flight) batched query: the
    un-synced outputs of a `Worker.query_batch_dispatch` call, held
    SELF-CONTAINED so a window of W dispatches can coexist without
    clobbering the worker's per-query result fields (`batch_rounds`,
    `_result_state`, ...) — the deferred batch-result surface the
    async serve pump (serve/pipeline.py) harvests from.

    Nothing here forces a host sync until asked: `is_ready()` polls,
    `wait()` syncs the per-lane verdicts (rounds / terminate codes —
    a few int32s), and `lane_values(b)` does the per-lane extraction
    (device_get + finalize) the harvest stage overlaps with the next
    batch's device execution."""

    __slots__ = ("app", "fragment", "eph", "state", "rounds_v",
                 "active_v", "breaches", "batch", "guarded",
                 "supersteps_counted", "_rounds", "_active")

    def __init__(self, *, app, fragment, eph, state, rounds_v,
                 active_v, batch, breaches=None, guarded=False,
                 supersteps_counted=False):
        self.app = app
        self.fragment = fragment
        self.eph = frozenset(eph)
        self.state = state  # {**carry, **eph} — device (or synced) refs
        self.rounds_v = rounds_v
        self.active_v = active_v
        self.batch = batch
        self.breaches = (
            list(breaches) if breaches is not None else [None] * batch
        )
        self.guarded = guarded
        # guarded dispatches count supersteps inside their chunk loop;
        # unguarded ones are counted by whoever harvests (the rounds
        # are not known until the dispatch settles)
        self.supersteps_counted = supersteps_counted
        self._rounds = None
        self._active = None

    def is_ready(self) -> bool:
        """True when the dispatch has settled (no sync forced); a
        backend without `jax.Array.is_ready` reports True and the
        first harvest simply blocks."""
        probe = getattr(self.rounds_v, "is_ready", None)
        return bool(probe()) if callable(probe) else True

    def wait(self) -> "BatchDispatch":
        """Sync the per-lane verdicts; values stay deferred per lane."""
        if self._rounds is None:
            self._rounds = np.asarray(self.rounds_v)
            self._active = np.asarray(self.active_v)
        return self

    @property
    def rounds(self) -> np.ndarray:
        return self.wait()._rounds

    @property
    def terminate(self) -> np.ndarray:
        return np.minimum(0, self.wait()._active)

    def lane_state(self, lane: int):
        """Lane `lane`'s carry view (ephemeral leaves are shared)."""
        return {
            k: (v if k in self.eph else v[lane])
            for k, v in self.state.items()
        }

    def lane_values(self, lane: int) -> np.ndarray:
        """Per-vertex assembled values for one lane, [fnum, vp] numpy —
        the host-sync the harvest stage pays lazily."""
        host = jax.device_get(self.lane_state(lane))
        return self.app.finalize(self.fragment, host)


class PreparedBatch:
    """A batched query with its host-side work DONE (state built and
    placed, runner resolved through the cache) but its execution not
    yet enqueued.  The async pump prepares ahead under the window and
    staggers `launch()` calls so executions never oversubscribe the
    backend (on the CPU fallback two concurrent XLA executions fight
    for the same cores; on a real accelerator the device queue
    serialises them anyway) while preparation and result extraction
    overlap whatever IS executing.  Guarded batches carry their args
    instead: the chunked monitor loop cannot split, so launch() runs
    it whole (serve/batch.py)."""

    __slots__ = ("worker", "app", "fragment", "eph", "runner", "carry",
                 "eph_part", "batch", "guarded", "_guard_args")

    def __init__(self, *, worker, app, fragment, eph=None, runner=None,
                 carry=None, eph_part=None, batch=0, guarded=False,
                 guard_args=None):
        self.worker = worker
        self.app = app
        self.fragment = fragment
        self.eph = eph
        self.runner = runner
        self.carry = carry
        self.eph_part = eph_part
        self.batch = batch
        self.guarded = guarded
        self._guard_args = guard_args

    def launch(self) -> "BatchDispatch":
        """Enqueue the execution (no host sync for unguarded batches —
        the refs ride back un-synced; guarded batches run their chunk
        loop here, which probes at boundaries by design)."""
        if self.guarded:
            from libgrape_lite_tpu.serve.batch import run_guarded_batch

            args_list, mr, guard_cfg = self._guard_args
            w = self.worker
            run_guarded_batch(w, args_list, mr, guard_cfg)
            return BatchDispatch(
                app=self.app, fragment=self.fragment,
                eph=frozenset(
                    getattr(self.app, "ephemeral_keys", ()) or ()
                ),
                state=w._result_state,
                rounds_v=np.asarray(w.batch_rounds).copy(),
                active_v=np.asarray(w.batch_terminate).copy(),
                batch=self.batch, breaches=w.batch_breaches,
                guarded=True, supersteps_counted=True,
            )
        out_state, rounds_v, active_v = self.runner(
            self.fragment.dev, self.carry, self.eph_part
        )
        return BatchDispatch(
            app=self.app, fragment=self.fragment, eph=self.eph,
            state={**out_state, **self.eph_part},
            rounds_v=rounds_v, active_v=active_v, batch=self.batch,
        )


def _unsqueeze_lane_state(state, squeezed):
    return {
        k: (v[:, None] if k in squeezed else v) for k, v in state.items()
    }


class Worker:
    """Binds an app to a sharded fragment and runs queries
    (reference `Worker<APP_T, MESSAGE_MANAGER_T>`).

    Failure handling follows the reference's cooperative-abort scope
    (`default_message_manager.h:156-166`, `ForceTerminate` +
    `TerminateInfo`): an app votes a NEGATIVE active value to abort;
    the psum carries it to every shard, the loop stops, and
    `get_terminate_info()` reports the failure.

    Checkpoint-restart (ft/): `query(..., checkpoint_every=K,
    checkpoint_dir=...)` degrades the fused loop to stepwise execution
    and snapshots the carry pytree + round counter every K supersteps
    (a superstep boundary is a consistent cut); `resume(dir)` validates
    the config fingerprint and continues from the last complete
    superstep with byte-identical results.  With checkpointing off
    (the default) the fused `shard_map(while_loop)` path is untouched —
    fail-fast, like the reference."""

    def __init__(self, app: AppBase, fragment: ShardedEdgecutFragment):
        self.app = app
        self.fragment = fragment
        self.comm_spec = fragment.comm_spec
        self._runner_cache = {}
        # hit/miss counters over the compiled-runner cache: serve/ pins
        # "a session's second query triggers zero XLA compilation" on
        # the miss count staying flat (tests/test_serve.py)
        self.runner_cache_stats = {"hits": 0, "misses": 0}
        self.rounds = 0
        self._result_state = None
        # the fragment each result was computed on: query_incremental's
        # safe prev_fragment default — a serve repack rebinds
        # self.fragment, but the PREVIOUS result's rows still live in
        # the old layout and must migrate by oid
        self._result_fragment = None
        self._terminate_code = 0
        self._guard_monitor = None  # guard/: set only while guards are armed
        self.batch_rounds = None  # per-lane rounds of the last query_batch
        self.batch_terminate = None  # per-lane terminate codes (min(0, v))
        self.batch_breaches = None  # per-lane guard bundles (serve/batch)
        # host-side stage decomposition of the last fused/batched
        # query: {"dispatch": ns, "device": ns} — perf_counter_ns
        # stamps around the runner enqueue and the result sync, so the
        # serve stage report (queue.ServeResult.stages) can split host
        # dispatch from device wait without touching the jitted
        # program (None on paths that do not decompose: guarded,
        # stepwise, host-only)
        self.last_stage_ns = None
        # dyn/: incremental-IncEval accounting — seeded vs (counted,
        # never silent) cold fallbacks, and the last query's plan
        self.inc_stats = {"seeded": 0, "cold": 0}
        self.inc_report = None
        self._seed_fn = None  # set only inside query_incremental

    @property
    def guard_report(self):
        """The last query's guard statistics (probes, breaches,
        rollbacks) or None when guards were off."""
        return (
            None if self._guard_monitor is None
            else self._guard_monitor.report()
        )

    def _seeded(self, state_np):
        """Apply the incremental-IncEval seed overrides (dyn/) to a
        freshly-built init state — identity outside query_incremental.
        The hook sits at every init_state call site, so the seeded
        query runs the SAME fused/stepwise/guarded machinery as a cold
        one (and a checkpoint resume restores over the fresh init the
        usual way: the restored carry came from the seeded run)."""
        if self._seed_fn is None:
            return state_np
        return self._seed_fn(state_np)

    def _check_dyn_view(self):
        """An app without a dyn-overlay contract must not run while the
        fragment holds staged delta edges — it would silently compute
        on the stale base graph.  ServeSession repacks automatically
        before dispatching such apps; bare Workers fail loudly."""
        ov = getattr(self.fragment, "dyn_overlay", None)
        if (
            ov is not None and ov.count > 0
            and not getattr(self.app, "dyn_overlay_support", False)
        ):
            raise ValueError(
                f"{type(self.app).__name__} has no dyn-overlay "
                f"contract and the fragment carries {ov.count} staged "
                "delta edge(s); fold them first (DynGraph.fold_now — "
                "ServeSession.ingest handles this automatically)"
            )

    def release_buffers(self) -> None:
        """Drop this worker's device-resident references — the last
        query's result carry, its fragment provenance, the guard
        monitor, and any device copies of const-mode pack streams
        (they lazily rebuild from the cached host plan) — so a fleet
        eviction (ServeSession.release_device) actually frees the
        HBM.  The compiled-runner cache is KEPT: re-admission must
        compile nothing (tests/test_fleet.py pins it)."""
        self._result_state = None
        self._result_fragment = None
        self._guard_monitor = None
        self.batch_rounds = None
        self.batch_terminate = None
        self.batch_breaches = None
        pack = getattr(self.app, "_pack", None)
        if pack is not None and hasattr(pack, "_const"):
            pack._const = None

    def get_terminate_info(self):
        """(success, info) — reference `Worker::GetTerminateInfo`
        (worker.h:150-152)."""
        if self._terminate_code >= 0:
            return True, ""
        return False, (
            f"query force-terminated with code {self._terminate_code} "
            f"after {self.rounds} rounds"
        )

    # ---- Init (reference worker.h:82-100) is construction above ----

    def _mesh_layout(self):
        """(mesh, frag/dim0 spec) for the app's mesh kind: the 1-D frag
        axis by default, the k x k SUMMA mesh for vc2d apps."""
        if self.app.mesh_kind == "vc2d":
            from libgrape_lite_tpu.parallel.comm_spec import (
                VC_COL_AXIS, VC_ROW_AXIS,
            )

            return self.comm_spec.mesh2d(), P((VC_ROW_AXIS, VC_COL_AXIS))
        return self.comm_spec.mesh, P(FRAG_AXIS)

    def _key_specs(self, state):
        """(spec per state key, keys squeezed of their leading frag
        dim).  Custom-spec leaves pass through as raw per-shard blocks."""
        app = self.app
        custom = app.custom_specs()
        replicated = set(app.replicated_keys)
        _, shard0 = self._mesh_layout()
        specs = {
            k: custom.get(k, P() if k in replicated else shard0)
            for k in state
        }
        squeezed = {
            k for k in state if k not in custom and k not in replicated
        }
        return specs, squeezed

    def _make_runner(self, max_rounds: int):
        app = self.app
        mesh, frag_spec = self._mesh_layout()
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())

        def stepper(frag_stacked, state, eph_state, squeezed):
            frag = frag_stacked.local()
            # ephemeral leaves (pack stream tables etc.) ride in a
            # separate, NON-donated argument: they are stripped from the
            # outputs, so donating them could never alias and would only
            # draw 'unusable donation' warnings on the largest buffers
            st_all = _squeeze_state({**state, **eph_state}, squeezed)
            eph_vals = {k: st_all[k] for k in eph}

            def strip(s):
                return {k: v for k, v in s.items() if k not in eph}

            ctx = StepContext()
            st, active = app.peval(ctx, frag, st_all)
            st = strip(st)
            limit = jnp.int32(max_rounds if max_rounds > 0 else _INT32_MAX)

            def cond(carry):
                _, act, r = carry
                return jnp.logical_and(act > 0, r < limit)

            def body(carry):
                s, _, r = carry
                s2, a2 = app.inceval(ctx, frag, {**s, **eph_vals})
                return strip(s2), jnp.int32(a2), r + jnp.int32(1)

            st, active, rounds = lax.while_loop(
                cond, body, (st, jnp.int32(active), jnp.int32(0))
            )
            return _unsqueeze_state(st, squeezed), rounds, active

        def compile_for(state):
            specs, squeezed = self._key_specs(state)
            carry_specs = {k: v for k, v in specs.items() if k not in eph}
            eph_specs = {k: v for k, v in specs.items() if k in eph}
            sm = compat.shard_map(
                partial(stepper, squeezed=squeezed),
                mesh=mesh,
                in_specs=(frag_spec, carry_specs, eph_specs),
                out_specs=(carry_specs, P(), P()),
                check_vma=False,
            )
            # donate the placed carry state: every query places fresh
            # buffers (query -> _place_state), so XLA may alias them
            # into the loop carry instead of holding input + output
            # copies in HBM (fragment CSRs and ephemeral tables are
            # reused / output-less and stay un-donated)
            return jax.jit(sm, donate_argnums=(1,))

        return compile_for

    def _make_pipelined_runner(self, max_rounds: int):
        """Software-pipelined twin of `_make_runner` (r9, parallel/
        pipeline.py): the loop carry additionally threads the exchange
        double buffer `xbuf` — created from the post-PEval carry at
        loop entry, advanced by each round's kickoff, DROPPED at exit.
        The jitted interface (and therefore the observable cut: the
        carry the caller, guard digests and checkpoints see) is
        identical to the serial runner's; `xbuf` is a pure function of
        the carry, so dropping and re-deriving it is bitwise free.
        Only reached when the app resolved `_pipeline`; with
        GRAPE_PIPELINE off `_runner_for` routes to `_make_runner`,
        whose trace is bit-for-bit untouched (lowered-HLO pinned)."""
        app = self.app
        mesh, frag_spec = self._mesh_layout()
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())

        def stepper(frag_stacked, state, eph_state, squeezed):
            frag = frag_stacked.local()
            st_all = _squeeze_state({**state, **eph_state}, squeezed)
            eph_vals = {k: st_all[k] for k in eph}

            def strip(s):
                return {k: v for k, v in s.items() if k not in eph}

            ctx = StepContext()
            st, active = app.peval(ctx, frag, st_all)
            st = strip(st)
            xbuf = app.pipeline_exchange(ctx, frag, {**st, **eph_vals})
            limit = jnp.int32(max_rounds if max_rounds > 0 else _INT32_MAX)

            def cond(carry):
                _, _, act, r = carry
                return jnp.logical_and(act > 0, r < limit)

            def body(carry):
                s, xb, _, r = carry
                s2, a2, xb2 = app.inceval_pipelined(
                    ctx, frag, {**s, **eph_vals}, xb
                )
                return strip(s2), xb2, jnp.int32(a2), r + jnp.int32(1)

            st, _, active, rounds = lax.while_loop(
                cond, body, (st, xbuf, jnp.int32(active), jnp.int32(0))
            )
            return _unsqueeze_state(st, squeezed), rounds, active

        def compile_for(state):
            specs, squeezed = self._key_specs(state)
            carry_specs = {k: v for k, v in specs.items() if k not in eph}
            eph_specs = {k: v for k, v in specs.items() if k in eph}
            sm = compat.shard_map(
                partial(stepper, squeezed=squeezed),
                mesh=mesh,
                in_specs=(frag_spec, carry_specs, eph_specs),
                out_specs=(carry_specs, P(), P()),
                check_vma=False,
            )
            return jax.jit(sm, donate_argnums=(1,))

        return compile_for

    def _make_chunk_runner(self, chunk: int, max_rounds: int):
        """Fused IncEval segment for the guarded path: runs up to
        `chunk` supersteps of the SAME `shard_map(while_loop)` body as
        `_make_runner`, but (a) skips PEval (the caller drives it once),
        (b) enters/exits at an arbitrary (round, active) so segments
        compose, and (c) does NOT donate the carry — the guard probe
        reads the pre-chunk carry for the consecutive-carry invariants
        (monotone distances etc.), so guarded execution holds two carry
        generations in HBM by design."""
        app = self.app
        mesh, frag_spec = self._mesh_layout()
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())

        def stepper(frag_stacked, state, eph_state, active0, r0, squeezed):
            frag = frag_stacked.local()
            st_all = _squeeze_state({**state, **eph_state}, squeezed)
            eph_vals = {k: st_all[k] for k in eph}

            def strip(s):
                return {k: v for k, v in s.items() if k not in eph}

            ctx = StepContext()
            st = strip(st_all)
            limit = jnp.int32(max_rounds if max_rounds > 0 else _INT32_MAX)
            stop = jnp.minimum(jnp.int32(r0) + jnp.int32(chunk), limit)

            def cond(carry):
                _, act, r = carry
                return jnp.logical_and(act > 0, r < stop)

            def body(carry):
                s, _, r = carry
                s2, a2 = app.inceval(ctx, frag, {**s, **eph_vals})
                return strip(s2), jnp.int32(a2), r + jnp.int32(1)

            st, active, rounds = lax.while_loop(
                cond, body, (st, jnp.int32(active0), jnp.int32(r0))
            )
            return _unsqueeze_state(st, squeezed), rounds, active

        def compile_for(state):
            specs, squeezed = self._key_specs(state)
            carry_specs = {k: v for k, v in specs.items() if k not in eph}
            eph_specs = {k: v for k, v in specs.items() if k in eph}
            sm = compat.shard_map(
                partial(stepper, squeezed=squeezed),
                mesh=mesh,
                in_specs=(frag_spec, carry_specs, eph_specs, P(), P()),
                out_specs=(carry_specs, P(), P()),
                check_vma=False,
            )

            return _jit_with_chunk_digest(sm, state, eph)

        return compile_for

    def _make_pipelined_chunk_runner(self, chunk: int, max_rounds: int):
        """Software-pipelined twin of `_make_chunk_runner` (r9): the
        exchange double buffer is re-derived from the entering carry at
        every chunk entry (it is a pure function of the carry, so the
        re-derivation is bitwise the value the previous chunk dropped)
        and dropped at exit — chunk boundaries therefore remain the
        SAME consistent cut as the serial chunked loop, and the
        watchdog digest / masked residual emitted by this dispatch
        observe the post-join carry (docs/PIPELINE.md).  Guard probes,
        checkpoint snapshots and fault hooks all sit at that cut."""
        app = self.app
        mesh, frag_spec = self._mesh_layout()
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())

        def stepper(frag_stacked, state, eph_state, active0, r0, squeezed):
            frag = frag_stacked.local()
            st_all = _squeeze_state({**state, **eph_state}, squeezed)
            eph_vals = {k: st_all[k] for k in eph}

            def strip(s):
                return {k: v for k, v in s.items() if k not in eph}

            ctx = StepContext()
            st = strip(st_all)
            xbuf = app.pipeline_exchange(ctx, frag, {**st, **eph_vals})
            limit = jnp.int32(max_rounds if max_rounds > 0 else _INT32_MAX)
            stop = jnp.minimum(jnp.int32(r0) + jnp.int32(chunk), limit)

            def cond(carry):
                _, _, act, r = carry
                return jnp.logical_and(act > 0, r < stop)

            def body(carry):
                s, xb, _, r = carry
                s2, a2, xb2 = app.inceval_pipelined(
                    ctx, frag, {**s, **eph_vals}, xb
                )
                return strip(s2), xb2, jnp.int32(a2), r + jnp.int32(1)

            st, _, active, rounds = lax.while_loop(
                cond, body,
                (st, xbuf, jnp.int32(active0), jnp.int32(r0)),
            )
            return _unsqueeze_state(st, squeezed), rounds, active

        def compile_for(state):
            specs, squeezed = self._key_specs(state)
            carry_specs = {k: v for k, v in specs.items() if k not in eph}
            eph_specs = {k: v for k, v in specs.items() if k in eph}
            sm = compat.shard_map(
                partial(stepper, squeezed=squeezed),
                mesh=mesh,
                in_specs=(frag_spec, carry_specs, eph_specs, P(), P()),
                out_specs=(carry_specs, P(), P()),
                check_vma=False,
            )
            # the SAME post-join digest/residual contract as the
            # serial chunk runner — one shared wrapper, so the two
            # guarded paths cannot drift apart
            return _jit_with_chunk_digest(sm, state, eph)

        return compile_for

    def _cached_runner(self, key, build):
        """One compiled-runner cache lookup with hit/miss accounting
        (serve/ asserts zero-recompile reuse through these counters)."""
        hit = key in self._runner_cache
        self.runner_cache_stats["hits" if hit else "misses"] += 1
        # the overlap truth meter (obs/truth.py) must EXCLUDE rounds
        # whose dispatch included trace+compile: the span sites read
        # this flag right after the first dispatch of a fresh runner
        # and stamp `mark("compiled")`
        self._last_runner_miss = not hit
        if not hit:
            self._runner_cache[key] = build()
        return self._runner_cache[key]

    def _state_struct(self, state):
        return state_struct(state)

    def _pipelined(self):
        """The app's resolved pipeline plan (r9), or None — the single
        routing predicate for the fused/chunked loop bodies.  The plan
        uid rides in `trace_key` (apps set `_pipeline_uid`), so serial
        and pipelined compiles never share a cache entry."""
        return getattr(self.app, "_pipeline", None)

    def _chunk_runner_for(self, chunk: int, max_rounds: int, state):
        key = (
            "chunk", chunk, max_rounds,
            self.app.trace_key(),
            self._state_struct(state),
        )
        make = (
            self._make_pipelined_chunk_runner
            if self._pipelined() is not None else self._make_chunk_runner
        )
        return self._cached_runner(
            key, lambda: make(chunk, max_rounds)(state)
        )

    def _runner_for(self, max_rounds: int, state):
        """Cache the jitted runner per (max_rounds, app hyperparameters,
        state structure) so repeated queries don't re-trace but changed
        query params (which are baked into the trace) do.  `max_rounds`
        is part of the key because the round limit is baked into the
        while_loop cond — a second query with a different limit must
        not silently reuse the first compile (pinned by
        tests/test_worker.py::test_runner_cache_keys_max_rounds)."""
        key = (
            max_rounds,
            self.app.trace_key(),
            self._state_struct(state),
        )
        make = (
            self._make_pipelined_runner
            if self._pipelined() is not None else self._make_runner
        )
        return self._cached_runner(
            key, lambda: make(max_rounds)(state)
        )

    # ---- batched multi-source execution (serve/) -------------------------

    def _check_batchable(self):
        """Batched dispatch covers superstep apps on the 1-D frag mesh
        and the 2-D vc2d mesh; everything else fails loudly BEFORE a
        cryptic trace error."""
        app = self.app
        if getattr(app, "host_only", False):
            raise ValueError(
                f"{type(app).__name__} is a host-only app: its "
                "data-dependent host loop has no superstep carry to vmap"
            )
        if hasattr(app, "collect_mutations"):
            raise ValueError(
                "MutationContext apps rebuild the fragment between "
                "rounds and cannot share one batched dispatch"
            )
        if app.mesh_kind not in ("frag", "vc2d"):
            raise ValueError(
                f"batched dispatch supports the frag and vc2d meshes "
                f"only (app mesh_kind={app.mesh_kind!r})"
            )
        if app.custom_specs() and app.mesh_kind != "vc2d":
            # vc2d's custom row-sharded specs are handled by
            # _key_specs_batch; any OTHER custom layout is unaudited
            raise ValueError(
                "batched dispatch does not support custom-spec state "
                "leaves outside the vc2d mesh"
            )

    def _key_specs_batch(self, state):
        """(spec per key, keys squeezed of their axis-1 frag dim) for a
        batched carry: sharded leaves are [B, fnum, ...] split on axis
        1, replicated leaves [B, ...] everywhere, ephemeral leaves stay
        unbatched [fnum, ...] (shared streams).  Custom-spec leaves
        (vc2d): ephemeral ones keep their per-shard layout unbatched,
        carry ones gain the leading lane axis with the custom spec
        shifted one dim right ([B, k*vc] rides P(None, vcrow)) — and
        are NOT squeezed, since their local block has no unit frag
        dim."""
        app = self.app
        custom = app.custom_specs()
        replicated = set(app.replicated_keys)
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        _, shard0 = self._mesh_layout()
        specs, squeezed = {}, set()
        for k in state:
            if k in eph:
                specs[k] = custom.get(k, shard0)
            elif k in replicated:
                specs[k] = P()
            elif k in custom:
                specs[k] = P(None, *custom[k])
            else:
                specs[k] = P(None, FRAG_AXIS)
                squeezed.add(k)
        return specs, squeezed

    def _place_state_batch(self, state_np):
        from libgrape_lite_tpu.parallel.comm_spec import put_global

        mesh, _ = self._mesh_layout()
        specs, _ = self._key_specs_batch(state_np)
        return {
            k: put_global(v, NamedSharding(mesh, specs[k]))
            for k, v in state_np.items()
        }

    def _lane_stepper_parts(self, eph_vals):
        """(strip, lane_peval, lane_inc): one lane's superstep closures
        over the shared per-shard fragment + ephemeral streams — the
        exact bodies of _make_runner, reused under vmap."""
        app = self.app
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        ctx = StepContext()

        def strip(s):
            return {k: v for k, v in s.items() if k not in eph}

        def lane_peval(frag, s):
            s2, a = app.peval(ctx, frag, {**s, **eph_vals})
            return strip(s2), jnp.int32(a)

        def lane_inc(frag, s):
            s2, a = app.inceval(ctx, frag, {**s, **eph_vals})
            return strip(s2), jnp.int32(a)

        return strip, lane_peval, lane_inc

    @staticmethod
    def _lane_body(lane_inc, frag, batch: int):
        """One batched IncEval round with the per-lane freeze mask:
        lanes whose vote has reached zero (or negative: cooperative
        abort) keep their carry PINNED, so each lane executes exactly
        the inceval sequence of its own sequential query and the
        per-lane result is byte-identical to k separate Worker.query
        runs — convergence raggedness costs masked (discarded) compute
        on finished lanes, never a value change."""
        def body(carry):
            s, act, rv, r = carry
            s2, a2 = jax.vmap(lambda st: lane_inc(frag, st))(s)
            live = act > 0

            def sel(new, old):
                mask = live.reshape((batch,) + (1,) * (new.ndim - 1))
                return jnp.where(mask, new, old)

            s3 = jtu.tree_map(sel, s2, s)
            a3 = jnp.where(live, a2, act)
            r2 = r + jnp.int32(1)
            return s3, a3, jnp.where(live, r2, rv), r2

        return body

    def _make_batched_runner(self, max_rounds: int, batch: int):
        """Fused multi-source runner: the SAME PEval+IncEval loop as
        _make_runner, vmapped over a leading lane axis of the carry.
        Each lane is an independent query against the shared HBM-
        resident fragment and ephemeral streams (pack tables, mirror
        send tables, pre-masked weights ride once, not per lane); the
        while_loop runs until EVERY lane's active vote has settled, and
        the freeze mask (see _lane_body) keeps finished lanes pinned so
        raggedness never perturbs results."""
        app = self.app
        mesh, frag_spec = self._mesh_layout()
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        custom = frozenset(app.custom_specs())

        def stepper(frag_stacked, state, eph_state, squeezed):
            frag = frag_stacked.local()
            # custom-spec ephemeral leaves (vc2d vmask_row) arrive as
            # their raw per-shard block — no unit frag dim to strip
            eph_vals = {
                k: (v if k in custom else v[0])
                for k, v in eph_state.items()
            }
            st = _squeeze_lane_state(state, squeezed)
            _, lane_peval, lane_inc = self._lane_stepper_parts(eph_vals)
            st, active = jax.vmap(lambda s: lane_peval(frag, s))(st)
            limit = jnp.int32(max_rounds if max_rounds > 0 else _INT32_MAX)

            def cond(carry):
                _, act, _, r = carry
                return jnp.logical_and(jnp.any(act > 0), r < limit)

            body = self._lane_body(lane_inc, frag, batch)
            st, active, rounds_v, _ = lax.while_loop(
                cond, body,
                (st, active, jnp.zeros((batch,), jnp.int32),
                 jnp.int32(0)),
            )
            return _unsqueeze_lane_state(st, squeezed), rounds_v, active

        def compile_for(state):
            specs, squeezed = self._key_specs_batch(state)
            carry_specs = {k: v for k, v in specs.items() if k not in eph}
            eph_specs = {k: v for k, v in specs.items() if k in eph}
            sm = compat.shard_map(
                partial(stepper, squeezed=squeezed),
                mesh=mesh,
                in_specs=(frag_spec, carry_specs, eph_specs),
                out_specs=(carry_specs, P(), P()),
                check_vma=False,
            )
            return jax.jit(sm, donate_argnums=(1,))

        return compile_for

    def _make_batched_chunk_runner(self, chunk: int, max_rounds: int,
                                   batch: int):
        """Batched analogue of _make_chunk_runner for the guarded serve
        path: runs up to `chunk` global supersteps from an arbitrary
        (per-lane active, per-lane rounds, global round) entry point,
        emitting a per-lane carry digest + masked residual as extra
        outputs of the same dispatch.  No carry donation — the per-lane
        guard probes read the pre-chunk carry."""
        app = self.app
        mesh, frag_spec = self._mesh_layout()
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        custom = frozenset(app.custom_specs())

        def stepper(frag_stacked, state, eph_state, active0, rv0, r0,
                    squeezed):
            frag = frag_stacked.local()
            eph_vals = {
                k: (v if k in custom else v[0])
                for k, v in eph_state.items()
            }
            st = _squeeze_lane_state(state, squeezed)
            _, _, lane_inc = self._lane_stepper_parts(eph_vals)
            limit = jnp.int32(max_rounds if max_rounds > 0 else _INT32_MAX)
            stop = jnp.minimum(jnp.int32(r0) + jnp.int32(chunk), limit)

            def cond(carry):
                _, act, _, r = carry
                return jnp.logical_and(jnp.any(act > 0), r < stop)

            body = self._lane_body(lane_inc, frag, batch)
            st, active, rv, r = lax.while_loop(
                cond, body,
                (st, jnp.asarray(active0, jnp.int32),
                 jnp.asarray(rv0, jnp.int32), jnp.int32(r0)),
            )
            return _unsqueeze_lane_state(st, squeezed), rv, active, r

        def compile_for(state):
            specs, squeezed = self._key_specs_batch(state)
            carry_specs = {k: v for k, v in specs.items() if k not in eph}
            eph_specs = {k: v for k, v in specs.items() if k in eph}
            sm = compat.shard_map(
                partial(stepper, squeezed=squeezed),
                mesh=mesh,
                in_specs=(frag_spec, carry_specs, eph_specs, P(), P(), P()),
                out_specs=(carry_specs, P(), P(), P()),
                check_vma=False,
            )

            from libgrape_lite_tpu.guard.watchdog import carry_digest

            float_keys = sorted(
                k for k, v in state.items()
                if k not in eph and np.dtype(v.dtype).kind == "f"
            )

            def lane_residual(out_f, st_f):
                diffs = [
                    jnp.max(jnp.where(
                        jnp.isfinite(d), d, jnp.float32(0)
                    ))
                    for k in float_keys
                    for d in [jnp.abs(
                        out_f[k].astype(jnp.float32)
                        - st_f[k].astype(jnp.float32)
                    )]
                ]
                return jnp.max(jnp.stack(diffs))

            def with_digest(frag_stacked, st, eph_state, active0, rv0, r0):
                out, rv, active, r = sm(
                    frag_stacked, st, eph_state, active0, rv0, r0
                )
                dig = jax.vmap(carry_digest)(out)  # [B, 2]
                if float_keys:
                    res = jax.vmap(lane_residual)(
                        {k: out[k] for k in float_keys},
                        {k: st[k] for k in float_keys},
                    )
                else:
                    res = jnp.full((batch,), jnp.float32(-1))
                return out, rv, active, r, dig, res

            return jax.jit(with_digest)

        return compile_for

    def _batched_runner_for(self, max_rounds: int, batch: int, state):
        key = (
            "batched", batch, max_rounds,
            self.app.trace_key(),
            self._state_struct(state),
        )
        return self._cached_runner(
            key,
            lambda: self._make_batched_runner(max_rounds, batch)(state),
        )

    def _batched_chunk_runner_for(self, chunk: int, max_rounds: int,
                                  batch: int, state):
        key = (
            "batched-chunk", chunk, batch, max_rounds,
            self.app.trace_key(),
            self._state_struct(state),
        )
        return self._cached_runner(
            key,
            lambda: self._make_batched_chunk_runner(
                chunk, max_rounds, batch
            )(state),
        )

    def query_batch(self, args_list, max_rounds: int | None = None, *,
                    guard=None):
        """Run k point queries as ONE vmapped dispatch over the shared
        fragment (serve/, ROADMAP item 1): `args_list` carries one
        query-arg dict per lane (e.g. [{"source": 3}, {"source": 9}]).
        Per-lane results are byte-identical to k sequential
        `Worker.query` runs (freeze-masked lanes, pinned by
        tests/test_serve.py); per-lane round counts land in
        `batch_rounds`, per-lane terminate codes in `batch_terminate`,
        and lane b's carry is `batch_lane_state(b)`.

        Guarded batched execution (per-lane monitors, breach isolation)
        is driven by serve/batch.py — `guard` here routes there."""
        self._check_batchable()
        # BEFORE the guard routing: the guarded batch path must reject
        # a stale dyn view exactly like the plain one
        self._check_dyn_view()
        app = self.app
        frag = self.fragment
        mr = app.max_rounds if max_rounds is None else max_rounds
        self._guard_monitor = None
        self.last_stage_ns = None

        from libgrape_lite_tpu.guard.config import GuardConfig

        guard_cfg = GuardConfig.resolve(guard)
        if guard_cfg.enabled:
            from libgrape_lite_tpu.serve.batch import run_guarded_batch

            return run_guarded_batch(self, args_list, mr, guard_cfg)

        import time as _time

        t_host0 = _time.perf_counter_ns()
        batch = len(args_list)
        state = self._place_state_batch(
            app.init_state_batch(frag, args_list)
        )
        runner = self._batched_runner_for(mr, batch, state)
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        carry = {k: v for k, v in state.items() if k not in eph}
        eph_part = {k: v for k, v in state.items() if k in eph}
        tr = obs.tracer()
        try:
            with tr.span("query", mode="batched",
                         app=type(app).__name__, batch=batch) as sp:
                out_state, rounds_v, active_v = runner(
                    frag.dev, carry, eph_part
                )
                t_enq = _time.perf_counter_ns()
                sp.mark("dispatched")
                out_state = jax.block_until_ready(out_state)
                rv = np.asarray(rounds_v)
                av = np.asarray(active_v)
                self.last_stage_ns = {
                    "dispatch": t_enq - t_host0,
                    "device": _time.perf_counter_ns() - t_enq,
                }
                self.batch_rounds = rv
                self.batch_terminate = np.minimum(0, av)
                self.batch_breaches = [None] * batch
                self.rounds = int(rv.max()) if batch else 0
                self._terminate_code = (
                    int(self.batch_terminate.min()) if batch else 0
                )
                if tr.enabled:
                    # each lane pays PEval + its own counted IncEvals,
                    # all inside the single batched dispatch (frozen-
                    # lane recomputes are discarded, not counted)
                    obs.metrics().counter(
                        "grape_supersteps_total"
                    ).inc(int(rv.sum()) + batch)
                    sp.set(lane_rounds=[int(x) for x in rv])
                self._finish_query_obs(sp)
        finally:
            if tr.enabled:
                obs.flush()
        self._result_state = {**out_state, **eph_part}
        self._result_fragment = self.fragment
        return self._result_state

    def batch_lane_state(self, lane: int):
        """Lane `lane`'s carry view of the last query_batch result
        (ephemeral leaves are shared, not sliced)."""
        if self._result_state is None or self.batch_rounds is None:
            raise RuntimeError("query_batch() first")
        eph = frozenset(getattr(self.app, "ephemeral_keys", ()) or ())
        return {
            k: (v if k in eph else v[lane])
            for k, v in self._result_state.items()
        }

    def batch_result_values(self, lane: int) -> np.ndarray:
        """Per-vertex assembled values for one lane, [fnum, vp] numpy."""
        host = jax.device_get(self.batch_lane_state(lane))
        return self.app.finalize(self.fragment, host)

    def query_batch_prepare(self, args_list,
                            max_rounds: int | None = None, *,
                            guard=None) -> PreparedBatch:
        """Do the HOST half of a batched dispatch — same checks, same
        state build/placement, same cached runner as `query_batch`
        (so a W=1 pump is byte-identical to the synchronous loop) —
        and return a PreparedBatch whose `launch()` enqueues the
        execution.  The async serve pump (serve/pipeline.py) prepares
        ahead under its window and staggers launches; the worker's own
        per-query result fields are left untouched, so W dispatches
        can coexist.

        Guarded batches defer the whole chunked per-lane monitor loop
        (serve/batch.py) to launch() — breach isolation needs probe
        verdicts, which sync at every chunk boundary by design — and
        their verdict arrays are SNAPSHOT into the launched handle, so
        a guarded batch mid-window never clobbers a neighbour's
        verdicts and its per-lane values still harvest lazily."""
        self._check_batchable()
        self._check_dyn_view()
        app = self.app
        frag = self.fragment
        mr = app.max_rounds if max_rounds is None else max_rounds

        from libgrape_lite_tpu.guard.config import GuardConfig

        guard_cfg = GuardConfig.resolve(guard)
        batch = len(args_list)
        if guard_cfg.enabled:
            return PreparedBatch(
                worker=self, app=app, fragment=frag, batch=batch,
                guarded=True,
                guard_args=(list(args_list), mr, guard_cfg),
            )

        state = self._place_state_batch(
            app.init_state_batch(frag, args_list)
        )
        runner = self._batched_runner_for(mr, batch, state)
        # AFTER init_state_batch: overlay-contracted apps extend their
        # ephemeral set there (dyn edge streams ride as shared eph
        # leaves), exactly as query_batch reads it
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        carry = {k: v for k, v in state.items() if k not in eph}
        eph_part = {k: v for k, v in state.items() if k in eph}
        return PreparedBatch(
            worker=self, app=app, fragment=frag, eph=eph,
            runner=runner, carry=carry, eph_part=eph_part, batch=batch,
        )

    def query_batch_dispatch(self, args_list,
                             max_rounds: int | None = None, *,
                             guard=None) -> BatchDispatch:
        """Prepare AND launch in one call: k point queries dispatched
        without waiting, outputs riding back un-synced in a
        self-contained BatchDispatch (JAX async dispatch).  The
        one-shot surface for callers that do not stagger launches."""
        return self.query_batch_prepare(
            args_list, max_rounds, guard=guard
        ).launch()

    def query(self, max_rounds: int | None = None, *,
              checkpoint_every: int | None = None,
              checkpoint_dir: str | None = None,
              fault_plan=None, guard=None, **query_args):
        """Run one query (reference `Worker::Query`, worker.h:104-146).

        `checkpoint_every=K` + `checkpoint_dir` degrade the fused loop
        to stepwise execution with a carry snapshot every K supersteps
        (ft/checkpoint.py); `checkpoint_every=None` (default) leaves
        the fused `shard_map(while_loop)` fast path untouched.

        `guard` arms the runtime invariant monitor (guard/):
        GuardConfig, a policy string ("warn"|"halt"|"rollback"), or
        None to read GRAPE_GUARD from the env.  With guards off (the
        default) this method compiles exactly the trace it always has —
        the guard decision is a host-side env read, so the fused fast
        path is byte-identical and zero-overhead.  Guards on: the loop
        runs in fused chunks of GRAPE_GUARD_EVERY supersteps with an
        invariant probe + watchdog digest at every boundary.

        Guards + checkpointing compose WITHOUT the stepwise degrade
        when `checkpoint_every` is a multiple of the guard chunk size:
        chunk boundaries are consistent cuts, so snapshots come
        straight from the chunk outputs (probed first — a state that
        fails its invariants never becomes a rollback target) and the
        inner loop stays the fused while_loop.  Misaligned cadences,
        and checkpointing without guards, keep the stepwise path."""
        from libgrape_lite_tpu.guard.config import GuardConfig

        app = self.app
        self._check_dyn_view()
        self.last_stage_ns = None
        if checkpoint_every is not None or checkpoint_dir is not None:
            guard_cfg = GuardConfig.resolve(guard)
            if (
                guard_cfg.enabled
                and checkpoint_every and checkpoint_dir
                and checkpoint_every % guard_cfg.every == 0
                and not getattr(app, "host_only", False)
                and not hasattr(app, "collect_mutations")
                and jax.process_count() == 1
            ):
                mr = app.max_rounds if max_rounds is None else max_rounds
                return self._query_guarded(
                    mr, guard_cfg,
                    checkpoint_every=checkpoint_every,
                    checkpoint_dir=checkpoint_dir,
                    fault_plan=fault_plan, **query_args,
                )
            return self.query_stepwise(
                max_rounds, checkpoint_every=checkpoint_every,
                checkpoint_dir=checkpoint_dir, fault_plan=fault_plan,
                guard=guard, **query_args,
            )
        frag = self.fragment
        mr = app.max_rounds if max_rounds is None else max_rounds
        self._guard_monitor = None

        guard_cfg = GuardConfig.resolve(guard)
        if guard_cfg.enabled:
            if getattr(app, "host_only", False):
                if not getattr(app, "host_guard", False):
                    from libgrape_lite_tpu.utils import logging as glog

                    glog.log_info(
                        "guard: host-only apps have no superstep carry "
                        "to monitor; guards are inert for "
                        f"{type(app).__name__}"
                    )
            elif hasattr(app, "collect_mutations"):
                # MutationContext apps run stepwise with a mutation-
                # aware monitor (digest history resets at boundaries)
                return self.query_stepwise(
                    max_rounds, guard=guard, **query_args
                )
            else:
                return self._query_guarded(
                    mr, guard_cfg, fault_plan=fault_plan, **query_args
                )

        tr = obs.tracer()
        if getattr(app, "host_only", False):
            # host-engine apps (irregular recursion, e.g. kclique) skip
            # the traced superstep loop entirely; iterative ones honor
            # the same round bound as everyone else
            import inspect

            kwargs = dict(query_args)
            if "max_rounds" in inspect.signature(app.host_compute).parameters:
                kwargs["max_rounds"] = mr
            if getattr(app, "host_guard", False):
                # guard-capable host loops (exchange apps) run their
                # own round-boundary probes; hand them THIS query's
                # RESOLVED config — enabled or not — so
                # Worker.query(guard=...) arms them like any superstep
                # app AND an explicit guard="off" genuinely disarms an
                # env-armed GRAPE_GUARD (the hooks fall back to the
                # env only when no worker handed them a config)
                app._host_guard_cfg = guard_cfg
            try:
                with tr.span("query", mode="host",
                             app=type(app).__name__) as sp:
                    self._result_state = app.host_compute(frag, **kwargs)
                    self._result_fragment = self.fragment
                    self.rounds = getattr(app, "rounds", 0)
                    self._finish_query_obs(sp)
            finally:
                # a breach raise must still surface the monitor (for
                # guard_report) and land its spans in the file sinks
                self._guard_monitor = getattr(
                    app, "_host_guard_monitor", None
                )
                if tr.enabled:
                    obs.flush()
            return self._result_state

        if hasattr(app, "collect_mutations"):
            # MutationContext apps need the host between supersteps;
            # the fused while_loop cannot rebuild the fragment mid-loop
            return self.query_stepwise(max_rounds, **query_args)

        import time as _time

        t_host0 = _time.perf_counter_ns()
        state = self._place_state(
            self._seeded(app.init_state(frag, **query_args))
        )
        runner = self._runner_for(mr, state)
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        carry = {k: v for k, v in state.items() if k not in eph}
        eph_part = {k: v for k, v in state.items() if k in eph}
        # the whole PEval+IncEval loop is one dispatch: the span's
        # dispatch/device split is the honest granularity here (per-
        # superstep spans need the stepwise or guarded-chunked paths)
        try:
            with tr.span("query", mode="fused",
                         app=type(app).__name__) as sp:
                if tr.enabled and self._pipelined() is not None:
                    # modeled overlap next to the measured dispatch/
                    # device split, in the same record (r9):
                    # trace_report derives overlap_hidden_us from it
                    sp.set(pipeline=self._pipelined().span_brief())
                out_state, rounds, active = runner(
                    frag.dev, carry, eph_part
                )
                t_enq = _time.perf_counter_ns()
                if getattr(self, "_last_runner_miss", False):
                    # fresh compile rode inside this enqueue: stamp it
                    # so truth.py excludes the query from the measured
                    # round wall (compile would launder the claim)
                    sp.mark("compiled")
                sp.mark("dispatched")
                out_state = jax.block_until_ready(out_state)
                self.rounds = int(rounds)
                self._terminate_code = min(0, int(active))
                self.last_stage_ns = {
                    "dispatch": t_enq - t_host0,
                    "device": _time.perf_counter_ns() - t_enq,
                }
                if tr.enabled:
                    # PEval + one IncEval per counted round, all
                    # inside the single fused dispatch
                    obs.metrics().counter(
                        "grape_supersteps_total"
                    ).inc(self.rounds + 1)
                    if self._pipelined() is not None:
                        # the modeled hidden-exchange split next to
                        # the measured dispatch/device marks (r9):
                        # trace_report's overlap column reads this
                        sp.set(overlap_hidden_us=round(
                            self._pipelined().hidden_us_per_round()
                            * self.rounds, 1))
                self._finish_query_obs(sp)
        finally:
            if tr.enabled:
                obs.flush()
        self._result_state = out_state
        self._result_fragment = self.fragment
        return out_state

    def query_incremental(self, prev_result, delta=None,
                          max_rounds: int | None = None, *,
                          prev_fragment=None, guard=None,
                          checkpoint_every: int | None = None,
                          checkpoint_dir: str | None = None,
                          fault_plan=None, **query_args):
        """Incremental IncEval (dyn/, PIE's headline capability): run
        this query seeded from `prev_result` — the state dict a
        previous `query` of the SAME app and args returned on the
        pre-delta graph — re-converging only the region the delta
        touched instead of recomputing from scratch.

        `delta` describes the staged change (a dyn.DeltaBuffer or its
        `summary()`); the app's `inc_mode` contract decides the path:

          * "monotone-min" + additive delta -> the carry is seeded with
            `min(fresh_init, migrated prev)` per `inc_seed_keys` key —
            EXACT, byte-identical to a cold full query on the mutated
            graph (the monotone-operator argument lives in
            dyn/incremental.py), typically in a fraction of the rounds;
          * anything else -> a cold full query through the same API,
            counted in `inc_stats["cold"]` — an honest fallback, never
            a silent wrong answer.

        `prev_fragment` names the fragment `prev_result` was computed
        on when a repack replaced it (rows migrate by oid, values remap
        via the app's `inc_value_map`).  Default: the fragment THIS
        worker's last query ran on (`_result_fragment`) — so the
        resident-worker pattern (query, session repack rebinds
        `self.fragment`, query_incremental) migrates correctly without
        the caller naming the old fragment; a prev_result imported
        from a DIFFERENT worker across a repack must pass it
        explicitly (falling back to the current fragment would attach
        old rows to renumbered vertices).  Composes with guard/ and
        ft/ exactly like `query` — the seeded run is an ordinary query
        with a different starting carry, so checkpoints taken inside
        it resume byte-identically through the mutation boundary."""
        from libgrape_lite_tpu.dyn.incremental import (
            incremental_plan,
            reseed_fold,
        )
        from libgrape_lite_tpu.utils import logging as glog

        app = self.app
        mode, reason = incremental_plan(app, delta)
        self.inc_report = {"mode": mode, "reason": reason}
        self.inc_stats[mode] += 1
        obs.tracer().instant("query_incremental", mode=mode)
        if mode == "cold":
            glog.vlog(
                1, "query_incremental: cold recompute (%s)", reason
            )
            return self.query(
                max_rounds, guard=guard,
                checkpoint_every=checkpoint_every,
                checkpoint_dir=checkpoint_dir, fault_plan=fault_plan,
                **query_args,
            )
        prev_frag = (
            prev_fragment or self._result_fragment or self.fragment
        )
        host_prev = {
            k: np.asarray(jax.device_get(prev_result[k]))
            for k in app.inc_seed_keys
            if k in prev_result
        }
        self._seed_fn = lambda fresh: {
            **fresh,
            **reseed_fold(app, self.fragment, fresh, prev_frag,
                          host_prev),
        }
        try:
            return self.query(
                max_rounds, guard=guard,
                checkpoint_every=checkpoint_every,
                checkpoint_dir=checkpoint_dir, fault_plan=fault_plan,
                **query_args,
            )
        finally:
            self._seed_fn = None

    def _ledger_brief(self):
        """Scalar totals of the engaged pack ledger (the query span's
        modeled-cost attachment: modeled ops/bytes sit next to the
        measured wall/device time in ONE record — the side-by-side the
        SparseP-style roofline accounting needs)."""
        led = self.pack_ledger()
        if not led:
            return None
        t = led["totals"]
        return {
            "edges": led["edges"],
            "vpu_ops": t["vpu_ops"],
            "mxu_ops": t["mxu_ops"],
            "gather_rows": t["gather_rows"],
            "hbm_bytes": t["hbm_bytes"],
            "blocks": t["blocks"],
        }

    def _finish_query_obs(self, sp):
        """Armed-query close-out: ledger totals + round count onto the
        query span, registry roll-ups.  A no-op when obs/ is disarmed
        (the caller passed the shared null span)."""
        if not obs.armed():
            return
        sp.set(rounds=self.rounds, terminate_code=self._terminate_code)
        led = self._ledger_brief()
        m = obs.metrics()
        m.counter("grape_queries_total").inc()
        m.gauge("grape_query_rounds").set(self.rounds)
        if led is not None:
            sp.set(pack_ledger=led)
            m.gauge("grape_pack_edges").set(led["edges"])
            m.gauge("grape_pack_hbm_bytes").set(led["hbm_bytes"])
            m.gauge("grape_pack_vpu_ops").set(led["vpu_ops"])
            m.gauge("grape_pack_mxu_ops").set(led["mxu_ops"])
        # 2-D vertex-cut queries attach their tile layout to the query
        # span (r10): trace_report renders per-tile rows + the
        # max-tile-skew column from exactly this record
        part = getattr(self.app, "_partition_stats", None)
        if part is not None:
            record = {
                "mode": getattr(self.app, "_partition", "2d"),
                "k": part["k"],
                "max_tile_edges": part["max_tile_edges"],
                "mean_tile_edges": part["mean_tile_edges"],
                "tile_skew": part["tile_skew"],
                "per_tile": part["per_tile"],
            }
            if "plan_uid" in part:
                # the R12 correlation key: the truth meter joins this
                # record against the modeled pipeline decision
                record["plan_uid"] = part["plan_uid"]
            sp.set(partition=record)
        # guard probe/breach/rollback counts live in the counters the
        # monitor itself maintains at the event sites — no duplicate
        # gauges here that could disagree after an aborted query

    def _mirror_superstep(self, tr, sp, rounds: int, name: str) -> None:
        """Re-emit a closed superstep span on every per-fragment track:
        SPMD execution is lockstep across the mesh, so the host wall
        interval IS each fragment's interval — multi-frag meshes render
        as parallel rows in Perfetto."""
        if self.fragment.fnum <= 1:
            return
        for f in range(self.fragment.fnum):
            tr.emit_span_raw(
                name, t0_ns=sp.t0_ns, dur_ns=sp.dur_ns,
                tid=tr.frag_tid(f), round=rounds, frag=f,
            )

    def _query_guarded(self, mr: int, guard_cfg, *,
                       checkpoint_every: int | None = None,
                       checkpoint_dir: str | None = None,
                       fault_plan=None, **query_args):
        """Guarded-fused query: PEval once, then fused IncEval chunks
        of `guard_cfg.every` supersteps with an invariant probe +
        watchdog digest at every chunk boundary — a breach is detected
        within one cadence while the inner loop stays the fused
        `shard_map(while_loop)`.  Policies: warn logs and continues,
        halt raises with the diagnostic bundle.

        With `checkpoint_every` (a multiple of the chunk size — query()
        enforces the alignment) snapshots are taken straight from the
        chunk outputs at matching boundaries, AFTER the probe (a state
        that fails its invariants never becomes the rollback target),
        and the rollback policy self-heals in place: restore the last
        good snapshot, rewind (rounds, active), and replay in paranoid
        mode (chunk size 1, so a recurring deterministic fault is
        localized to its exact superstep) — no stepwise degrade.
        Fault-injection hooks (GRAPE_FT_FAULTS / `fault_plan`) fire at
        chunk boundaries, the guarded path's consistent cuts."""
        from libgrape_lite_tpu.guard.monitor import GuardMonitor
        from libgrape_lite_tpu.utils import logging as glog

        app = self.app
        frag = self.fragment
        if mr <= 0:  # 0 = run until the termination vote fires
            mr = _INT32_MAX

        if fault_plan is None:
            from libgrape_lite_tpu.ft.faults import active_plan

            fault_plan = active_plan()
        if fault_plan.is_noop():
            fault_plan = None

        state = self._place_state(
            self._seeded(app.init_state(frag, **query_args))
        )
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        eph_part = {k: v for k, v in state.items() if k in eph}

        def carry_of(st):
            return {k: v for k, v in st.items() if k not in eph}

        ckpt = None
        if checkpoint_every:
            from libgrape_lite_tpu.ft.checkpoint import CheckpointManager
            from libgrape_lite_tpu.ft.fingerprint import (
                canonical_query_args, compute_fingerprint,
            )

            ckpt = CheckpointManager(
                checkpoint_dir,
                fingerprint=compute_fingerprint(app, frag, query_args),
                query_args=canonical_query_args(query_args),
                checkpoint_every=checkpoint_every,
                fresh_start=True,
            )

        monitor = GuardMonitor(
            app=app, frag=frag, config=guard_cfg, ckpt=ckpt,
            ledger=self.pack_ledger(),
        )
        self._guard_monitor = monitor
        glog.vlog(
            1, "guard: fused chunks of %d supersteps (policy=%s%s)",
            guard_cfg.every, guard_cfg.policy,
            f", snapshots every {checkpoint_every}" if ckpt else "",
        )

        tr = obs.tracer()
        try:
            with tr.span("query", mode="guarded-fused",
                         app=type(app).__name__) as qsp:
                if tr.enabled and self._pipelined() is not None:
                    qsp.set(pipeline=self._pipelined().span_brief())
                peval_fn = self._single_step_for("peval", state)
                prev = carry_of(state)
                with tr.span("peval") as sp:
                    out = peval_fn(frag.dev, state)
                    sp.mark("dispatched")
                    carry, active = jax.block_until_ready(out)
                    sp.set(active=int(active))
                if tr.enabled:
                    obs.metrics().counter(
                        "grape_supersteps_total"
                    ).inc()
                rounds = 0
                if fault_plan is not None:
                    corrupted = fault_plan.maybe_corrupt_carry(carry, 0)
                    if corrupted is not None:
                        carry = {**carry, **self._place_state(corrupted)}
                if int(active) >= 0:
                    # a PEval breach has no snapshot to restore — any
                    # non-warn verdict halts
                    breach = monitor.check(prev, carry, 0, int(active))
                    if breach is not None:
                        monitor.raise_breach(breach)
                if ckpt is not None:
                    # a superstep-0 snapshot always exists, so a breach
                    # at any later chunk has something to fall back to
                    ckpt.save_async(carry, 0, int(active))
                if fault_plan is not None:
                    fault_plan.on_superstep(0, ckpt)
                chunk_fn = self._chunk_runner_for(
                    guard_cfg.every, mr, state
                )
                chunk1_fn = None  # paranoid replay compiles lazily
                prev = carry
                while int(active) > 0 and rounds < mr:
                    cf = chunk_fn
                    if monitor.paranoid:
                        if chunk1_fn is None:
                            chunk1_fn = self._chunk_runner_for(
                                1, mr, state
                            )
                        cf = chunk1_fn
                    r0 = rounds
                    with tr.span("chunk", start_round=r0) as sp:
                        out = cf(frag.dev, carry, eph_part,
                                 jnp.int32(int(active)),
                                 jnp.int32(rounds))
                        sp.mark("dispatched")
                        new_carry, r2, new_active, dig, res = (
                            jax.block_until_ready(out)
                        )
                        sp.set(end_round=int(r2), active=int(new_active))
                    rounds = int(r2)
                    if tr.enabled:
                        tr.counter("active_vertices",
                                   value=int(new_active))
                        m = obs.metrics()
                        # every superstep inside the chunk counts; the
                        # active series only has chunk-BOUNDARY samples
                        # here (the in-chunk votes never reach the
                        # host) — documented in docs/OBSERVABILITY.md
                        m.counter("grape_supersteps_total").inc(
                            rounds - r0
                        )
                        m.series("grape_active_per_round").append(
                            int(new_active)
                        )
                    carry, active = new_carry, new_active
                    # injected corruption lands BEFORE the probe (same-
                    # round detection) and before the save; a corrupted
                    # carry invalidates the in-dispatch digest/residual,
                    # so the monitor re-probes fully
                    digest = tuple(int(x) for x in np.asarray(dig))
                    res_f = float(res)
                    residual = None if res_f < 0 else res_f
                    if fault_plan is not None:
                        corrupted = fault_plan.maybe_corrupt_carry(
                            carry, rounds
                        )
                        if corrupted is not None:
                            carry = {
                                **carry, **self._place_state(corrupted)
                            }
                            digest = residual = None
                    if int(active) >= 0:
                        breach = monitor.check(
                            prev, carry, rounds, int(active),
                            digest=digest, residual=residual,
                        )
                        if breach is not None:
                            if breach.action == "rollback":
                                restored, meta = monitor.rollback(breach)
                                carry = self._place_state(restored)
                                rounds = int(meta["rounds"])
                                active = np.int32(meta["active"])
                                prev = carry
                                # the rollback rewinds past this
                                # boundary's save and injection hooks
                                continue
                            monitor.raise_breach(breach)
                    prev = carry
                    if (
                        ckpt is not None
                        and rounds % checkpoint_every == 0
                        and rounds > 0
                    ):
                        ckpt.save_async(carry, rounds, int(active))
                    if fault_plan is not None:
                        fault_plan.on_superstep(rounds, ckpt)
                self.rounds = rounds
                self._terminate_code = min(0, int(active))
                if tr.enabled and self._pipelined() is not None:
                    qsp.set(overlap_hidden_us=round(
                        self._pipelined().hidden_us_per_round()
                        * self.rounds, 1))
                self._finish_query_obs(qsp)
        finally:
            # flush in finally: a halt-policy breach raises out of the
            # span context, and its guard_breach instant must still
            # land in the file sinks, not wait for the atexit hook;
            # the in-flight snapshot must land durable the same way
            if ckpt is not None:
                ckpt.close()
            if tr.enabled:
                obs.flush()
        self._result_state = {**carry, **eph_part}
        self._result_fragment = self.fragment
        return self._result_state

    def _place_state(self, state_np):
        """Place the init state: sharded leaves over the frag axis,
        declared-replicated leaves everywhere, custom-spec leaves per
        their declared PartitionSpec.  Multi-process meshes go through
        `put_global` (every process holds the same host arrays)."""
        from libgrape_lite_tpu.parallel.comm_spec import put_global

        mesh, _ = self._mesh_layout()
        specs, _ = self._key_specs(state_np)
        return {
            k: put_global(v, NamedSharding(mesh, specs[k]))
            for k, v in state_np.items()
        }

    def _compile_single_step(self, kind: str, state):
        """One jitted (PEval | IncEval) superstep — the unfused building
        block shared by query_stepwise; `query` fuses the whole loop via
        _make_runner instead."""
        app = self.app
        mesh, frag_spec = self._mesh_layout()
        specs, squeezed = self._key_specs(state)
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        out_specs = {k: v for k, v in specs.items() if k not in eph}

        def fn(frag_stacked, st):
            lf = frag_stacked.local()
            s = _squeeze_state(st, squeezed)
            from libgrape_lite_tpu.app.base import StepContext

            ctx = StepContext()
            s2, active = (
                app.peval(ctx, lf, s) if kind == "peval"
                else app.inceval(ctx, lf, s)
            )
            s2 = {k: v for k, v in s2.items() if k not in eph}
            return _unsqueeze_state(s2, squeezed), jnp.int32(active)

        return jax.jit(
            compat.shard_map(
                fn, mesh=mesh, in_specs=(frag_spec, specs),
                out_specs=(out_specs, P()), check_vma=False,
            )
        )

    def _compile_batched_step(self, kind: str, state, batch: int):
        """One jitted vmapped (PEval | IncEval) superstep over the lane
        axis — the guarded serve path's building block (serve/batch.py
        drives PEval once, then batched chunks)."""
        mesh, frag_spec = self._mesh_layout()
        specs, squeezed = self._key_specs_batch(state)
        eph = frozenset(getattr(self.app, "ephemeral_keys", ()) or ())
        out_specs = {k: v for k, v in specs.items() if k not in eph}

        def fn(frag_stacked, st):
            lf = frag_stacked.local()
            eph_state = {k: st[k] for k in eph}
            eph_vals = {k: v[0] for k, v in eph_state.items()}
            s = _squeeze_lane_state(
                {k: v for k, v in st.items() if k not in eph}, squeezed
            )
            _, lane_peval, lane_inc = self._lane_stepper_parts(eph_vals)
            lane = lane_peval if kind == "peval" else lane_inc
            s2, active = jax.vmap(lambda x: lane(lf, x))(s)
            return _unsqueeze_lane_state(s2, squeezed), active

        return jax.jit(
            compat.shard_map(
                fn, mesh=mesh, in_specs=(frag_spec, specs),
                out_specs=(out_specs, P()), check_vma=False,
            )
        )

    def _single_step_for(self, kind: str, state):
        """Cached _compile_single_step: the stepwise and guarded
        paths previously minted a fresh jit wrapper per query, so
        every stepwise profile run and every guarded query re-traced
        and re-compiled its PEval/IncEval step — invisible to
        runner_cache_stats, visible to analysis.compile_events()
        (grape-lint R2; the same class as PR 6's guarded-serve
        per-batch re-jit)."""
        key = (
            "step", kind,
            self.app.trace_key(),
            self._state_struct(state),
        )
        return self._cached_runner(
            key, lambda: self._compile_single_step(kind, state)
        )

    def _batched_step_for(self, kind: str, state, batch: int):
        """Cached _compile_batched_step: a serve session dispatches
        many guarded batches of the same shape, and each fresh jit
        wrapper would retrace + recompile the identical vmapped PEval
        (invisible to runner_cache_stats — the zero-recompile
        accounting must see it)."""
        key = (
            "batched-step", kind, batch,
            self.app.trace_key(),
            self._state_struct(state),
        )
        return self._cached_runner(
            key,
            lambda: self._compile_batched_step(kind, state, batch),
        )

    def query_stepwise(self, max_rounds: int | None = None, *,
                       checkpoint_every: int | None = None,
                       checkpoint_dir: str | None = None,
                       fault_plan=None, guard=None, _resume: bool = False,
                       **query_args):
        """Host-driven query: one jitted superstep per round with
        per-round wall time + termination-vote logs — the observable
        behavior of the reference's coordinator logs (`worker.h:120-139`)
        and -DPROFILING timers.  Also the execution mode for
        MutationContext apps (`query` routes them here), since the graph
        can be rebuilt between rounds, and for checkpointed queries
        (`checkpoint_every=K` snapshots the carry pytree every K
        supersteps via ft/checkpoint.py).  Slower than the fused `query`
        (host sync per round); results are identical for mutation-free
        apps.

        With obs/ armed, every round emits a `superstep` span.  Timing
        convention (documented on tracer.Span): the clock stops only
        AFTER `jax.block_until_ready` on the round's full carry, so
        `dur` is honest wall time; the `dispatched` mark splits it
        into `dispatched_us` (host enqueue — inflated by trace+compile
        on the first round) and `device_wait_us` (the device-execution
        estimate).  Reported vlog times follow the same synced
        interval."""
        # public entry point too (profiling surface): an uncontracted
        # app must fail loudly on a staged dyn view here as well
        self._check_dyn_view()
        tr = obs.tracer()
        if not tr.enabled:
            return self._query_stepwise_impl(
                max_rounds, checkpoint_every=checkpoint_every,
                checkpoint_dir=checkpoint_dir, fault_plan=fault_plan,
                guard=guard, _resume=_resume, **query_args,
            )
        try:
            with tr.span("query", mode="stepwise",
                         app=type(self.app).__name__) as sp:
                if self._pipelined() is not None:
                    # same record the fused path emits: the overlap
                    # truth meter joins the superstep spans inside
                    # this query window against this modeled brief
                    sp.set(pipeline=self._pipelined().span_brief())
                out = self._query_stepwise_impl(
                    max_rounds, checkpoint_every=checkpoint_every,
                    checkpoint_dir=checkpoint_dir, fault_plan=fault_plan,
                    guard=guard, _resume=_resume, **query_args,
                )
                self._finish_query_obs(sp)
        finally:
            # flush in finally: a breach/fault raising out of the loop
            # must still land its spans + instants in the file sinks
            obs.flush()
        return out

    def _query_stepwise_impl(self, max_rounds: int | None = None, *,
                             checkpoint_every: int | None = None,
                             checkpoint_dir: str | None = None,
                             fault_plan=None, guard=None,
                             _resume: bool = False, **query_args):
        import time

        from libgrape_lite_tpu.utils import logging as glog

        tr = obs.tracer()

        app = self.app
        frag = self.fragment
        has_mutations = hasattr(app, "collect_mutations")
        if checkpoint_dir and checkpoint_every is None and not _resume:
            raise ValueError(
                "checkpoint_dir requires checkpoint_every (a dir alone "
                "would run stepwise while writing no snapshots); to "
                "continue a previous run use Worker.resume"
            )
        checkpointing = checkpoint_every is not None or _resume
        if checkpointing:
            if getattr(app, "host_only", False):
                raise ValueError(
                    "checkpointing requires the superstep path; "
                    f"{type(app).__name__} is a host-only app"
                )
            if has_mutations:
                raise ValueError(
                    "checkpointing MutationContext apps is not supported "
                    "(the fragment itself changes between rounds)"
                )
            if not checkpoint_dir:
                raise ValueError("checkpoint_every requires checkpoint_dir")
            if checkpoint_every is not None and checkpoint_every <= 0:
                raise ValueError(
                    f"checkpoint_every must be >= 1, got {checkpoint_every}"
                )
        if getattr(app, "host_only", False):
            return self.query(max_rounds, **query_args)
        mr = app.max_rounds if max_rounds is None else max_rounds
        if mr <= 0:
            mr = _INT32_MAX

        if fault_plan is None:
            from libgrape_lite_tpu.ft.faults import active_plan

            fault_plan = active_plan()
        if fault_plan.is_noop():
            fault_plan = None

        from libgrape_lite_tpu.guard.config import GuardConfig

        guard_cfg = GuardConfig.resolve(guard)
        self._guard_monitor = None

        state_np = self._seeded(app.init_state(frag, **query_args))
        eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
        ckpt = None
        resume_meta = None
        if checkpointing:
            from libgrape_lite_tpu.ft.checkpoint import (
                CheckpointManager, CheckpointMismatchError, latest_meta,
                restore_latest,
            )
            from libgrape_lite_tpu.ft.fingerprint import (
                canonical_query_args, compute_fingerprint,
            )

            distributed = jax.process_count() > 1
            fingerprint = compute_fingerprint(app, frag, query_args)
            if _resume:
                meta0 = latest_meta(checkpoint_dir)
                fp0 = meta0.get("fingerprint", {})
                from libgrape_lite_tpu.ft.distributed import (
                    GEOMETRY_KEYS,
                )

                if meta0.get("layout") == "sharded" and any(
                    fp0.get(k) != fingerprint.get(k)
                    for k in GEOMETRY_KEYS
                ):
                    # reshard-on-loss: the snapshot was written by a
                    # different mesh — a lost rank, a changed fnum, or
                    # the same shape cut differently (fragment_hash);
                    # gather the surviving shard files and scatter the
                    # carry onto THIS mesh's layout
                    from libgrape_lite_tpu.ft.distributed import (
                        restore_resharded,
                    )

                    restored, resume_meta = restore_resharded(
                        checkpoint_dir, frag, fingerprint,
                        base_state={
                            k: v for k, v in state_np.items()
                            if k not in eph
                        },
                    )
                else:
                    restored, resume_meta = restore_latest(
                        checkpoint_dir, fingerprint
                    )
                carry_keys = {k for k in state_np if k not in eph}
                if set(restored) != carry_keys:
                    raise CheckpointMismatchError(
                        f"checkpoint carry keys {sorted(restored)} != "
                        f"this query's carry keys {sorted(carry_keys)}"
                    )
                state_np = {**state_np, **restored}
                if checkpoint_every is None:
                    checkpoint_every = (
                        resume_meta.get("checkpoint_every") or None
                    )
            if checkpoint_every is not None and distributed:
                # the carry spans non-addressable devices: each process
                # writes only its local shards, committed under the
                # two-phase barrier (ft/distributed.py)
                from libgrape_lite_tpu.ft.distributed import (
                    ShardedCheckpointManager,
                )

                ckpt = ShardedCheckpointManager(
                    checkpoint_dir,
                    fingerprint=fingerprint,
                    query_args=canonical_query_args(query_args),
                    checkpoint_every=checkpoint_every,
                    frag=frag,
                    fresh_start=not _resume,
                )
            elif checkpoint_every is not None:
                ckpt = CheckpointManager(
                    checkpoint_dir,
                    fingerprint=fingerprint,
                    query_args=canonical_query_args(query_args),
                    checkpoint_every=checkpoint_every,
                    # a new query starts a new lineage; stale
                    # checkpoints in a reused dir must not shadow it
                    fresh_start=not _resume,
                )

        state = self._place_state(state_np)
        led = self.pack_ledger() if glog.vlog_level() >= 1 else None
        if led:
            # per-stage ALU attribution for the engaged pack plan — the
            # stepwise profile's wall-clock lines read against these
            # modeled shares (first-light playbook step 3); the whole
            # block is gated on the level so a silent run never pays
            # the ledger merge + string build
            t = led["totals"]
            e = max(1, led["edges"])
            stages = ", ".join(
                f"{k}={v / e:.1f}"
                for k, v in sorted(t.get("per_stage", {}).items())
            )
            glog.vlog(
                1,
                f"pack op-budget: {t['vpu_ops'] / e:.1f} VPU ops/edge, "
                f"{t['mxu_ops'] / e:.1f} MXU elems/edge, "
                f"{t['gather_rows'] / e:.2f} gather rows/edge over "
                f"{t['blocks']} blocks / {len(led['levels'])} levels "
                f"(per-stage VPU ops/edge: {stages})",
            )
            if "pipeline" in led:
                p = led["pipeline"]
                glog.vlog(
                    1,
                    "pipeline split: %d boundary / %d interior "
                    "vertices (%d / %d edges), %s exchange, "
                    "%d B/round",
                    p.get("boundary_vertices", 0),
                    p.get("interior_vertices", 0),
                    p.get("boundary_edges", 0),
                    p.get("interior_edges", 0),
                    p.get("mode", "?"), p.get("exchange_bytes", 0),
                )
        inc_fn = self._single_step_for("inceval", state)
        # a fresh-compiled inc_fn means the FIRST superstep dispatch
        # below includes trace+compile: that round's span gets a
        # `compiled` mark so the overlap truth meter can exclude it
        inc_fresh = getattr(self, "_last_runner_miss", False)
        # ephemeral leaves drop out of each step's outputs; re-merge the
        # placed originals so the next step's inputs stay complete
        eph_vals = {k: state[k] for k in eph}

        def carry_of(st):
            return {k: v for k, v in st.items() if k not in eph}

        monitor = None
        if guard_cfg.enabled:
            from libgrape_lite_tpu.guard.monitor import GuardMonitor

            monitor = GuardMonitor(
                app=app, frag=frag, config=guard_cfg, ckpt=ckpt,
                ledger=self.pack_ledger(),
            )
            self._guard_monitor = monitor
            glog.vlog(
                1, "guard: stepwise probes every %d round(s) "
                "(policy=%s)", guard_cfg.every, guard_cfg.policy,
            )
            if has_mutations:
                # MutationContext apps guard too (dyn/): each mutation
                # boundary resets the watchdog digest history and
                # re-resolves the probe — a pre-mutation digest match
                # proves nothing about the REBUILT graph's operator
                glog.vlog(
                    1, "guard: mutation-aware — digest history resets "
                    "at every mutation boundary",
                )

        # cross-rank breach vote (guard/vote.py): armed only under
        # jax.distributed AND only when a hazard hook exists — guard,
        # checkpointing, or an injected fault plan, all of which are
        # env/flag-symmetric across the gang.  Single-process `vote`
        # stays None and voted_hooks degenerates to a plain call, so
        # this path's behavior is bit-identical to the pre-vote code.
        vote = None
        if jax.process_count() > 1 and (
            monitor is not None or ckpt is not None
            or fault_plan is not None
        ):
            from libgrape_lite_tpu.guard.vote import BreachVote

            vote = BreachVote.for_current_process()

        # gang trace federation (obs/gang.py): anchor the clock
        # handshake and land the first per-rank sidecar BEFORE the
        # first vote collective, so even a round-0 halt leaves a
        # mergeable file for the rank-0 assembler.  Symmetric by the
        # same contract as the vote itself: GRAPE_TRACE is documented
        # env-symmetric across the gang.
        gang_armed = vote is not None and tr.enabled
        if gang_armed:
            obs.gang.ensure_handshake()
            obs.gang.write_sidecar()

        def voted_hooks(vote_rounds, hooks):
            """Run one superstep boundary's host-side hazard hooks
            (probe / snapshot / fault injection) under the breach
            vote: every rank exchanges a verdict at this same cut, so
            a one-rank halt (InvariantBreachError, DivergenceError,
            InjectedFault, an IO error in a hook) halts EVERY rank
            instead of stranding siblings in the next collective.  A
            halt raised by the vote (local err re-raise or
            RemoteBreachError) first triggers the distributed flight
            recorder: every rank dumps its postmortem shard under the
            shared incident id the vote derived (obs/gang.py)."""
            if vote is None:
                return hooks()
            err = None
            out = None
            try:
                out = hooks()
            except Exception as e:
                err = e
            try:
                vote.round_vote(vote_rounds, err)  # re-raises err
            except BaseException as halt:
                if gang_armed:
                    obs.gang.on_breach_halt(halt, vote_rounds)
                raise
            return out

        # the monotone invariants compare against the carry of the LAST
        # probe (not the last round): with a probe cadence > 1 an
        # in-gap increase that settles into a new fixed point would
        # otherwise slip past round-to-round comparison
        guard_prev = None
        if resume_meta is not None:
            rounds = int(resume_meta["rounds"])
            active = np.int32(resume_meta["active"])
            guard_prev = carry_of(state) if monitor is not None else None
            glog.vlog(
                1, "resumed from superstep %d (active=%d, dir=%s)",
                rounds, int(active), checkpoint_dir,
            )
            tr.instant("resume", round=rounds, active=int(active))
        else:
            peval_fn = self._single_step_for("peval", state)
            prev_carry = carry_of(state) if monitor is not None else None
            t0 = time.perf_counter()
            # timing convention: the clock stops only after the sync on
            # the full carry (block_until_ready), so PEval's reported
            # time is wall including device execution — not the async
            # dispatch-only time a naive t1-t0 around the call measures
            with tr.span("peval", round=0) as sp:
                out = peval_fn(frag.dev, state)
                if getattr(self, "_last_runner_miss", False):
                    # truth.py excludes compile-bearing rounds
                    sp.mark("compiled")
                sp.mark("dispatched")
                state, active = jax.block_until_ready(out)
                sp.set(active=int(active))
            state = {**state, **eph_vals}
            glog.vlog(
                1, "PEval: %.6fs active=%d",
                time.perf_counter() - t0, int(active),
            )
            if tr.enabled:
                self._mirror_superstep(tr, sp, 0, "peval")
                tr.counter("active_vertices", value=int(active))
                m = obs.metrics()
                m.series("grape_active_per_round").append(int(active))
                m.counter("grape_supersteps_total").inc()
            rounds = 0
            if fault_plan is not None:
                # injected device-state corruption lands BEFORE the
                # probe (so detection is same-round) and before the
                # save (warn-policy runs aside, a corrupt state never
                # becomes the snapshot a rollback would restore)
                corrupted = fault_plan.maybe_corrupt_carry(
                    carry_of(state), 0
                )
                if corrupted is not None:
                    state = {**state, **self._place_state(corrupted)}
            def peval_hooks():
                if (
                    monitor is not None and int(active) >= 0
                    and monitor.due(0)
                ):
                    # a PEval breach has no snapshot to restore — any
                    # non-warn verdict halts
                    breach = monitor.check(
                        prev_carry, carry_of(state), 0, int(active)
                    )
                    if breach is not None:
                        monitor.raise_breach(breach)
                if ckpt is not None:
                    # a superstep-0 snapshot always exists, so a kill
                    # at any later round has something to fall back to
                    ckpt.save_async(carry_of(state), 0, int(active))
                if fault_plan is not None:
                    fault_plan.on_superstep(0, ckpt)

            voted_hooks(0, peval_hooks)
            if gang_armed:
                # drain this rank's spans so the merged gang timeline
                # survives a kill at any later round
                obs.gang.write_sidecar()
            if monitor is not None and int(active) >= 0 and monitor.due(0):
                guard_prev = carry_of(state)

        def apply_mutations_if_any(state, frag, inc_fn, rounds):
            host_state = {
                k: np.asarray(v) for k, v in jax.device_get(state).items()
            }
            mutator = app.collect_mutations(frag, host_state, rounds)
            if mutator is None:
                return state, frag, inc_fn, False
            old_frag = frag
            frag = mutator.mutate(frag)
            self.fragment = frag
            fresh = app.init_state(frag, **query_args)
            migrated = app.migrate_state(old_frag, frag, host_state, fresh)
            state = self._place_state(migrated)
            # cached too: an unchanged post-mutation state struct
            # re-uses the compiled step (the fragment rides as an
            # argument, so reuse is sound); a changed struct misses
            inc_fn = self._single_step_for("inceval", state)
            glog.vlog(1, "applied mutations after round %d", rounds)
            tr.instant("apply_mutations", round=rounds)
            return state, frag, inc_fn, True

        if has_mutations:
            # mutations staged during PEval apply even when the query
            # would otherwise converge immediately (worker.h:211-222
            # applies them every round boundary); a ForceTerminate vote
            # (negative active) still wins
            state, frag, inc_fn, changed = apply_mutations_if_any(
                state, frag, inc_fn, 0
            )
            if changed:
                # the rebuilt state carries fresh ephemeral leaves
                eph_vals = {k: state[k] for k in eph}
                inc_fresh = (inc_fresh
                             or getattr(self, "_last_runner_miss", False))
                if monitor is not None:
                    monitor.on_mutation(frag, self.pack_ledger())
                    guard_prev = carry_of(state)
            if changed and int(active) >= 0:
                active = 1
        try:
            while int(active) > 0 and rounds < mr:
                t0 = time.perf_counter()
                # same sync-before-clock-stop convention as PEval: the
                # span (and the vlog line) cover dispatch + device wait
                with tr.span("superstep", round=rounds + 1) as sp:
                    out = inc_fn(frag.dev, state)
                    if inc_fresh:
                        # first dispatch since (re)compile: truth.py
                        # excludes this round's wait from the join
                        sp.mark("compiled")
                        inc_fresh = False
                    sp.mark("dispatched")
                    state, active = jax.block_until_ready(out)
                    sp.set(active=int(active))
                state = {**state, **eph_vals}
                rounds += 1
                glog.vlog(
                    1, "IncEval round %d: %.6fs active=%d",
                    rounds, time.perf_counter() - t0, int(active),
                )
                if tr.enabled:
                    self._mirror_superstep(tr, sp, rounds, "superstep")
                    tr.counter("active_vertices", value=int(active))
                    m = obs.metrics()
                    m.series("grape_active_per_round").append(int(active))
                    m.counter("grape_supersteps_total").inc()
                if fault_plan is not None:
                    # corruption lands BEFORE the probe: detection is
                    # same-round even for carries a further superstep
                    # would wash clean (CDLP mode adoption)
                    corrupted = fault_plan.maybe_corrupt_carry(
                        carry_of(state), rounds
                    )
                    if corrupted is not None:
                        state = {**state, **self._place_state(corrupted)}
                # the probe runs BEFORE the cadence save — and is
                # FORCED on checkpoint rounds even when the guard
                # cadence would skip them: a state that fails its
                # invariants must never become the snapshot a later
                # rollback restores (a rollback `continue` also skips
                # this round's save and injection hooks)
                ckpt_round = (
                    ckpt is not None and rounds % checkpoint_every == 0
                )

                def round_hooks(rounds=rounds, active=active,
                                ckpt_round=ckpt_round):
                    # probe / snapshot / injection for this superstep;
                    # returns a (restored, meta) rollback payload or
                    # None.  The rollback decision is driven by jitted
                    # GLOBAL probes, so it is symmetric across ranks —
                    # every rank returns the same payload and the
                    # lockstep vote in voted_hooks holds.
                    if (
                        monitor is not None and int(active) >= 0
                        and (monitor.due(rounds) or ckpt_round)
                    ):
                        breach = monitor.check(
                            guard_prev, carry_of(state), rounds,
                            int(active)
                        )
                        if breach is not None:
                            if breach.action == "rollback":
                                return monitor.rollback(breach)
                            monitor.raise_breach(breach)
                    if (
                        ckpt is not None
                        and rounds % checkpoint_every == 0
                    ):
                        ckpt.save_async(
                            carry_of(state), rounds, int(active)
                        )
                    if fault_plan is not None:
                        fault_plan.on_superstep(rounds, ckpt)
                    return None

                rolled = voted_hooks(rounds, round_hooks)
                if gang_armed:
                    obs.gang.write_sidecar()
                if rolled is not None:
                    restored, meta = rolled
                    state = {**state, **self._place_state(restored)}
                    rounds = int(meta["rounds"])
                    active = np.int32(meta["active"])
                    guard_prev = carry_of(state)
                    continue
                if (
                    monitor is not None and int(active) >= 0
                    and (monitor.due(rounds) or ckpt_round)
                ):
                    guard_prev = carry_of(state)
                if has_mutations:
                    # MutationContext path (reference worker.h:211-222);
                    # never overrides a ForceTerminate vote
                    state, frag, inc_fn, changed = apply_mutations_if_any(
                        state, frag, inc_fn, rounds
                    )
                    if changed:
                        eph_vals = {k: state[k] for k in eph}
                        inc_fresh = (
                            inc_fresh
                            or getattr(self, "_last_runner_miss", False)
                        )
                        if monitor is not None:
                            # the graph (and its superstep operator)
                            # changed: digest history no longer proves
                            # cycles, monotone comparisons must not
                            # span the rebuild
                            monitor.on_mutation(frag, self.pack_ledger())
                            guard_prev = carry_of(state)
                    if changed and int(active) >= 0:
                        active = 1  # the new topology must be re-evaluated
                        if rounds >= mr:
                            glog.log_info(
                                "mutation applied on the final permitted "
                                "round; the rebuilt topology was NOT "
                                "re-evaluated — raise max_rounds"
                            )
        finally:
            # flush the in-flight snapshot even on an exception (an
            # injected raise-mode kill must leave a durable checkpoint)
            if ckpt is not None:
                ckpt.close()
        self.rounds = rounds
        self._terminate_code = min(0, int(active))
        self._result_state = state
        self._result_fragment = self.fragment
        return state

    def pack_ledger(self):
        """The engaged pack backend's static op-budget ledger
        (spmv_pack.plan_ledger form), or None when no pack dispatch is
        resolved on the app — the stepwise profiling hook and external
        harnesses read per-stage ALU attribution from here.  Apps that
        resolve SEVERAL dispatches (WCC pulls both directions) get the
        SUM of their ledgers: the per-round bill is every engaged
        plan's ops, and attributing only one would mislead the
        measured-vs-modeled comparison.

        With a superstep pipeline resolved (r9) the ledger carries the
        boundary-set stats under "pipeline" — boundary/interior
        vertex+edge totals, exchange mode and modeled bytes — so the
        plan's split is readable wherever the ledger is (the stepwise
        vlog, obs query spans, trace_report)."""
        def with_pipeline(led):
            pl = self._pipelined()
            if pl is None:
                return led
            return {**led, "pipeline": {
                **pl.stats.get("totals", {}),
                "mode": pl.mode,
                "exchange_bytes": pl.exchange_bytes,
            }}

        # the pipelined round dispatches the split sub-plans instead
        # of the full plan, but the split partitions the edge set, so
        # the full plan's ledger below remains the honest per-round
        # bill either way.  `_spgemm` (r11, ops/spgemm_pack.py) ships
        # the same split-column ledger shape, so the masked-SpGEMM
        # backend's bill surfaces through the identical path
        ledgers = []
        for attr in ("_pack", "_pack_ie", "_pack_oe", "_spgemm"):
            d = getattr(self.app, attr, None)
            if d is not None and callable(getattr(d, "ledger", None)):
                led = d.ledger()
                if led:
                    ledgers.append(led)
        if not ledgers:
            return None
        if len(ledgers) == 1:
            return with_pipeline(ledgers[0])
        totals = {"vpu_ops": 0, "mxu_ops": 0, "gather_rows": 0,
                  "hbm_bytes": 0, "blocks": 0, "per_stage": {}}
        out = {"edges": 0, "levels": [], "totals": totals}
        for di, led in enumerate(ledgers):
            out["edges"] += led["edges"]
            # re-index so merged level keys stay unique across plans
            # (a reader attributing wall clock per level must not see
            # two colliding "level 0" rows)
            out["levels"] += [
                {**lv, "level": len(out["levels"]) + i,
                 "dispatch": di}
                for i, lv in enumerate(led["levels"])
            ]
            for k in ("vpu_ops", "mxu_ops", "gather_rows",
                      "hbm_bytes", "blocks"):
                totals[k] += led["totals"][k]
            for k, v in led["totals"].get("per_stage", {}).items():
                totals["per_stage"][k] = (
                    totals["per_stage"].get(k, 0) + v
                )
        return with_pipeline(out)

    def resume(self, checkpoint_dir: str, max_rounds: int | None = None, *,
               checkpoint_every: int | None = None, fault_plan=None,
               guard=None):
        """Continue a checkpointed query from the last complete
        superstep.  The config fingerprint (app, fragment content, mesh
        shape, query args, numeric config) is validated before any
        state is adopted — a mismatch raises `CheckpointMismatchError`;
        a corrupt newest shard falls back to the previous complete
        superstep.  Query args are replayed from checkpoint metadata,
        so the resumed run finishes with byte-identical results to an
        uninterrupted one.  Checkpointing continues at the recorded
        cadence unless `checkpoint_every` overrides it."""
        from libgrape_lite_tpu.ft.checkpoint import (
            CheckpointMismatchError, latest_meta,
        )
        from libgrape_lite_tpu.ft.fingerprint import app_registry_name

        meta = latest_meta(checkpoint_dir)
        # reject a wrong-app resume BEFORE replaying its query args into
        # this app's init_state (which would fail with an opaque
        # TypeError instead of the fingerprint diagnosis)
        recorded = (meta.get("fingerprint") or {}).get("app")
        mine = app_registry_name(self.app)
        if recorded is not None and recorded != mine:
            raise CheckpointMismatchError(
                f"checkpoint {checkpoint_dir!r} does not match this "
                f"query: app: checkpoint has {recorded!r}, query has "
                f"{mine!r}"
            )
        query_args = meta.get("query_args") or {}
        return self.query_stepwise(
            max_rounds, checkpoint_every=checkpoint_every,
            checkpoint_dir=checkpoint_dir, fault_plan=fault_plan,
            guard=guard, _resume=True, **query_args,
        )

    # ---- Output / Assemble (reference worker.h:148-154, ctx.Output) ----

    def result_values(self) -> np.ndarray:
        """Per-vertex assembled values, [fnum, vp] numpy."""
        if self._result_state is None:
            raise RuntimeError("query() first")
        if jax.process_count() > 1:
            # the carry spans non-addressable devices in a
            # jax.distributed run; gather each sharded leaf to a full
            # host copy so finalize sees the same [fnum, vp] view a
            # single-process run would
            from jax.experimental import multihost_utils

            host_state = {}
            for k, v in self._result_state.items():
                if getattr(v, "is_fully_addressable", True):
                    host_state[k] = np.asarray(jax.device_get(v))
                else:
                    host_state[k] = np.asarray(
                        multihost_utils.process_allgather(v)
                    )
        else:
            host_state = jax.device_get(self._result_state)
        return self.app.finalize(self.fragment, host_state)

    def output(self, prefix: str) -> None:
        """Write per-fragment result files `result_frag_<fid>` with
        `oid value` lines (reference `GetResultFilename` + ctx Output)."""
        import os

        # result_values() runs a process_allgather on non-fully-
        # addressable leaves — a collective EVERY process must join, so
        # all ranks gather before the single-writer early return below
        values = self.result_values()
        if jax.process_count() > 1 and jax.process_index() != 0:
            # every process now holds the full gathered result; one
            # writer keeps a shared output dir race-free
            return
        os.makedirs(prefix, exist_ok=True)
        fmt = self.app.result_format
        for f in range(self.fragment.fnum):
            n = self.fragment.inner_vertices_num(f)
            oids = self.fragment.inner_oids(f)
            vals = values[f, :n]
            path = os.path.join(prefix, f"result_frag_{f}")
            with open(path, "w") as out:
                out.write(format_result_lines(oids, vals, fmt))


def format_result_lines(oids, vals, fmt: str) -> str:
    if len(oids) == 0:
        return ""
    lines = []
    if fmt == "int":
        for o, v in zip(oids.tolist(), np.asarray(vals).tolist()):
            # string-keyed graphs carry str component/community ids
            lines.append(f"{o} {v if isinstance(v, str) else int(v)}")
    elif fmt == "sssp_infinity":
        for o, v in zip(oids.tolist(), np.asarray(vals).tolist()):
            if not np.isfinite(v):
                lines.append(f"{o} infinity")
            else:
                lines.append(f"{o} {v:.15e}")
    else:
        for o, v in zip(oids.tolist(), np.asarray(vals).tolist()):
            lines.append(f"{o} {v:.15e}")
    return "\n".join(lines) + "\n"
