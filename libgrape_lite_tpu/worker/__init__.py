from libgrape_lite_tpu.worker.worker import Worker
