"""Core type vocabulary.

TPU-native re-design of the reference's `grape/types.h:36-198`: the enums
keep the same names/semantics so apps written against the reference map
1:1, but everything here is plain Python + numpy/JAX dtypes — there is no
C++ template machinery to mirror because shape/dtype specialisation is
done by XLA at trace time.
"""

from __future__ import annotations

import enum

import numpy as np


class EmptyType:
    """Zero-byte payload marker (reference `grape/types.h:36-57`).

    Used as the EDATA/VDATA type for unweighted graphs.  On TPU an
    "empty" per-edge payload simply means the fragment does not
    materialise an edge-data array at all.
    """

    __slots__ = ()

    def __eq__(self, other):  # all instances equal, like the reference POD
        return isinstance(other, EmptyType)

    def __hash__(self):
        return 0

    def __repr__(self):
        return "EmptyType()"


class LoadStrategy(enum.Enum):
    """How edges are attached to fragments (reference `grape/types.h:81-86`)."""

    kOnlyOut = "only_out"
    kOnlyIn = "only_in"
    kBothOutIn = "both_out_in"
    kNullLoadStrategy = "null"


class MessageStrategy(enum.Enum):
    """How cross-fragment messages flow (reference `grape/types.h:98-104`).

    On TPU these select the collective pattern a message manager uses:

    * kAlongEdgeToOuterVertex / kAlongOutgoingEdgeToOuterVertex /
      kAlongIncomingEdgeToOuterVertex — per-destination message tensors
      exchanged with `all_to_all` (push model).
    * kSyncOnOuterVertex — mirror sync via `all_gather` / `ppermute`.
    * kGatherScatter — vertex-cut segment reduce + broadcast.
    """

    kAlongOutgoingEdgeToOuterVertex = "along_out_edge"
    kAlongIncomingEdgeToOuterVertex = "along_in_edge"
    kAlongEdgeToOuterVertex = "along_edge"
    kSyncOnOuterVertex = "sync_on_outer_vertex"
    kGatherScatter = "gather_scatter"


# Default integer dtypes. The reference uses `fid_t = unsigned`
# (`grape/config.h:40-43`) and vid widths uint32/uint64 chosen by the
# `--opt` flag (`examples/analytical_apps/run_app.cc:48-52`). On TPU we
# default to int32 (native lane width); int64 is available for huge
# graphs and for exact-parity CPU testing under x64.
FID_DTYPE = np.int32
VID_DTYPE = np.int32
VID64_DTYPE = np.int64


def is_empty_type(t) -> bool:
    return t is EmptyType or isinstance(t, EmptyType) or t is None


def state_struct(state) -> tuple:
    """Sorted (key, shape, dtype) structural identity of a query
    state/carry dict — the cache-key component shared by the worker
    runner cache (Worker._state_struct) and the guard probe cache
    (guard/monitor._PROBE_CACHE).  One definition, so the two caches
    can never disagree about what "same structure" means."""
    return tuple(
        sorted(
            (k, tuple(v.shape), str(v.dtype)) for k, v in state.items()
        )
    )
