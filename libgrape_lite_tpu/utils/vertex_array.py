"""Vertex ranges and per-vertex arrays.

Re-design of `grape/utils/vertex_array.h:37-573`: `Vertex` (typed lid),
`VertexRange` / `DualVertexRange` (contiguous / two-segment lid spans)
and `VertexArray` (dense per-vertex storage indexed by Vertex).

On TPU a VertexArray *is* a jnp array row of the fragment state — these
host-side helpers exist for loaders, assemble/output code and tests;
device code indexes arrays by lid directly (the zero-cost form of the
reference's `Vertex` wrapper).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class VertexRange:
    """[begin, end) of local ids (reference vertex_array.h VertexRange)."""

    begin: int
    end: int

    def __len__(self) -> int:
        return max(0, self.end - self.begin)

    def __iter__(self):
        return iter(range(self.begin, self.end))

    def __contains__(self, lid: int) -> bool:
        return self.begin <= lid < self.end

    def to_numpy(self) -> np.ndarray:
        return np.arange(self.begin, self.end)


@dataclass(frozen=True)
class DualVertexRange:
    """Two disjoint spans — the reference's inner-head/outer-tail layout
    (`vertex_array.h` DualVertexRange; used by MutableEdgecutFragment)."""

    head: VertexRange
    tail: VertexRange

    def __len__(self) -> int:
        return len(self.head) + len(self.tail)

    def __iter__(self):
        yield from self.head
        yield from self.tail

    def __contains__(self, lid: int) -> bool:
        return lid in self.head or lid in self.tail


class VertexArray:
    """Dense per-vertex values over a VertexRange, offset by its begin
    (reference `VertexArray<T>`); numpy-backed."""

    def __init__(self, vertices: VertexRange, init=0, dtype=None):
        self.range = vertices
        self.data = np.full(len(vertices), init, dtype=dtype)

    def __getitem__(self, v):
        return self.data[np.asarray(v) - self.range.begin]

    def __setitem__(self, v, value):
        self.data[np.asarray(v) - self.range.begin] = value

    def set_value(self, value):
        self.data[:] = value

    def swap(self, other: "VertexArray"):
        self.data, other.data = other.data, self.data
