"""Packed bitsets.

Re-design of `grape/utils/bitset.h:41-412` (64-bit-word bitset with
atomic set/reset + parallel count) and the device bitmaps of
`grape/cuda/utils/bitset.h`.  Two forms:

* `Bitset` — host numpy uint64 words (loaders, tests),
* jnp helpers (`pack_bits`, `unpack_bits`, `popcount_rows`) for traced
  code; "atomic" set degenerates to scatter-or / unique-bit scatter-add
  because XLA scatters are race-free by construction.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax


class Bitset:
    def __init__(self, size: int):
        self.size = size
        self.words = np.zeros((size + 63) // 64, dtype=np.uint64)

    def set_bit(self, i) -> None:
        i = np.asarray(i)
        np.bitwise_or.at(
            self.words, i // 64, np.uint64(1) << (i % 64).astype(np.uint64)
        )

    def reset_bit(self, i) -> None:
        i = np.asarray(i)
        mask = np.uint64(1) << (i % 64).astype(np.uint64)
        # two-pass: collect per-word masks then AND-NOT
        acc = np.zeros_like(self.words)
        np.bitwise_or.at(acc, i // 64, mask)
        self.words &= ~acc

    def get_bit(self, i):
        i = np.asarray(i)
        return (self.words[i // 64] >> (i % 64).astype(np.uint64)) & np.uint64(1) != 0

    def count(self) -> int:
        if hasattr(np, "bitwise_count"):
            return int(np.bitwise_count(self.words).sum())
        return int(sum(bin(int(w)).count("1") for w in self.words))

    def clear(self) -> None:
        self.words[:] = 0


# ---- traced (jnp) helpers ----

def pack_bits(indices, keep, num_rows: int, rows, num_bits: int):
    """Scatter bit `indices[i]` into row `rows[i]` for kept entries;
    (row, index) pairs must be unique so add == or.  Returns
    [num_rows, ceil(num_bits/32)] uint32."""
    words = (num_bits + 31) // 32
    r = jnp.where(keep, rows, jnp.int32(num_rows))
    word = (indices >> 5).astype(jnp.int32)
    bit = jnp.uint32(1) << (indices & 31).astype(jnp.uint32)
    bm = jnp.zeros((num_rows + 1, words), dtype=jnp.uint32)
    bm = bm.at[r, word].add(jnp.where(keep, bit, jnp.uint32(0)))
    return bm[:num_rows]


def popcount_rows(bm) -> jnp.ndarray:
    """Row-wise population count of packed uint32 bitmaps."""
    return lax.population_count(bm).sum(axis=-1, dtype=jnp.int32)
