"""Byte archives + varint codecs.

Re-design of `grape/serialization/{in,out}_archive.h` and
`grape/utils/varint.h:39-402` (VarintEncoder / DeltaVarintEncoder).

On the TPU compute path there are no archives — messages are typed
tensors and XLA owns the wire format.  These codecs serve the *host*
boundary: the fragment serialization cache and any host-side spill
formats, where the reference's delta-varint gid compression still pays
(sorted neighbor/gid streams compress 3-5x).  Vectorised numpy, not a
byte-at-a-time port.
"""

from __future__ import annotations

import struct

import numpy as np


class InArchive:
    """Append-only byte buffer (reference in_archive.h:43-244)."""

    def __init__(self):
        self._parts: list[bytes] = []

    def add_bytes(self, b: bytes) -> None:
        self._parts.append(bytes(b))

    def add_scalar(self, v, fmt: str = "<q") -> None:
        self._parts.append(struct.pack(fmt, v))

    def add_array(self, a: np.ndarray) -> None:
        a = np.ascontiguousarray(a)
        self.add_scalar(a.nbytes)
        self._parts.append(a.tobytes())

    def get_buffer(self) -> bytes:
        return b"".join(self._parts)

    def __len__(self) -> int:
        return sum(len(p) for p in self._parts)


class OutArchive:
    """Cursor-based reader with zero-copy array views
    (reference out_archive.h `SetSlice`)."""

    def __init__(self, buf: bytes):
        self._buf = memoryview(buf)
        self._pos = 0

    def get_bytes(self, n: int) -> memoryview:
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def get_scalar(self, fmt: str = "<q"):
        n = struct.calcsize(fmt)
        (v,) = struct.unpack(fmt, self.get_bytes(n))
        return v

    def get_array(self, dtype) -> np.ndarray:
        nbytes = self.get_scalar()
        return np.frombuffer(self.get_bytes(nbytes), dtype=dtype)

    def empty(self) -> bool:
        return self._pos >= len(self._buf)


# ---- varint / delta-varint (reference varint.h) ----

def varint_encode(values: np.ndarray) -> bytes:
    """LEB128 encode an unsigned int64 array (native fast path,
    vectorised numpy fallback)."""
    v = np.asarray(values, dtype=np.uint64)
    if len(v) == 0:
        return b""
    from libgrape_lite_tpu.io.native import varint_encode_native

    nat = varint_encode_native(v, delta=False)
    if nat is not None:
        return nat
    nbytes = np.maximum((70 - _clz64(v)) // 7, 1)  # ceil(bits/7), min 1
    total = int(nbytes.sum())
    out = np.zeros(total, dtype=np.uint8)
    offs = np.concatenate([[0], np.cumsum(nbytes)[:-1]])
    rem = v.copy()
    for b in range(10):  # max 10 bytes for 64-bit
        active = nbytes > b
        if not active.any():
            break
        byte = (rem & np.uint64(0x7F)).astype(np.uint8)
        more = (b + 1) < nbytes
        byte = np.where(more, byte | 0x80, byte)
        out[(offs + b)[active]] = byte[active]
        rem >>= np.uint64(7)
    return out.tobytes()


def varint_decode(buf: bytes) -> np.ndarray:
    from libgrape_lite_tpu.io.native import varint_decode_native

    nat = varint_decode_native(buf, delta=False)
    if nat is not None:
        return nat
    b = np.frombuffer(buf, dtype=np.uint8)
    if len(b) == 0:
        return np.zeros(0, dtype=np.uint64)
    if b[-1] & 0x80:
        # truncated mid-value: match the native decoder instead of
        # silently dropping the tail
        raise ValueError("corrupt varint stream: trailing bytes have "
                         "no terminator")
    is_last = (b & 0x80) == 0
    ends = np.nonzero(is_last)[0]
    starts = np.concatenate([[0], ends[:-1] + 1])
    out = np.zeros(len(ends), dtype=np.uint64)
    max_len = int((ends - starts).max()) + 1
    for k in range(max_len):
        pos = starts + k
        active = pos <= ends
        out[active] |= (b[pos[active]] & np.uint64(0x7F)).astype(np.uint64) << np.uint64(
            7 * k
        )
    return out


def delta_varint_encode(sorted_values: np.ndarray) -> bytes:
    """Delta + varint for non-decreasing streams
    (reference DeltaVarintEncoder, varint.h:283-316)."""
    v = np.asarray(sorted_values, dtype=np.uint64)
    if len(v) == 0:
        return b""
    from libgrape_lite_tpu.io.native import varint_encode_native

    nat = varint_encode_native(v, delta=True)
    if nat is not None:
        return nat
    deltas = np.diff(v, prepend=np.uint64(0))
    return varint_encode(deltas)


def delta_varint_decode(buf: bytes) -> np.ndarray:
    from libgrape_lite_tpu.io.native import varint_decode_native

    nat = varint_decode_native(buf, delta=True)
    if nat is not None:
        return nat
    return np.cumsum(varint_decode(buf), dtype=np.uint64)


def _clz64(v: np.ndarray) -> np.ndarray:
    """Count leading zeros of uint64 via float64 exponent trick +
    correction (exact for all uint64)."""
    v = np.asarray(v, dtype=np.uint64)
    bits = np.zeros(len(v), dtype=np.int64)
    x = v.copy()
    for shift in (32, 16, 8, 4, 2, 1):
        m = x >= (np.uint64(1) << np.uint64(shift))
        bits[m] += shift
        x = np.where(m, x >> np.uint64(shift), x)
    # bits = floor(log2(v)) for v>0; clz = 63 - bits; v==0 -> 64
    return np.where(v == 0, 64, 63 - bits)
