from libgrape_lite_tpu.utils.types import EmptyType, LoadStrategy, MessageStrategy
from libgrape_lite_tpu.utils.id_parser import IdParser
