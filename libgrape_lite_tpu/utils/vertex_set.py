"""Frontier abstraction.

Re-design of `DenseVertexSet` (`grape/utils/vertex_set.h:32-443`):
Insert/Exist/Count/PartialEmpty/Swap over a bitset — the frontier type
of BFS/SSSP.

On the TPU compute path a frontier is simply a boolean mask array
(`frontier = changed & inner_mask`), which XLA fuses into the masked
relaxation; this class provides the host-side API and documents the
mapping.  `as_mask()` hands the device form back.
"""

from __future__ import annotations

import numpy as np

from libgrape_lite_tpu.utils.bitset import Bitset
from libgrape_lite_tpu.utils.vertex_array import VertexRange


class DenseVertexSet:
    def __init__(self, vertices: VertexRange):
        self.range = vertices
        self._bits = Bitset(len(vertices))

    def insert(self, v) -> None:
        self._bits.set_bit(np.asarray(v) - self.range.begin)

    def erase(self, v) -> None:
        self._bits.reset_bit(np.asarray(v) - self.range.begin)

    def exist(self, v):
        return self._bits.get_bit(np.asarray(v) - self.range.begin)

    def count(self) -> int:
        return self._bits.count()

    def empty(self) -> bool:
        return self.count() == 0

    def partial_empty(self, begin: int, end: int) -> bool:
        lo, hi = begin - self.range.begin, end - self.range.begin
        idx = np.arange(max(lo, 0), min(hi, len(self.range)))
        return not bool(self._bits.get_bit(idx).any())

    def clear(self) -> None:
        self._bits.clear()

    def swap(self, other: "DenseVertexSet") -> None:
        self._bits, other._bits = other._bits, self._bits

    def as_mask(self) -> np.ndarray:
        """Boolean mask over the range — the device-side frontier form."""
        idx = np.arange(len(self.range))
        return np.asarray(self._bits.get_bit(idx))
