"""Coordinator-only phase timer.

Re-design of `examples/analytical_apps/timer.h:43-75`: a stack of named
phases, printed by the coordinator (process index 0).  JAX devices are
asynchronous, so `timer_end` blocks on outstanding device work before
reading the clock (the analogue of the reference's implicit MPI barrier).
"""

from __future__ import annotations

import time
from typing import List, Tuple

import jax

_stack: List[Tuple[str, float, object]] = []
_is_coordinator = True


def set_coordinator(flag: bool) -> None:
    global _is_coordinator
    _is_coordinator = flag


def timer_start(name: str) -> None:
    jax.effects_barrier()
    # phases double as trace spans when obs/ is armed, so the driver's
    # load/run/output breakdown lands on the same timeline as the
    # worker's superstep spans (span() is a no-op when disarmed)
    from libgrape_lite_tpu import obs

    span = obs.tracer().span(name)
    _stack.append((name, time.perf_counter(), span))


def timer_end() -> float:
    jax.effects_barrier()
    name, t0, span = _stack.pop()
    span.close()
    dt = time.perf_counter() - t0
    if _is_coordinator:
        print(f"[timer] {name}: {dt:.6f} s")
    return dt


class phase:
    """Context-manager sugar: `with phase("run algorithm"): ...`"""

    def __init__(self, name: str):
        self.name = name
        self.seconds = None

    def __enter__(self):
        timer_start(self.name)
        return self

    def __exit__(self, *exc):
        self.seconds = timer_end()
        return False
