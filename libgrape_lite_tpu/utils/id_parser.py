"""Global-ID bit codec.

Re-design of the reference `IdParser` (`grape/fragment/id_parser.h:23-60`):
gid = [fid : high bits][lid : low bits].  The bit trick is kept verbatim
because it vectorises perfectly — on TPU fid/lid extraction over a whole
message tensor is a single shift/mask on the VPU, and the fid doubles as
the mesh shard index for collective routing.
"""

from __future__ import annotations

import numpy as np


class IdParser:
    """Encode/decode (fid, lid) <-> gid with a fixed bit split.

    Works on Python ints, numpy arrays and JAX arrays alike (pure
    shift/mask ops).  `lid_bits` is chosen as ceil(log2(max_lid_capacity))
    so every fragment's padded vertex capacity fits.
    """

    def __init__(self, fnum: int, max_lid_capacity: int, dtype=np.int64):
        if fnum < 1:
            raise ValueError("fnum must be >= 1")
        fid_bits = max(1, int(np.ceil(np.log2(max(fnum, 2)))))
        lid_bits = max(1, int(np.ceil(np.log2(max(max_lid_capacity, 2)))))
        total = np.dtype(dtype).itemsize * 8 - 1  # keep sign bit clear
        if fid_bits + lid_bits > total:
            raise ValueError(
                f"fid_bits({fid_bits}) + lid_bits({lid_bits}) > {total}; "
                "use a wider dtype"
            )
        self.fnum = fnum
        self.fid_bits = fid_bits
        self.lid_bits = lid_bits
        self.dtype = np.dtype(dtype)
        self.lid_mask = (1 << lid_bits) - 1

    def generate(self, fid, lid):
        """gid from (fid, lid); elementwise on arrays."""
        return (fid << self.lid_bits) | lid

    def get_fid(self, gid):
        return gid >> self.lid_bits

    def get_lid(self, gid):
        return gid & self.lid_mask

    def max_local_num(self) -> int:
        return 1 << self.lid_bits

    def __repr__(self):
        return (
            f"IdParser(fnum={self.fnum}, fid_bits={self.fid_bits}, "
            f"lid_bits={self.lid_bits})"
        )
