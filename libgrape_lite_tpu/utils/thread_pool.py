"""Host thread pool.

Re-design of `grape/utils/thread_pool.h:53-125` + `BlockingQueue`
(`grape/utils/concurrent_queue.h`): futures-based pool for host-side
work (parallel file parsing, per-fragment CSR builds).  Device-side
parallelism needs no pool — XLA owns it; the reference's CPU-affinity
option maps to nothing useful under a single-controller runtime and is
accepted but ignored.
"""

from __future__ import annotations

import queue
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Iterable


class ThreadPool:
    def __init__(self, num_threads: int | None = None, affinity=None):
        self._pool = ThreadPoolExecutor(max_workers=num_threads)
        self.num_threads = self._pool._max_workers

    def enqueue(self, fn: Callable, *args, **kwargs) -> Future:
        return self._pool.submit(fn, *args, **kwargs)

    def for_each(self, fn: Callable, items: Iterable):
        return list(self._pool.map(fn, items))

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)


class BlockingQueue:
    """Producer-count-aware MPMC queue (reference concurrent_queue.h):
    consumers see `None` end-markers once every producer finished."""

    def __init__(self, maxsize: int = 0):
        import threading

        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._producers = 0
        self._lock = threading.Lock()

    def set_producer_num(self, n: int) -> None:
        with self._lock:
            self._producers = n

    def decrement_producer(self) -> None:
        with self._lock:
            self._producers -= 1
            done = self._producers <= 0
        if done:
            self._q.put(None)

    def put(self, item) -> None:
        self._q.put(item)

    def get(self):
        item = self._q.get()
        if item is None:
            self._q.put(None)  # keep releasing other consumers
        return item
