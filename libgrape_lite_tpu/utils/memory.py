"""Device/host memory accounting.

Re-design of the reference's `MemoryTracker` (`grape/utils/memory_tracker.h:26-43`)
and `GetMemoryUsage` (`grape/util.h:51-69`): instead of interposing on
malloc, we read live/peak bytes from the JAX device allocator and RSS
from /proc.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax


@dataclass
class MemoryStats:
    device_bytes_in_use: int
    device_peak_bytes: int
    host_rss_bytes: int

    def __str__(self):
        gb = 1 << 30
        return (
            f"device in-use {self.device_bytes_in_use / gb:.3f} GiB, "
            f"device peak {self.device_peak_bytes / gb:.3f} GiB, "
            f"host rss {self.host_rss_bytes / gb:.3f} GiB"
        )


def get_host_rss() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def get_memory_stats(device=None) -> MemoryStats:
    in_use = peak = 0
    devs = [device] if device is not None else jax.local_devices()
    for d in devs:
        try:
            ms = d.memory_stats()
        except Exception:  # CPU backend has no allocator stats
            ms = None
        if ms:
            in_use += ms.get("bytes_in_use", 0)
            peak += ms.get("peak_bytes_in_use", 0)
    return MemoryStats(in_use, peak, get_host_rss())
