"""glog-style leveled logging (reference uses glog VLOG throughout,
e.g. `grape/worker/worker.h:120-139`).  Level via GRAPE_TPU_VLOG
(default 0 = silent) or `set_vlog_level`.

r8 (obs/):

* **lazy formatting** — `vlog(1, "round %d: %.6fs", r, dt)` defers the
  `%` interpolation until the level check passes, so disabled levels
  pay one int compare and nothing else (the worker's hot loop logs
  per round; f-strings formatted-then-dropped were measurable).  The
  f-string form still works for call sites off any hot path.
* **rank prefix** — every line carries `r<process>` so interleaved
  multi-host stderr is attributable (previously indistinguishable).
  The rank comes from jax's distributed global state WITHOUT touching
  `jax.process_index()` (which would force backend init at import
  time); single-host runs print `r0`.
* **thread safety** — `set_vlog_level` takes a lock (the CLI's
  --profile bump can race the checkpoint writer thread's vlog);
  readers stay lock-free — an int load is GIL-atomic, and the worst
  outcome of a racy read is one line logged at the old level.
* **tracer sink** — when obs/ is armed, every EMITTED line is also
  recorded as a `log` instant event on the trace timeline, so vlog
  output and spans interleave in one record (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
import sys
import threading
import time

_level = int(os.environ.get("GRAPE_TPU_VLOG", "0"))
_level_lock = threading.Lock()


def set_vlog_level(level: int) -> None:
    global _level
    with _level_lock:
        _level = int(level)


def vlog_level() -> int:
    return _level


def _rank() -> int:
    """Process index, read LIVE on every emitted line: the first log
    lines of a multi-host run can predate jax.distributed.initialize,
    and this jax build's pre-init process_id default is 0 — caching
    would freeze every process at r0.  The read is one attribute
    lookup, paid only on lines that actually print."""
    try:
        from jax._src import distributed

        pid = distributed.global_state.process_id
        return int(pid) if pid is not None else 0
    except Exception:
        return 0


def _emit(line: str, *, level: int) -> None:
    print(line, file=sys.stderr)
    # mirror onto the trace timeline when obs/ is armed (lazy import:
    # logging must stay importable before/without the obs package, and
    # obs modules themselves log through here)
    try:
        from libgrape_lite_tpu import obs

        tr = obs.tracer()
        if tr.enabled:
            tr.instant("log", msg=line, level=level)
    except Exception:
        pass  # logging must never take down the run (incl. interp shutdown)


def vlog(level: int, msg: str, *args) -> None:
    """Leveled log; pass printf-style `args` for lazy formatting —
    `vlog(1, "round %d", r)` formats only when level <= the threshold."""
    if level > _level:
        return
    if args:
        msg = msg % args
    ts = time.strftime("%H:%M:%S")
    _emit(f"[grape-tpu r{_rank()} {ts}] {msg}", level=level)


def log_info(msg: str, *args) -> None:
    if args:
        msg = msg % args
    _emit(f"[grape-tpu r{_rank()}] {msg}", level=0)
