"""glog-style leveled logging (reference uses glog VLOG throughout,
e.g. `grape/worker/worker.h:120-139`).  Level via GRAPE_TPU_VLOG
(default 0 = silent) or `set_vlog_level`."""

from __future__ import annotations

import os
import sys
import time

_level = int(os.environ.get("GRAPE_TPU_VLOG", "0"))


def set_vlog_level(level: int) -> None:
    global _level
    _level = level


def vlog(level: int, msg: str) -> None:
    if level <= _level:
        ts = time.strftime("%H:%M:%S")
        print(f"[grape-tpu {ts}] {msg}", file=sys.stderr)


def log_info(msg: str) -> None:
    print(f"[grape-tpu] {msg}", file=sys.stderr)
