"""run_app equivalent: dispatch by app name, load, query, output.

Re-design of `examples/analytical_apps/run_app.{cc,h}`
(`run_app.h:103-323`: CreateAndQuery / DoQuery) and `utils.h` (DoQuery
writes per-fragment results via `GetResultFilename`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict

import numpy as np

from libgrape_lite_tpu.fragment.loader import LoadGraph, LoadGraphSpec
from libgrape_lite_tpu.models import APP_REGISTRY
from libgrape_lite_tpu.parallel.comm_spec import CommSpec
from libgrape_lite_tpu.utils import timer
from libgrape_lite_tpu.utils.types import LoadStrategy
from libgrape_lite_tpu.worker.worker import Worker




@dataclass
class QueryArgs:
    """Flag bag (reference `examples/analytical_apps/flags.cc:23-69`)."""

    application: str = "sssp"
    efile: str = ""
    vfile: str = ""
    out_prefix: str = ""
    directed: bool = False
    sssp_source: int | str = 0
    bfs_source: int | str = 0
    bc_source: int | str = 0
    kcore_k: int = 0
    kclique_k: int = 3
    khop_k: int = 2  # k-hop neighborhood hop bound (models/khop.py)
    cn_source: int | str = 0  # common_neighbors 2-hop query source
    pr_d: float = 0.85
    pr_mr: int = 10
    cdlp_mr: int = 10
    degree_threshold: int = 0
    fnum: int | None = None
    # jax.distributed gang membership (parallel/comm_spec.py:
    # init_distributed runs before any backend use when
    # num_processes > 1); 0/unset = single-process
    coordinator: str = ""
    num_processes: int = 0
    process_id: int = -1
    partitioner_type: str = "map"
    idxer_type: str = "hashmap"
    rebalance: bool = False
    rebalance_vertex_factor: int = 0
    string_id: bool = False
    memory_stats: bool = False
    checkpoint_every: int = 0  # ft/: superstep checkpoint cadence (0 = off)
    checkpoint_dir: str = ""
    resume: bool = False  # continue from the last complete checkpoint
    guard: str = ""  # guard/: breach policy ("" reads GRAPE_GUARD)
    profile: bool = False
    trace: str = ""  # obs/: Chrome-trace output path ("" reads GRAPE_TRACE)
    metrics: str = ""  # obs/: metrics snapshot basename (GRAPE_METRICS)
    serialize: bool = False
    deserialize: bool = False
    serialization_prefix: str = ""
    vc: bool = False  # vertex-cut storage (reference --vc, run_app_vc.h)
    delta_efile: str = ""
    delta_vfile: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)


def _coerce_source(v, string_id: bool):
    if string_id or isinstance(v, int):
        return v
    try:
        return int(v)
    except (TypeError, ValueError):
        return v


def build_query_kwargs(app_name: str, args: QueryArgs) -> dict:
    if app_name.startswith("sssp"):
        return {"source": _coerce_source(args.sssp_source, args.string_id)}
    if app_name.startswith("bfs"):
        return {"source": _coerce_source(args.bfs_source, args.string_id)}
    if app_name == "bc":
        return {"source": _coerce_source(args.bc_source, args.string_id)}
    if app_name == "kcore":
        return {"k": args.kcore_k}
    if app_name == "kclique":
        return {"k": args.kclique_k}
    if app_name.startswith("pagerank"):
        return {"delta": args.pr_d, "max_round": args.pr_mr}
    if app_name.startswith("lcc") or app_name == "triangle_count":
        # hub cost cap (reference FLAGS_degree_threshold, lcc.h:234-243);
        # 0 = disabled (the reference's INT_MAX default);
        # triangle_count shares the LCC credit pass and its filter
        return {"degree_threshold": args.degree_threshold}
    if app_name == "common_neighbors":
        return {"source": _coerce_source(args.cn_source, args.string_id)}
    if app_name == "khop":
        # the hop bound is a constructor hyperparameter (run_app bakes
        # it into the app); the per-query arg is the source alone
        return {"source": _coerce_source(args.bfs_source, args.string_id)}
    if app_name.startswith("cdlp"):
        return {"max_round": args.cdlp_mr}
    return {}


def run_app(args: QueryArgs, comm_spec: CommSpec | None = None) -> Worker:
    # flag-consistency checks fail in milliseconds, BEFORE the (possibly
    # minutes-long) graph load
    if (args.checkpoint_every or args.resume) and not args.checkpoint_dir:
        raise ValueError(
            "--checkpoint_every/--resume require --checkpoint_dir"
        )
    if args.checkpoint_dir and not (args.checkpoint_every or args.resume):
        raise ValueError(
            "--checkpoint_dir requires --checkpoint_every (or --resume)"
        )
    if args.num_processes and args.num_processes > 1:
        if args.process_id < 0 or not args.coordinator:
            raise ValueError(
                "--num_processes > 1 requires --coordinator and "
                "--process_id (every member of the gang names itself)"
            )
        if comm_spec is not None:
            raise ValueError(
                "pass EITHER a prebuilt comm_spec or the "
                "--coordinator/--num_processes/--process_id flags, "
                "not both"
            )
        # must run before the partition probe or load touch a backend
        comm_spec = CommSpec.init_distributed(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
            fnum=args.fnum,
        )
    if args.trace or args.metrics:
        # arm obs/ BEFORE the load so the load_graph span is captured;
        # flags win over env (configure replaces any env-armed tracer)
        from libgrape_lite_tpu import obs

        obs.configure(
            trace_path=args.trace or None,
            metrics_path=args.metrics or None,
        )
    name = args.application
    if args.vc and name == "pagerank":
        name = "pagerank_vc"  # reference run_app_vc.h:82-89
    if name not in APP_REGISTRY:
        raise ValueError(
            f"unknown application {name!r}; known: {sorted(APP_REGISTRY)}"
        )
    app_cls = APP_REGISTRY[name]
    # khop's hop bound is a trace-key hyperparameter, not a query arg
    app = app_cls(k=args.khop_k) if name == "khop" else app_cls()

    if comm_spec is None:
        comm_spec = CommSpec(fnum=args.fnum)

    weighted = getattr(app_cls, "needs_edata", False)
    spec = LoadGraphSpec(
        directed=args.directed,
        weighted=weighted,
        load_strategy=app_cls.load_strategy,
        partitioner_type=args.partitioner_type,
        idxer_type=args.idxer_type,
        rebalance=args.rebalance,
        rebalance_vertex_factor=args.rebalance_vertex_factor,
        string_id=args.string_id,
        serialize=args.serialize,
        deserialize=args.deserialize,
        serialization_prefix=args.serialization_prefix,
        edata_dtype=np.float64,
    )

    from libgrape_lite_tpu.utils.types import MessageStrategy

    # 1-D vs 2-D partition choice (fragment/partition.py, ROADMAP
    # item 2): consulted ONLY when GRAPE_PARTITION asks — the default
    # path stays byte-for-byte the program it always was.  An engaged
    # decision swaps in the registered 2-D twin and the vertex-cut
    # fragment; EVERY declined request records its reason (never
    # silent), and the structurally-cheap declines (wrong app, non-
    # square fnum, string ids, delta load) are recorded WITHOUT
    # reading the edge file.
    vc2d_inputs = None
    if not args.vc:
        from libgrape_lite_tpu.fragment.partition import (
            VC2D_APPS,
            partition_mode,
            precheck_partition,
            resolve_partition,
        )

        if partition_mode() != "1d":
            empty = np.zeros(0, dtype=np.int64)
            if args.delta_efile or args.delta_vfile:
                resolve_partition(
                    name, comm_spec.fnum, empty, empty, empty,
                    directed=args.directed, string_id=args.string_id,
                    eligible=False,
                    reason="delta-mutation load has no vertex-cut path",
                )
            elif args.serialize or args.deserialize or not args.efile:
                # the garc serialization cache is an edge-cut artifact
                # (loader.py writes/reads it inside LoadGraph, which
                # the 2-D path bypasses) — and a deserialize run may
                # carry no edge file at all; decline with the reason
                # recorded rather than crash or silently skip the
                # cache write
                resolve_partition(
                    name, comm_spec.fnum, empty, empty, empty,
                    directed=args.directed, string_id=args.string_id,
                    eligible=False,
                    reason="serialization cache flags (or no edge "
                           "file): the vertex-cut fragment has no "
                           "serialized form",
                )
            elif precheck_partition(
                name, comm_spec.fnum, directed=args.directed,
                string_id=args.string_id,
            ) is not None:
                # structurally ineligible: record the decline cheaply
                # (resolve_partition re-derives the same reason before
                # touching the arrays)
                resolve_partition(
                    name, comm_spec.fnum, empty, empty, empty,
                    directed=args.directed, string_id=args.string_id,
                )
            else:
                from libgrape_lite_tpu.io.line_parser import (
                    read_edge_file,
                    read_vertex_file,
                )

                with timer.phase("partition probe"):
                    p_src, p_dst, p_w = read_edge_file(
                        args.efile, weighted=weighted
                    )
                    p_oids = (
                        read_vertex_file(args.vfile)
                        if args.vfile
                        else np.unique(np.concatenate([p_src, p_dst]))
                    )
                    decision = resolve_partition(
                        name, comm_spec.fnum, p_src, p_dst, p_oids,
                        directed=args.directed,
                    )
                if decision["engaged"]:
                    name = VC2D_APPS[name]
                    app = APP_REGISTRY[name]()
                    vc2d_inputs = (p_src, p_dst, p_w, p_oids)
                # an auto decline on modeled cost falls through to the
                # 1-D loader, which re-reads the file — the probe is
                # opt-in (GRAPE_PARTITION set) and the arrays cannot
                # seed LoadGraph's partitioner/idxer pipeline without
                # replicating it here

    is_vc = app_cls.message_strategy == MessageStrategy.kGatherScatter
    if args.vc and not is_vc:
        raise ValueError(
            f"--vc has no vertex-cut implementation for {name!r} "
            "(the reference's --vc path supports pagerank only, "
            "run_app_vc.h:82-89)"
        )
    if is_vc and (args.delta_efile or args.delta_vfile):
        raise ValueError("--delta_efile/--delta_vfile are not supported "
                         "with vertex-cut storage")
    if is_vc and args.string_id:
        raise ValueError(
            "--string_id is not supported with vertex-cut storage (the "
            "reference's VC fragment is specialized to uint64 oids, "
            "immutable_vertexcut_fragment.h)"
        )

    with timer.phase("load graph"):
        if vc2d_inputs is not None:
            from libgrape_lite_tpu.fragment.vertexcut import (
                ImmutableVertexcutFragment,
            )

            src, dst, w, oids = vc2d_inputs
            # min-fold pulls get symmetrised tiles (the 1-D loader's
            # undirected-CSR convention; WCC symmetrises even when
            # directed — weak connectivity IS the undirected
            # traversal); pagerank_vc keeps raw storage and
            # accumulates both directions in-app
            sym = (
                name == "wcc_vc"
                or (name != "pagerank_vc" and not args.directed)
            )
            frag = ImmutableVertexcutFragment.build(
                comm_spec, oids, src, dst, w if weighted else None,
                directed=args.directed, symmetrize=sym,
            )
        elif is_vc:
            from libgrape_lite_tpu.fragment.vertexcut import (
                ImmutableVertexcutFragment,
            )
            from libgrape_lite_tpu.io.line_parser import (
                read_edge_file,
                read_vertex_file,
            )

            src, dst, w = read_edge_file(args.efile, weighted=weighted)
            oids = (
                read_vertex_file(args.vfile)
                if args.vfile
                else np.unique(np.concatenate([src, dst]))
            )
            frag = ImmutableVertexcutFragment.build(
                comm_spec, oids, src, dst, w if weighted else None
            )
        elif args.delta_efile or args.delta_vfile:
            from libgrape_lite_tpu.fragment.mutation import LoadGraphAndMutate

            frag = LoadGraphAndMutate(
                args.efile, args.vfile or None,
                args.delta_efile or None, args.delta_vfile or None,
                comm_spec, spec,
            )
        else:
            frag = LoadGraph(args.efile, args.vfile or None, comm_spec, spec)

    if args.memory_stats:
        from libgrape_lite_tpu.utils.memory import get_memory_stats

        print(f"[memory] after load: {get_memory_stats()}")

    if name == "sssp_select":
        # per-(graph, source) dense-vs-delta decision on evidence
        # (models/sssp_select.py); the probe runs on the host CSRs the
        # load just produced, before any device compile
        from libgrape_lite_tpu.models.sssp_select import select_sssp_variant
        from libgrape_lite_tpu.utils import logging as glog

        with timer.phase("sssp variant probe"):
            picked, reason = select_sssp_variant(
                frag, _coerce_source(args.sssp_source, args.string_id)
            )
        glog.log_info(f"sssp_select -> {picked}: {reason}")
        app = APP_REGISTRY[picked]()

    with timer.phase("load application"):
        worker = Worker(app, frag)

    with timer.phase("run algorithm"):
        kw = build_query_kwargs(name, args)
        if args.profile and not getattr(app, "host_only", False):
            from libgrape_lite_tpu.utils import logging as glog

            if glog._level < 1:
                glog.set_vlog_level(1)  # --profile exists to show timings
        guard = args.guard or None  # None -> GRAPE_GUARD env
        if args.resume:
            # query args replay from the checkpoint metadata (the
            # fingerprint guarantees they match this invocation's app +
            # fragment); a fresh cadence flag overrides the recorded one
            worker.resume(
                args.checkpoint_dir,
                checkpoint_every=args.checkpoint_every or None,
                guard=guard,
            )
        elif args.checkpoint_every:
            worker.query(
                checkpoint_every=args.checkpoint_every,
                checkpoint_dir=args.checkpoint_dir,
                guard=guard,
                **kw,
            )
        elif args.profile and not getattr(app, "host_only", False):
            worker.query_stepwise(guard=guard, **kw)
        else:
            worker.query(guard=guard, **kw)

    if args.memory_stats:
        from libgrape_lite_tpu.utils.memory import get_memory_stats

        print(f"[memory] after query: {get_memory_stats()}")

    if args.out_prefix:
        with timer.phase("print output"):
            worker.output(args.out_prefix)

    from libgrape_lite_tpu import obs

    if obs.armed():
        # final flush: the worker flushes per query, but the output
        # phase above and any post-query spans must land too
        flushed = obs.flush()
        from libgrape_lite_tpu.utils import logging as glog

        if flushed["trace"]:
            glog.log_info(
                f"obs: trace -> {flushed['trace']} (JSONL twin "
                f"{flushed['jsonl']}); open via https://ui.perfetto.dev"
            )
        if flushed["metrics"]:
            glog.log_info(
                f"obs: metrics -> {flushed['metrics']}.json / .prom"
            )
    return worker
