"""Command-line driver: `python -m libgrape_lite_tpu.cli --application sssp ...`

Flag names mirror the reference gflags catalog
(`examples/analytical_apps/flags.cc:23-69`).

`python -m libgrape_lite_tpu.cli serve ...` drives the multi-query
serving runtime instead (serve/, docs/SERVING.md): load the graph
once, pump a scripted query stream through the admission queue with
vmapped multi-source batching, and print one JSON summary line
(queries, qps, p50/p99 latency globally and per app, batch-size
histogram).  `--replicas R / --tenants ... / --drain_at K` raise the
serving fleet instead (fleet/, docs/FLEET.md): replica routing
behind a graph-version fence, HBM-budget tenancy, and a
zero-downtime mid-stream drain; `--arrival_rate` feeds the stream
from a wall-clock feeder thread (serve/feeder.py).

`python -m libgrape_lite_tpu.cli lint ...` runs grape-lint
(analysis/, docs/STATIC_ANALYSIS.md): the AST contract rules R1-R8
over the library tree (or explicit paths), optionally the
compiled-artifact audits (--artifact), against the suppression
baseline — exits nonzero on any unsuppressed finding.

`python -m libgrape_lite_tpu.cli calibrate ...` runs the pricing-rate
calibration pass (ops/calibration.py, docs/CALIBRATION.md): a seeded
micro-bench sweep of the pack SpMV / masked-SpGEMM dispatches, a
least-squares rate fit over the measured walls, profile + sample
persistence, and the 5% modeled-vs-measured drift gate (`--check`
re-gates the active GRAPE_RATE_PROFILE without refitting; exit 2 on
drift).

`python -m libgrape_lite_tpu.cli postmortem <bundle.json>` renders a
flight-recorder bundle (obs/recorder.py; dumped into the
GRAPE_POSTMORTEM sink on a guard breach, fence violation or deadline
storm) and, with --trace, proves the bundle's serve_query span rows
byte-match the Chrome trace's rows for the same query ids.
"""

from __future__ import annotations

import argparse

from libgrape_lite_tpu.runner import QueryArgs, run_app


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="libgrape_lite_tpu")
    p.add_argument("--application", required=True)
    p.add_argument("--efile", required=True)
    p.add_argument("--vfile", default="")
    p.add_argument("--out_prefix", default="")
    p.add_argument("--directed", action="store_true")
    # source ids parse as text so --string_id graphs can name their
    # real ids; numeric strings coerce back to int in the runner
    p.add_argument("--sssp_source", default="0")
    p.add_argument("--bfs_source", default="0")
    p.add_argument("--bc_source", default="0")
    p.add_argument("--kcore_k", type=int, default=0)
    p.add_argument("--kclique_k", type=int, default=3)
    p.add_argument("--khop_k", type=int, default=2,
                   help="k-hop neighborhood hop bound (models/khop.py; "
                        "the source comes from --bfs_source)")
    p.add_argument("--cn_source", default="0",
                   help="common_neighbors 2-hop query source vertex")
    p.add_argument("--pr_d", type=float, default=0.85)
    p.add_argument("--pr_mr", type=int, default=10)
    p.add_argument("--cdlp_mr", type=int, default=10)
    p.add_argument("--degree_threshold", type=int, default=0,
                   help="LCC hub cap: skip neighbor lists of vertices "
                        "above this degree (flags.cc:39; 0 = disabled)")
    p.add_argument("--fnum", type=int, default=None,
                   help="fragment count (default: all local devices)")
    p.add_argument("--partitioner_type", default="map",
                   choices=["hash", "map", "segment"])
    p.add_argument("--idxer_type", default="hashmap",
                   choices=["hashmap", "sorted_array", "pthash", "local"])
    p.add_argument("--serialize", action="store_true")
    p.add_argument("--deserialize", action="store_true")
    p.add_argument("--serialization_prefix", default="")
    p.add_argument("--vc", action="store_true",
                   help="vertex-cut (2-D) storage; fnum must be k^2")
    p.add_argument("--delta_efile", default="")
    p.add_argument("--delta_vfile", default="")
    p.add_argument("--string_id", action="store_true",
                   help="treat vertex ids as strings (load_tests.cc:45)")
    p.add_argument("--rebalance", action="store_true")
    p.add_argument("--rebalance_vertex_factor", type=int, default=0)
    p.add_argument("--memory_stats", action="store_true")
    p.add_argument("--checkpoint_every", type=int, default=0,
                   help="snapshot the query carry every K supersteps "
                        "(ft/checkpoint.py; 0 = off; forces stepwise "
                        "execution, requires --checkpoint_dir)")
    p.add_argument("--checkpoint_dir", default="",
                   help="directory for superstep checkpoints")
    p.add_argument("--resume", action="store_true",
                   help="continue from the last complete checkpoint in "
                        "--checkpoint_dir (query args replay from the "
                        "checkpoint metadata; the config fingerprint "
                        "must match)")
    p.add_argument("--guard", default="",
                   choices=["", "off", "warn", "halt", "rollback"],
                   help="runtime invariant guard policy (guard/): warn "
                        "logs breaches, halt raises with a diagnostic "
                        "bundle, rollback self-heals from the last "
                        "checkpoint (needs --checkpoint_every); default "
                        "reads GRAPE_GUARD")
    p.add_argument("--profile", action="store_true",
                   help="stepwise rounds with per-round timing (PROFILING)")
    p.add_argument("--trace", default="",
                   help="arm obs/ tracing: write a Chrome trace_event "
                        "JSON (Perfetto-loadable) to this path plus a "
                        "JSONL twin next to it; equivalent to "
                        "GRAPE_TRACE=path (docs/OBSERVABILITY.md)")
    p.add_argument("--metrics", default="",
                   help="write the obs/ metrics snapshot to "
                        "<path>.json and <path>.prom at query end; "
                        "equivalent to GRAPE_METRICS=path")
    p.add_argument("--platform", default="",
                   help="jax platform override (e.g. cpu); default ambient")
    p.add_argument("--cpu_devices", type=int, default=0,
                   help="with --platform cpu: virtual device count")
    p.add_argument("--coordinator", default="",
                   help="jax.distributed coordinator address "
                        "(host:port); arms the multi-process runtime "
                        "together with --num_processes/--process_id")
    p.add_argument("--num_processes", type=int, default=0,
                   help="total process count for jax.distributed "
                        "(0 = single-process)")
    p.add_argument("--process_id", type=int, default=-1,
                   help="this process's rank in [0, num_processes)")
    return p


def make_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="libgrape_lite_tpu serve")
    p.add_argument("--efile", required=True)
    p.add_argument("--vfile", default="")
    p.add_argument("--directed", action="store_true")
    p.add_argument("--application", default="sssp",
                   help="app for --sources/--num_queries streams "
                        "(--stream lines carry their own app)")
    p.add_argument("--sources", default="",
                   help="comma-separated source ids, one query each")
    p.add_argument("--num_queries", type=int, default=0,
                   help="generate N queries with sources 0..N-1 "
                        "(used when --sources/--stream are not given)")
    p.add_argument("--stream", default="",
                   help="scripted stream file: one 'app source' line "
                        "per query")
    p.add_argument("--max_batch", type=int, default=8,
                   help="lanes per vmapped dispatch (serve/policy.py)")
    p.add_argument("--max_wait_ms", type=float, default=0.0,
                   help="queue-head wait before a partial batch ships")
    p.add_argument("--inflight", type=int, default=1,
                   help="dispatch-window depth (serve/pipeline.py): "
                        ">1 arms the async pump — up to W coalesced "
                        "batches dispatched un-synced with lazy FIFO "
                        "harvest, ingest as a window barrier; 1 "
                        "(default) keeps the synchronous loop "
                        "bit-for-bit (GRAPE_SERVE_INFLIGHT overrides "
                        "a pump's depth, recorded in PUMP_STATS)")
    p.add_argument("--dump_results", default="",
                   help="write one line per query in submit order "
                        "(index, app, ok, rounds, sha256 of the "
                        "assembled values) — the identity surface the "
                        "async smoke cmp's between --inflight 1 and "
                        "--inflight 4 runs")
    p.add_argument("--max_rounds", type=int, default=0)
    p.add_argument("--guard", default="",
                   choices=["", "off", "warn", "halt", "rollback"],
                   help="per-lane guard policy (breach isolation: a "
                        "poisoned lane fails alone)")
    p.add_argument("--replicas", type=int, default=1,
                   help="fleet/: serve the graph from R replica "
                        "sessions behind a least-outstanding front "
                        "router with a graph-version fence "
                        "(docs/FLEET.md); 1 keeps the single-session "
                        "path bit-for-bit")
    p.add_argument("--drain_at", type=int, default=-1,
                   help="fleet/: begin draining replica 0 before the "
                        "K-th query (zero-downtime drain drill — it "
                        "rejoins after the next ingest barrier, or at "
                        "stream end); requires --replicas >= 2")
    p.add_argument("--tenants", default="",
                   help="fleet/: multi-tenant front — 'by_app' gives "
                        "each distinct app its own tenant, an integer "
                        "N round-robins queries over N tenants; "
                        "tenants share the HBM budget "
                        "(GRAPE_FLEET_HBM_BYTES) with weighted "
                        "round-robin fairness and never share a "
                        "batched dispatch")
    p.add_argument("--arrival_rate", default="",
                   help="threaded admission front (serve/feeder.py): "
                        "submit the stream at this rate from a feeder "
                        "thread with real wall-clock arrivals, so "
                        "--max_wait_ms and priority/deadline "
                        "scheduling are exercised under load; a plain "
                        "QPS float, or a step schedule like "
                        "'50:2x@100' (double the rate from query "
                        "index 100 — the autopilot load-shift drill); "
                        "0/empty keeps the deterministic scripted "
                        "mode")
    p.add_argument("--autopilot", action="store_true",
                   help="autopilot/: close the observe->decide->act "
                        "loop over a replica fleet — an Autoscaler "
                        "scales replicas between --min_replicas and "
                        "--max_replicas through the zero-drop "
                        "drain/rejoin/replicate machinery, and a "
                        "shared fence-epoch result cache "
                        "(--cache_entries) answers repeated point "
                        "queries without the device "
                        "(docs/AUTOPILOT.md)")
    p.add_argument("--min_replicas", type=int, default=1,
                   help="autopilot: replica floor (and the initial "
                        "replica count)")
    p.add_argument("--max_replicas", type=int, default=4,
                   help="autopilot: replica ceiling")
    p.add_argument("--cache_entries", type=int, default=1024,
                   help="autopilot: result-cache capacity in entries "
                        "(0 disables the cache)")
    p.add_argument("--delta_stream", default="",
                   help="dyn/ live ingest: a delta-op stream file "
                        "('a src dst [w]' / 'd src dst' / 'u src dst "
                        "w' lines, scripts/gen_rmat.py --delta emits "
                        "one); chunks are ingested between query "
                        "batches while the stream runs")
    p.add_argument("--ingest_every", type=int, default=8,
                   help="queries pumped between delta-chunk ingests")
    p.add_argument("--dyn_repack_ratio", type=float, default=None,
                   help="delta ratio past which staged ops fold into "
                        "a rebuilt CSR (default GRAPE_DYN_REPACK_RATIO "
                        "or 0.05); below it, ingest is zero-recompile")
    p.add_argument("--fnum", type=int, default=None)
    p.add_argument("--string_id", action="store_true")
    p.add_argument("--trace", default="",
                   help="obs/ Chrome-trace path (per-query lane rows)")
    p.add_argument("--metrics", default="")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="obs/exporter.py: serve a live OpenMetrics "
                        "endpoint from a background thread for the "
                        "run's duration (/metrics, /federation, "
                        "/healthz); 0 binds an ephemeral port (the "
                        "URL prints to stderr); equivalent to "
                        "GRAPE_METRICS_PORT")
    p.add_argument("--slo", default="",
                   help="obs/slo.py latency objectives, e.g. "
                        "'sssp=5,tenant:t0=50,*=100' (ms per "
                        "app/tenant); a breach is a trace instant + "
                        "a federated error-budget burn counter, "
                        "never an exception; equivalent to GRAPE_SLO "
                        "(budget fraction: GRAPE_SLO_BUDGET)")
    p.add_argument("--platform", default="")
    p.add_argument("--cpu_devices", type=int, default=0)
    return p


def make_lint_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="libgrape_lite_tpu lint")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "installed libgrape_lite_tpu tree)")
    p.add_argument("--json", action="store_true",
                   help="print the structured report (schema-checked "
                        "against analysis/report.py before printing, "
                        "check_bench_schema discipline)")
    p.add_argument("--baseline", default="",
                   help="suppression baseline path (default: "
                        "analysis/baseline.json)")
    p.add_argument("--artifact", action="store_true",
                   help="also run the compiled-artifact audits "
                        "(A1 constant bloat / A2 donation / A3 "
                        "zero-compile warm matrix) — compiles small "
                        "canonical runners, so it needs a working "
                        "jax backend")
    p.add_argument("--update-baseline", default=None, metavar="REASON",
                   help="suppress every CURRENT unsuppressed AST "
                        "finding into the baseline with this reason "
                        "string (exceptions are named, not invisible)")
    p.add_argument("--platform", default="",
                   help="jax platform override for --artifact")
    return p


def lint_main(argv=None) -> int:
    """The `lint` subcommand; returns the process exit code (nonzero
    on any unsuppressed finding — the CI gate app_tests.sh enforces)."""
    import json as _json
    import sys

    ns = make_lint_parser().parse_args(argv)
    _apply_platform(ns.platform, 0)

    from libgrape_lite_tpu import analysis

    if ns.update_baseline is not None:
        import os

        if not ns.update_baseline:
            # an empty reason (e.g. an unset shell variable) must not
            # silently degrade to a plain lint run — the mandatory-
            # reason contract Baseline.add enforces starts HERE
            print(
                "grape-lint: --update-baseline needs a non-empty "
                "REASON — exceptions are named, not invisible",
                file=sys.stderr,
            )
            return 2

        paths = ns.paths or [
            os.path.join(analysis.repo_root(), "libgrape_lite_tpu")
        ]
        try:
            findings = analysis.lint_paths(paths)
        except FileNotFoundError as e:
            print(f"grape-lint: {e}", file=sys.stderr)
            return 2
        baseline = analysis.Baseline.load(ns.baseline or None)
        live, _ = analysis.split_by_baseline(findings, baseline)
        for f in live:
            baseline.add(f, ns.update_baseline)
        path = baseline.save()
        print(f"baseline: {len(live)} suppression(s) added -> {path}")
        return 0

    try:
        report, rc = analysis.run_lint(
            ns.paths, baseline_path=ns.baseline or None,
            artifact=ns.artifact,
        )
    except FileNotFoundError as e:
        print(f"grape-lint: {e}", file=sys.stderr)
        return 2
    if ns.json:
        errors = analysis.validate_lint_report(report)
        if errors:
            # the report record is a pinned artifact like the BENCH
            # json: schema drift fails AFTER the findings are shown
            print(_json.dumps(report), flush=True)
            for e in errors:
                print(f"lint-report schema: {e}", file=sys.stderr)
            return 3
        print(_json.dumps(report), flush=True)
    else:
        live = [analysis.Finding(**{k: f[k] for k in (
            "rule", "path", "line", "symbol", "message")})
            for f in report["findings"] if not f["suppressed"]]
        quiet = [f for f in report["findings"] if f["suppressed"]]
        print(analysis.render_text(live, quiet, report.get("stale")))
    return rc


def make_calibrate_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="libgrape_lite_tpu calibrate")
    p.add_argument("--out", default="",
                   help="write the fitted RateProfile json here "
                        "(install it via GRAPE_RATE_PROFILE=<path>)")
    p.add_argument("--samples-out", default="",
                   help="persist the measured sweep json — the bench "
                        "calibration lane and --check replay it "
                        "deterministically (GRAPE_CALIBRATION_SAMPLES)")
    p.add_argument("--samples", default="",
                   help="fit/check from a RECORDED sample set instead "
                        "of re-measuring")
    p.add_argument("--check", action="store_true",
                   help="no fit: drift-gate the ACTIVE profile "
                        "(GRAPE_RATE_PROFILE, or --profile) against "
                        "the samples; exit 2 beyond the 5%% tolerance")
    p.add_argument("--profile", default="",
                   help="explicit profile json for --check (default: "
                        "the active profile)")
    p.add_argument("--scales", default="8,9,10",
                   help="comma-separated RMAT scales for the sweep")
    p.add_argument("--ef", type=int, default=8,
                   help="sweep edge factor")
    p.add_argument("--repeats", type=int, default=3,
                   help="best-of-N walls per dispatch")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--min-wall-s", type=float, default=-1.0,
                   help="exclude sweep samples with walls under this "
                        "(default: backend-appropriate — 20ms on the "
                        "CPU backend where sub-noise-floor walls are "
                        "scheduler jitter, 0 on real accelerators)")
    p.add_argument("--json", action="store_true",
                   help="print one structured record instead of the "
                        "table")
    p.add_argument("--platform", default="",
                   help="jax platform override (e.g. cpu)")
    return p


def calibrate_main(argv=None) -> int:
    """The `calibrate` subcommand (ops/calibration.py,
    docs/CALIBRATION.md): measure device walls, fit the pricing-rate
    profile, persist it, and drift-gate modeled-vs-measured.  Exit 0 =
    fit ok / gate passed, 2 = infeasible fit or the drift gate
    tripped."""
    import json as _json
    import sys

    ns = make_calibrate_parser().parse_args(argv)
    _apply_platform(ns.platform, 0)

    from libgrape_lite_tpu.ops import calibration as calib

    try:
        if ns.samples:
            samples = calib.load_samples(ns.samples)
        else:
            scales = tuple(int(s) for s in ns.scales.split(",") if s)
            samples = calib.microbench_samples(
                scales=scales, ef=ns.ef, seed=ns.seed,
                repeats=ns.repeats,
            )
            floor = (ns.min_wall_s if ns.min_wall_s >= 0
                     else calib.default_min_wall_s())
            kept = [s for s in samples if s["wall_s"] >= floor]
            if len(kept) < len(samples):
                print(
                    f"calibrate: dropped {len(samples) - len(kept)} "
                    f"sample(s) under the {floor * 1e3:.0f}ms noise "
                    "floor",
                    file=sys.stderr,
                )
            samples = kept
        if not samples:
            print("calibrate: no usable samples measured — nothing "
                  "to fit", file=sys.stderr)
            return 2

        notes: list = []
        fit = None
        if ns.check:
            prof = (calib.load_profile(ns.profile) if ns.profile
                    else calib.active_profile())
        else:
            fit, notes = calib.fit_rates_auto(
                samples, base=calib.default_profile(),
                source="samples" if ns.samples else "microbench",
            )
            prof = fit.profile
        rep = calib.drift_report(prof, samples)
    except calib.CalibrationError as e:
        print(f"calibrate: {e}", file=sys.stderr)
        return 2

    out_path = samples_path = None
    if not ns.check and ns.out:
        out_path = calib.save_profile(prof, ns.out)
    if ns.samples_out:
        samples_path = calib.save_samples(samples, ns.samples_out)

    # the same shape as the bench record's `calibration` block, so one
    # schema (scripts/check_bench_schema.py _CALIBRATION) pins both
    block = {
        "profile": prof.label(),
        "fingerprint": calib.backend_fingerprint(),
        "source": prof.source,
        "fitted": bool(prof.fitted),
        "samples": len(samples),
        "residual_pct": (round(fit.residual * 100.0, 3)
                         if fit is not None else -1.0),
        "drift_pct": rep["drift_pct"],
        "max_sample_drift_pct": rep["max_sample_drift_pct"],
        "drift_ok": rep["drift_ok"],
        "rates": {
            "clock_hz": prof.clock_hz,
            "vpu_lanes_per_cycle": prof.vpu_lanes_per_cycle,
            "mxu_cyc_per_elem": prof.mxu_cyc_per_elem,
            "hbm_bps": prof.hbm_bps,
            "gather_rows_per_cycle": prof.gather_rows_per_cycle,
            "dispatch_overhead_s": prof.dispatch_overhead_s,
        },
        "unfitted": sorted(prof.unfitted),
        "fallback_notes": list(notes),
        "surfaces": rep["surfaces"],
    }
    if ns.json:
        print(_json.dumps({"calibration": block, "out": out_path,
                           "samples_out": samples_path}))
    else:
        print(f"profile:  {block['profile']} "
              f"(source={block['source']}, "
              f"fitted={block['fitted']})")
        for r, v in sorted(block["rates"].items()):
            print(f"  {r:<22} {v:g}")
        if block["unfitted"]:
            print(f"  unfitted (inherited): "
                  f"{', '.join(block['unfitted'])}")
        for n in notes:
            print(f"  [fallback] {n}")
        for surf, e in sorted(rep["surfaces"].items()):
            print(f"drift[{surf}]: modeled {e['modeled_s']:.4f}s vs "
                  f"measured {e['measured_s']:.4f}s over "
                  f"{e['samples']} sample(s) = {e['drift_pct']:g}%")
        verdict = "OK" if rep["drift_ok"] else "FAIL"
        print(f"{verdict}: drift {rep['drift_pct']:g}% "
              f"(tolerance {rep['tolerance_pct']:g}%), "
              f"residual {block['residual_pct']:g}%")
        if out_path:
            print(f"profile -> {out_path}")
        if samples_path:
            print(f"samples -> {samples_path}")
    return 0 if rep["drift_ok"] else 2


def _apply_platform(platform: str, cpu_devices: int) -> None:
    if cpu_devices:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={cpu_devices}"
        ).strip()
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def serve_main(argv=None):
    """The `serve` subcommand: resident session + scripted stream."""
    import json
    import sys
    import time

    import numpy as np

    ns = make_serve_parser().parse_args(argv)
    _apply_platform(ns.platform, ns.cpu_devices)
    if ns.trace or ns.metrics:
        from libgrape_lite_tpu import obs

        obs.configure(trace_path=ns.trace or None,
                      metrics_path=ns.metrics or None)
    if ns.slo:
        from libgrape_lite_tpu.obs import slo

        slo.configure(ns.slo)
    if ns.metrics_port is not None:
        from libgrape_lite_tpu.obs import exporter

        exp = exporter.start_exporter(ns.metrics_port)
        print(f"[serve] metrics exporter: {exp.url}", file=sys.stderr)
    else:
        from libgrape_lite_tpu.obs import exporter

        exp = exporter.maybe_start_from_env()
        if exp is not None:
            print(f"[serve] metrics exporter: {exp.url}",
                  file=sys.stderr)

    from libgrape_lite_tpu.fragment.loader import LoadGraph, LoadGraphSpec
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession
    from libgrape_lite_tpu.utils import timer

    # the scripted stream: (app, source) per query
    def coerce(src):
        if ns.string_id:
            return src
        try:
            return int(src)
        except ValueError:
            return src

    queries = []
    if ns.stream:
        for line in open(ns.stream):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            app_key, src = line.split()
            queries.append((app_key, coerce(src)))
    elif ns.sources:
        queries = [(ns.application, coerce(s))
                   for s in ns.sources.split(",")]
    else:
        queries = [(ns.application, s)
                   for s in range(max(1, ns.num_queries))]
    if not queries:
        # an all-comment --stream / empty --sources: fail BEFORE the
        # (possibly minutes-long) graph load, not on an empty latency
        # percentile afterwards
        sys.exit("serve: the query stream is empty")
    for app_key, _ in queries:
        if app_key not in APP_REGISTRY:
            raise ValueError(f"unknown application {app_key!r}")

    # one load serves every query — the point of the session
    weighted = any(
        getattr(APP_REGISTRY[a], "needs_edata", False) for a, _ in queries
    )

    # dyn/ live ingest: parse the delta stream up front (reproducible
    # chunking, malformed lines fail BEFORE the load) with the SAME
    # weightedness as the graph — a weighted serve must not silently
    # ingest zero-cost edges from an unweighted stream
    delta_ops = []
    if ns.delta_stream:
        from libgrape_lite_tpu.dyn import parse_ops_file

        delta_ops = parse_ops_file(
            ns.delta_stream, weighted=weighted, string_id=ns.string_id
        )
    # --arrival_rate: a float or a step spec ("50:2x@100") — validate
    # BEFORE the load; "0" keeps the legacy disabled meaning
    if ns.arrival_rate:
        try:
            if float(ns.arrival_rate) == 0.0:
                ns.arrival_rate = ""
        except ValueError:
            pass
    if ns.arrival_rate:
        from libgrape_lite_tpu.serve.feeder import parse_rate_spec

        try:
            parse_rate_spec(ns.arrival_rate)
        except ValueError as e:
            sys.exit(f"serve: {e}")
    fleet_mode = ns.replicas > 1 or bool(ns.tenants)
    if ns.drain_at >= 0 and ns.replicas < 2:
        sys.exit("serve: --drain_at needs --replicas >= 2 (draining "
                 "the only replica would drop traffic)")
    if ns.autopilot:
        # the autopilot runs its OWN fleet loop — it owns replica
        # count (min/max), so the static fleet knobs don't compose
        for flag, bad in (("--tenants", bool(ns.tenants)),
                          ("--drain_at", ns.drain_at >= 0),
                          ("--delta_stream", bool(ns.delta_stream))):
            if bad:
                sys.exit(f"serve: --autopilot does not compose with "
                         f"{flag} yet")
        if ns.min_replicas < 1:
            sys.exit("serve: --min_replicas must be >= 1")
        if ns.max_replicas < ns.min_replicas:
            sys.exit("serve: --max_replicas must be >= --min_replicas")
    elif fleet_mode and ns.arrival_rate:
        sys.exit("serve: --arrival_rate does not compose with "
                 "--replicas/--tenants yet")
    spec = LoadGraphSpec(
        directed=ns.directed, weighted=weighted,
        string_id=ns.string_id, edata_dtype=np.float64,
        # autopilot scale-ups replicate fresh fragments from the
        # retained edge list, exactly like --replicas
        retain_edge_list=bool(ns.delta_stream) or ns.replicas > 1
        or ns.autopilot,
    )
    with timer.phase("load graph"):
        frag = LoadGraph(ns.efile, ns.vfile or None,
                         CommSpec(fnum=ns.fnum), spec)

    def dyn_policy():
        if not ns.delta_stream:
            return None
        from libgrape_lite_tpu.dyn import RepackPolicy

        return (
            RepackPolicy(threshold=ns.dyn_repack_ratio)
            if ns.dyn_repack_ratio is not None
            else RepackPolicy.from_env()
        )

    policy = BatchPolicy(max_batch=ns.max_batch,
                         max_wait_s=ns.max_wait_ms / 1e3)

    if ns.autopilot:
        return _serve_autopilot(ns, frag, queries, policy, dyn_policy)
    if fleet_mode:
        return _serve_fleet(ns, frag, queries, delta_ops, policy,
                            dyn_policy)

    sess = ServeSession(
        frag,
        policy=policy,
        guard=ns.guard or None,
        dyn=dyn_policy(),
    )
    # --inflight > 1 arms the async pump (serve/pipeline.py): up to W
    # coalesced batches dispatched un-synced, lazy FIFO harvest, and
    # every ingest an explicit window barrier.  --inflight 1 keeps the
    # synchronous loop below bit-for-bit.
    pump = sess.async_pump(window=ns.inflight) if ns.inflight > 1 else None
    t0 = time.perf_counter()
    if ns.arrival_rate:
        # threaded admission front (serve/feeder.py): a feeder thread
        # submits at the asked rate with REAL wall-clock arrival
        # timestamps while this thread pumps — max_wait_ms and
        # priority/deadline scheduling genuinely gate under load.
        # Does not compose with --delta_stream (the deterministic
        # ingest cadence is pinned by dispatch count, which a
        # wall-clock feeder cannot reproduce).
        if delta_ops:
            sys.exit("serve: --arrival_rate does not compose with "
                     "--delta_stream")
        from libgrape_lite_tpu.serve import ArrivalFeeder

        feeder = ArrivalFeeder(
            sess.submit,
            # dict form so --max_rounds reaches submit exactly as on
            # the scripted path
            [{"app": app_key, "args": {"source": src},
              "max_rounds": ns.max_rounds or None}
             for app_key, src in queries],
            ns.arrival_rate,
        )
        results = []
        feeder.start()
        while feeder.is_alive() or sess.queue.pending() or (
            pump is not None and pump.inflight()
        ):
            got = (pump.pump() if pump is not None
                   else sess.pump())
            results.extend(got)
            if not got:
                time.sleep(1e-4)
        feeder.join()
        results.extend(
            pump.drain() if pump is not None else sess.drain()
        )
        reqs = feeder.requests
        wall = time.perf_counter() - t0
        return _serve_summary(ns, sess, pump, reqs, results, wall,
                              delta_ops)
    reqs = [
        sess.submit(app_key, {"source": src},
                    max_rounds=ns.max_rounds or None)
        for app_key, src in queries
    ]
    if delta_ops:
        # streaming mode: ingest a delta chunk after every
        # --ingest_every dispatched queries, so updates land between
        # batches while the query stream stays live.  The sync loop
        # makes each ingest a superstep boundary by construction; the
        # async pump makes it an explicit window quiesce — and pins
        # the SAME ingest points by dispatch count (`max_dispatch`),
        # so the batch <-> graph-version interleave (and therefore
        # every result byte) is identical at any --inflight.
        ingest_every = max(1, ns.ingest_every)
        n_chunks = max(1, -(-len(queries) // ingest_every))
        chunk = -(-len(delta_ops) // n_chunks)
        oi = 0
        results = []
        if pump is not None:
            while (sess.queue.pending() or pump.inflight()
                   or oi < len(delta_ops)):
                target = pump.dispatched_queries + ingest_every
                while (sess.queue.pending()
                       and pump.dispatched_queries < target):
                    pump.pump(force=True, block=True,
                              max_dispatch=target)
                if oi < len(delta_ops):
                    pump.ingest(delta_ops[oi:oi + chunk])
                    oi += chunk
                else:
                    pump.drain()
            results = [q.result for q in reqs]
        else:
            while sess.queue.pending() or oi < len(delta_ops):
                pumped = 0
                while sess.queue.pending() and pumped < ingest_every:
                    got = sess.pump(force=True)
                    results.extend(got)
                    pumped += len(got)
                if oi < len(delta_ops):
                    sess.ingest(delta_ops[oi:oi + chunk])
                    oi += chunk
    else:
        results = pump.drain() if pump is not None else sess.drain()
    wall = time.perf_counter() - t0
    return _serve_summary(ns, sess, pump, reqs, results, wall,
                          delta_ops)


def _serve_fleet(ns, frag, queries, delta_ops, policy, dyn_policy):
    """The fleet serving path (fleet/, docs/FLEET.md): R replica
    sessions behind a version-fenced router and/or N tenants under
    one HBM budget, driven by the deterministic
    `run_fleet_script` — so a `--replicas 2 --drain_at K` run is
    byte-identical per query to the plain single-replica run (the
    smoke in scripts/app_tests.sh cmp's exactly that via
    --dump_results).  `dyn_policy` is serve_main's own repack-policy
    factory — ONE copy of that decision, so the fleet run can never
    quietly use a different policy than the plain run it must match
    byte-for-byte."""
    import sys
    import time

    from libgrape_lite_tpu.fleet import (
        FLEET_STATS,
        FleetBudget,
        FleetManager,
        FleetRouter,
        run_fleet_script,
    )
    from libgrape_lite_tpu.fragment.mutation import replicate_fragment
    from libgrape_lite_tpu.serve import ServeSession

    # the summary's fleet counters are a per-run record (the bench
    # PUMP_STATS discipline): reset the process-global stats first
    FLEET_STATS.reset()

    def make_session(f):
        return ServeSession(
            f, policy=policy, guard=ns.guard or None,
            dyn=dyn_policy(),
        )

    frags = [frag] + [
        replicate_fragment(frag) for _ in range(ns.replicas - 1)
    ]
    sessions = [make_session(f) for f in frags]
    router = (
        FleetRouter(sessions, window=max(1, ns.inflight))
        if ns.replicas > 1 else None
    )
    target = router if router is not None else sessions[0]

    manager = None
    tenant_of = None
    if ns.tenants:
        manager = FleetManager(FleetBudget())
        if ns.tenants == "by_app":
            names = sorted({app for app, _ in queries})
            tenant_of = lambda i, app: app  # noqa: E731
        else:
            try:
                n_t = max(1, int(ns.tenants))
            except ValueError:
                sys.exit(f"serve: --tenants must be 'by_app' or an "
                         f"integer, got {ns.tenants!r}")
            names = [f"t{j}" for j in range(n_t)]
            tenant_of = lambda i, app: f"t{i % n_t}"  # noqa: E731
        for name in names:
            manager.add_tenant(name, target)

    fleet_queries = [
        (app_key, {"source": src}) for app_key, src in queries
    ]
    t0 = time.perf_counter()
    reqs = run_fleet_script(
        target, fleet_queries, manager=manager, tenant_of=tenant_of,
        delta_ops=delta_ops, ingest_every=max(1, ns.ingest_every),
        drain_at=(ns.drain_at if ns.drain_at >= 0 else None),
        drain_idx=0,
        # stream-wide limits reach the queue exactly as on the plain
        # path (a dropped --max_rounds would silently change results)
        submit_kwargs={"max_rounds": ns.max_rounds or None},
    )
    wall = time.perf_counter() - t0
    results = [q.result for q in reqs if q.result is not None]

    fleet_block = {
        "replicas": ns.replicas,
        "tenants": len(manager.tenants) if manager is not None else 0,
        "fence": router.fence if router is not None else 0,
        "dropped": len(reqs) - len(results),
        **FLEET_STATS.snapshot(),
    }
    if router is not None:
        fleet_block["router"] = router.summary(wall)
    if manager is not None:
        snap = manager.snapshot()
        fleet_block["tenant_stats"] = snap["tenants"]
        fleet_block["budget"] = {
            "capacity": snap["budget"]["capacity"],
            "used_bytes": snap["budget"]["used_bytes"],
        }
    return _serve_summary(
        ns, sessions[0], None, reqs, results, wall, delta_ops,
        fleet_block=fleet_block, sessions=sessions,
    )


def _serve_autopilot(ns, frag, queries, policy, dyn_policy):
    """The closed-loop serving path (autopilot/, docs/AUTOPILOT.md):
    a replica fleet whose size the Autoscaler moves between
    --min_replicas and --max_replicas from live queue/burn signals,
    with a shared fence-epoch result cache in front of the device.
    With --arrival_rate the stream arrives on a feeder thread (the
    rate may STEP mid-stream: '50:2x@100') while this thread routes,
    pumps, and ticks the control loop; without it the scripted stream
    submits up front and the loop still ticks between pumps."""
    import sys  # noqa: F401  (parity with the sibling drivers)
    import time
    from collections import deque

    from libgrape_lite_tpu.autopilot import (
        Autoscaler,
        ResultCache,
        ScalerConfig,
    )
    from libgrape_lite_tpu.autopilot.signals import AUTOPILOT_STATS
    from libgrape_lite_tpu.fleet import (
        FLEET_STATS,
        FleetBudget,
        FleetRouter,
    )
    from libgrape_lite_tpu.fragment.mutation import replicate_fragment
    from libgrape_lite_tpu.serve import ServeSession

    # per-run record discipline (the _serve_fleet PUMP_STATS rule):
    # process-global stats reset first
    FLEET_STATS.reset()
    AUTOPILOT_STATS.reset()

    def make_session(f):
        return ServeSession(
            f, policy=policy, guard=ns.guard or None, dyn=dyn_policy(),
        )

    n0 = max(1, ns.min_replicas, ns.replicas)
    frags = [frag] + [replicate_fragment(frag) for _ in range(n0 - 1)]
    sessions = [make_session(f) for f in frags]
    router = FleetRouter(sessions, window=max(1, ns.inflight))
    cache = None
    if ns.cache_entries > 0:
        cache = ResultCache(capacity=ns.cache_entries)
        router.attach_cache(cache)
    cfg = ScalerConfig(
        min_replicas=n0, max_replicas=max(n0, ns.max_replicas),
    )
    autopilot = Autoscaler(
        router, cfg, session_factory=make_session, budget=FleetBudget(),
    )

    def busy():
        return any(
            r.session.queue.pending() or r.pump.inflight()
            for r in router.replicas
        )

    stream = [
        {"app": app_key, "args": {"source": src},
         "max_rounds": ns.max_rounds or None}
        for app_key, src in queries
    ]
    reqs = []
    t0 = time.perf_counter()
    if ns.arrival_rate:
        from libgrape_lite_tpu.serve import ArrivalFeeder

        # the feeder thread only APPENDS arrivals; this thread alone
        # touches the router (submit/pump/tick), so the fleet stays
        # single-threaded like every other driver
        inbox: deque = deque()

        def enqueue(app_key, args, **kw):
            inbox.append((app_key, args, kw))

        feeder = ArrivalFeeder(enqueue, stream, ns.arrival_rate)
        feeder.start()
        while feeder.is_alive() or inbox or busy():
            moved = 0
            while inbox:
                app_key, args, kw = inbox.popleft()
                reqs.append(router.submit(app_key, args, **kw))
                moved += 1
            got = router.pump()
            autopilot.tick()
            if not got and not moved:
                time.sleep(1e-4)
        feeder.join()
    else:
        for item in stream:
            reqs.append(router.submit(
                item["app"], item["args"],
                max_rounds=item["max_rounds"],
            ))
            router.pump()
            autopilot.tick()
        while busy():
            router.pump()
            autopilot.tick()
    router.drain()
    wall = time.perf_counter() - t0
    results = [q.result for q in reqs if q.result is not None]

    routable = [r for r in router.replicas if r.routable]
    ap = AUTOPILOT_STATS.snapshot()
    autopilot_block = {
        "min_replicas": cfg.min_replicas,
        "max_replicas": cfg.max_replicas,
        "replicas_final": len(routable),
        "replicas_peak": len(router.replicas),
        **{k: ap[k] for k in (
            "ticks", "scale_ups", "scale_downs", "holds", "shed",
            "deferred", "cache_hits", "cache_misses", "cache_stores",
        )},
    }
    if cache is not None:
        autopilot_block["cache"] = cache.snapshot()
    fleet_block = {
        "replicas": len(router.replicas),
        "tenants": 0,
        "fence": router.fence,
        "dropped": len(reqs) - len(results),
        **FLEET_STATS.snapshot(),
        "router": router.summary(wall),
    }
    return _serve_summary(
        ns, router.replicas[0].session, None, reqs, results, wall,
        [], fleet_block=fleet_block,
        sessions=[r.session for r in router.replicas],
        autopilot_block=autopilot_block,
    )


def _per_app_latency_ms(results) -> dict:
    """Per-app p50/p99 latency next to the global one — the fleet
    bench's per-workload view of a mixed stream."""
    from libgrape_lite_tpu.serve.queue import latency_summary_ms

    by_app: dict = {}
    for r in results:
        by_app.setdefault(r.app_key, []).append(r.latency_s)
    out = {}
    for app, lat in sorted(by_app.items()):
        s = latency_summary_ms(lat)
        out[app] = {"p50": s["p50_ms"], "p99": s["p99_ms"]}
    return out


def _serve_summary(ns, sess, pump, reqs, results, wall, delta_ops,
                   fleet_block=None, sessions=None,
                   autopilot_block=None):
    """Build + print the serve summary record (shared by the plain,
    feeder and fleet paths).  `sessions` (fleet) merges batch
    histograms and admission waits across replicas/tenant sessions;
    otherwise `sess` alone reports."""
    import json
    import sys

    from libgrape_lite_tpu.serve.queue import latency_summary_ms

    sessions = sessions or [sess]
    lat = latency_summary_ms([r.latency_s for r in results])
    ok = sum(1 for r in results if r.ok)
    per_app: dict = {}
    for r in results:
        per_app[r.app_key] = per_app.get(r.app_key, 0) + 1
    waits = latency_summary_ms(
        [w for s in sessions for w in s.queue.admission_waits]
    )
    batch_hist: dict = {}
    for s in sessions:
        for k, v in s.queue.batch_hist.items():
            batch_hist[k] = batch_hist.get(k, 0) + v
    cache = {"runner": {"hits": 0, "misses": 0},
             "pack": sess.cache_stats()["pack"]}
    for s in sessions:
        st = s.cache_stats()["runner"]
        cache["runner"]["hits"] += st["hits"]
        cache["runner"]["misses"] += st["misses"]
    record = {
        "queries": len(results),
        "ok": ok,
        "failed": len(results) - ok,
        "wall_s": round(wall, 4),
        "qps": round(len(results) / wall, 2) if wall > 0 else 0.0,
        "p50_ms": lat["p50_ms"],
        "p99_ms": lat["p99_ms"],
        "max_batch": ns.max_batch,
        "inflight": ns.inflight,
        "batch_hist": {
            str(k): v for k, v in sorted(batch_hist.items())
        },
        # per-request submit->dispatch wait (serve/queue.py): the
        # admission-latency half of the p99 story, next to batch_hist
        "admission_wait_ms": {
            "p50": waits["p50_ms"], "p99": waits["p99_ms"],
        },
        "apps": per_app,
        # per-app latency split next to the global p50/p99 (a mixed
        # stream's per-workload tails diverge — sssp vs khop)
        "per_app_ms": _per_app_latency_ms(results),
        "cache": cache,
    }
    # per-stage p50/p99 decomposition (queue_wait/window_wait/
    # dispatch/device/harvest µs, from ServeResult.stages): where the
    # global p99 actually went — shared by plain, pump and fleet paths
    stage_lists: dict = {}
    for r in results:
        for k, v in (r.stages or {}).items():
            stage_lists.setdefault(k, []).append(v / 1e6)
    if stage_lists:
        record["stages"] = {}
        for k, v in sorted(stage_lists.items()):
            s = latency_summary_ms(v)
            record["stages"][k] = {"p50": s["p50_ms"], "p99": s["p99_ms"]}
    from libgrape_lite_tpu.obs import slo as _slo

    if _slo.configured():
        record["slo"] = _slo.SLO_STATS.snapshot()
    if pump is not None:
        from libgrape_lite_tpu.serve import PUMP_STATS

        record["pump"] = {
            "window": pump.window,
            **pump.stats,
            **PUMP_STATS.snapshot(),
        }
    if delta_ops:
        # the same field names as bench.py's schema-checked dyn block
        # (scripts/check_bench_schema.py _DYN), so both surfaces
        # validate against one declaration
        ingested = sum(s.stats["ingested_ops"] for s in sessions)
        record["dyn"] = {
            "ingested": ingested,
            "overlay_applies": sum(
                s.stats["overlay_applies"] for s in sessions
            ),
            "repack_count": sum(s.stats["repacks"] for s in sessions),
            "queries": len(results),
            "queries_ok": ok,
            "updates_per_s": (
                round(ingested / wall, 2) if wall > 0 else 0.0
            ),
        }
    if fleet_block is not None:
        record["fleet"] = fleet_block
    if autopilot_block is not None:
        record["autopilot"] = autopilot_block
    if ns.dump_results:
        # submit-order identity surface: one line per query with a
        # digest of its assembled values — byte-comparable across
        # --inflight settings (the async smoke cmp's 4 against 1)
        import hashlib

        with open(ns.dump_results, "w") as fh:
            for i, req in enumerate(reqs):
                r = req.result
                digest = (
                    hashlib.sha256(r.values.tobytes()).hexdigest()
                    if r is not None and r.ok and r.values is not None
                    else "-"
                )
                ok_flag = int(bool(r is not None and r.ok))
                rounds = r.rounds if r is not None else -1
                fh.write(
                    f"{i} {req.app_key} {ok_flag} {rounds} {digest}\n"
                )
    print(json.dumps(record), flush=True)
    if results and not ok:
        print("[serve] every query failed", file=sys.stderr)
        sys.exit(1)

    from libgrape_lite_tpu import obs

    if obs.armed():
        obs.flush()


def make_postmortem_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="libgrape_lite_tpu postmortem")
    p.add_argument("bundle",
                   help="flight-recorder bundle json (obs/recorder.py "
                        "writes one per trigger into the "
                        "GRAPE_POSTMORTEM sink directory)")
    p.add_argument("--trace", default="",
                   help="Chrome trace file from the same run: verify "
                        "every serve_query span row in the bundle "
                        "byte-matches the trace's row for the same "
                        "query id (exit 1 on any mismatch — the "
                        "postmortem and the timeline must join "
                        "row-for-row)")
    p.add_argument("--json", action="store_true",
                   help="print the raw bundle instead of the report")
    return p


def postmortem_main(argv=None) -> int:
    """The `postmortem` subcommand: render a flight-recorder bundle,
    and with --trace prove its span rows are the SAME rows as the
    Chrome trace's (byte-equality of the sort_keys serialization per
    query id — bundles copy tracer history verbatim, so any drift is
    a recorder bug, not formatting noise)."""
    import json
    import sys
    from collections import Counter

    from libgrape_lite_tpu.obs.recorder import BUNDLE_SCHEMA

    ns = make_postmortem_parser().parse_args(argv)
    try:
        with open(ns.bundle) as fh:
            bundle = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"postmortem: {ns.bundle}: {e}", file=sys.stderr)
        return 2
    if bundle.get("schema") != BUNDLE_SCHEMA:
        print(f"postmortem: {ns.bundle}: schema "
              f"{bundle.get('schema')!r} != {BUNDLE_SCHEMA!r}",
              file=sys.stderr)
        return 2
    if ns.json:
        print(json.dumps(bundle, indent=1))
        return 0

    events = bundle.get("events") or []
    spans = bundle.get("spans") or []
    instants = bundle.get("instants") or []
    fed = bundle.get("federation") or {}
    lines = [
        f"postmortem: {bundle['reason']}",
        f"  trace_id:    {bundle.get('trace_id')}",
        f"  extra:       {json.dumps(bundle.get('extra') or {}, sort_keys=True)}",
        f"  ring events: {len(events)} "
        f"({dict(Counter(e.get('kind') for e in events))})",
        f"  spans:       {len(spans)} "
        f"({dict(Counter(s.get('name') for s in spans))})",
        f"  instants:    {len(instants)} "
        f"({dict(Counter(i.get('name') for i in instants))})",
        f"  federation:  {sorted(fed)}",
        f"  guard:       "
        f"{'yes (' + str((bundle['guard'].get('verdict') or {}).get('kind')) + ')' if bundle.get('guard') else 'no'}",
    ]
    slo_snap = fed.get("slo") or {}
    if slo_snap.get("objectives_ms"):
        lines.append(
            f"  slo:         {slo_snap.get('breaches', 0)} breach(es) "
            f"of {slo_snap.get('observed', 0)} observed, "
            f"max burn {slo_snap.get('max_burn', 0.0)}"
        )
    print("\n".join(lines))

    if not ns.trace:
        return 0
    try:
        with open(ns.trace) as fh:
            trace = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"postmortem: {ns.trace}: {e}", file=sys.stderr)
        return 2
    by_qid: dict = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X" or ev.get("name") != "serve_query":
            continue
        qid = (ev.get("args") or {}).get("query_id")
        if qid is not None:
            by_qid.setdefault(qid, []).append(
                json.dumps(ev, sort_keys=True)
            )
    matched = mismatched = missing = 0
    for row in spans:
        if row.get("name") != "serve_query":
            continue
        qid = (row.get("args") or {}).get("query_id")
        want = json.dumps(row, sort_keys=True)
        cands = by_qid.get(qid, [])
        if want in cands:
            matched += 1
        elif cands:
            mismatched += 1
        else:
            missing += 1
    print(f"trace cross-check: {matched} serve_query row(s) "
          f"byte-matched, {mismatched} mismatched, {missing} absent "
          f"from the trace")
    return 1 if (mismatched or missing) else 0


def main(argv=None):
    import sys

    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        return serve_main(argv[1:])
    if argv and argv[0] == "postmortem":
        return postmortem_main(argv[1:])
    if argv and argv[0] == "lint":
        # returned (not sys.exit'd) so programmatic callers get the
        # code; the module tail exits with it
        return lint_main(argv[1:])
    if argv and argv[0] == "calibrate":
        return calibrate_main(argv[1:])
    ns = make_parser().parse_args(argv)
    _apply_platform(ns.platform, ns.cpu_devices)
    args = QueryArgs(
        **{k: v for k, v in vars(ns).items()
           if k not in ("platform", "cpu_devices")}
    )
    run_app(args)


if __name__ == "__main__":
    import sys

    sys.exit(main())
