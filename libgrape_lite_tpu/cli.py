"""Command-line driver: `python -m libgrape_lite_tpu.cli --application sssp ...`

Flag names mirror the reference gflags catalog
(`examples/analytical_apps/flags.cc:23-69`).
"""

from __future__ import annotations

import argparse

from libgrape_lite_tpu.runner import QueryArgs, run_app


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="libgrape_lite_tpu")
    p.add_argument("--application", required=True)
    p.add_argument("--efile", required=True)
    p.add_argument("--vfile", default="")
    p.add_argument("--out_prefix", default="")
    p.add_argument("--directed", action="store_true")
    # source ids parse as text so --string_id graphs can name their
    # real ids; numeric strings coerce back to int in the runner
    p.add_argument("--sssp_source", default="0")
    p.add_argument("--bfs_source", default="0")
    p.add_argument("--bc_source", default="0")
    p.add_argument("--kcore_k", type=int, default=0)
    p.add_argument("--kclique_k", type=int, default=3)
    p.add_argument("--pr_d", type=float, default=0.85)
    p.add_argument("--pr_mr", type=int, default=10)
    p.add_argument("--cdlp_mr", type=int, default=10)
    p.add_argument("--degree_threshold", type=int, default=0,
                   help="LCC hub cap: skip neighbor lists of vertices "
                        "above this degree (flags.cc:39; 0 = disabled)")
    p.add_argument("--fnum", type=int, default=None,
                   help="fragment count (default: all local devices)")
    p.add_argument("--partitioner_type", default="map",
                   choices=["hash", "map", "segment"])
    p.add_argument("--idxer_type", default="hashmap",
                   choices=["hashmap", "sorted_array", "pthash", "local"])
    p.add_argument("--serialize", action="store_true")
    p.add_argument("--deserialize", action="store_true")
    p.add_argument("--serialization_prefix", default="")
    p.add_argument("--vc", action="store_true",
                   help="vertex-cut (2-D) storage; fnum must be k^2")
    p.add_argument("--delta_efile", default="")
    p.add_argument("--delta_vfile", default="")
    p.add_argument("--string_id", action="store_true",
                   help="treat vertex ids as strings (load_tests.cc:45)")
    p.add_argument("--rebalance", action="store_true")
    p.add_argument("--rebalance_vertex_factor", type=int, default=0)
    p.add_argument("--memory_stats", action="store_true")
    p.add_argument("--checkpoint_every", type=int, default=0,
                   help="snapshot the query carry every K supersteps "
                        "(ft/checkpoint.py; 0 = off; forces stepwise "
                        "execution, requires --checkpoint_dir)")
    p.add_argument("--checkpoint_dir", default="",
                   help="directory for superstep checkpoints")
    p.add_argument("--resume", action="store_true",
                   help="continue from the last complete checkpoint in "
                        "--checkpoint_dir (query args replay from the "
                        "checkpoint metadata; the config fingerprint "
                        "must match)")
    p.add_argument("--guard", default="",
                   choices=["", "off", "warn", "halt", "rollback"],
                   help="runtime invariant guard policy (guard/): warn "
                        "logs breaches, halt raises with a diagnostic "
                        "bundle, rollback self-heals from the last "
                        "checkpoint (needs --checkpoint_every); default "
                        "reads GRAPE_GUARD")
    p.add_argument("--profile", action="store_true",
                   help="stepwise rounds with per-round timing (PROFILING)")
    p.add_argument("--trace", default="",
                   help="arm obs/ tracing: write a Chrome trace_event "
                        "JSON (Perfetto-loadable) to this path plus a "
                        "JSONL twin next to it; equivalent to "
                        "GRAPE_TRACE=path (docs/OBSERVABILITY.md)")
    p.add_argument("--metrics", default="",
                   help="write the obs/ metrics snapshot to "
                        "<path>.json and <path>.prom at query end; "
                        "equivalent to GRAPE_METRICS=path")
    p.add_argument("--platform", default="",
                   help="jax platform override (e.g. cpu); default ambient")
    p.add_argument("--cpu_devices", type=int, default=0,
                   help="with --platform cpu: virtual device count")
    return p


def main(argv=None):
    ns = make_parser().parse_args(argv)
    platform = ns.platform
    cpu_devices = ns.cpu_devices
    if cpu_devices:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={cpu_devices}"
        ).strip()
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    args = QueryArgs(
        **{k: v for k, v in vars(ns).items()
           if k not in ("platform", "cpu_devices")}
    )
    run_app(args)


if __name__ == "__main__":
    main()
