"""Layer 2 of grape-lint: audits on actually-lowered/compiled runners.

The AST rules prove the source can't *express* a defect class; these
audits recount from the shipped artifact — the lowered StableHLO
module and the live XLA compile stream — and fail on drift, the same
two-sided discipline the pack ledger applies to op counts (model from
the plan, recount from the arrays; cf. SparseP's cost-model
validation).  Three audits:

* **A1 constant-bloat** — scan the fused runner's lowered module for
  literal constants above a byte threshold.  Catches every R1 escape
  (closure paths the AST pattern missed, library code, future
  refactors) end-to-end: a baked fragment array WILL show up as a
  multi-MB `stablehlo.constant`.
* **A2 donation** — the fused runner must donate its carry (the
  `tf.aliasing_output` markers in the lowered module): losing
  `donate_argnums` silently doubles peak HBM for the loop carry.
* **A3 surprise-compile** — run the canonical warm query matrix
  (sssp/bfs x fused/guarded/batched/incremental) twice and pin ZERO
  XLA compiles on the second pass, counted by `compile_events()`
  (the real `/jax/core/compile` stream, not cache counters — PR 6's
  per-batch re-jit was invisible to the counters, never to this).

`compile_events()` is also the public counter the zero-recompile
tests (tests/test_serve.py, tests/test_dyn.py) pin on.
"""

from __future__ import annotations

import re
from contextlib import contextmanager
from typing import Dict, List, Optional

from libgrape_lite_tpu.analysis.report import Finding

DEFAULT_CONSTANT_THRESHOLD = 64 * 1024  # bytes

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# a persistent-compilation-cache hit (JAX_COMPILATION_CACHE_DIR — the
# recommended TPU-pod configuration) satisfies a compile REQUEST
# without ever invoking backend_compile: a per-dispatch fresh jit
# wrapper still retraces and re-requests every batch, so a warmed
# zero-compile pin must count these too or the exact defect class A3
# exists to catch hides behind the disk cache
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_COMPILE_EVENTS = (_BACKEND_COMPILE_EVENT, _CACHE_HIT_EVENT)


class CompileEvents:
    """Events captured while a `compile_events()` block was active.
    `.compiles` counts XLA compile requests that reached the backend —
    fresh backend_compile calls AND persistent-cache hits (both mean a
    new executable was requested, i.e. something retraced); `.events`
    keeps the raw (event, seconds) stream for diagnostics."""

    def __init__(self):
        self.events: List[tuple] = []

    @property
    def compiles(self) -> int:
        return sum(
            1 for name, _ in self.events
            if name in _COMPILE_EVENTS
        )

    def compile_seconds(self) -> float:
        return sum(
            dur for name, dur in self.events
            if name == _BACKEND_COMPILE_EVENT
        )


@contextmanager
def compile_events():
    """Count real XLA compiles inside the block::

        with compile_events() as ev:
            worker.query(source=0)      # warmed: expect ev.compiles == 0

    Counts the backend_compile monitoring event AND persistent-cache
    hits, so it sees EVERY compile request in the process — including
    ones invisible to the runner/plan cache counters (a fresh jit
    wrapper per dispatch compiles identical HLO through a brand-new
    cache entry; the counters stay flat, this does not — the PR 6
    guarded-serve incident) and ones invisible to backend_compile
    alone (the same fresh wrapper under JAX_COMPILATION_CACHE_DIR
    hits the disk cache instead of the compiler)."""
    from jax._src import monitoring

    rec = CompileEvents()

    def _listen(event, duration, **kw):
        rec.events.append((event, duration))

    def _listen_plain(event, **kw):
        # record_event stream (no duration): persistent-cache hits
        rec.events.append((event, 0.0))

    monitoring.register_event_duration_secs_listener(_listen)
    monitoring.register_event_listener(_listen_plain)
    try:
        yield rec
    finally:
        for unregister, cb in (
            (monitoring._unregister_event_duration_listener_by_callback,
             _listen),
            (monitoring._unregister_event_listener_by_callback,
             _listen_plain),
        ):
            try:
                unregister(cb)
            except Exception:
                # last-resort: a leaked listener only over-counts
                # future blocks; never take the audited run down
                pass


# ---------------------------------------------------------------------------
# lowered-module scanning (A1 constant bloat, A2 donation)
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8": 1,
    "i64": 8, "i32": 4, "i16": 2, "i8": 1, "i4": 1, "i1": 1,
    "ui64": 8, "ui32": 4, "ui16": 2, "ui8": 1, "ui4": 1,
    "c64": 8, "c128": 16,
}

_CONST_RE = re.compile(
    r"(?:stablehlo|mhlo)\.constant[^\n]*?:\s*tensor<([^>]*)>"
)
_ALIAS_RE = re.compile(r"tf\.aliasing_output")


def tensor_type_bytes(type_str: str) -> int:
    """Byte size of a `tensor<...>` element spec like '4x128xf32'."""
    parts = type_str.strip().split("x")
    dtype = parts[-1]
    width = _DTYPE_BYTES.get(dtype)
    if width is None:
        return 0  # opaque/quantized types: not a bloat candidate
    n = 1
    for d in parts[:-1]:
        if not d.isdigit():
            return 0  # dynamic dim: size unknowable, skip
        n *= int(d)
    return n * width


def scan_constants(lowered_text: str,
                   threshold: int = DEFAULT_CONSTANT_THRESHOLD):
    """(offenders, total_bytes, n_constants): every literal constant
    in the lowered module at/above `threshold` bytes."""
    offenders = []
    total = 0
    count = 0
    for m in _CONST_RE.finditer(lowered_text):
        nbytes = tensor_type_bytes(m.group(1))
        count += 1
        total += nbytes
        if nbytes >= threshold:
            offenders.append(
                {"tensor": m.group(1), "bytes": nbytes}
            )
    return offenders, total, count


def donation_info(lowered_text: str) -> dict:
    return {"donated_args": len(_ALIAS_RE.findall(lowered_text))}


def lower_fused(worker, max_rounds: Optional[int] = None,
                **query_args):
    """The fused runner's jax Lowered object for this worker+args —
    the exact artifact `Worker.query` would dispatch (same cache, so
    auditing does not add a compile the next query wouldn't hit)."""
    app = worker.app
    frag = worker.fragment
    mr = app.max_rounds if max_rounds is None else max_rounds
    state = worker._place_state(app.init_state(frag, **query_args))
    runner = worker._runner_for(mr, state)
    eph = frozenset(getattr(app, "ephemeral_keys", ()) or ())
    carry = {k: v for k, v in state.items() if k not in eph}
    eph_part = {k: v for k, v in state.items() if k in eph}
    return runner.lower(frag.dev, carry, eph_part)


def audit_fused_runner(worker, *, threshold: int =
                       DEFAULT_CONSTANT_THRESHOLD,
                       expect_donation: bool = True,
                       **query_args):
    """A1 + A2 on one worker's fused runner.  Returns (findings,
    info): findings use rules A1/A2; info carries the raw numbers for
    the report."""
    app_name = type(worker.app).__name__
    text = lower_fused(worker, **query_args).as_text()
    offenders, total, count = scan_constants(text, threshold)
    don = donation_info(text)
    findings: List[Finding] = []
    for off in offenders:
        findings.append(Finding(
            "A1", f"<lowered:{app_name}>", 0, f"{app_name}.fused",
            f"lowered module holds a {off['bytes']}-byte literal "
            f"constant (tensor<{off['tensor']}>) above the "
            f"{threshold}-byte threshold — a closure-captured array "
            "was baked in (R1 class)",
        ))
    if expect_donation and don["donated_args"] == 0:
        findings.append(Finding(
            "A2", f"<lowered:{app_name}>", 0, f"{app_name}.fused",
            "fused runner donates no input buffer — the carry is "
            "double-buffered in HBM instead of aliased into the loop",
        ))
    info = {
        "app": app_name,
        "constants": count,
        "constant_bytes": total,
        "offenders": offenders,
        "threshold": threshold,
        **don,
    }
    return findings, info


# ---------------------------------------------------------------------------
# A3 — the canonical warm query matrix under the compile counter
# ---------------------------------------------------------------------------

MATRIX_APPS = ("sssp", "bfs")
MATRIX_MODES = ("fused", "guarded", "batched", "incremental")


def _additive_delta():
    """A minimal additive delta description: enough for
    incremental_plan to pick the seeded path (the audit does not
    mutate the graph — it pins the seeded machinery's compile
    behavior, which is what serving exercises after every overlay
    ingest)."""
    from libgrape_lite_tpu.dyn.delta import DeltaBuffer

    buf = DeltaBuffer(capacity=4)
    buf.stage([("a", 0, 1, 1.0)])
    return buf.summary()


def _run_cell(worker, mode: str, sources):
    if mode == "fused":
        worker.query(source=sources[0])
    elif mode == "guarded":
        worker.query(source=sources[0], guard="halt")
    elif mode == "batched":
        worker.query_batch([{"source": s} for s in sources])
    elif mode == "incremental":
        prev = worker.query(source=sources[0])
        worker.query_incremental(
            prev, delta=_additive_delta(), source=sources[0]
        )
    else:
        raise ValueError(f"unknown matrix mode {mode!r}")


def warm_matrix_audit(frag, apps=MATRIX_APPS, modes=MATRIX_MODES,
                      sources=(0, 1)):
    """A3: run every (app, mode) cell once to warm, then re-run the
    whole matrix under `compile_events()` and pin zero compiles.
    Returns (findings, info); info["cells"] carries per-cell compile
    counts for the report."""
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.worker.worker import Worker

    workers = {a: Worker(APP_REGISTRY[a](), frag) for a in apps}
    for a in apps:
        for mode in modes:
            _run_cell(workers[a], mode, sources)

    findings: List[Finding] = []
    cells = []
    total = 0
    for a in apps:
        for mode in modes:
            with compile_events() as ev:
                _run_cell(workers[a], mode, sources)
            cells.append(
                {"app": a, "mode": mode, "compiles": ev.compiles}
            )
            total += ev.compiles
            if ev.compiles:
                findings.append(Finding(
                    "A3", f"<warm:{a}>", 0, f"{a}.{mode}",
                    f"warmed {mode} query compiled {ev.compiles} "
                    "module(s) — a runner/probe cache is leaking "
                    "(R2 class)",
                ))
    info = {
        "cells": cells,
        "unexpected_compiles": total,
        "apps": list(apps),
        "modes": list(modes),
    }
    return findings, info


# ---------------------------------------------------------------------------
# the full artifact audit (CLI --artifact, tpu_first_light.sh)
# ---------------------------------------------------------------------------


def _default_fragment(n: int = 400, e: int = 3200, fnum: int = 1):
    """A small weighted random graph — big enough to make a baked CSR
    obvious against the 64 KiB constant threshold, small enough to
    audit in seconds on the CPU fallback."""
    import numpy as np

    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.vertex_map.partitioner import MapPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    rng = np.random.default_rng(8)
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    w = rng.uniform(0.5, 2.0, e).astype(np.float32)
    oids = np.arange(n, dtype=np.int64)
    vm = VertexMap.build(oids, MapPartitioner(fnum, oids))
    return ShardedEdgecutFragment.build(
        CommSpec(fnum=fnum), vm, src, dst, w, directed=False,
    )


def run_artifact_audit(frag=None, *, threshold: int =
                       DEFAULT_CONSTANT_THRESHOLD,
                       apps=MATRIX_APPS, modes=MATRIX_MODES):
    """Everything Layer 2 knows how to prove, as (findings, report):
    constant-bloat + donation on each app's fused runner, then the
    zero-compile warm matrix.  `frag=None` builds the small canonical
    fragment (the CLI/tpu_first_light path); pass a real loaded
    fragment to audit production geometry."""
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.worker.worker import Worker

    if frag is None:
        frag = _default_fragment()
    findings: List[Finding] = []
    lowered: Dict[str, dict] = {}
    for a in apps:
        w = Worker(APP_REGISTRY[a](), frag)
        fs, info = audit_fused_runner(w, threshold=threshold, source=0)
        findings.extend(fs)
        lowered[a] = info
    mfs, matrix = warm_matrix_audit(frag, apps=apps, modes=modes)
    findings.extend(mfs)
    report = {
        "findings": [f.to_dict(False) for f in findings],
        "constant_bloat": {
            a: {
                "constants": i["constants"],
                "constant_bytes": i["constant_bytes"],
                "offenders": len(i["offenders"]),
            }
            for a, i in lowered.items()
        },
        "donation": {
            a: {"donated_args": i["donated_args"]}
            for a, i in lowered.items()
        },
        "compile_audit": matrix,
    }
    return findings, report
