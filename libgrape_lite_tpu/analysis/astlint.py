"""Layer 1 of grape-lint: AST checks R1-R9 over the library source.

Each checker's docstring names the historical, actually-shipped bug it
fossilizes (see analysis/rules.py for the catalogue and CHANGES.md for
the incident reports).  The analysis is deliberately intraprocedural +
pattern-anchored: it models the specific idioms this codebase uses
(runner builders behind `_cached_runner`, traced `stepper` closures,
`GuardConfig.resolve` guard arming) rather than attempting whole-
program dataflow — a lint that needs no annotations and produces
near-zero false positives on the shipped tree, with the intentional
exceptions named in analysis/baseline.json.

Entry points: `lint_source(src, relpath)` for one module,
`lint_paths(paths, root=...)` for trees (skips __pycache__/scratch).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set

from libgrape_lite_tpu.analysis.report import Finding

# function wrappers whose function-valued argument becomes traced code
_TRACE_WRAPPERS = {"jit", "shard_map", "pallas_call", "vmap", "pmap"}
# np/jnp constructors whose result is an array worth worrying about
# (dtype scalars like jnp.int32(x) are deliberately absent: a closure-
# captured scalar constant is harmless)
_ARRAY_FNS = {
    "array", "asarray", "ascontiguousarray", "zeros", "ones", "full",
    "empty", "arange", "linspace", "concatenate", "stack", "vstack",
    "hstack", "tile", "repeat", "zeros_like", "ones_like", "full_like",
    "eye", "tri", "tril", "triu", "loadtxt", "frombuffer", "fromfile",
}
_ARRAY_MODULES = {"np", "jnp", "numpy"}

# per-dispatch code paths: a jit (or builder) constructed here runs
# once per query/batch/ingest, not once per session
_DISPATCH_RE = re.compile(
    r"^_?(query|pump|drain|dispatch|ingest|serve|run|host_compute"
    r"|observe|check|resolve|submit|probe)"
)
# runner/probe builders: constructing a jit here is the point — the
# CALLER is responsible for routing through the cache (checked by the
# builder-call-site half of R2)
_BUILDER_RE = re.compile(r"^_?(make|compile|build)")
# the call-site half matches only the library's private runner-builder
# naming (a public Fragment.build() is a graph build, not a compile)
_BUILDER_CALL_RE = re.compile(r"^_(make|compile)_")

_FRAGISH_PARAM = re.compile(r"^(frag|fragment|dev)$|^frag_|_frag$")


class _Scope:
    def __init__(self, node, name: str, parent: Optional["_Scope"],
                 kind: str):
        self.node = node
        self.name = name
        self.parent = parent
        self.kind = kind  # module | class | function
        self.children: List[_Scope] = []
        self.params: Set[str] = set()
        self.assigned: Dict[str, str] = {}   # name -> arrayish|other
        self.assign_values: Dict[str, ast.AST] = {}
        self.cache_stored: Set[str] = set()  # names stored via x[...] = v
        self.calls: List[ast.Call] = []
        self.traced = False
        if parent is not None:
            parent.children.append(self)

    @property
    def qualname(self) -> str:
        parts = []
        s = self
        while s is not None and s.kind != "module":
            parts.append(s.name)
            s = s.parent
        return ".".join(reversed(parts)) or "<module>"

    def fn_chain(self) -> List["_Scope"]:
        """This scope and its enclosing FUNCTION scopes, innermost
        first (classes/module excluded)."""
        out, s = [], self
        while s is not None:
            if s.kind == "function":
                out.append(s)
            s = s.parent
        return out

    def binding_scope(self, name: str) -> Optional["_Scope"]:
        s = self.parent
        while s is not None:
            if s.kind == "function" and (
                name in s.params or name in s.assigned
            ):
                return s
            if s.kind == "module" and name in s.assigned:
                return s
            s = s.parent
        return None


def _callee_base(func) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _root_name(node) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _shallow(node):
    """Child nodes of `node` without descending into nested function /
    lambda / class scopes (each nested scope is analyzed on its own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


def _classify_value(scope: _Scope, value) -> str:
    """'arrayish' when the RHS plausibly builds a device/host array
    the tracer would bake as a constant."""
    if isinstance(value, ast.Call):
        f = value.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _ARRAY_FNS
            and _root_name(f) in _ARRAY_MODULES
        ):
            return "arrayish"
    if isinstance(value, ast.Attribute) and value.attr == "dev":
        return "arrayish"
    if (
        isinstance(value, ast.Name)
        and scope.assigned.get(value.id) == "arrayish"
    ):
        return "arrayish"
    return "other"


def _collect_params(node) -> Set[str]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _build_scopes(tree: ast.Module) -> _Scope:
    module = _Scope(tree, "<module>", None, "module")

    def build(node, scope: _Scope):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                s = _Scope(child, child.name, scope, "function")
                s.params = _collect_params(child)
                _scan_body(s)
                build(child, s)
            elif isinstance(child, ast.Lambda):
                s = _Scope(child, "<lambda>", scope, "function")
                s.params = _collect_params(child)
                _scan_body(s)
                build(child, s)
            elif isinstance(child, ast.ClassDef):
                s = _Scope(child, child.name, scope, "class")
                build(child, s)
            else:
                build(child, scope)

    def _scan_body(scope: _Scope):
        node = scope.node
        for n in _shallow(node):
            if isinstance(n, ast.Assign):
                kind = _classify_value(scope, n.value)
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        scope.assigned[t.id] = kind
                        scope.assign_values[t.id] = n.value
                    elif isinstance(t, ast.Subscript):
                        for sub in ast.walk(n.value):
                            if isinstance(sub, ast.Name):
                                scope.cache_stored.add(sub.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for el in t.elts:
                            if isinstance(el, ast.Name):
                                scope.assigned.setdefault(el.id, "other")
            elif isinstance(n, ast.AnnAssign):
                if isinstance(n.target, ast.Name):
                    scope.assigned[n.target.id] = (
                        _classify_value(scope, n.value)
                        if n.value is not None else "other"
                    )
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                for el in ast.walk(n.target):
                    if isinstance(el, ast.Name):
                        scope.assigned.setdefault(el.id, "other")
            elif isinstance(n, (ast.Import, ast.ImportFrom)):
                for alias in n.names:
                    scope.assigned.setdefault(
                        (alias.asname or alias.name).split(".")[0],
                        "other",
                    )
            elif isinstance(n, ast.withitem) and n.optional_vars:
                for el in ast.walk(n.optional_vars):
                    if isinstance(el, ast.Name):
                        scope.assigned.setdefault(el.id, "other")
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.assigned.setdefault(n.name, "other")
            elif isinstance(n, ast.Call):
                scope.calls.append(n)

    # module-level assigns/imports/calls
    _scan_body(module)
    build(tree, module)
    return module


def _all_scopes(scope: _Scope):
    yield scope
    for c in scope.children:
        yield from _all_scopes(c)


def _mark_traced(module: _Scope) -> None:
    # decorator-traced functions
    for s in _all_scopes(module):
        node = s.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                for n in ast.walk(dec):
                    name = (
                        n.attr if isinstance(n, ast.Attribute)
                        else n.id if isinstance(n, ast.Name) else None
                    )
                    if name in ("jit", "pallas_call"):
                        s.traced = True

    # functions passed (possibly through partial) to a trace wrapper
    def resolve(scope: _Scope, name: str) -> Optional[_Scope]:
        s = scope
        while s is not None:
            for c in s.children:
                if c.kind == "function" and c.name == name:
                    return c
            s = s.parent
        return None

    def mark_arg(scope: _Scope, arg) -> None:
        if isinstance(arg, ast.Name):
            target = resolve(scope, arg.id)
            if target is not None:
                target.traced = True
        elif isinstance(arg, ast.Lambda):
            for c in scope.children:
                if c.node is arg:
                    c.traced = True
        elif (
            isinstance(arg, ast.Call)
            and _callee_base(arg.func) == "partial"
            and arg.args
        ):
            mark_arg(scope, arg.args[0])

    for s in _all_scopes(module):
        for call in s.calls:
            if _callee_base(call.func) in _TRACE_WRAPPERS:
                for arg in call.args:
                    mark_arg(s, arg)

    # everything nested inside a traced function is traced
    def propagate(s: _Scope, inherited: bool):
        s.traced = s.traced or inherited
        for c in s.children:
            propagate(c, s.traced if s.kind == "function" else inherited)

    propagate(module, False)


# ---------------------------------------------------------------------------
# R1 — baked constants
# ---------------------------------------------------------------------------


def _check_r1(module: _Scope, path: str, findings: List[Finding]) -> None:
    """R1 baked-constant.  Historical bug: PR 3's guard probe closed
    over `frag.dev`, baking MB-scale fragment CSR arrays into the
    probe executable as XLA literal constants; the fix (dev as a jit
    ARGUMENT) is the pattern this rule enforces everywhere a traced
    body touches an np/jnp array or a frag/.dev attribute."""
    for s in _all_scopes(module):
        if not (s.kind == "function" and s.traced):
            continue
        seen: Set[str] = set()
        for n in _shallow(s.node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                name = n.id
                if (
                    name in seen or name in s.params
                    or name in s.assigned
                ):
                    continue
                b = s.binding_scope(name)
                if b is None or b.traced:
                    continue
                arrayish = b.assigned.get(name) == "arrayish"
                fragish = (
                    b.kind == "function" and name in b.params
                    and _FRAGISH_PARAM.match(name)
                )
                if arrayish or fragish:
                    seen.add(name)
                    findings.append(Finding(
                        "R1", path, n.lineno, s.qualname,
                        f"traced body captures {name!r} from the "
                        f"enclosing (untraced) scope "
                        f"{b.qualname!r}; pass it as a parameter or "
                        "XLA bakes it in as a literal constant",
                    ))
            elif (
                isinstance(n, ast.Attribute)
                and isinstance(n.ctx, ast.Load)
                and n.attr in ("dev", "fragment")
            ):
                root = _root_name(n)
                if root is None:
                    continue
                if root == "self":
                    key = f"self.{n.attr}"
                    if key not in seen:
                        seen.add(key)
                        findings.append(Finding(
                            "R1", path, n.lineno, s.qualname,
                            f"traced body reads {key} — fragment "
                            "arrays must ride as jit parameters, not "
                            "closure state",
                        ))
                elif root not in s.params and root not in s.assigned:
                    b = s.binding_scope(root)
                    if b is not None and not b.traced:
                        key = f"{root}.{n.attr}"
                        if key not in seen:
                            seen.add(key)
                            findings.append(Finding(
                                "R1", path, n.lineno, s.qualname,
                                f"traced body reads {key} captured "
                                f"from {b.qualname!r}; pass the "
                                "device fragment as a parameter",
                            ))


# ---------------------------------------------------------------------------
# R2 — per-dispatch jit / builder construction
# ---------------------------------------------------------------------------


def _is_cache_stored(call: ast.Call, scope: _Scope,
                     parents: Dict) -> bool:
    """True when the jit result is stored into a subscripted cache
    (`per_frag[cap] = fn` / `cache[key] = (probe, ...)`) within the
    same function — the models' per-fragment memo pattern."""
    n = call
    while n is not None and n is not scope.node:
        p = parents.get(n)
        if isinstance(p, ast.Assign) and n is p.value:
            for t in p.targets:
                if isinstance(t, ast.Subscript):
                    return True
                if isinstance(t, ast.Name):
                    return t.id in scope.cache_stored
            return False
        n = p
    return False


def _check_r2(module: _Scope, path: str, parents: Dict,
              findings: List[Finding]) -> None:
    """R2 uncached-jit.  Historical bug: PR 6's guarded serve path
    minted a fresh `jax.jit` wrapper around the batched PEval on every
    dispatch — steady guarded streams re-traced and re-compiled every
    batch, invisible to the zero-recompile counters (jit caches by
    wrapper identity, and the wrapper was new each time).  Two halves:
    a `jax.jit` call inside a per-dispatch function (unless its result
    lands in a subscripted cache), and a `_make_*`/`_compile_*`
    builder invoked from a per-dispatch function instead of through
    `_cached_runner`."""
    for s in _all_scopes(module):
        if s.kind != "function" or s.traced:
            continue
        chain = s.fn_chain()
        names = [f.name for f in chain]
        dispatchy = any(_DISPATCH_RE.match(n) for n in names)
        buildery = any(_BUILDER_RE.match(n) for n in names)
        for call in s.calls:
            base = _callee_base(call.func)
            if base == "jit":
                if buildery or not dispatchy:
                    continue
                if _is_cache_stored(call, s, parents):
                    continue
                findings.append(Finding(
                    "R2", path, call.lineno, s.qualname,
                    "jax.jit constructed on a per-dispatch path — a "
                    "fresh wrapper retraces and recompiles every "
                    "query; build it once behind the runner cache",
                ))
            elif (
                base is not None
                and _BUILDER_CALL_RE.match(base)
                and isinstance(call.func, ast.Attribute)
                and dispatchy
                and not buildery
                and not isinstance(s.node, ast.Lambda)
            ):
                findings.append(Finding(
                    "R2", path, call.lineno, s.qualname,
                    f"runner builder {base!r} invoked per dispatch; "
                    "route it through _cached_runner so repeated "
                    "queries reuse the compile",
                ))


# ---------------------------------------------------------------------------
# R3 — cache-key completeness
# ---------------------------------------------------------------------------


def _check_r3(module: _Scope, path: str, findings: List[Finding]) -> None:
    """R3 cache-key-field.  Historical bug: the fused-runner cache key
    originally omitted `max_rounds`, so a second query with a
    different round limit silently reused the first compile's baked
    while_loop bound (regression-pinned in PR 6,
    tests/test_worker.py::test_runner_cache_keys_max_rounds).  Every
    parameter of a function that calls `_cached_runner(key, ...)`
    must appear somewhere in the key expression."""
    for s in _all_scopes(module):
        if s.kind != "function":
            continue
        for call in s.calls:
            if _callee_base(call.func) != "_cached_runner":
                continue
            if not call.args:
                continue
            key_expr = call.args[0]
            if isinstance(key_expr, ast.Name):
                key_expr = s.assign_values.get(key_expr.id, key_expr)
            key_names = {
                n.id for n in ast.walk(key_expr)
                if isinstance(n, ast.Name)
            }
            for p in sorted(s.params - {"self", "cls"}):
                if p not in key_names:
                    findings.append(Finding(
                        "R3", path, call.lineno, s.qualname,
                        f"builder argument {p!r} is read by "
                        f"{s.name!r} but missing from its "
                        "_cached_runner key — two queries differing "
                        "only in it would share one compile",
                    ))


# ---------------------------------------------------------------------------
# R4 — query-path parity (stale dyn view + guard resolution)
# ---------------------------------------------------------------------------


def _method_facts(cls_node: ast.ClassDef):
    facts = {}
    for item in cls_node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        self_calls: Set[str] = set()
        marks: Set[str] = set()
        for n in ast.walk(item):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Attribute):
                if (
                    isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                ):
                    self_calls.add(f.attr)
                    if f.attr in ("_check_dyn_view", "_ensure_dyn_view"):
                        marks.add("dyn_view")
                if (
                    f.attr == "resolve"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "GuardConfig"
                ):
                    marks.add("guard_resolve")
        facts[item.name] = (item.lineno, self_calls, marks)
    return facts


def _reaches(facts, start: str, mark: str) -> bool:
    seen: Set[str] = set()
    stack = [start]
    while stack:
        m = stack.pop()
        if m in seen or m not in facts:
            continue
        seen.add(m)
        _, calls, marks = facts[m]
        if mark in marks:
            return True
        stack.extend(calls)
    return False


def _check_r4(module: _Scope, path: str, findings: List[Finding]) -> None:
    """R4 dyn-view-parity.  Historical bug (PR 7, found post-hoc in
    review): GUARDED `query_batch` ran the stale-view check only
    AFTER the guard routing, and `query_stepwise` (the public
    profiling surface) skipped `_check_dyn_view` entirely — both
    silently computed on the pre-delta base graph while delta edges
    sat staged in the overlay.  Every public `query*` entrypoint of a
    class that defines `_check_dyn_view` must (transitively, through
    self-calls) reach both the stale-view check and
    `GuardConfig.resolve`; a serving class that defines
    `_ensure_dyn_view` must reach it from its `_dispatch` callback."""
    for s in _all_scopes(module):
        if s.kind != "class" or not isinstance(s.node, ast.ClassDef):
            continue
        facts = _method_facts(s.node)
        if "_check_dyn_view" in facts:
            for name, (lineno, _, _) in sorted(facts.items()):
                if not name.startswith("query"):
                    continue
                if not _reaches(facts, name, "dyn_view"):
                    findings.append(Finding(
                        "R4", path, lineno, f"{s.name}.{name}",
                        "public query entrypoint never reaches "
                        "_check_dyn_view — it would silently compute "
                        "on a stale dyn view",
                    ))
                if not _reaches(facts, name, "guard_resolve"):
                    findings.append(Finding(
                        "R4", path, lineno, f"{s.name}.{name}",
                        "public query entrypoint never resolves the "
                        "guard config (GuardConfig.resolve) — "
                        "env-armed guards would be silently ignored",
                    ))
        if "_ensure_dyn_view" in facts and "_dispatch" in facts:
            lineno = facts["_dispatch"][0]
            if not _reaches(facts, "_dispatch", "dyn_view"):
                findings.append(Finding(
                    "R4", path, lineno, f"{s.name}._dispatch",
                    "dispatch callback never reaches "
                    "_ensure_dyn_view — uncontracted apps would read "
                    "a stale dyn view",
                ))


# ---------------------------------------------------------------------------
# R5 — eager logging + bool-in-numeric-schema
# ---------------------------------------------------------------------------


def _eager_msg(node) -> bool:
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Mod, ast.Add)
    ):
        # ANY + or % in the message argument builds the string per
        # call — including "round " + str(r), which is not literal
        # concatenation and pays str() + allocation at disabled levels
        return True
    if isinstance(node, ast.Call):
        return (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"
        )
    return False


def _check_r5(module: _Scope, path: str,
              findings: List[Finding]) -> None:
    """R5 eager-log / bool-in-numeric-schema.  Historical bugs (both
    PR 5): per-round f-strings in worker vlogs were formatted and
    then DROPPED at disabled levels — a measurable per-superstep cost
    the lazy `%`-args form avoids (utils/logging.py); and the bench
    schema checker accepted `True` in numeric fields because bool is
    an int subclass, silently typing a whole BENCH column wrong."""
    for s in _all_scopes(module):
        if s.kind == "class":
            continue
        for call in s.calls:
            base = _callee_base(call.func)
            if base == "vlog" and len(call.args) >= 2:
                if _eager_msg(call.args[1]):
                    findings.append(Finding(
                        "R5", path, call.lineno, s.qualname,
                        "vlog message is formatted eagerly — pass "
                        "printf-style args so disabled levels pay "
                        "one int compare, not the formatting",
                    ))

    # bool-in-numeric-schema: validator functions using
    # isinstance(x, int/(int,float)) without any bool rejection
    module_tuples = {
        name: val for name, val in module.assign_values.items()
        if isinstance(val, ast.Tuple)
    }

    def numeric_classinfo(node) -> bool:
        if isinstance(node, ast.Name):
            if node.id in ("int", "float"):
                return True
            t = module_tuples.get(node.id)
            return t is not None and numeric_classinfo(t)
        if isinstance(node, ast.Tuple):
            return any(numeric_classinfo(e) for e in node.elts)
        return False

    for s in _all_scopes(module):
        if s.kind != "function":
            continue
        if not re.search(r"valid|check|schema", s.name):
            continue
        has_bool_guard = any(
            isinstance(n, ast.Name) and n.id == "bool"
            for n in ast.walk(s.node)
        )
        if has_bool_guard:
            continue
        for n in ast.walk(s.node):
            if (
                isinstance(n, ast.Call)
                and _callee_base(n.func) == "isinstance"
                and len(n.args) == 2
                and numeric_classinfo(n.args[1])
            ):
                findings.append(Finding(
                    "R5", path, n.lineno, s.qualname,
                    "numeric schema check accepts bool — bool is an "
                    "int subclass; reject isinstance(x, bool) "
                    "explicitly",
                ))


# ---------------------------------------------------------------------------
# R6 — pipelined-window carry reads vs the worker pipeline contract
# ---------------------------------------------------------------------------


def _window_contract():
    """The shipped pipeline contract (exact names + '*'-suffixed
    prefixes + audited whole-carry callees).  Imported from the
    runtime module rather than re-parsed: the contract IS the worker's
    declaration, and the lint must judge fixtures and the tree against
    the same set."""
    try:
        from libgrape_lite_tpu.parallel.pipeline import (
            PIPELINE_WINDOW_CALLEES,
            PIPELINE_WINDOW_READS,
        )
    except Exception:  # pragma: no cover — partial checkouts
        return frozenset(), (), frozenset()
    exact = frozenset(c for c in PIPELINE_WINDOW_READS
                      if not c.endswith("*"))
    prefixes = tuple(c[:-1] for c in PIPELINE_WINDOW_READS
                     if c.endswith("*"))
    return exact, prefixes, frozenset(PIPELINE_WINDOW_CALLEES)


def _check_r6(module: _Scope, path: str, findings: List[Finding]) -> None:
    """R6 pipeline-window-read.  The double-buffered superstep pipeline
    (parallel/pipeline.py, r9) kicks off the next round's halo exchange
    mid-round and overlaps interior compute with the in-flight
    collective.  Every read of the query carry inside that window is
    only safe because the kickoff writes a fresh buffer and never
    aliases live state; each must be audited against the worker
    pipeline contract.  Audited forms:

    * a constant-keyed subscript of a carry-dict parameter after the
      kickoff line, or a load of a variable bound from one BEFORE the
      kickoff — the key must be named in PIPELINE_WINDOW_READS;
    * the WHOLE carry dict passed as a call argument after the kickoff
      (R6 cannot see the callee's body) — the callee must be named in
      PIPELINE_WINDOW_CALLEES;
    * reads inside a NESTED function that captures the carry dict —
      audited position-independently (its call time is unknowable
      statically), same two rules.

    An unnamed read is the aliasing bug class the double buffering
    exists to prevent, fossilized before it can ship (zero-entry
    baseline).  "Carry-dict parameter" = a parameter subscripted with
    a string constant anywhere in the function (frag/ctx params never
    are, so they don't trip the escape rule)."""
    exact, prefixes, callees = _window_contract()

    def named(key: str) -> bool:
        return key in exact or (
            bool(prefixes) and key.startswith(prefixes)
        )

    def callee_of(call: ast.Call):
        f = call.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return None

    for s in _all_scopes(module):
        if s.kind != "function":
            continue
        kick_line = None
        for n in _shallow(s.node):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "kickoff"
            ):
                kick_line = (
                    n.lineno if kick_line is None
                    else min(kick_line, n.lineno)
                )
        if kick_line is None:
            continue
        # parameters actually USED as carry dicts: subscripted with a
        # string constant somewhere in the function (incl. nested)
        dict_params: Set[str] = set()
        for n in ast.walk(s.node):
            if (
                isinstance(n, ast.Subscript)
                and isinstance(n.value, ast.Name)
                and n.value.id in s.params
                and isinstance(n.slice, ast.Constant)
                and isinstance(n.slice.value, str)
            ):
                dict_params.add(n.value.id)
        # carry aliases bound before the kickoff: x = state["key"]
        aliases: Dict[str, str] = {}
        for n in _shallow(s.node):
            if (
                isinstance(n, ast.Assign)
                and getattr(n, "lineno", 0) <= kick_line
                and isinstance(n.value, ast.Subscript)
                and isinstance(n.value.value, ast.Name)
                and n.value.value.id in s.params
                and isinstance(n.value.slice, ast.Constant)
                and isinstance(n.value.slice.value, str)
            ):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = n.value.slice.value
        seen: Set[str] = set()

        def flag(key: str, line: int, what: str) -> None:
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(
                "R6", path, line, s.qualname,
                f"{what} inside the pipelined window (after the "
                "exchange kickoff) is not named in the worker "
                "pipeline contract (parallel/pipeline."
                "PIPELINE_WINDOW_READS / PIPELINE_WINDOW_CALLEES) — "
                "audit it as double-buffer-safe and declare it, or "
                "move the read before the kickoff",
            ))

        def check_nodes(nodes, in_window, params) -> None:
            for n in nodes:
                post = in_window(n)
                if (
                    post
                    and isinstance(n, ast.Subscript)
                    and isinstance(n.ctx, ast.Load)
                    and isinstance(n.value, ast.Name)
                    and n.value.id in params
                    and isinstance(n.slice, ast.Constant)
                    and isinstance(n.slice.value, str)
                    and not named(n.slice.value)
                ):
                    flag(n.slice.value, n.lineno,
                         f"carry read {n.slice.value!r}")
                elif (
                    post
                    and isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in aliases
                    and not named(aliases[n.id])
                ):
                    flag(aliases[n.id], n.lineno,
                         f"carry read {aliases[n.id]!r} (via alias "
                         f"{n.id!r})")
                elif post and isinstance(n, ast.Call):
                    cn = callee_of(n)
                    if cn in callees:
                        continue
                    args = list(n.args) + [k.value for k in n.keywords]
                    for a in args:
                        if (
                            isinstance(a, ast.Name)
                            and a.id in params
                            and a.id in dict_params
                        ):
                            flag(f"<{a.id} -> {cn}()>", n.lineno,
                                 f"whole carry dict {a.id!r} passed "
                                 f"to unaudited callee {cn!r}")

        # (1) the kickoff function's own body, after the kickoff line
        check_nodes(
            _shallow(s.node),
            lambda n: getattr(n, "lineno", 0) > kick_line,
            s.params,
        )
        # (2) nested functions capturing a carry dict: call time is
        # unknowable, so every read is window-audited (a nested def
        # re-binding the name as its own param shadows it — own scope)
        for child in s.children:
            if child.kind != "function":
                continue
            free = dict_params - child.params
            if not free:
                continue
            check_nodes(
                (n for n in ast.walk(child.node)
                 if not isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))),
                lambda n: True,
                free,
            )


# ---------------------------------------------------------------------------
# R7 — host syncs on the async pump's dispatch stage
# ---------------------------------------------------------------------------

_R7_PATH_RE = re.compile(r"(^|/)serve/pipeline\.py$")
_R7_DISPATCH_RE = re.compile(r"^_?(dispatch|fill)")


def _pump_harvest_contract():
    """The audited harvest contract: the pump module's own declaration
    of which methods may force a host sync.  Imported from the runtime
    module (like R6's window contract) so the lint judges fixtures and
    the shipped tree against one set."""
    try:
        from libgrape_lite_tpu.serve.pipeline import PUMP_HARVEST_SYNCS
    except Exception:  # pragma: no cover — partial checkouts
        return frozenset()
    return frozenset(PUMP_HARVEST_SYNCS)


def _r7_sync_forcer(call: ast.Call) -> Optional[str]:
    """A human-readable tag when `call` forces a host sync, else None:
    block_until_ready / device_get, np/jnp.asarray (materialises the
    device buffer), .item()/.tolist(), and the builtins int()/float()
    on a non-literal argument (converting a device scalar blocks on
    it)."""
    base = _callee_base(call.func)
    if base in ("block_until_ready", "device_get"):
        return f"{base}()"
    if (
        base == "asarray"
        and isinstance(call.func, ast.Attribute)
        and _root_name(call.func) in _ARRAY_MODULES
    ):
        return "asarray() (materialises the device buffer)"
    if (
        isinstance(call.func, ast.Name)
        and base in ("int", "float")
        and call.args
        and not isinstance(call.args[0], ast.Constant)
    ):
        return f"{base}() on a non-literal value"
    if base in ("item", "tolist") and isinstance(call.func, ast.Attribute):
        return f".{base}()"
    return None


def _check_r7(module: _Scope, path: str, findings: List[Finding]) -> None:
    """R7 sync-in-pump.  The async serve pump's dispatch stage
    (serve/pipeline.py `_fill*`/`_dispatch*` self-call chains) exists
    to keep a window of batches in flight; a single host-sync forcer
    on that path silently re-serialises the whole window — the exact
    defect class the pump replaced (the synchronous loop blocked
    pulling every lane's result before the next batch could
    dispatch).  The harvest stage is WHERE syncs belong, and the pump
    module names its harvest-side methods in `PUMP_HARVEST_SYNCS`;
    this rule walks every self-call chain rooted at a dispatch-stage
    method, stops at contract names, and flags any sync forcer it
    reaches.  Nested functions are skipped: a deferred thunk built at
    dispatch time runs at harvest time.  Path-scoped to
    serve/pipeline.py — the synchronous session/queue loop is ALLOWED
    to sync; only the pump's dispatch stage carries the contract."""
    if not _R7_PATH_RE.search(path):
        return
    contract = _pump_harvest_contract()

    def scan(fs: _Scope, owner: str) -> None:
        for n in _shallow(fs.node):
            if isinstance(n, ast.Call):
                what = _r7_sync_forcer(n)
                if what is not None:
                    findings.append(Finding(
                        "R7", path, n.lineno, owner,
                        f"{what} reached from the pump's dispatch "
                        "stage outside the audited harvest contract "
                        "(serve/pipeline.PUMP_HARVEST_SYNCS) — one "
                        "stray sync re-serialises the dispatch "
                        "window; move it to the harvest stage or "
                        "audit and name the method in the contract",
                    ))

    for s in _all_scopes(module):
        if s.kind == "class" and isinstance(s.node, ast.ClassDef):
            facts = _method_facts(s.node)
            roots = [
                m for m in facts
                if _R7_DISPATCH_RE.match(m) and m not in contract
            ]
            if not roots:
                continue
            seen: Set[str] = set()
            stack = list(roots)
            while stack:
                m = stack.pop()
                if m in seen or m in contract or m not in facts:
                    continue
                seen.add(m)
                _, calls, _ = facts[m]
                stack.extend(c for c in calls if c not in contract)
            scopes = {
                c.name: c for c in s.children if c.kind == "function"
            }
            for name in sorted(seen):
                fs = scopes.get(name)
                if fs is not None:
                    scan(fs, f"{s.name}.{name}")
        elif (
            s.kind == "function"
            and s.parent is not None
            and s.parent.kind == "module"
            and _R7_DISPATCH_RE.match(s.name)
            and s.name not in contract
        ):
            scan(s, s.qualname)


# ---------------------------------------------------------------------------
# R8 — module-level *_STATS surfaces outside the stats federation
# ---------------------------------------------------------------------------

_R8_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*_STATS$")
_R8_FED_MODULE = "libgrape_lite_tpu.obs.federation"
_R8_OBS_MODULE = "libgrape_lite_tpu.obs"


def _r8_federation_names(tree: ast.Module):
    """Names under which this module can reach the federation:
    (module aliases of obs.federation / obs, direct `register` names,
    direct `FederatedStats` constructor names).  Function-level lazy
    imports count — registering inside an init helper is still
    registering."""
    mod_aliases: Set[str] = set()
    reg_names: Set[str] = set()
    ctor_names: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom):
            if n.module == _R8_FED_MODULE:
                for a in n.names:
                    bound = a.asname or a.name
                    if a.name == "register":
                        reg_names.add(bound)
                    elif a.name == "FederatedStats":
                        ctor_names.add(bound)
            elif n.module == _R8_OBS_MODULE:
                for a in n.names:
                    bound = a.asname or a.name
                    if a.name == "federation":
                        mod_aliases.add(bound)
                    elif a.name == "FederatedStats":
                        ctor_names.add(bound)
        elif isinstance(n, ast.Import):
            for a in n.names:
                if a.name == _R8_FED_MODULE:
                    mod_aliases.add(
                        a.asname or _R8_FED_MODULE.split(".")[0]
                    )
    return mod_aliases, reg_names, ctor_names


def _check_r8(module: _Scope, path: str,
              findings: List[Finding]) -> None:
    """R8 unfederated-stats.  A module-level ``*_STATS`` assignment
    declares an operational ledger; the stats federation
    (obs/federation.py) is THE registry every such surface must join
    so one ``snapshot()`` — and therefore the live exporter and every
    postmortem bundle — sees all of them.  A surface passes when its
    value is constructed as ``FederatedStats(...)`` (self-registering)
    or when the module calls ``federation.register(...)`` anywhere
    (lazy/function-level registration counts).  obs/federation.py
    itself is exempt: the registry cannot register into itself."""
    if path.endswith("obs/federation.py"):
        return
    tree = module.node
    mod_aliases, reg_names, ctor_names = _r8_federation_names(tree)

    def registers(call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name) and f.id in reg_names:
            return True
        return (
            isinstance(f, ast.Attribute)
            and f.attr == "register"
            and _root_name(f) in mod_aliases
        )

    if any(
        isinstance(n, ast.Call) and registers(n)
        for n in ast.walk(tree)
    ):
        return
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
            value = stmt.value
        else:
            continue
        names = [
            t.id for t in targets
            if isinstance(t, ast.Name) and _R8_NAME_RE.match(t.id)
        ]
        if not names:
            continue
        if (
            isinstance(value, ast.Call)
            and _callee_base(value.func) in ctor_names
        ):
            continue
        for name in names:
            findings.append(Finding(
                "R8", path, stmt.lineno, name,
                f"module-level stats surface {name} is not in the "
                "stats federation — construct it as "
                "obs.federation.FederatedStats or call "
                "federation.register(namespace, snapshot, reset) in "
                "this module, so federation.snapshot(), the live "
                "/metrics exporter, and postmortem bundles can see it",
            ))


# ---------------------------------------------------------------------------
# R9 cache-key-completeness
# ---------------------------------------------------------------------------

#: the result-cache identity contract (autopilot/cache.py
#: CACHE_KEY_FIELDS) with the synonyms a call site may spell each
#: field with — "fence" is the router's graph-version fence, which
#: bare sessions carry as an ingest epoch and replicas as a version
_R9_KEY_FIELDS = (
    ("compat", ("compat",)),
    ("source", ("source",)),
    ("fence", ("fence", "epoch", "version")),
)
_R9_CACHE_METHODS = {"lookup", "store"}


def _r9_idents(node: ast.AST) -> Set[str]:
    """Every identifier-ish token an argument expression names: Name
    ids, Attribute attrs, and string constants — the surface a key
    field could be spelled on."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)
    return out


def _check_r9(module: _Scope, path: str,
              findings: List[Finding]) -> None:
    """R9 cache-key-completeness.  A `.lookup(...)`/`.store(...)`
    call whose receiver chain names a cache (``self._cache``,
    ``queue.result_cache``, a bare ``cache``) is a result-cache call
    site; its arguments must name EVERY field of the result identity
    — the compat key, the lane source, and the fence epoch
    (autopilot/cache.py CACHE_KEY_FIELDS) — or two structurally
    different queries / two graph versions could share one cached
    answer.  autopilot/cache.py itself is exempt (it IS the keyed
    surface; its internals take the fields apart)."""
    if path.endswith("autopilot/cache.py"):
        return
    for n in ast.walk(module.node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in _R9_CACHE_METHODS):
            continue
        chain = []
        v = f.value
        while isinstance(v, ast.Attribute):
            chain.append(v.attr)
            v = v.value
        if isinstance(v, ast.Name):
            chain.append(v.id)
        if not any("cache" in part.lower() for part in chain):
            continue
        idents = set()
        for a in n.args:
            idents |= _r9_idents(a)
        for kw in n.keywords:
            if kw.arg:
                idents.add(kw.arg)
            idents |= _r9_idents(kw.value)
        lowered = {i.lower() for i in idents}
        missing = [
            field for field, synonyms in _R9_KEY_FIELDS
            if not any(s in tok for s in synonyms for tok in lowered)
        ]
        if missing:
            findings.append(Finding(
                "R9", path, n.lineno, f.attr,
                f"result-cache {f.attr}() does not name the full "
                f"result identity — missing {', '.join(missing)}: "
                "every lookup/store must carry every compat_key "
                "field plus the lane source and the fence epoch "
                "(autopilot/cache.py CACHE_KEY_FIELDS), or a stale "
                "or structurally different answer can be served as "
                "a hit",
            ))


#: module-level names that declare a pricing RATE.  Op-count
#: conventions (_ITEM_VPU, DEFAULT_OPS_PER_EDGE, stage heights) are
#: NOT rates — they must stay literal so the recount gates remain
#: independent of the planners they audit.
_R10_NAME_RE = re.compile(
    r"(_BPS|_HZ|_CYC_PER_ELEM|_PER_CYCLE|_ROWS_PER_CYCLE)$"
    r"|^_?GATHER_RATES$"
)


def _r10_literal_number(value: ast.AST) -> bool:
    """True when `value` is (or contains, for dict tables) a numeric
    literal — a profile-attribute read (`default_profile().hbm_bps`)
    is the sanctioned form and has no literal to flag."""
    if isinstance(value, ast.Constant):
        return isinstance(value.value, (int, float)) and not isinstance(
            value.value, bool
        )
    if isinstance(value, ast.BinOp):
        return (_r10_literal_number(value.left)
                and _r10_literal_number(value.right))
    if isinstance(value, ast.UnaryOp):
        return _r10_literal_number(value.operand)
    if isinstance(value, ast.Dict):
        return any(_r10_literal_number(v) for v in value.values)
    return False


def _check_r10(module: _Scope, path: str,
               findings: List[Finding]) -> None:
    """R10 pinned-rate-constant.  A module-level assignment whose name
    declares a pricing rate (``*_BPS``, ``*_HZ``, ``*_CYC_PER_ELEM``,
    ``*_PER_CYCLE``, a ``GATHER_RATES`` table) bound to a numeric
    LITERAL outside ops/calibration.py is a private rate copy: the
    calibration pass cannot fit it and the drift gate cannot see it.
    Reading the shared profile (``default_profile().hbm_bps``) passes
    — the name then tracks THE rate, pinned or fitted."""
    if path.endswith("ops/calibration.py"):
        return
    for stmt in module.node.body:
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        names = [
            t.id for t in targets
            if isinstance(t, ast.Name) and _R10_NAME_RE.search(t.id)
        ]
        if not names or not _r10_literal_number(value):
            continue
        for name in names:
            findings.append(Finding(
                "R10", path, stmt.lineno, name,
                f"pricing rate {name} is pinned as a numeric literal "
                "outside ops/calibration.py — a private copy the "
                "calibration fit cannot update and the drift gate "
                "cannot audit; read it from the shared RateProfile "
                "(ops/calibration.default_profile / active_profile) "
                "instead",
            ))


#: the sanctioned spellings of the SUMMA mesh axis names — the string
#: values of parallel/comm_spec.VC_ROW_AXIS / VC_COL_AXIS.  Inlined
#: (not imported) on purpose: the lint must keep flagging the raw
#: strings even if the runtime constants are renamed out from under
#: the literal copies it hunts.
_R11_AXIS_LITERALS = ("vcrow", "vccol")


def _check_r11(module: _Scope, path: str,
               findings: List[Finding]) -> None:
    """R11 raw-axis-name.  A models/ module that spells a SUMMA mesh
    axis name as a raw string literal ('vcrow'/'vccol') holds a
    private copy of the mesh contract: every pmin/psum/ppermute over
    the 2-D mesh is only correct because its axis name matches
    mesh2d()'s, and a renamed or extended mesh would miss the literal
    silently — wrong-axis collective, not an import error.  Importing
    VC_ROW_AXIS/VC_COL_AXIS from parallel/comm_spec.py is the
    sanctioned form (the defining module itself, and non-model layers
    like the worker/bench that never open a collective over the axis,
    are out of scope)."""
    if "/models/" not in "/" + path:
        return
    for n in ast.walk(module.node):
        if (
            isinstance(n, ast.Constant)
            and isinstance(n.value, str)
            and n.value in _R11_AXIS_LITERALS
        ):
            findings.append(Finding(
                "R11", path, n.lineno, "<module>",
                f"raw SUMMA axis name {n.value!r} in models/ — a "
                "private copy of the mesh contract; import "
                "VC_ROW_AXIS/VC_COL_AXIS from parallel/comm_spec.py "
                "so a mesh rename is a compile-time error instead of "
                "a wrong-axis collective",
            ))


#: a dict key that states a MODELED overlap claim (the planner's
#: side of the truth-meter join)
_R12_MODELED_RE = re.compile(r"^(modeled_|hidden_us)")

#: the sanctioned correlation keys the truth meter joins on
_R12_JOIN_KEYS = ("plan_uid", "trace_key")


def _r12_scopes(tree: ast.AST):
    """Module + every function def, each walked WITHOUT descending
    into nested function bodies (those are their own scopes)."""
    def shallow(node):
        for c in ast.iter_child_nodes(node):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield c
            yield from shallow(c)

    for n in ast.walk(tree):
        if isinstance(n, (ast.Module, ast.FunctionDef,
                          ast.AsyncFunctionDef)):
            yield n, list(shallow(n))


def _r12_str_keys(d: ast.Dict):
    return {k.value for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)}


def _check_r12(module: _Scope, path: str,
               findings: List[Finding]) -> None:
    """R12 unkeyed-modeled-claim.  A dict that carries a modeled
    overlap claim (``modeled_*`` / ``hidden_us*`` key) next to an
    ``engaged`` verdict is a pipeline/2-D decision record or span
    brief — the exact records obs/truth.py joins against measured
    device waits, and the join key is ``plan_uid`` (or ``trace_key``)
    riding in the SAME record.  Two forms are audited per scope: a
    dict literal holding both keys inline, and a name bound to a dict
    literal whose claim/verdict keys arrive via later subscript
    assignments (the decision-record idiom in parallel/pipeline.py).
    The union of literal + subscript-assigned keys must include a
    correlation key."""
    for _, nodes in _r12_scopes(module.node):
        # (a) self-contained literals (span_brief-style records)
        literal_of: dict = {}
        keys_of: dict = {}
        first_line: dict = {}
        bound_literals: set = set()
        for n in nodes:
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Dict):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        literal_of[t.id] = n
                        keys_of.setdefault(t.id, set()).update(
                            _r12_str_keys(n.value))
                        first_line.setdefault(t.id, n.lineno)
                        # audited via the key-union path below, where
                        # a later subscript may supply the join key
                        bound_literals.add(id(n.value))
            elif isinstance(n, ast.Dict) and id(n) not in bound_literals:
                keys = _r12_str_keys(n)
                if ("engaged" in keys
                        and any(_R12_MODELED_RE.match(k) for k in keys)
                        and not any(j in keys for j in _R12_JOIN_KEYS)):
                    findings.append(Finding(
                        "R12", path, n.lineno, "<dict>",
                        "modeled overlap claim next to an `engaged` "
                        "verdict without a plan_uid/trace_key — the "
                        "overlap truth meter cannot join this record "
                        "against measured device waits; stamp the "
                        "plan uid into the same dict",
                    ))
            elif (isinstance(n, ast.Assign)
                  and len(n.targets) == 1
                  and isinstance(n.targets[0], ast.Subscript)
                  and isinstance(n.targets[0].value, ast.Name)
                  and isinstance(n.targets[0].slice, ast.Constant)
                  and isinstance(n.targets[0].slice.value, str)):
                name = n.targets[0].value.id
                keys_of.setdefault(name, set()).add(
                    n.targets[0].slice.value)
        # (b) decision-record idiom: literal + subscript assignments
        for name, node in literal_of.items():
            keys = keys_of.get(name, set())
            if ("engaged" in keys
                    and any(_R12_MODELED_RE.match(k) for k in keys)
                    and not any(j in keys for j in _R12_JOIN_KEYS)):
                findings.append(Finding(
                    "R12", path, first_line[name], name,
                    f"decision record {name!r} claims modeled overlap "
                    "(modeled_*/hidden_us* key) next to `engaged` but "
                    "never stamps plan_uid/trace_key in this scope — "
                    "the truth meter cannot join the claim against "
                    "measured device waits",
                ))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def lint_source(src: str, relpath: str) -> List[Finding]:
    """All R1-R12 findings for one module's source text."""
    relpath = relpath.replace(os.sep, "/")
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(
            "E0", relpath, e.lineno or 0, "<module>",
            f"syntax error: {e.msg}",
        )]
    module = _build_scopes(tree)
    _mark_traced(module)
    parents = {
        child: parent
        for parent in ast.walk(tree)
        for child in ast.iter_child_nodes(parent)
    }
    findings: List[Finding] = []
    _check_r1(module, relpath, findings)
    _check_r2(module, relpath, parents, findings)
    _check_r3(module, relpath, findings)
    _check_r4(module, relpath, findings)
    _check_r5(module, relpath, findings)
    _check_r6(module, relpath, findings)
    _check_r7(module, relpath, findings)
    _check_r8(module, relpath, findings)
    _check_r9(module, relpath, findings)
    _check_r10(module, relpath, findings)
    _check_r11(module, relpath, findings)
    _check_r12(module, relpath, findings)
    return findings


_SKIP_DIRS = {"__pycache__", "scratch", ".git", ".pytest_cache",
              "node_modules"}


def iter_py_files(path: str):
    if os.path.isfile(path):
        yield path
        return
    if not os.path.isdir(path):
        # a mistyped path must FAIL the gate, not lint zero files and
        # report clean (os.walk on a missing dir silently yields nothing)
        raise FileNotFoundError(f"lint path does not exist: {path!r}")
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in _SKIP_DIRS]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(paths, root: Optional[str] = None) -> List[Finding]:
    """Findings over files/trees; paths in findings are relative to
    `root` (default: the repo root two levels above this package) so
    fingerprints stay stable regardless of invocation directory."""
    if root is None:
        root = repo_root()
    findings: List[Finding] = []
    for p in paths:
        for f in iter_py_files(p):
            rel = os.path.relpath(os.path.abspath(f), root)
            with open(f, encoding="utf-8") as fh:
                findings.extend(lint_source(fh.read(), rel))
    return findings


def repo_root() -> str:
    """The directory holding the libgrape_lite_tpu package."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
