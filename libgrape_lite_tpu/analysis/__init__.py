"""grape-lint: static contract linter + compiled-artifact auditor.

The compile-time complement to guard/ (which proves invariants at
runtime): Layer 1 AST lints (R1-R7, analysis/astlint.py) make the bug
classes earlier review passes caught by hand un-shippable — baked
closure constants, per-dispatch re-jits, incomplete cache keys, query
entrypoints that skip the dyn stale-view check, eager hot-loop
logging, host syncs on the async pump's dispatch stage; Layer 2 artifact audits (A1-A3, analysis/artifact.py)
recount the same contracts from the actually-lowered/compiled runners
and the live XLA compile stream.  Intentional exceptions are named in
analysis/baseline.json, never invisible.

Surfaces: `python -m libgrape_lite_tpu.cli lint`,
`scripts/grape_lint.py [--json] [--artifact]`, and
`analysis.compile_events()` for zero-recompile test pins.
docs/STATIC_ANALYSIS.md is the user guide.
"""

from libgrape_lite_tpu.analysis.artifact import (
    CompileEvents,
    compile_events,
    run_artifact_audit,
    scan_constants,
    warm_matrix_audit,
)
from libgrape_lite_tpu.analysis.astlint import (
    lint_paths,
    lint_source,
    repo_root,
)
from libgrape_lite_tpu.analysis.report import (
    Baseline,
    DEFAULT_BASELINE,
    Finding,
    build_report,
    render_text,
    split_by_baseline,
    stale_suppressions,
    validate_lint_report,
)
from libgrape_lite_tpu.analysis.rules import RULES

__all__ = [
    "Baseline",
    "CompileEvents",
    "DEFAULT_BASELINE",
    "Finding",
    "RULES",
    "build_report",
    "compile_events",
    "lint_paths",
    "lint_source",
    "render_text",
    "repo_root",
    "run_artifact_audit",
    "run_lint",
    "scan_constants",
    "split_by_baseline",
    "stale_suppressions",
    "validate_lint_report",
    "warm_matrix_audit",
]


def run_lint(paths=None, *, baseline_path=None, artifact: bool = False,
             root=None):
    """One linter invocation: (report_dict, exit_code).  Default scope
    is the shipped package tree; exit code is nonzero when any
    unsuppressed finding survives the baseline — the CI gate
    scripts/app_tests.sh enforces."""
    import os

    if root is None:
        root = repo_root()
    default_scope = not paths
    if default_scope:
        paths = [os.path.join(root, "libgrape_lite_tpu")]
    findings = lint_paths(paths, root=root)
    baseline = Baseline.load(baseline_path)
    art = None
    art_findings = []
    if artifact:
        art_findings, art = run_artifact_audit()
        findings = list(findings) + art_findings
    live, quiet = split_by_baseline(findings, baseline)
    if art is not None:
        # keep the artifact block's own findings list consistent with
        # the baseline verdicts above — one defect must not render as
        # live in one half of the record and suppressed in the other
        quiet_fps = {f.fingerprint for f in quiet}
        art["findings"] = [
            f.to_dict(f.fingerprint in quiet_fps) for f in art_findings
        ]
    # staleness is only provable on the default full-tree scope (a
    # single-file run legitimately matches almost no entries); there,
    # a baseline entry or budget unit no finding consumed fails the
    # gate — a retired defect must retire its named exception, or the
    # stale entry green-gates the defect's reintroduction
    stale = stale_suppressions(
        baseline, quiet, include_artifact=artifact,
    ) if default_scope else []
    report = build_report(
        live, quiet, root=root,
        baseline_path=baseline.path or DEFAULT_BASELINE,
        artifact=art, stale=stale,
    )
    return report, (0 if report["ok"] else 1)
