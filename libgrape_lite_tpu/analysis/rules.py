"""grape-lint rule catalogue.

Every rule fossilizes a bug this repo actually shipped (and caught by
hand in a review pass, per CHANGES.md) — the linter's job is to make
each class un-shippable instead of re-findable.  The rule ids are
stable contract: findings, baselines, and commit messages cite them.

The catalogue is data (id -> Rule); the checkers live in
analysis/astlint.py (R1-R8, pure AST) and analysis/artifact.py
(A1-A3, audits on actually-lowered/compiled runners).  Layer 1 proves
the source can't express the defect; Layer 2 recounts from the
shipped artifact — the same two-sided discipline the pack op ledger
applies to op counts (model from the plan, recount from the arrays,
fail on drift).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict


@dataclass(frozen=True)
class Rule:
    id: str
    slug: str
    summary: str   # what the rule forbids
    history: str   # the shipped bug it would have caught


RULES: Dict[str, Rule] = {
    r.id: r
    for r in [
        Rule(
            "R1", "baked-constant",
            "a jit/shard_map/pallas_call-traced body references a "
            "closure-captured np/jnp array or a frag/.dev attribute "
            "that is not a parameter — XLA bakes it into the "
            "executable as a literal constant",
            "PR 3: the guard probe closed over frag.dev, baking "
            "MB-scale fragment CSRs into the probe executable as XLA "
            "constants; fixed by passing dev as a jit argument",
        ),
        Rule(
            "R2", "uncached-jit",
            "jax.jit (or a _make_*/_compile_* runner builder) is "
            "invoked inside a per-query/per-dispatch code path "
            "instead of behind the runner cache — every dispatch "
            "silently retraces and recompiles",
            "PR 6: the guarded serve path's batched PEval minted a "
            "fresh jax.jit wrapper per batch, so steady guarded "
            "streams re-jitted every dispatch, invisibly to the "
            "zero-recompile counters",
        ),
        Rule(
            "R3", "cache-key-field",
            "a runner-builder argument does not appear in the "
            "_cached_runner cache key — two queries differing only "
            "in that argument silently share one compile",
            "PR 6 (pinned at HEAD): the fused-runner cache key "
            "initially omitted max_rounds, so a second query with a "
            "different round limit reused the first compile's baked "
            "while_loop bound",
        ),
        Rule(
            "R4", "dyn-view-parity",
            "a public query entrypoint does not reach the dyn "
            "stale-view check (_check_dyn_view / _ensure_dyn_view) "
            "and guard-config resolution — an uncontracted app can "
            "silently compute on the pre-delta graph",
            "PR 7 (post-hoc review): GUARDED query_batch ran the "
            "stale-view check after the guard routing, and "
            "query_stepwise skipped it entirely — both silently "
            "served the pre-delta graph on a staged dyn view",
        ),
        Rule(
            "R5", "eager-log-bool-schema",
            "a level-gated vlog call formats its message eagerly "
            "(f-string/%/.format/concat), or a numeric schema "
            "validator accepts bool through isinstance(x, int)",
            "PR 5: hot-loop f-strings were formatted-then-dropped at "
            "disabled vlog levels (measurable per round), and the "
            "bench schema checker accepted bools in numeric fields "
            "(bool is an int subclass)",
        ),
        Rule(
            "R6", "pipeline-window-read",
            "code between the exchange kickoff and the join point of a "
            "pipelined superstep reads a query-carry key (or a carry "
            "alias bound before the kickoff, or — position-"
            "independently — inside a nested function capturing the "
            "carry) that is not named in the worker pipeline contract "
            "(parallel/pipeline.PIPELINE_WINDOW_READS), or passes the "
            "whole carry dict to a callee not named in "
            "PIPELINE_WINDOW_CALLEES",
            "r9 (preventive): the double-buffered pipeline exists "
            "because an in-flight exchange aliasing the live carry "
            "reads torn state; every window read must be audited as "
            "double-buffer-safe and named in the contract, so the "
            "aliasing class is un-shippable instead of re-findable",
        ),
        Rule(
            "R7", "sync-in-pump",
            "a host-sync forcer (block_until_ready, jax.device_get, "
            "np/jnp.asarray, or int()/float() on a non-literal value) "
            "is reached from serve/pipeline.py dispatch-stage code "
            "(_dispatch*/_fill* self-call chains) outside the audited "
            "harvest contract (serve/pipeline.PUMP_HARVEST_SYNCS) — "
            "one stray sync re-serialises the whole dispatch window",
            "PR 12 (preventive): the synchronous serve loop blocked "
            "pulling every lane's result to host before the next "
            "batch could dispatch — the exact defect class the async "
            "pump removes; fossilized so it cannot creep back into "
            "the dispatch stage (zero-entry baseline)",
        ),
        Rule(
            "R8", "unfederated-stats",
            "a module-level *_STATS surface is neither constructed as "
            "obs.federation.FederatedStats nor registered with "
            "obs.federation.register in its defining module — the "
            "ledger is invisible to federation.snapshot(), the live "
            "/metrics exporter, and every postmortem bundle",
            "PR 15: PLAN/SPGEMM/PARTITION/PIPELINE_STATS were four "
            "hand-rolled module dicts and PUMP/FLEET_STATS two ad-hoc "
            "classes, each with its own snapshot/reset idiom; a "
            "scrape could not see them and a new one would have "
            "drifted the same way (zero-entry baseline)",
        ),
        Rule(
            "R9", "cache-key-completeness",
            "a call into the autopilot result cache "
            "(autopilot/cache.py lookup()/store()) does not name "
            "every field of the result identity — the compat key, "
            "the lane source, and the fence epoch "
            "(cache.CACHE_KEY_FIELDS) — so two structurally "
            "different queries (or two graph versions) could share "
            "one cached answer",
            "PR 16 (preventive): the result cache is sound only "
            "because its key carries the FULL compat_key plus the "
            "router fence; the R3 incident (a cache key missing "
            "max_rounds silently shared one compile) shows exactly "
            "how a dropped key field ships — fossilized here for the "
            "result cache before it can recur (zero-entry baseline)",
        ),
        Rule(
            "R10", "pinned-rate-constant",
            "a module-level float-literal pricing RATE (a *_BPS / "
            "*_HZ / *_CYC_PER_ELEM / *_PER_CYCLE constant or a "
            "GATHER_RATES table) is defined outside "
            "ops/calibration.py — a private rate copy that the "
            "calibration pass cannot fit and the drift gate cannot "
            "see, so the surface it prices silently diverges from "
            "measured truth",
            "PR 17: _MXU_CYC_PER_ELEM = 0.008 lived in BOTH "
            "ops/spgemm_pack.py and scripts/pack_cost_model.py, and "
            "pipeline/partition carried their own VPU/ICI copies — "
            "five pricing surfaces, four rate tables, none of them "
            "fittable; collapsed onto the RateProfile (zero-entry "
            "baseline over the migrated tree)",
        ),
        Rule(
            "R11", "raw-axis-name",
            "a models/ module spells a SUMMA mesh axis name as a raw "
            "string literal ('vcrow'/'vccol') instead of importing "
            "VC_ROW_AXIS/VC_COL_AXIS from parallel/comm_spec.py — a "
            "private copy of the mesh contract that a rename (or a "
            "third axis) silently misses, turning a compile-time "
            "import error into a wrong-axis collective at runtime",
            "PR 19 (preventive): the pipelined SUMMA round put the "
            "row-axis psum on the hot path of three apps at once; "
            "every collective's correctness now hangs on the axis "
            "names matching mesh2d()'s, so the string form is "
            "fossilized out of models/ (zero-entry baseline)",
        ),
        Rule(
            "R12", "unkeyed-modeled-claim",
            "a decision/brief dict that carries a modeled overlap "
            "claim (a modeled_* or hidden_us* key) next to an "
            "`engaged` verdict does not also carry the correlation "
            "key (`plan_uid` or `trace_key`) — the overlap truth "
            "meter (obs/truth.py) cannot join the claim against the "
            "tracer's measured device waits, so the modeled headline "
            "is unauditable",
            "PR 20 (preventive): every pipeline/2-D engagement "
            "headline in this tree is modeled, and until the truth "
            "meter landed nothing reconciled the claims against "
            "measured walls; the join hangs entirely on the plan uid "
            "riding in the same record, so an unkeyed claim is "
            "fossilized out (zero-entry baseline)",
        ),
        Rule(
            "A1", "constant-bloat",
            "the lowered HLO of a fused runner holds a literal "
            "constant above the byte threshold — an R1 escape "
            "caught end-to-end on the shipped artifact",
            "PR 3: same baked-constant incident as R1, audited here "
            "from the lowered module instead of the source",
        ),
        Rule(
            "A2", "donation",
            "the fused runner's lowered module donates no input "
            "buffer — the carry is double-buffered in HBM instead "
            "of aliased into the loop",
            "PR 6 era: the fused runner relies on donate_argnums "
            "aliasing the placed carry; losing it would silently "
            "double peak HBM at scale",
        ),
        Rule(
            "A3", "surprise-compile",
            "a warmed query of the canonical matrix (sssp/bfs x "
            "fused/guarded/batched/incremental) triggers an XLA "
            "compile — the runner/probe caches leak",
            "PR 6: per-batch re-jit of the guarded batched PEval; "
            "PR 8 first run: the stepwise/guarded single-step runner "
            "and the guard probe were rebuilt per query (fixed under "
            "R2 in this PR)",
        ),
    ]
}


def describe(rule_id: str) -> str:
    r = RULES[rule_id]
    return f"[{r.id} {r.slug}] {r.summary}"
