"""Findings, fingerprints, the suppression baseline, and the report
schema for grape-lint (analysis/).

A finding is structured — (rule id, file:line, enclosing symbol,
message, fingerprint) — so the same defect reads identically to a
human (`render_text`), to CI (`render_json` + `validate_lint_report`),
and to the suppression baseline.  The fingerprint deliberately
excludes the line number: a finding must survive unrelated edits above
it, or every refactor would churn the baseline (the same stability
rule ft/fingerprint.py applies to checkpoint identity).

The baseline (`analysis/baseline.json`, checked in) is the named-
exception mechanism: an intentional violation is suppressed by
fingerprint WITH a reason string, so exceptions are visible in review
instead of silently absent from the report — the same discipline as
the pack ledger's "recount from the shipped artifact" rule, applied
to lint verdicts.  docs/STATIC_ANALYSIS.md describes the workflow.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# severity is advisory (every unsuppressed finding fails the gate);
# it orders the human report so the compile-visible classes lead
_SEVERITY = {"R1": 0, "R2": 1, "R3": 2, "R4": 3, "R5": 4, "R6": 3,
             "R7": 1,
             "A1": 0, "A2": 1, "A3": 1}


@dataclass(frozen=True)
class Finding:
    rule: str          # "R1".."R7" (AST) / "A1".."A3" (artifact)
    path: str          # repo-relative, '/'-separated
    line: int          # 1-indexed; 0 for artifact-level findings
    symbol: str        # enclosing qualname ("Worker._make_runner.stepper")
    message: str       # one-sentence defect statement

    @property
    def fingerprint(self) -> str:
        """Line-stable identity: rule + path + symbol + message.
        Unrelated edits that shift line numbers do not invalidate a
        baseline entry; renaming the symbol or changing the defect
        does (and should — the exception must be re-justified)."""
        h = hashlib.sha256(
            "\x1f".join(
                (self.rule, self.path, self.symbol, self.message)
            ).encode()
        )
        return h.hexdigest()[:16]

    def to_dict(self, suppressed: bool = False) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "suppressed": suppressed,
        }


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(
        findings,
        key=lambda f: (_SEVERITY.get(f.rule, 9), f.path, f.line, f.rule),
    )


# ---- suppression baseline -------------------------------------------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclass
class Baseline:
    """Named suppressions keyed by finding fingerprint.  Every entry
    carries a human reason — `lint --update-baseline` refuses to write
    entries without one, so "why is this allowed" is always answerable
    from the file itself."""

    entries: Dict[str, dict] = field(default_factory=dict)
    path: Optional[str] = None

    @classmethod
    def load(cls, path: Optional[str] = None) -> "Baseline":
        path = os.path.abspath(path or DEFAULT_BASELINE)
        if not os.path.exists(path):
            return cls(entries={}, path=path)
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or "suppressions" not in doc:
            raise ValueError(
                f"{path}: baseline must be an object with a "
                "'suppressions' list"
            )
        entries = {}
        for e in doc["suppressions"]:
            missing = [k for k in ("fingerprint", "rule", "reason")
                       if k not in e]
            if missing:
                raise ValueError(
                    f"{path}: suppression entry {e!r} is missing "
                    f"{missing} — exceptions must be named, not vague"
                )
            entries[e["fingerprint"]] = dict(e)
        return cls(entries=entries, path=path)

    def suppresses(self, finding: Finding) -> bool:
        """Whether an entry MATCHES this finding (budget-blind; the
        per-entry `count` budget is enforced by split_by_baseline so
        one entry cannot silently absorb a SECOND identical-message
        violation added later to the same function)."""
        e = self.entries.get(finding.fingerprint)
        return e is not None and e.get("rule") == finding.rule

    def budget(self, fingerprint: str) -> int:
        e = self.entries.get(fingerprint)
        return int(e.get("count", 1)) if e is not None else 0

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path or DEFAULT_BASELINE
        doc = {
            "version": 1,
            "suppressions": sorted(
                self.entries.values(),
                key=lambda e: (e["rule"], e.get("path", ""),
                               e["fingerprint"]),
            ),
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        return path

    def add(self, finding: Finding, reason: str) -> None:
        if not reason:
            raise ValueError(
                "a baseline suppression needs a reason — intentional "
                "exceptions are named, not invisible"
            )
        prev = self.entries.get(finding.fingerprint)
        if prev is not None and prev.get("rule") == finding.rule:
            # a second identical-fingerprint finding (same defect
            # message repeated in one function) costs a second unit
            # of budget — it must be suppressed EXPLICITLY, never
            # absorbed by the first entry; its reason is recorded
            # too (every instance stays named, not just the first)
            prev["count"] = int(prev.get("count", 1)) + 1
            if reason not in prev["reason"]:
                prev["reason"] += (
                    f"; instance {prev['count']}: {reason}"
                )
            return
        self.entries[finding.fingerprint] = {
            "fingerprint": finding.fingerprint,
            "rule": finding.rule,
            "path": finding.path,
            "symbol": finding.symbol,
            "reason": reason,
        }


# ---- report rendering -----------------------------------------------------


def split_by_baseline(findings: List[Finding], baseline: Baseline):
    """(unsuppressed, suppressed) in stable severity order.  Each
    baseline entry suppresses at most its `count` (default 1)
    matching findings: fingerprints exclude the line number for
    line-stability, so two identical-message violations in one
    function collide — the budget keeps a shipped suppression from
    silently covering a NEW instance of the same defect class."""
    live, quiet = [], []
    used: Dict[str, int] = {}
    for f in sort_findings(findings):
        fp = f.fingerprint
        if (
            baseline.suppresses(f)
            and used.get(fp, 0) < baseline.budget(fp)
        ):
            used[fp] = used.get(fp, 0) + 1
            quiet.append(f)
        else:
            live.append(f)
    return live, quiet


def stale_suppressions(baseline: Baseline, quiet: List[Finding], *,
                       include_artifact: bool) -> List[dict]:
    """Baseline entries (or budget units) that matched NO finding in a
    full-default-scope run.  A fixed finding must retire its entry —
    a stale entry (or a stale raised `count`) would otherwise silently
    green-gate a later REINTRODUCTION of the exact defect it names.
    A-rule entries are only judged when the artifact audits actually
    ran (an AST-only pass proves nothing about them)."""
    used: Dict[str, int] = {}
    for f in quiet:
        used[f.fingerprint] = used.get(f.fingerprint, 0) + 1
    stale = []
    for fp, e in sorted(baseline.entries.items()):
        if e["rule"].startswith("A") and not include_artifact:
            continue
        unused = baseline.budget(fp) - used.get(fp, 0)
        if unused > 0:
            stale.append({
                "fingerprint": fp,
                "rule": e["rule"],
                "symbol": e.get("symbol", ""),
                "unused": unused,
            })
    return stale


def render_text(live: List[Finding], quiet: List[Finding],
                stale: Optional[List[dict]] = None) -> str:
    lines = []
    for f in live:
        lines.append(
            f"{f.path}:{f.line}: [{f.rule}] {f.symbol}: {f.message} "
            f"(fingerprint {f.fingerprint})"
        )
    if quiet:
        lines.append(
            f"({len(quiet)} finding(s) suppressed by baseline)"
        )
    for s in stale or []:
        lines.append(
            f"stale baseline entry [{s['rule']}] {s['symbol']}: "
            f"{s['unused']} unused suppression unit(s) "
            f"(fingerprint {s['fingerprint']}) — the finding is gone; "
            "retire the entry or lower its count"
        )
    if not live and not stale:
        lines.append("grape-lint: clean")
    elif not live:
        lines.append(
            f"grape-lint: {len(stale)} stale baseline entr(y/ies)"
        )
    else:
        lines.append(
            f"grape-lint: {len(live)} unsuppressed finding(s)"
        )
    return "\n".join(lines)


def build_report(live: List[Finding], quiet: List[Finding], *,
                 root: str, baseline_path: str,
                 artifact: Optional[dict] = None,
                 stale: Optional[List[dict]] = None) -> dict:
    counts: Dict[str, int] = {}
    for f in live:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    rec = {
        "ok": not live and not stale,
        "root": root,
        "baseline": baseline_path,
        "counts": counts,
        "suppressed": len(quiet),
        "stale": list(stale or []),
        "findings": [f.to_dict(False) for f in live]
        + [f.to_dict(True) for f in quiet],
    }
    if artifact is not None:
        rec["artifact"] = artifact
    return rec


# ---- report schema (check_bench_schema.py discipline) ---------------------

_NUM = (int, float)

# field -> (type tuple, required); unknown keys are errors, bool is
# rejected in numeric fields (bool is an int subclass — the r8 schema
# trap this package's R5 rule fossilizes)
_TOP = {
    "ok": (bool, True),
    "root": (str, True),
    "baseline": (str, True),
    "counts": (dict, True),
    "suppressed": (int, True),
    "stale": (list, True),
    "findings": (list, True),
    "artifact": (dict, False),
}

_STALE = {
    "fingerprint": (str, True),
    "rule": (str, True),
    "symbol": (str, True),
    "unused": (int, True),
}

_FINDING = {
    "rule": (str, True),
    "path": (str, True),
    "line": (int, True),
    "symbol": (str, True),
    "message": (str, True),
    "fingerprint": (str, True),
    "suppressed": (bool, True),
}

_ARTIFACT = {
    "findings": (list, True),
    "constant_bloat": (dict, False),
    "donation": (dict, False),
    "compile_audit": (dict, False),
}


def _check_block(block: dict, spec: dict, where: str,
                 errors: list) -> None:
    for fld, (types, required) in spec.items():
        if fld not in block:
            if required:
                errors.append(f"{where}: missing required field {fld!r}")
            continue
        v = block[fld]
        accepted = types if isinstance(types, tuple) else (types,)
        if isinstance(v, bool) and bool not in accepted:
            errors.append(f"{where}.{fld}: expected number, got bool")
        elif not isinstance(v, types):
            errors.append(
                f"{where}.{fld}: expected "
                f"{getattr(types, '__name__', types)}, got "
                f"{type(v).__name__}"
            )
    for k in block:
        if k not in spec:
            errors.append(
                f"{where}: unknown field {k!r} — declare it in "
                "analysis/report.py or fix the typo"
            )


def validate_lint_report(record) -> list:
    """Every schema violation in one lint-report record (empty =
    valid) — the same pinned-artifact contract as
    scripts/check_bench_schema.py, applied to the lint JSON that CI
    and tpu_first_light.sh consume."""
    errors: list = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected object"]
    _check_block(record, _TOP, "record", errors)
    for i, f in enumerate(record.get("findings") or []):
        if not isinstance(f, dict):
            errors.append(f"findings[{i}]: expected object")
            continue
        _check_block(f, _FINDING, f"findings[{i}]", errors)
    for i, s in enumerate(record.get("stale") or []):
        if not isinstance(s, dict):
            errors.append(f"stale[{i}]: expected object")
            continue
        _check_block(s, _STALE, f"stale[{i}]", errors)
    counts = record.get("counts")
    if isinstance(counts, dict):
        for k, v in counts.items():
            if not isinstance(v, int) or isinstance(v, bool):
                errors.append(
                    f"counts[{k!r}]: expected int, got {type(v).__name__}"
                )
    art = record.get("artifact")
    if isinstance(art, dict):
        _check_block(art, _ARTIFACT, "artifact", errors)
    return errors
