"""Query identity fingerprint for checkpoint validation.

A checkpoint is resumable only against the *same* computation: same
app, same fragment content, same mesh shape, same query arguments, and
the same numeric configuration (x64 and SpMV-path selection change
float reduction dtypes/order, which would break the byte-identical
resume contract).  The fingerprint captures exactly that set — and
deliberately NOT process-local identities like compiled-runner cache
keys or mirror-plan uids, which differ between the killed process and
the resuming one even for identical configs.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict

import numpy as np

FINGERPRINT_FORMAT = 1


def stable_config_digest(obj: Any) -> str:
    """sha256 hex of a canonical-JSON rendering of `obj` — the shared
    config-fingerprint primitive for cache keys (pack plan cache keys
    its entries by PackConfig + dtype through this).  Non-JSON leaves
    fall back to str(), so dataclass asdict() payloads with numpy
    scalars stay stable across processes."""
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=str).encode()
    ).hexdigest()


def app_registry_name(app) -> str:
    """The APP_REGISTRY name for this app instance (first registered
    alias, sorted for determinism), falling back to the class name for
    unregistered app classes (tests, user subclasses)."""
    from libgrape_lite_tpu.models import APP_REGISTRY

    names = sorted(k for k, v in APP_REGISTRY.items() if v is type(app))
    return names[0] if names else type(app).__name__


def _hash_array(h, a) -> None:
    a = np.asarray(a)
    if a.dtype == object:  # string oids
        for s in a.tolist():
            h.update(str(s).encode("utf-8"))
            h.update(b"\x00")
    else:
        h.update(np.ascontiguousarray(a).tobytes())


def fragment_content_hash(frag) -> str:
    """sha256 over the fragment's host CSR content (topology, weights,
    oid assignment) + shape metadata.  Cached on the fragment — the
    arrays are immutable after build, and a rebuild-on-mutate produces
    a fresh fragment object."""
    cached = getattr(frag, "_ft_content_hash", None)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    h.update(
        json.dumps(
            {
                "fnum": frag.fnum,
                "vp": frag.vp,
                "directed": bool(frag.directed),
                "weighted": bool(frag.weighted),
            },
            sort_keys=True,
        ).encode()
    )
    aliased = frag.host_ie is frag.host_oe
    sides = [frag.host_oe] if aliased else [frag.host_oe, frag.host_ie]
    for f in range(frag.fnum):
        _hash_array(h, frag.inner_oids(f))
        for csrs in sides:
            c = csrs[f]
            _hash_array(h, c.indptr)
            _hash_array(h, c.edge_nbr)
            _hash_array(h, c.edge_mask)
            if c.edge_w is not None:
                _hash_array(h, c.edge_w)
    digest = h.hexdigest()
    frag._ft_content_hash = digest
    return digest


def canonical_query_args(query_args: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-roundtrippable form of the query kwargs: numpy scalars
    become Python numbers, everything else must already be a JSON
    primitive (the resume path replays these through `init_state`)."""
    out = {}
    for k, v in sorted(query_args.items()):
        if isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        elif isinstance(v, (np.bool_,)):
            v = bool(v)
        if not isinstance(v, (int, float, str, bool, type(None))):
            raise TypeError(
                f"query arg {k!r}={v!r} is not checkpointable (must be a "
                "JSON primitive so resume can replay it through init_state)"
            )
        out[k] = v
    return out


def compute_fingerprint(app, frag, query_args: Dict[str, Any]) -> Dict[str, Any]:
    """The identity a checkpoint must match to be resumed."""
    import jax

    return {
        "format": FINGERPRINT_FORMAT,
        "app": app_registry_name(app),
        "app_class": type(app).__name__,
        "fragment_hash": fragment_content_hash(frag),
        "fnum": frag.fnum,
        "vp": frag.vp,
        "query_args": canonical_query_args(query_args),
        # numeric config that changes result bytes
        "x64": bool(jax.config.jax_enable_x64),
        "spmv_mode": os.environ.get("GRAPE_SPMV", "auto"),
        # mesh geometry beyond fnum/vp: the partition layout and the
        # process topology.  A 2-D-partition snapshot must never
        # silently restore into a 1-D worker (the carry layouts
        # differ), and a reshard restore must KNOW it is crossing a
        # process-count change (ft/distributed.py GEOMETRY_KEYS) —
        # both are loud CheckpointMismatchErrors, never guesses.
        "partition_mode": _partition_mode(),
        "processes": jax.process_count(),
    }


def _partition_mode() -> str:
    # local import: fragment/ pulls in the parallel stack; the
    # fingerprint module must stay importable standalone
    from libgrape_lite_tpu.fragment.partition import partition_mode

    return partition_mode()


def fingerprint_mismatch(expected: Dict, found: Dict) -> list[str]:
    """Human-readable list of differing fingerprint fields."""
    keys = sorted(set(expected) | set(found))
    return [
        f"{k}: checkpoint has {found.get(k)!r}, query has {expected.get(k)!r}"
        for k in keys
        if expected.get(k) != found.get(k)
    ]
