"""Distributed resilience: sharded two-phase checkpoints + reshard.

Under `jax.distributed` the carry spans non-addressable devices, so
`CheckpointManager`'s single `state.npz` cannot exist: no process can
see the whole array.  `ShardedCheckpointManager` keeps the same
superstep-cut contract with a per-process layout instead —

    <dir>/ckpt_<rounds:08d>/{rank_<r>.npz, rank_<r>.json, meta.json}

— committed with a **two-phase barrier** over a tiny host-side
allgather (`parallel.comm_spec.host_allgather`):

* **phase 1 (stage)** — every rank writes only its local
  `[fnum_local, vp]` blocks (from `leaf.addressable_shards`) plus the
  `__oids_<f>` vertex maps of the fragment rows it owns into a shared
  `.stage-<rounds:08d>` directory, then votes (ok, rounds,
  sha256-prefix).  A rank-local IO failure becomes an all-ranks error
  at this barrier instead of a stranded peer.
* **phase 2 (commit)** — the coordinator re-hashes every staged shard
  against the voted sha256, writes `meta.json` (`"layout":
  "sharded"`, per-rank shard manifest) into the staging dir, and
  renames it to `ckpt_<rounds:08d>`.  A second barrier makes every
  rank's return mean *durable* (the `kill@K`-after-checkpoint drill
  contract).

`meta.json` only ever appears inside a fully verified directory and
the rename is atomic, so a kill **between** the phases leaves a loud
`.stage-*` partial that `list_checkpoints`/`restore_latest` never
adopt; the next manager construction sweeps and reports it.

All of this assumes the checkpoint directory is on a filesystem every
process shares (the multi-process-per-host CPU drills trivially are;
a real multi-host run needs NFS or equivalent — the coordinator must
read every rank's staged shard to certify it).

Restore has two shapes:

* same mesh — `ft.checkpoint.restore_latest` recognises the sharded
  layout and gathers the full carry host-side (`load_sharded_state`),
  every shard integrity-checked against the committed manifest;
* **reshard-on-loss** — `restore_resharded` rebuilds the vertex map
  of the checkpointed mesh from the stored `__oids_<f>` arrays
  (`_CheckpointLayout`), aligns it to the survivors' new fragment by
  oid (`fragment.mutation.oid_row_alignment` — the same
  permutation/extraction/assignment primitive the migration paths
  use), scatters the old `[fnum, vp]` carry onto the new layout, and
  records the surviving mesh's 1d/2d pricing decision in the
  partition ledger.  Geometry (fnum, vp, fragment content hash,
  process count) is *allowed* to differ; everything else in the
  fingerprint must match loudly.

The collectives here are HOST-side and synchronous on purpose: a
writer-thread barrier could interleave with the main thread's device
collectives and deadlock the gang, so unlike `CheckpointManager`
there is no double buffer — `save_async` keeps the name (the worker
calls both managers through one interface) but returns only after
commit.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from libgrape_lite_tpu import obs
from libgrape_lite_tpu.ft.checkpoint import (
    CKPT_FORMAT,
    CheckpointMismatchError,
    CorruptCheckpointError,
    _step_path,
    list_checkpoints,
    read_meta,
)
from libgrape_lite_tpu.ft.faults import DEFAULT_KILL_EXIT_CODE
from libgrape_lite_tpu.ft.fingerprint import fingerprint_mismatch
from libgrape_lite_tpu.utils import logging as glog

#: fingerprint keys a reshard restore may legitimately change; every
#: other key (app, app_class, query_args, x64, spmv_mode,
#: partition_mode) must still match exactly
GEOMETRY_KEYS = ("fnum", "vp", "fragment_hash", "processes")

#: test-only hook: "K:R" kills rank R between the stage barrier and
#: the commit (the exact window the two-phase argument is about)
TWO_PHASE_KILL_ENV = "GRAPE_FT_2PC_KILL"

_OIDS_PREFIX = "__oids_"
_STAGE_PREFIX = ".stage-"


class _HostComm:
    """The tiny control plane a two-phase commit needs: who am I, how
    many of us, and a host-side allgather of a small int32 vector.
    Injectable so the commit protocol is unit-testable in one
    process."""

    def __init__(self, rank: Optional[int] = None,
                 nprocs: Optional[int] = None, allgather=None):
        import jax

        self.rank = jax.process_index() if rank is None else int(rank)
        self.nprocs = (
            jax.process_count() if nprocs is None else int(nprocs)
        )
        if allgather is None:
            from libgrape_lite_tpu.parallel.comm_spec import (
                host_allgather,
            )

            allgather = host_allgather
        self._allgather = allgather

    def allgather(self, vec: np.ndarray) -> np.ndarray:
        out = np.asarray(self._allgather(np.asarray(vec, np.int32)))
        if out.shape[0] != self.nprocs:
            raise RuntimeError(
                f"host allgather returned {out.shape[0]} rows for "
                f"{self.nprocs} processes"
            )
        return out

    def barrier(self) -> None:
        self.allgather(np.zeros(1, np.int32))


def _sha_prefix(sha_hex: str) -> Tuple[int, int]:
    # two 28-bit chunks: int32-safe in the vote vector; the commit
    # phase still verifies the FULL sha256 against the staged file
    return int(sha_hex[:7], 16), int(sha_hex[7:14], 16)


def _obs_trace_word() -> int:
    """28-bit trace-id prefix riding the 2PC vote vectors (0 when obs
    is disarmed) — the allgathered matrix then correlates every rank's
    trace file with this commit.  Readers tolerate its absence: fakes
    that allgather 4-wide stage votes keep working because nothing
    reads past the columns it already had."""
    try:
        from libgrape_lite_tpu.obs.gang import trace_word

        return trace_word()
    except Exception:
        return 0


def _ckpt_flow(comm, rounds: int, leg: str) -> None:
    """One flow-event leg per 2PC phase barrier: every rank shares
    `(cat="gang-ckpt", id=rounds+1)` so the merged gang trace renders
    stage→commit as one arrow across the rank tracks.  Never raises;
    two-branch no-op disarmed."""
    try:
        from libgrape_lite_tpu import obs

        tr = obs.tracer()
        if not tr.enabled:
            return
        if leg == "stage":
            phase = "s" if comm.rank == 0 else "t"
        else:
            phase = "f" if comm.rank == comm.nprocs - 1 else "t"
        tr.flow(f"ckpt_{leg}", flow_id=int(rounds) + 1, phase=phase,
                cat="gang-ckpt", round=int(rounds))
    except Exception:
        pass


def _maybe_kill_between_phases(rounds: int, rank: int) -> None:
    spec = os.environ.get(TWO_PHASE_KILL_ENV, "")
    if not spec:
        return
    k, _, r = spec.partition(":")
    try:
        k, r = int(k), int(r)
    except ValueError:
        raise ValueError(
            f"{TWO_PHASE_KILL_ENV}={spec!r} is not K:R"
        ) from None
    if k == rounds and r == rank:
        glog.log_info(
            f"fault injection: killing rank {rank} between checkpoint "
            f"phases at superstep {rounds} (stage is durable, commit "
            "never happens)"
        )
        os._exit(DEFAULT_KILL_EXIT_CODE)


def _extract_local(leaf, fnum: int):
    """(rows, block) of this process's slice of one carry leaf: `rows`
    is the list of fragment-row indices it owns (None when the leaf is
    replicated — every process holds the full value), `block` the
    stacked host array in `rows` order."""
    if not hasattr(leaf, "addressable_shards"):
        # host numpy (in-process tests, pre-placement carries): one
        # process owns everything sharded-shaped, rank 0 convention
        a = np.asarray(leaf)
        if a.ndim >= 1 and a.shape[0] == fnum:
            return list(range(fnum)), a
        return None, a
    rows: Dict[int, np.ndarray] = {}
    full = None
    for s in leaf.addressable_shards:
        idx = s.index[0] if len(s.index) else slice(None)
        if idx.start is None:
            full = np.asarray(s.data)
        else:
            block = np.asarray(s.data)
            for i in range(block.shape[0]):
                rows[int(idx.start) + i] = block[i]
    if rows:
        order = sorted(rows)
        return order, np.stack([rows[i] for i in order])
    if full is None:  # pragma: no cover - nothing addressable
        raise CorruptCheckpointError(
            "carry leaf has no addressable shards on this process"
        )
    return None, full


class ShardedCheckpointManager:
    """Per-process shard files + a two-phase commit barrier: the
    multi-process `CheckpointManager` (same call surface — the
    stepwise worker drives either through `save_async`/`wait`/
    `close`)."""

    def __init__(
        self,
        directory: str,
        *,
        fingerprint: Dict[str, Any],
        query_args: Dict[str, Any],
        checkpoint_every: int,
        frag,
        keep: int = 2,
        fresh_start: bool = False,
        comm: Optional[_HostComm] = None,
    ):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = directory
        self.fingerprint = fingerprint
        self.query_args = query_args
        self.checkpoint_every = checkpoint_every
        self.keep = keep
        self.frag = frag
        self.comm = comm if comm is not None else _HostComm()
        if self.comm.rank == 0:
            os.makedirs(directory, exist_ok=True)
            for name in os.listdir(directory):
                # a kill between the phases leaves a staged partial:
                # never adoptable (no meta.json outside a committed
                # dir), but LOUD — silence would hide that a previous
                # gang died inside the commit window
                if name.startswith(_STAGE_PREFIX) or name.startswith(
                    ".tmp-"
                ):
                    glog.log_info(
                        f"checkpoint: sweeping partial {name!r} (a "
                        "previous run died before its commit phase)"
                    )
                    shutil.rmtree(
                        os.path.join(directory, name),
                        ignore_errors=True,
                    )
            if fresh_start:
                # new query, new lineage (CheckpointManager contract)
                for _, path in list_checkpoints(directory):
                    shutil.rmtree(path, ignore_errors=True)
        # construction barrier: no rank may stage into a directory the
        # coordinator is still sweeping/wiping
        self.comm.barrier()
        os.makedirs(directory, exist_ok=True)

    # ---- save ------------------------------------------------------------

    def save_async(self, state: Dict[str, Any], rounds: int, active: int):
        """Stage + commit the superstep-`rounds` snapshot.  Synchronous
        despite the name: the phase barriers are collectives, and
        collectives must run on the caller thread in lockstep with the
        device program's — a writer-thread barrier could deadlock the
        gang."""
        t0 = time.perf_counter()
        with obs.tracer().span(
            "checkpoint_save_sharded", round=int(rounds)
        ) as sp:
            self._save(state, int(rounds), int(active), sp)
        m = obs.metrics()
        m.counter("grape_checkpoint_saves_total").inc()
        m.histogram("grape_checkpoint_save_seconds").observe(
            time.perf_counter() - t0
        )

    def wait(self) -> None:
        """No in-flight write exists: `save_async` returns only after
        the commit barrier (durability is the return value)."""

    def close(self) -> None:
        pass

    def _save(self, state, rounds: int, active: int, sp) -> None:
        stage = os.path.join(
            self.directory, f"{_STAGE_PREFIX}{rounds:08d}"
        )
        ok, sha_hex, stage_err = 1, "0" * 64, None
        try:
            os.makedirs(stage, exist_ok=True)
            sha_hex, nbytes = self._stage_local(
                state, rounds, active, stage
            )
            sp.set(bytes=nbytes)
        except Exception as e:  # voted, not raised: the barrier turns
            ok, stage_err = 0, e  # a local failure into a gang-wide one
        lo, hi = _sha_prefix(sha_hex)
        votes = self.comm.allgather(
            np.asarray([ok, rounds, lo, hi, _obs_trace_word()],
                       np.int32)
        )
        _ckpt_flow(self.comm, rounds, "stage")
        if not np.all(votes[:, 0] == 1):
            bad = np.nonzero(votes[:, 0] != 1)[0].tolist()
            raise CorruptCheckpointError(
                f"checkpoint stage failed on rank(s) {bad} at "
                f"superstep {rounds}; no rank commits"
            ) from stage_err
        if not np.all(votes[:, 1] == rounds):
            raise RuntimeError(
                "two-phase commit out of lockstep: per-rank supersteps "
                f"{votes[:, 1].tolist()} (this rank at {rounds})"
            )
        _maybe_kill_between_phases(rounds, self.comm.rank)
        committed, commit_err = 1, None
        if self.comm.rank == 0:
            try:
                self._commit(stage, rounds, active, votes)
            except Exception as e:
                committed, commit_err = 0, e
        done = self.comm.allgather(
            np.asarray([committed, rounds, _obs_trace_word()],
                       np.int32)
        )
        _ckpt_flow(self.comm, rounds, "commit")
        if not np.all(done[:, 0] == 1):
            raise CorruptCheckpointError(
                f"two-phase commit failed in the commit phase at "
                f"superstep {rounds} (coordinator could not certify "
                "every staged shard)"
            ) from commit_err

    def _stage_local(self, state, rounds: int, active: int,
                     stage: str) -> Tuple[str, int]:
        payload: Dict[str, np.ndarray] = {}
        leafmeta: Dict[str, Any] = {}
        owned: set = set()
        for k in sorted(state):
            if k.startswith(_OIDS_PREFIX):
                raise ValueError(
                    f"carry leaf {k!r} collides with the reserved "
                    f"{_OIDS_PREFIX}* vertex-map namespace"
                )
            rows, block = _extract_local(state[k], self.frag.fnum)
            if block.dtype == object:
                raise TypeError(
                    f"state leaf {k!r} has object dtype and cannot be "
                    "checkpointed without pickle (refused: a "
                    "checkpoint must never execute code on restore)"
                )
            payload[k] = block
            if rows is None:
                leafmeta[k] = {
                    "replicated": True,
                    "shape": list(block.shape),
                    "dtype": block.dtype.str,
                }
            else:
                owned.update(rows)
                leafmeta[k] = {
                    "rows": rows,
                    "shape": [self.frag.fnum] + list(block.shape[1:]),
                    "dtype": block.dtype.str,
                }
        if not owned and self.comm.rank == 0:
            # an all-replicated carry still needs the vertex maps for
            # a later reshard; the coordinator owns them by convention
            owned = set(range(self.frag.fnum))
        oid_rows = sorted(owned)
        for f in oid_rows:
            payload[f"{_OIDS_PREFIX}{f}"] = np.asarray(
                self.frag.inner_oids(f), np.int64
            )
        buf = io.BytesIO()
        np.savez(buf, **payload)
        blob = buf.getvalue()
        sha = hashlib.sha256(blob).hexdigest()
        npz = os.path.join(stage, f"rank_{self.comm.rank}.npz")
        with open(npz + ".part", "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.rename(npz + ".part", npz)
        rank_meta = {
            "rank": self.comm.rank,
            "rounds": rounds,
            "active": active,
            "sha256": sha,
            "leaves": leafmeta,
            "oid_rows": oid_rows,
            "vp": int(self.frag.vp),
        }
        with open(
            os.path.join(stage, f"rank_{self.comm.rank}.json"), "w"
        ) as fh:
            json.dump(rank_meta, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        return sha, len(blob)

    def _commit(self, stage: str, rounds: int, active: int,
                votes: np.ndarray) -> None:
        """Coordinator-side quorum check + atomic rename: every rank's
        staged shard must exist, hash to its voted sha256, and together
        cover every fragment row exactly once."""
        shards: Dict[str, Any] = {}
        leaves: Dict[str, Any] = {}
        covered: Dict[str, List[int]] = {}
        oid_cover: set = set()
        for r in range(self.comm.nprocs):
            npz = os.path.join(stage, f"rank_{r}.npz")
            try:
                with open(npz, "rb") as fh:
                    blob = fh.read()
                with open(
                    os.path.join(stage, f"rank_{r}.json")
                ) as fh:
                    rank_meta = json.load(fh)
            except OSError as e:
                raise CorruptCheckpointError(
                    f"rank {r} voted its stage complete but its shard "
                    f"is unreadable: {e}"
                ) from e
            sha = hashlib.sha256(blob).hexdigest()
            lo, hi = _sha_prefix(sha)
            if sha != rank_meta.get("sha256") or (
                lo != int(votes[r, 2]) or hi != int(votes[r, 3])
            ):
                raise CorruptCheckpointError(
                    f"rank {r} staged shard hash {sha[:12]}… does not "
                    "match its vote/manifest — refusing to commit"
                )
            shards[str(r)] = {
                "sha256": sha,
                "leaves": rank_meta["leaves"],
                "oid_rows": rank_meta["oid_rows"],
            }
            oid_cover.update(rank_meta["oid_rows"])
            for k, lm in rank_meta["leaves"].items():
                prev = leaves.setdefault(
                    k, {"shape": lm["shape"], "dtype": lm["dtype"]}
                )
                if prev["shape"] != lm["shape"] or (
                    prev["dtype"] != lm["dtype"]
                ):
                    raise CorruptCheckpointError(
                        f"leaf {k!r}: rank {r} disagrees on global "
                        "shape/dtype"
                    )
                if not lm.get("replicated"):
                    covered.setdefault(k, []).extend(lm["rows"])
        every = set(range(self.frag.fnum))
        for k, rows in covered.items():
            if sorted(rows) != sorted(every):
                raise CorruptCheckpointError(
                    f"leaf {k!r}: staged rows {sorted(rows)} do not "
                    f"cover fragment rows {sorted(every)} exactly once"
                )
        if covered and oid_cover != every:
            raise CorruptCheckpointError(
                f"staged vertex maps cover rows {sorted(oid_cover)}, "
                f"not {sorted(every)}"
            )
        meta = {
            "format": CKPT_FORMAT,
            "layout": "sharded",
            "ranks": self.comm.nprocs,
            "fnum": int(self.frag.fnum),
            "vp": int(self.frag.vp),
            "rounds": rounds,
            "active": active,
            "checkpoint_every": self.checkpoint_every,
            "fingerprint": self.fingerprint,
            "query_args": self.query_args,
            "leaves": leaves,
            "shards": shards,
        }
        with open(os.path.join(stage, "meta.json"), "w") as fh:
            json.dump(meta, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        final = _step_path(self.directory, rounds)
        if os.path.exists(final):  # rollback-replay re-save
            shutil.rmtree(final, ignore_errors=True)
        os.rename(stage, final)
        self._gc()
        glog.vlog(
            1, "checkpoint: superstep %d -> %s (%d rank shards)",
            rounds, final, self.comm.nprocs,
        )

    def _gc(self) -> None:
        try:
            steps = list_checkpoints(self.directory)
        except OSError:  # pragma: no cover - listdir race
            return
        for _, path in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(path, ignore_errors=True)


# ---- restore -------------------------------------------------------------


def _read_rank_npz(step_path: str, r: str, info: Dict[str, Any]):
    npz = os.path.join(step_path, f"rank_{r}.npz")
    try:
        with open(npz, "rb") as fh:
            blob = fh.read()
    except OSError as e:
        raise CorruptCheckpointError(
            f"unreadable checkpoint shard {npz}: {e}"
        ) from e
    sha = hashlib.sha256(blob).hexdigest()
    if sha != info.get("sha256"):
        raise CorruptCheckpointError(
            f"checkpoint shard {npz} failed its integrity check "
            f"(sha256 {sha[:12]}… != recorded "
            f"{str(info.get('sha256'))[:12]}…)"
        )
    try:
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            return {k: z[k] for k in z.files}
    except (ValueError, OSError, KeyError) as e:
        raise CorruptCheckpointError(
            f"undecodable checkpoint shard {npz}: {e}"
        ) from e


def load_sharded_state(
    step_path: str, meta: Dict[str, Any]
) -> Dict[str, np.ndarray]:
    """Gather the full `[fnum, vp]` carry host-side from every rank's
    shard file — the sharded-layout `load_state`, with the same
    integrity contract (per-shard sha256 + leaf/row coverage against
    the committed manifest)."""
    manifest = meta.get("leaves", {})
    fnum = int(meta["fnum"])
    out: Dict[str, np.ndarray] = {}
    seen_rows: Dict[str, set] = {}
    for r, info in sorted(meta.get("shards", {}).items(), key=lambda
                          kv: int(kv[0])):
        arrays = _read_rank_npz(step_path, r, info)
        for k, lm in info["leaves"].items():
            if k not in arrays:
                raise CorruptCheckpointError(
                    f"rank {r} shard is missing leaf {k!r}"
                )
            a = arrays[k]
            if lm.get("replicated"):
                prev = out.get(k)
                if prev is None:
                    out[k] = a
                elif (
                    prev.shape != a.shape
                    or prev.dtype != a.dtype
                    or prev.tobytes() != a.tobytes()
                ):
                    # a "replicated" leaf must be byte-identical on
                    # every rank; divergence means the gang was not in
                    # lockstep when it staged
                    raise CorruptCheckpointError(
                        f"replicated leaf {k!r} diverges across shard "
                        f"files (rank {r} copy != earlier ranks')"
                    )
                continue
            dst = out.setdefault(
                k,
                np.empty(
                    tuple(lm["shape"]), dtype=np.dtype(lm["dtype"])
                ),
            )
            rows = lm["rows"]
            if a.shape[0] != len(rows):
                raise CorruptCheckpointError(
                    f"rank {r} leaf {k!r} block has {a.shape[0]} rows "
                    f"for manifest rows {rows}"
                )
            for i, row in enumerate(rows):
                dst[row] = a[i]
            seen_rows.setdefault(k, set()).update(rows)
    for k, rows in seen_rows.items():
        if rows != set(range(fnum)):
            raise CorruptCheckpointError(
                f"leaf {k!r}: shard files cover rows {sorted(rows)}, "
                f"not 0..{fnum - 1}"
            )
    if set(out) != set(manifest):
        raise CorruptCheckpointError(
            f"sharded checkpoint leaf set {sorted(out)} != manifest "
            f"{sorted(manifest)}"
        )
    return out


def load_shard_layout(
    step_path: str, meta: Dict[str, Any]
) -> Dict[int, np.ndarray]:
    """{fragment row: inner oids} of the checkpointed mesh, from the
    `__oids_<f>` arrays the stage phase embedded in each shard."""
    fnum = int(meta["fnum"])
    oids: Dict[int, np.ndarray] = {}
    for r, info in meta.get("shards", {}).items():
        arrays = _read_rank_npz(step_path, r, info)
        for f in info.get("oid_rows", []):
            key = f"{_OIDS_PREFIX}{f}"
            if key not in arrays:
                raise CorruptCheckpointError(
                    f"rank {r} shard is missing vertex map {key!r}"
                )
            oids[int(f)] = np.asarray(arrays[key], np.int64)
    if set(oids) != set(range(fnum)):
        raise CorruptCheckpointError(
            f"shard vertex maps cover rows {sorted(oids)}, not "
            f"0..{fnum - 1}"
        )
    return oids


class _CheckpointLayout:
    """Duck-typed stand-in for the checkpointed mesh's fragment in
    `oid_row_alignment`: fnum/vp/inner_oids/oid_to_pid rebuilt from
    the `__oids_<f>` arrays alone — the dead mesh never has to be
    reconstructed to migrate its carry."""

    def __init__(self, fnum: int, vp: int,
                 oids_by_row: Dict[int, np.ndarray]):
        self.fnum = int(fnum)
        self.vp = int(vp)
        self._oids = oids_by_row
        all_oids = (
            np.concatenate([oids_by_row[f] for f in range(self.fnum)])
            if self.fnum
            else np.zeros(0, np.int64)
        )
        all_pids = (
            np.concatenate([
                f * self.vp + np.arange(len(oids_by_row[f]), dtype=np.int64)
                for f in range(self.fnum)
            ])
            if self.fnum
            else np.zeros(0, np.int64)
        )
        order = np.argsort(all_oids, kind="stable")
        self._sorted_oids = all_oids[order]
        self._sorted_pids = all_pids[order]

    def inner_oids(self, f: int) -> np.ndarray:
        return self._oids[int(f)]

    def oid_to_pid(self, oids) -> np.ndarray:
        oids = np.asarray(oids, np.int64)
        if not len(self._sorted_oids):
            return np.full(oids.shape, -1, np.int64)
        idx = np.searchsorted(self._sorted_oids, oids)
        idx = np.minimum(idx, len(self._sorted_oids) - 1)
        hit = self._sorted_oids[idx] == oids
        return np.where(hit, self._sorted_pids[idx], -1)


def restore_resharded(
    directory: str,
    new_frag,
    expected_fingerprint: Dict[str, Any],
    *,
    base_state: Dict[str, np.ndarray],
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """(state, meta) of the newest usable **sharded** checkpoint,
    resharded onto `new_frag`'s mesh — the survivors-on-a-smaller-fnum
    restore.  Geometry in the fingerprint (GEOMETRY_KEYS) may differ;
    every other field must match, the vertex universes must be
    identical (same graph, different cut), and `base_state` supplies
    the new mesh's freshly initialised carry so padding rows keep
    their init values.  Walks newest-first like `restore_latest`:
    mismatches raise, corrupt shards fall back a superstep."""
    t0 = time.perf_counter()
    with obs.tracer().span(
        "checkpoint_restore_resharded", dir=directory
    ) as sp:
        state, meta = _restore_resharded(
            directory, new_frag, expected_fingerprint, base_state
        )
        sp.set(round=int(meta.get("rounds", -1)))
    m = obs.metrics()
    m.counter("grape_checkpoint_restores_total").inc()
    m.counter("grape_checkpoint_reshards_total").inc()
    m.histogram("grape_checkpoint_restore_seconds").observe(
        time.perf_counter() - t0
    )
    return state, meta


def _reshard_fingerprint_check(path, expected, found):
    exp = {
        k: v for k, v in expected.items() if k not in GEOMETRY_KEYS
    }
    fnd = {k: v for k, v in found.items() if k not in GEOMETRY_KEYS}
    diffs = fingerprint_mismatch(exp, fnd)
    if diffs:
        raise CheckpointMismatchError(
            f"checkpoint {path} does not match this query (beyond "
            "mesh geometry, which a reshard may change): "
            + "; ".join(diffs)
        )


def _restore_resharded(directory, new_frag, expected_fingerprint,
                       base_state):
    from libgrape_lite_tpu.fragment.mutation import oid_row_alignment

    steps = list_checkpoints(directory)
    if not steps:
        raise FileNotFoundError(
            f"no complete checkpoint under {directory!r}"
        )
    last_err: Optional[Exception] = None
    picked = None
    for rounds, path in reversed(steps):
        try:
            meta = read_meta(path)
        except CorruptCheckpointError as e:
            glog.log_info(f"skipping corrupt checkpoint {path}: {e}")
            last_err = e
            continue
        if meta.get("layout") != "sharded":
            raise CheckpointMismatchError(
                f"checkpoint {path} was written single-process (no "
                "per-rank shard files or vertex maps); resume it on "
                "its original mesh instead of resharding"
            )
        _reshard_fingerprint_check(
            path, expected_fingerprint, meta.get("fingerprint", {})
        )
        try:
            state = load_sharded_state(path, meta)
            oids = load_shard_layout(path, meta)
        except CorruptCheckpointError as e:
            glog.log_info(f"skipping corrupt checkpoint {path}: {e}")
            last_err = e
            continue
        picked = (path, meta, state, oids)
        break
    if picked is None:
        raise CorruptCheckpointError(
            f"every checkpoint under {directory!r} is corrupt; last "
            f"error: {last_err}"
        )
    path, meta, state, oids = picked
    layout = _CheckpointLayout(meta["fnum"], meta["vp"], oids)

    # same graph, different cut: the vertex universes must be
    # IDENTICAL — a missing oid means the survivors loaded a different
    # graph, and resuming would silently compute garbage
    old_u = np.sort(
        np.concatenate([oids[f] for f in range(layout.fnum)])
    )
    new_u = np.sort(np.concatenate([
        np.asarray(new_frag.inner_oids(f), np.int64)
        for f in range(new_frag.fnum)
    ]))
    if old_u.shape != new_u.shape or not np.array_equal(old_u, new_u):
        raise CheckpointMismatchError(
            f"checkpoint {path} covers {old_u.size} vertices but the "
            f"restore fragment holds {new_u.size}; the vertex "
            "universes differ — this is a different graph, not a "
            "reshard"
        )
    of, ol, nf, nl = oid_row_alignment(layout, new_frag)
    out: Dict[str, np.ndarray] = {}
    for k, v in state.items():
        if k not in base_state:
            raise CheckpointMismatchError(
                f"checkpoint carry leaf {k!r} has no counterpart in "
                "this query's carry"
            )
        b = np.array(np.asarray(base_state[k]))
        if (
            v.ndim >= 2
            and v.shape[:2] == (layout.fnum, layout.vp)
            and b.shape[:2] == (new_frag.fnum, new_frag.vp)
            and v.shape[2:] == b.shape[2:]
        ):
            b[nf, nl] = v[of, ol]
        elif v.shape == b.shape:
            b[...] = v
        else:
            raise CheckpointMismatchError(
                f"carry leaf {k!r}: cannot reshard shape "
                f"{tuple(v.shape)} onto {tuple(b.shape)}"
            )
        out[k] = b

    # re-price the partition decision for the SURVIVING mesh and
    # record it in the ledger: the checkpointed carry is 1-D edge-cut
    # layout, so a 2d/auto request during a reshard restore is a
    # recorded decline, never a silent downgrade
    from libgrape_lite_tpu.fragment.partition import (
        partition_mode, resolve_partition,
    )

    if partition_mode() != "1d":
        z = np.zeros(0, np.int64)
        resolve_partition(
            str(meta.get("fingerprint", {}).get("app", "?")),
            new_frag.fnum, z, z, z, eligible=False,
            reason=(
                "reshard restore: the checkpointed carry is 1-D "
                f"edge-cut layout (fnum {layout.fnum} -> "
                f"{new_frag.fnum}); re-partitioning mid-query would "
                "change the compiled program"
            ),
        )
    glog.log_info(
        f"resharded checkpoint {path}: fnum {layout.fnum} -> "
        f"{new_frag.fnum} (vp {layout.vp} -> {new_frag.vp}) at "
        f"superstep {int(meta['rounds'])}"
    )
    meta = dict(meta)
    meta["resharded_from"] = {
        "fnum": layout.fnum,
        "vp": layout.vp,
        "ranks": meta.get("ranks"),
    }
    return out, meta
