"""Fault-tolerance subsystem: superstep checkpoint/restore, fault
injection, and retry/backoff.

The PIE model makes a superstep boundary a consistent cut of the whole
computation (every shard has voted, no collective is in flight), so
durable fault tolerance costs one host snapshot of the query carry
pytree per cadence interval:

* `checkpoint` — `CheckpointManager` writes double-buffered, checksummed
  snapshots of the carry + round counter + config fingerprint;
  `restore_latest` walks them newest-first, rejecting fingerprint
  mismatches and skipping corrupt shards.
* `fingerprint` — the identity of a query (app, fragment content, mesh
  shape, query args, numeric config) that a checkpoint must match to be
  resumable with byte-identical results.
* `faults` — `FaultPlan`, an env/CLI-driven harness that kills the
  process at superstep k, corrupts a checkpoint shard, or clamps the
  message capacity to force the overflow-retry path; recovery is tested,
  not assumed (scripts/fault_drill.py).
* `retry` — `with_retries`, the shared exponential-backoff policy with
  typed retryable-error classification, wrapped around
  `jax.distributed.initialize` (parallel/comm_spec.py) and garc cache
  reads (fragment/loader.py).
* `distributed` — the multi-process layer (docs/FAULT_TOLERANCE.md,
  "Distributed resilience"): `ShardedCheckpointManager` writes
  per-rank shard files under a two-phase commit barrier, and
  `restore_resharded` gathers a snapshot's full carry from surviving
  shards onto a *different* mesh (reshard-on-loss).
"""

from libgrape_lite_tpu.ft.checkpoint import (
    CheckpointManager,
    CheckpointMismatchError,
    CorruptCheckpointError,
    restore_latest,
)
from libgrape_lite_tpu.ft.distributed import (
    ShardedCheckpointManager,
    load_sharded_state,
    restore_resharded,
)
from libgrape_lite_tpu.ft.faults import FaultPlan, InjectedFault, active_plan
from libgrape_lite_tpu.ft.fingerprint import compute_fingerprint
from libgrape_lite_tpu.ft.retry import (
    RetryPolicy,
    RetryableError,
    is_transient_distributed_error,
    is_transient_io_error,
    with_retries,
)

__all__ = [
    "CheckpointManager",
    "CheckpointMismatchError",
    "CorruptCheckpointError",
    "FaultPlan",
    "InjectedFault",
    "RetryPolicy",
    "RetryableError",
    "ShardedCheckpointManager",
    "active_plan",
    "compute_fingerprint",
    "is_transient_distributed_error",
    "is_transient_io_error",
    "load_sharded_state",
    "restore_latest",
    "restore_resharded",
    "with_retries",
]
