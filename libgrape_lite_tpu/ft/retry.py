"""Retry/backoff policy with typed retryable-error classification.

The reference runtime is fail-fast (one MPI_Init attempt, one fread per
cache shard); at the scale the ROADMAP targets, coordinator hiccups and
flaky network filesystems are routine, so the transient subset of those
failures gets a bounded exponential-backoff retry instead.  One policy
object serves every call site — `jax.distributed.initialize`
(parallel/comm_spec.py) and garc cache reads (fragment/loader.py) — so
backoff behavior never diverges between subsystems.

Classification is explicit: a call site passes a `retryable` predicate
(or raises `RetryableError` itself); anything the predicate rejects
propagates unchanged on the first attempt.  Retrying an error you
cannot classify is how double-initialization bugs get hidden.
"""

from __future__ import annotations

import errno
import os
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

from libgrape_lite_tpu.utils import logging as glog

#: seeds the backoff-jitter RNG so a fault drill that crosses a retry
#: is byte-reproducible (two runs with the same seed sleep the same
#: sequence); unset = wall-entropy jitter, the storm-decorrelating
#: default
RETRY_SEED_ENV = "GRAPE_RETRY_SEED"


def _default_rng() -> random.Random:
    seed = os.environ.get(RETRY_SEED_ENV, "")
    if not seed:
        return random.Random()
    try:
        return random.Random(int(seed))
    except ValueError:
        raise ValueError(
            f"{RETRY_SEED_ENV}={seed!r} is not an integer; a typo "
            "must not silently decorrelate a drill that expected "
            "deterministic backoff"
        ) from None


class RetryableError(Exception):
    """Wrap an error a caller positively knows to be transient."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with bounded jitter.

    Delay before retry i (0-based) is
    `min(base_delay * multiplier**i, max_delay)`, scaled by a uniform
    factor in [1 - jitter, 1 + jitter] (decorrelates retry storms when
    many workers lose the same coordinator at once)."""

    max_attempts: int = 3
    base_delay: float = 0.5
    multiplier: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.1

    def delay(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        d = min(self.base_delay * self.multiplier ** attempt, self.max_delay)
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)


#: initialization-path default: a failed coordinator handshake is worth
#: ~3 attempts over ~10 s before giving up the whole job
DISTRIBUTED_INIT_POLICY = RetryPolicy(max_attempts=3, base_delay=2.0)

#: cache-read default: short, cheap — the loader can always fall back
#: to rebuilding from source text
CACHE_READ_POLICY = RetryPolicy(max_attempts=3, base_delay=0.2, max_delay=2.0)


def with_retries(
    fn: Callable,
    *,
    policy: RetryPolicy = RetryPolicy(),
    retryable: Optional[Callable[[BaseException], bool]] = None,
    describe: str = "",
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
):
    """Call `fn()` under `policy`.

    An exception is retried iff it is a `RetryableError` or the
    `retryable` predicate returns True for it; everything else (and the
    final exhausted attempt) propagates unchanged."""
    if policy.max_attempts < 1:
        raise ValueError(f"max_attempts must be >= 1, got {policy.max_attempts}")
    if rng is None and policy.jitter:
        rng = _default_rng()
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classification below
            transient = isinstance(e, RetryableError) or (
                retryable is not None and retryable(e)
            )
            if not transient or attempt + 1 >= policy.max_attempts:
                raise
            d = policy.delay(attempt, rng)
            from libgrape_lite_tpu import obs

            obs.metrics().counter("grape_retry_attempts_total").inc()
            obs.tracer().instant(
                "retry", attempt=attempt + 1,
                of=describe or None, delay_s=round(d, 3),
                error=f"{type(e).__name__}: {e}",
            )
            glog.log_info(
                f"retry {attempt + 1}/{policy.max_attempts - 1}"
                f"{' of ' + describe if describe else ''} in {d:.2f}s "
                f"after {type(e).__name__}: {e}"
            )
            sleep(d)
    raise AssertionError("unreachable")  # loop always returns or raises


# ---- classifiers ---------------------------------------------------------

#: phrases jax's distributed runtime uses for contract violations (a
#: late or duplicate initialize) — never transient, never retried
LATE_INIT_PHRASES = (
    "must be called before",
    "before any JAX",
    "already initialized",
    "Distributed initialization should be called before",
)

#: phrases the coordinator client surfaces for transient transport
#: failures (gRPC status names ride through the RuntimeError text)
_TRANSIENT_DIST_PHRASES = (
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "timed out",
    "timeout",
    "connection refused",
    "connection reset",
    "failed to connect",
    "temporarily unavailable",
)


def is_late_init_error(exc: BaseException) -> bool:
    """The caller violated the initialize-before-backend contract."""
    msg = str(exc)
    return isinstance(exc, RuntimeError) and any(
        p.lower() in msg.lower() for p in LATE_INIT_PHRASES
    )


def is_transient_distributed_error(exc: BaseException) -> bool:
    """A coordinator handshake failure worth retrying."""
    if is_late_init_error(exc):
        return False
    msg = str(exc).lower()
    return isinstance(exc, (RuntimeError, ConnectionError, TimeoutError)) and (
        isinstance(exc, (ConnectionError, TimeoutError))
        or any(p.lower() in msg for p in _TRANSIENT_DIST_PHRASES)
    )


#: OSError subclasses that describe a *state* of the filesystem, not a
#: transient fault — retrying cannot change the outcome
_PERMANENT_IO = (
    FileNotFoundError,
    IsADirectoryError,
    NotADirectoryError,
    PermissionError,
)

#: errnos seen from flaky network filesystems / stale NFS handles
_TRANSIENT_ERRNOS = frozenset(
    e for e in (
        errno.EAGAIN, errno.EBUSY, errno.EIO, errno.ESTALE,
        errno.ETIMEDOUT, errno.EINTR,
    ) if e is not None
)


def is_transient_io_error(exc: BaseException) -> bool:
    """A cache-read failure worth retrying (flaky shared filesystem)."""
    if not isinstance(exc, OSError) or isinstance(exc, _PERMANENT_IO):
        return False
    return exc.errno is None or exc.errno in _TRANSIENT_ERRNOS
