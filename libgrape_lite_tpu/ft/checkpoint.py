"""Superstep checkpoint/restore.

A superstep boundary in the PIE model is a consistent cut: every shard
has voted, no collective is in flight, and the entire query is the
carry pytree + the round counter.  `CheckpointManager` snapshots that
cut at a configurable cadence:

* **async double-buffered offload** — `save_async` kicks per-leaf
  device→host DMA (`copy_to_host_async`) and hands serialization to a
  single writer thread, so the next K supersteps overlap the previous
  write; at most one write is ever in flight (the double buffer), and
  a new save waits for the previous one first.
* **atomic commit** — a checkpoint is staged in a temp directory and
  `os.rename`d into place; `meta.json` (inside the directory before
  the rename) is the completeness marker.  A kill mid-write leaves
  only a stale temp dir, never a half checkpoint.
* **corruption detection** — `meta.json` records the sha256 of
  `state.npz`; `restore_latest` walks checkpoints newest-first,
  *rejects* fingerprint mismatches (wrong app/fragment/args — resuming
  would silently compute garbage) and *skips* corrupt shards, falling
  back to the previous complete superstep.
* **retention** — the newest `keep` complete checkpoints survive
  (default 2: the one being written can never orphan the last good
  one).

Layout: `<dir>/ckpt_<rounds:08d>/{state.npz, meta.json}`.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import shutil
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import time

import numpy as np

from libgrape_lite_tpu import obs
from libgrape_lite_tpu.ft.fingerprint import fingerprint_mismatch
from libgrape_lite_tpu.utils import logging as glog

CKPT_FORMAT = 1
_STEP_RE = re.compile(r"^ckpt_(\d{8})$")


class CheckpointMismatchError(ValueError):
    """The checkpoint belongs to a different computation (app, fragment
    content, mesh shape, query args, or numeric config differ)."""


class CorruptCheckpointError(ValueError):
    """The checkpoint failed its integrity check (sha256 mismatch,
    unreadable metadata, or missing leaves)."""


def _step_path(directory: str, rounds: int) -> str:
    return os.path.join(directory, f"ckpt_{rounds:08d}")


def list_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """(rounds, path) of every *complete* checkpoint, ascending."""
    out = []
    try:
        entries = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in entries:
        m = _STEP_RE.match(name)
        path = os.path.join(directory, name)
        if m and os.path.exists(os.path.join(path, "meta.json")):
            out.append((int(m.group(1)), path))
    return sorted(out)


def read_meta(step_path: str) -> Dict[str, Any]:
    try:
        with open(os.path.join(step_path, "meta.json")) as fh:
            meta = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        raise CorruptCheckpointError(
            f"unreadable checkpoint metadata in {step_path}: {e}"
        ) from e
    if meta.get("format") != CKPT_FORMAT:
        raise CorruptCheckpointError(
            f"unsupported checkpoint format {meta.get('format')!r} "
            f"in {step_path}"
        )
    return meta


def load_state(step_path: str, meta: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Read and integrity-check one checkpoint's state leaves."""
    npz_path = os.path.join(step_path, "state.npz")
    try:
        with open(npz_path, "rb") as fh:
            blob = fh.read()
    except OSError as e:
        raise CorruptCheckpointError(
            f"unreadable checkpoint shard {npz_path}: {e}"
        ) from e
    digest = hashlib.sha256(blob).hexdigest()
    if digest != meta.get("npz_sha256"):
        raise CorruptCheckpointError(
            f"checkpoint shard {npz_path} failed its integrity check "
            f"(sha256 {digest[:12]}… != recorded "
            f"{str(meta.get('npz_sha256'))[:12]}…)"
        )
    try:
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            state = {k: z[k] for k in z.files}
    except (ValueError, OSError, KeyError) as e:
        raise CorruptCheckpointError(
            f"undecodable checkpoint shard {npz_path}: {e}"
        ) from e
    manifest = meta.get("leaves", {})
    if set(state) != set(manifest):
        raise CorruptCheckpointError(
            f"checkpoint shard {npz_path} leaf set "
            f"{sorted(state)} != manifest {sorted(manifest)}"
        )
    return state


def latest_meta(directory: str) -> Dict[str, Any]:
    """Metadata of the newest complete checkpoint (for replaying query
    args before the fragment-dependent restore).  Checkpoints with
    unreadable metadata are skipped, mirroring `restore_latest`."""
    steps = list_checkpoints(directory)
    if not steps:
        raise FileNotFoundError(
            f"no complete checkpoint under {directory!r}"
        )
    last_err: Optional[Exception] = None
    for _, path in reversed(steps):
        try:
            return read_meta(path)
        except CorruptCheckpointError as e:
            glog.log_info(f"skipping corrupt checkpoint {path}: {e}")
            last_err = e
    raise CorruptCheckpointError(
        f"every checkpoint under {directory!r} has unreadable metadata; "
        f"last error: {last_err}"
    )


def restore_latest(
    directory: str, expected_fingerprint: Dict[str, Any]
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """(state, meta) of the newest usable checkpoint.

    Fingerprint mismatches raise `CheckpointMismatchError` immediately
    (resuming a different computation is never safe); corrupt shards
    are skipped with a warning, falling back to the previous complete
    superstep."""
    t0 = time.perf_counter()
    with obs.tracer().span("checkpoint_restore", dir=directory) as sp:
        state, meta = _restore_latest(directory, expected_fingerprint)
        sp.set(round=int(meta.get("rounds", -1)))
    m = obs.metrics()
    m.counter("grape_checkpoint_restores_total").inc()
    m.histogram("grape_checkpoint_restore_seconds").observe(
        time.perf_counter() - t0
    )
    return state, meta


def _restore_latest(
    directory: str, expected_fingerprint: Dict[str, Any]
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    steps = list_checkpoints(directory)
    if not steps:
        raise FileNotFoundError(
            f"no complete checkpoint under {directory!r}"
        )
    last_err: Optional[Exception] = None
    for rounds, path in reversed(steps):
        try:
            meta = read_meta(path)
        except CorruptCheckpointError as e:
            glog.log_info(f"skipping corrupt checkpoint {path}: {e}")
            last_err = e
            continue
        found = meta.get("fingerprint", {})
        diffs = fingerprint_mismatch(expected_fingerprint, found)
        if diffs:
            raise CheckpointMismatchError(
                f"checkpoint {path} does not match this query: "
                + "; ".join(diffs)
            )
        try:
            if meta.get("layout") == "sharded":
                # multi-process lineage (ft/distributed.py): gather
                # the full carry from the per-rank shard files; the
                # fingerprint check above already proved same-mesh
                from libgrape_lite_tpu.ft.distributed import (
                    load_sharded_state,
                )

                state = load_sharded_state(path, meta)
            else:
                state = load_state(path, meta)
        except CorruptCheckpointError as e:
            glog.log_info(f"skipping corrupt checkpoint {path}: {e}")
            last_err = e
            continue
        return state, meta
    raise CorruptCheckpointError(
        f"every checkpoint under {directory!r} is corrupt; last error: "
        f"{last_err}"
    )


class CheckpointManager:
    """Writes double-buffered superstep checkpoints for one query."""

    def __init__(
        self,
        directory: str,
        *,
        fingerprint: Dict[str, Any],
        query_args: Dict[str, Any],
        checkpoint_every: int,
        keep: int = 2,
        fresh_start: bool = False,
    ):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = directory
        self.fingerprint = fingerprint
        self.query_args = query_args
        self.checkpoint_every = checkpoint_every
        self.keep = keep
        os.makedirs(directory, exist_ok=True)
        # a kill mid-write leaves a .tmp-<rounds>-<pid> staging dir
        # behind (different pid on resume, so the per-write cleanup
        # never matches it) — sweep them all here
        for name in os.listdir(directory):
            if name.startswith(".tmp-"):
                shutil.rmtree(
                    os.path.join(directory, name), ignore_errors=True
                )
        if fresh_start:
            # a NEW query (not a resume) starts a new checkpoint
            # lineage: stale higher-round checkpoints from a previous
            # run would otherwise shadow this run's fresh snapshots in
            # both _gc's round-ordered retention and restore_latest's
            # newest-first walk
            for _, path in list_checkpoints(directory):
                shutil.rmtree(path, ignore_errors=True)
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="grape-ckpt"
        )
        self._pending: Optional[Future] = None

    # ---- save ------------------------------------------------------------

    def save_async(self, state: Dict[str, Any], rounds: int, active: int):
        """Snapshot the carry at superstep `rounds` without blocking the
        superstep loop: device→host copies are kicked asynchronously and
        the serialization runs on the writer thread.  Waits only for the
        *previous* write (double buffer)."""
        with obs.tracer().span("checkpoint_save", round=int(rounds)):
            # span covers the double-buffer wait + D2H kick — the part
            # the superstep loop actually pays; the serialization cost
            # lands in the writer thread's checkpoint_write span
            self.wait()
            for v in state.values():
                # start the D2H DMA now; np.asarray on the writer
                # thread then completes an already-running transfer
                if hasattr(v, "copy_to_host_async"):
                    v.copy_to_host_async()
            snap = dict(state)
            self._pending = self._executor.submit(
                self._write, snap, int(rounds), int(active)
            )

    def wait(self) -> None:
        """Block until the in-flight write (if any) is durable;
        propagates writer-thread failures to the superstep loop."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def close(self) -> None:
        self.wait()
        self._executor.shutdown(wait=True)

    def _write(self, state: Dict[str, Any], rounds: int, active: int):
        t0 = time.perf_counter()
        with obs.tracer().span("checkpoint_write", round=rounds) as sp:
            self._write_inner(state, rounds, active, sp)
        m = obs.metrics()
        m.counter("grape_checkpoint_saves_total").inc()
        m.histogram("grape_checkpoint_save_seconds").observe(
            time.perf_counter() - t0
        )

    def _write_inner(self, state, rounds: int, active: int, sp):
        host: Dict[str, np.ndarray] = {}
        for k, v in state.items():
            a = np.asarray(v)
            if a.dtype == object:
                raise TypeError(
                    f"state leaf {k!r} has object dtype and cannot be "
                    "checkpointed without pickle (refused: a checkpoint "
                    "must never execute code on restore)"
                )
            host[k] = a
        buf = io.BytesIO()
        np.savez(buf, **host)
        blob = buf.getvalue()
        meta = {
            "format": CKPT_FORMAT,
            "rounds": rounds,
            "active": active,
            "checkpoint_every": self.checkpoint_every,
            "fingerprint": self.fingerprint,
            "query_args": self.query_args,
            "leaves": {
                k: {"shape": list(v.shape), "dtype": v.dtype.str}
                for k, v in host.items()
            },
            "npz_sha256": hashlib.sha256(blob).hexdigest(),
        }
        final = _step_path(self.directory, rounds)
        tmp = os.path.join(
            self.directory, f".tmp-{rounds}-{os.getpid()}"
        )
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        with open(os.path.join(tmp, "state.npz"), "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump(meta, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        if os.path.exists(final):  # re-checkpoint of the same round
            # (a guard rollback-replay re-saves restored rounds);
            # ignore_errors: a concurrent cleaner may have won the race
            shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)
        self._gc()
        sp.set(bytes=len(blob))
        glog.vlog(
            1, "checkpoint: superstep %d -> %s (%d bytes)",
            rounds, final, len(blob),
        )

    def _gc(self) -> None:
        """Retention sweep: keep the newest `keep` complete
        checkpoints.  Tolerant of concurrent removal — another process
        (an external cleaner, a second resume, a shared-dir race) may
        delete entries or the directory itself between the listing and
        the rmtree; retention must never take down a healthy run, so
        every step of the sweep swallows FileNotFoundError/OSError and
        moves on."""
        try:
            steps = list_checkpoints(self.directory)
        except OSError as e:  # pragma: no cover - listdir race
            glog.vlog(1, "checkpoint gc: listing failed (%s); skipping", e)
            return
        for _, path in steps[: max(0, len(steps) - self.keep)]:
            # ignore_errors: the entry may already be gone
            shutil.rmtree(path, ignore_errors=True)
