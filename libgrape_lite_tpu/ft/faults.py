"""Fault injection: recovery is tested, not assumed.

A `FaultPlan` describes the faults to inject into a query, driven by
the `GRAPE_FT_FAULTS` env var (so `scripts/fault_drill.py` can arm a
child process without code changes) or constructed directly in tests.

Spec grammar — comma-separated tokens:

    kill@K            kill the process after superstep K's checkpoint
                      is durable (os._exit; `mode=raise` raises
                      InjectedFault instead, for in-process tests)
    kill_rank@K:R     rank-targeted kill: same as kill@K but only on
                      jax.process_index() == R — the 1-of-N process
                      loss the reshard-on-loss restore drills
                      (ft/distributed.py); the same spec can arm every
                      rank of a gang and fire on exactly one
    corrupt@K         flip bytes in the newest checkpoint shard after
                      the superstep-K checkpoint lands (exercises the
                      corrupt-shard fallback on resume)
    corrupt_carry@K   overwrite a slice of the live device carry right
                      after superstep K (once, stepwise path): NaN
                      into the primary float leaf, a negative sentinel
                      into an int leaf — the guard/ self-heal drill's
                      device-state fault
    capacity=N        clamp the planned all_to_all message capacity to
                      N, forcing the overflow vote + capacity-retry
                      ladder (message_manager.plan_initial_capacity)
    mode=raise        kill via InjectedFault instead of os._exit
    exit=N            exit code for the kill (default 17)

An unknown or malformed token raises `FaultSpecError` naming the
grammar — a typo like `kil@3` must never parse to a silent no-op plan.

Example drill: `GRAPE_FT_FAULTS=kill@4` then resume from the same
checkpoint dir — the resumed run must be byte-identical to an
uninterrupted one.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from libgrape_lite_tpu.utils import logging as glog

FAULTS_ENV = "GRAPE_FT_FAULTS"
DEFAULT_KILL_EXIT_CODE = 17


class InjectedFault(RuntimeError):
    """A deliberately injected fault (mode=raise kills)."""


SPEC_GRAMMAR = (
    "kill@K, kill_rank@K:R, corrupt@K, corrupt_carry@K, capacity=N, "
    "mode=raise|exit, exit=N"
)


class FaultSpecError(ValueError):
    """A GRAPE_FT_FAULTS spec token is unknown or malformed.  Typed so
    drills can distinguish a bad spec from a genuinely injected fault;
    the message always lists the supported grammar."""

    def __init__(self, token: str, why: str):
        super().__init__(
            f"bad fault token {token!r} in {FAULTS_ENV}: {why} "
            f"(supported spec forms: {SPEC_GRAMMAR})"
        )
        self.token = token


def corrupt_file(path: str, nbytes: int = 16, offset: Optional[int] = None):
    """Flip `nbytes` bytes mid-file — a truncation-free corruption that
    only a content checksum can catch."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {path}")
    nbytes = min(nbytes, size)
    if offset is None:
        offset = max(0, size // 2 - nbytes // 2)
    with open(path, "r+b") as fh:
        fh.seek(offset)
        chunk = fh.read(nbytes)
        fh.seek(offset)
        fh.write(bytes(b ^ 0xFF for b in chunk))


@dataclass
class FaultPlan:
    kill_at_superstep: Optional[int] = None
    kill_rank_at: Optional[int] = None   # kill_rank@K:R superstep K
    kill_rank: Optional[int] = None      # kill_rank@K:R rank R
    corrupt_checkpoint_at: Optional[int] = None
    corrupt_carry_at: Optional[int] = None
    capacity_clamp: Optional[int] = None
    mode: str = "exit"  # exit | raise
    exit_code: int = DEFAULT_KILL_EXIT_CODE
    _carry_fired: bool = False  # corrupt_carry injects once per process

    @staticmethod
    def _int_of(tok: str, payload: str) -> int:
        try:
            return int(payload)
        except ValueError:
            raise FaultSpecError(
                tok, f"{payload!r} is not an integer"
            ) from None

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for tok in filter(None, (t.strip() for t in spec.split(","))):
            # longest prefixes first: corrupt@ must not swallow
            # corrupt_carry@
            if tok.startswith("corrupt_carry@"):
                plan.corrupt_carry_at = cls._int_of(
                    tok, tok[len("corrupt_carry@"):]
                )
            elif tok.startswith("kill_rank@"):
                payload = tok[len("kill_rank@"):]
                k, sep, r = payload.partition(":")
                if not sep:
                    raise FaultSpecError(
                        tok, f"{payload!r} is not K:R (missing rank)"
                    )
                plan.kill_rank_at = cls._int_of(tok, k)
                plan.kill_rank = cls._int_of(tok, r)
                if plan.kill_rank < 0:
                    raise FaultSpecError(
                        tok, f"rank {plan.kill_rank} is negative"
                    )
            elif tok.startswith("kill@"):
                plan.kill_at_superstep = cls._int_of(tok, tok[len("kill@"):])
            elif tok.startswith("corrupt@"):
                plan.corrupt_checkpoint_at = cls._int_of(
                    tok, tok[len("corrupt@"):]
                )
            elif tok.startswith("capacity="):
                plan.capacity_clamp = max(
                    1, cls._int_of(tok, tok[len("capacity="):])
                )
            elif tok.startswith("mode="):
                mode = tok[len("mode="):]
                if mode not in ("exit", "raise"):
                    raise FaultSpecError(
                        tok, f"unknown kill mode {mode!r}"
                    )
                plan.mode = mode
            elif tok.startswith("exit="):
                plan.exit_code = cls._int_of(tok, tok[len("exit="):])
            else:
                raise FaultSpecError(tok, "unknown fault kind")
        return plan

    @classmethod
    def from_env(cls, environ=None) -> "FaultPlan":
        return cls.from_spec((environ or os.environ).get(FAULTS_ENV, ""))

    def is_noop(self) -> bool:
        return (
            self.kill_at_superstep is None
            and self.kill_rank_at is None
            and self.corrupt_checkpoint_at is None
            and self.corrupt_carry_at is None
            and self.capacity_clamp is None
        )

    # ---- hook points -----------------------------------------------------

    def clamp_capacity(self, cap: int) -> int:
        """plan_initial_capacity hook: force a capacity small enough to
        overflow so the retry ladder actually runs."""
        if self.capacity_clamp is None:
            return cap
        clamped = max(1, min(cap, self.capacity_clamp))
        if clamped != cap:
            glog.log_info(
                f"fault injection: message capacity clamped "
                f"{cap} -> {clamped}"
            )
        return clamped

    def maybe_corrupt_carry(self, carry, rounds: int):
        """corrupt_carry@K hook (stepwise worker, after superstep
        `rounds` and its checkpoint save): returns `{key: corrupted
        ndarray}` for the worker to re-place on device, or None.  Fires
        once — a guard rollback-replay passes the same superstep again
        and must then run clean, so the drill can prove byte-identical
        recovery.  The corruption is a band of poisoned values in the
        primary per-vertex leaf: NaN for float carries, a negative
        sentinel for int carries — both are invariant-visible for every
        model app (guard/invariants.py)."""
        if (
            self.corrupt_carry_at is None
            or rounds != self.corrupt_carry_at
            or self._carry_fired
        ):
            return None
        import numpy as np

        # deterministic target: the first float per-vertex leaf, else
        # the first int one (sorted keys)
        key = None
        for want_float in (True, False):
            for k in sorted(carry):
                a = carry[k]
                if getattr(a, "ndim", 0) < 2:
                    continue
                kind = np.dtype(a.dtype).kind
                if (kind == "f") == want_float and kind in "fi":
                    key = k
                    break
            if key is not None:
                break
        if key is None:
            glog.log_info(
                "fault injection: corrupt_carry found no per-vertex "
                "leaf to poison; skipping"
            )
            return None
        self._carry_fired = True
        a = np.array(np.asarray(carry[key]))
        flat = a.reshape(a.shape[0], -1)
        n = min(16, flat.shape[1])
        poison = np.nan if np.dtype(a.dtype).kind == "f" else -7
        flat[0, :n] = poison
        glog.log_info(
            f"fault injection: corrupted carry leaf {key!r} after "
            f"superstep {rounds} ({n} values set to {poison!r})"
        )
        return {key: a}

    def on_superstep(self, rounds: int, manager=None) -> None:
        """Called by the stepwise worker after superstep `rounds` (and
        its checkpoint save, if any) completes."""
        if (
            self.corrupt_checkpoint_at is not None
            and rounds == self.corrupt_checkpoint_at
            and manager is not None
        ):
            from libgrape_lite_tpu.ft.checkpoint import list_checkpoints

            manager.wait()  # the shard must exist before we can maul it
            steps = list_checkpoints(manager.directory)
            if steps:
                shard = os.path.join(steps[-1][1], "state.npz")
                corrupt_file(shard)
                glog.log_info(
                    f"fault injection: corrupted checkpoint shard {shard}"
                )
        if (
            self.kill_at_superstep is not None
            and rounds == self.kill_at_superstep
        ):
            if manager is not None:
                manager.wait()  # kill only after the checkpoint is durable
            glog.log_info(
                f"fault injection: killing at superstep {rounds} "
                f"(mode={self.mode})"
            )
            if self.mode == "raise":
                raise InjectedFault(f"injected kill at superstep {rounds}")
            os._exit(self.exit_code)
        if (
            self.kill_rank_at is not None
            and rounds == self.kill_rank_at
            and self._this_rank() == self.kill_rank
        ):
            if manager is not None:
                manager.wait()  # kill only after the checkpoint is durable
            glog.log_info(
                f"fault injection: killing rank {self.kill_rank} at "
                f"superstep {rounds} (mode={self.mode})"
            )
            if self.mode == "raise":
                raise InjectedFault(
                    f"injected kill of rank {self.kill_rank} at "
                    f"superstep {rounds}"
                )
            os._exit(self.exit_code)

    @staticmethod
    def _this_rank() -> int:
        import jax

        return jax.process_index()


_NOOP = FaultPlan()


def active_plan() -> FaultPlan:
    """The env-armed plan (a no-op plan when GRAPE_FT_FAULTS is unset)."""
    spec = os.environ.get(FAULTS_ENV, "")
    if not spec:
        return _NOOP
    return FaultPlan.from_spec(spec)
