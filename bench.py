"""Benchmark driver: PageRank throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: PageRank MTEPS/chip (edges traversed per second across the 10
pull rounds, symmetrised edge count), on an RMAT-style power-law graph.

Baseline derivation (BASELINE.md): the reference GPU backend runs
PageRank on soc-LiveJournal1 (68.99M directed edges) in 24.65 ms on
8× V100 (`Performance.md:94`), i.e. 68.99e6 * 10 rounds / 0.02465 s
/ 8 chips ≈ 3500 MTEPS per chip.  vs_baseline = our MTEPS/chip / 3500.
"""

from __future__ import annotations

import json
import time

import numpy as np


BASELINE_MTEPS_PER_CHIP = 3500.0
SCALE = 20  # 2^20 vertices
EDGE_FACTOR = 16


def rmat_edges(scale: int, edge_factor: int, seed: int = 7):
    """Vectorised RMAT (a=0.57,b=0.19,c=0.19,d=0.05)."""
    n = 1 << scale
    e = n * edge_factor
    rng = np.random.default_rng(seed)
    src = np.zeros(e, dtype=np.int64)
    dst = np.zeros(e, dtype=np.int64)
    a, b, c = 0.57, 0.19, 0.19
    for bit in range(scale):
        r = rng.random(e)
        src_bit = r >= a + b
        dst_bit = ((r >= a) & (r < a + b)) | (r >= a + b + c)
        src = (src << 1) | src_bit
        dst = (dst << 1) | dst_bit
    return n, src, dst


def _backend_alive(timeout_s: int = 150) -> bool:
    """Probe the default JAX backend in a subprocess (the axon TPU
    tunnel can hang backend init indefinitely when it is down; a
    blocked C call cannot be interrupted in-process)."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; jnp.ones((8, 8)).sum().block_until_ready()"],
            timeout=timeout_s, capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    import os

    suffix = ""
    tunneled = "axon" in os.environ.get("JAX_PLATFORMS", "")
    if (
        tunneled
        and not os.environ.get("GRAPE_BENCH_NO_PROBE")
        and not _backend_alive()
    ):
        # default backend unreachable: measure on CPU and say so
        import jax

        jax.config.update("jax_platforms", "cpu")
        suffix = "_cpu_fallback"

    import jax

    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.models import PageRank
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.id_parser import IdParser
    from libgrape_lite_tpu.utils.types import LoadStrategy
    from libgrape_lite_tpu.vertex_map.idxer import SortedArrayIdxer
    from libgrape_lite_tpu.vertex_map.partitioner import SegmentedPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
    from libgrape_lite_tpu.worker.worker import Worker

    n, src, dst = rmat_edges(SCALE, EDGE_FACTOR)
    comm_spec = CommSpec(fnum=1)

    # identity vertex map (oids are already dense 0..n-1)
    class _IdentityPartitioner:
        fnum = 1
        type_name = "identity"

        def get_fnum(self):
            return 1

        def get_partition_id(self, oids):
            return np.zeros(len(oids), dtype=np.int64)

    class _IdentityIdxer:
        type_name = "identity"

        def __init__(self, size):
            self._n = size

        def get_index(self, oids):
            return np.asarray(oids, dtype=np.int64)

        def get_oid(self, lids):
            return np.asarray(lids, dtype=np.int64)

        def size(self):
            return self._n

    vm = VertexMap(_IdentityPartitioner(), [_IdentityIdxer(n)], IdParser(1, n))
    frag = ShardedEdgecutFragment.build(
        comm_spec, vm, src, dst, None,
        directed=False,
        load_strategy=LoadStrategy.kBothOutIn,
    )
    e_sym = 2 * len(src)  # undirected pull touches each edge twice per round

    rounds = 10
    app = PageRank(delta=0.85, max_round=rounds)
    worker = Worker(app, frag)

    # warmup (compile)
    worker.query(max_round=rounds)
    # timed
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        worker.query(max_round=rounds)
        dt = time.perf_counter() - t0
        best = min(best, dt)

    mteps = e_sym * rounds / best / 1e6
    print(
        json.dumps(
            {
                "metric": f"pagerank_rmat{SCALE}_mteps_per_chip{suffix}",
                "value": round(mteps, 1),
                "unit": "MTEPS/chip",
                "vs_baseline": round(mteps / BASELINE_MTEPS_PER_CHIP, 3),
            }
        )
    )

    if os.environ.get("GRAPE_BENCH_FULL"):
        # side metrics on stderr AFTER the primary line is out — a hang
        # or failure here must not cost the already-made measurement
        import sys

        from libgrape_lite_tpu.models import BFS, CDLP, WCC

        for nm, a, kw in (
            ("wcc", WCC(), {}),
            ("bfs", BFS(), {"source": 0}),
            ("cdlp", CDLP(), {"max_round": 10}),
        ):
            try:
                wk = Worker(a, frag)
                wk.query(**kw)  # compile
                t0 = time.perf_counter()
                wk.query(**kw)
                print(
                    f"[bench-extra] {nm}: {time.perf_counter() - t0:.4f}s "
                    f"rounds={wk.rounds}",
                    file=sys.stderr,
                )
            except Exception as e:  # side metrics are best-effort
                print(f"[bench-extra] {nm}: failed ({e})", file=sys.stderr)


if __name__ == "__main__":
    main()
