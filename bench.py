"""Benchmark driver: PageRank + SSSP throughput on one TPU chip.

Prints ONE JSON line.  Primary metric: PageRank MTEPS/chip (edges
traversed per second across the 10 pull rounds, symmetrised edge
count) on an RMAT-style power-law graph.  The same line carries the
second north star as a nested object under "sssp" (VERDICT r3 next
#5): SSSP MTEPS/chip = single-pass edge count / query wall-clock on
the same graph with uniform(0.1,10) weights.

The bench A/Bs the SpMV backends ITSELF (VERDICT r2 weak #1: the pack
pipeline must never hide behind an env var): on a live TPU it measures
both the XLA gather+segment_sum path and the pack-gather Pallas path,
reports the best honest number, and says which path won in the metric
name.  On the CPU fallback (dead tunnel) only the XLA path is timed —
interpret-mode Pallas at RMAT-20 is not a measurement — and the metric
says `_cpu_fallback`.  Env knobs:
  GRAPE_SPMV=xla|pack          pin one backend
  GRAPE_BENCH_SCALE=N          RMAT scale (default 20)
  GRAPE_BENCH_ASSUME_ALIVE=1   skip the probe AND trust the backend
                               (enables the pack A/B without probing)
  GRAPE_BENCH_NO_PROBE=1       skip the probe and assume DEAD (CPU
                               fallback, XLA only — the safe default
                               for probe-less smoke runs)
  GRAPE_PACK_SCAN=mxu|shift    pack segmented-scan backend (default
                               mxu: triangular-matmul prefix on the
                               matrix unit; shift: the log-stage
                               ladder, kept for A/B)

BENCH-json ledger fields (r7): `pack_ledger` carries the planner's
static op budget at bench geometry with SPLIT engine columns —
`vpu_ops_per_edge` (vector-ALU work), `mxu_elems_per_edge` (matmul
output elements of the MXU scan), `bytes_per_edge` (every shipped
stream table at its real narrowed dtype), `gather_slots_per_edge`,
`per_stage_ops_per_edge` (VPU attribution: overlay/route/flags/scan/
extract), the modeled MTEPS bracket under `modeled`, and
`ledger_recount_mismatch` (planner annotations vs the cost model's
independent recount from the shipped arrays; > 5% on either engine
column fails the bench after the measurements are printed).

BENCH-json obs fields (r8): `obs` carries the per-phase span rollup
from the in-memory tracer armed for the whole bench — `spans` maps
span name (query/peval/superstep/chunk/...) to {count, total_s,
mean_s, max_s}, `trace_id` ties the record to a GRAPE_TRACE file when
one was requested.  Every record is self-checked against
scripts/check_bench_schema.py before printing; schema drift exits 3
AFTER all measurements are out (ledger drift keeps exit 2).

BENCH-json serve fields (r9): `serve` carries the serving-runtime
throughput lane (serve/, docs/SERVING.md) — per app (sssp, bfs) and
per batch size (b1/b8/b32), queries/sec with p50/p99 latency over a
32-query single-source stream on the serve-scale RMAT twin, plus the
admission queue's batch-size histogram.  Env knobs:
GRAPE_BENCH_NO_SERVE=1 skips, GRAPE_BENCH_SERVE_SCALE /
GRAPE_BENCH_SERVE_QUERIES size the lane.

BENCH-json serve_async fields (r12): `serve_async` carries the
async-pump dispatch-window A/B (serve/pipeline.py, docs/SERVING.md
"The async pump") — `window_ab` maps w1/w4 to per-batch-size
(b1/b8/b32) points of qps/p50/p99/updates_per_s over a 32-query SSSP
stream WITH a concurrent barrier-ingested delta stream, `identical`
is the per-query byte-identity verdict W=4 vs W=1 (a break exits 2),
`overlay_recompiles` counts XLA compiles during the measured
overlay-only ingests (non-zero exits 2), `qps_win_b8` is the headline
measured ratio, and `admission_wait_ms` carries the submit->dispatch
p50/p99 of the W=4 b=8 run.  Unlike the pipeline/2-D lanes this win
is MEASURED on CPU fallback, not modeled.  Env knobs:
GRAPE_BENCH_NO_SERVE_ASYNC=1 skips, GRAPE_BENCH_SERVE_ASYNC_QUERIES /
_UPDATES size the lane (scale follows GRAPE_BENCH_SERVE_SCALE).

BENCH-json fleet fields (r13): `fleet` carries the serving-fleet
drain drill (fleet/, docs/FLEET.md) — R=2 replica sessions behind a
version-fenced least-outstanding router serving a mixed sssp+khop
stream with concurrent barrier ingest, replica 0 drained mid-run for
an offline forced repack and rejoined through its catch-up log.
`per_replica` maps r0/r1 to sustained qps with p50/p99 (the ROADMAP
target bench: qps@p99 PER REPLICA), `byte_identical` is the
per-query verdict vs the undrained R=1 run, `dropped` must be 0
(zero-downtime), and `readmit_compiles` counts XLA compiles after an
evict -> re-admit of a replica session (must be 0 — warm host
artifacts); any verdict failure exits 2.  Env knobs:
GRAPE_BENCH_NO_FLEET=1 skips, GRAPE_BENCH_FLEET_QUERIES / _UPDATES
size the lane (scale follows GRAPE_BENCH_SERVE_SCALE).

BENCH-json autopilot fields (r16): `autopilot` carries the
closed-loop drill (autopilot/, docs/AUTOPILOT.md) — the feeder's
arrival rate is calibrated to 0.8x the measured service rate and
DOUBLED a third of the way in (`rate_spec`, serve/feeder.py step
schedule); the Autoscaler must answer with >= 1 scale-up through the
zero-drop drain/rejoin/replicate machinery (`scale_ups`, `dropped`
must be 0, `byte_identical` vs the static R=1 scripted run, `p99_ok`
under GRAPE_BENCH_AUTOPILOT_P99_MS), and the result-cache sub-drill
pins a repeated source answered with ZERO XLA compiles
(`cache_hit_compiles`), a fence-bumping ingest reaping the epoch
(`cache_invalidations` > 0), and the post-ingest answer
byte-identical to a cache-less run on the same mutated graph
(`post_ingest_identical`); any verdict failure exits 2.  Env knobs:
GRAPE_BENCH_NO_AUTOPILOT=1 skips, GRAPE_BENCH_AUTOPILOT_QUERIES /
_P99_MS size the lane (scale follows GRAPE_BENCH_SERVE_SCALE).

BENCH-json telemetry fields (r15): `telemetry` carries the
observability plane's own lane (obs/, docs/OBSERVABILITY.md) — the
stats-federation census (`namespaces` registered + the
`federation_ok` self_check verdict), `scrape_ok` from a LIVE
mid-process scrape of the OpenMetrics exporter (the text must name
every federated namespace and end with `# EOF`), `stages` with the
per-stage p50/p99 latency decomposition from ServeResult.stages
(queue_wait/window_wait/dispatch/device/harvest), the SLO burn under
a generous objective, and the flight-recorder counters.  Env knobs:
GRAPE_BENCH_NO_TELEMETRY=1 skips, GRAPE_BENCH_TELEMETRY_SCALE /
_QUERIES size the lane.

BENCH-json dyn fields (r10): `dyn` carries the dynamic-graph lane
(dyn/, docs/DYNAMIC_GRAPHS.md) — `updates_per_s` ingested through
ServeSession.ingest while an SSSP query stream stays live (overlay
side-path below the repack threshold: zero replanning/recompiles),
`repack_count` / `overlay_applies`, live-query ok counts, and the
incremental-IncEval point: `inc_seeded_rounds` vs `inc_cold_rounds`
and the `inc_speedup` wall ratio of `Worker.query_incremental` seeded
from the pre-delta fixed point against a cold recompute.  Env knobs:
GRAPE_BENCH_NO_DYN=1 skips, GRAPE_BENCH_DYN_SCALE /
GRAPE_BENCH_DYN_UPDATES size the lane.

BENCH-json partition2d fields (r10): `partition2d` carries the 1-D
edge-cut vs 2-D vertex-cut A/B (fragment/partition.py, models/
vc2d.py, docs/PARTITION2D.md) on a hub-heavy RMAT at fnum 4 (k=2) —
max-tile edge count vs the raw 1-D hub fragment (the SCALE_NOTES
pathology), modeled exchange bytes under the shared ledgers,
serial-vs-2D wall, SSSP byte-identity / PageRank eps-identity
verdicts, the planner's recorded auto decision against the measured
winner, and the per-tile pack-plan ledger recount (the 5% gate).
Env knobs: GRAPE_BENCH_NO_P2D=1 skips, GRAPE_BENCH_P2D_SCALE sizes
the twin (default 12 regardless of GRAPE_BENCH_SCALE — hub
statistics under-develop below that).

BENCH-json spgemm fields (r11): `spgemm` carries the masked-SpGEMM
lane (ops/spgemm_pack.py, docs/SPGEMM.md) — LCC intersect-vs-spgemm
wall A/B at GRAPE_BENCH_SPGEMM_SCALE (default min(SCALE, 10)) with
the bit-exactness verdict, the shipped-plan ledger recount (the 5%
gate), plan-time pruning stats (items / items_per_edge over the
oriented mask edges), and the MODELED ops/edge A/B at full bench
geometry: `mxu_elems_per_edge` + `vpu_ops_per_edge` for the spgemm
pipeline vs `intersect_word_ops_per_edge` for the popcount sweep
(per mask edge; the intersect bitmap is O(N²/8) bytes at scale 20 —
physically unbuildable, which is the breadth ceiling the primitive
lifts), priced into `modeled_*_s` with the `modeled_win` verdict and
the ledger-auto decision at lane geometry.  Env knobs:
GRAPE_BENCH_NO_SPGEMM=1 skips, GRAPE_BENCH_SPGEMM_SCALE sizes the
executed A/B.

The `calibration` lane (r17, ops/calibration.py, docs/CALIBRATION.md)
re-prices a measured sample set under the ACTIVE RateProfile and
exits 2 when an explicitly installed GRAPE_RATE_PROFILE has drifted
more than 5% from measurement on any priced surface; it also reports
a fresh fit (rates, RMS residual, fallback notes) for the
pinned-vs-fitted PERF_NOTES table.  Env knobs:
GRAPE_BENCH_NO_CALIBRATION=1 skips, GRAPE_CALIBRATION_SAMPLES points
at a recorded sweep (deterministic in CI) instead of re-measuring.

Baseline derivation (BASELINE.md): the reference GPU backend runs
PageRank on soc-LiveJournal1 (68.99M directed edges) in 24.65 ms on
8× V100 (`Performance.md:94`), i.e. 68.99e6 * 10 rounds / 0.02465 s
/ 8 chips ≈ 3500 MTEPS per chip.  SSSP: 32.3 ms on the same graph
(`Performance.md:82`) ≈ 68.99e6 / 0.0323 / 8 ≈ 267 MTEPS per chip
(single-pass convention — SSSP round counts are graph-dependent, so
TEPS counts each edge once per query).  vs_baseline = ours / theirs.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

import numpy as np


BASELINE_MTEPS_PER_CHIP = 3500.0
PLAN_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "scratch", "pack_plans"
)
SSSP_BASELINE_MTEPS_PER_CHIP = 267.0
SCALE = int(os.environ.get("GRAPE_BENCH_SCALE", 20))  # 2^20 vertices
EDGE_FACTOR = 16


def rmat_edges(scale: int, edge_factor: int, seed: int = 7):
    """Vectorised RMAT (a=0.57,b=0.19,c=0.19,d=0.05)."""
    n = 1 << scale
    e = n * edge_factor
    rng = np.random.default_rng(seed)
    src = np.zeros(e, dtype=np.int64)
    dst = np.zeros(e, dtype=np.int64)
    a, b, c = 0.57, 0.19, 0.19
    for bit in range(scale):
        r = rng.random(e)
        src_bit = r >= a + b
        dst_bit = ((r >= a) & (r < a + b)) | (r >= a + b + c)
        src = (src << 1) | src_bit
        dst = (dst << 1) | dst_bit
    return n, src, dst


def _backend_alive(timeout_s: int = 150) -> bool:
    """Probe the default JAX backend in a subprocess (the axon TPU
    tunnel can hang backend init indefinitely when it is down; a
    blocked C call cannot be interrupted in-process)."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; jnp.ones((8, 8)).sum().block_until_ready()"],
            timeout=timeout_s, capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def build_bench_inputs(scale: int | None = None):
    """(n, src, dst, comm_spec, vm): the bench graph's host-side
    inputs — shared by every lane so RMAT draws and the vertex map
    stay bit-identical by construction.  Lanes that only build a
    WEIGHTED twin (the dyn lane) stop here and skip the unweighted
    shard build + device upload."""
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.id_parser import IdParser
    from libgrape_lite_tpu.vertex_map.idxer import HashMapIdxer
    from libgrape_lite_tpu.vertex_map.partitioner import (
        SegmentedPartitioner,
    )
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    n, src, dst = rmat_edges(SCALE if scale is None else scale,
                             EDGE_FACTOR)
    comm_spec = CommSpec(fnum=1)
    oids = np.arange(n, dtype=np.int64)
    part = SegmentedPartitioner(1, oids)
    vm = VertexMap(part, [HashMapIdxer(oids)], IdParser(1, n))
    return n, src, dst, comm_spec, vm


def build_bench_fragment(scale: int | None = None):
    """The bench graph + fragment, shared with scripts/seed_pack_plans.py
    so the pre-seeded plan-cache digests stay bit-identical by
    construction.  The real load path: hash-partitioned vertex map over
    the native open-addressing idxer (round 1 bypassed VertexMap with an
    identity idxer because the dict path was load-bound; the native
    table is ~30x faster, so the bench exercises the honest path).
    `scale` overrides GRAPE_BENCH_SCALE (the serve lane runs a smaller
    twin of the same construction)."""
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.utils.types import LoadStrategy

    n, src, dst, comm_spec, vm = build_bench_inputs(scale)
    frag = ShardedEdgecutFragment.build(
        comm_spec, vm, src, dst, None,
        directed=False,
        load_strategy=LoadStrategy.kBothOutIn,
    )
    return n, src, dst, comm_spec, vm, frag


def build_bench_weighted_fragment(src, dst, comm_spec, vm,
                                  retain_edge_list=False):
    """The SSSP lane's weighted twin (seed-11 uniform(0.1,10) f32) —
    also shared with the plan-cache seeder.  The dyn lane builds its
    twin with retain_edge_list=True (the repack path edits the host
    edge list)."""
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.utils.types import LoadStrategy

    rng_w = np.random.default_rng(11)
    w = rng_w.uniform(0.1, 10.0, size=len(src)).astype(np.float32)
    return ShardedEdgecutFragment.build(
        comm_spec, vm, src, dst, w,
        directed=False,
        load_strategy=LoadStrategy.kBothOutIn,
        retain_edge_list=retain_edge_list,
    )


def spgemm_lane(scale: int, bench_scale: int, ef: int) -> dict:
    """The r11 masked-SpGEMM lane (ops/spgemm_pack.py, ROADMAP 5a):
    LCC intersect-vs-spgemm wall A/B at the lane geometry with the
    bit-exactness verdict and the shipped-plan recount, plus the
    MODELED ops/edge A/B at full bench geometry (plan_only — the
    intersect bitmap is O(N^2/8) bytes there, physically unbuildable,
    which is exactly the ceiling the primitive lifts)."""
    import libgrape_lite_tpu.ops.spgemm_pack as sg
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.worker.worker import Worker

    n, src, dst, comm_spec, vm, frag = build_bench_fragment(scale)
    prev_backend = os.environ.get("GRAPE_LCC_BACKEND")

    def restore():
        if prev_backend is None:
            os.environ.pop("GRAPE_LCC_BACKEND", None)
        else:
            os.environ["GRAPE_LCC_BACKEND"] = prev_backend

    def best_of(backend: str, n_meas: int = 2):
        os.environ["GRAPE_LCC_BACKEND"] = backend
        try:
            app = APP_REGISTRY["lcc_bitmap"]()
            wk = Worker(app, frag)
            wk.query()  # compile + plan
            best = math.inf
            for _ in range(n_meas):
                t0 = time.perf_counter()
                wk.query()
                best = min(best, time.perf_counter() - t0)
            return best, wk.result_values()
        finally:
            restore()

    t_int, r_int = best_of("intersect")
    t_sp, r_sp = best_of("spgemm")
    byte_identical = bool(np.array_equal(r_int, r_sp))

    # recount gate on the EXECUTED plan's shipped streams
    scripts = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    from pack_cost_model import spgemm_recount

    plan = sg.resolve_spgemm_dispatch(frag).plan
    rec = spgemm_recount(plan)

    # modeled A/B at FULL bench geometry (plan_only: counts + ledger,
    # no stream materialization)
    from libgrape_lite_tpu.models.lcc import _lcc_chunk

    lcc_chunk = _lcc_chunk()  # the intersect model must price the
    # chunk a real query would run (GRAPE_LCC_CHUNK), not a literal
    bn, bsrc, bdst = rmat_edges(bench_scale, ef)
    bplan = sg.plan_spgemm_edges(bsrc, bdst, bn, plan_only=True)
    n_pad_b = bplan.n_pad
    ep_sym = 2 * len(bsrc)
    b_int = sg.intersect_ledger_geom(
        n_pad_b, ep_sym, ep_sym, 1, n_pad_b, lcc_chunk)
    prices = sg.price_backends(bplan.ledger, b_int)
    me = max(1, bplan.mask_edges)
    # the auto decision AT LANE GEOMETRY, recorded like any query's
    os.environ["GRAPE_LCC_BACKEND"] = "auto"
    try:
        auto_backend = sg.resolve_lcc_backend("LCC", frag,
                                              chunk=lcc_chunk)
    finally:
        restore()
    return {
        "scale": scale,
        "bench_scale": bench_scale,
        "intersect_s": round(t_int, 4),
        "spgemm_s": round(t_sp, 4),
        "byte_identical": byte_identical,
        "items": int(plan.items),
        "items_per_edge": float(plan.stats["items_per_edge"]),
        "mask_edges": int(plan.mask_edges),
        "ledger_recount_mismatch": rec["spgemm_recount_mismatch"],
        # per MASK (oriented dedup) edge, at bench geometry
        "bench_mask_edges": int(bplan.mask_edges),
        "bench_items_per_edge": float(bplan.stats["items_per_edge"]),
        "mxu_elems_per_edge": round(
            bplan.ledger["totals"]["mxu_ops"] / me, 1),
        "vpu_ops_per_edge": round(
            bplan.ledger["totals"]["vpu_ops"] / me, 1),
        "intersect_word_ops_per_edge": round(b_int["word_ops"] / me, 1),
        "modeled_spgemm_s": round(prices["t_spgemm_s"], 6),
        "modeled_intersect_s": round(prices["t_intersect_s"], 6),
        "modeled_win": bool(prices["spgemm_wins"]),
        "auto_backend": auto_backend,
    }


def pipeline_lane(scale: int) -> dict:
    """The superstep-pipelining A/B (r9, parallel/pipeline.py): serial
    vs pipelined wall on a weighted-SSSP RMAT twin at fnum>=2, with
    the byte-identity verdict, the plan's modeled hidden-exchange
    fraction and boundary-set sizes, and the cost model's independent
    overlap recount (drift gated like the op-budget ledger).

    The lane FORCES engagement (GRAPE_PIPELINE=force): the A/B is the
    point, and on small CPU-fallback twins the auto byte threshold
    would correctly decline — that gate has its own tests
    (tests/test_pipeline.py).  Runs in-process when the active backend
    already spans >=2 devices; main() re-invokes it in a forced
    2-device CPU subprocess otherwise (`bench.py --pipeline-lane N`)."""
    import jax

    from libgrape_lite_tpu import obs
    from libgrape_lite_tpu.obs import truth
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.types import LoadStrategy
    from libgrape_lite_tpu.vertex_map.partitioner import (
        SegmentedPartitioner,
    )
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
    from libgrape_lite_tpu.models import SSSP
    from libgrape_lite_tpu.worker.worker import Worker

    fnum = min(jax.device_count(), 4)
    if fnum < 2:
        raise RuntimeError("pipeline lane needs >= 2 devices")
    if not obs.armed():
        # the --pipeline-lane subprocess entrypoint skips main()'s
        # arming, and the overlap truth meter below joins the tracer's
        # measured device waits against the plan's modeled claim
        obs.configure(in_memory=True)
    n, src, dst = rmat_edges(scale, EDGE_FACTOR)
    comm_spec = CommSpec(fnum=fnum)
    oids = np.arange(n, dtype=np.int64)
    vm = VertexMap.build(oids, SegmentedPartitioner(fnum, oids))
    rng_w = np.random.default_rng(11)
    w = rng_w.uniform(0.1, 10.0, size=len(src)).astype(np.float32)
    frag = ShardedEdgecutFragment.build(
        comm_spec, vm, src, dst, w, directed=False,
        load_strategy=LoadStrategy.kBothOutIn,
    )

    def best_of(pipe: str, n_meas: int = 3):
        prev = os.environ.get("GRAPE_PIPELINE")
        os.environ["GRAPE_PIPELINE"] = pipe
        try:
            app = SSSP()
            worker = Worker(app, frag)
            worker.query(source=0)  # warm (compile + plan)
            best = float("inf")
            for _ in range(n_meas):
                t0 = time.perf_counter()
                worker.query(source=0)
                best = min(best, time.perf_counter() - t0)
            return best, worker.result_values().tobytes(), app
        finally:
            if prev is None:
                os.environ.pop("GRAPE_PIPELINE", None)
            else:
                os.environ["GRAPE_PIPELINE"] = prev

    t_serial, bytes_serial, _ = best_of("0")
    t_pipe, bytes_pipe, app = best_of("force")
    plan = getattr(app, "_pipeline", None)
    if plan is None:
        # forced and still declined: surface the recorded reason (the
        # parent gates on engaged=false — a vacuous serial-vs-serial
        # A/B must never read as a green pipeline verdict)
        from libgrape_lite_tpu.parallel.pipeline import PIPELINE_STATS

        print(
            f"[bench] pipeline: declined under force: "
            f"{PIPELINE_STATS['last_decision']}",
            file=sys.stderr,
        )
    block = {
        "scale": scale,
        "fnum": fnum,
        "app": "sssp",
        "engaged": plan is not None,
        "mode": plan.mode if plan is not None else "none",
        "plan_uid": plan.uid if plan is not None else "-",
        "serial_s": round(t_serial, 4),
        "pipelined_s": round(t_pipe, 4),
        "byte_identical": bytes_pipe == bytes_serial,
        "modeled_hidden_frac": 0.0,
        "exchange_bytes": 0,
        "boundary_vertices": 0,
        "interior_vertices": 0,
        "boundary_edges": 0,
        "interior_edges": 0,
        "overlap_recount_mismatch": 0.0,
    }
    if plan is not None:
        brief = plan.span_brief()
        for k in ("modeled_hidden_frac", "exchange_bytes",
                  "boundary_vertices", "interior_vertices",
                  "boundary_edges", "interior_edges"):
            block[k] = brief[k]
        scripts = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts")
        if scripts not in sys.path:
            sys.path.insert(0, scripts)
        from pack_cost_model import overlap_recount

        block["overlap_recount_mismatch"] = (
            overlap_recount(plan)["overlap_recount_mismatch"]
        )
    # the overlap truth meter (obs/truth.py): join the pipelined
    # queries this lane just ran against the tracer's measured
    # device waits, per plan uid — the modeled hidden_us claim is
    # reconciled here instead of shipping unaudited.  Joined rows
    # also feed the calibration harvest (GRAPE_CALIBRATE_HARVEST).
    rep = truth.truth_report(obs.history())
    block["overlap_truth"] = truth.block_brief(rep)
    truth.harvest_report(
        rep,
        pipe_brief=plan.span_brief() if plan is not None else None,
    )
    return block


def partition2d_lane(scale: int) -> dict:
    """The 1-D edge-cut vs 2-D vertex-cut A/B (r10, ROADMAP item 2;
    fragment/partition.py, models/vc2d.py, docs/PARTITION2D.md) on a
    hub-heavy RMAT at fnum 4 (k=2):

      * `hub_1d_edges` — the max 1-D shard edge count on the RAW
        degree-correlated id space: the recorded pathology
        (docs/SCALE_NOTES.md) every shard's padding pays;
      * the WALL A/B runs on the SHUFFLED id space (gen_rmat
        shuffle_perm — the honest best-case 1-D baseline, satellite
        of this PR): SSSP serial-1-D vs 2-D best-of-3, byte-identity
        of per-oid results, PageRank 1-D vs PageRankVC eps-identity;
      * the planner's recorded auto decision (modeled costs from the
        shared rate/byte ledgers) against the measured winner — walls
        within PARTITION_TIE_BAND count as agreeing with the planner:
        the model prices TPU rates, and a CPU-fallback wall split
        finer than the band is collective-dispatch noise, not signal;
      * the per-tile pack sub-plan ledger recount
        (pack_cost_model.tile_plan_recount), gated at the same 5%.
    """
    import jax

    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.fragment.partition import resolve_partition
    from libgrape_lite_tpu.fragment.vertexcut import (
        ImmutableVertexcutFragment,
    )
    from libgrape_lite_tpu.models import (
        PageRank,
        PageRankVC,
        SSSP,
        SSSPVC2D,
    )
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.types import LoadStrategy
    from libgrape_lite_tpu.vertex_map.partitioner import (
        SegmentedPartitioner,
    )
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
    from libgrape_lite_tpu.worker.worker import Worker

    fnum, k = 4, 2
    if jax.device_count() < fnum:
        raise RuntimeError("partition2d lane needs >= 4 devices")
    scripts = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    from gen_rmat import shuffle_perm
    from pack_cost_model import tile_plan_recount

    n, src_raw, dst_raw = rmat_edges(scale, EDGE_FACTOR)
    # the recorded pathology: max 1-D shard ie-edge count on the raw
    # degree-correlated ids (contiguous-range partitioner convention)
    shard_w = max(1, -(-n // fnum))
    d_sym = np.concatenate([dst_raw, src_raw])
    hub_1d = int(np.bincount(
        np.minimum(d_sym // shard_w, fnum - 1), minlength=fnum
    ).max())

    perm = shuffle_perm(n)
    src, dst = perm[src_raw], perm[dst_raw]
    rng_w = np.random.default_rng(11)
    w = rng_w.uniform(0.1, 10.0, size=len(src)).astype(np.float32)
    oids = np.arange(n, dtype=np.int64)

    comm = CommSpec(fnum=fnum)
    vm = VertexMap.build(oids, SegmentedPartitioner(fnum, oids))
    frag_1d = ShardedEdgecutFragment.build(
        comm, vm, src, dst, w, directed=False,
        load_strategy=LoadStrategy.kBothOutIn,
    )
    max_1d = int(np.bincount(
        np.minimum(np.concatenate([dst, src]) // shard_w, fnum - 1),
        minlength=fnum,
    ).max())
    frag_2d = ImmutableVertexcutFragment.build(
        comm, oids, src, dst, w, directed=False, symmetrize=True,
    )
    tiles = frag_2d.tile_stats()

    def assembled(worker, frag):
        vals = worker.result_values()
        out = np.full(n, np.nan, dtype=vals.dtype)
        for f in range(frag.fnum):
            m = frag.inner_vertices_num(f)
            if m:
                out[np.asarray(frag.inner_oids(f))] = vals[f, :m]
        return out

    def best_of(app, frag, n_meas=3, **kw):
        worker = Worker(app, frag)
        worker.query(**kw)  # warm (compile + plan)
        best = float("inf")
        for _ in range(n_meas):
            t0 = time.perf_counter()
            worker.query(**kw)
            best = min(best, time.perf_counter() - t0)
        return best, assembled(worker, frag)

    t_1d, res_1d = best_of(SSSP(), frag_1d, source=0)
    t_2d, res_2d = best_of(SSSPVC2D(), frag_2d, source=0)
    byte_identical = res_1d.tobytes() == res_2d.tobytes()

    # PageRank: sum folds regroup across tiles -> eps, not bytes (the
    # documented pipeline-SUM class of decline)
    _, pr_1d = best_of(PageRank(delta=0.85, max_round=10), frag_1d,
                       n_meas=1, max_round=10)
    frag_2d_raw = ImmutableVertexcutFragment.build(
        comm, oids, src, dst, None, directed=False,
    )
    _, pr_2d = best_of(PageRankVC(), frag_2d_raw, n_meas=1,
                       delta=0.85, max_round=10)
    # the repo's eps convention (tests/verifiers.py eps_verify, from
    # the reference's eps_check.cc): 1e-4 relative — the bench runs
    # f32 (x64 off), so f64-tight bounds would misread f32 epsilon
    # accumulation as divergence
    pr_rel = float(np.max(
        np.abs(pr_1d - pr_2d) / np.maximum(np.abs(pr_1d), 1e-300)
    ))

    decision = resolve_partition(
        "sssp", fnum, src, dst, oids, directed=False, mode="auto"
    )
    costs = decision["costs"]
    planner_choice = decision["mode"]
    measured_winner = "2d" if t_2d < t_1d else "1d"
    tie = abs(t_2d - t_1d) / max(min(t_2d, t_1d), 1e-9) \
        <= PARTITION_TIE_BAND
    decision_matches = (planner_choice == measured_winner) or tie

    # tile-plan availability and recount drift are DISTINCT verdicts:
    # a failed resolve must not masquerade as ledger drift
    disp = None
    try:
        from libgrape_lite_tpu.ops.spmv_pack import resolve_pack_dispatch

        disp = resolve_pack_dispatch(
            frag_2d, direction="ie", prefix="pk_ie_",
            with_weights=True, role=f"vc2d-k{k}",
        )
    except Exception as e:
        print(f"[bench] partition2d: tile plan failed: {e}",
              file=sys.stderr)
    recount = (
        tile_plan_recount(disp.mplan) if disp is not None
        else {"tile_recount_mismatch": 1.0}
    )

    return {
        "scale": scale,
        "fnum": fnum,
        "k": k,
        "app": "sssp",
        "hub_1d_edges": hub_1d,
        "max_1d_edges": max_1d,
        "max_tile_edges": tiles["max_tile_edges"],
        "tile_skew": tiles["tile_skew"],
        "tile_ratio_vs_hub": round(
            tiles["max_tile_edges"] / max(1, hub_1d), 4),
        "tile_bound_ok": tiles["max_tile_edges"] <= 0.5 * hub_1d,
        "exchange_bytes_1d": costs["1d"]["exchange_bytes"],
        "exchange_bytes_2d": costs["2d"]["exchange_bytes"],
        "exchange_reduced": (
            costs["2d"]["exchange_bytes"] < costs["1d"]["exchange_bytes"]
        ),
        "serial_1d_s": round(t_1d, 4),
        "vc2d_s": round(t_2d, 4),
        "sssp_byte_identical": byte_identical,
        "pagerank_max_rel_err": pr_rel,
        "pagerank_eps_identical": pr_rel < 1e-4,
        "planner_choice": planner_choice,
        "planner_t1d_s": costs["1d"]["t_round_s"],
        "planner_t2d_s": costs["2d"]["t_round_s"],
        "measured_winner": measured_winner,
        "decision_matches": decision_matches,
        "tile_plan_ok": disp is not None,
        "tile_recount_mismatch": recount["tile_recount_mismatch"],
    }


def vc2d_pipeline_lane(scale: int) -> dict:
    """The pipelined-SUMMA A/B (PR 19; parallel/pipeline.py
    VC2DPipelinePlan, models/vc2d.py inceval_pipelined): SSSP on the
    fnum 4 (k=2) vertex-cut mesh, pipelined vs unpipelined vs the 1-D
    edge-cut baseline, all three byte-compared per oid.

    Verdicts are split HONESTLY: byte-identity and the decision
    record (rate-profile label + modeled hidden-µs per round) are
    hard gates; the measured wall is reported with the backend it ran
    on — the CPU fallback dispatches collectives synchronously, so a
    CPU wall is a correctness proxy, never overlap evidence (the
    modeled TPU dividend is what `modeled_hidden_us` prices).

    Like the pipeline lane, engagement is FORCED (the auto byte floor
    would correctly decline a small CPU twin; that gate has its own
    tests)."""
    import jax

    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.fragment.vertexcut import (
        ImmutableVertexcutFragment,
    )
    from libgrape_lite_tpu.models import SSSP, SSSPVC2D
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.types import LoadStrategy
    from libgrape_lite_tpu.vertex_map.partitioner import (
        SegmentedPartitioner,
    )
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
    from libgrape_lite_tpu.worker.worker import Worker

    fnum, k = 4, 2
    if jax.device_count() < fnum:
        raise RuntimeError("vc2d_pipeline lane needs >= 4 devices")
    scripts = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    from gen_rmat import shuffle_perm

    n, src_raw, dst_raw = rmat_edges(scale, EDGE_FACTOR)
    perm = shuffle_perm(n)
    src, dst = perm[src_raw], perm[dst_raw]
    rng_w = np.random.default_rng(11)
    w = rng_w.uniform(0.1, 10.0, size=len(src)).astype(np.float32)
    oids = np.arange(n, dtype=np.int64)

    comm = CommSpec(fnum=fnum)
    vm = VertexMap.build(oids, SegmentedPartitioner(fnum, oids))
    frag_1d = ShardedEdgecutFragment.build(
        comm, vm, src, dst, w, directed=False,
        load_strategy=LoadStrategy.kBothOutIn,
    )
    frag_2d = ImmutableVertexcutFragment.build(
        comm, oids, src, dst, w, directed=False, symmetrize=True,
    )

    def assembled(worker, frag):
        vals = worker.result_values()
        out = np.full(n, np.nan, dtype=vals.dtype)
        for f in range(frag.fnum):
            m = frag.inner_vertices_num(f)
            if m:
                out[np.asarray(frag.inner_oids(f))] = vals[f, :m]
        return out

    def best_of(app_cls, frag, pipe: str, n_meas=3, **kw):
        prev = os.environ.get("GRAPE_PIPELINE")
        os.environ["GRAPE_PIPELINE"] = pipe
        try:
            worker = Worker(app_cls(), frag)
            worker.query(**kw)  # warm (compile + plan)
            best = float("inf")
            for _ in range(n_meas):
                t0 = time.perf_counter()
                worker.query(**kw)
                best = min(best, time.perf_counter() - t0)
            return best, assembled(worker, frag), worker.app
        finally:
            if prev is None:
                os.environ.pop("GRAPE_PIPELINE", None)
            else:
                os.environ["GRAPE_PIPELINE"] = prev

    t_1d, res_1d, _ = best_of(SSSP, frag_1d, "0", source=0)
    t_s2d, res_s2d, _ = best_of(SSSPVC2D, frag_2d, "0", source=0)
    t_p2d, res_p2d, app = best_of(SSSPVC2D, frag_2d, "force", source=0)
    plan = getattr(app, "_pipeline", None)
    if plan is None:
        from libgrape_lite_tpu.parallel.pipeline import PIPELINE_STATS

        print(
            f"[bench] vc2d_pipeline: declined under force: "
            f"{PIPELINE_STATS['last_decision']}",
            file=sys.stderr,
        )
    dec = plan.decision if plan is not None else {}
    brief = plan.span_brief() if plan is not None else {}
    t = plan.stats["totals"] if plan is not None else {}
    return {
        "scale": scale,
        "fnum": fnum,
        "k": k,
        "app": "sssp",
        "engaged": plan is not None,
        "phase_split": int(t.get("phase_split", 0)),
        "edge_slots": int(t.get("edge_slots", 0)),
        "exchange_bytes": plan.exchange_bytes if plan is not None else 0,
        "serial_1d_s": round(t_1d, 4),
        "serial_2d_s": round(t_s2d, 4),
        "pipelined_2d_s": round(t_p2d, 4),
        "pipelined_eq_serial_2d": (
            res_p2d.tobytes() == res_s2d.tobytes()
        ),
        "pipelined_eq_1d": res_p2d.tobytes() == res_1d.tobytes(),
        "profile": str(dec.get("profile", "")),
        "plan_uid": str(dec.get("plan_uid", "-")),
        "modeled_hidden_us": float(dec.get("modeled_hidden_us", -1.0)),
        "modeled_hidden_frac": float(
            brief.get("modeled_hidden_frac", 0.0)),
        "measured_speedup": round(t_s2d / max(t_p2d, 1e-9), 4),
        "wall_backend": str(jax.default_backend()),
        "wall_is_overlap_evidence": jax.default_backend() == "tpu",
    }


def _vc2d_pipeline_lane_subprocess(scale: int) -> dict:
    """Run the lane in a fresh CPU process with a forced 4-device host
    platform (same pattern as the partition2d lane)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--vc2d-pipeline-lane", str(scale)],
        capture_output=True, text=True, timeout=900, env=env,
    )
    if r.returncode != 0:
        raise RuntimeError(
            f"vc2d-pipeline-lane subprocess failed: "
            f"{r.stderr.strip()[-500:]}"
        )
    return json.loads(r.stdout.strip().splitlines()[-1])


def obs_gang_lane() -> dict:
    """The gang-telemetry self-drill (PR 20; obs/gang.py,
    docs/OBSERVABILITY.md "Gang-wide telemetry").  The bench is a
    single process, so the lane builds the gang in-process: two fake
    rank tracers (the constructor rank/nprocs fallback) each record a
    superstep span and one leg of a breach-vote flow, write real
    sidecars into a scratch `.gang` dir with an injected clock
    handshake (rank 1's clock deliberately skewed), and the rank-0
    assembler must merge them into one complete, aligned, monotonic
    timeline with the vote arrow crossing both rank tracks — the same
    code path `trace_report --gang` and the fault drill run.

    The second leg re-proves the PR 15 invariant at bench time: the
    fused runner's lowered HLO must be byte-identical armed vs
    disarmed (tracing is a host-side decision; gang stamping is gated
    on nprocs > 1 and must never reach the compiled program)."""
    import shutil
    import tempfile

    import jax

    from libgrape_lite_tpu import obs
    from libgrape_lite_tpu.obs import gang
    from libgrape_lite_tpu.obs.tracer import Tracer

    # -- two-rank sidecar federation ----------------------------------
    tracers = [Tracer(enabled=True, rank=r, nprocs=2) for r in (0, 1)]
    # rank 1's monotonic clock reads 2.5ms ahead of rank 0's: the
    # assembler must shift it back or the merged order interleaves
    offsets = {"0": 0, "1": -2_500_000}
    hs = {"nprocs": 2, "offsets_ns": offsets, "allgather_wall_ns": 0}
    for r, t in enumerate(tracers):
        with t.span("superstep", round=1):
            pass
        t.flow("breach_vote", flow_id=1, cat="gang-vote",
               phase="s" if r == 0 else "f", round=1)
    wd = tempfile.mkdtemp(prefix="grape_obs_gang_")
    try:
        gdir = os.path.join(wd, "trace.gang")
        for r, t in enumerate(tracers):
            gang.write_sidecar(
                tracer=t, handshake=dict(hs, rank=r),
                path=os.path.join(gdir, f"rank_{r}.json"),
                events=t.events(),
            )
        summary = gang.assemble(
            gdir, out_path=os.path.join(wd, "merged.json"))
    finally:
        shutil.rmtree(wd, ignore_errors=True)

    # -- armed-vs-disarmed fused-HLO identity -------------------------
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.models import SSSP
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.types import LoadStrategy
    from libgrape_lite_tpu.vertex_map.partitioner import (
        SegmentedPartitioner,
    )
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap
    from libgrape_lite_tpu.worker.worker import Worker

    n = 32
    src = np.arange(n - 1, dtype=np.int64)
    dst = src + 1
    wts = np.ones(n - 1, np.float32)
    oids = np.arange(n, dtype=np.int64)
    fnum = min(jax.device_count(), 2)
    vm = VertexMap.build(oids, SegmentedPartitioner(fnum, oids))
    frag = ShardedEdgecutFragment.build(
        CommSpec(fnum=fnum), vm, src, dst, wts, directed=False,
        load_strategy=LoadStrategy.kBothOutIn,
    )

    def lowered_text():
        w = Worker(SSSP(), frag)
        state = w._place_state(w.app.init_state(frag, source=0))
        eph = frozenset(getattr(w.app, "ephemeral_keys", ()) or ())
        carry = {k: v for k, v in state.items() if k not in eph}
        eph_part = {k: v for k, v in state.items() if k in eph}
        runner = w._make_runner(0)(state)
        return jax.jit(runner).lower(frag.dev, carry, eph_part).as_text()

    armed_txt = lowered_text()  # main() armed obs at the top
    obs.reset()
    disarmed_txt = lowered_text()
    # re-arm: env sinks re-resolve lazily, else back to in-memory
    if os.environ.get(obs.TRACE_ENV) or os.environ.get(obs.METRICS_ENV):
        obs.tracer()
    else:
        obs.configure(in_memory=True)

    return {
        "ranks": len(summary["ranks"]),
        "events": int(summary["events"]),
        "flow_events": int(summary["flow_events"]),
        "cross_rank_flows": int(summary["cross_rank_flows"]),
        "aligned": bool(summary["aligned"]),
        "monotonic": bool(summary["monotonic"]),
        "complete": bool(summary["complete"]),
        "hlo_identical": armed_txt == disarmed_txt,
    }


# measured walls within this band of each other count as agreeing
# with the planner's modeled choice: the model prices TPU VPU/ICI
# rates, and a CPU-fallback split finer than this is dispatch noise
PARTITION_TIE_BAND = 0.25


def _partition2d_lane_subprocess(scale: int) -> dict:
    """Run the lane in a fresh CPU process with a forced 4-device
    host platform (same pattern as the pipeline lane: the CPU-fallback
    bench holds a 1-device backend, frozen at init)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--partition2d-lane", str(scale)],
        capture_output=True, text=True, timeout=900, env=env,
    )
    if r.returncode != 0:
        raise RuntimeError(
            f"partition2d-lane subprocess failed: "
            f"{r.stderr.strip()[-500:]}"
        )
    return json.loads(r.stdout.strip().splitlines()[-1])


def _pipeline_lane_subprocess(scale: int) -> dict:
    """Run the lane in a fresh CPU process with a forced 2-device host
    platform (the CPU-fallback bench itself holds a 1-device backend,
    and the device count is frozen at backend init)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--pipeline-lane", str(scale)],
        capture_output=True, text=True, timeout=900, env=env,
    )
    if r.returncode != 0:
        raise RuntimeError(
            f"pipeline-lane subprocess failed: {r.stderr.strip()[-500:]}"
        )
    return json.loads(r.stdout.strip().splitlines()[-1])


_SCHEMA_ERRORS: list = []
_VALIDATE_RECORD = None


def _validator():
    """One-time import of the schema checker (the scripts dir goes on
    sys.path once, not per emitted record)."""
    global _VALIDATE_RECORD
    if _VALIDATE_RECORD is None:
        scripts = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts")
        if scripts not in sys.path:
            sys.path.insert(0, scripts)
        from check_bench_schema import validate_record

        _VALIDATE_RECORD = validate_record
    return _VALIDATE_RECORD


def _emit_record(record) -> None:
    """Print one BENCH json line, self-checked against the declared
    schema first (scripts/check_bench_schema.py).  A schema breach is
    loud on stderr but must never cost a measurement — the line still
    prints, and main() exits nonzero at the end instead."""
    try:
        errs = _validator()(record)
    except Exception as e:  # checker bugs must not kill the bench
        errs = [f"schema checker unavailable: {type(e).__name__}: {e}"]
    if errs:
        for err in errs:
            print(f"[bench] SCHEMA: {err}", file=sys.stderr)
        _SCHEMA_ERRORS.extend(errs)
    print(json.dumps(record), flush=True)


def main():
    suffix = ""
    # ALWAYS probe in a subprocess before touching the default backend:
    # the axon plugin registers through sitecustomize and initializes
    # even under JAX_PLATFORMS=cpu, so an env check cannot detect the
    # tunnel — and a dead tunnel hangs backend init uninterruptibly.
    # "skip the probe" and "backend known alive" are distinct requests
    # (ADVICE r3): NO_PROBE alone must not enable interpret-mode pack
    # on a dead backend.
    if os.environ.get("GRAPE_BENCH_ASSUME_ALIVE"):
        alive = True
    elif os.environ.get("GRAPE_BENCH_NO_PROBE"):
        alive = False
    else:
        alive = _backend_alive()
    if not alive:
        # default backend unreachable: measure on CPU and say so
        import jax

        jax.config.update("jax_platforms", "cpu")
        suffix = "_cpu_fallback"

    import jax  # noqa: F401 — backend init order matters

    from libgrape_lite_tpu import obs
    from libgrape_lite_tpu.models import PageRank
    from libgrape_lite_tpu.worker.worker import Worker

    # obs/: the BENCH record carries per-phase span rollups.  With
    # GRAPE_TRACE set the env arms the file-backed tracer itself (its
    # history feeds the same rollup); otherwise arm in-memory —
    # keeping any GRAPE_METRICS file sink, which alone would drop
    # drained events and leave the rollup empty.  The spans are a few
    # host events per measured query (the fused path is ONE dispatch),
    # so the rollup costs the measurement nothing observable.
    if not os.environ.get(obs.TRACE_ENV):
        obs.configure(
            in_memory=True,
            metrics_path=os.environ.get(obs.METRICS_ENV) or None,
        )

    # persist pack plans across bench invocations: a live-TPU window is
    # scarce, and re-running the O(E log E) host planner on every A/B
    # wastes minutes of it (explicit GRAPE_PACK_PLAN_CACHE wins)
    os.environ.setdefault("GRAPE_PACK_PLAN_CACHE", PLAN_CACHE_DIR)

    t_load0 = time.perf_counter()
    n, src, dst, comm_spec, vm, frag = build_bench_fragment()
    t_load = time.perf_counter() - t_load0
    e_sym = 2 * len(src)  # undirected pull touches each edge twice per round

    rounds = 10

    def measure(name: str, mode: str, app_factory, bench_frag, kwargs):
        """Time one app with the given SpMV backend pinned; returns
        (best seconds, engaged backend name) or None on failure."""
        prev = os.environ.get("GRAPE_SPMV")
        os.environ["GRAPE_SPMV"] = mode
        try:
            app = app_factory()
            worker = Worker(app, bench_frag)
            t_c0 = time.perf_counter()
            worker.query(**kwargs)  # warmup (compile + plan)
            t_compile = time.perf_counter() - t_c0
            engaged = (
                "pack" if getattr(app, "_pack", None) is not None
                else "xla"
            )
            if mode == "pack" and engaged != "pack":
                print(f"[bench] {name}: pack requested but not engaged",
                      file=sys.stderr)
                return None
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                worker.query(**kwargs)
                best = min(best, time.perf_counter() - t0)
            print(
                f"[bench] {name}: mode={mode} engaged={engaged} "
                f"best={best:.4f}s warm+compile={t_compile:.1f}s "
                f"rounds={worker.rounds}",
                file=sys.stderr,
            )
            return best, engaged
        except Exception as e:  # a failed backend must not kill the bench
            print(
                f"[bench] {name}: mode {mode} failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )
            return None
        finally:
            if prev is None:
                os.environ.pop("GRAPE_SPMV", None)
            else:
                os.environ["GRAPE_SPMV"] = prev

    # the A/B: both backends on a live TPU; XLA only on the CPU
    # fallback (interpret-mode Pallas is not a measurement) — unless
    # GRAPE_SPMV pins a single path explicitly
    forced = os.environ.get("GRAPE_SPMV")
    if forced:
        modes = [forced]
    elif alive:
        modes = ["xla", "pack"]
    else:
        modes = ["xla"]

    def ab(name, app_factory, bench_frag, kwargs):
        results = {}
        for mode in modes:
            r = measure(name, mode, app_factory, bench_frag, kwargs)
            if r is not None:
                results[mode] = r
        if not results:
            return None
        return min(results.values(), key=lambda r: r[0])

    pr = ab("pagerank", lambda: PageRank(delta=0.85, max_round=rounds),
            frag, {"max_round": rounds})
    if pr is None:
        raise RuntimeError("no SpMV backend produced a measurement")
    best_time, winner = pr
    mteps = e_sym * rounds / best_time / 1e6
    tag = f"_{winner}" if len(modes) > 1 or forced else ""
    record = {
        "metric": f"pagerank_rmat{SCALE}_mteps_per_chip{tag}{suffix}",
        "value": round(mteps, 1),
        "unit": "MTEPS/chip",
        "vs_baseline": round(mteps / BASELINE_MTEPS_PER_CHIP, 3),
        # occupancy context (VERDICT r4 weak #2): fallback numbers on a
        # shared 1-core box wobble with box load; a reader comparing
        # rounds must be able to see whether the box was contended
        "load_avg_1m": round(os.getloadavg()[0], 2),
    }

    # the primary measurement goes out BEFORE the SSSP lane: a chip
    # death mid-SSSP (the documented r1/r2 failure mode) hangs
    # uninterruptibly, and the driver reads the LAST JSON line — so a
    # completed SSSP lane supersedes this line with the combined record
    _emit_record(record)

    # second north star: SSSP on the same graph, weighted (best-effort —
    # a failure must not cost the PageRank measurement)
    try:
        from libgrape_lite_tpu.models import APP_REGISTRY
        from libgrape_lite_tpu.models.sssp_select import select_sssp_variant

        frag_w = build_bench_weighted_fragment(src, dst, comm_spec, vm)
        # probe-and-pick (VERDICT r4 next #4): the bench runs whichever
        # variant the evidence picks for this graph — RMAT is
        # low-diameter, so this resolves to the dense pull, but the
        # decision is now measured, not assumed
        picked, reason = select_sssp_variant(frag_w, 0)
        print(f"[bench] sssp_select -> {picked}: {reason}", file=sys.stderr)
        ss = ab("sssp", APP_REGISTRY[picked], frag_w, {"source": 0})
        if ss is not None:
            ss_time, ss_winner = ss
            ss_mteps = e_sym / ss_time / 1e6
            ss_tag = f"_{ss_winner}" if len(modes) > 1 or forced else ""
            record["sssp"] = {
                "metric":
                    f"sssp_rmat{SCALE}_mteps_per_chip{ss_tag}{suffix}",
                "value": round(ss_mteps, 1),
                "unit": "MTEPS/chip",
                "variant": picked,
                # r6: the dense pull pre-masks the weight stream at
                # init (one gather pass/round instead of gather +
                # mask-select); GRAPE_SSSP_FUSE=0 reverts for A/B.
                # Only the dense-pull variant on the XLA backend HAS
                # the fused form (sssp_delta never does; the pack
                # backend bakes weights into the plan instead)
                "fused_pull": (
                    picked == "sssp" and ss_winner == "xla"
                    and os.environ.get(
                        "GRAPE_SSSP_FUSE", "1") not in ("0", "")
                ),
                "vs_baseline":
                    round(ss_mteps / SSSP_BASELINE_MTEPS_PER_CHIP, 3),
            }
    except Exception as e:
        print(f"[bench] sssp lane failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    else:
        if "sssp" in record:
            _emit_record(record)

    # guard overhead lane (r7): guards OFF take literally the same code
    # path as the primary measurement above (Worker.query consults only
    # a host-side env read before compiling the untouched fused runner
    # — tests/test_guard.py pins trace identity), so the off-delta is
    # re-measured here only to put a number next to the structural
    # claim; guards ON pay chunked-fused execution + a probe per chunk,
    # and that overhead is the honest cost of online validation.
    # GRAPE_BENCH_NO_GUARD=1 skips the lane.
    if not os.environ.get("GRAPE_BENCH_NO_GUARD"):
        try:
            from libgrape_lite_tpu.guard import GuardConfig

            def best_of(worker, n=3, **kw):
                b = float("inf")
                for _ in range(n):
                    t0 = time.perf_counter()
                    worker.query(**kw)
                    b = min(b, time.perf_counter() - t0)
                return b

            w_off = Worker(PageRank(delta=0.85, max_round=rounds), frag)
            w_off.query(max_round=rounds)  # warm
            t_off = best_of(w_off, max_round=rounds)
            cfg = GuardConfig(policy="warn", every=2)
            w_on = Worker(PageRank(delta=0.85, max_round=rounds), frag)
            w_on.query(max_round=rounds, guard=cfg)  # warm
            t_on = best_of(w_on, max_round=rounds, guard=cfg)
            record["guard"] = {
                # guards-off IS the fused fast path (trace-identical by
                # construction; pinned in tests/test_guard.py) — the
                # number is here so a reader sees the same wall clock,
                # not a near-zero delta to squint at
                "fused_off_s": round(t_off, 4),
                "guarded_s": round(t_on, 4),
                "guarded_overhead_pct": round((t_on / t_off - 1) * 100, 1),
                "policy": cfg.policy,
                "cadence": cfg.every,
                "probes": (w_on.guard_report or {}).get("probes", 0),
            }
            _emit_record(record)
            print(
                f"[bench] guard: off={t_off:.4f}s on={t_on:.4f}s "
                f"(+{record['guard']['guarded_overhead_pct']}%)",
                file=sys.stderr,
            )
        except Exception as e:  # the guard lane must not cost the bench
            print(
                f"[bench] guard lane failed: {type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # serving throughput lane (r9, ROADMAP item 1): queries/sec at
    # fixed p99 next to MTEPS.  A session pins the graph once; a
    # 32-query single-source stream runs at batch sizes {1, 8, 32} for
    # SSSP and BFS — b=1 is today's one-query-at-a-time dispatch
    # sequence, larger batches share one vmapped dispatch, and the qps
    # ratio IS the amortization win the obs traces predicted (dispatch
    # overhead dominates small queries).  Point queries are a
    # small-graph story, so the lane runs its own smaller RMAT twin
    # (GRAPE_BENCH_SERVE_SCALE, default min(SCALE, 12)): a serving
    # fleet shards many resident graphs rather than one planet-scale
    # one, and a b=32 lane at RMAT-20 would not fit the CPU-fallback
    # heap.  GRAPE_BENCH_NO_SERVE=1 skips.
    if not os.environ.get("GRAPE_BENCH_NO_SERVE"):
        try:
            from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

            serve_scale = int(os.environ.get(
                "GRAPE_BENCH_SERVE_SCALE", min(SCALE, 12)))
            n_q = int(os.environ.get("GRAPE_BENCH_SERVE_QUERIES", 32))
            sn, ssrc, sdst, scomm, svm, sfrag = build_bench_fragment(
                serve_scale
            )
            sfrag_w = build_bench_weighted_fragment(
                ssrc, sdst, scomm, svm
            )
            rng_q = np.random.default_rng(5)
            sources = [int(x) for x in rng_q.integers(0, sn, size=n_q)]
            serve_block = {
                "scale": serve_scale, "queries_per_app": n_q,
            }
            hist: dict = {}
            for app_key, sf in (("sssp", sfrag_w), ("bfs", sfrag)):
                app_block = {}
                for bsz in (1, 8, 32):
                    sess = ServeSession(
                        sf, policy=BatchPolicy(max_batch=bsz)
                    )
                    # warm: compile this (app, batch-shape) runner once
                    for s in sources[:min(bsz, n_q)]:
                        sess.submit(app_key, {"source": s})
                    sess.drain()
                    sess.queue.batch_hist = {}  # hist counts measured work
                    t0 = time.perf_counter()
                    for s in sources:
                        sess.submit(app_key, {"source": s})
                    res = sess.drain()
                    wall = time.perf_counter() - t0
                    lat = sorted(r.latency_s for r in res)
                    point = {
                        "qps": round(len(res) / wall, 2),
                        "p50_ms": round(1e3 * lat[len(lat) // 2], 3),
                        "p99_ms": round(1e3 * lat[
                            min(len(lat) - 1, int(len(lat) * 0.99))
                        ], 3),
                        "n": len(res),
                        "ok": sum(1 for r in res if r.ok),
                    }
                    app_block[f"b{bsz}"] = point
                    for k, v in sess.queue.batch_hist.items():
                        hist[k] = hist.get(k, 0) + v
                    print(
                        f"[bench] serve {app_key} b{bsz}: "
                        f"{point['qps']} q/s p99={point['p99_ms']}ms "
                        f"({point['ok']}/{point['n']} ok)",
                        file=sys.stderr,
                    )
                serve_block[app_key] = app_block
            serve_block["batch_hist"] = {
                str(k): v for k, v in sorted(hist.items())
            }
            record["serve"] = serve_block
            _emit_record(record)
        except Exception as e:  # the serve lane must not cost the bench
            print(f"[bench] serve lane failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    # telemetry lane (r15, obs/, docs/OBSERVABILITY.md): the stats-
    # federation census (registered namespaces + self_check verdict),
    # a LIVE scrape of the OpenMetrics exporter taken mid-serve (the
    # text must name every federated namespace), the per-stage
    # latency decomposition from ServeResult.stages, the SLO burn
    # under a generous objective, and the flight-recorder counters.
    # GRAPE_BENCH_NO_TELEMETRY=1 skips.
    if not os.environ.get("GRAPE_BENCH_NO_TELEMETRY"):
        try:
            import urllib.request

            from libgrape_lite_tpu.obs import exporter, federation, slo
            from libgrape_lite_tpu.obs.recorder import REC_STATS
            from libgrape_lite_tpu.serve import BatchPolicy, ServeSession
            from libgrape_lite_tpu.serve.queue import latency_summary_ms

            tel_scale = int(os.environ.get(
                "GRAPE_BENCH_TELEMETRY_SCALE", min(SCALE, 10)))
            n_q = int(os.environ.get(
                "GRAPE_BENCH_TELEMETRY_QUERIES", 16))
            tn, tsrc, tdst, tcomm, tvm, tfrag = build_bench_fragment(
                tel_scale
            )
            # a generous objective: observed counters move per query,
            # burn stays 0 unless something is genuinely pathological
            slo.configure("*=60000")
            exp = exporter.start_exporter(0)
            sess = ServeSession(tfrag, policy=BatchPolicy(max_batch=8))
            pump = sess.async_pump(window=2)
            rng_t = np.random.default_rng(6)
            for s in (int(x) for x in rng_t.integers(0, tn, size=n_q)):
                sess.submit("bfs", {"source": s})
            results = []
            while sess.queue.pending() or pump.inflight():
                results.extend(pump.pump(force=True, block=True))
            results.extend(pump.drain())
            # the live mid-process scrape: every federated namespace
            # must be named in the OpenMetrics text
            scrape_ok = False
            try:
                with urllib.request.urlopen(
                    exp.url + "/metrics", timeout=5
                ) as resp:
                    text = resp.read().decode("utf-8")
                scrape_ok = all(
                    f'grape_stats_registry{{namespace="{ns}"}}' in text
                    for ns in federation.registered()
                ) and text.endswith("# EOF\n")
            finally:
                exporter.stop_exporter()
            stage_lists: dict = {}
            for r in results:
                for k, v in (r.stages or {}).items():
                    stage_lists.setdefault(k, []).append(v / 1e6)
            stages_block = {}
            for k, v in sorted(stage_lists.items()):
                s = latency_summary_ms(v)
                stages_block[k] = {"p50": s["p50_ms"],
                                   "p99": s["p99_ms"]}
            fed_errors = federation.self_check()
            slo_snap = slo.SLO_STATS.snapshot()
            telemetry_block = {
                "namespaces": len(federation.registered()),
                "federation_ok": not fed_errors,
                "scrape_ok": scrape_ok,
                "stages": stages_block,
                "slo_observed": int(slo_snap["observed"]),
                "slo_breaches": int(slo_snap["breaches"]),
                "slo_max_burn": float(slo_snap["max_burn"]),
                "recorder_recorded": int(REC_STATS["recorded"]),
                "recorder_dropped": int(REC_STATS["dropped"]),
                "recorder_triggers": int(REC_STATS["triggers"]),
            }
            print(
                f"[bench] telemetry: {telemetry_block['namespaces']} "
                f"namespace(s), scrape_ok={scrape_ok}, "
                f"federation_ok={telemetry_block['federation_ok']}, "
                f"stages={sorted(stages_block)}",
                file=sys.stderr,
            )
            record["telemetry"] = telemetry_block
            _emit_record(record)
        except Exception as e:  # the telemetry lane must not cost the bench
            print(
                f"[bench] telemetry lane failed: {type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # async-pump serving lane (r12, ROADMAP item 2a): the dispatch-
    # window A/B — W in {1, 4} at batch sizes {1, 8, 32} over the
    # serve-scale twin WITH a concurrent barrier-ingested delta stream
    # (serve/pipeline.py, docs/SERVING.md).  Unlike the modeled
    # pipeline/2-D wins, this one is MEASURED even on CPU fallback:
    # the window overlaps host admission/state-build/extraction with
    # device execution (JAX async dispatch runs XLA on its own
    # threads), so qps@p99 moves without a TPU in the loop.  Gated on
    # per-query byte identity W=4 vs W=1 (exit 2 on a break) and on
    # zero XLA compiles during the measured overlay-only ingests.
    # GRAPE_BENCH_NO_SERVE_ASYNC=1 skips;
    # GRAPE_BENCH_SERVE_ASYNC_QUERIES / _UPDATES size the lane.
    serve_async_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_SERVE_ASYNC"):
        try:
            from libgrape_lite_tpu.analysis import compile_events
            from libgrape_lite_tpu.dyn import RepackPolicy
            from libgrape_lite_tpu.serve import (
                PUMP_STATS,
                BatchPolicy,
                ServeSession,
            )

            sys.path.insert(
                0, os.path.join(os.path.dirname(os.path.abspath(
                    __file__)), "scripts"))
            from gen_rmat import delta_edges

            sa_scale = int(os.environ.get(
                "GRAPE_BENCH_SERVE_SCALE", min(SCALE, 12)))
            # 64 queries = 8 b8-batches: the pipeline needs depth to
            # amortize its boundary (the first batch's prepare and the
            # last batch's extraction overlap nothing)
            sa_q = int(os.environ.get(
                "GRAPE_BENCH_SERVE_ASYNC_QUERIES", 64))
            sa_upd = int(os.environ.get(
                "GRAPE_BENCH_SERVE_ASYNC_UPDATES", 256))
            an, asrc, adst, acomm, avm = build_bench_inputs(sa_scale)
            rng_q = np.random.default_rng(5)
            sa_sources = [
                int(x) for x in rng_q.integers(0, an, size=sa_q)
            ]
            u_src, u_dst = delta_edges(sa_scale, sa_upd, seed=37)
            rng_uw = np.random.default_rng(41)
            u_w = rng_uw.uniform(0.1, 10.0, sa_upd)
            sa_ops = [("a", int(s), int(d), float(x)) for s, d, x in
                      zip(u_src, u_dst, u_w)]
            # two ingest groups: at b=8 each group holds MULTIPLE
            # batches, so the window genuinely overlaps between
            # barriers (one batch per group would let the barrier
            # serialise the window and measure nothing)
            n_groups = 2
            sa_chunk = -(-sa_upd // n_groups)
            sa_group = -(-sa_q // n_groups)

            def serve_async_run(window, bsz):
                """One measured (W, b) run: sa_q queries dispatched in
                n_groups groups with a barrier-ingested delta chunk
                between groups (ingest points pinned by DISPATCH
                count, so the batch <-> graph-version interleave is
                identical at every window depth).  Warm covers every
                shape the run touches: the batched runner pre- and
                post-overlay and a chunk-sized overlay apply.  Returns
                (point, per-query digests, measured XLA compiles)."""
                afrag = build_bench_weighted_fragment(
                    asrc, adst, acomm, avm, retain_edge_list=True
                )
                sess = ServeSession(
                    afrag, policy=BatchPolicy(max_batch=bsz),
                    dyn=RepackPolicy(capacity=max(4096, 4 * sa_upd)),
                )
                pump = sess.async_pump(window=window)
                for s in sa_sources[:min(bsz, sa_q)]:
                    sess.submit("sssp", {"source": s})
                pump.drain()
                pump.ingest(sa_ops[:sa_chunk])  # warm the overlay shape
                for s in sa_sources[:min(bsz, sa_q)]:
                    sess.submit("sssp", {"source": s})
                pump.drain()
                # one measured pass — the caller interleaves (w1, w4)
                # reps and keeps the best, so de-noising lives where
                # the drift does
                sess.queue.batch_hist = {}
                sess.queue.admission_waits = []
                oi = sa_chunk
                n_meas_ops = len(sa_ops) - oi
                t0 = time.perf_counter()
                with compile_events() as ev:
                    reqs = [
                        sess.submit("sssp", {"source": s})
                        for s in sa_sources
                    ]
                    while (sess.queue.pending() or pump.inflight()
                           or oi < len(sa_ops)):
                        target = pump.dispatched_queries + sa_group
                        while (sess.queue.pending()
                               and pump.dispatched_queries < target):
                            pump.pump(force=True, block=True,
                                      max_dispatch=target)
                        if oi < len(sa_ops):
                            pump.ingest(sa_ops[oi:oi + sa_chunk])
                            oi += sa_chunk
                        else:
                            pump.drain()
                wall = time.perf_counter() - t0
                res = [q.result for q in reqs]
                lat = sorted(r.latency_s for r in res)
                digests = [
                    r.values.tobytes() if r.ok else b"" for r in res
                ]
                point = {
                    "qps": round(len(res) / wall, 2),
                    "p50_ms": round(1e3 * lat[len(lat) // 2], 3),
                    "p99_ms": round(1e3 * lat[
                        min(len(lat) - 1, int(len(lat) * 0.99))
                    ], 3),
                    "n": len(res),
                    "ok": sum(1 for r in res if r.ok),
                    "updates_per_s": (
                        round(n_meas_ops / wall, 1) if wall > 0
                        else 0.0
                    ),
                }
                waits = sess.queue.admission_wait_summary()
                pump.close()
                return point, digests, ev.compiles, waits

            PUMP_STATS.reset()
            window_ab: dict = {"w1": {}, "w4": {}}
            digests_ab: dict = {}
            sa_compiles = 0
            sa_waits = {"p50_ms": 0.0, "p99_ms": 0.0}
            # interleaved (w1, w4, w1, w4) reps per batch size, best
            # qps kept per arm: process-global warmth (disk plan
            # cache, XLA code paths, allocator arenas) drifts run to
            # run, and a one-shot A/B would attribute that drift to
            # the window — alternation cancels it (digests compare
            # across the FIRST rep of each arm, which see identical
            # fresh sessions)
            for bsz in (1, 8, 32):
                for rep in range(2):
                    for window in (1, 4):
                        point, digs, compiles, waits = serve_async_run(
                            window, bsz
                        )
                        prev = window_ab[f"w{window}"].get(f"b{bsz}")
                        if prev is None or point["qps"] > prev["qps"]:
                            window_ab[f"w{window}"][f"b{bsz}"] = point
                        if rep == 0:
                            digests_ab[(window, bsz)] = digs
                        sa_compiles += compiles
                        if window == 4 and bsz == 8:
                            sa_waits = waits
                        print(
                            f"[bench] serve_async w{window} b{bsz} "
                            f"rep{rep}: {point['qps']} q/s "
                            f"p99={point['p99_ms']}ms "
                            f"{point['updates_per_s']} upd/s "
                            f"({point['ok']}/{point['n']} ok, "
                            f"{compiles} compiles)",
                            file=sys.stderr,
                        )
            identical = all(
                digests_ab[(1, bsz)] == digests_ab[(4, bsz)]
                for bsz in (1, 8, 32)
            )
            w1b8 = window_ab["w1"]["b8"]["qps"]
            w4b8 = window_ab["w4"]["b8"]["qps"]
            serve_async_block = {
                "scale": sa_scale, "app": "sssp", "queries": sa_q,
                "window_ab": window_ab,
                "identical": identical,
                "qps_win_b8": round(w4b8 / w1b8, 3) if w1b8 else 0.0,
                "updates_per_chunk": sa_chunk,
                "overlay_recompiles": sa_compiles,
                "admission_wait_ms": {
                    "p50": sa_waits["p50_ms"], "p99": sa_waits["p99_ms"],
                },
                "declines": PUMP_STATS.snapshot()["declines"],
            }
            record["serve_async"] = serve_async_block
            _emit_record(record)
            print(
                f"[bench] serve_async: b8 qps w4/w1 = "
                f"{serve_async_block['qps_win_b8']}x, identical="
                f"{identical}, overlay_recompiles={sa_compiles}",
                file=sys.stderr,
            )
            if not identical:
                serve_async_mismatch = (
                    "W=4 results diverged from W=1 — the dispatch "
                    "window changed answers"
                )
            elif sa_compiles:
                serve_async_mismatch = (
                    f"{sa_compiles} XLA compile(s) during measured "
                    "overlay-only ingests — the zero-recompile "
                    "contract broke under the pump"
                )
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] serve_async lane failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # dynamic-graph lane (r10, ROADMAP item 4): updates/sec ingested
    # while a query stream stays live, plus the incremental-vs-cold
    # comparison (dyn/, docs/DYNAMIC_GRAPHS.md).  A dyn-enabled
    # session pins a weighted RMAT twin; a reproducible additive
    # update stream (scripts/gen_rmat.py delta_edges — the SAME
    # distribution the --delta flag scripts) ingests in chunks between
    # 4-query groups, riding the overlay below the repack threshold so
    # the live queries recompile nothing.  The incremental point:
    # Worker.query_incremental seeded from the pre-delta fixed point
    # vs a cold recompute on the mutated view, wall and rounds.
    # GRAPE_BENCH_NO_DYN=1 skips; GRAPE_BENCH_DYN_SCALE /
    # GRAPE_BENCH_DYN_UPDATES size the lane.
    if not os.environ.get("GRAPE_BENCH_NO_DYN"):
        try:
            from libgrape_lite_tpu.dyn import DynGraph, RepackPolicy
            from libgrape_lite_tpu.models import SSSP
            from libgrape_lite_tpu.serve import (
                BatchPolicy,
                ServeSession,
            )

            sys.path.insert(
                0, os.path.join(os.path.dirname(os.path.abspath(
                    __file__)), "scripts"))
            from gen_rmat import delta_edges

            dyn_scale = int(os.environ.get(
                "GRAPE_BENCH_DYN_SCALE", min(SCALE, 12)))
            n_upd = int(os.environ.get(
                "GRAPE_BENCH_DYN_UPDATES", 1024))
            dn, dsrc, ddst, dcomm, dvm = build_bench_inputs(dyn_scale)
            dfrag = build_bench_weighted_fragment(
                dsrc, ddst, dcomm, dvm, retain_edge_list=True
            )
            u_src, u_dst = delta_edges(dyn_scale, n_upd, seed=29)
            rng_uw = np.random.default_rng(31)
            u_w = rng_uw.uniform(0.1, 10.0, n_upd)
            ops = [("a", int(s), int(d), float(x)) for s, d, x in
                   zip(u_src, u_dst, u_w)]
            # capacity sized to hold the full stream as an overlay;
            # the ratio threshold still fires if the stream is large
            # relative to the graph (a counted repack, reported below)
            sess = ServeSession(
                dfrag, policy=BatchPolicy(max_batch=8),
                dyn=RepackPolicy(capacity=max(4096, 2 * n_upd)),
            )
            rng_q = np.random.default_rng(17)
            warm_sources = [int(x) for x in rng_q.integers(0, dn, 8)]
            for s in warm_sources:
                sess.submit("sssp", {"source": s})
            sess.drain()  # warm the batched runner shapes

            chunk = max(1, n_upd // 8)
            q_ok = q_n = 0
            t0 = time.perf_counter()
            oi = 0
            while oi < len(ops):
                for s in rng_q.integers(0, dn, 4):
                    sess.submit("sssp", {"source": int(s)})
                res = sess.drain()
                q_n += len(res)
                q_ok += sum(1 for r in res if r.ok)
                sess.ingest(ops[oi:oi + chunk])
                oi += chunk
            wall = time.perf_counter() - t0
            dyn_block = {
                "updates_per_s": round(n_upd / wall, 1),
                "ingested": sess.stats["ingested_ops"],
                "repack_count": sess.stats["repacks"],
                "overlay_applies": sess.stats["overlay_applies"],
                "queries": q_n,
                "queries_ok": q_ok,
            }
            print(
                f"[bench] dyn: {dyn_block['updates_per_s']} upd/s "
                f"({n_upd} ingested, {q_n} queries live, "
                f"{dyn_block['repack_count']} repack(s))",
                file=sys.stderr,
            )

            # incremental-vs-cold: seed from the pre-delta fixed point
            from libgrape_lite_tpu.worker.worker import Worker

            base = build_bench_weighted_fragment(
                dsrc, ddst, dcomm, dvm, retain_edge_list=True
            )
            w_prev = Worker(SSSP(), base)
            prev = w_prev.query(source=0)
            dg = DynGraph(base, RepackPolicy(
                capacity=max(4096, 2 * n_upd)))
            small = ops[:max(1, n_upd // 16)]
            # the report's delta snapshot stays valid even if the
            # apply repacked (summary() would then be empty)
            inc_delta = dg.ingest(small)["delta"]
            w_cold = Worker(SSSP(), dg.fragment)
            w_cold.query(source=0)  # warm (compiles the overlay shape)
            tc = time.perf_counter()
            w_cold.query(source=0)
            t_cold = time.perf_counter() - tc
            # prev came from a DIFFERENT worker on the pre-ingest
            # fragment: name it, so a repacking ingest still migrates
            # the seeded rows by oid instead of trusting the layout
            w_inc = Worker(SSSP(), dg.fragment)
            w_inc.query_incremental(prev, inc_delta,
                                    prev_fragment=base, source=0)
            ti = time.perf_counter()
            w_inc.query_incremental(prev, inc_delta,
                                    prev_fragment=base, source=0)
            t_inc = time.perf_counter() - ti
            dyn_block["inc_cold_rounds"] = int(w_cold.rounds)
            dyn_block["inc_seeded_rounds"] = int(w_inc.rounds)
            dyn_block["inc_speedup"] = round(
                t_cold / t_inc, 3) if t_inc > 0 else 0.0
            print(
                f"[bench] dyn incremental: seeded {w_inc.rounds} "
                f"rounds / {t_inc:.4f}s vs cold {w_cold.rounds} "
                f"rounds / {t_cold:.4f}s "
                f"({dyn_block['inc_speedup']}x)",
                file=sys.stderr,
            )
            record["dyn"] = dyn_block
            _emit_record(record)
        except Exception as e:  # the dyn lane must not cost the bench
            print(f"[bench] dyn lane failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    # serving-fleet lane (r13, ROADMAP item 2b/2c): the drain drill —
    # R=2 replica sessions behind a version-fenced router serving a
    # mixed sssp+khop stream (khop = the sampling-shaped workload,
    # ROADMAP 5c one notch) with a concurrent barrier-ingested delta
    # stream, one replica drained mid-run for an offline forced
    # repack and rejoined through its catch-up log.  Gated exit-2 on:
    # per-query byte identity vs the undrained R=1 run, zero dropped
    # queries, and zero XLA compiles on an evict -> re-admit of a
    # replica session (the warm-host-artifact contract).  Reports
    # sustained qps@p99 PER REPLICA — the ROADMAP's stated target
    # bench.  GRAPE_BENCH_NO_FLEET=1 skips;
    # GRAPE_BENCH_FLEET_QUERIES / _UPDATES size the lane.
    fleet_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_FLEET"):
        try:
            from libgrape_lite_tpu.analysis import compile_events
            from libgrape_lite_tpu.dyn import RepackPolicy
            from libgrape_lite_tpu.fleet import (
                FLEET_STATS,
                FleetRouter,
                run_fleet_script,
            )
            from libgrape_lite_tpu.fragment.mutation import (
                replicate_fragment,
            )
            from libgrape_lite_tpu.serve import (
                BatchPolicy,
                ServeSession,
            )

            sys.path.insert(
                0, os.path.join(os.path.dirname(os.path.abspath(
                    __file__)), "scripts"))
            from gen_rmat import delta_edges

            fl_scale = int(os.environ.get(
                "GRAPE_BENCH_SERVE_SCALE", min(SCALE, 12)))
            fl_q = int(os.environ.get(
                "GRAPE_BENCH_FLEET_QUERIES", 64))
            fl_upd = int(os.environ.get(
                "GRAPE_BENCH_FLEET_UPDATES", 128))
            fn_, fsrc, fdst, fcomm, fvm = build_bench_inputs(fl_scale)
            rng_q = np.random.default_rng(7)
            fl_srcs = [
                int(x) for x in rng_q.integers(0, fn_, size=fl_q)
            ]
            fl_queries = [
                ("sssp" if i % 2 == 0 else "khop", {"source": s})
                for i, s in enumerate(fl_srcs)
            ]
            u_src, u_dst = delta_edges(fl_scale, fl_upd, seed=43)
            rng_uw = np.random.default_rng(47)
            u_w = rng_uw.uniform(0.1, 10.0, fl_upd)
            fl_ops = [("a", int(s), int(d), float(x)) for s, d, x in
                      zip(u_src, u_dst, u_w)]
            fl_drain_at = fl_q // 2

            def fleet_run(R, drain):
                base = build_bench_weighted_fragment(
                    fsrc, fdst, fcomm, fvm, retain_edge_list=True
                )
                frags = [base] + [
                    replicate_fragment(base) for _ in range(R - 1)
                ]
                sessions = [
                    ServeSession(
                        f, policy=BatchPolicy(max_batch=8),
                        dyn=RepackPolicy(
                            capacity=max(4096, 4 * fl_upd)),
                    )
                    for f in frags
                ]
                router = FleetRouter(sessions)
                # warm every (app, batch-shape) runner the run touches
                for s in fl_srcs[:8]:
                    router.submit("sssp", {"source": s})
                    router.submit("khop", {"source": s})
                router.drain()
                for r in router.replicas:  # hist/latency = measured
                    r.latencies, r.served, r.ok = [], 0, 0
                t0 = time.perf_counter()
                reqs = run_fleet_script(
                    router, fl_queries, delta_ops=fl_ops,
                    ingest_every=16,
                    drain_at=fl_drain_at if drain else None,
                    drain_idx=0,
                    # the offline work: a forced empty-delta repack
                    # THROUGH the session (counted, adopts the rebuilt
                    # fragment into the resident workers)
                    offline=(lambda s: s.ingest([], force_repack=True))
                    if drain else None,
                )
                wall = time.perf_counter() - t0
                digs = [
                    q.result.values.tobytes()
                    if q.result is not None and q.result.ok else b""
                    for q in reqs
                ]
                dropped = sum(1 for q in reqs if q.result is None)
                return router, reqs, digs, dropped, wall

            FLEET_STATS.reset()
            _, _, base_digs, base_drop, _ = fleet_run(1, False)
            router, reqs, digs, dropped, wall = fleet_run(2, True)
            identical = digs == base_digs
            # evict -> re-admit drill on replica 0: warm the probe
            # shape once (the drain's offline repack re-keyed the
            # runners, an ordinary counted compile), then release the
            # device buffers and re-admit — the REPEAT of a warmed
            # query must compile NOTHING (the tenancy zero-replanning
            # contract: host plan caches and runner caches stay warm
            # across eviction)
            sess0 = router.replicas[0].session
            sess0.submit("sssp", {"source": fl_srcs[0]})
            sess0.drain()
            sess0.release_device()
            sess0.restore_device()
            with compile_events() as ev:
                sess0.submit("sssp", {"source": fl_srcs[0]})
                sess0.drain()
            readmit_compiles = ev.compiles
            drain_evs = [e for e in FLEET_STATS.events
                         if e.get("kind") == "drain"]
            rejoin_evs = [e for e in FLEET_STATS.events
                          if e.get("kind") == "rejoin"]
            per_replica = {}
            for rkey, s in router.summary(wall)["replicas"].items():
                per_replica[rkey] = {
                    "qps": s.get("qps", 0.0), "p50_ms": s["p50_ms"],
                    "p99_ms": s["p99_ms"], "served": s["served"],
                    "ok": s["ok"],
                }
            fleet_block = {
                "scale": fl_scale,
                "replicas": 2,
                "tenants": 0,
                "queries": fl_q,
                "ok": sum(
                    1 for q in reqs
                    if q.result is not None and q.result.ok
                ),
                "dropped": dropped + base_drop,
                "drain_at": fl_drain_at,
                "drained_replica": 0,
                "drain_wall_s": (
                    drain_evs[-1]["wall_s"] if drain_evs else 0.0
                ),
                "catchup_ops": (
                    rejoin_evs[-1]["catchup_ops"] if rejoin_evs
                    else 0
                ),
                "updates": fl_upd,
                "updates_per_s": (
                    round(fl_upd / wall, 1) if wall > 0 else 0.0
                ),
                "fence": router.fence,
                "byte_identical": identical,
                "per_replica": per_replica,
                "evictions": FLEET_STATS.evictions,
                "readmit_compiles": readmit_compiles,
            }
            record["fleet"] = fleet_block
            _emit_record(record)
            print(
                f"[bench] fleet: R=2 drain@{fl_drain_at} "
                f"identical={identical} dropped={fleet_block['dropped']} "
                + " ".join(
                    f"{k}={v['qps']}q/s@p99={v['p99_ms']}ms"
                    for k, v in per_replica.items()
                )
                + f" catchup={fleet_block['catchup_ops']}ops "
                f"readmit_compiles={readmit_compiles}",
                file=sys.stderr,
            )
            if not identical:
                fleet_mismatch = (
                    "drained R=2 results diverged from the undrained "
                    "R=1 run — the drain/fence changed answers"
                )
            elif fleet_block["dropped"]:
                fleet_mismatch = (
                    f"{fleet_block['dropped']} dropped quer(ies) — "
                    "the drain was not zero-downtime"
                )
            elif readmit_compiles:
                fleet_mismatch = (
                    f"{readmit_compiles} XLA compile(s) after "
                    "evict -> re-admit — the warm-host-artifact "
                    "contract broke"
                )
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] fleet lane failed: {type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # autopilot lane (r16, ROADMAP item 2): the closed-loop drill —
    # one replica serving an sssp stream whose arrival rate (real
    # wall-clock feeder) is calibrated to 0.8x the measured service
    # rate and DOUBLED a third of the way in; the Autoscaler must
    # answer with >= 1 scale-up through the zero-drop machinery, with
    # zero dropped queries and per-query byte identity vs the static
    # R=1 scripted run.  Then the result-cache sub-drill: a repeated
    # source must hit with ZERO XLA compiles, one fence-bumping
    # ingest must reap the cached epoch, and the post-ingest answer
    # must byte-match a cache-less session on the same mutated graph.
    # All five verdicts gate exit-2.  GRAPE_BENCH_NO_AUTOPILOT=1
    # skips; GRAPE_BENCH_AUTOPILOT_QUERIES / _P99_MS size the lane.
    autopilot_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_AUTOPILOT"):
        try:
            from collections import deque as _deque

            from libgrape_lite_tpu.analysis import compile_events
            from libgrape_lite_tpu.autopilot import (
                Autoscaler,
                ResultCache,
                ScalerConfig,
            )
            from libgrape_lite_tpu.autopilot.signals import (
                AUTOPILOT_STATS,
            )
            from libgrape_lite_tpu.dyn import RepackPolicy
            from libgrape_lite_tpu.fleet import FleetRouter
            from libgrape_lite_tpu.serve import (
                ArrivalFeeder,
                BatchPolicy,
                ServeSession,
            )

            sys.path.insert(
                0, os.path.join(os.path.dirname(os.path.abspath(
                    __file__)), "scripts"))
            from gen_rmat import delta_edges

            ap_scale = int(os.environ.get(
                "GRAPE_BENCH_SERVE_SCALE", min(SCALE, 12)))
            ap_q = int(os.environ.get(
                "GRAPE_BENCH_AUTOPILOT_QUERIES", 48))
            ap_p99_bound = float(os.environ.get(
                "GRAPE_BENCH_AUTOPILOT_P99_MS", 15000.0))
            an_, a_src, a_dst, a_comm, a_vm = build_bench_inputs(
                ap_scale)
            rng_a = np.random.default_rng(11)
            ap_srcs = [
                int(x) for x in rng_a.integers(0, an_, size=ap_q)
            ]

            def ap_fragment():
                return build_bench_weighted_fragment(
                    a_src, a_dst, a_comm, a_vm, retain_edge_list=True
                )

            def ap_session(f):
                return ServeSession(
                    f, policy=BatchPolicy(max_batch=8),
                    dyn=RepackPolicy(capacity=4096),
                )

            # static reference (R=1, scripted): the identity digests
            # AND the service rate the feeder calibrates from
            ref = ap_session(ap_fragment())
            for s in ap_srcs[:4]:
                ref.submit("sssp", {"source": s})
            ref.drain()
            t0 = time.perf_counter()
            ref_reqs = [
                ref.submit("sssp", {"source": s}) for s in ap_srcs
            ]
            ref.drain()
            ref_wall = time.perf_counter() - t0
            ref_digs = [
                q.result.values.tobytes()
                if q.result is not None and q.result.ok else b""
                for q in ref_reqs
            ]
            svc_qps = ap_q / max(ref_wall, 1e-6)

            # the load shift: 0.8x service rate, doubled at a third
            # of the stream — the queue MUST grow from there, so the
            # scale-up is deterministic, not a timing accident
            step_at = max(2, ap_q // 3)
            rate_spec = (
                f"{max(1.0, round(0.8 * svc_qps, 1))}:2x@{step_at}"
            )
            AUTOPILOT_STATS.reset()
            router = FleetRouter([ap_session(ap_fragment())])
            ap_cache = ResultCache(capacity=1024)
            router.attach_cache(ap_cache)

            def ap_factory(f):
                # a scale-up replica joins WARM (one throwaway query
                # compiles its runners before it becomes routable)
                s = ap_session(f)
                s.submit("sssp", {"source": ap_srcs[0]})
                s.drain()
                return s

            pilot = Autoscaler(
                router,
                ScalerConfig(min_replicas=1, max_replicas=2,
                             window=2, cooldown_ticks=2,
                             up_queue_depth=4),
                session_factory=ap_factory,
            )
            for s in ap_srcs[:4]:  # warm r0 before the clock starts
                router.submit("sssp", {"source": s})
            router.drain()
            inbox = _deque()
            feeder = ArrivalFeeder(
                lambda app, args, **kw: inbox.append((app, args)),
                [("sssp", {"source": s}) for s in ap_srcs],
                rate_spec,
            )
            ap_reqs = []
            feeder.start()
            while feeder.is_alive() or inbox or any(
                r.session.queue.pending() or r.pump.inflight()
                for r in router.replicas
            ):
                while inbox:
                    app_key, args = inbox.popleft()
                    ap_reqs.append(router.submit(app_key, dict(args)))
                router.pump()
                pilot.tick()
            feeder.join()
            router.drain()
            ap_digs = [
                q.result.values.tobytes()
                if q.result is not None and q.result.ok else b""
                for q in ap_reqs
            ]
            ap_drop = sum(1 for q in ap_reqs if q.result is None)
            identical = ap_digs == ref_digs
            from libgrape_lite_tpu.serve.queue import (
                latency_summary_ms,
            )

            ap_lat = latency_summary_ms([
                q.result.latency_s for q in ap_reqs
                if q.result is not None
            ])
            p99_ok = ap_lat["p99_ms"] <= ap_p99_bound

            # cache sub-drill: repeat of an answered source = a hit
            # with ZERO compiles
            hit_src = ap_srcs[0]
            router.submit("sssp", {"source": hit_src})
            router.drain()
            hits0 = ap_cache.hits
            with compile_events() as ev:
                router.submit("sssp", {"source": hit_src})
                router.drain()
            cache_hit_compiles = ev.compiles
            hit_seen = ap_cache.hits > hits0
            # fence invalidation: one barrier ingest bumps the fence
            # and reaps the epoch; the post-ingest answer must match
            # a CACHE-LESS session on the same mutated graph
            u2s, u2d = delta_edges(ap_scale, 32, seed=51)
            rng_w2 = np.random.default_rng(53)
            ap_ops = [
                ("a", int(s), int(d), float(x)) for s, d, x in
                zip(u2s, u2d, rng_w2.uniform(0.1, 10.0, 32))
            ]
            inv0 = ap_cache.invalidations
            router.ingest(ap_ops)
            invalidated = ap_cache.invalidations - inv0
            post_req = router.submit("sssp", {"source": hit_src})
            router.drain()
            cold = ap_session(ap_fragment())
            cold.ingest(ap_ops)
            cold_req = cold.submit("sssp", {"source": hit_src})
            cold.drain()
            post_identical = bool(
                post_req.result is not None and post_req.result.ok
                and cold_req.result is not None
                and cold_req.result.ok
                and post_req.result.values.tobytes()
                == cold_req.result.values.tobytes()
            )

            ap_stats = AUTOPILOT_STATS.snapshot()
            autopilot_block = {
                "scale": ap_scale,
                "queries": ap_q,
                "ok": sum(
                    1 for q in ap_reqs
                    if q.result is not None and q.result.ok
                ),
                "dropped": ap_drop,
                "rate_spec": rate_spec,
                "min_replicas": 1,
                "max_replicas": 2,
                "replicas_final": sum(
                    1 for r in router.replicas if r.routable
                ),
                "scale_ups": ap_stats["scale_ups"],
                "scale_downs": ap_stats["scale_downs"],
                "ticks": ap_stats["ticks"],
                "p99_ms": ap_lat["p99_ms"],
                "p99_bound_ms": ap_p99_bound,
                "p99_ok": p99_ok,
                "byte_identical": identical,
                "cache_hits": ap_cache.hits,
                "cache_misses": ap_cache.misses,
                "cache_hit_compiles": cache_hit_compiles,
                "cache_invalidations": ap_cache.invalidations,
                "post_ingest_identical": post_identical,
            }
            record["autopilot"] = autopilot_block
            _emit_record(record)
            print(
                f"[bench] autopilot: rate={rate_spec} "
                f"scale_ups={ap_stats['scale_ups']} "
                f"replicas={autopilot_block['replicas_final']} "
                f"identical={identical} dropped={ap_drop} "
                f"p99={ap_lat['p99_ms']}ms "
                f"cache_hits={ap_cache.hits} "
                f"hit_compiles={cache_hit_compiles} "
                f"invalidated={invalidated} "
                f"post_ingest_identical={post_identical}",
                file=sys.stderr,
            )
            if not identical:
                autopilot_mismatch = (
                    "autoscaled results diverged from the static R=1 "
                    "run — scaling changed answers"
                )
            elif ap_drop:
                autopilot_mismatch = (
                    f"{ap_drop} dropped quer(ies) — the scale moves "
                    "were not zero-drop"
                )
            elif ap_stats["scale_ups"] < 1:
                autopilot_mismatch = (
                    "no scale-up under a 2x mid-stream rate step — "
                    "the control loop never closed"
                )
            elif not p99_ok:
                autopilot_mismatch = (
                    f"p99 {ap_lat['p99_ms']}ms over the "
                    f"{ap_p99_bound}ms bound"
                )
            elif cache_hit_compiles or not hit_seen:
                autopilot_mismatch = (
                    f"repeated-source hit compiled "
                    f"{cache_hit_compiles} time(s) (hit_seen="
                    f"{hit_seen}) — the cache did not skip the device"
                )
            elif not invalidated:
                autopilot_mismatch = (
                    "the fence-bumping ingest invalidated nothing — "
                    "stale epoch entries survived"
                )
            elif not post_identical:
                autopilot_mismatch = (
                    "post-ingest answer diverged from a cache-less "
                    "run on the mutated graph — the cache served a "
                    "stale epoch"
                )
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] autopilot lane failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # superstep-pipelining lane (r9, ROADMAP item 3): serial vs
    # pipelined wall at fnum>=2 with the byte-identity verdict, the
    # modeled hidden-exchange fraction, the boundary-set sizes and the
    # cost model's overlap recount (parallel/pipeline.py,
    # docs/PIPELINE.md).  The fnum=1 bench backend can't host the A/B,
    # so the CPU fallback re-invokes the lane in a forced 2-device
    # subprocess.  GRAPE_BENCH_NO_PIPELINE=1 skips;
    # GRAPE_BENCH_PIPELINE_SCALE sizes the twin.
    pipeline_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_PIPELINE"):
        try:
            pipe_scale = int(os.environ.get(
                "GRAPE_BENCH_PIPELINE_SCALE", min(SCALE, 12)))
            if jax.device_count() >= 2:
                pipe_block = pipeline_lane(pipe_scale)
            else:
                pipe_block = _pipeline_lane_subprocess(pipe_scale)
            record["pipeline"] = pipe_block
            _emit_record(record)
            print(
                f"[bench] pipeline: serial={pipe_block['serial_s']}s "
                f"pipelined={pipe_block['pipelined_s']}s "
                f"byte_identical={pipe_block['byte_identical']} "
                f"hidden_frac={pipe_block['modeled_hidden_frac']} "
                f"({pipe_block['boundary_vertices']} boundary / "
                f"{pipe_block['interior_vertices']} interior vertices)",
                file=sys.stderr,
            )
            # the SAME tolerance as the op-budget ledger gate (the
            # docs declare them identical — no private constant copy)
            scripts = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "scripts")
            if scripts not in sys.path:
                sys.path.insert(0, scripts)
            from pack_cost_model import MISMATCH_TOLERANCE as _TOL

            if pipe_block["overlap_recount_mismatch"] > _TOL:
                pipeline_mismatch = pipe_block["overlap_recount_mismatch"]
            if not pipe_block["byte_identical"]:
                pipeline_mismatch = 1.0
            if not pipe_block["engaged"]:
                # the lane FORCES engagement, so engaged=false is a
                # regression that silently disabled pipelining — the
                # vacuously-identical A/B must not read as green
                pipeline_mismatch = 1.0
                print(
                    "[bench] pipeline: lane ran FORCED but the plan "
                    "did not engage — see the decline reason above",
                    file=sys.stderr,
                )
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] pipeline lane failed: {type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # 2-D vertex-cut partition lane (r10, ROADMAP item 2): the
    # hub-heavy RMAT A/B at fnum 4 (k=2) — max-tile vs the raw hub
    # fragment, modeled exchange bytes, serial-vs-2D wall, byte/eps
    # identity verdicts, the planner's recorded auto decision against
    # the measured winner, and the per-tile pack-plan recount (gated
    # at the shared 5% tolerance).  GRAPE_BENCH_NO_P2D=1 skips;
    # GRAPE_BENCH_P2D_SCALE sizes the twin.
    p2d_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_P2D"):
        try:
            # default 12 REGARDLESS of GRAPE_BENCH_SCALE: the lane's
            # tile-vs-hub bound is a statement about RMAT hub
            # statistics, which under-develop below scale ~12 (at
            # scale 10 the raw hub fragment is only ~2x the mean and
            # the 0.5x bound sits on the noise floor)
            p2d_scale = int(os.environ.get(
                "GRAPE_BENCH_P2D_SCALE", 12))
            if jax.device_count() >= 4:
                p2d = partition2d_lane(p2d_scale)
            else:
                p2d = _partition2d_lane_subprocess(p2d_scale)
            record["partition2d"] = p2d
            _emit_record(record)
            print(
                f"[bench] partition2d: 1d={p2d['serial_1d_s']}s "
                f"2d={p2d['vc2d_s']}s byte_identical="
                f"{p2d['sssp_byte_identical']} max_tile="
                f"{p2d['max_tile_edges']} vs hub={p2d['hub_1d_edges']} "
                f"({p2d['tile_ratio_vs_hub']}x) planner="
                f"{p2d['planner_choice']} measured="
                f"{p2d['measured_winner']}",
                file=sys.stderr,
            )
            scripts = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "scripts")
            if scripts not in sys.path:
                sys.path.insert(0, scripts)
            from pack_cost_model import MISMATCH_TOLERANCE as _TOL2

            for bad, why in (
                (not p2d["sssp_byte_identical"],
                 "2-D SSSP diverged from the 1-D result"),
                (not p2d["pagerank_eps_identical"],
                 "2-D PageRank drifted past eps"),
                (not p2d["tile_bound_ok"],
                 "max tile exceeds 0.5x the 1-D hub fragment"),
                (not p2d["exchange_reduced"],
                 "modeled 2-D exchange bytes not below the 1-D "
                 "gather"),
                (not p2d["tile_plan_ok"],
                 "per-tile pack plan unavailable (resolve failed — "
                 "see the lane's stderr)"),
                (p2d["tile_plan_ok"]
                 and p2d["tile_recount_mismatch"] > _TOL2,
                 "tile pack-plan ledger recount drifted"),
                (not p2d["decision_matches"],
                 "planner decision contradicts the measured winner"),
            ):
                if bad:
                    p2d_mismatch = why
                    break
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] partition2d lane failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # pipelined-SUMMA lane (PR 19): 2-D SSSP pipelined vs unpipelined
    # vs the 1-D baseline, byte-compared per oid; the decision record
    # must carry the rate-profile label and the modeled hidden-µs per
    # round.  GRAPE_BENCH_NO_VC2D_PIPELINE=1 skips;
    # GRAPE_BENCH_VC2D_PIPELINE_SCALE sizes the twin.
    vc2dp_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_VC2D_PIPELINE"):
        try:
            vc2dp_scale = int(os.environ.get(
                "GRAPE_BENCH_VC2D_PIPELINE_SCALE", min(SCALE, 12)))
            if jax.device_count() >= 4:
                vc2dp = vc2d_pipeline_lane(vc2dp_scale)
            else:
                vc2dp = _vc2d_pipeline_lane_subprocess(vc2dp_scale)
            record["vc2d_pipeline"] = vc2dp
            _emit_record(record)
            print(
                f"[bench] vc2d_pipeline: 1d={vc2dp['serial_1d_s']}s "
                f"2d={vc2dp['serial_2d_s']}s "
                f"2d-pipelined={vc2dp['pipelined_2d_s']}s "
                f"eq_2d={vc2dp['pipelined_eq_serial_2d']} "
                f"eq_1d={vc2dp['pipelined_eq_1d']} "
                f"hidden_us={vc2dp['modeled_hidden_us']} "
                f"profile={vc2dp['profile']} "
                f"(wall on {vc2dp['wall_backend']}: "
                + ("overlap evidence"
                   if vc2dp["wall_is_overlap_evidence"]
                   else "correctness proxy only — collectives are "
                        "synchronous off-TPU") + ")",
                file=sys.stderr,
            )
            for bad, why in (
                (not vc2dp["engaged"],
                 "lane ran FORCED but the vc2d plan did not engage — "
                 "see the decline reason above"),
                (not vc2dp["pipelined_eq_serial_2d"],
                 "pipelined 2-D diverged from the unpipelined 2-D "
                 "round"),
                (not vc2dp["pipelined_eq_1d"],
                 "2-D result diverged from the 1-D baseline"),
                (not vc2dp["profile"],
                 "decision record is missing the rate-profile label"),
                (vc2dp["modeled_hidden_us"] < 0,
                 "decision record is missing modeled_hidden_us"),
            ):
                if bad:
                    vc2dp_mismatch = why
                    break
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] vc2d_pipeline lane failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # masked-SpGEMM lane (r11, ROADMAP 5a): LCC intersect-vs-spgemm
    # wall A/B at GRAPE_BENCH_SPGEMM_SCALE (default min(SCALE, 10))
    # with the bit-exactness verdict + shipped-plan recount, and the
    # modeled ops/edge A/B at the full bench geometry.  Gated like the
    # ledger lane: recount drift > 5%, a non-identical result, or a
    # modeled LOSS against popcount fails the bench with exit 2.
    # GRAPE_BENCH_NO_SPGEMM=1 skips.
    spgemm_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_SPGEMM"):
        try:
            sg_scale = int(os.environ.get(
                "GRAPE_BENCH_SPGEMM_SCALE", min(SCALE, 10)))
            sgb = spgemm_lane(sg_scale, SCALE, EDGE_FACTOR)
            record["spgemm"] = sgb
            _emit_record(record)
            print(
                f"[bench] spgemm: intersect={sgb['intersect_s']}s "
                f"spgemm={sgb['spgemm_s']}s byte_identical="
                f"{sgb['byte_identical']} modeled@{SCALE}: "
                f"mxu/edge={sgb['mxu_elems_per_edge']} vs popcount "
                f"word-ops/edge={sgb['intersect_word_ops_per_edge']} "
                f"win={sgb['modeled_win']} auto={sgb['auto_backend']}",
                file=sys.stderr,
            )
            scripts = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "scripts")
            if scripts not in sys.path:
                sys.path.insert(0, scripts)
            from pack_cost_model import MISMATCH_TOLERANCE as _TOLS

            # the modeled-win verdict gates only at/above the
            # crossover scale (~2^13 vertices, docs/SPGEMM.md): below
            # it the packed-bitmap sweep SHOULD win and auto records
            # the intersect decline — a shrunken GRAPE_BENCH_SCALE
            # smoke (app_tests runs scale 10) must not read an
            # expected loss as drift.  Identity + recount gate always.
            for bad, why in (
                (not sgb["byte_identical"],
                 "spgemm LCC diverged from the intersect backend"),
                (sgb["ledger_recount_mismatch"] > _TOLS,
                 "spgemm ledger recount drifted"),
                (SCALE >= 14 and not sgb["modeled_win"],
                 "modeled spgemm cost does not beat popcount at bench "
                 "geometry"),
            ):
                if bad:
                    spgemm_mismatch = why
                    break
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] spgemm lane failed: {type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # static op-budget ledger (r6): the planner's exact per-stage ALU
    # counts at the bench geometry ride in the BENCH json, and the
    # cost model's independent recount must agree within 5% — the
    # op budget is a pinned contract, so a drift fails the bench LOUDLY
    # (after every measurement is already printed).  First run pays the
    # O(E log E) planner; the summary is cached under the plan-cache
    # dir afterwards.  GRAPE_BENCH_NO_LEDGER=1 skips the lane.
    ledger_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_LEDGER"):
        try:
            sys.path.insert(
                0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "scripts"))
            from pack_cost_model import (
                MISMATCH_TOLERANCE,
                bench_ledger_summary,
            )

            summ = bench_ledger_summary(SCALE, EDGE_FACTOR,
                                        cache_dir=PLAN_CACHE_DIR)
            record["pack_ledger"] = {
                "vpu_ops_per_edge": summ["vpu_ops_per_edge"],
                "mxu_elems_per_edge": summ["mxu_elems_per_edge"],
                "gather_slots_per_edge": summ["gather_slots_per_edge"],
                "bytes_per_edge": summ["bytes_per_edge"],
                "per_stage_ops_per_edge": summ["per_stage_ops_per_edge"],
                "scan_mode": os.environ.get("GRAPE_PACK_SCAN", "mxu"),
                "modeled": summ["scenarios"],
                "ledger_recount_mismatch":
                    summ["ledger_recount_mismatch"],
            }
            _emit_record(record)
            if summ["ledger_recount_mismatch"] > MISMATCH_TOLERANCE:
                ledger_mismatch = summ["ledger_recount_mismatch"]
        except Exception as e:  # the ledger lane must not cost the bench
            print(
                f"[bench] pack ledger lane failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # calibration lane (r17, ops/calibration.py, docs/CALIBRATION.md):
    # the drift gate — recompute the ACTIVE profile's modeled walls
    # over a measured sample set and fail the bench when an explicit
    # GRAPE_RATE_PROFILE has drifted >5% from measurement on any
    # priced surface.  Samples come from GRAPE_CALIBRATION_SAMPLES
    # (the recorded sweep a `calibrate` run persisted — deterministic
    # in CI) or a fresh small-geometry sweep.  The pinned default is
    # NOT gated off-hardware: CPU walls are not v5e walls by
    # construction, only a profile somebody explicitly installed
    # claims to model THIS backend.  A fresh fit is also reported
    # (rates + residual + fallback notes) so PERF_NOTES can table
    # pinned-vs-fitted.  GRAPE_BENCH_NO_CALIBRATION=1 skips.
    calibration_mismatch = None
    truth_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_CALIBRATION"):
        try:
            from libgrape_lite_tpu.obs import truth
            from libgrape_lite_tpu.ops import calibration as calib

            spath = os.environ.get("GRAPE_CALIBRATION_SAMPLES")
            if spath:
                samples = calib.load_samples(spath)
            else:
                samples = calib.microbench_samples(
                    scales=(8, 9, 10), repeats=2)
                floor = calib.default_min_wall_s()
                samples = [s for s in samples if s["wall_s"] >= floor]
            prof = calib.active_profile()
            rep = calib.drift_report(prof, samples)
            try:
                fit, notes = calib.fit_rates_auto(
                    samples, base=prof, name="bench-fit")
                fitted_prof = fit.profile
                residual_pct = round(fit.residual * 100.0, 3)
            except calib.CalibrationError as e:
                fitted_prof = prof
                notes = [f"fit failed: {e}"]
                residual_pct = -1.0
            # the overlap truth meter over THIS process's span history
            # (the pipeline lane reconciles its own run; this row
            # covers any pipelined query the main bench dispatched).
            # Informational on the CPU-fallback host; gated below only
            # under an explicit GRAPE_RATE_PROFILE — same condition as
            # the rate-drift gate, and for the same reason.
            trep = truth.truth_report(obs.history())
            record["calibration"] = {
                "profile": prof.label(),
                "fingerprint": calib.backend_fingerprint(),
                "source": prof.source,
                "fitted": bool(prof.fitted),
                "samples": len(samples),
                "residual_pct": residual_pct,
                "drift_pct": rep["drift_pct"],
                "max_sample_drift_pct": rep["max_sample_drift_pct"],
                "drift_ok": rep["drift_ok"],
                "rates": {
                    "clock_hz": fitted_prof.clock_hz,
                    "vpu_lanes_per_cycle":
                        fitted_prof.vpu_lanes_per_cycle,
                    "mxu_cyc_per_elem": fitted_prof.mxu_cyc_per_elem,
                    "hbm_bps": fitted_prof.hbm_bps,
                    "gather_rows_per_cycle":
                        fitted_prof.gather_rows_per_cycle,
                    "dispatch_overhead_s":
                        fitted_prof.dispatch_overhead_s,
                },
                "unfitted": sorted(fitted_prof.unfitted),
                "fallback_notes": notes,
                "surfaces": rep["surfaces"],
                "overlap_truth": truth.block_brief(trep),
            }
            _emit_record(record)
            if os.environ.get(calib.PROFILE_ENV) and not rep["drift_ok"]:
                calibration_mismatch = rep["drift_pct"]
            if os.environ.get(calib.PROFILE_ENV) and not trep["ok"]:
                truth_mismatch = trep["max_claim_frac"]
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] calibration lane failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    if os.environ.get("GRAPE_BENCH_FULL"):
        # side metrics on stderr AFTER the primary line is out — a hang
        # or failure here must not cost the already-made measurement
        # (SSSP graduated to the primary record above)
        from libgrape_lite_tpu.models import BFS, CDLP, WCC

        print(f"[bench-extra] load: {t_load:.2f}s", file=sys.stderr)

        for nm, a, kw in (
            ("wcc", WCC(), {}),
            ("bfs", BFS(), {"source": 0}),
            ("cdlp", CDLP(), {"max_round": 10}),
        ):
            try:
                wk = Worker(a, frag)
                wk.query(**kw)  # compile
                t0 = time.perf_counter()
                wk.query(**kw)
                print(
                    f"[bench-extra] {nm}: {time.perf_counter() - t0:.4f}s "
                    f"rounds={wk.rounds}",
                    file=sys.stderr,
                )
            except Exception as e:  # side metrics are best-effort
                print(f"[bench-extra] {nm}: failed ({e})", file=sys.stderr)

    # obs rollup (r8): per-phase span aggregation over every query the
    # bench ran (warmups included — their compile-heavy first rounds
    # are why max_s >> mean_s on the query span).  The tracer was armed
    # in-memory at the top of main(), so this costs no file I/O unless
    # GRAPE_TRACE asked for it.
    try:
        record["obs"] = {
            "trace_id": obs.trace_id(),
            "spans": obs.rollup(obs.history()),
        }
        _emit_record(record)
        if os.environ.get(obs.TRACE_ENV) or os.environ.get(
            obs.METRICS_ENV
        ):
            out = obs.flush()
            print(f"[bench] obs: trace={out['trace']} "
                  f"metrics={out['metrics']}", file=sys.stderr)
    except Exception as e:  # the obs lane must not cost the bench
        print(f"[bench] obs lane failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    # gang-telemetry lane (PR 20, obs/gang.py): the in-process
    # two-rank sidecar federation drill plus the armed-vs-disarmed
    # fused-HLO identity re-proof.  Runs AFTER the obs rollup: the
    # HLO leg has to fully disarm (obs.reset), which drops the span
    # history the rollup reads.  GRAPE_BENCH_NO_OBS_GANG=1 skips.
    obs_gang_mismatch = None
    if not os.environ.get("GRAPE_BENCH_NO_OBS_GANG"):
        try:
            og = obs_gang_lane()
            record["obs_gang"] = og
            _emit_record(record)
            print(
                f"[bench] obs_gang: ranks={og['ranks']} "
                f"events={og['events']} cross_rank_flows="
                f"{og['cross_rank_flows']} complete={og['complete']} "
                f"monotonic={og['monotonic']} "
                f"hlo_identical={og['hlo_identical']}",
                file=sys.stderr,
            )
            if not og["complete"]:
                obs_gang_mismatch = (
                    "the merged gang trace is incomplete (missing "
                    "rank, unaligned clocks, or a span-less rank)"
                )
            elif og["cross_rank_flows"] < 1:
                obs_gang_mismatch = (
                    "no flow arrow crossed the rank tracks — the "
                    "vote legs lost their shared (cat, id)"
                )
            elif not og["monotonic"]:
                obs_gang_mismatch = (
                    "post-alignment timestamps are not monotonic"
                )
            elif not og["hlo_identical"]:
                obs_gang_mismatch = (
                    "arming the tracer changed the fused runner's "
                    "lowered HLO — tracing leaked into the program"
                )
        except Exception as e:  # the lane must not cost the bench
            print(
                f"[bench] obs_gang lane failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    if ledger_mismatch is not None:
        print(
            f"[bench] FATAL: op-budget ledger and cost-model recount "
            f"disagree by {ledger_mismatch:.1%} (> 5%) — the planner's "
            "annotations have drifted from the shipped kernels",
            file=sys.stderr,
        )
        sys.exit(2)
    if pipeline_mismatch is not None:
        print(
            f"[bench] FATAL: pipeline overlap term drifted "
            f"{pipeline_mismatch:.1%} from the shipped-plan recount "
            "(or the pipelined run was not byte-identical) — see the "
            "pipeline block above",
            file=sys.stderr,
        )
        sys.exit(2)
    if p2d_mismatch is not None:
        print(
            f"[bench] FATAL: partition2d lane verdict failed: "
            f"{p2d_mismatch} — see the partition2d block above",
            file=sys.stderr,
        )
        sys.exit(2)
    if vc2dp_mismatch is not None:
        print(
            f"[bench] FATAL: vc2d_pipeline lane verdict failed: "
            f"{vc2dp_mismatch} — see the vc2d_pipeline block above",
            file=sys.stderr,
        )
        sys.exit(2)
    if spgemm_mismatch is not None:
        print(
            f"[bench] FATAL: spgemm lane verdict failed: "
            f"{spgemm_mismatch} — see the spgemm block above",
            file=sys.stderr,
        )
        sys.exit(2)
    if serve_async_mismatch is not None:
        print(
            f"[bench] FATAL: serve_async lane verdict failed: "
            f"{serve_async_mismatch} — see the serve_async block above",
            file=sys.stderr,
        )
        sys.exit(2)
    if fleet_mismatch is not None:
        print(
            f"[bench] FATAL: fleet lane verdict failed: "
            f"{fleet_mismatch} — see the fleet block above",
            file=sys.stderr,
        )
        sys.exit(2)
    if autopilot_mismatch is not None:
        print(
            f"[bench] FATAL: autopilot lane verdict failed: "
            f"{autopilot_mismatch} — see the autopilot block above",
            file=sys.stderr,
        )
        sys.exit(2)
    if calibration_mismatch is not None:
        print(
            f"[bench] FATAL: the installed GRAPE_RATE_PROFILE drifts "
            f"{calibration_mismatch:.1f}% (> 5%) from measured device "
            "walls — recalibrate (python -m libgrape_lite_tpu.cli "
            "calibrate) or unset the stale profile",
            file=sys.stderr,
        )
        sys.exit(2)
    if truth_mismatch is not None:
        print(
            f"[bench] FATAL: the modeled overlap claim is "
            f"{truth_mismatch:.2f}x the measured round wall (> the "
            "claim limit) — the pipeline model claims to hide more "
            "exchange than the round took; see calibration."
            "overlap_truth above",
            file=sys.stderr,
        )
        sys.exit(2)
    if obs_gang_mismatch is not None:
        print(
            f"[bench] FATAL: obs_gang lane verdict failed: "
            f"{obs_gang_mismatch} — see the obs_gang block above",
            file=sys.stderr,
        )
        sys.exit(2)
    if _SCHEMA_ERRORS:
        print(
            f"[bench] FATAL: {len(_SCHEMA_ERRORS)} BENCH-record schema "
            "error(s) (see SCHEMA lines above) — the record drifted "
            "from scripts/check_bench_schema.py",
            file=sys.stderr,
        )
        sys.exit(3)


if __name__ == "__main__":
    if "--pipeline-lane" in sys.argv:
        # subprocess entrypoint for the CPU-fallback pipeline A/B (the
        # parent's backend is frozen at 1 device); prints ONE json line
        _i = sys.argv.index("--pipeline-lane")
        print(json.dumps(pipeline_lane(int(sys.argv[_i + 1]))))
    elif "--partition2d-lane" in sys.argv:
        # subprocess entrypoint for the 1-D vs 2-D partition A/B
        _i = sys.argv.index("--partition2d-lane")
        print(json.dumps(partition2d_lane(int(sys.argv[_i + 1]))))
    elif "--vc2d-pipeline-lane" in sys.argv:
        # subprocess entrypoint for the pipelined-SUMMA A/B
        _i = sys.argv.index("--vc2d-pipeline-lane")
        print(json.dumps(vc2d_pipeline_lane(int(sys.argv[_i + 1]))))
    else:
        main()
