"""GNN sampler tests (analogue of `misc/sampler_test.sh`)."""

import os

import numpy as np

ROOT_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)
import pytest


@pytest.fixture()
def frag():
    from libgrape_lite_tpu.sampler import AppendOnlyEdgecutFragment

    rng = np.random.default_rng(2)
    n, e = 50, 400
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    w = rng.random(e).astype(np.float32)
    return AppendOnlyEdgecutFragment(n, src, dst, w), (n, src, dst, w)


def adj_of(n, src, dst):
    adj = [[] for _ in range(n)]
    for a, b in zip(src.tolist(), dst.tolist()):
        adj[a].append(b)
    return adj


def test_random_sampling_valid(frag):
    from libgrape_lite_tpu.sampler import GraphSampler

    f, (n, src, dst, w) = frag
    adj = adj_of(n, src, dst)
    s = GraphSampler(f, "random")
    qs = np.arange(20)
    hops = s.sample(qs, fanouts=(4, 3), seed=1)
    assert hops[0].shape == (20, 4) and hops[1].shape == (20, 12)
    for i, q in enumerate(qs):
        for x in hops[0][i]:
            if adj[q]:
                assert x in adj[q]
            else:
                assert x == -1


def test_topk_sampling_deterministic(frag):
    from libgrape_lite_tpu.sampler import GraphSampler

    f, (n, src, dst, w) = frag
    s = GraphSampler(f, "top_k")
    qs = np.arange(10)
    h1 = s.sample(qs, fanouts=(3,), seed=1)[0]
    h2 = s.sample(qs, fanouts=(3,), seed=99)[0]
    assert np.array_equal(h1, h2)  # top-k ignores the seed
    # verify the picks are the max-weight neighbors
    wmap = {}
    for a, b, x in zip(src.tolist(), dst.tolist(), w.tolist()):
        wmap.setdefault(a, []).append((x, b))
    for i, q in enumerate(qs):
        top = sorted(wmap.get(q, []), reverse=True)[:3]
        expect = sorted(b for _, b in top)
        got = sorted(x for x in h1[i].tolist() if x >= 0)
        assert got == expect, (q, got, expect)


def test_edge_weight_sampling_no_replacement(frag):
    from libgrape_lite_tpu.sampler import GraphSampler

    f, (n, src, dst, w) = frag
    s = GraphSampler(f, "edge_weight")
    hops = s.sample(np.arange(n), fanouts=(5,), seed=3)[0]
    adj = adj_of(n, src, dst)
    for q in range(n):
        picks = [x for x in hops[q].tolist() if x >= 0]
        assert len(picks) == min(5, len(adj[q]))


def test_streaming_pipeline(tmp_path):
    from libgrape_lite_tpu.sampler import AppendOnlyEdgecutFragment, GraphSampler
    from libgrape_lite_tpu.sampler.stream import FileSink, FileSource, run_pipeline

    src_file = tmp_path / "stream.txt"
    src_file.write_text(
        "e 0 1\ne 0 2\ne 1 2\nq 0\ne 2 3\nq 2\nq 7\n"
    )
    frag = AppendOnlyEdgecutFragment(4, np.zeros(0, int), np.zeros(0, int))
    sampler = GraphSampler(frag, "random")
    sink = FileSink(str(tmp_path / "out.txt"))
    emitted = run_pipeline(
        frag, sampler, FileSource(str(src_file)), sink, fanouts=(2,)
    )
    sink.close()
    assert emitted == 3
    lines = (tmp_path / "out.txt").read_text().strip().splitlines()
    assert lines[0].startswith("0:")
    samples0 = set(lines[0].split(":")[1].split())
    assert samples0 <= {"1", "2"}
    # vertex 7 unknown at query time: grows the id space, no neighbors
    assert lines[2].strip() == "7:"


def test_run_sampler_driver(tmp_path, monkeypatch):
    """scripts/run_sampler.py end to end, both modes (parity with
    run_sampler.cc + misc/sampler_test.sh)."""
    monkeypatch.syspath_prepend(str(ROOT_SCRIPTS))
    import run_sampler as drv

    e = tmp_path / "g.e"
    v = tmp_path / "g.v"
    v.write_text("".join(f"{i}\n" for i in range(8)))
    e.write_text("0 1 1.0\n0 2 2.0\n1 2 1.0\n2 3 4.0\n4 5 1.0\n")

    # static mode: every vertex sampled once
    out = tmp_path / "static"
    rc = drv.main([
        "--efile", str(e), "--vfile", str(v), "--weighted",
        "--sampling_strategy", "top_k", "--hop_and_num", "2-2",
        "--out_prefix", str(out),
    ])
    assert rc == 0
    lines = (out / "result_frag_0").read_text().strip().splitlines()
    assert len(lines) == 8
    got = dict(ln.split(":", 1) for ln in lines)
    # deterministic top_k: 0's two heaviest neighbors are 2 (w=2) then 1
    assert got["0"].split()[:2] == ["2", "1"]
    assert got["7"].strip() == ""  # isolated vertex -> empty list

    # streaming mode: updates become sampleable, undirected both ways
    stream = tmp_path / "in.txt"
    stream.write_text("q 6\ne 6 7 3.0\nq 6\nq 7\n")
    sout = tmp_path / "out.txt"
    rc = drv.main([
        "--efile", str(e), "--vfile", str(v), "--weighted",
        "--sampling_strategy", "top_k", "--hop_and_num", "1",
        "--input_stream", str(stream), "--output_stream", str(sout),
        "--batch", "1",
    ])
    assert rc == 0
    slines = sout.read_text().strip().splitlines()
    assert slines[0].strip() == "6:"        # before the update
    assert slines[1].strip() == "6: 7"      # after e 6 7
    assert slines[2].strip() == "7: 6"      # reverse direction too


def test_async_sink_preserves_order(tmp_path):
    """AsyncSink (the reference's threaded output job) must deliver
    every line in emission order through the BlockingQueue."""
    from libgrape_lite_tpu.sampler.stream import AsyncSink, FileSink

    out = tmp_path / "async.txt"
    sink = AsyncSink(FileSink(str(out)))
    for i in range(500):
        sink.emit(f"line {i}")
    sink.close()
    lines = out.read_text().strip().splitlines()
    assert lines == [f"line {i}" for i in range(500)]


def test_async_sink_surfaces_writer_errors(tmp_path):
    """A failing writer must raise on the producer side, not exit 0
    with a truncated file (review r4 finding)."""
    import pytest

    from libgrape_lite_tpu.sampler.stream import AsyncSink

    class FailSink:
        def __init__(self):
            self.n = 0

        def emit(self, line):
            self.n += 1
            if self.n >= 2:
                raise IOError("disk full")

        def close(self):
            pass

    sink = AsyncSink(FailSink(), maxsize=4)
    with pytest.raises(RuntimeError, match="async sink writer failed"):
        for i in range(100):
            sink.emit(f"line {i}")
        sink.close()
