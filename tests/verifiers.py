"""Result verifiers, ported behaviorally from the reference harness:

* exact — byte-equal sorted compare (`misc/app_tests.sh:6-16`)
* eps — relative tolerance 1e-4 (`misc/eps_check.cc:24,56`)
* wcc — component partition isomorphism (`misc/wcc_check.cc`)
"""

from __future__ import annotations

import numpy as np

COMPARISON_THRESHOLD = 1e-4  # eps_check.cc:24
NEAR_INFINITY_FRAC = 0.999  # eps_check.cc:22


def load_result_lines(text: str) -> dict:
    out = {}
    for line in text.strip().splitlines():
        k, v = line.split()
        out[int(k)] = v
    return out


def load_golden(path: str) -> dict:
    with open(path) as f:
        return load_result_lines(f.read())


def exact_verify(result: dict, golden: dict) -> None:
    assert result.keys() == golden.keys(), (
        f"vertex sets differ: {len(result)} vs {len(golden)}"
    )
    bad = []
    for k, v in golden.items():
        r = result[k]
        if _norm_num(r) != _norm_num(v):
            bad.append((k, r, v))
            if len(bad) >= 5:
                break
    assert not bad, f"exact mismatch (first {len(bad)}): {bad}"


def _norm_num(s: str):
    try:
        f = float(s)
        return f
    except ValueError:
        return s


def eps_verify(result: dict, golden: dict, eps: float = COMPARISON_THRESHOLD) -> None:
    assert result.keys() == golden.keys()
    bad = []
    for k, v in golden.items():
        g = float(v)
        r = float(result[k])
        if np.isinf(g) or np.isinf(r):
            # eps_check.cc:22 treats near-infinity specially; exact
            # infinities must simply agree
            ok = np.isinf(g) and np.isinf(r) and (g > 0) == (r > 0)
        elif g == 0:
            ok = abs(r) < max(1e-12, eps * 1e-8)
        else:
            ok = abs(r - g) <= eps * abs(g)
        if not ok:
            bad.append((k, r, g))
            if len(bad) >= 5:
                break
    assert not bad, f"eps mismatch (first {len(bad)}): {bad}"


def collect_worker_result(app, frag, **kwargs) -> dict:
    """Run a query and collect its output lines as a {oid: value} dict —
    the shared bridge between Worker.output formatting and the
    verifiers, usable from conftest-free scripts (x32_check) and the
    pytest lanes alike."""
    from libgrape_lite_tpu.worker.worker import Worker, format_result_lines

    w = Worker(app, frag)
    w.query(**kwargs)
    values = w.result_values()
    chunks = []
    for f in range(frag.fnum):
        n = frag.inner_vertices_num(f)
        chunks.append(
            format_result_lines(
                frag.inner_oids(f), values[f, :n], app.result_format
            )
        )
    return load_result_lines("".join(chunks))


def wcc_verify(result: dict, golden: dict) -> None:
    """Partition isomorphism: same grouping, arbitrary labels."""
    assert result.keys() == golden.keys()
    fwd = {}
    bwd = {}
    for k in golden:
        g, r = golden[k], result[k]
        if g in fwd:
            assert fwd[g] == r, f"vertex {k}: golden comp {g} split in result"
        else:
            fwd[g] = r
        if r in bwd:
            assert bwd[r] == g, f"vertex {k}: result comp {r} merges golden comps"
        else:
            bwd[r] = g
