"""Native C++ loader vs Python parser parity."""

import numpy as np
import pytest

from tests.conftest import dataset_path


def test_native_parser_parity(tmp_path):
    from libgrape_lite_tpu.io.native import available, parse_file_native
    from libgrape_lite_tpu.io.line_parser import _parse_columns

    if not available():
        pytest.skip("native toolchain unavailable")

    src, dst, w = parse_file_native(dataset_path("p2p-31.e"), 2, True)
    with open(dataset_path("p2p-31.e"), "rb") as f:
        cols = _parse_columns(f.read(), 2, 3)
    assert np.array_equal(src, cols[0])
    assert np.array_equal(dst, cols[1])
    assert np.allclose(w, cols[2])

    oids = parse_file_native(dataset_path("p2p-31.v"), 1, False)[0]
    with open(dataset_path("p2p-31.v"), "rb") as f:
        vcols = _parse_columns(f.read(), 1, 1)
    assert np.array_equal(oids, vcols[0])


def test_native_parser_edge_cases(tmp_path):
    from libgrape_lite_tpu.io.native import available, parse_file_native

    if not available():
        pytest.skip("native toolchain unavailable")

    p = tmp_path / "t.e"
    p.write_text(
        "# comment line\n"
        "1 2 0.5\n"
        "\n"
        "9007199254740993 4 1.25\n"  # 2^53+1: must stay int64-exact
        "-3 7 2.0\n"
    )
    src, dst, w = parse_file_native(str(p), 2, True)
    assert src.tolist() == [1, 9007199254740993, -3]
    assert dst.tolist() == [2, 4, 7]
    assert w.tolist() == [0.5, 1.25, 2.0]


def test_native_parser_missing_file(tmp_path):
    from libgrape_lite_tpu.io.native import available, parse_file_native

    if not available():
        pytest.skip("native toolchain unavailable")
    with pytest.raises(FileNotFoundError):
        parse_file_native(str(tmp_path / "nope.e"), 2, True)


def test_native_edge_sort_parity():
    from libgrape_lite_tpu.io.native import available, sort_edges_native

    if not available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(3)
    n_rows, n_cols, e = 500, 900, 20000
    src = rng.integers(0, n_rows, e)
    nbr = rng.integers(0, n_cols, e)
    w = rng.random(e)
    out = sort_edges_native(src, nbr, w, n_rows, n_cols)
    order = np.lexsort((nbr, src))
    assert np.array_equal(out[0], src[order])
    assert np.array_equal(out[1], nbr[order])
    assert np.allclose(out[2], w[order])
    counts = np.bincount(src, minlength=n_rows)
    ip = np.zeros(n_rows + 1, np.int64)
    np.cumsum(counts, out=ip[1:])
    assert np.array_equal(out[3], ip)
    # unweighted path
    out2 = sort_edges_native(src, nbr, None, n_rows, n_cols)
    assert out2[2] is None and np.array_equal(out2[0], src[order])


def test_varint_native_matches_numpy_and_detects_corruption():
    """Native LEB128 codec: byte-identical to the numpy encoder, and a
    truncated stream raises instead of silently dropping the tail."""
    import numpy as np
    import pytest

    from libgrape_lite_tpu.io.native import (
        varint_decode_native, varint_encode_native,
    )

    if varint_encode_native(np.zeros(1, np.uint64), False) is None:
        pytest.skip("native library unavailable")

    rng = np.random.default_rng(4)
    vals = np.concatenate([
        rng.integers(0, 128, 50), rng.integers(0, 1 << 40, 50),
        [0, 1, 127, 128, (1 << 64) - 1],
    ]).astype(np.uint64)

    import libgrape_lite_tpu.io.native as nat
    import libgrape_lite_tpu.utils.archive as arc

    enc_nat = varint_encode_native(vals, False)
    orig = nat.varint_encode_native
    nat.varint_encode_native = lambda *a, **k: None
    try:
        enc_np = arc.varint_encode(vals)
    finally:
        nat.varint_encode_native = orig
    assert enc_nat == enc_np
    assert np.array_equal(varint_decode_native(enc_nat, False), vals)

    srt = np.sort(vals)
    assert np.array_equal(
        varint_decode_native(varint_encode_native(srt, True), True), srt
    )

    # truncate mid-value: last byte keeps its continuation bit
    bad = enc_nat[:-1]
    if bad[-1] & 0x80:
        with pytest.raises(ValueError, match="corrupt varint"):
            varint_decode_native(bad, False)
