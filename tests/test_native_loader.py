"""Native C++ loader vs Python parser parity."""

import numpy as np
import pytest

from tests.conftest import dataset_path


def test_native_parser_parity(tmp_path):
    from libgrape_lite_tpu.io.native import available, parse_file_native
    from libgrape_lite_tpu.io.line_parser import _parse_columns

    if not available():
        pytest.skip("native toolchain unavailable")

    src, dst, w = parse_file_native(dataset_path("p2p-31.e"), 2, True)
    with open(dataset_path("p2p-31.e"), "rb") as f:
        cols = _parse_columns(f.read(), 2, 3)
    assert np.array_equal(src, cols[0])
    assert np.array_equal(dst, cols[1])
    assert np.allclose(w, cols[2])

    oids = parse_file_native(dataset_path("p2p-31.v"), 1, False)[0]
    with open(dataset_path("p2p-31.v"), "rb") as f:
        vcols = _parse_columns(f.read(), 1, 1)
    assert np.array_equal(oids, vcols[0])


def test_native_parser_edge_cases(tmp_path):
    from libgrape_lite_tpu.io.native import available, parse_file_native

    if not available():
        pytest.skip("native toolchain unavailable")

    p = tmp_path / "t.e"
    p.write_text(
        "# comment line\n"
        "1 2 0.5\n"
        "\n"
        "9007199254740993 4 1.25\n"  # 2^53+1: must stay int64-exact
        "-3 7 2.0\n"
    )
    src, dst, w = parse_file_native(str(p), 2, True)
    assert src.tolist() == [1, 9007199254740993, -3]
    assert dst.tolist() == [2, 4, 7]
    assert w.tolist() == [0.5, 1.25, 2.0]


def test_native_parser_missing_file(tmp_path):
    from libgrape_lite_tpu.io.native import available, parse_file_native

    if not available():
        pytest.skip("native toolchain unavailable")
    with pytest.raises(FileNotFoundError):
        parse_file_native(str(tmp_path / "nope.e"), 2, True)
