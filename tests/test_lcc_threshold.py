"""--degree_threshold LCC parity (reference `lcc.h:234-243` filterByDegree
+ FLAGS_degree_threshold, `flags.cc:39`): vertices with degree above the
threshold build no oriented neighbor list, so a triangle is counted iff
its apex v and middle u are both unfiltered (the far end w is exempt —
it only needs membership, `lcc.h:172-179`)."""

import numpy as np
import pytest

from tests.test_worker import build_fragment
from tests.verifiers import collect_worker_result


def er_graph(n=48, p=0.15, seed=3):
    rng = np.random.default_rng(seed)
    a = rng.random((n, n)) < p
    a = np.triu(a, 1)
    src, dst = np.nonzero(a)
    return src.astype(np.int64), dst.astype(np.int64)


def brute_force_lcc(frag, n, src, dst, thr):
    """Reference-semantics LCC with the degree filter, on pids."""
    pid = frag.oid_to_pid(np.arange(n, dtype=np.int64))
    adj = {int(p): set() for p in pid}
    for s, d in zip(pid[src], pid[dst]):
        adj[int(s)].add(int(d))
        adj[int(d)].add(int(s))
    deg = {v: len(ns) for v, ns in adj.items()}

    def nplus(v):
        if thr > 0 and deg[v] > thr:
            return set()
        return {
            u for u in adj[v]
            if deg[u] < deg[v] or (deg[u] == deg[v] and u < v)
        }

    np_of = {v: nplus(v) for v in adj}
    tri = {v: 0 for v in adj}
    for v in adj:
        for u in np_of[v]:
            for w in np_of[u]:
                if w in np_of[v]:
                    tri[v] += 1
                    tri[u] += 1
                    tri[w] += 1
    out = {}
    inv = {int(p): o for o, p in enumerate(pid.tolist())}
    for v, t in tri.items():
        d = deg[v]
        out[inv[v]] = 2.0 * t / (d * (d - 1)) if d >= 2 else 0.0
    return out


@pytest.mark.parametrize("app_name", ["lcc_bitmap", "lcc_beta"])
@pytest.mark.parametrize("thr", [0, 5, 8])
def test_degree_threshold_parity(app_name, thr):
    from libgrape_lite_tpu.models import APP_REGISTRY

    n = 48
    src, dst = er_graph(n)
    frag = build_fragment(src, dst, None, n, 4)
    res = collect_worker_result(
        APP_REGISTRY[app_name](), frag, degree_threshold=thr
    )
    want = brute_force_lcc(frag, n, src, dst, thr)
    assert set(res) == set(want)
    for k, v in want.items():
        assert abs(float(res[k]) - v) < 1e-9, (k, res[k], v)


def test_threshold_above_max_degree_is_identity():
    from libgrape_lite_tpu.models import APP_REGISTRY

    n = 48
    src, dst = er_graph(n)
    frag = build_fragment(src, dst, None, n, 2)
    base = collect_worker_result(APP_REGISTRY["lcc"](), frag)
    same = collect_worker_result(
        APP_REGISTRY["lcc"](), frag, degree_threshold=n
    )
    assert base == same
