"""fleet/ — multi-tenant serving fleet (ISSUE 13 acceptance).

Pins: evict -> re-admit of a resident session performs ZERO pack
re-planning and ZERO XLA recompiles (counter- and compile_events-
pinned) and answers byte-identically; the budget's cost-weighted-LRU
eviction and its recorded reject decisions; per-tenant breach
isolation (tenants never share a batched dispatch, a poisoned tenant
lane fails alone); WRR fairness starvation bound; the drain drill —
R in {2, 3} replicas serving a stream with concurrent ingest, one
replica drained mid-stream, zero dropped queries, every per-query
result byte-identical to the undrained R=1 run; version-fence
violations are LOUD errors; priority/deadline scheduling in the
admission queue (expired requests fail with a recorded reason, never
silently dropped); the threaded admission front; the khop
serve-routable sampling app; and the bench-schema self-consistency
gate (every declared block wired into SCHEMA/validate_record).
"""

import sys
import time

import numpy as np
import pytest

from tests.test_dyn import ADDS, build_graph

SOURCES = [0, 7, 19, 30]


def _sequential(frag, app_factory, sources):
    from libgrape_lite_tpu.worker.worker import Worker

    values = {}
    for s in sources:
        w = Worker(app_factory(), frag)
        w.query(source=s)
        values[s] = w.result_values()
    return values


# ---- budget: pricing + cost-weighted LRU ---------------------------------


def test_footprint_prices_existing_ledgers():
    """The footprint comes from the ledgers that already exist: CSR
    bytes, overlay planes, retained runner buffers."""
    from libgrape_lite_tpu.fleet import session_footprint
    from libgrape_lite_tpu.serve import ServeSession

    sess = ServeSession(build_graph(2), dyn=True)
    fp0 = session_footprint(sess)
    assert fp0.frag_bytes > 0
    assert fp0.overlay_bytes > 0  # the empty overlay is pre-attached
    assert fp0.runner_bytes == 0  # nothing resident yet
    res = sess.serve([("sssp", {"source": 0})])
    assert res[0].ok
    fp1 = session_footprint(sess)
    assert fp1.runner_bytes > 0
    assert fp1.frag_bytes == fp0.frag_bytes
    assert fp1.total > fp0.total


def test_budget_cost_weighted_lru_picks_cold_large_victim():
    from libgrape_lite_tpu.fleet import FLEET_STATS, FleetBudget, Footprint

    FLEET_STATS.reset()
    clock = [0.0]
    b = FleetBudget(capacity_bytes=1000, clock=lambda: clock[0])
    evicted = []
    big = Footprint(frag_bytes=600, frag_keys={1: 600})
    small = Footprint(frag_bytes=300, frag_keys={2: 300})
    assert b.admit("cold_big", big, evict=evicted.append)["admitted"]
    clock[0] = 10.0
    assert b.admit("hot_small", small, evict=evicted.append)["admitted"]
    clock[0] = 11.0
    newcomer = Footprint(frag_bytes=500, frag_keys={3: 500})
    d = b.admit("newcomer", newcomer, evict=evicted.append)
    assert d["admitted"]
    # idle * bytes: cold_big (11s idle, 600B) beats hot_small (1s, 300B)
    assert evicted == ["cold_big"]
    assert "hot_small" in b.residents and "newcomer" in b.residents
    assert FLEET_STATS.evictions == 1


def test_budget_weight_protects_heavy_tenants():
    from libgrape_lite_tpu.fleet import FleetBudget, Footprint

    clock = [0.0]
    b = FleetBudget(capacity_bytes=1000, clock=lambda: clock[0])
    fp = lambda k: Footprint(frag_bytes=450, frag_keys={k: 450})  # noqa: E731
    b.admit("weighted", fp(1), weight=100.0)
    b.admit("light", fp(2), weight=1.0)
    clock[0] = 1.0
    evicted = []
    d = b.admit("next", fp(3), evict=evicted.append)
    assert d["admitted"] and evicted == ["light"]


def test_budget_reject_is_recorded_never_silent():
    from libgrape_lite_tpu.fleet import FLEET_STATS, FleetBudget, Footprint

    FLEET_STATS.reset()
    b = FleetBudget(capacity_bytes=100)
    b.admit("pinned", Footprint(frag_bytes=80, frag_keys={1: 80}),
            evictable=False)
    d = b.admit("too_big", Footprint(frag_bytes=90, frag_keys={2: 90}))
    assert not d["admitted"]
    assert "no evictable resident" in d["reason"]
    assert FLEET_STATS.rejects == 1
    assert any(e["kind"] == "reject" for e in FLEET_STATS.events)


def test_budget_shared_fragment_billed_once():
    from libgrape_lite_tpu.fleet import FleetBudget, Footprint

    b = FleetBudget(capacity_bytes=1000)
    shared = {7: 600}
    b.admit("a", Footprint(frag_bytes=600, frag_keys=dict(shared)))
    # the second tenant over the SAME fragment costs only its private
    # bytes — 600 + 600 would not fit, shared dedup does
    d = b.admit("b", Footprint(frag_bytes=600, runner_bytes=100,
                               frag_keys=dict(shared)))
    assert d["admitted"]
    assert b.used_bytes() == 700


# ---- eviction -> re-admission: the zero-replanning pin -------------------


def _pack_fragment(fnum=1, n=700, e=6000):
    """f32-weighted fragment (pack-eligible under x64)."""
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.vertex_map.partitioner import MapPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    rng = np.random.default_rng(21)
    src, dst = rng.integers(0, n, e), rng.integers(0, n, e)
    w = rng.uniform(0.5, 2.0, e).astype(np.float32)
    oids = np.arange(n, dtype=np.int64)
    vm = VertexMap.build(oids, MapPartitioner(fnum, oids))
    return ShardedEdgecutFragment.build(
        CommSpec(fnum=fnum), vm, src, dst, w, directed=False,
    )


def test_evict_readmit_zero_replanning_zero_compiles(monkeypatch):
    """The acceptance pin: release_device drops the HBM arrays; the
    next query after restore_device hits the warm per-fragment plan
    cache (planned flat) and the warm runner cache (zero compiles on
    the REAL XLA stream), and answers byte-identically."""
    import libgrape_lite_tpu.ops.spmv_pack as sp
    from libgrape_lite_tpu.analysis import compile_events
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

    monkeypatch.setenv("GRAPE_SPMV", "pack")
    monkeypatch.delenv("GRAPE_PACK_PLAN_CACHE", raising=False)
    sess = ServeSession(_pack_fragment(), policy=BatchPolicy(max_batch=1))
    r1 = sess.serve([("sssp", {"source": 0})])
    assert r1[0].ok
    assert sess.worker("sssp").app._pack is not None
    want = r1[0].values.tobytes()

    planned = sp.plan_stats()["planned"]
    rel = sess.release_device()
    assert rel["fragment_released"] and not sess.resident
    assert sess.fragment.dev is None
    assert sess.restore_device() and sess.resident
    with compile_events() as ev:
        r2 = sess.serve([("sssp", {"source": 0})])
    assert r2[0].ok and r2[0].values.tobytes() == want
    assert ev.compiles == 0, ("re-admission recompiled", ev.events)
    assert sp.plan_stats()["planned"] == planned, (
        "re-admission re-ran the pack planner"
    )


def test_release_restore_is_idempotent():
    from libgrape_lite_tpu.serve import ServeSession

    sess = ServeSession(build_graph(2))
    assert sess.fragment.release_device() is True
    assert sess.fragment.release_device() is False
    assert sess.fragment.restore_device() is True
    assert sess.fragment.restore_device() is False
    res = sess.serve([("sssp", {"source": 0})])
    assert res[0].ok


def test_session_close_is_terminal():
    from libgrape_lite_tpu.serve import ServeSession

    sess = ServeSession(build_graph(2))
    assert sess.serve([("sssp", {"source": 0})])[0].ok
    sess.close()
    assert not sess.resident
    assert sess._workers == {}
    with pytest.raises(RuntimeError, match="closed"):
        sess.submit("sssp", {"source": 0})
    sess.close()  # idempotent


def test_manager_evicts_and_readmits_under_pressure():
    """Two single-fragment tenants under a budget that holds one:
    activating B evicts A (cost-weighted LRU), A's next use re-admits
    with correct answers; every transition is counted."""
    from libgrape_lite_tpu.fleet import (
        FLEET_STATS,
        FleetBudget,
        FleetManager,
        fragment_bytes,
    )
    from libgrape_lite_tpu.serve import ServeSession

    FLEET_STATS.reset()
    fa, fb = build_graph(2, seed=3), build_graph(2, seed=5)
    cap = int(max(fragment_bytes(fa), fragment_bytes(fb)) * 1.5)
    mgr = FleetManager(FleetBudget(capacity_bytes=cap))
    sa, sb = ServeSession(fa), ServeSession(fb)
    want_a = _sequential(fa, _sssp_factory(), [0])[0]
    mgr.add_tenant("a", sa)
    mgr.add_tenant("b", sb)

    mgr.submit("a", "sssp", {"source": 0})
    mgr.drain()
    mgr.submit("b", "sssp", {"source": 0})
    mgr.drain()
    assert not sa.resident, "admitting b should have evicted a"
    assert FLEET_STATS.evictions >= 1

    t = mgr.submit("a", "sssp", {"source": 0})
    mgr.drain()
    assert t.done and t.result.ok
    assert t.result.values.tobytes() == want_a.tobytes()
    assert sa.resident
    assert mgr.tenants["a"].stats["readmits"] == 1


def _sssp_factory():
    from libgrape_lite_tpu.models import APP_REGISTRY

    return APP_REGISTRY["sssp"]


# ---- tenancy: isolation + fairness ---------------------------------------


def test_tenants_never_share_a_batched_dispatch():
    """Same app, same shapes, one shared session: requests of two
    tenants must land in separate batches (the tenant tag is in the
    compat key) — the structural half of breach isolation."""
    from libgrape_lite_tpu.fleet import FleetBudget, FleetManager
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

    sess = ServeSession(build_graph(2), policy=BatchPolicy(max_batch=8))
    mgr = FleetManager(FleetBudget(capacity_bytes=0))
    mgr.add_tenant("a", sess)
    mgr.add_tenant("b", sess)
    for s in SOURCES:
        mgr.submit("a", "sssp", {"source": s})
        mgr.submit("b", "sssp", {"source": s})
    mgr.drain()
    hist = sess.queue.batch_hist
    assert hist == {4: 2}, hist  # one 4-lane batch per tenant, never 8


def test_tenant_breach_isolation(graph_cache):
    """A poisoned lane in tenant A's guarded batch fails ALONE —
    every tenant-B query completes with correct bytes (tenants never
    coalesce, so the blast radius cannot reach a batchmate tenant)."""
    import jax

    from libgrape_lite_tpu.fleet import FleetBudget, FleetManager
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession
    from libgrape_lite_tpu.serve import batch as serve_batch

    frag = graph_cache(2)
    p2p = [6, 17, 3, 42, 11]  # real p2p-31 vertex ids
    want = _sequential(frag, APP_REGISTRY["sssp"], p2p[2:])

    orig = serve_batch.run_guarded_batch
    poisoned_batches = []

    def poisoned(worker, args_list, mr, cfg, **kw):
        # poison lane 0 of tenant a's batch only (identified by its
        # lane count: a submits 2, b submits 3)
        if len(args_list) != 2:
            return orig(worker, args_list, mr, cfg, **kw)

        def hook(carry, rounds):
            if rounds != 2:
                return None
            dist = np.array(jax.device_get(carry["dist"]))
            dist[0, 0, :4] = -5.0
            return {"dist": dist}

        poisoned_batches.append(len(args_list))
        return orig(worker, args_list, mr, cfg, chunk_hook=hook)

    serve_batch.run_guarded_batch = poisoned
    try:
        sess = ServeSession(frag, policy=BatchPolicy(max_batch=8),
                            guard="halt")
        mgr = FleetManager(FleetBudget(capacity_bytes=0))
        mgr.add_tenant("a", sess)
        mgr.add_tenant("b", sess)
        ta = [mgr.submit("a", "sssp", {"source": s})
              for s in p2p[:2]]
        tb = [mgr.submit("b", "sssp", {"source": s})
              for s in p2p[2:]]
        mgr.drain()
    finally:
        serve_batch.run_guarded_batch = orig
    assert poisoned_batches == [2]
    assert not ta[0].result.ok
    assert ta[0].result.error["verdict"]["kind"] == "invariant"
    for t, s in zip(tb, p2p[2:]):
        assert t.result.ok, f"tenant b query {s} hurt by a's breach"
        assert t.result.values.tobytes() == want[s].tobytes()
    snap = mgr.snapshot()
    assert snap["tenants"]["a"]["failed"] == 1
    assert snap["tenants"]["b"]["failed"] == 0


def test_wrr_starvation_bound():
    """A 16-deep backlog on tenant A cannot starve tenant B: B's 4
    tickets all forward within the first 8 forwards (alternating WRR
    cycles)."""
    from libgrape_lite_tpu.fleet import FleetBudget, FleetManager
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

    sess = ServeSession(build_graph(2), policy=BatchPolicy(max_batch=8))
    mgr = FleetManager(FleetBudget(capacity_bytes=0))
    mgr.add_tenant("a", sess)
    mgr.add_tenant("b", sess)
    for s in range(16):
        mgr.submit("a", "sssp", {"source": s % 32})
    for s in range(4):
        mgr.submit("b", "sssp", {"source": s})
    mgr.drain()
    first8 = mgr.forward_order[:8]
    assert first8 == ["a", "b"] * 4, first8
    assert all(t.done for t in mgr.tenants["b"].tickets)


def test_wrr_weights_shape_the_cycle():
    from libgrape_lite_tpu.fleet import FleetBudget, FleetManager
    from libgrape_lite_tpu.serve import ServeSession

    sess = ServeSession(build_graph(2))
    mgr = FleetManager(FleetBudget(capacity_bytes=0))
    mgr.add_tenant("a", sess, weight=2.0)
    mgr.add_tenant("b", sess, weight=1.0)
    for s in range(6):
        mgr.submit("a", "sssp", {"source": s})
        mgr.submit("b", "sssp", {"source": s})
    mgr.forward_round()
    assert mgr.forward_order == ["a", "a", "b"]
    mgr.drain()


# ---- replica routing + the version fence ---------------------------------


def _router(R, *, dyn=True, max_batch=4):
    from libgrape_lite_tpu.dyn import RepackPolicy
    from libgrape_lite_tpu.fleet import FleetRouter
    from libgrape_lite_tpu.fragment.mutation import replicate_fragment
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

    base = build_graph(2)
    frags = [base] + [replicate_fragment(base) for _ in range(R - 1)]
    sessions = [
        ServeSession(
            f, policy=BatchPolicy(max_batch=max_batch),
            dyn=RepackPolicy(threshold=0.5, capacity=64) if dyn
            else None,
        )
        for f in frags
    ]
    return FleetRouter(sessions)


def test_router_least_outstanding_alternates():
    router = _router(2, dyn=False)
    picks = []
    for s in range(4):
        router.submit("sssp", {"source": s})
        picks.append([r.outstanding for r in router.replicas])
    assert picks == [[1, 0], [1, 1], [2, 1], [2, 2]]
    res = router.drain()
    assert len(res) == 4 and all(r.ok for r in res)
    assert all(r.outstanding == 0 for r in router.replicas)
    assert all(r.served == 2 for r in router.replicas)


def test_fence_violation_is_loud():
    from libgrape_lite_tpu.fleet import FenceViolationError

    router = _router(2, dyn=False)
    router.replicas[1].version = 99  # tampered: routable at wrong version
    with pytest.raises(FenceViolationError, match="mix graph versions"):
        router.submit("sssp", {"source": 0})
    with pytest.raises(FenceViolationError):
        router.pump()


def test_all_replicas_draining_is_a_fence_error():
    from libgrape_lite_tpu.fleet import FenceError

    router = _router(3, dyn=False)
    router.replicas[0].routable = False
    router.replicas[1].routable = False
    router.replicas[2].routable = False
    with pytest.raises(FenceError, match="no routable replica"):
        router.submit("sssp", {"source": 0})


def test_drain_last_routable_replica_refused():
    router = _router(2, dyn=False)
    router.begin_drain(0)
    with pytest.raises(ValueError, match="last routable"):
        router.begin_drain(1)
    router.rejoin(0)
    with pytest.raises(ValueError, match="already draining"):
        router.begin_drain(0)
        router.begin_drain(0)


def test_rejoin_with_incomplete_catchup_is_loud():
    from libgrape_lite_tpu.fleet import FenceViolationError

    router = _router(2)
    router.begin_drain(0)
    router.fence += 1  # a fence move that never logged catch-up
    with pytest.raises(FenceViolationError, match="catch-up log"):
        router.rejoin(0)


@pytest.mark.parametrize("R", [2, 3])
def test_drain_mid_stream_byte_identity(R):
    """THE drill: R replicas serving a stream with concurrent ingest,
    one replica drained mid-stream (offline forced repack, rejoins
    through its catch-up log) — zero dropped queries, every per-query
    result byte-identical to the undrained R=1 run."""
    from libgrape_lite_tpu.fleet import run_fleet_script

    rng = np.random.default_rng(11)
    queries = [("sssp", {"source": int(s)})
               for s in rng.integers(0, 32, 18)]

    def run(R_, drain_at):
        router = _router(R_)
        reqs = run_fleet_script(
            router, queries, delta_ops=ADDS + [
                ("a", 1, 30, 0.2), ("a", 2, 28, 0.3), ("a", 5, 9, 0.7),
            ],
            ingest_every=6, drain_at=drain_at, drain_idx=0,
            offline=lambda s: s.ingest([], force_repack=True),
        )
        assert all(q.result is not None for q in reqs), "dropped query"
        return [
            q.result.values.tobytes() if q.result.ok else b""
            for q in reqs
        ], router

    want, _ = run(1, None)
    got, router = run(R, 7)
    assert got == want, f"R={R} drained run diverged from R=1"
    assert router.replicas[0].drains == 1
    # the drained replica rejoined at the fence and genuinely served
    assert router.replicas[0].version == router.fence
    assert all(r.served > 0 for r in router.replicas)


def test_drain_catchup_applies_missed_deltas():
    """An ingest landing WHILE a replica drains goes to its catch-up
    log and replays at rejoin — both replicas then answer the
    post-delta query identically."""
    router = _router(2)
    for s in SOURCES:
        router.submit("sssp", {"source": s})
    router.drain()
    router.begin_drain(0)
    rep = router.ingest(ADDS)
    assert rep["applied_replicas"] == 1
    assert router.replicas[0].version == 0  # still pre-delta
    out = router.rejoin(0)
    assert out["catchup_ops"] == len(ADDS)
    assert router.replicas[0].version == router.fence == 1
    # both replicas now answer the delta-dependent query identically
    w = {}
    for r in router.replicas:
        res = r.session.serve([("sssp", {"source": 0})])
        assert res[0].ok
        w[r.idx] = res[0].values.tobytes()
    assert w[0] == w[1]


def test_fleet_script_threads_submit_kwargs():
    """Review-pass regression: a stream-wide --max_rounds must reach
    the underlying queue on the fleet path exactly as on the plain
    one — a dropped limit silently changes results and round counts."""
    from libgrape_lite_tpu.fleet import run_fleet_script

    queries = [("sssp", {"source": s}) for s in SOURCES]
    router = _router(2, dyn=False)
    reqs = run_fleet_script(router, queries,
                            submit_kwargs={"max_rounds": 1})
    assert all(q.result.ok for q in reqs)
    assert all(q.result.rounds <= 1 for q in reqs), [
        q.result.rounds for q in reqs
    ]
    assert all(q.max_rounds == 1 for q in reqs)


def test_rejected_readmission_places_no_buffers():
    """Review-pass regression: a budget REJECT must not leave the
    tenant's fragment re-placed in HBM (admit decides first, buffers
    place second), and a rejected re-pricing must keep the prior
    resident entry so used_bytes stays truthful."""
    from libgrape_lite_tpu.fleet import (
        FleetAdmissionError,
        FleetBudget,
        FleetManager,
        Footprint,
        fragment_bytes,
    )
    from libgrape_lite_tpu.serve import ServeSession

    fa = build_graph(2, seed=3)
    sa = ServeSession(fa)
    cap = int(fragment_bytes(fa) * 1.2)
    mgr = FleetManager(FleetBudget(capacity_bytes=cap))
    mgr.add_tenant("a", sa)
    mgr.submit("a", "sssp", {"source": 0})
    mgr.drain()
    # wedge the budget with a non-evictable phantom bigger than the
    # remaining headroom, then evict a and try to come back
    mgr.budget.release("a")
    mgr.tenants["a"].admitted = False
    sa.release_device()
    mgr.budget.admit(
        "pinned", Footprint(frag_bytes=cap, frag_keys={-1: cap}),
        evictable=False,
    )
    used_before = mgr.budget.used_bytes()
    mgr.submit("a", "sssp", {"source": 0})
    with pytest.raises(FleetAdmissionError, match="rejected"):
        mgr.drain()
    assert not sa.resident, (
        "reject left the evicted tenant's buffers placed"
    )
    assert mgr.budget.used_bytes() == used_before


def test_budget_readmit_reject_restores_prior_entry():
    from libgrape_lite_tpu.fleet import FleetBudget, Footprint

    b = FleetBudget(capacity_bytes=1000)
    b.admit("a", Footprint(frag_bytes=400, frag_keys={1: 400}))
    b.admit("pinned", Footprint(frag_bytes=500, frag_keys={2: 500}),
            evictable=False)
    # re-pricing a at a footprint that no longer fits must keep the
    # OLD entry (a is still resident at 400B), not forget it
    d = b.admit("a", Footprint(frag_bytes=800, frag_keys={1: 800}))
    assert not d["admitted"]
    assert "a" in b.residents
    assert b.used_bytes() == 900


# ---- priority / deadline scheduling --------------------------------------


def test_priority_class_dispatches_first_and_never_coalesces():
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

    sess = ServeSession(build_graph(2), policy=BatchPolicy(max_batch=8))
    low = [sess.submit("sssp", {"source": s}) for s in SOURCES[:2]]
    high = [sess.submit("sssp", {"source": s}, priority=5)
            for s in SOURCES[2:]]
    first = sess.pump(force=True)
    # the high class ships first, FIFO within the class, and the low
    # requests did NOT ride the urgent batch
    assert {r.request_id for r in first} == {r.id for r in high}
    assert all(not r.done for r in low)
    rest = sess.drain()
    assert {r.request_id for r in rest} == {r.id for r in low}
    assert sess.queue.batch_hist == {2: 2}


def test_deadline_expiry_fails_with_reason_never_drops():
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

    sess = ServeSession(build_graph(2),
                        policy=BatchPolicy(max_batch=8, max_wait_s=60.0))
    doomed = sess.submit("sssp", {"source": 0}, deadline_s=0.001)
    live = sess.submit("sssp", {"source": 7})
    time.sleep(0.01)
    out = sess.drain()
    assert len(out) == 2
    assert doomed.done and not doomed.result.ok
    assert doomed.result.error["reason"] == "deadline_expired"
    assert doomed.result.error["waited_s"] > 0
    assert sess.queue.expired == 1
    assert live.done and live.result.ok


def test_deadline_expiry_surfaces_through_async_pump():
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

    sess = ServeSession(build_graph(2), policy=BatchPolicy(max_batch=4))
    pump = sess.async_pump(window=2)
    doomed = sess.submit("sssp", {"source": 0}, deadline_s=0.001)
    live = sess.submit("sssp", {"source": 7})
    time.sleep(0.01)
    out = pump.drain()
    assert doomed.done and not doomed.result.ok
    assert doomed.result.error["reason"] == "deadline_expired"
    assert live.done and live.result.ok
    assert any(r.request_id == doomed.id for r in out), (
        "expired result was not returned by the pump"
    )
    pump.close()


# ---- threaded admission front --------------------------------------------


def test_arrival_feeder_real_wall_clock_arrivals():
    from libgrape_lite_tpu.serve import (
        ArrivalFeeder,
        BatchPolicy,
        ServeSession,
    )

    sess = ServeSession(
        build_graph(2),
        policy=BatchPolicy(max_batch=4, max_wait_s=0.002),
    )
    stream = [("sssp", {"source": s % 32}) for s in range(12)]
    feeder = ArrivalFeeder(sess.submit, stream, rate_qps=400.0)
    results = []
    feeder.start()
    while feeder.is_alive() or sess.queue.pending():
        got = sess.pump()  # NOT forced: max_wait_s genuinely gates
        results.extend(got)
        if not got:
            time.sleep(5e-4)
    feeder.join()
    results.extend(sess.drain())
    assert len(results) == 12 and all(r.ok for r in results)
    # arrivals are genuinely spread in wall-clock time
    stamps = [r.submitted_s for r in feeder.requests]
    assert stamps == sorted(stamps)
    assert stamps[-1] - stamps[0] >= 11 * (1.0 / 400.0) * 0.5
    # the wait record saw real (non-zero) queueing
    assert sess.queue.admission_waits


def test_feeder_rejects_nonpositive_rate():
    from libgrape_lite_tpu.serve import ArrivalFeeder

    with pytest.raises(ValueError, match="rate_qps"):
        ArrivalFeeder(lambda *a, **k: None, [], 0.0)


# ---- khop: the serve-routable sampling workload --------------------------


def test_khop_matches_depth_bounded_bfs(graph_cache):
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.worker.worker import Worker

    frag = graph_cache(2)
    wb = Worker(APP_REGISTRY["bfs"](), frag)
    wb.query(source=6)
    full = wb.result_values()
    wk = Worker(APP_REGISTRY["khop"](k=2), frag)
    wk.query(source=6)
    got = wk.result_values()
    want = np.where((full >= 0) & (full <= 2), full, -1)
    assert got.tobytes() == want.tobytes()
    assert wk.rounds <= 2
    assert (got >= -1).all() and (got <= 2).all()
    assert (got == -1).any()  # p2p-31's 2-hop ball is not the graph


def test_khop_serve_batched_identical_per_lane(graph_cache):
    from libgrape_lite_tpu.models import APP_REGISTRY
    from libgrape_lite_tpu.serve import BatchPolicy, ServeSession

    frag = graph_cache(2)
    sources = [6, 17, 3, 999999]
    want = _sequential(
        frag, lambda: APP_REGISTRY["khop"](k=2), sources
    )
    sess = ServeSession(frag, policy=BatchPolicy(max_batch=4))
    res = sess.serve([("khop", {"source": s}) for s in sources])
    for r, s in zip(res, sources):
        assert r.ok
        assert r.values.tobytes() == want[s].tobytes()
    assert sess.queue.batch_hist == {4: 1}  # genuinely coalesced


def test_khop_k_is_a_compile_key():
    from libgrape_lite_tpu.models import APP_REGISTRY

    a2 = APP_REGISTRY["khop"](k=2)
    a3 = APP_REGISTRY["khop"](k=3)
    assert a2.trace_key() != a3.trace_key()
    assert a2.max_rounds == 2 and a3.max_rounds == 3
    with pytest.raises(ValueError, match="k >= 1"):
        APP_REGISTRY["khop"](k=0)


# ---- CLI fleet surface ----------------------------------------------------


def test_cli_serve_fleet_replicas_and_tenants(capsys, tmp_path):
    import json

    from libgrape_lite_tpu.cli import serve_main
    from tests.conftest import dataset_path

    dump = tmp_path / "fleet.res"
    serve_main([
        "--efile", dataset_path("p2p-31.e"),
        "--vfile", dataset_path("p2p-31.v"),
        "--fnum", "2", "--application", "sssp",
        "--sources", "6,17,3,42,11,12",
        "--max_batch", "4", "--replicas", "2", "--tenants", "2",
        "--drain_at", "3", "--dump_results", str(dump),
    ])
    out = capsys.readouterr().out
    rec = json.loads(
        [l for l in out.splitlines() if l.startswith("{")][-1]
    )
    assert rec["queries"] == 6 and rec["failed"] == 0
    fl = rec["fleet"]
    assert fl["replicas"] == 2 and fl["tenants"] == 2
    assert fl["dropped"] == 0 and fl["drains"] == 1
    assert fl["rejoins"] == 1  # drained AND back in rotation
    assert all(
        r["served"] > 0 for r in fl["router"]["replicas"].values()
    )
    assert "per_app_ms" in rec and "sssp" in rec["per_app_ms"]
    lines = dump.read_text().splitlines()
    assert len(lines) == 6
    assert all(l.split()[2] == "1" for l in lines)  # every query ok


# ---- bench schema: the self-consistency gate -----------------------------


def _schema_mod():
    import importlib
    import os

    sys.path.insert(0, os.path.join(
        os.path.dirname(__file__), "..", "scripts"))
    import check_bench_schema

    return importlib.reload(check_bench_schema)


def test_bench_schema_self_check_clean_and_fleet_wired():
    c = _schema_mod()
    assert c.self_check() == []
    assert "fleet" in c.SCHEMA and "fleet" in c._TOP
    blk = {
        "scale": 10, "replicas": 2, "tenants": 0, "queries": 64,
        "ok": 64, "dropped": 0, "drain_at": 32, "drained_replica": 0,
        "drain_wall_s": 0.5, "catchup_ops": 64, "updates": 128,
        "updates_per_s": 100.0, "fence": 4, "byte_identical": True,
        "per_replica": {
            "r0": {"qps": 10.0, "p50_ms": 1.0, "p99_ms": 2.0,
                   "served": 30, "ok": 30},
        },
        "evictions": 0, "readmit_compiles": 0,
    }
    rec = {"metric": "m", "value": 1, "unit": "u", "vs_baseline": 1.0,
           "fleet": blk}
    assert c.validate_record(rec) == []
    bad = {**rec, "fleet": {**blk, "byte_identical": 1}}
    assert any("byte_identical" in e for e in c.validate_record(bad))
    bad2 = {**rec, "fleet": {**blk, "dropped": True}}
    assert any("dropped" in e for e in c.validate_record(bad2))
    bad3 = {**rec, "fleet": {**blk, "per_replica": {
        "x9": blk["per_replica"]["r0"]}}}
    assert any("r<k>" in e for e in c.validate_record(bad3))


def test_bench_schema_self_check_catches_unwired_block():
    """The wiring-gap gate itself: a block declared in _TOP but absent
    from SCHEMA/_BLOCKS (the PR 9/11/12 bug class) must fail
    self_check — and the CLI exits 2 on it."""
    c = _schema_mod()
    c._TOP["ghost_block"] = (dict, False)
    try:
        import os

        errors = c.self_check()
        assert errors, "an unwired declared block passed self_check"
        assert any("ghost_block" in e for e in errors)
        r05 = os.path.join(
            os.path.dirname(__file__), "..", "BENCH_r05.json")
        assert c.main([r05]) == 2
    finally:
        del c._TOP["ghost_block"]
    assert c.self_check() == []


def test_bench_schema_self_check_catches_unchecked_block(monkeypatch):
    """A block wired into the tables but skipped by validate_record
    must also fail (the derivation is what makes this impossible —
    the gate pins that it STAYS impossible)."""
    c = _schema_mod()
    orig = c.validate_record

    def lazy_validate(record):
        errs = orig(record)
        return [e for e in errs if not e.startswith("fleet")]

    monkeypatch.setattr(c, "validate_record", lazy_validate)
    errors = c.self_check()
    assert any("fleet" in e for e in errors)


# ---- obs: per-replica attribution ----------------------------------------


def test_router_obs_per_replica_tracks():
    from libgrape_lite_tpu import obs

    obs.configure(in_memory=True)
    try:
        router = _router(2, dyn=False)
        for s in SOURCES:
            router.submit("sssp", {"source": s})
        router.drain()
        evs = obs.history()
        reps = {
            e["args"]["replica"] for e in evs
            if e.get("name") == "fleet_replica"
        }
        assert reps == {0, 1}
        router.begin_drain(0)
        router.rejoin(0)
        kinds = {e.get("name") for e in obs.history()}
        assert "fleet_drain_begin" in kinds and "fleet_rejoin" in kinds
    finally:
        obs.reset()
