"""Strict-tile Pallas SpMV vs the XLA segment-sum path (interpret mode
on CPU; the on-TPU A/B lives in scripts/spmv_ab.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from libgrape_lite_tpu.ops.segment import segment_reduce
from libgrape_lite_tpu.ops.spmv import plan_tiles, spmv_strict


def _case(n_rows, degrees, seed=0):
    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(n_rows), degrees)
    vals = rng.normal(size=len(src)).astype(np.float32)
    return src.astype(np.int32), vals


@pytest.mark.parametrize(
    "shape",
    [
        ("hub", 8, [4000, 1000, 500, 100, 50, 20, 10, 4]),
        ("uniform", 64, [16] * 64),
        ("mixed", 32, [512] + [3] * 31),
    ],
    ids=lambda s: s[0],
)
def test_spmv_strict_matches_segment_sum(shape):
    _, n_rows, degrees = shape
    src, vals = _case(n_rows, degrees)
    vp = n_rows + 1  # leave an empty row to check zero-fill
    tile = 512
    # pad edges to the tile grid with overflow rows (vp)
    row_lo, rmax, num_tiles = plan_tiles(src, tile, vp)

    got = spmv_strict(
        jnp.asarray(vals), jnp.asarray(src), row_lo, vp, tile, rmax,
        interpret=True,
    )
    want = segment_reduce(jnp.asarray(vals), jnp.asarray(src), vp, "sum")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-5)


def test_spmv_strict_with_padded_edges():
    src, vals = _case(16, [32] * 16)
    vp = 16
    # simulate CSR padding: pad rows carry src == vp, value garbage
    src_p = np.concatenate([src, np.full(100, vp, np.int32)])
    vals_p = np.concatenate([vals, np.full(100, 7.7, np.float32)])
    row_lo, rmax, num_tiles = plan_tiles(src_p, 256, vp)
    got = spmv_strict(
        jnp.asarray(vals_p * (src_p != vp)), jnp.asarray(src_p), row_lo,
        vp, 256, rmax, interpret=True,
    )
    want = segment_reduce(jnp.asarray(vals), jnp.asarray(src), vp, "sum")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-5)


def test_plan_tiles_spans():
    src = np.array([0, 0, 0, 1, 1, 2, 5, 5, 5, 5], dtype=np.int32)
    row_lo, rmax, nt = plan_tiles(src, 4, 6)
    assert nt == 3
    np.testing.assert_array_equal(row_lo, [0, 1, 5])
    assert rmax >= 2  # lane-aligned to 128 in practice


def test_pagerank_through_strict_plan(monkeypatch):
    """End-to-end consumer: GRAPE_SPMV=strict routes PageRank's pull
    through the strict-tile kernel (interpret mode on CPU); ranks match
    the XLA path within f32 accumulation error."""
    from libgrape_lite_tpu.models import PageRank
    from tests.test_lcc_threshold import er_graph
    from tests.test_worker import build_fragment
    from tests.verifiers import collect_worker_result

    n = 64
    src, dst = er_graph(n, p=0.2, seed=5)
    frag = build_fragment(src, dst, None, n, 4)
    base = collect_worker_result(PageRank(), frag, max_round=10)
    monkeypatch.setenv("GRAPE_SPMV", "strict")
    app = PageRank()
    strict = collect_worker_result(app, frag, max_round=10)
    assert app._spmv_rmax > 0  # the plan actually activated
    for k in base:
        b, s = float(base[k]), float(strict[k])
        assert abs(b - s) <= 1e-4 * max(abs(b), 1e-9), (k, b, s)
    monkeypatch.setenv("GRAPE_SPMV", "xla")
    app_x = PageRank()
    collect_worker_result(app_x, frag, max_round=10)
    assert app_x._spmv_rmax == 0  # explicit opt-out takes the XLA path
