"""Mutable-fragment tests (analogue of `tests/mutable_fragment_tests.cc`
driven by `app_tests.sh:115-167`): load p2p-31.e.mutable_base, apply
p2p-31.e.mutable_delta, results must equal the plain p2p-31 goldens."""

import numpy as np
import pytest

from tests.conftest import dataset_path
from tests.test_apps_golden import run_worker
from tests.verifiers import eps_verify, exact_verify, load_golden, wcc_verify

FNUMS = [1, 4]


@pytest.fixture(scope="module")
def mutated_cache():
    from libgrape_lite_tpu.fragment.loader import LoadGraphSpec
    from libgrape_lite_tpu.fragment.mutation import LoadGraphAndMutate
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec

    cache = {}

    def get(fnum):
        if fnum not in cache:
            spec = LoadGraphSpec(weighted=True, edata_dtype=np.float64)
            cache[fnum] = LoadGraphAndMutate(
                dataset_path("p2p-31.e.mutable_base"),
                dataset_path("p2p-31.v"),
                dataset_path("p2p-31.e.mutable_delta"),
                None,
                CommSpec(fnum=fnum),
                spec,
            )
        return cache[fnum]

    return get


@pytest.mark.parametrize("fnum", FNUMS)
def test_mutable_sssp(mutated_cache, fnum):
    from libgrape_lite_tpu.models import SSSP

    res = run_worker(SSSP(), mutated_cache(fnum), source=6)
    exact_verify(res, load_golden(dataset_path("p2p-31-SSSP")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_mutable_bfs(mutated_cache, fnum):
    from libgrape_lite_tpu.models import BFS

    res = run_worker(BFS(), mutated_cache(fnum), source=6)
    exact_verify(res, load_golden(dataset_path("p2p-31-BFS")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_mutable_pagerank(mutated_cache, fnum):
    from libgrape_lite_tpu.models import PageRank

    res = run_worker(PageRank(), mutated_cache(fnum), delta=0.85, max_round=10)
    eps_verify(res, load_golden(dataset_path("p2p-31-PR")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_mutable_wcc(mutated_cache, fnum):
    from libgrape_lite_tpu.models import WCC

    res = run_worker(WCC(), mutated_cache(fnum))
    wcc_verify(res, load_golden(dataset_path("p2p-31-WCC")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_mutable_cdlp(mutated_cache, fnum):
    from libgrape_lite_tpu.models import CDLP

    res = run_worker(CDLP(), mutated_cache(fnum), max_round=10)
    exact_verify(res, load_golden(dataset_path("p2p-31-CDLP")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_mutable_lcc(mutated_cache, fnum):
    from libgrape_lite_tpu.models import LCC

    res = run_worker(LCC(), mutated_cache(fnum))
    eps_verify(res, load_golden(dataset_path("p2p-31-LCC")))


def test_staged_mutator_api():
    """MutationContext-style staged ops on a tiny graph."""
    from libgrape_lite_tpu.fragment.mutation import BasicFragmentMutator
    from libgrape_lite_tpu.models import SSSP
    from libgrape_lite_tpu.worker.worker import Worker
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.vertex_map.partitioner import MapPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    oids = np.arange(4, dtype=np.int64)
    cs = CommSpec(fnum=2)
    vm = VertexMap.build(oids, MapPartitioner(2, oids))
    frag = ShardedEdgecutFragment.build(
        cs, vm,
        np.array([0, 1, 2]), np.array([1, 2, 3]),
        np.array([1.0, 1.0, 10.0]),
        directed=False, retain_edge_list=True,
    )
    m = BasicFragmentMutator()
    m.AddVertex(4)
    m.AddEdge(2, 4, 1.0)
    m.AddEdge(4, 3, 1.0)  # shortcut 2-4-3 cheaper than 2-3 (10)
    m.RemoveEdge(0, 1)
    m.RemoveEdge(1, 0)
    frag2 = m.mutate(frag)

    w = Worker(SSSP(), frag2)
    w.query(source=1)
    oid_to_val = {}
    vals = w.result_values()
    for f in range(frag2.fnum):
        for o, v in zip(
            frag2.inner_oids(f).tolist(),
            vals[f, : frag2.inner_vertices_num(f)].tolist(),
        ):
            oid_to_val[o] = v
    assert oid_to_val[0] == np.inf  # edge removed
    assert oid_to_val[2] == 1.0
    assert oid_to_val[4] == 2.0  # via new vertex
    assert oid_to_val[3] == 3.0  # via the shortcut, not the 10-edge
