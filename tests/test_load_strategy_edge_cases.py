"""Regression: undirected + kOnlyIn must alias the symmetrised CSR like
kOnlyOut instead of crashing on an empty CSR stack (ADVICE r1,
fragment/edgecut.py need_oe/need_ie)."""

import numpy as np

from libgrape_lite_tpu.utils.types import LoadStrategy


def _tiny_frag(load_strategy, directed=False):
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.vertex_map.partitioner import HashPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    oids = np.arange(6, dtype=np.int64)
    src = np.array([0, 1, 2, 3, 4], dtype=np.int64)
    dst = np.array([1, 2, 3, 4, 5], dtype=np.int64)
    vm = VertexMap.build(oids, HashPartitioner(2))
    return ShardedEdgecutFragment.build(
        CommSpec(fnum=2), vm, src, dst, None,
        directed=directed, load_strategy=load_strategy,
    )


def _total_degree(frag):
    return sum(int(c.num_edges) for c in frag.host_oe)


def test_undirected_konlyin_builds():
    frag = _tiny_frag(LoadStrategy.kOnlyIn, directed=False)
    # symmetrised aliased CSR: every vertex on the path sees both nbrs
    assert _total_degree(frag) == 10  # 5 edges symmetrised


def test_undirected_konlyin_matches_konlyout():
    fin = _tiny_frag(LoadStrategy.kOnlyIn, directed=False)
    fout = _tiny_frag(LoadStrategy.kOnlyOut, directed=False)
    for a, b in zip(fin.host_oe, fout.host_oe):
        np.testing.assert_array_equal(a.indptr, b.indptr)
        np.testing.assert_array_equal(a.edge_nbr, b.edge_nbr)


def test_hbm_budget_and_skew_warnings(capsys, monkeypatch):
    """Skewed partitions and over-budget fragments must warn before
    device placement (VERDICT r3 weak #6) — the failure mode is an
    opaque allocator error otherwise."""
    import numpy as np

    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.utils.types import LoadStrategy
    from libgrape_lite_tpu.vertex_map.partitioner import MapPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    # all edges incident to fragment 0's vertices -> heavy skew
    n = 64
    src = np.zeros(200, dtype=np.int64)
    dst = np.arange(200, dtype=np.int64) % n
    oids = np.arange(n, dtype=np.int64)
    comm = CommSpec(fnum=4)
    vm = VertexMap.build(oids, MapPartitioner(4, oids))
    monkeypatch.setenv("GRAPE_HBM_BYTES", "1024")  # absurdly small
    ShardedEdgecutFragment.build(
        comm, vm, src, dst, None, directed=False,
        load_strategy=LoadStrategy.kBothOutIn,
    )
    err = capsys.readouterr().err
    assert "partition skew" in err
    assert "HBM budget" in err

    # a balanced small graph under a sane budget warns about neither
    monkeypatch.setenv("GRAPE_HBM_BYTES", str(16 << 30))
    rng = np.random.default_rng(0)
    ShardedEdgecutFragment.build(
        comm, vm, rng.integers(0, n, 500), rng.integers(0, n, 500),
        None, directed=False, load_strategy=LoadStrategy.kBothOutIn,
    )
    err = capsys.readouterr().err
    assert "partition skew" not in err
    assert "HBM budget" not in err
