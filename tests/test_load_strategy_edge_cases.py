"""Regression: undirected + kOnlyIn must alias the symmetrised CSR like
kOnlyOut instead of crashing on an empty CSR stack (ADVICE r1,
fragment/edgecut.py need_oe/need_ie)."""

import numpy as np

from libgrape_lite_tpu.utils.types import LoadStrategy


def _tiny_frag(load_strategy, directed=False):
    from libgrape_lite_tpu.fragment.edgecut import ShardedEdgecutFragment
    from libgrape_lite_tpu.parallel.comm_spec import CommSpec
    from libgrape_lite_tpu.vertex_map.partitioner import HashPartitioner
    from libgrape_lite_tpu.vertex_map.vertex_map import VertexMap

    oids = np.arange(6, dtype=np.int64)
    src = np.array([0, 1, 2, 3, 4], dtype=np.int64)
    dst = np.array([1, 2, 3, 4, 5], dtype=np.int64)
    vm = VertexMap.build(oids, HashPartitioner(2))
    return ShardedEdgecutFragment.build(
        CommSpec(fnum=2), vm, src, dst, None,
        directed=directed, load_strategy=load_strategy,
    )


def _total_degree(frag):
    return sum(int(c.num_edges) for c in frag.host_oe)


def test_undirected_konlyin_builds():
    frag = _tiny_frag(LoadStrategy.kOnlyIn, directed=False)
    # symmetrised aliased CSR: every vertex on the path sees both nbrs
    assert _total_degree(frag) == 10  # 5 edges symmetrised


def test_undirected_konlyin_matches_konlyout():
    fin = _tiny_frag(LoadStrategy.kOnlyIn, directed=False)
    fout = _tiny_frag(LoadStrategy.kOnlyOut, directed=False)
    for a, b in zip(fin.host_oe, fout.host_oe):
        np.testing.assert_array_equal(a.indptr, b.indptr)
        np.testing.assert_array_equal(a.edge_nbr, b.edge_nbr)
