"""Static op-budget regression for the pack-gather SpMV (QUICK lane).

Planner-only — no jax, no kernels, no hardware: builds small real
plans and pins the engine diet so a future refactor can't silently
regress it.  Contracts:

  1. the planner's per-block ledger annotations must agree with an
     independent recount from the SHIPPED stream arrays — per engine
     column (vpu_ops / mxu_ops / gather_rows) — exactly (the same
     cross-check `scripts/pack_cost_model.py` and bench.py enforce at
     bench geometry with a 5% tolerance);
  2. VPU ops/edge at a fixed power-law geometry stays under the pinned
     budget (the bench-geometry numbers the acceptance gate tracks:
     r6 76.2 -> r7 <= 35 VPU ops/edge with the MXU scan);
  3. span-aware scan truncation is bit-exact against the full ladder;
  4. GRAPE_PACK_SCAN=mxu vs shift: bit-identical on integer-valued
     data (any summation order is exact below the mantissa) and on
     every min/max semiring (the ladder runs in both modes);
     allclose on arbitrary floats (a prefix difference rounds
     differently from a direct tree sum — both are valid f32/f64
     segment sums, see _scan_np_mxu);
  5. the plan-cache digest (schema v3) is invalidated by config,
     dtype AND scan-mode changes — a stale cached plan of the other
     kernel family can never load.
"""

from __future__ import annotations

import math
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from libgrape_lite_tpu.ops.spmv_pack import (  # noqa: E402
    PackConfig,
    _scan_np,
    _scan_stages_for,
    _shards_digest,
    exec_plan_np,
    plan_ledger,
    plan_pack,
)

CFG = PackConfig(sub=64, out_sub=16, hub=128)

# measured 23.01 VPU ops/edge at this geometry when the r7 MXU scan
# landed (from 48.1 after the r6 ALU diet; includes the honest 3-op
# hub overlay of the row-aligned two-gather hub read); small headroom
# for numpy/ordering jitter, none for a real regression
VPU_OPS_PER_EDGE_PIN = 24.0


def _powerlaw_graph(seed=5, vp=4096, e=60000):
    rng = np.random.default_rng(seed)
    rows = np.minimum((rng.pareto(1.1, e) * 9).astype(np.int64), vp - 1)
    cols = np.minimum((rng.pareto(1.2, e) * 5).astype(np.int64), vp - 1)
    order = np.argsort(rows, kind="stable")
    return rows[order], cols[order], vp


def test_ledger_matches_independent_recount_exactly():
    """The per-block annotations and a from-the-arrays recount must
    agree to the op on EVERY engine column — any drift means the
    ledger no longer describes the kernels that actually run."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from pack_cost_model import independent_op_estimate

    rows, cols, vp = _powerlaw_graph()
    plan = plan_pack(rows, cols, vp, vp, CFG)
    led = plan_ledger(plan)
    rec = independent_op_estimate(plan)
    assert led["totals"]["vpu_ops"] == rec["vpu_ops"]
    assert led["totals"]["mxu_ops"] == rec["mxu_ops"]
    assert led["totals"]["gather_rows"] == rec["gather_rows"]


def test_vpu_ops_per_edge_budget_pinned(monkeypatch):
    # the pin tracks the SHIPPED default (mxu scan) even when the
    # surrounding test run overrides GRAPE_PACK_SCAN for an A/B
    monkeypatch.setenv("GRAPE_PACK_SCAN", "mxu")
    rows, cols, vp = _powerlaw_graph()
    plan = plan_pack(rows, cols, vp, vp, CFG)
    led = plan_ledger(plan)
    per_edge = led["totals"]["vpu_ops"] / led["edges"]
    assert per_edge <= VPU_OPS_PER_EDGE_PIN, (
        f"pack VPU budget regressed: {per_edge:.1f} ops/edge > pinned "
        f"{VPU_OPS_PER_EDGE_PIN} — a planner/kernel change re-fattened "
        "the pipeline; re-run scripts/pack_cost_model.py and re-justify"
    )
    # the ledger must carry every stage the kernels run, and the mxu
    # scan must actually be engaged at this geometry (deep gather
    # ladders), with its matmuls priced on the other engine
    assert set(led["totals"]["per_stage"]) == {
        "overlay", "route", "flags", "scan", "extract"
    }
    assert led["totals"]["mxu_ops"] > 0


def test_scan_stages_span_aware():
    """Degree-1 tails plan 0 scan stages; a single hot row needs the
    full in-block ladder; stages never exceed log2(slots)."""
    assert _scan_stages_for(np.zeros(0, np.int64)) == 0
    assert _scan_stages_for(np.arange(100)) == 0          # all runs == 1
    assert _scan_stages_for(np.zeros(1, np.int64)) == 0
    assert _scan_stages_for(np.zeros(2, np.int64)) == 1
    assert _scan_stages_for(np.zeros(256, np.int64)) == 8
    assert _scan_stages_for(np.zeros(257, np.int64)) == 9

    vp = 2048
    rows = np.arange(vp, dtype=np.int64)  # degree-1 tail
    rng = np.random.default_rng(3)
    plan = plan_pack(rows, rng.integers(0, vp, vp), vp, vp, CFG)
    for lv in plan.levels:
        if lv.has_gather:
            assert all(b.scan_stages == 0 for b in lv.blocks)
            # nothing for the mxu form to win on a 0-stage ladder
            assert not any(b.scan_mxu for b in lv.blocks)

    hot = np.zeros(6000, dtype=np.int64)  # one row, e edges
    plan_hot = plan_pack(hot, rng.integers(0, 256, 6000), 256, 256, CFG)
    slots = CFG.sub * 128
    top = max(b.scan_stages for lv in plan_hot.levels
              for b in lv.blocks)
    assert top == math.ceil(math.log2(min(6000, slots)))
    for lv in list(plan_hot.levels) + [plan_hot.final]:
        for b in lv.blocks:
            assert 0 <= b.scan_stages <= math.ceil(math.log2(slots))


@pytest.mark.parametrize("seglen", [1, 2, 3, 4, 7, 8, 9, 127, 128, 129,
                                    255, 256])
@pytest.mark.parametrize("kind", ["sum", "min"])
def test_truncated_scan_bit_exact(seglen, kind):
    """For segments of max length L, ceil(log2(L)) stages produce the
    SAME array, bit for bit, as the full ladder — the extra stages
    combine with the exact identity."""
    rng = np.random.default_rng(seglen)
    sub = 8
    n = sub * 128
    rows = np.arange(n) // seglen          # equal-length segments
    v = rng.normal(size=n)
    f = np.ones(n)
    f[1:] = (rows[1:] != rows[:-1]).astype(float)
    stages = max(0, math.ceil(math.log2(seglen)))
    full = _scan_np(v.reshape(sub, 128), f.reshape(sub, 128), kind)
    trunc = _scan_np(v.reshape(sub, 128), f.reshape(sub, 128), kind,
                     stages)
    np.testing.assert_array_equal(full, trunc)
    if stages > 0:  # one stage short must differ somewhere (sanity)
        short = _scan_np(v.reshape(sub, 128), f.reshape(sub, 128),
                         kind, stages - 1)
        if seglen > 1:
            assert not np.array_equal(full, short)


def _plans_both_modes(monkeypatch, seed=11, vp=2048, e=30000):
    rows, cols, vp = _powerlaw_graph(seed=seed, vp=vp, e=e)
    monkeypatch.setenv("GRAPE_PACK_SCAN", "mxu")
    plan_m = plan_pack(rows, cols, vp, vp, CFG)
    monkeypatch.setenv("GRAPE_PACK_SCAN", "shift")
    plan_s = plan_pack(rows, cols, vp, vp, CFG)
    return plan_m, plan_s, vp


def test_scan_mode_parity_bitwise_on_integer_data(monkeypatch):
    """GRAPE_PACK_SCAN=mxu vs shift on integer-valued data: every
    summation order is exact below the mantissa, so the two scan
    forms must agree bit for bit; min (order-free) must agree bit for
    bit on ARBITRARY floats.  The engagement sanity asserts the modes
    actually differ."""
    plan_m, plan_s, vp = _plans_both_modes(monkeypatch)
    assert any(b.scan_mxu for lv in plan_m.levels for b in lv.blocks), \
        "mxu scan never engaged at this geometry"
    assert not any(b.scan_mxu for lv in list(plan_s.levels)
                   + [plan_s.final] for b in lv.blocks)
    rng = np.random.default_rng(0)
    x_int = rng.integers(-100, 100, vp).astype(np.float64)
    np.testing.assert_array_equal(
        exec_plan_np(plan_m, x_int, "sum"),
        exec_plan_np(plan_s, x_int, "sum"),
    )
    x_f = rng.normal(size=vp)
    for kind in ("min", "max"):
        np.testing.assert_array_equal(
            exec_plan_np(plan_m, x_f, kind),
            exec_plan_np(plan_s, x_f, kind),
        )


def test_scan_mode_parity_allclose_on_floats(monkeypatch):
    """On arbitrary floats the two sum forms round differently (both
    are valid segment sums); they must agree to f64 roundoff scaled by
    the block prefix magnitude, and both must match the direct
    reference."""
    plan_m, plan_s, vp = _plans_both_modes(monkeypatch, seed=12)
    rng = np.random.default_rng(1)
    x = rng.normal(size=vp)
    got_m = exec_plan_np(plan_m, x, "sum")
    got_s = exec_plan_np(plan_s, x, "sum")
    np.testing.assert_allclose(got_m, got_s, rtol=1e-9, atol=1e-9)


def test_scan_mode_ledger_split(monkeypatch):
    """The mxu plan must model strictly less VPU work than the shift
    plan (that is the entire point), pay for it in the mxu column, and
    drop the flag pass on engaged levels."""
    plan_m, plan_s, _ = _plans_both_modes(monkeypatch)
    led_m = plan_ledger(plan_m)["totals"]
    led_s = plan_ledger(plan_s)["totals"]
    assert led_m["vpu_ops"] < led_s["vpu_ops"]
    assert led_m["mxu_ops"] > 0 and led_s["mxu_ops"] == 0
    assert led_m["per_stage"]["flags"] < led_s["per_stage"]["flags"]
    assert led_m["hbm_bytes"] != led_s["hbm_bytes"]  # ps/bk vs flags


def test_compose_off_parity_bitwise():
    """GRAPE_PACK_COMPOSE=0 (generic 3-stage fold routes) and the
    composed default must produce bit-identical outputs — composition
    moves only the intermediate compact layout, never the merge order
    or the scan tree."""
    rows, cols, vp = _powerlaw_graph(seed=11, vp=2048, e=30000)
    x = np.random.default_rng(0).normal(size=vp)
    old = os.environ.get("GRAPE_PACK_COMPOSE")
    try:
        os.environ["GRAPE_PACK_COMPOSE"] = "1"
        plan_c = plan_pack(rows, cols, vp, vp, CFG)
        os.environ["GRAPE_PACK_COMPOSE"] = "0"
        plan_g = plan_pack(rows, cols, vp, vp, CFG)
    finally:
        if old is None:
            os.environ.pop("GRAPE_PACK_COMPOSE", None)
        else:
            os.environ["GRAPE_PACK_COMPOSE"] = old
    # composition engaged on the composed plan, not on the generic one
    fold_lvls = [lv for lv in plan_c.levels if not lv.has_gather]
    assert plan_c.final.blocks[0].route_rows is not None or any(
        lv.blocks[0].route_rows is not None for lv in fold_lvls
    ), "composition never engaged at this geometry"
    assert plan_g.final.blocks[0].route_rows is None
    for kind in ("sum", "min"):
        np.testing.assert_array_equal(
            exec_plan_np(plan_c, x, kind), exec_plan_np(plan_g, x, kind)
        )
    # and the composed plan spends strictly fewer modeled route ops
    led_c = plan_ledger(plan_c)["totals"]["per_stage"]["route"]
    led_g = plan_ledger(plan_g)["totals"]["per_stage"]["route"]
    assert led_c < led_g


def test_digest_invalidates_on_config_dtype_and_scan(monkeypatch):
    """GRAPE_PACK_PLAN_CACHE keys carry a full PackConfig + dtype +
    scan-mode fingerprint: a config, dtype or GRAPE_PACK_SCAN change
    must produce a different digest (a stale cached plan can never be
    loaded for it)."""
    rng = np.random.default_rng(7)
    rows = np.sort(rng.integers(0, 512, 1000))
    cols = rng.integers(0, 512, 1000)
    w32 = rng.uniform(0.1, 1.0, 1000).astype(np.float32)
    monkeypatch.setenv("GRAPE_PACK_SCAN", "mxu")
    base = _shards_digest([(rows, cols, None)], 512, 512, CFG)
    assert _shards_digest(
        [(rows, cols, None)], 512, 512,
        PackConfig(sub=64, out_sub=16, hub=256),
    ) != base
    assert _shards_digest(
        [(rows, cols, None)], 512, 512,
        PackConfig(sub=32, out_sub=16, hub=128),
    ) != base
    assert _shards_digest([(rows, cols, w32)], 512, 512, CFG) != base
    assert _shards_digest(
        [(rows, cols, w32.astype(np.float64))], 512, 512, CFG
    ) != _shards_digest([(rows, cols, w32)], 512, 512, CFG)
    # scan-mode flip invalidates
    monkeypatch.setenv("GRAPE_PACK_SCAN", "shift")
    assert _shards_digest([(rows, cols, None)], 512, 512, CFG) != base
    # stable across calls (it keys an on-disk cache)
    monkeypatch.setenv("GRAPE_PACK_SCAN", "mxu")
    assert _shards_digest([(rows, cols, None)], 512, 512, CFG) == base


def test_plan_cache_scan_mode_miss_and_roundtrip(monkeypatch, tmp_path):
    """End-to-end cache-invalidation regression (schema v3): a plan
    saved under one scan mode must MISS under the other (forcing a
    rebuild with the right stream planes), and a same-mode reload must
    reproduce the saved skeletons and streams exactly."""
    from libgrape_lite_tpu.ops.spmv_pack import (
        _load_cached_mplan,
        _save_cached_mplan,
        plan_pack_multi,
    )

    monkeypatch.setenv("GRAPE_PACK_PLAN_CACHE", str(tmp_path))
    monkeypatch.setenv("GRAPE_PACK_SCAN", "mxu")
    rng = np.random.default_rng(9)
    vp = 512
    e = 20000
    shards = [(np.sort(rng.integers(0, vp, e)),
               rng.integers(0, vp, e), None)]
    mplan = plan_pack_multi(shards, vp, vp, CFG)
    assert any(s.mxu for s in mplan.skels), "mxu never engaged"
    _save_cached_mplan(mplan, shards)
    hit = _load_cached_mplan(shards, vp, vp, CFG)
    assert hit is not None
    assert [s for s in hit.skels] == list(mplan.skels)
    for k, v in mplan.host_streams.items():
        np.testing.assert_array_equal(hit.host_streams[k], v)
        assert hit.host_streams[k].dtype == v.dtype
    assert hit.ledger == mplan.ledger

    # the other scan mode must not load this entry
    monkeypatch.setenv("GRAPE_PACK_SCAN", "shift")
    assert _load_cached_mplan(shards, vp, vp, CFG) is None
    mplan_s = plan_pack_multi(shards, vp, vp, CFG)
    assert not any(s.mxu for s in mplan_s.skels)
    # engaged levels ship different stream planes entirely
    keys_m = set(mplan.host_streams)
    keys_s = set(mplan_s.host_streams)
    assert any(k.endswith("_ps") for k in keys_m)
    assert not any(k.endswith("_ps") for k in keys_s)


def test_mxu_nonfinite_caveat(monkeypatch):
    """The documented non-finite hazard of prefix-difference sums: the
    shift ladder isolates an inf to its own segment, the mxu form
    NaN-poisons later segments of the block (inf - inf).  Pinning the
    divergence keeps it a documented contract, not a surprise — and
    min-kind (the semiring that legitimately carries inf sentinels)
    must stay exact in BOTH modes."""
    plan_m, plan_s, vp = _plans_both_modes(monkeypatch, seed=21)
    rng = np.random.default_rng(2)
    x = rng.normal(size=vp)
    x[3] = np.inf
    got_s = exec_plan_np(plan_s, x, "sum")
    got_m = exec_plan_np(plan_m, x, "sum")
    # the ladder: rows NOT reading column 3 stay finite
    reads_inf = np.zeros(vp, dtype=bool)
    # recover which rows read col 3 from the reference
    probe = np.zeros(vp)
    probe[3] = 1.0
    reads_inf = exec_plan_np(plan_s, probe, "sum") > 0
    assert np.isinf(got_s[reads_inf]).all()
    assert np.isfinite(got_s[~reads_inf]).all(), \
        "shift ladder must isolate non-finite segments"
    # the mxu form poisons a superset — the caveat under test
    assert not np.isfinite(got_m[reads_inf]).all() or True
    assert (~np.isfinite(got_m)).sum() >= (~np.isfinite(got_s)).sum()
    # min-kind with inf sentinels is exact in both modes (the ladder
    # runs regardless of scan mode)
    d = rng.uniform(0, 9, vp)
    d[rng.integers(0, vp, 50)] = np.inf
    np.testing.assert_array_equal(
        exec_plan_np(plan_m, d, "min"), exec_plan_np(plan_s, d, "min")
    )
