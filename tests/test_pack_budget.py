"""Static op-budget regression for the pack-gather SpMV (QUICK lane).

Planner-only — no jax, no kernels, no hardware: builds small real
plans and pins the ALU diet so a future refactor can't silently
regress it.  Three contracts:

  1. the planner's per-block ledger annotations must agree with an
     independent recount from the SHIPPED stream arrays (the same
     cross-check `scripts/pack_cost_model.py` and bench.py enforce at
     bench geometry with a 5% tolerance — here, exactly);
  2. ops/edge at a fixed power-law geometry stays under the pinned
     budget (measured 48.1 at pin time; the bench-geometry number the
     acceptance gate tracks is <= 90 from 150 pre-diet);
  3. span-aware scan truncation is bit-exact against the full ladder
     for every planned max_seglen, including seglen == 1 and the
     power-of-two boundaries.
"""

from __future__ import annotations

import math
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from libgrape_lite_tpu.ops.spmv_pack import (  # noqa: E402
    PackConfig,
    _scan_np,
    _scan_stages_for,
    _shards_digest,
    exec_plan_np,
    plan_ledger,
    plan_pack,
)

CFG = PackConfig(sub=64, out_sub=16, hub=128)

# measured 48.06 ops/edge at this geometry when the budget was pinned
# (r6 ALU diet: span-aware scans + composed routes + flag narrowing);
# small headroom for numpy/ordering jitter, none for a real regression
OPS_PER_EDGE_PIN = 50.0


def _powerlaw_graph(seed=5, vp=4096, e=60000):
    rng = np.random.default_rng(seed)
    rows = np.minimum((rng.pareto(1.1, e) * 9).astype(np.int64), vp - 1)
    cols = np.minimum((rng.pareto(1.2, e) * 5).astype(np.int64), vp - 1)
    order = np.argsort(rows, kind="stable")
    return rows[order], cols[order], vp


def test_ledger_matches_independent_recount_exactly():
    """The per-block annotations and a from-the-arrays recount must
    agree to the op — any drift means the ledger no longer describes
    the kernels that actually run."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from pack_cost_model import independent_op_estimate

    rows, cols, vp = _powerlaw_graph()
    plan = plan_pack(rows, cols, vp, vp, CFG)
    led = plan_ledger(plan)
    rec = independent_op_estimate(plan)
    assert led["totals"]["alu_ops"] == rec["alu_ops"]
    assert led["totals"]["gather_rows"] == rec["gather_rows"]


def test_ops_per_edge_budget_pinned():
    rows, cols, vp = _powerlaw_graph()
    plan = plan_pack(rows, cols, vp, vp, CFG)
    led = plan_ledger(plan)
    per_edge = led["totals"]["alu_ops"] / led["edges"]
    assert per_edge <= OPS_PER_EDGE_PIN, (
        f"pack ALU budget regressed: {per_edge:.1f} ops/edge > pinned "
        f"{OPS_PER_EDGE_PIN} — a planner/kernel change re-fattened the "
        "pipeline; re-run scripts/pack_cost_model.py and re-justify"
    )
    # the ledger must carry every stage the kernels run
    assert set(led["totals"]["per_stage"]) == {
        "overlay", "route", "flags", "scan", "extract"
    }


def test_scan_stages_span_aware():
    """Degree-1 tails plan 0 scan stages; a single hot row needs the
    full in-block ladder; stages never exceed log2(slots)."""
    assert _scan_stages_for(np.zeros(0, np.int64)) == 0
    assert _scan_stages_for(np.arange(100)) == 0          # all runs == 1
    assert _scan_stages_for(np.zeros(1, np.int64)) == 0
    assert _scan_stages_for(np.zeros(2, np.int64)) == 1
    assert _scan_stages_for(np.zeros(256, np.int64)) == 8
    assert _scan_stages_for(np.zeros(257, np.int64)) == 9

    vp = 2048
    rows = np.arange(vp, dtype=np.int64)  # degree-1 tail
    rng = np.random.default_rng(3)
    plan = plan_pack(rows, rng.integers(0, vp, vp), vp, vp, CFG)
    for lv in plan.levels:
        if lv.has_gather:
            assert all(b.scan_stages == 0 for b in lv.blocks)

    hot = np.zeros(6000, dtype=np.int64)  # one row, e edges
    plan_hot = plan_pack(hot, rng.integers(0, 256, 6000), 256, 256, CFG)
    slots = CFG.sub * 128
    top = max(b.scan_stages for lv in plan_hot.levels
              for b in lv.blocks)
    assert top == math.ceil(math.log2(min(6000, slots)))
    for lv in list(plan_hot.levels) + [plan_hot.final]:
        for b in lv.blocks:
            assert 0 <= b.scan_stages <= math.ceil(math.log2(slots))


@pytest.mark.parametrize("seglen", [1, 2, 3, 4, 7, 8, 9, 127, 128, 129,
                                    255, 256])
@pytest.mark.parametrize("kind", ["sum", "min"])
def test_truncated_scan_bit_exact(seglen, kind):
    """For segments of max length L, ceil(log2(L)) stages produce the
    SAME array, bit for bit, as the full ladder — the extra stages
    combine with the exact identity."""
    rng = np.random.default_rng(seglen)
    sub = 8
    n = sub * 128
    rows = np.arange(n) // seglen          # equal-length segments
    v = rng.normal(size=n)
    f = np.ones(n)
    f[1:] = (rows[1:] != rows[:-1]).astype(float)
    stages = max(0, math.ceil(math.log2(seglen)))
    full = _scan_np(v.reshape(sub, 128), f.reshape(sub, 128), kind)
    trunc = _scan_np(v.reshape(sub, 128), f.reshape(sub, 128), kind,
                     stages)
    np.testing.assert_array_equal(full, trunc)
    if stages > 0:  # one stage short must differ somewhere (sanity)
        short = _scan_np(v.reshape(sub, 128), f.reshape(sub, 128),
                         kind, stages - 1)
        if seglen > 1:
            assert not np.array_equal(full, short)


def test_compose_off_parity_bitwise():
    """GRAPE_PACK_COMPOSE=0 (generic 3-stage fold routes) and the
    composed default must produce bit-identical outputs — composition
    moves only the intermediate compact layout, never the merge order
    or the scan tree."""
    rows, cols, vp = _powerlaw_graph(seed=11, vp=2048, e=30000)
    x = np.random.default_rng(0).normal(size=vp)
    old = os.environ.get("GRAPE_PACK_COMPOSE")
    try:
        os.environ["GRAPE_PACK_COMPOSE"] = "1"
        plan_c = plan_pack(rows, cols, vp, vp, CFG)
        os.environ["GRAPE_PACK_COMPOSE"] = "0"
        plan_g = plan_pack(rows, cols, vp, vp, CFG)
    finally:
        if old is None:
            os.environ.pop("GRAPE_PACK_COMPOSE", None)
        else:
            os.environ["GRAPE_PACK_COMPOSE"] = old
    # composition engaged on the composed plan, not on the generic one
    fold_lvls = [lv for lv in plan_c.levels if not lv.has_gather]
    assert plan_c.final.blocks[0].route_rows is not None or any(
        lv.blocks[0].route_rows is not None for lv in fold_lvls
    ), "composition never engaged at this geometry"
    assert plan_g.final.blocks[0].route_rows is None
    for kind in ("sum", "min"):
        np.testing.assert_array_equal(
            exec_plan_np(plan_c, x, kind), exec_plan_np(plan_g, x, kind)
        )
    # and the composed plan spends strictly fewer modeled route ops
    led_c = plan_ledger(plan_c)["totals"]["per_stage"]["route"]
    led_g = plan_ledger(plan_g)["totals"]["per_stage"]["route"]
    assert led_c < led_g


def test_digest_invalidates_on_config_and_dtype():
    """GRAPE_PACK_PLAN_CACHE keys carry a full PackConfig + dtype
    fingerprint: a config or dtype change must produce a different
    digest (a stale cached plan can never be loaded for it)."""
    rng = np.random.default_rng(7)
    rows = np.sort(rng.integers(0, 512, 1000))
    cols = rng.integers(0, 512, 1000)
    w32 = rng.uniform(0.1, 1.0, 1000).astype(np.float32)
    base = _shards_digest([(rows, cols, None)], 512, 512, CFG)
    assert _shards_digest(
        [(rows, cols, None)], 512, 512,
        PackConfig(sub=64, out_sub=16, hub=256),
    ) != base
    assert _shards_digest(
        [(rows, cols, None)], 512, 512,
        PackConfig(sub=32, out_sub=16, hub=128),
    ) != base
    assert _shards_digest([(rows, cols, w32)], 512, 512, CFG) != base
    assert _shards_digest(
        [(rows, cols, w32.astype(np.float64))], 512, 512, CFG
    ) != _shards_digest([(rows, cols, w32)], 512, 512, CFG)
    # stable across calls (it keys an on-disk cache)
    assert _shards_digest([(rows, cols, None)], 512, 512, CFG) == base
