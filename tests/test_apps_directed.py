"""Directed-graph golden tests (`misc/app_tests.sh`: sssp/bfs with
--directed vs p2p-31-SSSP-directed / -BFS-directed; pagerank_parallel
--directed vs p2p-31-PR-directed)."""

import pytest

from tests.conftest import dataset_path
from tests.test_apps_golden import run_worker
from tests.verifiers import eps_verify, exact_verify, load_golden

FNUMS = [1, 4]


@pytest.mark.parametrize("fnum", FNUMS)
def test_sssp_directed(graph_cache, fnum):
    from libgrape_lite_tpu.models import SSSP

    frag = graph_cache(fnum, directed=True)
    res = run_worker(SSSP(), frag, source=6)
    exact_verify(res, load_golden(dataset_path("p2p-31-SSSP-directed")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_bfs_directed(graph_cache, fnum):
    from libgrape_lite_tpu.models import BFS

    frag = graph_cache(fnum, directed=True)
    res = run_worker(BFS(), frag, source=6)
    exact_verify(res, load_golden(dataset_path("p2p-31-BFS-directed")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_pagerank_directed(graph_cache, fnum):
    from libgrape_lite_tpu.models import PageRank

    frag = graph_cache(fnum, directed=True)
    res = run_worker(PageRank(), frag, delta=0.85, max_round=10)
    eps_verify(res, load_golden(dataset_path("p2p-31-PR-directed")))
