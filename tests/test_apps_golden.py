"""Golden-file app tests, the analogue of `misc/app_tests.sh`:
every app × fragment counts {1,2,4,8} (the reference's `mpirun -n N`),
verified exact / eps / isomorphism against `dataset/p2p-31-*`.
"""

import numpy as np
import pytest

from tests.conftest import dataset_path
from tests.verifiers import (
    collect_worker_result as run_worker,
    eps_verify,
    exact_verify,
    load_golden,
    wcc_verify,
)

FNUMS = [1, 2, 4, 8]


@pytest.mark.parametrize("fnum", FNUMS)
def test_sssp(graph_cache, fnum):
    from libgrape_lite_tpu.models import SSSP

    frag = graph_cache(fnum)
    res = run_worker(SSSP(), frag, source=6)
    exact_verify(res, load_golden(dataset_path("p2p-31-SSSP")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_bfs(graph_cache, fnum):
    from libgrape_lite_tpu.models import BFS

    frag = graph_cache(fnum)
    res = run_worker(BFS(), frag, source=6)
    exact_verify(res, load_golden(dataset_path("p2p-31-BFS")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_pagerank(graph_cache, fnum):
    from libgrape_lite_tpu.models import PageRank

    frag = graph_cache(fnum)
    res = run_worker(PageRank(), frag, delta=0.85, max_round=10)
    eps_verify(res, load_golden(dataset_path("p2p-31-PR")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_wcc(graph_cache, fnum):
    from libgrape_lite_tpu.models import WCC

    frag = graph_cache(fnum)
    res = run_worker(WCC(), frag)
    wcc_verify(res, load_golden(dataset_path("p2p-31-WCC")))


@pytest.mark.parametrize("fnum", FNUMS)
def test_cdlp(graph_cache, fnum):
    from libgrape_lite_tpu.models import CDLP

    frag = graph_cache(fnum)
    res = run_worker(CDLP(), frag, max_round=10)
    exact_verify(res, load_golden(dataset_path("p2p-31-CDLP")))


@pytest.mark.parametrize("fnum", [1, 4])
def test_lcc(graph_cache, fnum):
    from libgrape_lite_tpu.models import LCC

    frag = graph_cache(fnum)
    res = run_worker(LCC(), frag)
    eps_verify(res, load_golden(dataset_path("p2p-31-LCC")))


@pytest.mark.parametrize("fnum", [1, 4])
def test_cdlp_opt(graph_cache, fnum):
    """CDLPOpt's round-1 min shortcut must stay golden-identical
    (cdlp_opt.h's PEval exploits all-distinct initial labels)."""
    from libgrape_lite_tpu.models import CDLPOpt

    frag = graph_cache(fnum)
    res = run_worker(CDLPOpt(), frag, max_round=10)
    exact_verify(res, load_golden(dataset_path("p2p-31-CDLP")))


@pytest.mark.parametrize("fnum", [1, 4])
def test_cdlp_dynamic_compression(graph_cache, fnum):
    """Dynamic label-universe compression (the RMAT-20+ wide-path
    replacement): force the dynamic path; p2p-31's live universe fits
    the budget, so every round takes the packed-compressed branch of
    the in-jit lax.cond — must stay golden-exact."""
    from libgrape_lite_tpu.models import CDLP

    frag = graph_cache(fnum)
    app = CDLP()
    app._force_dynamic = True
    res = run_worker(app, frag, max_round=10)
    exact_verify(res, load_golden(dataset_path("p2p-31-CDLP")))


def test_cdlp_dynamic_wide_fallback(graph_cache):
    """Shrink the universe budget below the live label count so the
    lax.cond's runtime check routes every round to the wide branch —
    the fallback must also stay golden-exact."""
    from libgrape_lite_tpu.models import CDLP

    frag = graph_cache(4)
    app = CDLP()
    app._force_dynamic = True
    app._u_budget_override = 64  # << p2p-31's 62k live labels
    res = run_worker(app, frag, max_round=10)
    exact_verify(res, load_golden(dataset_path("p2p-31-CDLP")))


def test_cdlp_opt_single_round(graph_cache):
    """max_round=1 exercises exactly the shortcut round."""
    from libgrape_lite_tpu.models import CDLP, CDLPOpt

    frag = graph_cache(2)
    base = run_worker(CDLP(), frag, max_round=1)
    opt = run_worker(CDLPOpt(), frag, max_round=1)
    assert base == opt
